"""Named multi-axis mesh construction.

Extends the world-mesh bootstrap (common/topology.py) to the standard
dp/pp/sp/tp/ep axis factorization. Axis order is chosen so the most
bandwidth-hungry axis (tp) maps to the innermost/fastest ICI dimension —
the layout discipline the scaling-book recipe prescribes; the reference's
analog is its hierarchical intra/inter-node split
(HOROVOD_HIERARCHICAL_ALLREDUCE, nccl_operations.cc [V])."""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

# Outer→inner order: dp spans hosts/DCN first, tp stays innermost on ICI.
AXIS_ORDER = ("dp", "pp", "ep", "sp", "tp")


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    dp: int = 1
    pp: int = 1
    ep: int = 1
    sp: int = 1
    tp: int = 1

    @property
    def size(self) -> int:
        return self.dp * self.pp * self.ep * self.sp * self.tp

    def build(self, devices: Optional[Sequence] = None) -> Mesh:
        devices = list(devices if devices is not None else jax.devices())
        if len(devices) != self.size:
            raise ValueError(
                f"mesh spec {self} needs {self.size} devices, "
                f"got {len(devices)}"
            )
        shape = tuple(getattr(self, a) for a in AXIS_ORDER)
        return Mesh(np.asarray(devices).reshape(shape), AXIS_ORDER)

    @staticmethod
    def auto(
        n_devices: int,
        tp: Optional[int] = None,
        sp: int = 1,
        pp: int = 1,
        ep: int = 1,
    ) -> "MeshSpec":
        """Factor n_devices into a sensible default: fix the model axes,
        give the remainder to dp (the reference's only axis)."""
        tp = tp if tp is not None else 1
        denom = tp * sp * pp * ep
        if n_devices % denom != 0:
            raise ValueError(
                f"{n_devices} devices not divisible by tp*sp*pp*ep={denom}"
            )
        return MeshSpec(dp=n_devices // denom, pp=pp, ep=ep, sp=sp, tp=tp)
