"""Estimator-layer training — the ``horovod.spark`` porting path.

Parity with the reference's Spark Estimator flow
(ref: horovod/spark/torch/estimator.py examples — declare model +
optimizer + loss + store, call fit on data, get a servable model back
[V]; scope decisions in docs/design.md "Spark / Ray depth"): the same
four-step shape on the TPU-native stack. Where the reference's fit
consumes a Spark DataFrame through Petastorm, this one consumes arrays
or a batch iterable — the identical slot in the API.

Run (single host, 8-way CPU simulation):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python examples/estimator_train.py

Run (TPU): python examples/estimator_train.py
"""

import argparse
import os
import tempfile

# CPU-simulation friendliness, mirroring the other examples.
if os.environ.get("JAX_PLATFORMS") == "cpu":
    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
    )

import jax.numpy as jnp
import numpy as np
import optax

import flax.linen as nn

from horovod_tpu.spark import LocalStore, TpuEstimator, TpuModel


class Net(nn.Module):
    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Dense(64)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9)(x)
        x = nn.relu(x)
        x = nn.Dropout(0.1, deterministic=not train)(x)
        return nn.Dense(1)(x)


def mse(preds, y):
    return jnp.mean((preds - y) ** 2)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=10)
    parser.add_argument("--batch-size", type=int, default=64)
    parser.add_argument("--store", default=None)
    args = parser.parse_args()

    rng = np.random.default_rng(0)
    x = rng.normal(size=(2048, 8)).astype(np.float32)
    w = rng.normal(size=(8, 1)).astype(np.float32)
    y = np.tanh(x @ w).astype(np.float32)

    store_dir = args.store or tempfile.mkdtemp(prefix="hvd_store_")
    est = TpuEstimator(
        model=Net(),
        loss=mse,
        optimizer=optax.adam(1e-2),
        store=LocalStore(store_dir),
        run_id="example",
        epochs=args.epochs,
        batch_size=args.batch_size,
    )
    model = est.fit(x, y)
    for h in est.history:
        print(f"epoch {h['epoch']}: loss {h['loss']:.5f}")

    preds = model.predict(x[:8])
    print("predictions:", np.round(preds.ravel(), 3))

    served = os.path.join(store_dir, "serving")
    model.save(served)
    reloaded = TpuModel.load(Net(), served)
    assert np.allclose(reloaded.predict(x[:8]), preds, rtol=1e-6)
    print(f"model served from {served} — save/load round-trip ok")


if __name__ == "__main__":
    main()
