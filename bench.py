"""Synthetic ResNet-50 benchmark — parity with the reference's headline
harness (ref: examples/pytorch/pytorch_synthetic_benchmark.py [V]:
ResNet-50, synthetic ImageNet batches, reports img/sec; BASELINE.md
north star tracks the same metric on TPU).

Prints ONE JSON line:
  {"metric": "resnet50_synth_img_per_sec", "value": N, "unit": "img/s",
   "vs_baseline": R}

vs_baseline compares against the canonical single-P100 fp32 ResNet-50
throughput (~219 img/s, the tf_cnn_benchmarks number contemporaneous with
the reference's published scaling figures — BASELINE.md [V]): the
reference's own benchmark prints absolute img/sec per device, so the
honest single-chip comparison is chip vs chip.

Env knobs: BENCH_BATCH (default 256 — measured-best MXU utilization on
the v5e-class chip; the reference harness defaults to 32, which here
leaves ~15% throughput on the table), BENCH_ITERS, BENCH_WARMUP,
BENCH_PLATFORM=cpu to force the host platform.
"""

import json
import os
import time
from functools import partial

P100_FP32_IMG_PER_SEC = 219.0

batch = int(os.environ.get("BENCH_BATCH", "256"))
n_iters = int(os.environ.get("BENCH_ITERS", "20"))
n_warmup = int(os.environ.get("BENCH_WARMUP", "3"))

import jax  # noqa: E402

if os.environ.get("BENCH_PLATFORM"):
    jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

from horovod_tpu.models import ResNet50  # noqa: E402


def main():
    model = ResNet50(dtype=jnp.bfloat16)
    rng = jax.random.PRNGKey(0)
    images = jnp.asarray(
        np.random.default_rng(0).uniform(size=(batch, 224, 224, 3)),
        jnp.bfloat16,
    )
    labels = jnp.zeros((batch,), jnp.int32)
    variables = jax.jit(lambda: model.init(rng, images, train=False))()
    params, batch_stats = variables["params"], variables["batch_stats"]
    opt = optax.sgd(0.01, momentum=0.9)
    opt_state = opt.init(params)

    # Donating the carried state lets XLA update params/opt-state in
    # place instead of allocating fresh buffers every step — the same
    # HBM-traffic discipline the fusion-buffer reuse gives the reference.
    @partial(jax.jit, donate_argnums=(0, 1, 2))
    def train_step(params, batch_stats, opt_state, images, labels):
        def loss_fn(p):
            logits, mutated = model.apply(
                {"params": p, "batch_stats": batch_stats},
                images,
                train=True,
                mutable=["batch_stats"],
            )
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, labels
            ).mean()
            return loss, mutated["batch_stats"]

        (loss, new_stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params
        )
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, new_stats, opt_state, loss

    for _ in range(n_warmup):
        params, batch_stats, opt_state, loss = train_step(
            params, batch_stats, opt_state, images, labels
        )
    if n_warmup > 0:
        jax.block_until_ready(loss)

    t0 = time.perf_counter()
    for _ in range(n_iters):
        params, batch_stats, opt_state, loss = train_step(
            params, batch_stats, opt_state, images, labels
        )
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    img_per_sec = batch * n_iters / dt
    print(
        json.dumps(
            {
                "metric": "resnet50_synth_img_per_sec",
                "value": round(img_per_sec, 2),
                "unit": "img/s",
                "vs_baseline": round(img_per_sec / P100_FP32_IMG_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
