"""Hierarchical (two-level) allreduce tests — the ICI/DCN analog of the
reference's NCCL-intra + MPI-inter path (HOROVOD_HIERARCHICAL_ALLREDUCE,
nccl_operations.cc [V])."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P


def test_hierarchical_mesh_shape(hvd):
    from horovod_tpu.ops import traced

    mesh = traced.hierarchical_mesh(local_size=4)
    assert mesh.axis_names == (traced.INTER_AXIS, traced.INTRA_AXIS)
    assert mesh.devices.shape == (2, 4)
    with pytest.raises(ValueError):
        traced.hierarchical_mesh(local_size=3)  # 3 does not divide 8


@pytest.mark.parametrize("local_size", [2, 4])
@pytest.mark.parametrize("op_name", ["sum", "avg"])
def test_hierarchical_allreduce_matches_flat(hvd, rng, local_size, op_name):
    """rs→ar→ag over (inter, intra) must equal a flat allreduce."""
    from horovod_tpu.ops import traced

    mesh = traced.hierarchical_mesh(local_size=local_size)
    n = 8
    per_rank = rng.normal(size=(n, 37)).astype(np.float32)  # odd length
    op = hvd.Sum if op_name == "sum" else hvd.Average

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=P((traced.INTER_AXIS, traced.INTRA_AXIS)),
        out_specs=P((traced.INTER_AXIS, traced.INTRA_AXIS)),
        check_vma=False,
    )
    def reduce(x):
        return traced.hierarchical_allreduce(x[0], op=op)[None]

    got = np.asarray(jax.jit(reduce)(jnp.asarray(per_rank)))
    want = per_rank.sum(axis=0)
    if op_name == "avg":
        want = want / n
    for r in range(n):
        np.testing.assert_allclose(got[r], want, rtol=1e-5, atol=1e-5)


def test_hierarchical_allreduce_scales(hvd, rng):
    from horovod_tpu.ops import traced

    mesh = traced.hierarchical_mesh(local_size=4)
    per_rank = rng.normal(size=(8, 16)).astype(np.float32)

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=P((traced.INTER_AXIS, traced.INTRA_AXIS)),
        out_specs=P((traced.INTER_AXIS, traced.INTRA_AXIS)),
        check_vma=False,
    )
    def reduce(x):
        return traced.hierarchical_allreduce(
            x[0], op=hvd.Sum, prescale_factor=0.5, postscale_factor=2.0
        )[None]

    got = np.asarray(jax.jit(reduce)(jnp.asarray(per_rank)))
    np.testing.assert_allclose(
        got[0], per_rank.sum(axis=0), rtol=1e-5, atol=1e-5
    )


def test_hierarchical_allreduce_rejects_min(hvd):
    from horovod_tpu.ops import traced

    with pytest.raises(ValueError):
        traced.hierarchical_allreduce(jnp.zeros(4), op="min")


def test_hierarchical_stage_groups():
    from horovod_tpu.ops.fusion import hierarchical_stage_groups

    intra, inter = hierarchical_stage_groups(8, 4)
    assert intra == [[0, 1, 2, 3], [4, 5, 6, 7]]
    assert inter == [[0, 4], [1, 5], [2, 6], [3, 7]]
    # every rank appears exactly once per stage
    for stage in (intra, inter):
        flat = sorted(r for g in stage for r in g)
        assert flat == list(range(8))
    # degenerate hierarchies fall back to flat
    assert hierarchical_stage_groups(8, 1) is None
    assert hierarchical_stage_groups(8, 8) is None
    assert hierarchical_stage_groups(8, 3) is None


def test_eager_hierarchical_flag_correctness(rng, monkeypatch):
    """With HOROVOD_HIERARCHICAL_ALLREDUCE=1 and a multi-host-shaped
    topology (local_size 4 of world 8), the eager allreduce decomposes
    into two grouped psums and still produces the exact flat result."""
    import dataclasses

    monkeypatch.setenv("HOROVOD_HIERARCHICAL_ALLREDUCE", "1")
    import horovod_tpu as hvd_mod
    from horovod_tpu.common import basics

    hvd_mod.shutdown()
    hvd_mod.init()
    try:
        assert hvd_mod.get_config().hierarchical_allreduce
        # Simulate 2 hosts x 4 chips on the 8-device sim (the env
        # contract can't fake this: the validator checks it against the
        # real runtime, so patch the discovered topology instead).
        topo = basics.topology()
        patched = dataclasses.replace(topo, local_device_count=4)
        monkeypatch.setattr(
            basics._state, "topology", patched, raising=False
        )
        assert basics.topology().local_size == 4
        per_rank = rng.normal(size=(8, 33)).astype(np.float32)
        x = hvd_mod.shard_from_rank_fn(
            lambda r: per_rank[r], hvd_mod.mesh()
        )
        out = np.asarray(jax.device_get(hvd_mod.allreduce(x, op=hvd_mod.Sum)))
        for r in range(8):
            np.testing.assert_allclose(
                out[r], per_rank.sum(axis=0), rtol=1e-5, atol=1e-5
            )
        # Average path too
        out = np.asarray(
            jax.device_get(hvd_mod.allreduce(x, op=hvd_mod.Average))
        )
        np.testing.assert_allclose(
            out[0], per_rank.mean(axis=0), rtol=1e-5, atol=1e-5
        )
    finally:
        hvd_mod.shutdown()


@pytest.mark.parametrize("local_size", [2, 4])
@pytest.mark.parametrize("op_name", ["sum", "avg"])
def test_hierarchical_quantized_matches_within_quanta(
    hvd, rng, local_size, op_name
):
    """int8-on-DCN-only: rs(fp) -> quantized AR(inter) -> ag(fp) must
    match the exact hierarchical result within the two-stage int8
    bound (~3 quanta of the reduced tensor's absmax), and must be
    IDENTICAL across ranks (a well-formed allreduce)."""
    from horovod_tpu.ops import traced

    mesh = traced.hierarchical_mesh(local_size=local_size)
    n = 8
    per_rank = rng.normal(size=(n, 37)).astype(np.float32)
    op = hvd.Sum if op_name == "sum" else hvd.Average

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=P((traced.INTER_AXIS, traced.INTRA_AXIS)),
        out_specs=P((traced.INTER_AXIS, traced.INTRA_AXIS)),
        check_vma=False,
    )
    def reduce(x):
        return traced.hierarchical_quantized_allreduce(x[0], op=op)[None]

    got = np.asarray(jax.jit(reduce)(jnp.asarray(per_rank)))
    want = per_rank.sum(axis=0)
    scale = np.abs(want).max() / 127.0
    if op_name == "avg":
        want = want / n
        scale = scale / n
    for r in range(n):
        np.testing.assert_allclose(got[r], got[0], rtol=0, atol=0)
        assert np.max(np.abs(got[r] - want)) < 3.0 * scale


def test_hierarchical_quantized_residual_reconstructs(hvd, rng):
    """EF carry in input units: adding the returned residual to the
    NEXT step's identical input must cancel the previous quantization
    error — two chained steps land ~1 quantum from exact (vs up to ~3
    for one EF-less step), and the residual's intra re-broadcast /L
    reconstructs exactly one copy at the shard owner."""
    from horovod_tpu.ops import traced

    mesh = traced.hierarchical_mesh(local_size=4)
    n = 8
    per_rank = rng.normal(size=(n, 64)).astype(np.float32)

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(
            P((traced.INTER_AXIS, traced.INTRA_AXIS)),
            P((traced.INTER_AXIS, traced.INTRA_AXIS)),
        ),
        out_specs=(
            P((traced.INTER_AXIS, traced.INTRA_AXIS)),
            P((traced.INTER_AXIS, traced.INTRA_AXIS)),
        ),
        check_vma=False,
    )
    def reduce_ef(x, carry):
        out, res = traced.hierarchical_quantized_allreduce(
            x[0] + carry[0], op=hvd.Sum, seed=7, return_residual=True
        )
        return out[None], res[None]

    step = jax.jit(reduce_ef)
    want = per_rank.sum(axis=0)
    scale = np.abs(want).max() / 127.0
    carry = jnp.zeros_like(jnp.asarray(per_rank))
    outs = []
    for _ in range(2):
        out, carry = step(jnp.asarray(per_rank), carry)
        outs.append(np.asarray(out))
    # step 2 transmits grad + step-1's error, so the CUMULATIVE
    # transmitted signal outs[0]+outs[1] must sit within one fresh
    # step's error of 2*want — the EF property. (Without a working
    # residual, independent step errors would not cancel, and an
    # all-zeros residual fails the inequality below too.)
    cum_err_ef = np.max(np.abs(outs[0] + outs[1] - 2 * want))
    assert cum_err_ef < 4.0 * scale, (cum_err_ef, scale)
    # an all-zeros/mis-scaled residual also can't reproduce this: the
    # carry must actually CHANGE what step 2 transmits (same input,
    # same seed, different carry => different wire value)
    assert np.max(np.abs(outs[1] - outs[0])) > 0.0
    # and the residual really was consumed: with a zero carry the same
    # seed reproduces step 1 exactly
    out0, _ = step(jnp.asarray(per_rank), jnp.zeros_like(carry))
    np.testing.assert_allclose(np.asarray(out0), outs[0], atol=0)
