"""``import horovod_tpu.tensorflow as hvd`` — gated TensorFlow binding.

Parity target: the reference's TF surface (ref:
horovod/tensorflow/__init__.py + mpi_ops.py + gradients.py [V] —
SURVEY.md §2.4, ~2,500 LoC). Scope decision (docs/design.md "Framework
bindings"): this module is a *gated minimal binding* — the same
host-bridge pattern as the torch shim (horovod_tpu/torch), delegating
every collective to the eager XLA path. It imports only when TF is
present; otherwise it raises immediately with this scope note rather
than failing somewhere deep inside a user script.

What is here when TF is available: init/rank/size identity, allreduce /
allgather / broadcast (sync + _async + in-place variants where TF
semantics allow), alltoall (+v), reducescatter, join,
broadcast_variables, DistributedGradientTape (IndexedSlices gradients
densify with a one-time warning, matching the reference's
sparse_as_dense fallback), and a Keras ``DistributedOptimizer`` —
the TF2 idioms the reference's docs lead with (SURVEY.md §3.5).
Deliberately absent (would need TF to even design honestly): TF1
Session-era DistributedOptimizer, custom-op kernels (`mpi_ops.cc`) and
the XLA custom-call hooks (`xla_mpi_ops.cc`) — on TPU the XLA hook is
the *whole framework* (collectives are compiler-visible), so that row
is subsumed rather than missing.
"""

from __future__ import annotations

try:
    import tensorflow as tf  # noqa: F401
except Exception as _e:  # pragma: no cover - exercised only without TF
    raise ImportError(
        "horovod_tpu.tensorflow requires the 'tensorflow' package, which "
        "is not installed in this environment. This binding is a gated "
        "compatibility layer (see module docstring / docs/design.md); "
        "the TPU-native training path is the JAX API: "
        "`import horovod_tpu as hvd`."
    ) from _e

import numpy as np

from ..common.basics import (  # noqa: F401
    add_process_set,
    cross_rank,
    cross_size,
    global_process_set,
    init,
    is_initialized,
    local_rank,
    local_size,
    mpi_threads_supported,
    rank,
    remove_process_set,
    shutdown,
    size,
)
from ..common.process_sets import (  # noqa: F401
    ProcessSet,
    warn_nonmember_controller as _warn_nonmember_controller,
)
from ..ops import eager as _eager
from ..ops.reduction_ops import (  # noqa: F401
    Adasum,
    Average,
    Max,
    Min,
    Product,
    Sum,
)


def _replicated_payload(tensor):
    return _eager.replicate(np.asarray(tensor))


def _concat_rows(host):
    # eager allgather returns rank-major [world, n, ...]; the TF
    # contract concatenates along dim 0 [V]
    return host.reshape((-1,) + host.shape[2:])


class _TFHandle:
    def __init__(self, inner, like, post=None):
        self._inner = inner
        self._like = like
        self._post = post

    def poll(self):
        return self._inner.poll()

    def wait(self):
        host = np.asarray(_eager.first(self._inner.wait()))
        if self._post is not None:
            host = self._post(host)
        return tf.convert_to_tensor(host, dtype=self._like.dtype)


def allreduce_async(tensor, average=None, name=None, op=None,
                    process_set=None, prescale_factor=1.0,
                    postscale_factor=1.0):
    _warn_nonmember_controller("allreduce", process_set)
    handle = _eager.allreduce_async(
        _replicated_payload(tensor), average=average, name=name, op=op,
        process_set=process_set, prescale_factor=prescale_factor,
        postscale_factor=postscale_factor,
    )
    return _TFHandle(handle, tensor)


def allreduce(tensor, average=None, name=None, op=None, process_set=None,
              prescale_factor=1.0, postscale_factor=1.0):
    return allreduce_async(
        tensor, average=average, name=name, op=op, process_set=process_set,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
    ).wait()


def allgather_async(tensor, name=None, process_set=None):
    _warn_nonmember_controller("allgather", process_set)
    handle = _eager.allgather_async(
        _replicated_payload(tensor), name=name, process_set=process_set
    )
    return _TFHandle(handle, tensor, post=_concat_rows)


def allgather(tensor, name=None, process_set=None):
    return allgather_async(tensor, name=name, process_set=process_set).wait()


def broadcast(tensor, root_rank, name=None, process_set=None):
    _warn_nonmember_controller("broadcast", process_set)
    handle = _eager.broadcast_async(
        _replicated_payload(tensor), root_rank, name=name,
        process_set=process_set,
    )
    return _TFHandle(handle, tensor).wait()


def broadcast_variables(variables, root_rank: int = 0) -> None:
    """Assign root's values into ``variables`` in place (ref:
    hvd.broadcast_variables [V])."""
    for var in variables:
        var.assign(broadcast(var, root_rank, name=var.name))


def alltoall(tensor, splits=None, name=None, process_set=None):
    """Scatter dim-0 blocks to peers and gather theirs (ref: hvd.alltoall
    in horovod/tensorflow/mpi_ops.py [V]). With ``splits`` (1-D, one
    entry per rank) returns ``(output, received_splits)`` like the
    reference's v-variant; without, the equal-split fast path."""
    _warn_nonmember_controller("alltoall", process_set)
    if splits is None:
        handle = _eager.alltoall_async(
            _replicated_payload(tensor), name=name, process_set=process_set
        )
        return _TFHandle(handle, tensor).wait()
    host = np.asarray(tensor)
    world = size()
    participants = (
        len(process_set.ranks)
        if process_set is not None and process_set.process_set_id != 0
        else world
    )
    splits_1d = [int(s) for s in np.asarray(splits).reshape(-1).tolist()]
    if len(splits_1d) != participants:
        raise ValueError(
            f"splits has {len(splits_1d)} entries but the exchange has "
            f"{participants} participants"
        )
    if sum(splits_1d) != host.shape[0]:
        raise ValueError(
            f"splits sum to {sum(splits_1d)} but tensor dim0 is "
            f"{host.shape[0]}"
        )
    handle = _eager.alltoall_async(
        [host] * world, splits=[splits_1d] * world, name=name,
        process_set=process_set,
    )
    outputs, recv_splits = handle.wait()
    return (
        tf.convert_to_tensor(np.asarray(outputs[0]), dtype=tensor.dtype),
        tf.convert_to_tensor(np.asarray(recv_splits[0], dtype=np.int32)),
    )


def grouped_allreduce(tensors, average=None, name=None, op=None,
                      process_set=None, prescale_factor=1.0,
                      postscale_factor=1.0):
    """Atomic multi-tensor allreduce (ref: hvd.grouped_allreduce in
    horovod/tensorflow/mpi_ops.py [V]): one fused collective for the
    whole list."""
    _warn_nonmember_controller("grouped_allreduce", process_set)
    handles = _eager.grouped_allreduce_async(
        [_replicated_payload(t) for t in tensors],
        average=average, name=name, op=op, process_set=process_set,
        prescale_factor=prescale_factor, postscale_factor=postscale_factor,
    )
    return [
        _TFHandle(h, t).wait() for h, t in zip(handles, tensors)
    ]


def grouped_allgather(tensors, name=None, process_set=None):
    """Atomic multi-tensor allgather (ref: hvd.grouped_allgather,
    upstream v0.28+ [V])."""
    _warn_nonmember_controller("grouped_allgather", process_set)
    handles = _eager.grouped_allgather_async(
        [_replicated_payload(t) for t in tensors], name=name,
        process_set=process_set,
    )
    return [
        _TFHandle(h, t, post=_concat_rows).wait()
        for h, t in zip(handles, tensors)
    ]


def grouped_reducescatter(tensors, op=None, name=None, process_set=None):
    """Atomic multi-tensor reduce-scatter (ref: hvd.grouped_reducescatter,
    upstream v0.28+ [V])."""
    _warn_nonmember_controller("grouped_reducescatter", process_set)
    handles = _eager.grouped_reducescatter_async(
        [_replicated_payload(t) for t in tensors], op=op, name=name,
        process_set=process_set,
    )
    return [
        _TFHandle(h, t).wait() for h, t in zip(handles, tensors)
    ]


def reducescatter(tensor, op=None, name=None, process_set=None):
    """This rank's shard of the world-reduced tensor, split along dim 0
    (ref: hvd.reducescatter, upstream v0.27+ [V]). Under the single
    controller this process is rank 0, so the rank-0 row is our shard —
    even and uneven (v-variant) cases both."""
    _warn_nonmember_controller("reducescatter", process_set)
    handle = _eager.reducescatter_async(
        _replicated_payload(tensor), op=op, name=name,
        process_set=process_set,
    )
    return _TFHandle(handle, tensor).wait()


def __getattr__(name):  # PEP 562 — keeps the class build off import time
    if name == "SyncBatchNormalization":
        from .sync_batch_norm import SyncBatchNormalization

        return SyncBatchNormalization
    if name == "elastic":
        # hvd.elastic.run / hvd.elastic.TensorFlowKerasState from the
        # shim namespace, matching horovod.tensorflow.elastic [V]
        import importlib

        return importlib.import_module(".elastic", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def join(joined_ranks=None) -> int:
    """API-parity join (ref: hvd.join [V]): flush outstanding work; with
    ``joined_ranks`` returns the last joined rank."""
    return _eager.join(joined_ranks)


def barrier(process_set=None) -> None:
    """Block until all processes (or all members of ``process_set``)
    reach the barrier (ref: horovod.tensorflow.barrier [V])."""
    _eager.barrier(process_set=process_set)


def broadcast_object(obj, root_rank: int = 0, name=None):
    """Pickle-broadcast an arbitrary Python object from ``root_rank``
    (ref: horovod.tensorflow.broadcast_object [V])."""
    from ..optimizer import broadcast_object as _impl

    return _impl(obj, root_rank=root_rank, name=name)


def allgather_object(obj, name=None):
    """Gather one arbitrary Python object per rank into a list
    (ref: horovod.tensorflow.allgather_object [V])."""
    from ..optimizer import allgather_object as _impl

    return _impl(obj, name=name)


class _NoneCompressor:
    @staticmethod
    def compress(tensor):
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _FP16Compressor:
    """fp16 wire compression for tf tensors (ref:
    horovod/tensorflow/compression.py [V])."""

    @staticmethod
    def compress(tensor):
        ctx = tensor.dtype
        if tensor.dtype.is_floating:
            tensor = tf.cast(tensor, tf.float16)
        return tensor, ctx

    @staticmethod
    def decompress(tensor, ctx):
        return tf.cast(tensor, ctx) if tensor.dtype != ctx else tensor


class Compression:
    """hvd.Compression namespace for tf tensors [V]."""

    none = _NoneCompressor
    fp16 = _FP16Compressor


class DistributedGradientTape:
    """Wrap a tf.GradientTape so gradient() allreduces the grads (ref:
    horovod/tensorflow/__init__.py DistributedGradientTape [V])."""

    def __init__(self, tape, op=None, process_set=None,
                 compression=None):
        self._tape = tape
        self._op = op
        self._process_set = process_set
        self._compression = compression or Compression.none

    def __getattr__(self, name):
        return getattr(self._tape, name)

    def _reduce_one(self, g):
        if g is None:
            return None
        g = _densify(g)
        g, ctx = self._compression.compress(g)
        out = allreduce(g, op=self._op, process_set=self._process_set)
        return self._compression.decompress(out, ctx)

    def gradient(self, target, sources, output_gradients=None, **kwargs):
        # **kwargs forwards tf.GradientTape extras (unconnected_gradients)
        # so the wrapper stays a drop-in replacement.
        grads = self._tape.gradient(target, sources, output_gradients,
                                    **kwargs)
        # Mirror tf.GradientTape: single source in -> single grad out.
        if isinstance(grads, (list, tuple)):
            reduced = [self._reduce_one(g) for g in grads]
            return type(grads)(reduced) if isinstance(
                grads, tuple) else reduced
        return self._reduce_one(grads)


def _densify(g):
    """IndexedSlices → dense with a one-time warning — the reference
    reduces sparse grads via allgather or densifies under
    sparse_as_dense (horovod/tensorflow/__init__.py [V]); shared by the
    tape and the Keras optimizer paths."""
    if isinstance(g, tf.IndexedSlices):
        _warn_sparse_once()
        g = tf.convert_to_tensor(g)
    return g


_sparse_warned = False


def _warn_sparse_once() -> None:
    global _sparse_warned
    if not _sparse_warned:
        _sparse_warned = True
        import warnings

        warnings.warn(
            "horovod_tpu.tensorflow: densifying IndexedSlices gradient "
            "for allreduce (the reference's sparse_as_dense behavior); "
            "for very large embeddings prefer the JAX path",
            stacklevel=3,
        )


def load_model(path, custom_objects=None, compile=True, **kwargs):
    """Load a model saved while compiled with this module's
    DistributedOptimizer (ref: horovod/tensorflow/keras/__init__.py
    load_model [V] — the reference injects the same custom objects; a
    plain tf.keras.models.load_model cannot know the dynamic
    Distributed* classes). The deserialized optimizer is re-wrapped, so
    training can resume distributed."""
    objects = dict(custom_objects or {})

    def _factory(base_cls):
        # must look like a class: Keras deserialization calls
        # cls.from_config(config) on registered custom objects
        class _Reconstruct:
            @classmethod
            def from_config(cls, config, custom_objects=None):
                return DistributedOptimizer(base_cls.from_config(config))

        return _Reconstruct

    for name in dir(tf.keras.optimizers):
        base_cls = getattr(tf.keras.optimizers, name)
        if isinstance(base_cls, type) and issubclass(
            base_cls, tf.keras.optimizers.Optimizer
        ):
            objects.setdefault(f"Distributed{name}", _factory(base_cls))
    return tf.keras.models.load_model(
        path, custom_objects=objects, compile=compile, **kwargs
    )


def DistributedOptimizer(optimizer, op=None, process_set=None,
                         compression=None):
    """Wrap a Keras optimizer so apply_gradients() allreduces gradients
    first (ref: horovod/tensorflow/keras/__init__.py
    DistributedOptimizer [V]). Like the reference, this builds a dynamic
    subclass of the wrapped optimizer's own class so Keras type checks
    and get_config round-trips keep working."""
    base_cls = optimizer.__class__

    class _DistributedKerasOptimizer(base_cls):
        _hvd_op = op
        _hvd_process_set = process_set
        _hvd_compression = compression or Compression.none

        def _hvd_reduce(self, g):
            g = _densify(g)
            g, _hvd_ctx = self._hvd_compression.compress(g)
            # model.fit traces apply_gradients into a tf.function; the
            # shim's collectives are host bridges, so symbolic tensors
            # route through py_function (same host round-trip either
            # way — this is the documented cost profile of the shim).
            if tf.executing_eagerly():
                out = allreduce(
                    g, op=self._hvd_op, process_set=self._hvd_process_set
                )
                return self._hvd_compression.decompress(out, _hvd_ctx)
            out = tf.py_function(
                func=lambda t: allreduce(
                    t, op=self._hvd_op, process_set=self._hvd_process_set
                ),
                inp=[g],
                Tout=g.dtype,
            )
            out.set_shape(g.shape)
            return self._hvd_compression.decompress(out, _hvd_ctx)

        def apply_gradients(self, grads_and_vars, *args, **kwargs):
            pairs = list(grads_and_vars)
            reduced = [
                (g if g is None else self._hvd_reduce(g), v)
                for g, v in pairs
            ]
            return super().apply_gradients(reduced, *args, **kwargs)

    _DistributedKerasOptimizer.__name__ = (
        "Distributed" + base_cls.__name__
    )
    return _DistributedKerasOptimizer.from_config(optimizer.get_config())
