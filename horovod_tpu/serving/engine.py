"""InferenceEngine: prefill/decode split over compiled executables.

The serving analog of the PR 1 fusion-executor rework, with sequence
length where byte size was:

* **Prefill** is shape-polymorphic in the prompt length, so it compiles
  through a two-tier executor cache: a *bucket* tier keyed by the
  power-of-two padded length (any prompt length runs immediately, pad
  tokens are masked garbage the causal mask never attends), and an
  *exact* tier a recurring length is promoted into after
  ``promote_after`` sightings (no pad FLOPs for the lengths a workload
  actually serves). Prompts past the bucket ceiling run as successive
  ceiling-sized chunks through the SAME cache-threaded executables
  (each chunk attends to everything before it), so long prompts cost
  compile entries only for the ceiling and the remainder bucket.
* **Decode** is ONE fixed-shape jitted step — ``[slots]`` last tokens +
  ``[slots]`` cache indices in, ``[slots]`` next tokens + the updated
  cache out — over the slot-batched KV cache, which is DONATED through
  every prefill/decode executable so steady-state serving allocates no
  new cache buffers and never retraces: admissions, evictions and slot
  reuse change data, never shapes.

Executables are built ahead-of-time (``jit(...).lower(...).compile()``)
and held in engine-owned tables, so compile counts are exact, assertable
numbers (``stats()``), not inferences about jit's internal cache.

The model contract (``models/transformer.py``): ``model_fn(params,
tokens, cache, cache_index) -> (logits, new_cache)`` with per-slot
write positions and the global causal mask — any model implementing it
serves; flax Transformer modules are adapted automatically.
"""

from __future__ import annotations

import collections
import threading
from typing import Callable, Dict, Optional

import numpy as np

from ..common.logging import get_logger
from ..common.metrics import registry as _metrics
from .kv_cache import KVCacheManager

_log = get_logger("serve.engine")

DEFAULT_MIN_BUCKET = 8
DEFAULT_PROMOTE_AFTER = 2
# exact-tier LRU bound: one executable per distinct recurring prompt
# length; the bucket tier below it is bounded by log2(ceiling) anyway
DEFAULT_EXACT_CAPACITY = 32


def next_pow2(n: int) -> int:
    return 1 << max(int(n) - 1, 0).bit_length()


def _as_model_fn(model) -> Callable:
    """Adapt a flax module (``.apply``; params or full variables dict)
    to the positional model contract; pass callables through."""
    apply = getattr(model, "apply", None)
    if apply is None:
        if not callable(model):
            raise TypeError(
                f"model must be a flax module or a model_fn callable, "
                f"got {type(model)!r}"
            )
        return model

    def model_fn(params, tokens, cache, cache_index):
        variables = (
            params
            if isinstance(params, dict) and "params" in params
            else {"params": params}
        )
        return apply(
            variables, tokens, train=False,
            cache=cache, cache_index=cache_index,
        )

    return model_fn


def _default_cache_factory(model):
    cfg = getattr(model, "cfg", None)
    if cfg is None:
        raise TypeError(
            "cache_factory= is required when the model does not carry "
            "a TransformerConfig (.cfg) to derive the KV layout from"
        )
    from ..models.transformer import init_cache

    return lambda batch, max_len: init_cache(cfg, batch, max_len)


class InferenceEngine:
    """Compiled prefill/decode over a slot-batched, donated KV cache.

    Not thread-safe by design: exactly one consumer (the batcher's step
    loop) drives it, which is also what makes the donated cache carry
    sound — there is never a second reference to consume.
    """

    def __init__(
        self,
        model,
        params,
        *,
        slots: int,
        max_len: int,
        cache_factory=None,
        min_bucket: int = DEFAULT_MIN_BUCKET,
        prefill_ceiling: Optional[int] = None,
        promote_after: int = DEFAULT_PROMOTE_AFTER,
        exact_capacity: int = DEFAULT_EXACT_CAPACITY,
        donate: Optional[bool] = None,
        mesh=None,
        tp_axis: str = "tp",
    ) -> None:
        self._model_fn = _as_model_fn(model)
        self._params = params
        if cache_factory is None:
            cache_factory = _default_cache_factory(model)
        self.manager = KVCacheManager(
            cache_factory, slots=slots, max_len=max_len,
            mesh=mesh, tp_axis=tp_axis,
        )
        self.slots = self.manager.slots
        self.max_len = self.manager.max_len
        self.min_bucket = max(int(min_bucket), 1)
        # bucket ceiling: a power of two that FITS the cache — clamp to
        # the largest pow2 <= max_len, never round past it (a prefill
        # width beyond max_len would build kv updates larger than the
        # cache leaf and fail at compile)
        floor_pow2 = 1 << (self.max_len.bit_length() - 1)
        ceiling = int(prefill_ceiling) if prefill_ceiling else floor_pow2
        self.prefill_ceiling = min(next_pow2(ceiling), floor_pow2)
        self.promote_after = max(int(promote_after), 1)
        self._mesh = mesh
        if donate is None:
            import jax

            donate = jax.devices()[0].platform in (
                "tpu", "gpu", "cuda", "rocm",
            )
        self.donate = bool(donate)
        # two-tier prefill executor cache (PR 1 design on the length
        # axis) + the one decode executable
        self._prefill_exact: "collections.OrderedDict" = (
            collections.OrderedDict()
        )
        self._prefill_bucket: Dict[int, object] = {}
        self._seen: "collections.OrderedDict" = collections.OrderedDict()
        self._exact_capacity = max(int(exact_capacity), 1)
        self._decode_exe = None
        self._lock = threading.Lock()  # guards counters for stats readers
        self._counters = collections.Counter()

    # -------------------------------------------------------- compile layer

    def _out_shardings(self):
        """With a tp-sharded cache, pin the outputs: the cache keeps
        its sharding (a changed output sharding would break the donated
        carry on the NEXT call), the token output is replicated."""
        if self.manager.sharding is None:
            return None
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        rep = NamedSharding(self._mesh, P())
        cache_sh = jax.tree_util.tree_map(
            lambda _: self.manager.sharding, self.manager.cache
        )
        return (rep, cache_sh)

    def _compile(self, fn, args, kind: str):
        import jax

        kwargs = {}
        if self.donate:
            kwargs["donate_argnums"] = (1,)  # the cache carry
        out_sh = self._out_shardings()
        if out_sh is not None:
            kwargs["out_shardings"] = out_sh
        exe = jax.jit(fn, **kwargs).lower(*args).compile()
        with self._lock:
            self._counters[f"{kind}_compiles"] += 1
        return exe

    def _prefill_fn(self, width: int):
        """Build the prefill computation for a fixed token width: slice
        the slot's cache row, run the cache-threaded model over the
        chunk, write the row back, emit the greedy next token at
        ``last_pos`` (pad positions beyond it are causal-masked junk a
        later write overwrites before it is ever attendable)."""
        import jax
        import jax.numpy as jnp
        from jax import lax

        model_fn = self._model_fn

        def fn(params, cache, tokens, slot, start, last_pos):
            slot_cache = jax.tree_util.tree_map(
                lambda leaf: lax.dynamic_slice_in_dim(leaf, slot, 1, 0),
                cache,
            )
            logits, new_slot = model_fn(
                params, tokens, slot_cache, jnp.reshape(start, (1,))
            )
            cache = jax.tree_util.tree_map(
                lambda leaf, upd: lax.dynamic_update_slice_in_dim(
                    leaf, upd, slot, 0
                ),
                cache,
                new_slot,
            )
            row = lax.dynamic_index_in_dim(
                logits[0], last_pos, axis=0, keepdims=False
            )
            return jnp.argmax(row).astype(jnp.int32), cache

        return fn

    def _decode_fn(self):
        import jax.numpy as jnp

        model_fn = self._model_fn

        def fn(params, cache, tokens, lengths):
            logits, cache = model_fn(
                params, tokens[:, None], cache, lengths
            )
            return (
                jnp.argmax(logits[:, 0, :], axis=-1).astype(jnp.int32),
                cache,
            )

        return fn

    def _prefill_args(self, width: int):
        return (
            self._params,
            self.manager.cache,
            np.zeros((1, width), np.int32),
            np.int32(0),
            np.int32(0),
            np.int32(0),
        )

    def _bucket_exe(self, width: int):
        """Bucket-tier lookup/compile for an executable of exactly
        ``width`` tokens (shared by the two-tier path and the
        chunked-prefill loop — one home for the hit accounting)."""
        exe = self._prefill_bucket.get(width)
        if exe is None:
            exe = self._compile(
                self._prefill_fn(width),
                self._prefill_args(width),
                "prefill",
            )
            self._prefill_bucket[width] = exe
        else:
            self._counters["prefill_bucket_hits"] += 1
        return exe

    def _get_prefill_exe(self, length: int):
        """Two-tier lookup for the final (or only) chunk of ``length``
        tokens: exact executable if promoted, else the power-of-two
        bucket. Returns ``(exe, width)``."""
        exact = self._prefill_exact
        if length in exact:
            exact.move_to_end(length)
            self._counters["prefill_exact_hits"] += 1
            return exact[length], length
        count = self._seen.get(length, 0) + 1
        self._seen[length] = count
        self._seen.move_to_end(length)
        while len(self._seen) > 4 * self._exact_capacity:
            self._seen.popitem(last=False)  # bounded, PR 1 lesson
        if count >= self.promote_after:
            exe = self._compile(
                self._prefill_fn(length),
                self._prefill_args(length),
                "prefill",
            )
            exact[length] = exe
            self._counters["prefill_promotions"] += 1
            while len(exact) > self._exact_capacity:
                exact.popitem(last=False)
            return exe, length
        bucket = min(
            max(next_pow2(length), self.min_bucket), self.prefill_ceiling
        )
        exe = self._bucket_exe(bucket)
        self._counters["prefill_pad_tokens"] += bucket - length
        return exe, bucket

    # ------------------------------------------------------------ execution

    def prefill(self, slot: int, prompt) -> int:
        """Run the prompt through the slot's cache row; returns the
        first greedy token. Prompts past the bucket ceiling stream as
        ceiling-sized chunks (each attends to the cache written so
        far), the remainder through the two-tier cache like any short
        prompt."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        n = prompt.size
        if not 0 < n <= self.max_len:
            raise ValueError(
                f"prompt length {n} outside (0, {self.max_len}]"
            )
        start = 0
        ceiling = self.prefill_ceiling
        while n - start > ceiling:
            exe = self._bucket_exe(ceiling)
            self._counters["chunked_prefill_chunks"] += 1
            tok, self.manager.cache = exe(
                self._params,
                self.manager.cache,
                prompt[None, start:start + ceiling],
                np.int32(slot),
                np.int32(start),
                np.int32(ceiling - 1),
            )
            start += ceiling
        tail = n - start
        exe, width = self._get_prefill_exe(tail)
        tokens = np.zeros((1, width), np.int32)
        tokens[0, :tail] = prompt[start:]
        tok, self.manager.cache = exe(
            self._params,
            self.manager.cache,
            tokens,
            np.int32(slot),
            np.int32(start),
            np.int32(tail - 1),
        )
        self.manager.set_length(slot, n)
        self._counters["prefills"] += 1
        return int(tok)

    def decode_step(self, tokens: np.ndarray) -> np.ndarray:
        """ONE fixed-shape step over every slot: feed each slot's last
        token at its cache index, return each slot's greedy next token.
        Inactive slots (length 0) compute masked junk at position 0
        that the next occupant's prefill overwrites — the price of a
        shape that never changes is a little wasted compute, never a
        retrace."""
        tokens = np.asarray(tokens, np.int32).reshape(self.slots)
        lengths = self.manager.lengths_array()
        if self._decode_exe is None:
            self._decode_exe = self._compile(
                self._decode_fn(),
                (self._params, self.manager.cache, tokens, lengths),
                "decode",
            )
        out, self.manager.cache = self._decode_exe(
            self._params, self.manager.cache, tokens, lengths
        )
        self._counters["decode_steps"] += 1
        return np.asarray(out)

    # ----------------------------------------------------------------- stats

    def stats(self) -> Dict[str, float]:
        with self._lock:
            out = dict(self._counters)
        for key in (
            "prefill_compiles", "decode_compiles", "prefills",
            "decode_steps", "prefill_exact_hits", "prefill_bucket_hits",
            "prefill_promotions", "prefill_pad_tokens",
            "chunked_prefill_chunks",
        ):
            out.setdefault(key, 0)
        out["prefill_exact_entries"] = len(self._prefill_exact)
        out["prefill_bucket_entries"] = len(self._prefill_bucket)
        return out

    def publish(self) -> None:
        _metrics.update("serve", self.stats())
