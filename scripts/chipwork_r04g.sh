#!/usr/bin/env bash
# Round-4 chip work, part g: consolidated resume. The c->d->e->f chain
# was killed by a driver restart mid-list (last in-flight: gpt2_blk256).
# This part re-runs EVERYTHING still missing from those parts in one
# sequential queue, highest-value first per VERDICT.md item 2:
#   flash sweep completion -> bert fresh -> vit_b16 -> TPU allreduce
#   busbw -> LM remat/batch/head sweeps -> fused-xent A/B -> resnet
#   clean A/B -> published-family models.
# Same discipline as part c: skip-if-done, one attempt, backend-probe
# gate, one retry. One TPU process at a time.
set -uo pipefail
cd "$(dirname "$0")/.."
mkdir -p bench_results
R=r04

finalize() {  # adopt a finished .tmp if it has JSON
  local out="bench_results/$1_${R}.json"
  if [ -f "$out.tmp" ] && grep -qE '^\{' "$out.tmp"; then
    grep -E '^\{' "$out.tmp" > "$out"
    rm -f "$out.tmp" "bench_results/$1_${R}.err"
    echo "=== finalized $1 from previous part:" >&2
    cat "$out" >&2
  fi
}

echo "=== waiting for in-flight bench processes" >&2
while pgrep -f "chipwork_r04[cdef].sh" >/dev/null 2>&1 \
      || pgrep -f "python bench(_lm|_allreduce)?.py" >/dev/null 2>&1; do
  sleep 60
done
finalize gpt2_blk256

probe_backend() {
  timeout 7200 python - <<'PYEOF' >/dev/null 2>&1
import jax
assert jax.devices()[0].platform == "tpu"
PYEOF
}

wait_backend() {
  echo "=== probing TPU backend $(date -u +%H:%M)" >&2
  until probe_backend; do
    echo "backend still down $(date -u +%H:%M); retry in 300s" >&2
    sleep 300
  done
  echo "=== backend UP $(date -u +%H:%M)" >&2
}

run_one() {
  local name="$1"; shift
  local out="bench_results/${name}_${R}.json"
  echo "=== $name $(date -u +%H:%M)" >&2
  "$@" > "$out.tmp" 2> "bench_results/${name}_${R}.err"
  if grep -qE '^\{' "$out.tmp"; then
    grep -E '^\{' "$out.tmp" > "$out"
    rm -f "$out.tmp" "bench_results/${name}_${R}.err"
    cat "$out" >&2
    return 0
  fi
  rm -f "$out.tmp"
  return 1
}

cap() {
  local name="$1"
  local out="bench_results/${name}_${R}.json"
  if [ -s "$out" ]; then
    echo "=== $name already captured, skipping" >&2
    return 0
  fi
  if run_one "$@"; then return 0; fi
  echo "=== $name failed; gating on backend health before one retry" >&2
  wait_backend
  if run_one "$@"; then return 0; fi
  echo "FAILED $name twice with backend up (see .err)" >&2
  return 1
}

# -- flash block sweep (complete part c's list)
cap gpt2_blk256        env BENCH_MODEL=gpt2_medium BENCH_FLASH_BLOCK=256 python bench_lm.py
cap gpt2_blk512        env BENCH_MODEL=gpt2_medium BENCH_FLASH_BLOCK=512 python bench_lm.py

# -- fresh BERT + the two VERDICT-named missing baseline configs
cap bert_large         env BENCH_MODEL=bert_large python bench_lm.py
cap vit_b16            env BENCH_INNER=1 BENCH_MODEL=vit_b16 python bench.py
cap allreduce          python bench_allreduce.py

# -- LM remat/batch/seq sweeps (MFU-push experiments, docs/perf.md)
cap gpt2_noremat_b16   env BENCH_MODEL=gpt2_medium BENCH_BATCH=16 BENCH_REMAT=0 python bench_lm.py
cap gpt2_seq1024       env BENCH_MODEL=gpt2_medium BENCH_BATCH=4 BENCH_SEQ=1024 python bench_lm.py
cap bert_noremat_b16   env BENCH_MODEL=bert_large BENCH_BATCH=16 BENCH_REMAT=0 python bench_lm.py

# -- part d: LM head precision controls + best-config candidate
cap gpt2_head_fp32     env BENCH_MODEL=gpt2_medium BENCH_HEAD=fp32 python bench_lm.py
cap bert_head_fp32     env BENCH_MODEL=bert_large BENCH_HEAD=fp32 python bench_lm.py
cap gpt2_best          env BENCH_MODEL=gpt2_medium BENCH_BATCH=16 BENCH_REMAT=0 BENCH_FLASH_BLOCK=256 python bench_lm.py

# -- part f: chunked fused linear-cross-entropy A/B
cap gpt2_fxent         env BENCH_MODEL=gpt2_medium BENCH_FUSED_XENT=1 python bench_lm.py
cap gpt2_best_fxent    env BENCH_MODEL=gpt2_medium BENCH_BATCH=16 BENCH_REMAT=0 BENCH_FLASH_BLOCK=256 BENCH_FUSED_XENT=1 python bench_lm.py
cap gpt2_b32_fxent     env BENCH_MODEL=gpt2_medium BENCH_BATCH=32 BENCH_REMAT=0 BENCH_FUSED_XENT=1 python bench_lm.py
cap bert_fxent         env BENCH_MODEL=bert_large BENCH_BATCH=16 BENCH_REMAT=0 BENCH_FUSED_XENT=1 python bench_lm.py

# -- clean resnet stem A/B on an idle host + large batch
cap resnet50_b512      env BENCH_INNER=1 BENCH_BATCH=512 python bench.py
cap resnet50_clean     env BENCH_INNER=1 python bench.py
cap resnet50_s2d_clean env BENCH_INNER=1 BENCH_STEM=space_to_depth python bench.py

# -- part e: published-family models
cap inception_v3       env BENCH_INNER=1 BENCH_MODEL=inception_v3 python bench.py
cap resnet101          env BENCH_INNER=1 BENCH_MODEL=resnet101 python bench.py
cap vgg16              env BENCH_INNER=1 BENCH_MODEL=vgg16 BENCH_BATCH=128 python bench.py

echo "=== chipwork_r04g complete $(date -u +%H:%M)" >&2
