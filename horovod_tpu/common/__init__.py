"""Framework-agnostic core: config, topology, global state, process sets,
timeline, stall inspection, autotune. TPU-native rebuild of
horovod/common/ [V] (SURVEY.md §2.1)."""
