"""Runtime collective-schedule audit: the divergence-before-deadlock
detector.

A rank whose compiled collective schedule diverges from the gang's —
a tuner that picked a different wire, a skewed fusion composition, a
code path taken on one host only — is the canonical distributed
deadlock precursor (the failure Horovod's timeline was built to debug,
arXiv 1802.05799): every rank blocks in a collective the others never
entered, and nothing says so until a heartbeat stall minutes later.

This module turns that into a diagnosed quarantine:

* every eager fused dispatch folds ``(op kind, fused-entry composition
  hash, wire format, pset id)`` into a per-rank ROLLING fingerprint
  (one SHA-256 update per dispatch — sub-microsecond; the
  :class:`~..ops.fusion.FusionManager` calls :func:`record` from its
  dispatch path);
* on the ``HOROVOD_AUDIT_STEPS`` cadence (the PR 7 parameter-digest
  cadence — :func:`~..audit.audit` publishes both), ranks publish
  ``(step, fingerprint, dispatch_count)`` plus a bounded ring of
  recent per-dispatch digests through the rendezvous KV
  (``runner/rendezvous.py`` ``put_sched``);
* the elastic driver's ``_poll_audit`` compares the gang's
  fingerprints at the newest quorum step — majority wins, matching
  the parameter-digest arbitration — and quarantines divergent ranks
  with reason ``sched_divergence``, logging the FIRST divergent
  dispatch index recovered from the rings.

``HOROVOD_SCHED_AUDIT=0`` disables recording and publication.
Identical schedules fold to identical fingerprints by construction:
the folded key is built from rank-invariant facts (shapes, dtypes,
wire, pset id), never from rank ids or payload values.
"""

from __future__ import annotations

import hashlib
import threading
from collections import deque
from typing import Dict, Optional, Tuple

from ..common.logging import get_logger

_log = get_logger("sched_audit")

# per-dispatch digests kept for first-divergent-index recovery; the KV
# payload carries the newest _RING entries (bounded: the ring exists to
# LOCATE a divergence, the fingerprint to DETECT it)
_RING = 128
_DIGEST_CHARS = 16  # 64 bits of each per-dispatch digest ride the KV


class ScheduleRecorder:
    """Per-process rolling schedule fingerprint."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._hash = hashlib.sha256()
        self._count = 0
        self._ring: "deque[Tuple[int, str]]" = deque(maxlen=_RING)

    def record(
        self,
        kind: str,
        composition,
        wire: Optional[str] = None,
        pset: int = 0,
    ) -> None:
        """Fold one dispatch. ``composition`` is any stable,
        rank-invariant description of the fused batch (entry names +
        shapes + dtypes); it is hashed, never stored."""
        key = repr((str(kind), repr(composition), wire or "fp32", int(pset)))
        entry = hashlib.sha256(key.encode()).hexdigest()[:_DIGEST_CHARS]
        with self._lock:
            self._hash.update(entry.encode())
            self._ring.append((self._count, entry))
            self._count += 1

    @property
    def dispatch_count(self) -> int:
        with self._lock:
            return self._count

    def fingerprint(self) -> str:
        with self._lock:
            return self._hash.copy().hexdigest()

    def snapshot(self) -> dict:
        """The publishable view: rolling fingerprint, total dispatch
        count, and the recent-dispatch ring as ``[[index, digest],...]``."""
        with self._lock:
            return {
                "fingerprint": self._hash.copy().hexdigest(),
                "dispatches": self._count,
                "ring": [[i, d] for i, d in self._ring],
            }

    def reset(self) -> None:
        with self._lock:
            self._hash = hashlib.sha256()
            self._count = 0
            self._ring.clear()


_recorder = ScheduleRecorder()


def recorder() -> ScheduleRecorder:
    return _recorder


def enabled() -> bool:
    from ..common import basics

    return bool(basics.live_config().sched_audit)


def record(
    kind: str, composition, wire: Optional[str] = None, pset: int = 0
) -> None:
    """Dispatch-path hook (FusionManager): fold one dispatch into the
    process fingerprint. No-op when HOROVOD_SCHED_AUDIT=0."""
    if not enabled():
        return
    _recorder.record(kind, composition, wire=wire, pset=pset)


def reset() -> None:
    """Elastic restart / test hook: a new gang starts a new schedule."""
    _recorder.reset()


def publish(step: int, rank: Optional[int] = None) -> bool:
    """Publish ``(step, fingerprint, dispatch_count, ring)`` to the
    rendezvous KV beside the parameter digests. Called by
    ``hvd.audit`` on the shared cadence; callable directly by loops
    that audit schedules without digesting parameters. Returns False
    when disabled or no rendezvous is configured."""
    if not enabled():
        return False
    from ..common import basics
    from ..common.metrics import registry as _metrics

    if rank is None:
        rank = basics.rank() if basics.is_initialized() else 0
    snap = _recorder.snapshot()
    _metrics.gauge("audit.sched_dispatches", snap["dispatches"])
    _metrics.gauge("audit.sched_last_step", int(step))
    ok = _publish_kv(int(rank), int(step), snap)
    if ok:
        _metrics.counter("audit.sched_published")
    return ok


def _publish_kv(rank: int, step: int, snap: dict) -> bool:
    """Best-effort KV publication through the shared cached client in
    ``audit.py`` (same rendezvous, same failure posture: silence).
    NB: ``from .. import audit`` would pick up the ``hvd.audit``
    FUNCTION (the package re-export shadows the module attribute);
    import the symbol from the module directly."""
    from ..audit import _cached_kv_client
    from ..runner.rendezvous import put_sched

    client = _cached_kv_client()
    if client is None:
        return False
    try:
        put_sched(
            client, rank, step, snap["fingerprint"], snap["dispatches"],
            snap["ring"],
        )
        return True
    except Exception:
        _log.debug("sched publish failed", exc_info=True)
        return False


def find_divergent(
    entries: Dict[int, dict],
) -> Optional[Tuple[int, Tuple[int, ...]]]:
    """Driver-side comparison over ``{rank: {"step", "fingerprint",
    ...}}`` (the shape ``read_sched_fingerprints`` returns): newest
    step reported by >= 2 ranks, majority fingerprint wins, ties break
    toward the lowest rank — the exact arbitration of the parameter
    audit, reused from ``audit.find_divergent``."""
    from ..audit import find_divergent as _fd

    shaped = {}
    for rank, payload in entries.items():
        if isinstance(payload, dict) and "fingerprint" in payload:
            shaped[rank] = {
                "step": payload.get("step"),
                "digest": payload.get("fingerprint"),
            }
    return _fd(shaped)


def first_divergent_index(
    bad: dict, good: dict
) -> Optional[int]:
    """Locate the first dispatch where a divergent rank's ring
    disagrees with a majority rank's: the driver logs this index so a
    postmortem starts at the exact dispatch, not at 'the fingerprints
    differ'. None when the rings no longer overlap (divergence is
    older than the ring) — the dispatch-count delta is the fallback
    breadcrumb."""
    ring_a = {int(i): d for i, d in (bad.get("ring") or [])}
    ring_b = {int(i): d for i, d in (good.get("ring") or [])}
    shared = sorted(set(ring_a) & set(ring_b))
    for idx in shared:
        if ring_a[idx] != ring_b[idx]:
            return idx
    if shared:
        # shared prefix agrees: the divergence is the first dispatch
        # past the common range — one rank ran further than the other
        # (both rings may be full, so compare frontiers, not lengths)
        hi_a, hi_b = max(ring_a), max(ring_b)
        if hi_a != hi_b:
            return min(hi_a, hi_b) + 1
    return None
