"""Eager-mode dispatch: tensor queue, fusion buffer, cycle batching, handles.

This module is the TPU re-design of the reference's core machine —
background loop + tensor queue + fusion buffer + response cache
(ref: horovod/common/operations.cc RunLoopOnce, tensor_queue.cc,
fusion_buffer_manager.cc, response_cache.cc [V]; SURVEY.md §2.1, §3.2) —
re-thought for a single controller:

* No negotiation: every process sees the same eager dispatch order, so
  tensor-readiness agreement is structural. What the reference's controller
  negotiates dynamically, the single controller knows trivially.
* Fusion survives: many small eager collectives are still slow if dispatched
  one XLA executable each. Entries accumulate in a queue; a *cycle* flush
  batches same-key collectives (`HOROVOD_FUSION_THRESHOLD` caps each
  fused batch, `HOROVOD_CYCLE_TIME` bounds queue latency — same env
  contract, same semantics). Fusion covers the whole collective family:
  allreduce AND same-key broadcast / allgather / reducescatter groups
  ride the same pack → collective → unpack machinery.
* One fused cycle is ONE compiled XLA executable (the in-JIT pack path,
  `HOROVOD_FUSION_INJIT`, default on): the cached executor takes the
  batch's raw per-entry tensors as arguments and performs the
  flatten/concat pack, the collective, and the per-entry split/reshape
  unpack entirely inside `jax.jit`. XLA fuses the pack into the
  collective's producer and the unpack into its consumers (the EQuARX
  observation, arXiv 2506.17615), eliminating the two extra full HBM
  passes and the ~2N Python dispatches the host-side pack paid. Inputs
  are donated (`HOROVOD_FUSION_DONATE`, default auto: on for TPU/GPU)
  so the fusion buffer aliases the argument storage instead of doubling
  peak HBM — eager collectives CONSUME their inputs on backends with
  donation support, matching the reference's in-place `allreduce_`
  semantics.
* The executor cache is stabilized under batch-composition churn by
  SHAPE BUCKETING (`HOROVOD_FUSION_BUCKETS`, default on): the fused
  buffer's per-rank row is rounded up to the next power-of-two element
  count (zero-pad tail, sliced off inside the program; zero is the
  identity of every supported reduction, Adasum's inner products
  included) and executors are cached in two tiers —

    exact tier  (op-key, bucket, per-entry shape tuple) → the fused
                in-JIT executable, one dispatch per batch, packed
                UNPADDED (its key pins the shapes, so padding would
                only put dead zeros on the wire of a stable job);
    bucket tier (op-key, bucket)                        → a padded
                buffer → buffer collective program, composition-
                independent.

  A batch whose exact composition is cached dispatches the single
  fused executable. A NEW composition inside an already-seen bucket
  falls back to the bucket-tier program (host-side pack into the
  padded buffer — the pre-rework dispatch path) instead of compiling,
  so a long eager job with a drifting tensor set stops recompiling
  every cycle; compositions seen `HOROVOD_FUSION_PROMOTE_AFTER` times
  (default 2) are promoted to their own exact executable. Padding cost
  is observable: `bucket_pad_bytes`, per-cycle pad, recompile and
  dispatch counts all land in cache_stats()/common.metrics, and the
  autotune parameter manager is fed useful-vs-wire bytes so the GP
  scores goodput, not padded throughput.
* The response cache's job (skip re-negotiation for repeating tensor
  sets) is played by this executor cache: repeated (op, dtype, shape)
  batches hit an already-compiled XLA executable
  (`HOROVOD_CACHE_CAPACITY` bounds both tiers via one LRU).
* The fused buffer can traverse the wire QUANTIZED
  (`HOROVOD_FUSION_WIRE={fp32,bf16,int8,auto}`): on the int8 wire the
  compiled program block-quantizes the packed buffer (one scale per
  `HOROVOD_FUSION_WIRE_BLOCK` elements, stochastic rounding seeded per
  rank and dispatch), runs the quantized reduce-scatter/all-gather
  recipe of `traced.quantized_allreduce`, and dequantizes before the
  unpack — quantize once per BATCH instead of once per tensor, still
  exactly one dispatch, ~4x fewer wire bytes for fp32 payloads
  (EQuARX, arXiv 2506.17615). `auto` picks the format per bucket tier
  online by goodput (common/autotune.py WireTuner); `bf16` moves the
  buffer as a half-width cast; `HOROVOD_FUSION_WIRE_HIER` places bf16
  on the intra-host stage and int8 on the cross-host stage only.
  Error-feedback residuals are sliced per entry from the fused
  residual buffer (`allreduce(..., return_residual=True)`), so EF
  composes with fusion.
* Flushing is cooperative (on enqueue-over-threshold, cycle expiry at next
  enqueue, or synchronize()) — there is no background thread to race with
  JAX dispatch.

Handles reproduce the async API: `allreduce_async_` returns a handle;
`synchronize(handle)` blocks (ref: horovod/torch/handle_manager.cc [V]).
"""

from __future__ import annotations

import dataclasses
import re
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..common.compat import shard_map
from ..common.topology import WORLD_AXIS
from ..common.process_sets import ProcessSet
from ..common.logging import get_logger
from ..analysis import sched_audit as _sched_audit
from .reduction_ops import Average, Sum, Adasum, Min, Max, Product, ReduceOp

_log = get_logger("fusion")


@dataclasses.dataclass
class _Entry:
    """One pending collective (ref: TensorTableEntry in common.h [V])."""

    name: str
    kind: str  # 'allreduce' | 'allgather' | 'broadcast' | 'alltoall' | 'reducescatter'
    payload: Any  # rank-major jax.Array [world, ...]
    op: ReduceOp = Average
    prescale: float = 1.0
    postscale: float = 1.0
    root_rank: int = 0
    process_set: Optional[ProcessSet] = None
    mask: Optional[np.ndarray] = None  # [world] bool; False = rank joined
    extra: Any = None  # op-specific (e.g. uneven-length info)
    handle: "Handle" = None
    enqueue_t: float = 0.0
    group_id: Optional[int] = None  # grouped_allreduce membership
    wire: Optional[str] = None  # per-entry wire override (None = manager)
    wire_block: Optional[int] = None  # per-entry block size (compressor's)
    want_residual: bool = False  # error-feedback carry (int8 wire only)


class Handle:
    """Async completion handle (ref: handle_manager.cc [V])."""

    def __init__(self, fusion: "FusionManager", entry: _Entry):
        self._fusion = fusion
        self._entry = entry
        self._result = None
        self._done = False

    def _fulfill(self, result) -> None:
        self._result = result
        self._done = True

    def poll(self) -> bool:
        """Non-blocking done check; also drives a cooperative cycle tick."""
        if not self._done:
            self._fusion.maybe_cycle()
        return self._done

    def wait(self):
        if not self._done:
            self._fusion.flush()
        assert self._done, "flush did not fulfill handle"
        return self._result


_SCHED_NONAME = re.compile(r"^(\w+)\.noname\.\d+(\..+)?$")


def _sched_entry_name(name: str) -> str:
    """Schedule-fingerprint view of an entry name: auto-generated
    ``<op>.noname.<counter>`` labels collapse to the op prefix — the
    process-global counter only restates dispatch order (which the
    rolling fold already encodes) and would make two identical
    schedules diverge on counter offset alone (e.g. a rejoined worker
    restarting its counter at 0). Grouped entries
    (``<op>.noname.<counter>.<i>``) keep the member index ``<i>`` —
    that part IS schedule identity. User-supplied names fold as-is."""
    m = _SCHED_NONAME.match(name or "")
    if m is None:
        return name or ""
    return m.group(1) + (m.group(2) or "")


def _group_key(e: _Entry) -> Tuple:
    mask_key = None if e.mask is None else e.mask.tobytes()
    pset = 0 if e.process_set is None else e.process_set.process_set_id
    return (
        e.kind,
        int(e.op),
        e.payload.dtype.name,
        e.prescale,
        e.postscale,
        e.root_rank,
        pset,
        mask_key,
        e.extra is not None,  # v-variant allgather never fuses with even
        e.wire,  # entries on different wire formats never share a batch
        e.wire_block,
        e.want_residual,
    )


def _bucket_elems(elems: int, bucketing: bool) -> int:
    """Round a per-rank row length up to the next power of two."""
    if not bucketing or elems <= 1:
        return max(elems, 1)
    return 1 << (elems - 1).bit_length()


@dataclasses.dataclass(frozen=True)
class _BatchPlan:
    """Static pack/unpack geometry of one fused batch."""

    family: str  # 'allreduce' | 'adasum_pset' | 'broadcast' | 'allgather' | 'reducescatter'
    shapes: Tuple[Tuple[int, ...], ...]  # per-entry payload shapes
    dtype: str
    sizes: Tuple[int, ...]  # per-entry packed columns (per-rank-chunk for rs)
    useful: int  # packed columns before padding
    bucket: int  # packed columns after bucketing
    world: int
    n_ranks: int  # participating ranks (world, or process-set size)
    itemsize: int

    @property
    def pad_elems(self) -> int:
        return self.bucket - self.useful

    @property
    def pad_bytes(self) -> int:
        # padding is carried on every rank's row (and every rank chunk
        # for reducescatter, whose pad rides inside each chunk)
        rows = self.world * (
            self.n_ranks if self.family == "reducescatter" else 1
        )
        return self.pad_elems * rows * self.itemsize


@dataclasses.dataclass(frozen=True)
class _ExecSpec:
    """One batch's resolved execution recipe: geometry, cache keys, the
    per-shard core builder, and the wire format the fused buffer will
    traverse the collective in."""

    plan: _BatchPlan
    core_key: Tuple
    builder: Callable
    needs_keep: bool = False  # adasum_pset: dynamic join-mask argument
    needs_seed: bool = False  # quantized wire: per-dispatch rounding seed
    want_res: bool = False  # error-feedback residual outputs
    wire: str = "fp32"  # INTER-hop (or flat) wire: 'fp32' | 'bf16' | 'int8'
    hier_n: Optional[int] = None  # two-level: inter-group (slice) count
    intra_n: Optional[int] = None  # two-level: chips per slice (L)
    intra_wire: str = "fp32"  # two-level: the intra-hop wire format
    tuned: bool = False  # wire chosen by the WireTuner (auto mode)
    block: Optional[int] = None  # int8: elements per block scale


def _make_plan(
    family: str, batch: List[_Entry], world: int, n_ranks: int, bucketing: bool
) -> _BatchPlan:
    shapes = tuple(tuple(e.payload.shape) for e in batch)
    itemsize = int(batch[0].payload.dtype.itemsize)
    if family == "reducescatter":
        sizes = tuple(
            int(np.prod(s[1:], dtype=np.int64)) // n_ranks for s in shapes
        )
    else:
        sizes = tuple(int(np.prod(s[1:], dtype=np.int64)) for s in shapes)
    useful = sum(sizes)
    return _BatchPlan(
        family=family,
        shapes=shapes,
        dtype=batch[0].payload.dtype.name,
        sizes=sizes,
        useful=useful,
        bucket=_bucket_elems(useful, bucketing),
        world=world,
        n_ranks=n_ranks,
        itemsize=itemsize,
    )


def _pack(tensors, plan: _BatchPlan):
    """Flatten + concat + zero-pad the batch into the fused buffer.

    Runs either under `jax.jit` tracing (the in-JIT path — XLA fuses it
    into the collective's producer) or eagerly (the bucket-tier / legacy
    host-pack path). Zero padding is safe for every reduction: zeros are
    the identity of sum/avg contributions and of Adasum's inner
    products, and min/max/product padding lanes are sliced off unread.
    """
    world = plan.world
    if plan.family == "reducescatter":
        # chunk-major layout: [world, n_ranks, chunk]; rank r's result is
        # the concatenation of every entry's r-th chunk
        mats = [t.reshape(world, plan.n_ranks, -1) for t in tensors]
        buf = mats[0] if len(mats) == 1 else jnp.concatenate(mats, axis=2)
        if plan.pad_elems:
            buf = jnp.pad(buf, ((0, 0), (0, 0), (0, plan.pad_elems)))
    else:
        mats = [t.reshape(world, -1) for t in tensors]
        buf = mats[0] if len(mats) == 1 else jnp.concatenate(mats, axis=1)
        if plan.pad_elems:
            buf = jnp.pad(buf, ((0, 0), (0, plan.pad_elems)))
    return buf


def _unpack(out, plan: _BatchPlan):
    """Split the collective's output back into per-entry results,
    slicing the bucket padding off. Inverse of `_pack` modulo each
    family's output geometry."""
    pieces = []
    off = 0
    if plan.family == "allgather":
        # out: [world, n_ranks, bucket] → per entry [world, n_ranks, n, ...]
        for shape, sz in zip(plan.shapes, plan.sizes):
            pieces.append(
                out[:, :, off : off + sz].reshape(
                    (plan.world, plan.n_ranks) + shape[1:]
                )
            )
            off += sz
    elif plan.family == "reducescatter":
        # out: [world, bucket] → per entry [world, n/n_ranks, ...]
        for shape, sz in zip(plan.shapes, plan.sizes):
            pieces.append(
                out[:, off : off + sz].reshape(
                    (plan.world, shape[1] // plan.n_ranks) + tuple(shape[2:])
                )
            )
            off += sz
    else:
        # out: [world, bucket] → per entry payload-shaped
        for shape, sz in zip(plan.shapes, plan.sizes):
            pieces.append(out[:, off : off + sz].reshape(shape))
            off += sz
    return pieces


class FusionManager:
    def __init__(
        self,
        mesh: Mesh,
        threshold_bytes: int,
        cycle_time_ms: float,
        cache_capacity: Optional[int] = None,
        injit_pack: Optional[bool] = None,
        bucketing: Optional[bool] = None,
        donate: Optional[bool] = None,
        promote_after: Optional[int] = None,
        wire: Optional[str] = None,
        wire_block: Optional[int] = None,
        wire_hier: Optional[bool] = None,
        wire_min_bytes: Optional[int] = None,
        guard: Optional[bool] = None,
    ):
        self.mesh = mesh
        self.threshold_bytes = threshold_bytes
        self.cycle_time_ms = cycle_time_ms
        self.world = int(mesh.devices.size)
        self.pending: List[_Entry] = []
        self.pending_bytes = 0
        self.cycle_start: Optional[float] = None
        # attached by basics.init:
        self.timeline = None
        self.stall_inspector = None
        self.parameter_manager = None
        if (
            cache_capacity is None
            or injit_pack is None
            or bucketing is None
            or donate is None
            or promote_after is None
            or wire is None
            or wire_block is None
            or wire_hier is None
            or wire_min_bytes is None
            or guard is None
        ):
            from ..common.config import Config

            cfg = Config.from_env()
            if guard is None:
                guard = cfg.guard
            if cache_capacity is None:
                cache_capacity = cfg.cache_capacity
            if injit_pack is None:
                injit_pack = cfg.fusion_injit
            if bucketing is None:
                bucketing = cfg.fusion_buckets
            if donate is None:
                donate = cfg.fusion_donate
            if promote_after is None:
                promote_after = cfg.fusion_promote_after
            if wire is None:
                wire = cfg.fusion_wire
            if wire_block is None:
                wire_block = cfg.fusion_wire_block
            if wire_hier is None:
                wire_hier = cfg.fusion_wire_hier
            if wire_min_bytes is None:
                wire_min_bytes = cfg.fusion_wire_min_bytes
        self.injit_pack = bool(injit_pack)
        # Non-finite sentinel on the eager data plane (HOROVOD_GUARD /
        # common/guard.py): float allreduce batches fold ONE
        # all(isfinite) scalar over the fused output buffer into the
        # SAME compiled executable. Flags are device scalars collected
        # without syncing; guard_poll() (called from hvd.guard_check /
        # State.commit) is the explicit sync point that counts
        # guard.nonfinite_batches. Detection-only here — eager handles
        # are already fulfilled by flush time, so skip-step semantics
        # belong to the optimizers, not the dispatcher.
        self.guard = bool(guard)
        self._guard_flags: List = []
        self.wire = str(wire)
        self.wire_block = max(int(wire_block), 1)
        self.wire_hier = bool(wire_hier)
        self.wire_min_bytes = int(wire_min_bytes)
        self.wire_tuner = None
        if self.wire == "auto":
            self.wire_tuner = self._make_wire_tuner()
        self.bucketing = bool(bucketing)
        if donate is None:
            # auto: donation is a no-op (plus a warning) on backends
            # without buffer aliasing — enable only where it bites
            platform = getattr(
                mesh.devices.reshape(-1)[0], "platform", "cpu"
            )
            donate = platform in ("tpu", "gpu", "cuda", "rocm")
        self.donate = bool(donate)
        self.promote_after = max(int(promote_after), 1)
        # Executor cache — the response-cache analog, with the
        # reference's HOROVOD_CACHE_CAPACITY semantics enforced (ref:
        # response_cache.cc [V]): ONE LRU bounds both tiers (exact fused
        # executables AND bucket-level core programs), so a long eager
        # job with varying shapes cannot leak compiled executables;
        # capacity 0 disables caching entirely.
        self.cache_capacity = max(int(cache_capacity), 0)
        self._executors: "OrderedDict[Tuple, Callable]" = OrderedDict()
        self._buckets_seen: "OrderedDict[Tuple, None]" = OrderedDict()
        self._comp_seen: "OrderedDict[Tuple, int]" = OrderedDict()
        self.cache_hits = 0  # dispatched a cached executor for the key
        self.cache_misses = 0  # executor builds (exact or bucket tier)
        self.cache_evictions = 0
        # persistent disk tier below exact/bucket (common/exe_cache.py,
        # HOROVOD_EXE_CACHE): a "miss" above may deserialize instead of
        # compile — disk_hits counts those. With no cache dir
        # configured both stay 0 and every build path is byte-identical
        # to the memory-only manager.
        from ..common import exe_cache as _exe_cache

        self._exe_base = _exe_cache.cache_dir()
        self._exe_fp = None  # resolved lazily: topology may not be up
        self.disk_hits = 0
        self.disk_misses = 0
        self.bucket_hits = 0  # exact miss served by the bucket tier
        self.promotions = 0  # compositions promoted to an exact executable
        self.dispatches = 0  # executor invocations, cumulative
        self.last_cycle_dispatches = 0
        self.pad_bytes_total = 0  # cumulative bucket padding on the wire
        self.last_cycle_pad_bytes = 0
        # cumulative payload bytes flushed — with pad/saved totals this
        # lets the telemetry hub reconstruct per-step wire bytes as a
        # snapshot delta (common/telemetry.py StepStats)
        self.flushed_bytes_total = 0
        self.donated_bytes_total = 0
        # quantized-wire observability (payload-width byte model: the
        # fused buffer's wire footprint at the chosen format vs fp32)
        self.wire_bytes_saved_total = 0
        self.last_cycle_wire_saved = 0
        self.quant_blocks_total = 0  # block-scale quantizations performed
        self.last_wire_format = "fp32"  # wire of the most recent dispatch
        # two-level (intra/inter) split of the same ledger — advanced
        # only by hierarchical dispatches, so the inter counter is a
        # pure DCN-byte meter (docs/observability.md)
        self.hier_dispatches = 0
        self.wire_bytes_saved_intra_total = 0
        self.wire_bytes_saved_inter_total = 0
        self.last_wire_format_intra = "fp32"
        self.last_wire_format_inter = "fp32"
        # eager alltoall observability (the gap PR 12 closed: these
        # dispatches were counted in `dispatches` but never reached a
        # metrics legend, so expert-dispatch bytes were invisible to
        # the flight recorder). Wire bytes use the (n-1)/n·payload
        # exchange model — the self block never leaves the chip.
        self.alltoall_dispatches = 0
        self.alltoall_wire_bytes_total = 0
        # local-SGD phase routing (horovod_tpu/local_sgd.py): fused
        # allreduce dispatches that ran group-limited to the intra
        # slice while a local phase was active
        self.local_dispatches = 0
        self.ef_residual_norm = 0.0  # L2 of the last EF residual batch
        self._seed_counter = 0  # decorrelates stochastic rounding per dispatch
        self._prev_outs = None  # queue-drain anchor for WireTuner trials
        self._anchor_ttl = 0  # dispatches the anchor stays alive for
        self.cycles = 0
        self._group_depth = 0
        self._next_group_id = 0

    def _make_wire_tuner(self):
        """WireTuner construction with durable state (HOROVOD_TUNER_CACHE):
        warm-started from the (topology-fingerprinted) cache so a
        restarted job skips straight to exploitation, and registered
        for persist-at-exit so this run's observations join the
        fleet's. No cache dir configured = exactly the old in-memory
        behavior."""
        from ..common.autotune import (
            WireTuner,
            register_persist_at_exit,
            warm_start,
        )

        tuner = WireTuner(min_int8_bytes=self.wire_min_bytes)
        warm_start(tuner, "wire")
        register_persist_at_exit(tuner, "wire")
        return tuner

    # ------------------------------------------------------------------ queue

    def begin_group(self) -> int:
        """Start an atomic enqueue group (ref: group_table.cc — a group
        is fused and reduced as one unit [V]): threshold/cycle flush
        triggers are deferred until the matching end_group(), so a group
        larger than the fusion threshold cannot be split mid-group."""
        self._group_depth += 1
        gid = self._next_group_id
        self._next_group_id += 1
        return gid

    def abort_group(self, gid: int) -> None:
        """Drop an incompletely-enqueued group (a member failed
        validation): its entries must not dispatch at end_group."""
        kept = [e for e in self.pending if e.group_id != gid]
        dropped = len(self.pending) - len(kept)
        if dropped:
            self.pending = kept
            self.pending_bytes = sum(
                int(e.payload.nbytes) for e in self.pending
            )

    def end_group(self) -> None:
        self._group_depth = max(self._group_depth - 1, 0)
        if self._group_depth == 0 and (
            self.pending_bytes >= self.threshold_bytes
            or self._cycle_expired()
        ):
            self.flush()

    def enqueue(self, entry: _Entry) -> Handle:
        entry.enqueue_t = time.monotonic()
        entry.handle = Handle(self, entry)
        if self.timeline is not None:
            self.timeline.begin(entry.name, "QUEUE")
        if self.stall_inspector is not None:
            self.stall_inspector.record_enqueue(entry.name)
        if self.cycle_start is None:
            self.cycle_start = entry.enqueue_t
        self.pending.append(entry)
        self.pending_bytes += int(entry.payload.nbytes)
        if self._group_depth == 0 and (
            self.pending_bytes >= self.threshold_bytes
            or self._cycle_expired()
        ):
            self.flush()
        return entry.handle

    def _cycle_expired(self) -> bool:
        return (
            self.cycle_start is not None
            and (time.monotonic() - self.cycle_start) * 1e3 >= self.cycle_time_ms
        )

    def maybe_cycle(self) -> None:
        if self.pending and self._cycle_expired():
            self.flush()

    # ------------------------------------------------------------------ flush

    def flush(self) -> None:
        if not self.pending:
            return
        # ``fusion.dispatch`` injection site: a transport-shaped fault
        # here models a peer dying under a collective. It surfaces as
        # HorovodInternalError — the exception the elastic contract
        # (hvd.elastic.run -> state.restore) is built to absorb — so
        # chaos tests can drive the rollback path deterministically.
        from ..testing import chaos as _chaos

        try:
            chaos_kind = _chaos.inject("fusion.dispatch")
        except (
            ConnectionResetError, TimeoutError, _chaos.InjectedServerError
        ) as e:
            from ..common.basics import HorovodInternalError

            raise HorovodInternalError(str(e)) from e
        t0 = time.monotonic()
        entries, self.pending = self.pending, []
        if chaos_kind == "nan":
            # data-plane corruption drill: poison ONE element of the
            # first float payload in the batch — exactly what a flipped
            # gradient bit looks like to the guard's isfinite sentinel
            for e in entries:
                if jnp.issubdtype(e.payload.dtype, jnp.floating):
                    e.payload = jnp.reshape(
                        jnp.reshape(e.payload, (-1,)).at[0].set(jnp.nan),
                        e.payload.shape,
                    )
                    break
        flushed_bytes, self.pending_bytes = self.pending_bytes, 0
        self.flushed_bytes_total += flushed_bytes
        self.cycle_start = None
        self.cycles += 1
        self.last_cycle_dispatches = 0
        self.last_cycle_pad_bytes = 0
        self.last_cycle_wire_saved = 0
        if self.timeline is not None:
            self.timeline.mark_cycle()
        if self.stall_inspector is not None:
            self.stall_inspector.check()

        # Group fusable entries; preserve dispatch order within groups.
        groups: Dict[Tuple, List[_Entry]] = {}
        for e in entries:
            groups.setdefault(_group_key(e), []).append(e)
        for key, group in groups.items():
            kind = key[0]
            if kind == "alltoall":
                for e in group:
                    self._execute_alltoall(e)
            elif kind == "allgather" and group[0].extra is not None:
                # v-variant: padded rows + per-rank valid-prefix slicing;
                # host-repack-bound like the reference's MPI_Allgatherv,
                # dispatched one entry at a time
                for e in group:
                    self._execute_batch([e])
            elif kind == "allreduce" and ReduceOp(key[1]) == Adasum:
                # Adasum's dot-product coefficients are per-tensor;
                # concatenating entries would compute joint projections
                # over the fused buffer. Execute one entry at a time
                # (still through the in-JIT pack machinery — bucketing
                # is sound because zero-padding adds nothing to Adasum's
                # inner products).
                for e in group:
                    self._execute_batch([e])
            else:
                for batch in self._batches_by_threshold(group):
                    self._execute_batch(batch)

        for e in entries:
            if self.timeline is not None:
                self.timeline.end(e.name, "QUEUE")
            if self.stall_inspector is not None:
                self.stall_inspector.record_complete(e.name)
        if _log.isEnabledFor(10):  # DEBUG — cycle + cache stats
            _log.debug(
                "cycle %d: %d entries, %dB (+%dB pad), %d dispatches, "
                "%.2fms; cache hits=%d bucket_hits=%d misses=%d "
                "evictions=%d size=%d",
                self.cycles,
                len(entries),
                flushed_bytes,
                self.last_cycle_pad_bytes,
                self.last_cycle_dispatches,
                (time.monotonic() - t0) * 1e3,
                self.cache_hits,
                self.bucket_hits,
                self.cache_misses,
                self.cache_evictions,
                len(self._executors),
            )
        from ..common.metrics import registry as _metrics

        _metrics.update("fusion", self.cache_stats())
        # expert-dispatch legend (MOE_METRICS): cumulative values under
        # their own prefix so StepStats _COUNTER_KEYS can delta them —
        # the eager alltoall family finally reaches the flight recorder
        _metrics.gauge("alltoall.dispatches", self.alltoall_dispatches)
        _metrics.gauge(
            "alltoall.wire_bytes", self.alltoall_wire_bytes_total
        )
        _metrics.gauge("fusion.cycles", self.cycles)
        _metrics.gauge("fusion.last_flush_bytes", flushed_bytes)
        _metrics.gauge(
            "fusion.last_cycle_pad_bytes", self.last_cycle_pad_bytes
        )
        _metrics.gauge(
            "fusion.last_cycle_dispatches", self.last_cycle_dispatches
        )
        _metrics.gauge(
            "fusion.last_cycle_wire_saved", self.last_cycle_wire_saved
        )
        _metrics.maybe_dump()
        if self.timeline is not None:
            self.timeline.counter(
                "fusion.pad_bytes", self.last_cycle_pad_bytes
            )
            self.timeline.counter(
                "fusion.dispatches", self.last_cycle_dispatches
            )
            self.timeline.counter(
                "fusion.wire_bytes_saved", self.last_cycle_wire_saved
            )
            from ..common.metrics import WIRE_FORMAT_CODES

            self.timeline.counter(
                "fusion.wire_format",
                WIRE_FORMAT_CODES.get(self.last_wire_format, 0),
            )
        if self.parameter_manager is not None:
            # useful vs wire bytes: the GP scores goodput (useful/sec),
            # so bucket padding — which costs time but moves no payload
            # — is penalized, not rewarded; a quantized wire that
            # removes payload bytes is credited the same way
            self.parameter_manager.record(
                bytes_=flushed_bytes,
                seconds=time.monotonic() - t0,
                wire_bytes=max(
                    flushed_bytes
                    + self.last_cycle_pad_bytes
                    - self.last_cycle_wire_saved,
                    0,
                ),
            )
            self.threshold_bytes, self.cycle_time_ms = (
                self.parameter_manager.current()
            )

    def _batches_by_threshold(self, group: List[_Entry]):
        """Split a fusable group into batches of <= threshold bytes,
        mirroring the fusion buffer's capacity (fusion_buffer_manager.cc
        [V]). A single over-threshold entry still goes alone, and a
        grouped_allreduce group is one indivisible unit — its members
        always share one fused collective (group_table.cc [V])."""
        units: List[List[_Entry]] = []
        for e in group:
            if (
                e.group_id is not None
                and units
                and units[-1][0].group_id == e.group_id
            ):
                units[-1].append(e)
            else:
                units.append([e])
        batch, batch_bytes = [], 0
        for unit in units:
            nbytes = sum(int(e.payload.nbytes) for e in unit)
            if batch and batch_bytes + nbytes > self.threshold_bytes:
                yield batch
                batch, batch_bytes = [], 0
            batch.extend(unit)
            batch_bytes += nbytes
        if batch:
            yield batch

    # ------------------------------------------------------------- executors

    def _pset_mask(self, e: _Entry):
        """Static [world] membership tuple for a proper-subset process
        set, else None. Masked full-axis collectives replace
        axis_index_groups here: XLA's TPU lowering requires equal-sized
        replica groups, which a set+singletons partition can never be
        (ref: per-set communicators in process_set.cc [V])."""
        if e.process_set is None or e.process_set.process_set_id == 0:
            return None
        if e.process_set.size == self.world:
            return None
        members = set(e.process_set.ranks)
        return tuple(r in members for r in range(self.world))

    def _pset_ranks(self, e: _Entry) -> Optional[Tuple[int, ...]]:
        if e.process_set is None or e.process_set.process_set_id == 0:
            return None
        return tuple(e.process_set.ranks)

    def _cache_get(self, key: Tuple) -> Optional[Callable]:
        if self.cache_capacity == 0:
            return None
        fn = self._executors.get(key)
        if fn is not None:
            self._executors.move_to_end(key)
        return fn

    def _cache_put(self, key: Tuple, fn: Callable) -> None:
        if self.cache_capacity == 0:
            return
        self._executors[key] = fn
        while len(self._executors) > self.cache_capacity:
            self._executors.popitem(last=False)
            self.cache_evictions += 1

    def _executor(self, key: Tuple, builder: Callable) -> Callable:
        """Single-tier lookup (alltoall and other non-fused paths)."""
        fn = self._cache_get(key)
        if fn is not None:
            self.cache_hits += 1
            return fn
        self.cache_misses += 1
        fn = builder()
        self._cache_put(key, fn)
        return fn

    def _note_composition(self, exact_key: Tuple) -> int:
        """Count sightings of an exact batch composition (bounded)."""
        n = self._comp_seen.pop(exact_key, 0) + 1
        self._comp_seen[exact_key] = n
        limit = max(self.cache_capacity * 4, 256)
        while len(self._comp_seen) > limit:
            self._comp_seen.popitem(last=False)
        return n

    def _note_bucket(self, core_key: Tuple) -> bool:
        """Record a bucket sighting; True when first seen. Bounded the
        same way as _comp_seen — core keys embed prescale/postscale
        floats, so a drifting scale (dynamic loss scaling) would
        otherwise grow this O(steps)."""
        fresh = self._buckets_seen.pop(core_key, "absent") == "absent"
        self._buckets_seen[core_key] = None
        limit = max(self.cache_capacity * 4, 256)
        while len(self._buckets_seen) > limit:
            self._buckets_seen.popitem(last=False)
        return fresh

    def cache_stats(self) -> Dict[str, int]:
        from ..common.metrics import WIRE_FORMAT_CODES

        return {
            "capacity": self.cache_capacity,
            "size": len(self._executors),
            "hits": self.cache_hits,
            "misses": self.cache_misses,
            "evictions": self.cache_evictions,
            "disk_hits": self.disk_hits,
            "disk_misses": self.disk_misses,
            "bucket_hits": self.bucket_hits,
            "promotions": self.promotions,
            "recompiles": self.cache_misses,
            "dispatches": self.dispatches,
            "bucket_pad_bytes": self.pad_bytes_total,
            "flushed_bytes": self.flushed_bytes_total,
            "donated_bytes": self.donated_bytes_total,
            "wire_bytes_saved": self.wire_bytes_saved_total,
            "quant_blocks": self.quant_blocks_total,
            "wire_format": WIRE_FORMAT_CODES.get(self.last_wire_format, 0),
            "hier_dispatches": self.hier_dispatches,
            "local_dispatches": self.local_dispatches,
            "wire_bytes_saved_intra": self.wire_bytes_saved_intra_total,
            "wire_bytes_saved_inter": self.wire_bytes_saved_inter_total,
            "wire_format_intra": WIRE_FORMAT_CODES.get(
                self.last_wire_format_intra, 0
            ),
            "wire_format_inter": WIRE_FORMAT_CODES.get(
                self.last_wire_format_inter, 0
            ),
            "alltoall_dispatches": self.alltoall_dispatches,
            "alltoall_wire_bytes": self.alltoall_wire_bytes_total,
        }

    def _shard_map(self, fn, in_specs=P(WORLD_AXIS), out_specs=P(WORLD_AXIS)):
        return shard_map(
            fn,
            mesh=self.mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=False,
        )

    # ---------------------------------------------------- fused dispatch

    def _hier_stages(self):
        """Two-level replica groups for an EXPLICIT per-call request
        (Compression.hier_int8 / HOROVOD_FUSION_WIRE_HIER): any
        resolvable split qualifies (mode "on"), or None when the
        hierarchy degenerates. Factored out so tests can inject a
        synthetic multi-slice split on a single-host mesh."""
        from ..common import topology as _topo

        return _topo.hierarchy_stages(world=self.world, mode="on")

    def _default_hier_stages(self):
        """The DEFAULT-routing decision — HOROVOD_HIERARCHICAL's
        tri-state (common/topology.py hierarchy_stages): every fused
        allreduce batch rides the two-level recipe when a real inter
        axis is present, flat otherwise."""
        from ..common import topology as _topo

        return _topo.hierarchy_stages(world=self.world)

    def _resolve_wire(self, e0: _Entry, plan: _BatchPlan):
        """Pick the wire plan for one allreduce batch: the entry's
        compression override beats the manager knob; ``auto`` asks the
        per-bucket WireTuner. Returns ``(wire, hier_stages, tuned,
        intra_wire)`` with both wires in {'fp32','bf16','int8'} —
        ineligible batches (non-float dtype, reductions that don't
        commute with quantization/cast) always ride fp32; ``tuned``
        marks choices that came from the tuner (only those dispatches
        ever pay trial synchronization).

        Hierarchy: an explicit request (``Compression.hier_int8`` /
        ``HOROVOD_FUSION_WIRE_HIER``) places bf16 intra + int8 inter
        whenever a split is resolvable; otherwise EVERY eligible batch
        consults the HOROVOD_HIERARCHICAL default decision — when an
        inter axis is present, the fused collective decomposes into
        intra RS -> inter collective on the 1/L shard -> intra AG,
        with each hop's format resolved independently (``wire`` names
        the INTER hop; the WireTuner keys are per (bucket-tier, hop))."""
        import jax.numpy as _jnp

        wire = e0.wire or self.wire
        eligible = e0.op in (Average, Sum) and _jnp.issubdtype(
            _jnp.dtype(plan.dtype), _jnp.floating
        )
        if e0.want_residual:
            if not eligible:
                raise ValueError(
                    "return_residual needs the int8 wire, which supports "
                    "float Sum/Average allreduce only"
                )
            # EF is defined by the quantization error — it forces the
            # flat int8 wire (the hierarchical split has no single
            # local residual to carry on this path).
            return "int8", None, False, "fp32"
        if not eligible:
            return "fp32", None, False, "fp32"
        explicit_hier = wire == "int8_hier" or (
            wire == "int8" and self.wire_hier
        )
        if wire == "int8_hier":
            wire = "int8"
        hier = (
            self._hier_stages()
            if explicit_hier
            else self._default_hier_stages()
        )
        tuned = False
        if wire in (None, "fp32"):
            return "fp32", hier, False, "fp32"
        if wire == "auto":
            if self.wire_tuner is None:  # knob flipped after init
                self.wire_tuner = self._make_wire_tuner()
            bucket_key = ("allreduce", plan.bucket, plan.dtype)
            if hier is not None:
                # per-hop choice: the inter hop sees 1/L of the bytes
                # (int8 competes there), the intra hop the full buffer
                # (fp32/bf16 only — ICI is fast, the quant tax never
                # pays for itself inside the slice)
                intra_n = len(hier[0][0])
                wire = self.wire_tuner.choose(
                    bucket_key + ("inter",),
                    payload_bytes=plan.bucket * plan.itemsize // intra_n,
                    itemsize=plan.itemsize,
                )
                intra_wire = self.wire_tuner.choose(
                    bucket_key + ("intra",),
                    payload_bytes=plan.bucket * plan.itemsize,
                    itemsize=plan.itemsize,
                    candidates=("fp32", "bf16"),
                )
                return wire, hier, True, intra_wire
            wire = self.wire_tuner.choose(
                bucket_key,
                payload_bytes=plan.bucket * plan.itemsize,
                itemsize=plan.itemsize,
            )
            tuned = True
            if wire == "int8" and self.wire_hier:
                hier = self._hier_stages()
        # static per-hop defaults: the EQuARX placement (bf16 intra
        # under an int8 inter); exact/bf16 wires apply hop-uniformly
        intra_wire = "bf16" if wire == "int8" and hier is not None else wire
        return wire, hier, tuned, intra_wire

    def _classify(self, batch: List[_Entry]) -> "_ExecSpec":
        """Resolve a batch to an _ExecSpec. `core_key` identifies the
        composition-independent padded-buffer program; the exact fused
        executable's key appends the per-entry shape tuple."""
        e0 = batch[0]
        kind = e0.kind
        if kind == "allreduce":
            pset_mask = self._pset_mask(e0)
            if e0.op == Adasum and pset_mask is not None:
                # Adasum over a process set rides adasum_allreduce's
                # masked full-axis formulation; a join mask composes by
                # zeroing the joined MEMBERS' rows (zero is Adasum's
                # identity) via the dynamic `keep` argument — NOT the
                # key — so one compiled program serves every join
                # pattern. Full-axis is the multi-process-safe shape
                # (tests/test_multiprocess_ops.py).
                ranks = self._pset_ranks(e0)
                plan = self._plan(batch, "adasum_pset", self.world)
                core_key = (
                    "adasum_pset", e0.prescale, e0.postscale, ranks,
                    plan.bucket, plan.dtype,
                )
                builder = lambda: self._core_adasum_pset(
                    e0.prescale, e0.postscale, ranks
                )
                return _ExecSpec(plan, core_key, builder, needs_keep=True)
            mask = None if e0.mask is None else tuple(bool(b) for b in e0.mask)
            plan = self._plan(batch, "allreduce", self.world)
            wire, hier, tuned, intra_wire = self._resolve_wire(e0, plan)
            # local-SGD local phase (horovod_tpu/local_sgd.py): an
            # active phase restricts every eligible fused allreduce to
            # its intra group — no inter hop exists, so the two-level
            # decomposition is moot. Masked/pset batches stay flat
            # (a masked subgroup has no uniform replica-group shape);
            # they are the caller's explicit cross-slice request.
            local_groups = None
            if (
                pset_mask is None
                and mask is None
                and e0.op in (Average, Sum)
            ):
                from .. import local_sgd as _local_sgd

                local_groups = _local_sgd.active_intra_groups()
            if local_groups is not None:
                hier = None
                if tuned:
                    # never feed an ICI-only dispatch's timing into
                    # the WireTuner's world/hier keys (they persist via
                    # HOROVOD_TUNER_CACHE and would poison the goodput
                    # real DCN-crossing dispatches choose from) — and
                    # auto never picks int8 inside a slice (the quant
                    # tax cannot pay for itself on ICI)
                    tuned = False
                    if wire == "int8":
                        wire = "fp32"
                if (e0.wire or self.wire) == "int8_hier":
                    # int8 was licensed for the inter hop only; with no
                    # inter hop the placement degenerates to its intra
                    # leg's wire
                    wire = "bf16"
                self.local_dispatches += 1
            if pset_mask is not None or mask is not None:
                # masked hierarchy degenerates to flat inside the core;
                # keep the spec (and so the wire-byte model + autotune
                # feed) consistent with what actually compiles
                hier = None
            # the canonical (world, L) layout pins the group structure,
            # so this pair is the cache-key-safe hier fingerprint (a
            # topology change mid-process re-keys the executors)
            hier_key = (
                None if hier is None else (len(hier[0]), len(hier[0][0]))
            )
            # the local phase re-keys the executors the same way: a
            # flat-wire executable must never serve a local dispatch
            local_key = (
                None
                if local_groups is None
                else (len(local_groups), len(local_groups[0]))
            )
            if wire == "int8":
                # a compressor's block_size (Compression.int8_block
                # subclasses) beats the manager knob, matching the
                # traced/optimizer path's granularity
                block = e0.wire_block or self.wire_block
                core_key = (
                    "allreduce_q", int(e0.op), e0.prescale, e0.postscale,
                    pset_mask, mask, plan.bucket, plan.dtype, block,
                    e0.want_residual, hier_key, intra_wire, local_key,
                )
                builder = lambda: self._core_allreduce_q(
                    e0.op, e0.prescale, e0.postscale, pset_mask, mask,
                    block, e0.want_residual, hier, intra_wire,
                    local_groups,
                )
                return _ExecSpec(
                    plan, core_key, builder, needs_seed=True,
                    want_res=e0.want_residual, wire="int8",
                    hier_n=None if hier is None else len(hier[1][0]),
                    intra_n=None if hier is None else len(hier[0][0]),
                    tuned=tuned, block=block, intra_wire=intra_wire,
                )
            core_key = (
                "allreduce", int(e0.op), e0.prescale, e0.postscale,
                pset_mask, mask, plan.bucket, plan.dtype, wire,
                hier_key, intra_wire, local_key,
            )
            builder = lambda: self._core_allreduce(
                e0.op, e0.prescale, e0.postscale, pset_mask, mask,
                wire=wire, hier_stages=hier, intra_wire=intra_wire,
                local_groups=local_groups,
            )
            return _ExecSpec(
                plan, core_key, builder, wire=wire, tuned=tuned,
                hier_n=None if hier is None else len(hier[1][0]),
                intra_n=None if hier is None else len(hier[0][0]),
                intra_wire=intra_wire,
            )
        if kind == "broadcast":
            pset_mask = self._pset_mask(e0)
            plan = self._plan(batch, "broadcast", self.world)
            core_key = (
                "broadcast", e0.root_rank, pset_mask, plan.bucket,
                plan.dtype,
            )
            builder = lambda: self._core_broadcast(e0.root_rank, pset_mask)
            return _ExecSpec(plan, core_key, builder)
        if kind == "allgather":
            ranks = self._pset_ranks(e0)
            n_ranks = self.world if ranks is None else len(ranks)
            plan = self._plan(batch, "allgather", n_ranks)
            core_key = ("allgather", ranks, plan.bucket, plan.dtype)
            builder = lambda: self._core_allgather(ranks)
            return _ExecSpec(plan, core_key, builder)
        if kind == "reducescatter":
            ranks = self._pset_ranks(e0)
            n_ranks = self.world if ranks is None else len(ranks)
            for e in batch:
                if e.payload.shape[1] % n_ranks != 0:
                    raise ValueError(
                        f"equal-split reducescatter needs dim1 divisible "
                        f"by the participating rank count {n_ranks}"
                    )
            plan = self._plan(batch, "reducescatter", n_ranks)
            core_key = (
                "reducescatter", int(e0.op), e0.prescale, e0.postscale,
                ranks, plan.bucket, plan.dtype,
            )
            builder = lambda: self._core_reducescatter(
                e0.op, e0.prescale, e0.postscale, ranks
            )
            return _ExecSpec(plan, core_key, builder)
        raise ValueError(f"unknown kind {kind}")

    def _plan(self, batch, family, n_ranks) -> _BatchPlan:
        return _make_plan(family, batch, self.world, n_ranks, self.bucketing)

    def _keep_arg(self, e: _Entry):
        """[world, 1] keep-row flags for the adasum_pset join mask:
        joined MEMBERS' contributions are zeroed (Adasum identity);
        joined NON-members keep their rows — their pass-through must
        return the original input."""
        if e.mask is None:
            return jnp.ones((self.world, 1), dtype=bool)
        member_set = set(self._pset_ranks(e) or range(self.world))
        return jnp.asarray(
            [
                [not (r in member_set and not bool(e.mask[r]))]
                for r in range(self.world)
            ]
        )

    def _execute_batch(self, batch: List[_Entry]) -> None:
        spec = self._classify(batch)
        plan, core_key = spec.plan, spec.core_key
        # collective-schedule audit (analysis/sched_audit.py): fold this
        # dispatch's rank-invariant identity — kind/op, fused-entry
        # composition, resolved wire, pset — into the rolling per-rank
        # fingerprint. A rank whose tuner, composition, or code path
        # diverges here is about to compile a DIFFERENT collective
        # sequence: the deadlock precursor the driver quarantines on.
        # (enabled() gates at the call site so a disabled audit skips
        # the composition-tuple construction too, not just the fold)
        if _sched_audit.enabled():
            _sched_audit.record(
                f"{batch[0].kind}:"
                f"{'' if batch[0].op is None else int(batch[0].op)}",
                (
                    plan.family,
                    tuple(_sched_entry_name(e.name) for e in batch),
                    plan.shapes,
                    plan.dtype,
                ),
                wire=(
                    f"{spec.intra_wire}/{spec.wire}"
                    if spec.hier_n
                    else spec.wire
                ),
                pset=(
                    0
                    if batch[0].process_set is None
                    else batch[0].process_set.process_set_id
                ),
            )
        # the non-finite sentinel rides only float batches (integer
        # payloads are finite by construction); the flag is an extra
        # executor output, so it is part of what the cache key already
        # pins (guard is fixed per manager, dtype is in every key)
        guarded = self.guard and jnp.issubdtype(
            jnp.dtype(plan.dtype), jnp.floating
        )
        exact_key = core_key + ("x", plan.shapes)
        # The exact tier is keyed on the full per-entry shape tuple, so
        # bucket padding buys it zero cache stability — it would only
        # put dead zeros on the wire every cycle of a stable job. Pad
        # only the bucket tier, whose executables must be
        # composition-independent.
        exact_plan = (
            plan
            if plan.bucket == plan.useful
            else dataclasses.replace(plan, bucket=plan.useful)
        )
        phase = batch[0].kind.upper()
        if self.timeline is not None:
            for e in batch:
                self.timeline.begin(e.name, phase)

        keep = self._keep_arg(batch[0]) if spec.needs_keep else None
        seed = self._next_seed() if spec.needs_seed else None
        outs = None
        used_plan = plan
        misses_before = self.cache_misses
        trial_pairs = []
        if spec.tuned:  # wire came from the tuner — no trials otherwise
            bucket_key = ("allreduce", plan.bucket, plan.dtype)
            if spec.hier_n:
                # per-hop keys: the inter and intra decisions explore
                # and converge independently (bf16-intra / int8-inter
                # is reachable without a combined menu)
                cand = [
                    (bucket_key + ("inter",), spec.wire),
                    (bucket_key + ("intra",), spec.intra_wire),
                ]
            else:
                cand = [(bucket_key, spec.wire)]
            trial_pairs = [
                (k, c)
                for k, c in cand
                if self.wire_tuner.needs_trial(k, c)
            ]
            if trial_pairs:
                self._anchor_ttl = 16  # exploration active: keep anchors
                # drain the dispatch queue up to the PREVIOUS batch so
                # the trial's clock measures this dispatch alone, not
                # whatever earlier async work was still in flight
                if self._prev_outs is not None:
                    try:
                        jax.block_until_ready(self._prev_outs)
                    except RuntimeError:
                        # the user may have DONATED the fulfilled
                        # outputs since (deleted buffers); the queue is
                        # then already drained past them
                        pass
        t_disp = time.monotonic()
        if not self.injit_pack or self.cache_capacity == 0:
            # host-pack mode (the A/B baseline leg), or caching disabled
            # — capacity 0 must not build a throwaway fused program per
            # cycle on top of an uncacheable core
            if self.injit_pack and self.cache_capacity == 0:
                self.cache_misses += 1
                fn = self._build_fused(
                    exact_plan, spec.builder(), spec, guarded
                )
                outs = self._dispatch_fused(
                    fn, batch, exact_plan, keep, seed, guarded
                )
                used_plan = exact_plan
            else:
                fn = self._executor(core_key, lambda: self._build_core(
                    plan, spec.builder(), spec, guarded))
                outs = self._dispatch_core(
                    fn, batch, plan, keep, seed, spec, guarded
                )
        else:
            fn = self._cache_get(exact_key)
            if fn is not None:
                self.cache_hits += 1
                outs = self._dispatch_fused(
                    fn, batch, exact_plan, keep, seed, guarded
                )
                used_plan = exact_plan
            else:
                seen = self._note_composition(exact_key)
                core = self._cache_get(core_key)
                fresh_bucket = self._note_bucket(core_key)
                if fresh_bucket or seen >= self.promote_after:
                    # first composition in this bucket, or a composition
                    # hot enough to deserve its own fused executable
                    self.cache_misses += 1
                    if not fresh_bucket:
                        self.promotions += 1
                    fn = self._build_fused(
                        exact_plan, spec.builder(), spec, guarded
                    )
                    fn = self._finalize_exe(
                        fn, "fusion.fused", spec,
                        lambda: [e.payload for e in batch]
                        + self._extra_args(keep, seed),
                        donate_n=len(exact_plan.shapes),
                    )
                    self._cache_put(exact_key, fn)
                    outs = self._dispatch_fused(
                        fn, batch, exact_plan, keep, seed, guarded
                    )
                    used_plan = exact_plan
                else:
                    # composition churn inside a known bucket: reuse (or
                    # build once) the bucket-tier program instead of
                    # compiling per composition
                    if core is None:
                        self.cache_misses += 1
                        core = self._build_core(
                            plan, spec.builder(), spec, guarded
                        )
                        core = self._finalize_exe(
                            core, "fusion.core", spec,
                            lambda: [
                                _pack([e.payload for e in batch], plan)
                            ] + self._extra_args(keep, seed),
                        )
                        self._cache_put(core_key, core)
                    self.bucket_hits += 1
                    outs = self._dispatch_core(
                        core, batch, plan, keep, seed, spec, guarded
                    )

        self.pad_bytes_total += used_plan.pad_bytes
        self.last_cycle_pad_bytes += used_plan.pad_bytes
        self._account_wire(spec, used_plan)
        if trial_pairs and self.cache_misses == misses_before:
            # exploration observation: pay one sync so the sample
            # measures execution (quant tax + wire), not the
            # format-independent async dispatch overhead; compile-time
            # dispatches are excluded — they would poison the goodput.
            # A hierarchical dispatch feeds BOTH hop keys the same
            # whole-dispatch sample — each hop's bandit ranks its own
            # candidates by it across dispatches.
            jax.block_until_ready(outs)
            seconds = time.monotonic() - t_disp
            for k, c in trial_pairs:
                self.wire_tuner.record(
                    k,
                    c,
                    useful_bytes=spec.plan.useful
                    * spec.plan.itemsize
                    * used_plan.world,
                    seconds=seconds,
                )
        # the anchor pins the previous batch's outputs in memory, so it
        # lives only while exploration is ACTIVE: each trial refreshes
        # a small TTL, and a half-explored bucket that stops recurring
        # stops pinning buffers after the TTL drains (it would
        # otherwise hold a threshold-sized batch for the process
        # lifetime)
        self._anchor_ttl = max(self._anchor_ttl - 1, 0)
        self._prev_outs = outs if self._anchor_ttl > 0 else None
        if self.timeline is not None and self.timeline.active:
            # device-completion stamp (SURVEY §7 checklist, eager half):
            # one block_until_ready per flush while someone is WATCHING
            # — the dispatch→completion delta is the device-side span
            # the dispatch-lifecycle begin/end pairs cannot see. The
            # sync is an observability cost the timeline explicitly
            # opts into (same gate as the EF-norm metrics); `active`
            # matters: after stop_timeline() the Timeline object stays
            # attached, and paying a sync per flush for spans the
            # writer would drop would serialize dispatch forever. The
            # span anchors at dispatch time ONLY when this flush
            # compiled nothing — on a cache-miss flush the executor
            # build/JIT ran after t_disp, and back-dating would report
            # host compile seconds as device collective time (the same
            # poisoning the WireTuner guards its goodput against), so
            # those spans anchor post-dispatch and measure the
            # remaining completion wait only.
            if self.cache_misses == misses_before:
                t0_us = self.timeline.now_us() - (
                    time.monotonic() - t_disp
                ) * 1e6
            else:
                t0_us = self.timeline.now_us()
            jax.block_until_ready(outs)
            dur_us = self.timeline.now_us() - t0_us
            for e in batch:
                self.timeline.span(
                    e.name, f"{phase}_DEVICE", t0_us, dur_us
                )
        resids = None
        if spec.want_res:
            outs, resids = outs
            self._note_residuals(resids)
        for i, (e, out) in enumerate(zip(batch, outs)):
            if e.kind == "allgather" and e.extra is not None:
                # Uneven dim0: rows were padded to max length; slice each
                # rank's valid prefix and concat (MPI_Allgatherv parity).
                lengths = e.extra
                ranks = self._pset_ranks(e)
                srcs = range(self.world) if ranks is None else ranks
                pieces = [
                    out[:, i, : lengths[s]] for i, s in enumerate(srcs)
                ]
                out = jnp.concatenate(pieces, axis=1)
            if self.timeline is not None:
                self.timeline.end(e.name, phase)
            e.handle._fulfill(
                (out, resids[i]) if resids is not None else out
            )

    def _next_seed(self) -> int:
        """Per-dispatch stochastic-rounding seed: monotone, so no two
        fused dispatches (within or across cycles) reuse a rounding
        pattern; the per-rank decorrelation is folded in inside the
        compiled program (rank index is not known on the host)."""
        s = self._seed_counter
        self._seed_counter += 1
        return s

    @staticmethod
    def _hop_bytes(elems: int, wire: str, itemsize: int, n: int, block):
        """Payload-width model of one hop's per-row wire bytes: the
        allreduce-equivalent traffic of ``elems`` elements at ``wire``
        over ``n`` participants (RS+AG of a ring allreduce jointly move
        ~one payload; ring/topology factors cancel in every ratio this
        model feeds). int8 adds both stages' block scales."""
        if wire == "bf16":
            return elems * 2, 0
        if wire == "int8":
            chunk = -(-elems // max(n, 1))
            nb = -(-chunk // block)
            return elems + nb * (n + 1) * 4, nb * (n + 1)
        return elems * itemsize, 0

    def _account_wire(
        self, spec: "_ExecSpec", used_plan: _BatchPlan
    ) -> None:
        """Wire-byte accounting for one dispatch, payload-width model
        (:meth:`_hop_bytes`), vs the flat-fp32 baseline of
        ``bucket·itemsize`` per rank row.

        Flat dispatches feed the aggregate ``wire_bytes_saved`` /
        ``wire_format`` exactly as before. A HIERARCHICAL dispatch
        splits the ledger per hop: the intra hop carries the full
        buffer at ``intra_wire``; the inter (DCN) hop carries the
        1/L shard at ``wire`` — so ``wire_bytes_saved_inter`` measures
        exactly the scarce-hop bytes the two-level recipe removed
        (≥3x for fp32 payloads under int8-inter: L·4x minus scale
        overhead), and ``wire_format_intra/inter`` let telemetry and
        the flight recorder attribute a regression to the right hop."""
        self.last_wire_format = spec.wire
        rows = used_plan.world
        elems = used_plan.bucket
        itemsize = used_plan.itemsize
        fp32_b = elems * itemsize
        block = spec.block or self.wire_block
        if spec.hier_n:
            L = spec.intra_n or 1
            shard = -(-elems // L)
            intra_b, _ = self._hop_bytes(
                elems, spec.intra_wire, itemsize, L, block
            )
            inter_b, qb = self._hop_bytes(
                shard, spec.wire, itemsize, spec.hier_n, block
            )
            self.quant_blocks_total += qb * rows
            saved_intra = max(fp32_b - intra_b, 0) * rows
            saved_inter = max(fp32_b - inter_b, 0) * rows
            self.wire_bytes_saved_intra_total += saved_intra
            self.wire_bytes_saved_inter_total += saved_inter
            self.last_wire_format_intra = spec.intra_wire
            self.last_wire_format_inter = spec.wire
            self.hier_dispatches += 1
            saved = max(fp32_b - intra_b - inter_b, 0) * rows
            self.wire_bytes_saved_total += saved
            self.last_cycle_wire_saved += saved
            return
        saved = 0
        if spec.wire == "bf16":
            saved = max(fp32_b - elems * 2, 0) * rows
        elif spec.wire == "int8":
            n = self.world
            wire_b, qb = self._hop_bytes(
                elems, "int8", itemsize, n, block
            )
            saved = max(fp32_b - wire_b, 0) * rows
            self.quant_blocks_total += qb * rows
        self.wire_bytes_saved_total += saved
        self.last_cycle_wire_saved += saved

    def _note_residuals(self, resids) -> None:
        """EF-residual observability: the L2 norm of the batch's carry.
        Computed only when someone is watching (timeline or metrics
        sink) — it forces a host sync on the eager path."""
        from ..common.metrics import registry as _metrics

        if self.timeline is None and not _metrics.exporting:
            return
        # one traced reduction over every entry, ONE host transfer —
        # per-entry float() would serialize a device sync per tensor
        # against the dispatch pipeline
        sq = sum(
            jnp.vdot(jnp.asarray(r, jnp.float32), jnp.asarray(r, jnp.float32))
            for r in resids
        )
        self.ef_residual_norm = float(jnp.sqrt(sq))
        _metrics.gauge("fusion.ef_residual_norm", self.ef_residual_norm)
        if self.timeline is not None:
            self.timeline.counter(
                "fusion.ef_residual_norm", self.ef_residual_norm
            )

    @staticmethod
    def _extra_args(keep, seed):
        extra = []
        if keep is not None:
            extra.append(keep)
        if seed is not None:
            # a committed scalar array, not a Python int: weak-typed
            # host scalars would re-trace the executable per value
            extra.append(jnp.int32(seed))
        return extra

    def _note_guard_flag(self, ok) -> None:
        """Collect a device-scalar finite flag WITHOUT syncing; the
        list is bounded so an unpolled guard cannot pin buffers
        forever (old flags drop oldest-first — the poll is a
        rate-limited health check, not an exact ledger)."""
        self._guard_flags.append(ok)
        if len(self._guard_flags) > 256:
            del self._guard_flags[: len(self._guard_flags) - 256]

    def guard_poll(self) -> int:
        """Sync point for the eager sentinel: resolve the collected
        flags (this is where the host pays the transfer — call it from
        commit-boundary code, not per dispatch), count non-finite
        batches into ``guard.nonfinite_batches``, return the count."""
        flags, self._guard_flags = self._guard_flags, []
        bad = 0
        for f in flags:
            try:
                if not bool(f):
                    bad += 1
            except Exception:  # deleted/donated buffer: unknowable
                continue
        if bad:
            from ..common.metrics import registry as _metrics

            _metrics.counter("guard.nonfinite_batches", bad)
            _log.warning(
                "non-finite values in %d fused batch(es) since the "
                "last guard poll", bad,
            )
        return bad

    def _dispatch_fused(self, fn, batch, plan, keep, seed=None, guarded=False):
        """One executor invocation covering pack + collective + unpack
        (and, on the quantized wire, quantize + dequantize)."""
        args = [e.payload for e in batch] + self._extra_args(keep, seed)
        self.dispatches += 1
        self.last_cycle_dispatches += 1
        if self.donate:
            self.donated_bytes_total += sum(
                int(e.payload.nbytes) for e in batch
            )
        out = fn(*args)
        if guarded:
            out, ok = out
            self._note_guard_flag(ok)
        return out

    def _dispatch_core(
        self, fn, batch, plan, keep, seed=None, spec=None, guarded=False
    ):
        """Bucket-tier dispatch: host-side pack into the padded buffer,
        one collective invocation, host-side unpack. This is the
        pre-rework dispatch path, kept as the composition-independent
        fallback and as `bench_fusion.py`'s host-pack A/B leg."""
        if self.timeline is not None and len(batch) > 1:
            for e in batch:
                self.timeline.begin(e.name, "MEMCPY_IN_FUSION_BUFFER")
        buf = _pack([e.payload for e in batch], plan)
        if self.timeline is not None and len(batch) > 1:
            for e in batch:
                self.timeline.end(e.name, "MEMCPY_IN_FUSION_BUFFER")
        self.dispatches += 1
        self.last_cycle_dispatches += 1
        out = fn(buf, *self._extra_args(keep, seed))
        if guarded:
            out, ok = out
            self._note_guard_flag(ok)
        if spec is not None and spec.want_res:
            out, res = out
            return _unpack(out, plan), _unpack(res, plan)
        return _unpack(out, plan)

    def _mapped_core(self, per_shard, spec: "_ExecSpec"):
        """shard_map the per-shard core with the argument/output specs
        its flags imply: buffer (+ keep) (+ replicated seed) in, buffer
        (+ residual buffer) out."""
        in_specs = [P(WORLD_AXIS)]
        if spec.needs_keep:
            in_specs.append(P(WORLD_AXIS))
        if spec.needs_seed:
            in_specs.append(P())
        out_specs = (
            (P(WORLD_AXIS), P(WORLD_AXIS)) if spec.want_res else P(WORLD_AXIS)
        )
        return self._shard_map(
            per_shard, in_specs=tuple(in_specs), out_specs=out_specs
        )

    def _finalize_exe(
        self, jitted, family: str, spec: "_ExecSpec", args_thunk,
        donate_n: int = 0,
    ):
        """Disk tier below the exact/bucket tiers (HOROVOD_EXE_CACHE,
        common/exe_cache.py): AOT-lower the freshly built program with
        its first dispatch's argument avals, then load a previously
        persisted executable by (topology, HLO, wire, donation) key —
        or compile and persist for the next process/standby. Includes
        bucket→exact promotions: a recurring composition promotes from
        disk instead of paying the promotion compile. No cache dir →
        the jitted callable is returned untouched (zero behavior
        change); any AOT/serialization failure falls back the same
        way — the disk tier is an accelerator, never a dependency."""
        if self._exe_base is None:
            return jitted
        from ..common import exe_cache as _exe_cache

        if self._exe_fp is None:
            self._exe_fp = _exe_cache.topology_fingerprint()
        wire = (
            f"{spec.intra_wire}/{spec.wire}" if spec.hier_n else spec.wire
        )
        donation = _exe_cache.donation_signature(
            tuple(range(donate_n)) if (self.donate and donate_n) else ()
        )
        try:
            lowered = jitted.lower(*args_thunk())
            exe, hit = _exe_cache.get_or_compile(
                lowered,
                family=family,
                wire=wire,
                donation=donation,
                fingerprint=self._exe_fp,
                base=self._exe_base,
            )
        except Exception as e:
            _log.warning(
                "exe disk tier unavailable for %s (%s); serving the "
                "jit path", family, e,
            )
            return jitted
        if hit:
            self.disk_hits += 1
        else:
            self.disk_misses += 1
        return exe

    def _build_core(
        self, plan: _BatchPlan, per_shard, spec: "_ExecSpec",
        guarded: bool = False,
    ) -> Callable:
        """Compile the composition-independent padded-buffer program.
        ``guarded`` appends the non-finite sentinel — one
        ``all(isfinite)`` scalar over the output buffer, inside the
        same executable."""
        mapped = self._mapped_core(per_shard, spec)
        if not guarded:
            return jax.jit(mapped)
        want_res = spec.want_res

        def core(*args):
            out = mapped(*args)
            buf = out[0] if want_res else out
            return out, jnp.all(jnp.isfinite(buf))

        return jax.jit(core)

    def _build_fused(
        self, plan: _BatchPlan, per_shard, spec: "_ExecSpec",
        guarded: bool = False,
    ) -> Callable:
        """Compile the whole batch — in-JIT pack, (quantize,)
        collective, (dequantize,) in-JIT unpack — as ONE donated
        executable. XLA sees the reshape/concat producers and the
        slice/reshape consumers next to the collective and fuses them;
        donation lets the fusion buffer alias the argument storage
        instead of doubling peak HBM. ``guarded`` folds the
        non-finite sentinel (one scalar reduction over the fused
        output buffer) into the same program."""
        mapped = self._mapped_core(per_shard, spec)
        n_tensors = len(plan.shapes)
        want_res = spec.want_res

        def fused(*args):
            tensors = args[:n_tensors]
            buf = _pack(tensors, plan)
            out = mapped(buf, *args[n_tensors:])
            if want_res:
                out, res = out
                pieces = (
                    tuple(_unpack(out, plan)), tuple(_unpack(res, plan))
                )
            else:
                pieces = tuple(_unpack(out, plan))
            if guarded:
                return pieces, jnp.all(jnp.isfinite(out))
            return pieces

        kwargs = {}
        if self.donate:
            kwargs["donate_argnums"] = tuple(range(n_tensors))
        return jax.jit(fused, **kwargs)

    # ----------------------------------------------------- per-shard cores
    #
    # Each core is a per-shard function over the fused buffer
    # ([1, bucket] rows; [1, n_ranks, bucket] for reducescatter). The
    # bucket tier caches it on the PADDED power-of-two geometry; the
    # exact tier wraps the same (shape-polymorphic) core with in-JIT
    # pack/unpack over the UNPADDED (bucket == useful) geometry — its
    # key already pins the exact shapes, so padding would buy nothing.

    def _core_allreduce(
        self, op, prescale, postscale, pset_mask, mask, wire="fp32",
        hier_stages=None, intra_wire=None, local_groups=None,
    ):
        world = self.world
        op = ReduceOp(op)
        bf16_wire = wire == "bf16"
        mask_arr = (
            None if mask is None else np.asarray(mask, dtype=bool)
        )
        pset_arr = (
            None if pset_mask is None else np.asarray(pset_mask, dtype=bool)
        )
        # Effective participation = joined AND in the process set; the
        # two masks share one identity-masked full-axis collective.
        if mask_arr is not None and pset_arr is not None:
            active_arr = mask_arr & pset_arr
        else:
            active_arr = mask_arr if mask_arr is not None else pset_arr

        # Two-level decomposition (ref: nccl_operations.cc
        # HOROVOD_HIERARCHICAL_ALLREDUCE [V], promoted to the
        # HOROVOD_HIERARCHICAL default): the caller (_classify /
        # _resolve_wire) already resolved the topology decision; masked
        # batches arrive with hier_stages=None (degenerate to flat).
        # Only the unrestricted Sum/Average path qualifies.
        if active_arr is not None or op not in (Average, Sum):
            hier_stages = None
            local_groups = None  # masked local phase degenerates flat
        if local_groups is not None:
            # local-SGD local phase (horovod_tpu/local_sgd.py): the
            # collective never leaves the slice — and a two-level
            # decomposition would reintroduce the inter hop
            hier_stages = None
        if intra_wire is None:
            intra_wire = wire if bf16_wire else "fp32"

        def per_shard(x):  # x: [1, N] — this rank's slice of the buffer
            idx = lax.axis_index(WORLD_AXIS)
            raw = x
            if prescale != 1.0:
                x = x * jnp.asarray(prescale, x.dtype)
            if active_arr is not None:
                active = jnp.asarray(active_arr)[idx]
                contrib = jnp.where(active, x, jnp.zeros_like(x))
            else:
                active = jnp.asarray(True)
                contrib = x
            if op in (Average, Sum) and hier_stages is not None:
                # intra RS -> inter psum on the 1/L shard -> intra AG
                # (ops/traced.py recipe family): the DCN hop carries
                # 1/L of the buffer; exact for fp32 hops.
                from .traced import hierarchical_allreduce_groups

                out = hierarchical_allreduce_groups(
                    contrib[0], op=ReduceOp(op), axis_name=WORLD_AXIS,
                    stages=hier_stages, intra_wire=intra_wire,
                    inter_wire=wire,
                )[None]
            elif op in (Average, Sum) and local_groups is not None:
                # local phase: one group-limited psum per slice; the
                # divisor is the slice width (masks/psets never reach
                # this branch — they degenerate to flat above)
                if bf16_wire:
                    contrib = contrib.astype(jnp.bfloat16)
                out = lax.psum(
                    contrib, WORLD_AXIS,
                    axis_index_groups=[list(g) for g in local_groups],
                )
                if bf16_wire:
                    out = out.astype(x.dtype)
                if op == Average:
                    out = out / jnp.asarray(
                        len(local_groups[0]), out.dtype
                    )
            elif op in (Average, Sum):
                # bf16 wire: the cast is the compression — XLA fuses it
                # into the collective's producer/consumer, so the wire
                # moves half-width bytes at zero extra HBM passes
                # (Compression.bf16's contract, applied buffer-wide)
                if bf16_wire:
                    contrib = contrib.astype(jnp.bfloat16)
                out = lax.psum(contrib, WORLD_AXIS)
                if bf16_wire:
                    out = out.astype(x.dtype)
                if op == Average:
                    count = lax.psum(active.astype(x.dtype), WORLD_AXIS)
                    out = out / jnp.maximum(count, 1)
            elif op == Min:
                big = jnp.full_like(x, _max_value(x.dtype))
                contrib = (
                    jnp.where(active, x, big)
                    if active_arr is not None
                    else x
                )
                out = lax.pmin(contrib, WORLD_AXIS)
            elif op == Max:
                small = jnp.full_like(x, _min_value(x.dtype))
                contrib = (
                    jnp.where(active, x, small)
                    if active_arr is not None
                    else x
                )
                out = lax.pmax(contrib, WORLD_AXIS)
            elif op == Product:
                contrib = (
                    jnp.where(active, x, jnp.ones_like(x))
                    if active_arr is not None
                    else x
                )
                gathered = lax.all_gather(contrib, WORLD_AXIS)
                out = jnp.prod(gathered, axis=0)
            elif op == Adasum:
                from .adasum import adasum_allreduce

                # Zero is Adasum's identity (a zero vector has no
                # projection to remove and adds nothing), so the same
                # contribution masking covers joined ranks here too —
                # and the bucket's zero tail pads harmlessly.
                out = adasum_allreduce(contrib, axis_name=WORLD_AXIS)
            else:
                raise ValueError(f"unsupported op {op}")
            if postscale != 1.0:
                out = out * jnp.asarray(postscale, out.dtype)
            # Ranks outside the process set keep their input untouched
            # (reference: non-members don't participate at all). Joined
            # ranks (join mask) DO take the result — that's the point
            # of join().
            if pset_arr is not None:
                out = jnp.where(jnp.asarray(pset_arr)[idx], out, raw)
            return out

        return per_shard

    def _core_allreduce_q(
        self, op, prescale, postscale, pset_mask, mask, block,
        want_res, hier_stages, intra_wire="bf16", local_groups=None,
    ):
        """The quantized fused wire: the whole fused buffer traverses
        the collective as block-scaled int8, entirely inside the
        compiled program — quantize ONCE over the batch instead of once
        per tensor (the per-tensor quantize tax bench_int8.py measures,
        amortized to one).

        Recipe = traced.quantized_allreduce's two-stage shape applied
        to this rank's [1, N] buffer row: block-quantize the row split
        into per-peer chunks → all_to_all of int8 + block scales (the
        scatter half of reduce-scatter) → dequant-sum the received
        chunks at f32 → block-quantize the reduced shard → all_gather →
        dequant. XLA fuses the quantize into the pack producer and the
        dequant into the unpack consumers, so the batch still costs
        exactly ONE dispatch; wire bytes drop ~4x for fp32 payloads
        (block scales cost 4·(n+1)/n/block of the payload — <1% at
        block=512).

        ``prescale`` folds into the stage-1 wire scales (quantization
        is scale-invariant — see traced.quantized_allreduce), so the
        quantized path never pays a pre-multiply HBM pass. Bucket-tier
        zero padding is excluded from the scales by construction (zeros
        never raise a block absmax, quantize to zero, and leave a zero
        residual). With ``hier_stages``, compression follows the
        topology: bf16 psum on the intra-host (ICI) stage, the int8
        recipe on the cross-host (DCN) stage only — EQuARX's placement.

        ``want_res=True`` returns ``(out, residual)`` — the
        error-feedback carry in INPUT units, per-entry slices of which
        `_unpack` hands back so DistributedOptimizer-style EF composes
        with fusion.

        NOTE this body intentionally mirrors the ``block_size`` branch
        of ``traced.quantized_allreduce`` (which lacks the mask/pset/
        hier machinery but shares every numeric contract: wire-scale
        prescale fold, Average×n and /prescale residual corrections,
        prescale==0 zero carry). A change to either residual contract
        must land in BOTH — tests/test_fusion_quantized.py's fused-vs-
        unfused parity tests are the tripwire.
        """
        world = self.world
        op = ReduceOp(op)
        mask_arr = None if mask is None else np.asarray(mask, dtype=bool)
        pset_arr = (
            None if pset_mask is None else np.asarray(pset_mask, dtype=bool)
        )
        if mask_arr is not None and pset_arr is not None:
            active_arr = mask_arr & pset_arr
        else:
            active_arr = mask_arr if mask_arr is not None else pset_arr
        if active_arr is not None:
            local_groups = None  # masked local phase degenerates flat
        if local_groups is not None:
            hier_stages = None  # the local phase has no inter hop
        # divisor is static: the single controller knows the join mask
        n_active = (
            (len(local_groups[0]) if local_groups is not None else world)
            if active_arr is None
            else max(int(active_arr.sum()), 1)
        )
        if hier_stages is not None and active_arr is not None:
            hier_stages = None  # masked hierarchy degenerates to flat

        from .traced import _block_dequant, _stochastic_round_blocks

        def per_shard(x, seed):  # x: [1, N]; seed: replicated scalar
            idx = lax.axis_index(WORLD_AXIS)
            raw = x
            row = x[0].astype(jnp.float32)
            if active_arr is not None:
                active = jnp.asarray(active_arr)[idx]
                row = jnp.where(active, row, jnp.zeros_like(row))
            if hier_stages is not None:
                intra_groups, inter_groups = hier_stages
                # intra reduce-scatter FIRST (bf16 by default — ICI is
                # fast, spend 2 bytes), so the int8 inter stage below
                # quantizes the 1/L shard: the DCN hop pays
                # payload/L/4, not payload/4 (the full hierarchical
                # recipe, ops/traced.py). The matching intra all-gather
                # runs after the inter stage.
                L = len(intra_groups[0])
                mfull = row.shape[0]
                pad_l = (-mfull) % L
                if pad_l:
                    row = jnp.pad(row, (0, pad_l))
                wire_row = (
                    row.astype(jnp.bfloat16)
                    if intra_wire == "bf16"
                    else row
                )
                row = lax.psum_scatter(
                    wire_row, WORLD_AXIS, scatter_dimension=0,
                    tiled=True, axis_index_groups=intra_groups,
                ).astype(jnp.float32)
                n = len(inter_groups[0])
                groups = inter_groups
            elif local_groups is not None:
                # local phase: the whole two-stage int8 recipe runs
                # inside the slice (chunk ownership by group position)
                n = len(local_groups[0])
                groups = [list(g) for g in local_groups]
            else:
                n = world
                groups = None
            m = row.shape[0]
            chunk = -(-m // n)
            flat = (
                jnp.pad(row, (0, chunk * n - m))
                if chunk * n != m
                else row
            )
            chunks = flat.reshape(n, chunk)
            key = jax.random.fold_in(
                jax.random.fold_in(jax.random.PRNGKey(0), seed), idx
            )
            q, scales = _stochastic_round_blocks(chunks, block, key)
            wire_scales = (
                scales * jnp.asarray(prescale, scales.dtype)
                if prescale != 1.0
                else scales
            )
            recv = lax.all_to_all(
                q, WORLD_AXIS, split_axis=0, concat_axis=0, tiled=True,
                axis_index_groups=groups,
            )
            recv_s = lax.all_to_all(
                wire_scales, WORLD_AXIS, split_axis=0, concat_axis=0,
                tiled=True, axis_index_groups=groups,
            )
            shard = jnp.sum(_block_dequant(recv, recv_s), axis=0)  # [cpad]
            if op == Average:
                shard = shard / jnp.asarray(n_active, shard.dtype)
            q2, s2 = _stochastic_round_blocks(
                shard[None], block, jax.random.fold_in(key, 7919)
            )
            all_q = lax.all_gather(
                q2[0], WORLD_AXIS, axis_index_groups=groups
            )
            all_s = lax.all_gather(
                s2[0], WORLD_AXIS, axis_index_groups=groups
            )
            out = _block_dequant(all_q, all_s)[:, :chunk].reshape(-1)[:m]
            if hier_stages is not None:
                # the reduced 1/L shard rides the intra all-gather home
                # (same wire as the intra RS leg)
                ag = (
                    out.astype(jnp.bfloat16)
                    if intra_wire == "bf16"
                    else out
                )
                out = lax.all_gather(
                    ag, WORLD_AXIS, tiled=True,
                    axis_index_groups=hier_stages[0],
                ).astype(jnp.float32)[:mfull]
            if postscale != 1.0:
                out = out * jnp.asarray(postscale, out.dtype)
            out = out.astype(x.dtype)[None]
            if pset_arr is not None:
                out = jnp.where(jnp.asarray(pset_arr)[idx], out, raw)
            if not want_res:
                return out
            if prescale == 0.0:
                # nothing is transmitted: zero carry (see
                # traced.quantized_allreduce) rather than 0/0 NaNs
                return out, jnp.zeros_like(out)
            # EF carry, both stages, input units (traced.
            # quantized_allreduce's contract): stage-1 against the
            # UNSCALED block scales; stage-2 on the owned chunk,
            # un-Averaged and un-prescaled so a +res input correction
            # cancels it exactly.
            res1 = chunks - _block_dequant(q, scales)[:, :chunk]
            res_flat = res1.reshape(-1)
            e2 = (shard - _block_dequant(q2, s2)[0])[:chunk]
            if op == Average:
                e2 = e2 * jnp.asarray(n_active, e2.dtype)
            if prescale != 1.0:
                e2 = e2 / jnp.asarray(prescale, e2.dtype)
            if local_groups is not None:
                # chunk ownership = position within the intra group
                from .traced import _group_pos_table

                own = jnp.asarray(_group_pos_table(local_groups))[idx]
            else:
                own = idx
            res_flat = lax.dynamic_update_slice(
                res_flat,
                lax.dynamic_slice(res_flat, (own * chunk,), (chunk,)) + e2,
                (own * chunk,),
            )
            res = res_flat[:m].astype(x.dtype)[None]
            if pset_arr is not None:
                res = jnp.where(
                    jnp.asarray(pset_arr)[idx], res, jnp.zeros_like(res)
                )
            return out, res

        return per_shard

    def _core_broadcast(self, root_rank, pset_mask):
        pset_arr = (
            None if pset_mask is None else np.asarray(pset_mask, dtype=bool)
        )

        def per_shard(x):
            idx = lax.axis_index(WORLD_AXIS)
            contrib = jnp.where(idx == root_rank, x, jnp.zeros_like(x))
            out = lax.psum(contrib, WORLD_AXIS)
            # Non-members of the process set keep their input unchanged
            # (reference: they don't participate at all).
            if pset_arr is not None:
                out = jnp.where(jnp.asarray(pset_arr)[idx], out, x)
            return out

        return per_shard

    def _member_tables(self, ranks):
        from ..common.process_sets import member_tables

        return member_tables(self.world, ranks)

    def _core_allgather(self, ranks=None):
        ranks_t = None if ranks is None else tuple(ranks)
        member = None
        if ranks_t is not None:
            member, _ = self._member_tables(ranks_t)

        def per_shard(x):  # [1, N] → [1, n_ranks, N]
            g = lax.all_gather(x[0], WORLD_AXIS)  # [world, N]
            if ranks_t is None:
                return g[None]
            mg = g[jnp.asarray(ranks_t)]  # static member selection
            is_m = jnp.asarray(member)[lax.axis_index(WORLD_AXIS)]
            return jnp.where(is_m, mg, jnp.zeros_like(mg))[None]

        return per_shard

    def _core_reducescatter(self, op, prescale, postscale, ranks=None):
        op = ReduceOp(op)
        if ranks is None:
            n_ranks = self.world

            def per_shard(x):  # [1, n_ranks, K] → [1, K]
                if prescale != 1.0:
                    x = x * jnp.asarray(prescale, x.dtype)
                k = x.shape[2]
                out = lax.psum_scatter(
                    x.reshape(1, n_ranks * k),
                    WORLD_AXIS,
                    scatter_dimension=1,
                    tiled=True,
                )
                if op == Average:
                    out = out / jnp.asarray(n_ranks, out.dtype)
                if postscale != 1.0:
                    out = out * jnp.asarray(postscale, out.dtype)
                return out
        else:
            ranks_t = tuple(ranks)
            n_ranks = len(ranks_t)
            member, pos = self._member_tables(ranks_t)

            def per_shard(x):  # [1, n_ranks, K] → [1, K]
                if prescale != 1.0:
                    x = x * jnp.asarray(prescale, x.dtype)
                idx = lax.axis_index(WORLD_AXIS)
                is_m = jnp.asarray(member)[idx]
                contrib = jnp.where(is_m, x, jnp.zeros_like(x))
                total = lax.psum(contrib, WORLD_AXIS)  # member sum
                mine = lax.dynamic_index_in_dim(
                    total, jnp.asarray(pos)[idx], axis=1, keepdims=False
                )  # [1, K]
                if op == Average:
                    mine = mine / jnp.asarray(n_ranks, mine.dtype)
                if postscale != 1.0:
                    mine = mine * jnp.asarray(postscale, mine.dtype)
                return jnp.where(is_m, mine, jnp.zeros_like(mine))

            return per_shard

        return per_shard

    def _core_adasum_pset(self, prescale, postscale, ranks):
        """Adasum over a process set as a masked full-axis program
        (adasum_allreduce's gather+tree formulation); non-members keep
        their input. Join masking rides the dynamic `keep` argument so
        the compiled program is mask-independent."""
        from .adasum import adasum_allreduce

        ranks_l = list(ranks)
        member, _ = self._member_tables(ranks_l)

        def per_shard(x, keep):  # x: [1, N]; keep: [1, 1] bool
            idx = lax.axis_index(WORLD_AXIS)
            raw = x
            x = jnp.where(keep, x, jnp.zeros_like(x))
            if prescale != 1.0:
                x = x * jnp.asarray(prescale, x.dtype)
            out = adasum_allreduce(
                x[0], WORLD_AXIS, groups=[ranks_l]
            )[None]
            if postscale != 1.0:
                out = out * jnp.asarray(postscale, out.dtype)
            return jnp.where(jnp.asarray(member)[idx], out, raw)

        return per_shard

    # -------------------------------------------------------- alltoall

    def _execute_alltoall(self, e: _Entry) -> None:
        """Equal-split alltoall — the one family outside the fused
        machinery (its split/concat geometry is per-entry; the uneven
        v-variant repacks on host in eager.py)."""
        if self.timeline is not None:
            self.timeline.begin(e.name, "ALLTOALL")
        ranks = self._pset_ranks(e)
        n_ranks = self.world if ranks is None else len(ranks)
        payload = e.payload
        if payload.shape[1] % n_ranks != 0:
            raise ValueError(
                f"equal-split alltoall needs dim1 divisible by the "
                f"participating rank count {n_ranks}"
            )
        key = ("alltoall", ranks, payload.shape, payload.dtype.name)
        if _sched_audit.enabled():
            _sched_audit.record(
                "alltoall",
                (
                    _sched_entry_name(e.name),
                    tuple(payload.shape),
                    payload.dtype.name,
                ),
                wire=e.wire,
                pset=(
                    0
                    if e.process_set is None
                    else e.process_set.process_set_id
                ),
            )
        fn = self._executor(key, lambda: self._build_alltoall(ranks))
        self.dispatches += 1
        self.last_cycle_dispatches += 1
        self.alltoall_dispatches += 1
        self.alltoall_wire_bytes_total += (
            int(payload.nbytes) * max(n_ranks - 1, 0) // max(n_ranks, 1)
        )
        out = fn(payload)
        if self.timeline is not None:
            self.timeline.end(e.name, "ALLTOALL")
        e.handle._fulfill(out)

    def _build_alltoall(self, ranks=None):
        if ranks is None:
            def per_shard(x):  # [1, n, ...]; n % world == 0
                return lax.all_to_all(
                    x, WORLD_AXIS, split_axis=1, concat_axis=1, tiled=True
                )
        else:
            ranks_t = tuple(ranks)
            n_ranks = len(ranks_t)
            member, pos = self._member_tables(ranks_t)

            def per_shard(x):  # [1, n, ...]; n % n_ranks == 0
                # Masked full-axis formulation: gather every row, select
                # the member block addressed to this rank's member
                # position. More wire than a member-only exchange, but
                # expressible with equal replica groups AND launched
                # identically by every process.
                row = x[0]
                k = row.shape[0] // n_ranks
                g = lax.all_gather(row, WORLD_AXIS)  # [world, n, ...]
                mg = g[jnp.asarray(ranks_t)]         # [n_ranks, n, ...]
                blocks = mg.reshape(
                    (n_ranks, n_ranks, k) + row.shape[1:]
                )
                idx = lax.axis_index(WORLD_AXIS)
                mine = lax.dynamic_index_in_dim(
                    blocks, jnp.asarray(pos)[idx], axis=1, keepdims=False
                )  # [n_ranks, k, ...]
                mine = mine.reshape((n_ranks * k,) + row.shape[1:])
                is_m = jnp.asarray(member)[idx]
                return jnp.where(is_m, mine, jnp.zeros_like(mine))[None]

        return jax.jit(self._shard_map(per_shard))


# The group builder moved to common/topology.py (the one home of the
# two-level split); re-exported here for the existing import surface.
from ..common.topology import hierarchical_stage_groups  # noqa: E402,F401


def _max_value(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.finfo(dtype).max
    return jnp.iinfo(dtype).max


def _min_value(dtype):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.finfo(dtype).min
    return jnp.iinfo(dtype).min
