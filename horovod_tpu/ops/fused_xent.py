"""Chunked fused linear + softmax-cross-entropy for LM heads.

TPU-first replacement for the reference's ``logits -> loss`` tail
(ref: the synthetic LM benches materialize full logits and call the
framework's cross-entropy — e.g. examples/pytorch/pytorch_synthetic
_benchmark.py's criterion path [V]; SURVEY.md §2.6 treats the LM loss
as framework-side). At GPT-2 vocabulary width the logits tensor is the
single largest activation in the step: ``(batch·seq, vocab)`` fp32 is
~823 MB at batch 8 / seq 512 / V=50257, written once forward, read by
softmax, and the same again for ``dlogits`` backward — all HBM
traffic on a step whose profile is bandwidth-sensitive (docs/perf.md).

This op never materializes them. The vocabulary axis is processed in
chunks (an unrolled loop — every matmul stays MXU-sized and XLA's cost
analysis sees every FLOP; no ``while`` body undercounting):

* forward: online logsumexp (running max + scaled sum) plus a gathered
  target logit per token; only ``(N,)`` statistics survive the loop.
* backward (custom VJP): recompute each chunk's logits from the saved
  activations, form ``softmax - onehot`` locally, and accumulate
  ``dx`` / write ``dW``/``db`` slices.

Cost: one extra ``N·d·chunk``-per-chunk matmul in backward (the logits
recompute), ~``2NdV`` FLOPs ≈ +4% of a GPT-2-medium step — traded for
never writing/reading the two ``(N, V)`` fp32 tensors and an ~800 MB
lower activation footprint (which is what lets batch grow without
remat). Matmul precision follows the LM head recipe: ``compute_dtype``
operands (bf16 by default) with fp32 accumulation, fp32 statistics.
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def _partial_logits(x, kernel, bias, start: int, width: int, dtype):
    """Logits for vocab columns [start, start+width) — fp32 out."""
    k = lax.slice_in_dim(kernel, start, start + width, axis=1)
    b = lax.slice_in_dim(bias, start, start + width, axis=0)
    if dtype is not None:
        y = lax.dot_general(
            x.astype(dtype),
            k.astype(dtype),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
    else:
        y = jnp.dot(x.astype(jnp.float32), k.astype(jnp.float32))
    return y + b[None, :].astype(jnp.float32)


def _chunk_starts(vocab: int, chunk: int):
    """(start, width) pairs covering [0, vocab) — full chunks plus one
    static tail, no padding, no overlap."""
    chunk = max(1, min(int(chunk), vocab))
    starts = [(s, chunk) for s in range(0, vocab - chunk + 1, chunk)]
    done = starts[-1][0] + chunk if starts else 0
    if done < vocab:
        starts.append((done, vocab - done))
    return starts


@functools.lru_cache(maxsize=None)
def _build(chunk: int, dtype_name: Optional[str]):
    dtype = None if dtype_name is None else jnp.dtype(dtype_name)

    @jax.custom_vjp
    def f(x, kernel, bias, labels):
        loss, _ = f_fwd(x, kernel, bias, labels)
        return loss

    def f_fwd(x, kernel, bias, labels):
        n = x.shape[0]
        vocab = kernel.shape[1]
        m = jnp.full((n,), -np.inf, jnp.float32)
        s = jnp.zeros((n,), jnp.float32)
        tl = jnp.zeros((n,), jnp.float32)
        for start, width in _chunk_starts(vocab, chunk):
            logits = _partial_logits(x, kernel, bias, start, width, dtype)
            cmax = jnp.max(logits, axis=-1)
            new_m = jnp.maximum(m, cmax)
            s = s * jnp.exp(m - new_m) + jnp.sum(
                jnp.exp(logits - new_m[:, None]), axis=-1
            )
            m = new_m
            local = labels - start
            hit = (local >= 0) & (local < width)
            idx = jnp.clip(local, 0, width - 1)
            tl = tl + jnp.where(
                hit,
                jnp.take_along_axis(logits, idx[:, None], axis=1)[:, 0],
                0.0,
            )
        lse = m + jnp.log(s)
        return lse - tl, (x, kernel, bias, labels, lse)

    def f_bwd(res, g):
        x, kernel, bias, labels, lse = res
        n = x.shape[0]
        vocab = kernel.shape[1]
        dx = jnp.zeros(x.shape, jnp.float32)
        dw_slices = []
        db_slices = []
        for start, width in _chunk_starts(vocab, chunk):
            logits = _partial_logits(x, kernel, bias, start, width, dtype)
            p = jnp.exp(logits - lse[:, None])
            local = labels - start
            hit = (local >= 0) & (local < width)
            idx = jnp.clip(local, 0, width - 1)
            dlogits = p * g[:, None]
            dlogits = dlogits.at[jnp.arange(n), idx].add(
                jnp.where(hit, -g, 0.0)
            )
            k = lax.slice_in_dim(kernel, start, start + width, axis=1)
            if dtype is not None:
                dl = dlogits.astype(dtype)
                dx = dx + lax.dot_general(
                    dl, k.astype(dtype),
                    dimension_numbers=(((1,), (1,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
                dwc = lax.dot_general(
                    x.astype(dtype), dl,
                    dimension_numbers=(((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32,
                )
            else:
                dx = dx + jnp.dot(dlogits, k.astype(jnp.float32).T)
                dwc = jnp.dot(x.astype(jnp.float32).T, dlogits)
            dw_slices.append(dwc.astype(kernel.dtype))
            db_slices.append(dlogits.sum(axis=0).astype(bias.dtype))
        dw = jnp.concatenate(dw_slices, axis=1)
        db = jnp.concatenate(db_slices, axis=0)
        dlabels = np.zeros(labels.shape, jax.dtypes.float0)
        return dx.astype(x.dtype), dw, db, dlabels

    f.defvjp(f_fwd, f_bwd)
    return f


def fused_linear_cross_entropy(
    x,
    kernel,
    bias,
    labels,
    *,
    chunk: int = 8192,
    compute_dtype: Any = jnp.bfloat16,
):
    """Per-token softmax cross-entropy of ``x @ kernel + bias`` against
    integer ``labels`` — without materializing the logits.

    Args:
      x: ``(N, d_model)`` activations (any float dtype; gradients come
        back in the same dtype).
      kernel: ``(d_model, vocab)`` projection (fp32 master weights).
      bias: ``(vocab,)``.
      labels: ``(N,)`` int32/int64 targets in ``[0, vocab)``.
      chunk: vocabulary chunk width. The working set per chunk is
        ``N × chunk`` fp32; the loop is unrolled, so every chunk is a
        full MXU matmul and XLA sees the true FLOP count.
      compute_dtype: matmul operand dtype (None = all-fp32). Default
        bf16 matches ``TransformerConfig.head_mixed_precision``.

    Returns ``(N,)`` fp32 per-token losses (mean-reduce for the usual
    scalar objective). Numerics match the materialized
    ``optax.softmax_cross_entropy_with_integer_labels`` path to the
    matmul-precision tolerance (exactly, under ``compute_dtype=None``).
    """
    if x.ndim != 2:
        raise ValueError(f"x must be (tokens, d_model); got {x.shape}")
    if labels.shape != x.shape[:1]:
        raise ValueError(
            f"labels shape {labels.shape} != tokens axis {x.shape[:1]}"
        )
    dtype_name = None if compute_dtype is None else jnp.dtype(
        compute_dtype
    ).name
    return _build(int(chunk), dtype_name)(x, kernel, bias, labels)
