"""Numerical properties of the Adasum combiner.

Reference model: test/parallel/test_adasum_pytorch.py — checks Adasum's
defining properties rather than exact values [V] (SURVEY.md §4.1):
identical inputs → identity; orthogonal inputs → sum; parallel inputs →
average; scale invariance of the mixing coefficients.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from horovod_tpu.ops.adasum import (
    _tree_combine,
    adasum_allreduce,
    adasum_pair,
    adasum_vhdd_host,
    vhdd_wire_bytes,
)


def _run_distributed(stack, world):
    """adasum_allreduce under shard_map over `world` devices; returns
    every rank's output row."""
    mesh = jax.sharding.Mesh(
        np.array(jax.devices()[:world]), ("world",)
    )
    fn = jax.shard_map(
        lambda x: adasum_allreduce(x[0], axis_name="world")[None],
        mesh=mesh,
        in_specs=P("world"),
        out_specs=P("world"),
        check_vma=False,
    )
    return np.asarray(jax.jit(fn)(jnp.asarray(stack)))


def test_identical_inputs_average_to_self():
    a = jnp.asarray(np.random.default_rng(0).normal(size=16).astype(np.float32))
    out = adasum_pair(a, a)
    # dot = ||a||² → coefs = 1 - 1/2 = 1/2 each → result = a
    np.testing.assert_allclose(np.asarray(out), np.asarray(a), rtol=1e-6)


def test_orthogonal_inputs_add():
    a = jnp.asarray([1.0, 0.0, 0.0, 0.0])
    b = jnp.asarray([0.0, 2.0, 0.0, 0.0])
    out = adasum_pair(a, b)
    np.testing.assert_allclose(np.asarray(out), [1.0, 2.0, 0.0, 0.0])


def test_parallel_inputs_average():
    a = jnp.asarray([2.0, 4.0])
    b = jnp.asarray([4.0, 8.0])  # b = 2a
    out = adasum_pair(a, b)
    # parallel case: result = (a + b)/2 * ... exact: coefs (1 - 2asq/2asq)=0
    # for a? dot=2||a||², acoef = 1 - 2||a||²/(2||a||²) = 0,
    # bcoef = 1 - 2||a||²/(2·4||a||²) = 3/4 → out = 3/4·b = [3, 6]
    np.testing.assert_allclose(np.asarray(out), [3.0, 6.0], rtol=1e-6)


def test_zero_input_passthrough():
    a = jnp.zeros(4)
    b = jnp.asarray([1.0, 2.0, 3.0, 4.0])
    np.testing.assert_allclose(np.asarray(adasum_pair(a, b)), np.asarray(b))
    np.testing.assert_allclose(np.asarray(adasum_pair(b, a)), np.asarray(b))


def test_scale_homogeneous():
    rng = np.random.default_rng(1)
    a = jnp.asarray(rng.normal(size=8).astype(np.float32))
    b = jnp.asarray(rng.normal(size=8).astype(np.float32))
    out1 = adasum_pair(a, b)
    out2 = adasum_pair(3.0 * a, 3.0 * b)
    np.testing.assert_allclose(np.asarray(out2), 3.0 * np.asarray(out1), rtol=1e-5)


def test_symmetry():
    rng = np.random.default_rng(2)
    a = jnp.asarray(rng.normal(size=8).astype(np.float32))
    b = jnp.asarray(rng.normal(size=8).astype(np.float32))
    np.testing.assert_allclose(
        np.asarray(adasum_pair(a, b)), np.asarray(adasum_pair(b, a)), rtol=1e-6
    )


def test_tree_combine_odd_count():
    vals = [jnp.full(4, float(i + 1)) for i in range(5)]
    out = _tree_combine(vals)
    assert out.shape == (4,)
    assert np.isfinite(np.asarray(out)).all()


@pytest.mark.parametrize("world", [2, 3, 5, 6, 8])
def test_vhdd_matches_host_oracle(world):
    """The distributed VHDD result must equal the host pairwise-tree
    oracle (adasum_pair_host math) on every rank — pow2 and non-pow2
    worlds, payload not divisible by the world (exercises padding)."""
    rng = np.random.default_rng(world)
    stack = rng.normal(size=(world, 13)).astype(np.float32)
    out = _run_distributed(stack, world)
    expect = adasum_vhdd_host(stack.astype(np.float64))
    for r in range(world):
        np.testing.assert_allclose(out[r], expect, rtol=1e-5, atol=1e-6)


def test_vhdd_identical_inputs_identity():
    """All ranks contributing the same vector must get it back (the
    n-way generalization of adasum(a,a)=a)."""
    base = np.linspace(-1.0, 1.0, 16, dtype=np.float32)
    stack = np.tile(base, (8, 1))
    out = _run_distributed(stack, 8)
    for r in range(8):
        np.testing.assert_allclose(out[r], base, rtol=1e-5, atol=1e-6)


def test_vhdd_wire_bytes_is_2p_not_logp():
    """The ~2P wire claim: per-rank bytes stay bounded (~2P) as the
    world grows, vs the naive full-tensor XOR loop's log2(n)*P."""
    P_bytes = 1 << 20
    for n in (8, 64, 256):
        naive = (n.bit_length() - 1) * P_bytes  # old: full tensor per stage
        vhdd = vhdd_wire_bytes(n, P_bytes)
        assert vhdd < 2 * P_bytes  # both sweeps sum below 2P
        assert vhdd < naive or n <= 4
    # non-pow2 adds one P-sized hop each way, still far under gather's n*P
    assert vhdd_wire_bytes(5, P_bytes) <= 4 * P_bytes


def test_bf16_inputs_keep_dtype():
    a = jnp.ones(8, dtype=jnp.bfloat16)
    b = jnp.ones(8, dtype=jnp.bfloat16)
    out = adasum_pair(a, b)
    assert out.dtype == jnp.bfloat16
