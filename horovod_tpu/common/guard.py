"""Non-finite sentinel: the host half of the GradGuard skip-step plane.

The data plane's silent killer is a single NaN/Inf: one non-finite
value in a fused gradient bucket poisons every tensor in the batch at
the next update, and the Horovod contract — bit-identical replicas
after every allreduce (arXiv 1802.05799) — means the poison is
perfectly replicated, so nothing ever *disagrees* loudly. The guard
closes that hole in two halves:

* **In-JIT half** (``ops/traced.finite_scalar`` / ``tree_finite``,
  folded into ``ops/overlap.bucketed_allreduce`` and the fused eager
  dispatch): one boolean ``all(isfinite(bucket))`` reduction per
  bucket, computed on the already-reduced values — a psum's output is
  replicated, so the flag agrees across ranks with NO extra
  collective and the skip decision stays inside ``lax.cond`` with no
  host sync on the healthy path.
* **Host half** (this module): the skip branch fires a
  ``jax.debug.callback`` — only when taken, so a healthy run never
  pays a host transfer — which counts ``guard.nonfinite_steps``,
  logs, and, after ``HOROVOD_GUARD_MAX_SKIPS`` CONSECUTIVE skips,
  LATCHES an escalation. The latch is raised as
  :class:`~horovod_tpu.common.basics.HorovodInternalError` at the
  next host touchpoint — ``State.commit()`` (so the elastic restore
  contract fires: ``hvd.elastic.run`` rolls back to the last commit
  instead of the job skipping forever against a poisoned input) or an
  explicit :func:`check`. Raising *inside* the callback would surface
  as ``XlaRuntimeError`` and sail past the elastic wrapper's
  ``except HorovodInternalError`` — the latch exists because the
  exception type must survive the device boundary.

Enable with ``HOROVOD_GUARD=1`` fleet-wide or ``grad_guard=True`` per
optimizer. Skipped steps keep the optimizer state, the step counter
advance, and the error-feedback residuals of the LAST APPLIED step —
the quantization-error carry stays coherent with what was actually
transmitted.
"""

from __future__ import annotations

import itertools
import threading
from typing import Optional

from .logging import get_logger

_log = get_logger("guard")

# per-optimizer identity for the skip-callback dedup: two guarded
# optimizers in one process can both be "at step 7", and deduping on
# the bare step id would silently drop the second one's skip (and its
# escalation check)
_source_ids = itertools.count()


def new_source() -> int:
    return next(_source_ids)


def default_enabled() -> bool:
    """The config-driven default for ``grad_guard=None`` optimizers."""
    from . import basics

    return bool(basics.live_config().guard)


def default_max_skips() -> int:
    from . import basics

    return int(basics.live_config().guard_max_skips)


class GradGuard:
    """Process-wide skip-step ledger (one per process, like the
    telemetry hub — the guard must survive an elastic reinit so its
    counters tell the whole job's story)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.nonfinite_steps = 0  # total skipped updates
        self.max_streak = 0  # worst consecutive run seen
        self._escalated: Optional[str] = None  # pending escalation msg
        self._last_step: Optional[tuple] = None  # (source, step) dedup

    def record_skip(
        self, streak: int, step: int, max_skips: int, source: int = 0
    ) -> None:
        """``jax.debug.callback`` target, fired from the SKIP branch of
        the guarded update only. ``streak`` is the traced consecutive-
        skip counter carried in the optimizer state (host-side counting
        can't see the GOOD steps that reset it — they never call back).
        An update running under the user's ``shard_map`` fires one
        callback PER SHARD; duplicates are deduped by (optimizer
        ``source``, step id) — the telemetry tick's trick, with the
        source added so two guarded optimizers sharing a step count
        cannot swallow each other's skips — so one skipped step
        counts once.
        At the escalation threshold the failure is LATCHED, not raised
        (module docstring: the exception type must survive the device
        boundary); :func:`check` / ``State.commit()`` raise it."""
        streak = int(streak)
        step = int(step)
        with self._lock:
            if self._last_step == (source, step):
                return
            self._last_step = (source, step)
            self.nonfinite_steps += 1
            self.max_streak = max(self.max_streak, streak)
        from .metrics import registry as _metrics

        _metrics.counter("guard.nonfinite_steps")
        _metrics.gauge("guard.skip_streak", streak)
        _log.warning(
            "non-finite gradients at step %d: update SKIPPED "
            "(consecutive skips: %d)", step, streak,
        )
        if max_skips > 0 and streak >= max_skips:
            _log.error(
                "guard escalation: %d consecutive non-finite steps "
                "(HOROVOD_GUARD_MAX_SKIPS=%d) — latched for the "
                "elastic restore contract", streak, max_skips,
            )
            with self._lock:
                self._escalated = (
                    f"{streak} consecutive non-finite gradient steps "
                    f"(threshold {max_skips}); training state is "
                    "suspect — restore from the last commit"
                )

    def raise_if_escalated(self) -> None:
        """Host-side escalation point: raises HorovodInternalError when
        the callback latched past the threshold. Cleared on raise so
        the retry (post-restore) starts with a clean slate."""
        with self._lock:
            msg, self._escalated = self._escalated, None
        if msg is not None:
            from .basics import HorovodInternalError

            raise HorovodInternalError(f"grad guard: {msg}")

    def reset(self) -> None:
        """Clear the streak view and any pending escalation after an
        elastic restore (the restored state predates the poison, so the
        streak is moot); cumulative ``nonfinite_steps`` is preserved —
        it is job history."""
        with self._lock:
            self.max_streak = 0
            self._escalated = None
            self._last_step = None  # restored step ids may repeat

    def status(self) -> dict:
        with self._lock:
            return {
                "nonfinite_steps": self.nonfinite_steps,
                "max_streak": self.max_streak,
                "escalated": self._escalated is not None,
            }


_guard: Optional[GradGuard] = None
_guard_lock = threading.Lock()


def guard() -> GradGuard:
    global _guard
    with _guard_lock:
        if _guard is None:
            _guard = GradGuard()
        return _guard


def _reset_guard() -> None:
    """Test hook: drop the singleton."""
    global _guard
    with _guard_lock:
        _guard = None


def record_skip(streak, step, max_skips, source=0) -> None:
    """Module-level callback target (stable identity for
    ``jax.debug.callback``). Never raises: an exception here would
    surface as XlaRuntimeError mid-dispatch; escalation rides the
    latch + :func:`check` instead."""
    try:
        guard().record_skip(
            int(streak), int(step), int(max_skips), source=int(source)
        )
    except Exception:
        _log.debug("guard skip callback failed", exc_info=True)


def check() -> None:
    """``hvd.guard_check()`` — raise the latched escalation (if any) as
    HorovodInternalError. ``State.commit()`` calls this, so elastic
    loops get it for free at every commit boundary; bare loops can
    call it themselves once per step (cheap: one lock, plus the eager
    fusion sentinel's flag sync when that guard is on)."""
    from . import basics

    if basics.is_initialized():
        fusion = basics._state.fusion
        if fusion is not None and getattr(fusion, "guard", False):
            fusion.guard_poll()
    guard().raise_if_escalated()


def status() -> dict:
    """``hvd.guard_status()`` — the skip ledger as a plain dict."""
    return guard().status()
