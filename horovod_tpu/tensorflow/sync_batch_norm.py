"""Cross-rank synchronized BatchNormalization for the TF shim.

TPU-native rebuild of the reference's TF ``hvd.SyncBatchNormalization``
(ref: horovod/tensorflow/sync_batch_norm.py [V]): batch statistics are
reduced across all ranks in forward, and the two gradient reductions of
the exact BN backward are likewise cross-rank, so every replica
normalizes — and differentiates — with global-batch statistics. Like
the torch shim's SyncBatchNorm (horovod_tpu/torch/sync_batch_norm.py),
the forward stats ride ONE fused allreduce (sum | sumsq | count) and
the backward one more (Σdy | Σdy·x̂); the host bridge is a
``tf.py_function``, so the layer works in eager and inside
``tf.function``/``model.fit`` graphs alike.

Keras semantics are preserved: ``momentum`` is the Keras moving-average
decay (``moving = moving·m + batch·(1−m)``), the moving variance stores
the biased batch variance, and eval normalizes with the moving stats —
with every rank seeing the same batch this layer is numerically
identical to ``keras.layers.BatchNormalization`` (the reference's own
equivalence contract).
"""

from __future__ import annotations

import numpy as np
import tensorflow as tf


def _host_allreduce_sum(vec):
    """Sum a 1-D float tensor across the mesh via the shim's eager path.

    Runs as a py_function so it is legal inside tf.function graphs; the
    inner body executes eagerly on the host (the same two-copy cost the
    shim's module docstring owns up to).
    """
    from . import Sum, allreduce

    def _np_sum(v):
        return np.asarray(allreduce(v.numpy(), op=Sum))

    out = tf.py_function(_np_sum, [vec], Tout=vec.dtype)
    out.set_shape(vec.shape)
    return out


class SyncBatchNormalization(tf.keras.layers.Layer):
    """Drop-in for ``keras.layers.BatchNormalization`` that synchronizes
    batch statistics across all horovod ranks during training (ref:
    horovod/tensorflow/sync_batch_norm.py [V])."""

    def __init__(
        self,
        axis: int = -1,
        momentum: float = 0.99,
        epsilon: float = 1e-3,
        center: bool = True,
        scale: bool = True,
        **kwargs,
    ):
        super().__init__(**kwargs)
        self.axis = axis
        self.momentum = momentum
        self.epsilon = epsilon
        self.center = center
        self.scale = scale

    def build(self, input_shape):
        ndim = len(input_shape)
        axis = self.axis % ndim
        self._channel_axis = axis
        dim = int(input_shape[axis])
        self._dim = dim
        self._reduce_axes = [a for a in range(ndim) if a != axis]
        # broadcast shape for per-channel vectors
        self._bshape = [1] * ndim
        self._bshape[axis] = dim
        if self.scale:
            self.gamma = self.add_weight(
                name="gamma", shape=(dim,), initializer="ones",
                trainable=True,
            )
        else:
            self.gamma = None
        if self.center:
            self.beta = self.add_weight(
                name="beta", shape=(dim,), initializer="zeros",
                trainable=True,
            )
        else:
            self.beta = None
        self.moving_mean = self.add_weight(
            name="moving_mean", shape=(dim,), initializer="zeros",
            trainable=False,
        )
        self.moving_variance = self.add_weight(
            name="moving_variance", shape=(dim,), initializer="ones",
            trainable=False,
        )

    def _affine(self, xhat, dtype):
        out = xhat
        if self.gamma is not None:
            out = out * tf.reshape(tf.cast(self.gamma, dtype), self._bshape)
        if self.beta is not None:
            out = out + tf.reshape(tf.cast(self.beta, dtype), self._bshape)
        return out

    def call(self, inputs, training=None):
        x = tf.convert_to_tensor(inputs)
        if tf.is_tensor(training):
            # Symbolic training flag (legacy Keras passes a placeholder
            # inside tf.function graphs): `not training` would raise
            # OperatorNotAllowedInGraphError, so build both branches and
            # select like keras.layers.BatchNormalization's smart_cond.
            # Stateful ops (the moving-average assigns, the py_function
            # allreduce) execute only in the taken branch.
            return tf.cond(
                tf.cast(training, tf.bool),
                lambda: self._train_call(x),
                lambda: self._infer_call(x),
            )
        if not training:
            return self._infer_call(x)
        return self._train_call(x)

    def _infer_call(self, x):
        dtype = x.dtype
        mean = tf.reshape(
            tf.cast(self.moving_mean, dtype), self._bshape
        )
        invstd = tf.reshape(
            tf.math.rsqrt(
                tf.cast(self.moving_variance, dtype) + self.epsilon
            ),
            self._bshape,
        )
        return self._affine((x - mean) * invstd, dtype)

    def _train_call(self, x):
        dtype = x.dtype
        c = self._dim
        xf = tf.cast(x, tf.float32)
        count_local = tf.cast(tf.size(xf) / c, tf.float32)
        local_sum = tf.reduce_sum(xf, self._reduce_axes)
        local_sumsq = tf.reduce_sum(xf * xf, self._reduce_axes)
        # one fused allreduce for the forward stats [V]
        fused = tf.concat(
            [local_sum, local_sumsq, tf.reshape(count_local, (1,))], 0
        )
        fused_g = _host_allreduce_sum(tf.stop_gradient(fused))
        n = fused_g[2 * c]
        mean = fused_g[:c] / n
        var = tf.maximum(fused_g[c : 2 * c] / n - mean * mean, 0.0)

        # Keras moving-average semantics: biased batch variance, decay m
        m = self.momentum
        self.moving_mean.assign(self.moving_mean * m + mean * (1.0 - m))
        self.moving_variance.assign(
            self.moving_variance * m + var * (1.0 - m)
        )

        invstd = tf.math.rsqrt(var + self.epsilon)
        mean_b = tf.reshape(mean, self._bshape)
        invstd_b = tf.reshape(invstd, self._bshape)
        reduce_axes = self._reduce_axes
        bshape = self._bshape
        gamma = self.gamma
        beta = self.beta

        @tf.custom_gradient
        def _bn_train(x32, g, b):
            xhat = (x32 - mean_b) * invstd_b
            out = xhat * tf.reshape(g, bshape) + tf.reshape(b, bshape)

            def grad(dy):
                sum_dy = tf.reduce_sum(dy, reduce_axes)
                sum_dy_xhat = tf.reduce_sum(dy * xhat, reduce_axes)
                # the exact BN backward needs GLOBAL Σdy and Σdy·x̂ [V]
                fused_bwd = _host_allreduce_sum(
                    tf.concat([sum_dy, sum_dy_xhat], 0)
                )
                sum_dy_g = fused_bwd[:c]
                sum_dy_xhat_g = fused_bwd[c:]
                dx = (
                    invstd_b
                    * tf.reshape(g, bshape)
                    * (
                        dy
                        - tf.reshape(sum_dy_g, bshape) / n
                        - xhat * tf.reshape(sum_dy_xhat_g, bshape) / n
                    )
                )
                # weight/bias grads stay LOCAL Σdy·x̂ / Σdy —
                # DistributedOptimizer / DistributedGradientTape reduces
                # parameter grads, exactly like the reference.
                return dx, sum_dy_xhat, sum_dy

            return out, grad

        # center/scale-off cases pass identity coefficients: they are
        # plain tensors (not variables), so their returned grads vanish
        g32 = (
            tf.cast(gamma, tf.float32)
            if gamma is not None
            else tf.ones((c,), tf.float32)
        )
        b32 = (
            tf.cast(beta, tf.float32)
            if beta is not None
            else tf.zeros((c,), tf.float32)
        )
        return tf.cast(_bn_train(xf, g32, b32), dtype)

    def get_config(self):
        cfg = super().get_config()
        cfg.update(
            axis=self.axis,
            momentum=self.momentum,
            epsilon=self.epsilon,
            center=self.center,
            scale=self.scale,
        )
        return cfg
