// Adasum pairwise combiner — host-side native math.
//
// TPU-native rebuild of the reference's Adasum core (ref:
// horovod/common/ops/adasum/adasum.h, the recursive
// vector-halving-distance-doubling combiner, and
// adasum_mpi_operations.cc — SURVEY.md §2.2). The on-device path lives
// in horovod_tpu/ops/adasum.py (XLA collectives + MXU dots); this is
// the CPU-buffer variant mirroring the reference's Adasum-MPI host
// path, used for host-resident tensors (elastic state reconciliation,
// tests, eager CPU arrays) and as the numerics oracle for the device
// kernels.
//
// Combine rule (adasum.h):
//   out = (1 - a.b / (2 a.a)) * a + (1 - a.b / (2 b.b)) * b
// Dot products accumulate in double regardless of input precision,
// matching the reference's accumulation discipline.

#include "export.h"

#include <cstring>
#include <vector>

namespace {

template <typename T>
void adasum_pair(const T* a, const T* b, T* out, long n) {
  double dot = 0.0, asq = 0.0, bsq = 0.0;
  for (long i = 0; i < n; ++i) {
    double av = static_cast<double>(a[i]);
    double bv = static_cast<double>(b[i]);
    dot += av * bv;
    asq += av * av;
    bsq += bv * bv;
  }
  double acoef = asq > 0.0 ? 1.0 - dot / (2.0 * asq) : 1.0;
  double bcoef = bsq > 0.0 ? 1.0 - dot / (2.0 * bsq) : 1.0;
  for (long i = 0; i < n; ++i) {
    out[i] = static_cast<T>(acoef * static_cast<double>(a[i]) +
                            bcoef * static_cast<double>(b[i]));
  }
}

// Pairwise tree over k row-major vectors of length n. Odd counts carry
// the trailing vector up a level — the same combination order as
// horovod_tpu/ops/adasum.py::_tree_combine, so both paths agree.
template <typename T>
void adasum_tree(const T* stack, long k, long n, T* out) {
  std::vector<std::vector<T>> vals;
  vals.reserve(k);
  for (long i = 0; i < k; ++i) {
    vals.emplace_back(stack + i * n, stack + (i + 1) * n);
  }
  while (vals.size() > 1) {
    std::vector<std::vector<T>> nxt;
    for (size_t i = 0; i + 1 < vals.size(); i += 2) {
      std::vector<T> combined(n);
      adasum_pair(vals[i].data(), vals[i + 1].data(), combined.data(), n);
      nxt.push_back(std::move(combined));
    }
    if (vals.size() % 2 == 1) nxt.push_back(std::move(vals.back()));
    vals = std::move(nxt);
  }
  std::memcpy(out, vals[0].data(), sizeof(T) * n);
}

}  // namespace

HVD_EXPORT void hvd_adasum_pair_f32(const float* a, const float* b, float* out,
                                    long n) {
  adasum_pair(a, b, out, n);
}

HVD_EXPORT void hvd_adasum_pair_f64(const double* a, const double* b,
                                    double* out, long n) {
  adasum_pair(a, b, out, n);
}

HVD_EXPORT void hvd_adasum_tree_f32(const float* stack, long k, long n,
                                    float* out) {
  adasum_tree(stack, k, n, out);
}

HVD_EXPORT void hvd_adasum_tree_f64(const double* stack, long k, long n,
                                    double* out) {
  adasum_tree(stack, k, n, out);
}
