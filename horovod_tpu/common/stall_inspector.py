"""Stall detection: cycle-latency watchdog + cross-process heartbeats.

TPU-native rebuild of horovod/common/stall_inspector.cc/.h [V]
(SURVEY.md §2.1). The reference warns when some ranks have submitted a
tensor and others haven't for >60s. Under a single controller that
exact skew cannot happen, so this inspector watches the signals that
CAN:

1. **Cycle-latency watchdog** (intra-process): an entry enqueued but
   never synchronized/flushed past the warning age — a leaked handle
   or a deadlocked consumer. This is the signal `check()` always has.
2. **Heartbeat staleness** (cross-process): in multi-process jobs
   (runner/elastic), worker processes PUT `heartbeat/<rank>` into the
   rendezvous KV on a timer (`runner.service.heartbeat` /
   `read_heartbeats`); the driver feeds those timestamps in via
   :meth:`record_heartbeat`, and `check()` warns when a rank goes
   silent past the warning age — the true analog of the reference's
   "some ranks are absent" report, rebuilt on the rendezvous channel
   the TPU runner actually has.
3. **Stragglers** (cross-rank, the telemetry upgrade): heartbeats now
   piggyback ``{step, step_ms_p50, last_step_ts}`` from each worker's
   flight-recorder ring (common/telemetry.py), so the driver can tell
   a SLOW rank from a SILENT one: :meth:`straggler_ranks` flags ranks
   whose step time is a configurable multiple
   (``HOROVOD_STRAGGLER_FACTOR``) of the gang median, or whose step
   counter lags the gang.

`check()` also publishes its view through the metrics registry
(``stall.pending``, ``stall.stale_ranks``, ``stall.straggler.*``), so
stalls are visible in JSON-lines dumps and on the live ``/metrics``
endpoint, not only in logs.
"""

from __future__ import annotations

import statistics
import time
from typing import Dict, List, Optional

from .basics import HorovodInternalError
from .logging import get_logger

logger = get_logger("stall")

DEFAULT_STRAGGLER_FACTOR = 3.0
# step-counter lag (vs the gang median) that flags a straggler even
# when its per-step time looks healthy — catches a rank that is
# silently re-doing work (e.g. recompiling every step)
DEFAULT_STRAGGLER_LAG_STEPS = 25


class StallInspector:
    def __init__(
        self,
        warning_seconds: float = 60.0,
        shutdown_seconds: float = 0.0,
        straggler_factor: float = DEFAULT_STRAGGLER_FACTOR,
    ):
        self.warning_seconds = warning_seconds
        self.shutdown_seconds = shutdown_seconds
        self.straggler_factor = float(straggler_factor)
        self._pending: Dict[str, float] = {}
        self._warned: set = set()
        self._heartbeats: Dict[int, float] = {}
        self._hb_stats: Dict[int, dict] = {}
        self._hb_warned: set = set()
        self._straggler_warned: set = set()
        # hysteresis for the self-healing driver: rank -> number of
        # consecutive FRESH heartbeat observations it has been flagged
        # a straggler (one blip must not quarantine a host; K-in-a-row
        # does). Streaks advance at most once per new heartbeat stamp —
        # the driver polls far more often than workers beat (1s vs
        # 10s), and re-judging the same stale payload K times would
        # turn one noisy sample into a quarantine.
        self._straggler_streaks: Dict[int, int] = {}
        self._streak_stamp: Dict[int, Optional[float]] = {}

    def record_enqueue(self, name: str) -> None:
        self._pending.setdefault(name, time.monotonic())

    def record_complete(self, name: str) -> None:
        self._pending.pop(name, None)
        self._warned.discard(name)

    def reset_heartbeats(self) -> None:
        """Forget all liveness state — call when the worker set
        changes (gang restart): departed ranks must not read as
        stalled."""
        self._heartbeats.clear()
        self._hb_stats.clear()
        self._hb_warned.clear()
        self._straggler_warned.clear()
        self._straggler_streaks.clear()
        self._streak_stamp.clear()

    def record_heartbeat(
        self,
        rank: int,
        ts: float = None,
        step: Optional[int] = None,
        step_ms_p50: Optional[float] = None,
        last_step_ts: Optional[float] = None,
    ) -> None:
        """Feed a worker heartbeat (driver side of signal #2). ``ts`` is
        a unix epoch stamp (``time.time()`` — the domain
        ``runner.rendezvous.put_heartbeat`` writes, chosen because the
        stamps cross machines); defaults to now. The optional telemetry
        fields are the straggler-ledger payload the worker piggybacks
        from its flight recorder (signal #3); absent fields keep the
        rank's previous values."""
        rank = int(rank)
        self._heartbeats[rank] = time.time() if ts is None else float(ts)
        self._hb_warned.discard(rank)
        stats = self._hb_stats.setdefault(rank, {})
        if step is not None:
            stats["step"] = int(step)
        if step_ms_p50 is not None:
            stats["step_ms_p50"] = float(step_ms_p50)
        if last_step_ts is not None:
            stats["last_step_ts"] = float(last_step_ts)

    def stale_ranks(self, now: float = None):
        """Ranks whose last heartbeat is older than warning_seconds.
        ``now`` is unix epoch (heartbeats cross machines; monotonic
        clocks don't)."""
        if not self._heartbeats:
            return []
        now = time.time() if now is None else now
        return sorted(
            r
            for r, t in self._heartbeats.items()
            if now - t > self.warning_seconds
        )

    def straggler_ranks(
        self,
        factor: Optional[float] = None,
        lag_steps: int = DEFAULT_STRAGGLER_LAG_STEPS,
    ) -> List[int]:
        """Ranks that are SLOW rather than silent — the upgrade over
        :meth:`stale_ranks`, possible because heartbeats now carry each
        worker's step telemetry. A rank is a straggler when:

        * its ``step_ms_p50`` exceeds ``factor`` × the gang median
          (``factor`` defaults to ``HOROVOD_STRAGGLER_FACTOR``), or
        * its step counter trails the gang's median step by more than
          ``lag_steps`` — it heartbeats fine but isn't making progress.

        Needs at least two reporting ranks (a median of one is the rank
        itself); returns a sorted rank list."""
        factor = self.straggler_factor if factor is None else float(factor)
        out = set()
        p50s = {
            r: s["step_ms_p50"]
            for r, s in self._hb_stats.items()
            if s.get("step_ms_p50", 0) > 0
        }
        if len(p50s) >= 2:
            median = statistics.median(p50s.values())
            if median > 0:
                out.update(
                    r for r, v in p50s.items() if v > factor * median
                )
        steps = {
            r: s["step"]
            for r, s in self._hb_stats.items()
            if s.get("step") is not None
        }
        if len(steps) >= 2 and lag_steps > 0:
            median_step = statistics.median(steps.values())
            out.update(
                r for r, v in steps.items() if median_step - v > lag_steps
            )
        return sorted(out)

    def heartbeat_stats(self) -> Dict[int, dict]:
        """Driver-side view of the per-rank straggler ledger."""
        return {r: dict(s) for r, s in self._hb_stats.items()}

    def straggler_streaks(self) -> Dict[int, int]:
        """rank -> consecutive fresh-heartbeat observations flagged."""
        return dict(self._straggler_streaks)

    def quarantine_candidates(self, polls: int) -> List[int]:
        """Ranks a straggler for at least ``polls`` CONSECUTIVE fresh
        heartbeat observations — the hysteresis gate the self-healing
        elastic driver uses before quarantining a host (one noisy
        sample must not cost a gang restart, however often the driver
        re-reads it). Empty when ``polls`` <= 0."""
        if polls <= 0:
            return []
        return sorted(
            r for r, n in self._straggler_streaks.items() if n >= polls
        )

    def _publish(self, stale, stragglers) -> None:
        """Registry gauges so stalls show up in metrics dumps and on
        the /metrics scrape, not only in logs. p50s are re-read so the
        worst-ratio gauge tracks the same data straggler_ranks used."""
        from .metrics import registry as _metrics

        p50s = [
            s["step_ms_p50"]
            for s in self._hb_stats.values()
            if s.get("step_ms_p50", 0) > 0
        ]
        worst_ratio = 0.0
        if len(p50s) >= 2:
            median = statistics.median(p50s)
            if median > 0:
                worst_ratio = max(p50s) / median
        _metrics.update(
            "stall",
            {
                "pending": len(self._pending),
                "stale_ranks": len(stale),
                "straggler.count": len(stragglers),
                "straggler.factor": self.straggler_factor,
                "straggler.worst_ratio": worst_ratio,
                "straggler.max_streak": max(
                    self._straggler_streaks.values(), default=0
                ),
            },
        )

    def check(self) -> None:
        """Called once per eager fusion cycle AND per traced-collective
        dispatch / telemetry step close (the reference checks once per
        background-loop cycle, stall_inspector.cc::CheckForStalledTensors
        [V]; the traced path has no background loop, so its dispatch
        sites stand in)."""
        now = time.monotonic()
        for name, t in list(self._pending.items()):
            age = now - t
            if (
                self.shutdown_seconds > 0
                and age > self.shutdown_seconds
            ):
                raise HorovodInternalError(
                    f"collective '{name}' stalled for {age:.0f}s "
                    f"(> HOROVOD_STALL_SHUTDOWN_TIME_SECONDS)"
                )
            if age > self.warning_seconds and name not in self._warned:
                self._warned.add(name)
                logger.warning(
                    "One or more collectives submitted but not completed "
                    "for %.0fs: %s. A consumer may be stalled.",
                    age,
                    name,
                )
        wall = time.time()  # heartbeats live in the epoch domain
        stale = self.stale_ranks(wall)
        stragglers = self.straggler_ranks()
        # hysteresis ledger: streaks grow while a rank STAYS flagged
        # across fresh heartbeat stamps and reset the moment it
        # recovers; an unchanged stamp (driver polls outpace worker
        # beats) neither grows nor resets the streak
        streaks: Dict[int, int] = {}
        stamps: Dict[int, Optional[float]] = {}
        for r in stragglers:
            stamp = self._heartbeats.get(r)
            prev = self._straggler_streaks.get(r, 0)
            if prev == 0 or stamp is None or stamp != self._streak_stamp.get(r):
                streaks[r] = prev + 1
                stamps[r] = stamp
            else:
                streaks[r] = prev
                stamps[r] = self._streak_stamp.get(r)
        self._straggler_streaks = streaks
        self._streak_stamp = stamps
        self._publish(stale, stragglers)
        for rank in stragglers:
            if rank not in self._straggler_warned:
                self._straggler_warned.add(rank)
                stats = self._hb_stats.get(rank, {})
                logger.warning(
                    "Rank %d is straggling: step_ms_p50=%.1f step=%s "
                    "(gang flags ranks past %.1fx the median). The "
                    "worker is alive but slow.",
                    rank,
                    stats.get("step_ms_p50", 0.0),
                    stats.get("step", "?"),
                    self.straggler_factor,
                )
        # a rank that left the straggler set may warn again on relapse
        self._straggler_warned.intersection_update(stragglers)
        for rank in stale:
            age = wall - self._heartbeats[rank]
            # Shutdown escalation re-checks EVERY cycle (like the
            # pending-entry path) — it must fire even after the
            # one-time warning already did.
            if (
                self.shutdown_seconds > 0
                and age > self.shutdown_seconds
            ):
                raise HorovodInternalError(
                    f"rank {rank} heartbeat silent for {age:.0f}s "
                    f"(> HOROVOD_STALL_SHUTDOWN_TIME_SECONDS)"
                )
            if rank not in self._hb_warned:
                self._hb_warned.add(rank)
                logger.warning(
                    "Rank %d has not heartbeat for %.0fs; the worker "
                    "may be stalled or partitioned.",
                    rank,
                    age,
                )
