"""Durable checkpointing — async, sharded, elastic-aware.

The reference has NO core checkpoint subsystem (SURVEY.md §5.4): users
save on rank 0 by hand (`examples/pytorch/pytorch_mnist.py` pattern
[V]) and elastic state lives only in memory (`State.commit()`), so a
full-job failure loses everything since the last user save. On TPU this
gap is load-bearing — preemption is the COMMON failure — so this module
provides what the reference papered over, with Horovod's idioms:

* ``CheckpointManager`` — Orbax-backed async save/restore of arbitrary
  pytrees (params/opt_state/step), sharded-array aware: each host
  writes its own shards (no rank-0 gather bottleneck), restore places
  leaves back on the current mesh.
* ``DurableJaxState`` — ``hvd.elastic.JaxState`` whose ``commit()``
  ALSO persists to disk every ``save_interval`` commits, and which can
  resume from the latest checkpoint after a full-job restart — the
  elastic protocol extended beyond the reference's in-memory-only
  rollback.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from .common.logging import get_logger
from .testing import chaos as _chaos

_log = get_logger("checkpoint")


class CheckpointManager:
    """Async sharded checkpoints (Orbax engine, Horovod-shaped API).

    Degradation-aware by design: saves are atomic (Orbax finalizes a
    step directory with a commit marker only after every artifact write
    lands, so a SIGKILL mid-save leaves an *uncommitted* directory the
    step listing ignores, never a truncated file the restore path
    trusts), and :meth:`restore_latest_good` walks the retained steps
    newest-first past any corrupt/partial checkpoint — counting each
    skip as ``checkpoint.fallback`` — instead of crashing the resume.
    """

    def __init__(
        self,
        directory: str,
        max_to_keep: int = 3,
        async_save: bool = True,
    ) -> None:
        import orbax.checkpoint as ocp

        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                enable_async_checkpointing=async_save,
            ),
        )

    def save(self, step: int, tree: Any, force: bool = False) -> bool:
        """Queue an async save of ``tree`` at ``step``. Returns whether
        a save was started (Orbax dedupes repeated steps)."""
        import orbax.checkpoint as ocp

        _chaos.inject("checkpoint.save")
        return self._mgr.save(
            step, args=ocp.args.StandardSave(tree), force=force
        )

    def restore(self, step: Optional[int] = None, like: Any = None) -> Any:
        """Restore the checkpoint at ``step`` (default: latest). With
        ``like`` (a pytree of arrays or ShapeDtypeStructs, possibly
        sharded), leaves are restored directly onto matching devices."""
        import orbax.checkpoint as ocp

        _chaos.inject("checkpoint.restore")
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoint under {self._dir}"
                )
        if like is not None:
            target = jax.tree_util.tree_map(_as_restore_spec, like)
            return self._mgr.restore(
                step, args=ocp.args.StandardRestore(target)
            )
        return self._mgr.restore(step)

    def restore_latest_good(
        self, like: Any = None
    ) -> Tuple[int, Any]:
        """Restore the newest checkpoint that actually loads.

        Walks the retained steps newest-first; a step that fails to
        restore (corrupt array file, half-written metadata — anything
        the atomic-commit marker didn't guard, e.g. post-commit disk
        damage) is logged, counted as ``checkpoint.fallback``, and
        skipped in favor of the next older one. Raises
        ``FileNotFoundError`` when no checkpoints exist, and a
        ``RuntimeError`` (chained to the last failure) when every
        retained checkpoint is bad — losing the whole retention window
        is a real failure the job must surface, not silently train
        from scratch over, so the all-corrupt case deliberately cannot
        collide with the fresh-start ``FileNotFoundError`` even when
        the underlying damage IS a missing file."""
        steps = sorted(self.all_steps(), reverse=True)
        if not steps:
            raise FileNotFoundError(f"no checkpoint under {self._dir}")
        last_exc: Optional[BaseException] = None
        for step in steps:
            try:
                return step, self.restore(step, like=like)
            except Exception as e:  # noqa: BLE001 — any load failure
                from .common.metrics import registry as _metrics

                _metrics.counter("checkpoint.fallback")
                _log.warning(
                    "checkpoint step %d failed to restore (%s: %s); "
                    "falling back to the previous one",
                    step, type(e).__name__, e,
                )
                last_exc = e
        assert last_exc is not None
        raise RuntimeError(
            f"all {len(steps)} retained checkpoint(s) under "
            f"{self._dir} failed to restore"
        ) from last_exc

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return sorted(self._mgr.all_steps())

    def wait_until_finished(self) -> None:
        """Block until queued async saves are durable — call before
        letting a preempted VM die (the TPU preemption-notice handler's
        job)."""
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _as_restore_spec(leaf):
    if isinstance(leaf, jax.Array):
        return jax.ShapeDtypeStruct(
            leaf.shape, leaf.dtype, sharding=leaf.sharding
        )
    if isinstance(leaf, np.ndarray):
        return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype)
    return leaf


# --------------------------------------------------- elastic integration

from .elastic.state import JaxState  # noqa: E402  (import cycle: none)


class DurableJaxState(JaxState):
    """Elastic state with a durable spine.

    ``commit()`` keeps the reference's in-memory rollback semantics
    (peer failure → ``restore()`` to last commit, SURVEY.md §3.4) and
    additionally persists every ``save_interval``-th commit through a
    :class:`CheckpointManager`, so a FULL-job failure (every peer gone —
    the case the reference cannot survive) resumes from disk via
    :meth:`resume_latest`.

    The pytree attributes are saved; plain-object attributes ride along
    pickled into a side leaf only if numpy-representable (scalars/ints),
    mirroring what JaxState snapshots.
    """

    def __init__(
        self,
        checkpoint_dir: str,
        save_interval: int = 1,
        max_to_keep: int = 3,
        **kwargs: Any,
    ) -> None:
        self._ckpt = CheckpointManager(
            checkpoint_dir, max_to_keep=max_to_keep
        )
        self._save_interval = max(int(save_interval), 1)
        self._commits = 0
        self._step_counter = 0
        super().__init__(**kwargs)

    def _durable_tree(self) -> Dict[str, Any]:
        tree = {k: v for k, v in self._trees.items()}
        scalars = {
            k: v
            for k, v in self._attrs().items()
            if isinstance(v, (int, float, bool, np.integer, np.floating))
        }
        return {"trees": tree, "scalars": scalars}

    def commit(self) -> None:
        super().commit()
        self._commits += 1
        if self._commits % self._save_interval == 0:
            self._step_counter += 1
            self._ckpt.save(self._step_counter, self._durable_tree())

    def persist(self) -> None:
        """Unconditionally write the CURRENT live state to a durable
        checkpoint — no ``save_interval`` batching, no host-update check
        (``commit()`` does both, and either can lose the grace window:
        with save_interval>1 the write is skipped, and
        ``check_host_updates()`` can raise ``HostsUpdatedInterrupt``
        before saving). :class:`~horovod_tpu.preemption.GracefulShutdown`
        calls this, so a preempted VM always flushes its latest state."""
        self._step_counter += 1
        self._ckpt.save(self._step_counter, self._durable_tree(), force=True)

    def resume_latest(self) -> bool:
        """Load the newest *good* durable checkpoint into this state.
        Returns False when none exists (fresh start). A corrupt or
        partially-damaged newest checkpoint does not crash the resume:
        the manager falls back through the retention window
        (``checkpoint.fallback`` counts each skip) and only raises when
        every retained checkpoint is bad."""
        try:
            step, restored = self._ckpt.restore_latest_good(
                like=self._durable_tree()
            )
        except FileNotFoundError:
            return False
        for key, value in restored["trees"].items():
            self._trees[key] = self._replicate(value)
        for key, value in restored["scalars"].items():
            current = getattr(self, key, None)
            if isinstance(current, bool) or isinstance(value, np.bool_):
                value = bool(value)
            elif isinstance(current, int):
                value = int(value)
            elif isinstance(current, float):
                value = float(value)
            setattr(self, key, value)
        self._step_counter = step
        self.save()  # the restored state is the new rollback point
        return True

    def wait_until_finished(self) -> None:
        self._ckpt.wait_until_finished()

    def close(self) -> None:
        self._ckpt.close()
