"""Quantized fused wire (ISSUE 2): the fused buffer traverses the
collective as block-scaled int8 inside ONE compiled executable.

Acceptance surface:
* one fused quantized batch = one dispatch, served by the executor
  cache exactly like the fp32 path;
* wire-byte counter shows ~4x reduction vs the fp32 fused wire;
* numerical parity with the unfused `traced.quantized_allreduce`
  within the quantization error budget (process-set and join-mask
  cases included);
* bucket-tier pad bytes never leak into block scales or residuals;
* error-feedback carry stays bounded across a bucket→exact promotion;
* `HOROVOD_FUSION_WIRE=auto` picks fp32/bf16 for tiny buckets and
  int8 for large ones;
* prescale folding (satellite): `quantized_allreduce(prescale_factor=)`
  is bit-exact vs the two-pass pre-multiply form.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd_mod
from horovod_tpu.ops import fusion as fusion_mod
from horovod_tpu.ops.compression import Compression

WORLD = 8


def rank_major(fn, dtype=np.float32):
    return np.stack([np.asarray(fn(r), dtype=dtype) for r in range(WORLD)])


def _fusion():
    return hvd_mod.common.basics.state().fusion


def _freeze_cycle(fusion):
    fusion.cycle_time_ms = 1e6
    fusion.threshold_bytes = 1 << 30


def _shmap(mesh, fn, n_out=1):
    out_specs = (
        P(hvd_mod.WORLD_AXIS)
        if n_out == 1
        else tuple(P(hvd_mod.WORLD_AXIS) for _ in range(n_out))
    )
    return jax.jit(
        partial(
            jax.shard_map,
            mesh=mesh,
            in_specs=P(hvd_mod.WORLD_AXIS),
            out_specs=out_specs,
            check_vma=False,
        )(fn)
    )


def _quantum_bound(rows, n=WORLD):
    """Worst-case error of the two-stage quantized recipe for a batch
    whose per-rank rows are `rows`: one quantum per rank at stage 1
    plus one at stage 2, each quantum <= absmax/127 of its source."""
    q1 = sum(np.abs(np.asarray(r)).max() for r in rows) / 127.0
    total = np.sum(np.stack([np.asarray(r) for r in rows]), axis=0)
    q2 = np.abs(total).max() / 127.0
    return q1 + q2


def _batch_bound(tensors):
    """Quantum bound for a FUSED batch: block boundaries follow the
    concatenated buffer, not the entries, so an entry's error budget is
    set by the absmax of whatever shares its blocks — bound it by the
    per-rank concatenated row."""
    rows = [
        np.concatenate([np.asarray(t[r]).ravel() for t in tensors])
        for r in range(WORLD)
    ]
    return _quantum_bound(rows)


# ------------------------------------------------ single dispatch + bytes


def test_fused_quantized_batch_is_one_cached_dispatch(hvd):
    """A quantized fused batch compiles to ONE executable, dispatches
    once per cycle, and repeats hit the exact-tier cache — same
    contract as the fp32 path (PR 1)."""
    fusion = _fusion()
    _freeze_cycle(fusion)
    sizes = [600, 300, 100]

    def run():
        handles = [
            hvd.allreduce_async(
                rank_major(lambda r, n=n: np.arange(n, dtype=np.float32) + r),
                op=hvd_mod.Sum,
                name=f"q{i}",
                compression=Compression.int8,
            )
            for i, n in enumerate(sizes)
        ]
        return [np.asarray(h.wait()) for h in handles]

    run()  # warm: compiles the fused quantized executable
    d0, h0 = fusion.dispatches, fusion.cache_hits
    outs = run()
    assert fusion.dispatches == d0 + 1
    assert fusion.cache_hits == h0 + 1
    bound = _batch_bound(
        [
            rank_major(lambda r, n=n: np.arange(n, dtype=np.float32) + r)
            for n in sizes
        ]
    )
    for n, out in zip(sizes, outs):
        exact = 8 * np.arange(n) + 28.0
        assert np.abs(out[0] - exact).max() <= bound * 1.01


def test_wire_byte_counter_shows_4x_reduction(hvd):
    """For fp32 payloads the int8 wire's saved-bytes counter must show
    >= 3.5x reduction (4x minus the block-scale overhead)."""
    fusion = _fusion()
    _freeze_cycle(fusion)
    n = 4096  # bucket == useful == 4096, nb = 8/rank-chunk at block 512
    s0 = fusion.wire_bytes_saved_total
    b0 = fusion.quant_blocks_total
    h = hvd.allreduce_async(
        rank_major(lambda r: np.ones(n, np.float32) * (r + 1)),
        op=hvd_mod.Sum,
        compression=Compression.int8,
    )
    h.wait()
    saved = fusion.wire_bytes_saved_total - s0
    fp32_wire = n * 4 * 8  # bucket elems x itemsize x world rows
    actual = fp32_wire - saved
    assert fp32_wire / actual >= 3.5
    assert fusion.quant_blocks_total > b0
    assert fusion.last_wire_format == "int8"
    stats = fusion.cache_stats()
    for key in ("wire_bytes_saved", "quant_blocks", "wire_format"):
        assert key in stats, key
    from horovod_tpu.common.metrics import WIRE_FORMAT_CODES

    assert stats["wire_format"] == WIRE_FORMAT_CODES["int8"]


# -------------------------------------------- parity vs unfused recipe


def test_parity_fused_vs_unfused_quantized_allreduce(hvd):
    """The fused quantized batch must land within the same quantization
    error budget as per-tensor `traced.quantized_allreduce` — both are
    two-stage stochastic quantizers, so each sits within the two-stage
    quantum bound of the exact result and within twice that of each
    other."""
    from horovod_tpu.ops import traced

    fusion = _fusion()
    _freeze_cycle(fusion)
    mesh = hvd_mod.mesh()
    rng = np.random.default_rng(3)
    sizes = [700, 260]
    tensors = [
        rank_major(lambda r, n=n: rng.normal(size=n) * (r + 1))
        for n in sizes
    ]

    handles = [
        hvd.allreduce_async(
            t, op=hvd_mod.Sum, name=f"p{i}", compression=Compression.int8
        )
        for i, t in enumerate(tensors)
    ]
    fused = [np.asarray(h.wait()) for h in handles]

    batch_bound = _batch_bound(tensors)
    for t, out in zip(tensors, fused):
        unfused = _shmap(
            mesh,
            lambda x: traced.quantized_allreduce(x[0], op=hvd_mod.Sum)[None],
        )(jnp.asarray(t))
        exact = np.asarray(t).sum(0)
        bound = _quantum_bound(list(t))
        assert np.abs(out[0] - exact).max() <= batch_bound * 1.01
        assert np.abs(np.asarray(unfused)[0] - exact).max() <= bound * 1.01
        assert (
            np.abs(out[0] - np.asarray(unfused)[0]).max()
            <= batch_bound + bound
        )


def test_parity_quantized_with_join_mask_and_process_set(hvd):
    """Masked participation composes with the quantized wire: joined
    ranks drop out of the average, non-members of a process set keep
    their input, and the result stays within the quantum budget of the
    exact masked reduction."""
    fusion = _fusion()
    _freeze_cycle(fusion)
    n = 600

    with hvd.join_ranks([2]):
        h = hvd.allreduce_async(
            rank_major(lambda r: np.full(n, float(r))),
            op=hvd_mod.Average,
            compression=Compression.int8,
        )
    out = np.asarray(h.wait())
    true = np.mean([r for r in range(8) if r != 2])
    quantum = 7.0 / 127.0  # absmax of any contributing row / 127
    assert np.abs(out[0] - true).max() <= 9 * quantum

    ps = hvd.add_process_set([1, 3, 5])
    h = hvd.allreduce_async(
        rank_major(lambda r: np.full(n, float(r))),
        op=hvd_mod.Average,
        process_set=ps,
        compression=Compression.int8,
    )
    out = np.asarray(h.wait())
    assert np.abs(out[1] - 3.0).max() <= 9 * quantum  # member: mean{1,3,5}
    np.testing.assert_allclose(out[0], 0.0)  # non-member keeps input
    np.testing.assert_allclose(out[6], 6.0)


def test_quantized_wire_rejects_nonfloat_and_nonlinear_ops(hvd):
    """Min/Max/Product and integer payloads silently ride the fp32 wire
    (quantization commutes with neither), keeping results exact."""
    fusion = _fusion()
    _freeze_cycle(fusion)
    h = hvd.allreduce_async(
        rank_major(lambda r: np.arange(1.0, 6.0) + r),
        op=hvd_mod.Min,
        compression=Compression.int8,
    )
    out = np.asarray(h.wait())
    np.testing.assert_allclose(out[0], np.arange(1.0, 6.0))
    assert fusion.last_wire_format == "fp32"


# ------------------------------------------------------- pad exclusion


def test_bucket_pad_does_not_leak_into_scales_or_residuals(hvd):
    """On the padded bucket tier, the zero tail must not raise any
    block scale (the result of the valid region matches the unpadded
    exact-tier result to the shared quantum budget) and the residual
    of the pad region must be exactly zero."""
    fusion = _fusion()
    _freeze_cycle(fusion)
    rng = np.random.default_rng(7)
    base = rank_major(lambda r: rng.normal(size=300) * (r + 1))
    tail = rank_major(lambda r: rng.normal(size=100))

    # composition A claims the 512-bucket's exact tier (same core key:
    # int8 wire + residuals); composition B (300+100 elems, same
    # bucket) then rides the PADDED bucket core.
    hvd.allreduce_async(
        rank_major(lambda r: np.ones(500, np.float32)),
        op=hvd_mod.Sum, name="warm", compression=Compression.int8,
        return_residual=True,
    ).wait()
    b0 = fusion.bucket_hits
    hs = hvd.grouped_allreduce_async(
        [base, tail],
        op=hvd_mod.Sum,
        compression=Compression.int8,
        return_residual=True,
    )
    (out, res), (_out2, _res2) = [h.wait() for h in hs]
    assert fusion.bucket_hits == b0 + 1  # padded bucket-tier dispatch
    assert fusion.last_cycle_pad_bytes > 0
    exact = np.asarray(base).sum(0)
    # pad contributes nothing to the bound: a leaked pad scale would
    # show up as error/residual far beyond this budget
    bound = _batch_bound([base, tail])
    assert np.abs(np.asarray(out)[0] - exact).max() <= bound * 1.01
    # residual = local - wire value: bounded by the per-rank quantum of
    # the CONCATENATED row (+ the owned shard's), pad excluded
    total_row = np.concatenate([exact, np.asarray(tail).sum(0)])
    shard_quantum = np.abs(total_row).max() / 127.0
    for r in range(8):
        row = np.concatenate([np.asarray(base[r]), np.asarray(tail[r])])
        local_quantum = np.abs(row).max() / 127.0
        assert (
            np.abs(np.asarray(res)[r]).max()
            <= (local_quantum + shard_quantum) * 1.01
        )


def test_pad_blocks_quantize_to_exact_zero():
    """Unit check on the kernel contract the bucket tier relies on:
    zero pad elements quantize to zero values, contribute a minimal
    scale, and dequantize to exactly zero."""
    from horovod_tpu.ops.pallas_kernels import (
        int8_block_dequantize,
        int8_block_quantize,
    )

    x = np.zeros(1024, np.float32)
    x[:100] = np.linspace(-3, 3, 100)
    vals, scales = jax.jit(
        partial(int8_block_quantize, block_size=512)
    )(jnp.asarray(x))
    vals, scales = np.asarray(vals), np.asarray(scales)
    assert vals.shape == (1024,) and scales.shape == (2,)
    assert np.all(vals[512:] == 0)  # pure-pad block: all-zero values
    assert scales[1] <= 1e-30 / 127.0 * 1.01  # floor scale, not leaked
    back = np.asarray(
        int8_block_dequantize(jnp.asarray(vals), jnp.asarray(scales),
                              block_size=512)
    )
    assert np.all(back[512:] == 0.0)
    assert np.abs(back[:100] - x[:100]).max() <= 6 / 127.0 * 1.01


# ------------------------------------------- error feedback + promotion


def test_error_feedback_carry_across_bucket_to_exact_promotion(hvd):
    """EF keeps the cumulative transmitted signal within a constant
    number of quanta of the truth, INCLUDING across the dispatch-path
    change when a composition is promoted from the padded bucket tier
    to its own exact executable mid-run."""
    fusion = _fusion()
    _freeze_cycle(fusion)
    assert fusion.promote_after == 2
    g = rank_major(lambda r: np.full(300, 0.01) * (r + 1))
    exact_step = np.asarray(g).sum(0)

    # claim the bucket with a different composition (same core key:
    # int8 + residuals) so `g`'s composition starts on the padded
    # bucket tier
    hvd.allreduce_async(
        rank_major(lambda r: np.ones(480, np.float32)),
        op=hvd_mod.Sum,
        compression=Compression.int8,
        return_residual=True,
    ).wait()

    steps = 6
    res = np.zeros_like(np.asarray(g))
    cumulative = np.zeros_like(exact_step)
    p0 = fusion.promotions
    for _ in range(steps):
        h = hvd.allreduce_async(
            np.asarray(g) + res,
            op=hvd_mod.Sum,
            compression=Compression.int8,
            return_residual=True,
        )
        out, new_res = h.wait()
        cumulative += np.asarray(out)[0]
        res = np.asarray(new_res)
    assert fusion.promotions == p0 + 1  # the path DID change mid-run
    per_step_quantum = _quantum_bound(list(g))
    err = np.abs(cumulative - steps * exact_step).max()
    # EF: bounded by ~one step's budget, not steps x budget
    assert err <= 2 * per_step_quantum + 1e-5


def test_residual_reconstructs_wire_value_fused(hvd):
    """Fused EF contract matches traced.quantized_allreduce's: the
    residual is bounded by local + shard quanta, per entry."""
    fusion = _fusion()
    _freeze_cycle(fusion)
    rng = np.random.default_rng(0)
    t = rank_major(lambda r: rng.normal(size=256))
    h = hvd.allreduce_async(
        t, op=hvd_mod.Sum, compression=Compression.int8,
        return_residual=True,
    )
    out, res = h.wait()
    res = np.asarray(res)
    total = np.asarray(t).sum(0)
    q2 = np.abs(total).max() / 127.0
    for r in range(8):
        q1 = np.abs(np.asarray(t[r])).max() / 127.0
        assert np.abs(res[r]).max() <= (q1 + q2) * 1.01


def test_ef_residual_norm_gauge_when_observability_on(hvd, tmp_path):
    """fusion.ef_residual_norm lands in the metrics registry when a
    sink is configured (and only then — it costs a host sync)."""
    from horovod_tpu.common.metrics import registry

    fusion = _fusion()
    _freeze_cycle(fusion)
    registry.configure_export(str(tmp_path / "metrics.jsonl"))
    try:
        h = hvd.allreduce_async(
            rank_major(lambda r: np.ones(128) * (r + 1)),
            op=hvd_mod.Sum,
            compression=Compression.int8,
            return_residual=True,
        )
        h.wait()
        snap = registry.snapshot()
        assert "fusion.ef_residual_norm" in snap
        assert snap["fusion.ef_residual_norm"] == fusion.ef_residual_norm
        assert np.isfinite(fusion.ef_residual_norm)
    finally:
        registry._path = None  # restore: no sink outside this test


def test_return_residual_requires_int8_wire(hvd):
    with pytest.raises(ValueError, match="int8"):
        hvd.allreduce_async(
            rank_major(lambda r: np.ones(8)),
            op=hvd_mod.Sum,
            compression=Compression.bf16,
            return_residual=True,
        )


def test_bad_residual_request_raises_at_enqueue_not_flush(hvd):
    """An ineligible return_residual request (op/dtype) must fail AT
    ENQUEUE — a flush-time failure would abort the cycle and strand
    every other pending entry's handle."""
    fusion = _fusion()
    _freeze_cycle(fusion)
    healthy = hvd.allreduce_async(
        rank_major(lambda r: np.ones(16)), op=hvd_mod.Sum, name="healthy"
    )
    with pytest.raises(ValueError, match="Sum/Average"):
        hvd.allreduce_async(
            rank_major(lambda r: np.ones(8)),
            op=hvd_mod.Min,
            return_residual=True,
        )
    with pytest.raises(ValueError, match="floating"):
        hvd.allreduce_async(
            rank_major(lambda r: np.ones(8, np.int32), dtype=np.int32),
            op=hvd_mod.Sum,
            return_residual=True,
        )
    with pytest.raises(ValueError, match="Sum/Average"):
        hvd.allreduce_async(
            rank_major(lambda r: np.ones(8)),
            op=hvd_mod.Adasum,
            process_set=hvd.add_process_set([0, 1]),
            return_residual=True,
        )
    # the healthy entry's cycle was never poisoned
    np.testing.assert_allclose(np.asarray(healthy.wait())[0], 8.0)


# ----------------------------------------------------- wire knob + auto


def test_bf16_wire_halves_and_int8_quarters_wire_bytes(hvd):
    fusion = _fusion()
    _freeze_cycle(fusion)
    n = 2048
    t = rank_major(lambda r: np.ones(n, np.float32))
    s0 = fusion.wire_bytes_saved_total
    hvd.allreduce(t, op=hvd_mod.Sum, compression=Compression.bf16)
    bf16_saved = fusion.wire_bytes_saved_total - s0
    assert bf16_saved == n * 2 * 8  # half of fp32's 4 bytes/elem
    assert fusion.last_wire_format == "bf16"


def test_manager_wire_knob_applies_without_per_call_compression(hvd):
    """HOROVOD_FUSION_WIRE=int8 quantizes plain hvd.allreduce calls."""
    fusion = _fusion()
    _freeze_cycle(fusion)
    fusion.wire = "int8"
    try:
        s0 = fusion.wire_bytes_saved_total
        out = hvd.allreduce(
            rank_major(lambda r: np.full(1024, float(r + 1))),
            op=hvd_mod.Sum,
        )
        assert fusion.wire_bytes_saved_total > s0
        assert np.abs(np.asarray(out)[0] - 36.0).max() <= 9 * 8 / 127.0
    finally:
        fusion.wire = "fp32"


def test_auto_wire_picks_fp32_small_int8_large(hvd):
    """The WireTuner contract the auto mode rides: tiny buckets never
    try int8 (static floor); large buckets explore, then exploit the
    goodput argmax."""
    from horovod_tpu.common.autotune import WireTuner

    tuner = WireTuner(min_int8_bytes=64 * 1024, trials=2)
    tiny = ("allreduce", 256, "float32")
    # tiny bucket: int8 is never even explored
    for _ in range(10):
        assert tuner.choose(tiny, payload_bytes=256 * 4) != "int8"
    big = ("allreduce", 1 << 20, "float32")
    useful = (1 << 20) * 4 * 8
    seen = []
    for _ in range(3 * tuner.trials):
        w = tuner.choose(big, payload_bytes=(1 << 20) * 4)
        seen.append(w)
        # synthetic goodput: int8 moves 4x fewer bytes -> 3x faster
        tuner.record(big, w, useful, 1.0 if w != "int8" else 1 / 3.0)
    assert set(seen) == {"fp32", "bf16", "int8"}  # explored everything
    for _ in range(5):
        assert tuner.choose(big, payload_bytes=(1 << 20) * 4) == "int8"


def test_auto_wire_end_to_end_in_fusion(hvd):
    """auto mode wired through the manager: tiny batches dispatch on a
    non-int8 wire, large batches reach int8 once explored, and every
    dispatch feeds the tuner an observation."""
    from horovod_tpu.common.autotune import WireTuner

    fusion = _fusion()
    _freeze_cycle(fusion)
    fusion.wire = "auto"
    fusion.wire_tuner = WireTuner(min_int8_bytes=16 * 1024, trials=1)
    try:
        hvd.allreduce(
            rank_major(lambda r: np.ones(64, np.float32)), op=hvd_mod.Sum
        )
        assert fusion.last_wire_format != "int8"  # under the floor
        seen = set()
        # compile-time dispatches are excluded from tuner observations,
        # so each format takes up to 2 calls (compile, then record) to
        # finish its single trial
        for _ in range(8):
            hvd.allreduce(
                rank_major(lambda r: np.ones(8192, np.float32)),
                op=hvd_mod.Sum,
            )
            seen.add(fusion.last_wire_format)
        assert "int8" in seen  # explored within 2 x trials x candidates
        key = ("allreduce", 8192, "float32")
        assert any(
            fusion.wire_tuner._obs.get((key, w), [0, 0, 0])[2] > 0
            for w in ("fp32", "bf16", "int8")
        )
    finally:
        fusion.wire = "fp32"
        fusion.wire_tuner = None


# ------------------------------------------------ hierarchical placement


def test_hierarchical_int8_wire_with_synthetic_stages(hvd):
    """bf16-intra + int8-inter placement on a synthetic 4-host x
    2-chip split of the 8-device test mesh: result within the (now
    host-count-sized) quantum budget."""
    fusion = _fusion()
    _freeze_cycle(fusion)
    fusion._hier_stages = lambda: fusion_mod.hierarchical_stage_groups(8, 2)
    h = hvd.allreduce_async(
        rank_major(lambda r: np.full(600, float(r))),
        op=hvd_mod.Average,
        compression=Compression.hier_int8,
    )
    out = np.asarray(h.wait())
    assert np.abs(out[0] - 3.5).max() <= 0.5  # coarse: two int8 stages
    np.testing.assert_allclose(out[0], out[5])  # all ranks agree


def test_hier_degenerates_to_flat_int8_on_single_host(hvd):
    fusion = _fusion()
    _freeze_cycle(fusion)
    # default topology: one host -> hierarchical_stage_groups is None
    h = hvd.allreduce_async(
        rank_major(lambda r: np.full(600, float(r))),
        op=hvd_mod.Sum,
        compression=Compression.hier_int8,
    )
    out = np.asarray(h.wait())
    assert np.abs(out[0] - 28.0).max() <= 9 * 7 / 127.0
    assert fusion.last_wire_format == "int8"


# -------------------------------------------------- satellite: prescale


def test_prescale_folds_into_wire_scales_bit_exact(hvd):
    """quantized_allreduce(prescale_factor=c) vs quantized_allreduce of
    c*x: quantization is scale-invariant for c > 0, so the folded form
    (which skips a full HBM pass) must be BIT-exact, residual included
    (in input units: two-pass residual / c)."""
    from horovod_tpu.ops import traced

    mesh = hvd_mod.mesh()
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(8, 130)).astype(np.float32))
    c = 0.125

    two_pass = _shmap(
        mesh,
        lambda t: traced.quantized_allreduce(
            t[0] * c, op=hvd_mod.Sum, seed=5, return_residual=True
        ),
        n_out=2,
    )
    folded = _shmap(
        mesh,
        lambda t: traced.quantized_allreduce(
            t[0], op=hvd_mod.Sum, seed=5, return_residual=True,
            prescale_factor=c,
        ),
        n_out=2,
    )
    out_a, res_a = two_pass(x)
    out_b, res_b = folded(x)
    assert np.array_equal(np.asarray(out_a), np.asarray(out_b))
    # two-pass residual is in PRESCALED units; folded is input units
    np.testing.assert_allclose(
        np.asarray(res_a) / c, np.asarray(res_b), rtol=1e-5, atol=1e-7
    )


def test_quantized_allreduce_block_size_traced_path(hvd):
    """block_size= on the traced recipe (the Compression.int8_block
    optimizer path): mixed-magnitude rows stay within their own
    block's quantum instead of the chunk absmax, and the residual
    contract holds."""
    from horovod_tpu.ops import traced

    mesh = hvd_mod.mesh()
    # one huge block next to tiny ones: per-chunk scaling would cost
    # the tiny region quanta of ~1000/127; block scaling must not
    n = 1024
    row = np.ones(n, np.float32) * 0.01
    row[:256] = 1000.0
    x = jnp.asarray(np.stack([row] * 8))

    out, res = _shmap(
        mesh,
        lambda t: tuple(
            a[None]
            for a in traced.quantized_allreduce(
                t[0], op=hvd_mod.Sum, seed=3, return_residual=True,
                block_size=128,
            )
        ),
        n_out=2,
    )(x)
    out, res = np.asarray(out)[0], np.asarray(res)
    exact = row * 8
    # the tiny region's error budget is its own blocks' quanta
    # (0.08/127 per stage x (8+1) contributions), nowhere near the
    # ~63 quanta a shared chunk scale would allow
    assert np.abs(out[256:] - exact[256:]).max() <= 9 * 0.08 / 127 + 1e-5
    assert np.abs(out[:256] - exact[:256]).max() <= 9 * 8000 / 127
    for r in range(8):
        assert np.abs(res[r][-128:]).max() <= 2 * 0.08 / 127 + 1e-6


def test_prescale_zero_residual_is_zero_not_nan(hvd):
    from horovod_tpu.ops import traced

    mesh = hvd_mod.mesh()
    x = jnp.asarray(np.ones((8, 130), np.float32))
    out, res = _shmap(
        mesh,
        lambda t: traced.quantized_allreduce(
            t[0], op=hvd_mod.Sum, return_residual=True,
            prescale_factor=0.0,
        ),
        n_out=2,
    )(x)
    np.testing.assert_allclose(np.asarray(out), 0.0)
    np.testing.assert_allclose(np.asarray(res), 0.0)  # not NaN


def test_optimizer_int8_block_uses_block_scales(hvd):
    """Compression.int8_block through DistributedOptimizer: the tiny
    region of a mixed-magnitude gradient survives (per-chunk scaling
    would flush 0.01-sized entries quantized against a 1000 absmax)."""
    import optax

    mesh = hvd_mod.mesh()
    opt = hvd_mod.DistributedOptimizer(
        optax.sgd(1.0), compression=Compression.int8_block,
        op=hvd_mod.Average,
    )
    g_row = np.ones(1024, np.float32) * 0.01
    g_row[:512] = 500.0
    g = jnp.asarray(np.stack([g_row] * 8))
    params = {"w": jnp.zeros(1024, jnp.float32)}
    state = opt.init(params)

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(), P(), P(hvd_mod.WORLD_AXIS)),
        out_specs=P(),
        check_vma=False,
    )
    def step(p, s, grads):
        u, _ = opt.update({"w": grads[0]}, s, p)
        return u["w"]

    upd = np.asarray(jax.jit(step)(params, state, g))
    # tail blocks (all 0.01): error within their own block quanta
    assert np.abs(upd[512:] + 0.01).max() <= 9 * 0.01 / 127 + 1e-6


def test_optimizer_predivide_uses_folded_prescale(hvd):
    """DistributedOptimizer(gradient_predivide_factor=) on the int8
    wire still averages correctly with the folded prescale."""
    import optax

    mesh = hvd_mod.mesh()
    opt = hvd_mod.DistributedOptimizer(
        optax.sgd(1.0),
        compression=Compression.int8,
        op=hvd_mod.Average,
        gradient_predivide_factor=2.0,
    )
    g = jnp.asarray(
        np.stack([np.full(64, float(r + 1), np.float32) for r in range(8)])
    )
    params = {"w": jnp.zeros(64, jnp.float32)}
    state = opt.init(params)

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(), P(), P(hvd_mod.WORLD_AXIS)),
        out_specs=P(),
        check_vma=False,
    )
    def step(p, s, grads):
        updates, _ = opt.update({"w": grads[0]}, s, p)
        return updates["w"]

    upd = np.asarray(jax.jit(step)(params, state, g))
    # average of 1..8 = 4.5; sgd(1.0) update = -reduced
    assert np.abs(upd + 4.5).max() <= 2 * 8 / 127.0 + 1e-3


# ------------------------------------------- satellite: seed threading


@pytest.mark.filterwarnings("ignore:hvd.value_and_grad")
def test_value_and_grad_auto_threads_step_counter(hvd):
    """Two eager calls without hvd_step= must produce DIFFERENT
    stochastic-rounding patterns (the internal counter advanced).
    shard_map re-traces per call, so the auto counter genuinely
    advances here (the tracer warning is the jit heads-up; ignored)."""
    mesh = hvd_mod.mesh()
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(8, 257)).astype(np.float32))

    vg = hvd_mod.value_and_grad(
        lambda t: jnp.sum(t * t) / 2, compression=Compression.int8,
        op=hvd_mod.Sum,
    )

    def run():
        @partial(
            jax.shard_map, mesh=mesh, in_specs=P(hvd_mod.WORLD_AXIS),
            out_specs=P(hvd_mod.WORLD_AXIS), check_vma=False,
        )
        def body(t):
            _, g = vg(t[0])
            return g[None]

        return np.asarray(body(x))

    a, b = run(), run()
    assert not np.array_equal(a, b)  # different rounding pattern


def test_value_and_grad_warns_once_on_constant_seed(hvd):
    import warnings

    mesh = hvd_mod.mesh()
    x = jnp.asarray(np.ones((8, 130), np.float32))
    vg = hvd_mod.value_and_grad(
        lambda t: jnp.sum(t * t), compression=Compression.int8,
        op=hvd_mod.Sum,
    )

    @partial(
        jax.shard_map, mesh=mesh, in_specs=P(hvd_mod.WORLD_AXIS),
        out_specs=P(hvd_mod.WORLD_AXIS), check_vma=False,
    )
    def body(t):
        _, g = vg(t[0], hvd_step=7)
        return g[None]

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        body(x)
        body(x)  # same constant seed again -> warn
        body(x)  # warned already -> silent
    msgs = [str(x.message) for x in w if "hvd_step" in str(x.message)]
    assert len(msgs) == 1


def test_value_and_grad_warns_under_jit_without_step(hvd):
    import warnings

    mesh = hvd_mod.mesh()
    x = jnp.asarray(np.ones((8, 130), np.float32))
    vg = hvd_mod.value_and_grad(
        lambda t: jnp.sum(t * t), compression=Compression.int8,
        op=hvd_mod.Sum,
    )

    @jax.jit
    @partial(
        jax.shard_map, mesh=mesh, in_specs=P(hvd_mod.WORLD_AXIS),
        out_specs=P(hvd_mod.WORLD_AXIS), check_vma=False,
    )
    def body(t):
        _, g = vg(t[0])  # traced, no hvd_step: pattern would freeze
        return g[None]

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        body(x)
    assert any("constant-folds" in str(x.message) for x in w)


def test_value_and_grad_warns_under_jit_with_pytree_args(hvd):
    """Tracers hiding inside dict args (the params-pytree case) must
    still trigger the frozen-seed warning."""
    import warnings

    mesh = hvd_mod.mesh()
    x = jnp.asarray(np.ones((8, 130), np.float32))
    vg = hvd_mod.value_and_grad(
        lambda d: jnp.sum(d["t"] * d["t"]), compression=Compression.int8,
        op=hvd_mod.Sum,
    )

    @jax.jit
    @partial(
        jax.shard_map, mesh=mesh, in_specs=P(hvd_mod.WORLD_AXIS),
        out_specs=P(hvd_mod.WORLD_AXIS), check_vma=False,
    )
    def body(t):
        _, g = vg({"t": t[0]})  # tracer is a dict LEAF, not an arg
        return g["t"][None]

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        body(x)
    assert any("constant-folds" in str(x.message) for x in w)
