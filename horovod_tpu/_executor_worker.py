"""Per-rank entry point for :mod:`horovod_tpu.executor` jobs.

A separate module (not imported by the package __init__) so running it
with ``python -m`` doesn't re-execute an already-imported module — the
pickled function must unpickle against the one true copy of its module.
"""

from __future__ import annotations

import os
import pickle
import sys


def main() -> None:
    payload_path = sys.argv[1]
    out_dir = os.environ["HOROVOD_EXECUTOR_OUT"]
    rank = os.environ.get("HOROVOD_RANK", "0")
    # `or None`: plain Executor jobs override an inherited elastic
    # epoch with "" (nested Executor.run inside an elastic worker must
    # collect from the flat out_dir it owns)
    epoch = os.environ.get("HOROVOD_ELASTIC_EPOCH") or None
    if epoch is not None:
        # Elastic gangs restart into the same HOROVOD_EXECUTOR_OUT; a
        # per-epoch subdirectory keeps a shrunken final gang from
        # reading a larger earlier epoch's stale results.
        out_dir = os.path.join(out_dir, f"epoch.{epoch}")
        os.makedirs(out_dir, exist_ok=True)
    try:
        # Inside the reporting block: unpickle failure (e.g. a payload
        # cloudpickled by value on a driver whose cloudpickle the worker
        # host lacks) must surface as an ('error', ...) result, not as
        # a missing result file the driver reports as 'produced no
        # result'.
        with open(payload_path, "rb") as f:
            fn, args, kwargs = pickle.load(f)
        value = fn(*args, **kwargs)
        result = ("ok", value)
    except BaseException as exc:  # report, don't swallow
        result = ("error", f"{type(exc).__name__}: {exc}")
    tmp = os.path.join(out_dir, f".result.{rank}.tmp")
    with open(tmp, "wb") as f:
        pickle.dump(result, f)
    os.replace(tmp, os.path.join(out_dir, f"result.{rank}.pkl"))
    if result[0] == "error":
        sys.exit(1)


if __name__ == "__main__":
    main()
