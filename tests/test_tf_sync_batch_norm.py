"""TF SyncBatchNormalization equivalence tests (ref:
horovod/tensorflow/sync_batch_norm.py [V]): with every rank seeing the
same replicated batch, global stats == local stats, so forward, input
grads, parameter grads, and moving stats must match plain
keras BatchNormalization — the reference's own equivalence contract
(mirrors tests/test_torch_shim.py::test_sync_batch_norm_matches_local_bn).
"""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

import horovod_tpu.tensorflow as hvd_tf  # noqa: E402


@pytest.fixture
def hvd_mesh(hvd):
    """JAX-side fixture brings the mesh up; the TF shim shares it."""
    hvd_tf.init()
    return hvd_tf


def test_training_matches_plain_bn(hvd_mesh):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(4, 5, 5, 3)).astype(np.float32)

    sbn = hvd_tf.SyncBatchNormalization(momentum=0.9, epsilon=1e-3)
    bn = tf.keras.layers.BatchNormalization(momentum=0.9, epsilon=1e-3)
    sbn.build(x.shape)
    bn.build(x.shape)

    xa = tf.constant(x)
    with tf.GradientTape(persistent=True) as tape:
        tape.watch(xa)
        ya = sbn(xa, training=True)
        la = tf.reduce_sum(ya * ya)
    with tf.GradientTape(persistent=True) as tape_b:
        tape_b.watch(xa)
        yb = bn(xa, training=True)
        lb = tf.reduce_sum(yb * yb)

    np.testing.assert_allclose(ya.numpy(), yb.numpy(), rtol=1e-4, atol=1e-5)
    # input grads via the exact synced backward
    np.testing.assert_allclose(
        tape.gradient(la, xa).numpy(),
        tape_b.gradient(lb, xa).numpy(),
        rtol=1e-3, atol=1e-4,
    )
    # parameter grads stay local
    np.testing.assert_allclose(
        tape.gradient(la, sbn.gamma).numpy(),
        tape_b.gradient(lb, bn.gamma).numpy(),
        rtol=1e-3, atol=1e-4,
    )
    np.testing.assert_allclose(
        tape.gradient(la, sbn.beta).numpy(),
        tape_b.gradient(lb, bn.beta).numpy(),
        rtol=1e-3, atol=1e-4,
    )
    # Keras moving-average semantics match (biased variance, decay m)
    np.testing.assert_allclose(
        sbn.moving_mean.numpy(), bn.moving_mean.numpy(),
        rtol=1e-4, atol=1e-6,
    )
    np.testing.assert_allclose(
        sbn.moving_variance.numpy(), bn.moving_variance.numpy(),
        rtol=1e-3, atol=1e-5,
    )


def test_eval_uses_moving_stats(hvd_mesh):
    sbn = hvd_tf.SyncBatchNormalization(epsilon=1e-5)
    sbn.build((2, 2))
    sbn.moving_mean.assign(tf.constant([1.0, -1.0]))
    sbn.moving_variance.assign(tf.constant([4.0, 0.25]))
    x = tf.ones((3, 2))
    out = sbn(x, training=False).numpy()
    expected = np.stack(
        [np.full(3, (1.0 - 1.0) / np.sqrt(4.0 + 1e-5)),
         np.full(3, (1.0 + 1.0) / np.sqrt(0.25 + 1e-5))], axis=1
    )
    np.testing.assert_allclose(out, expected, rtol=1e-5)


def test_inside_tf_function_and_fit(hvd_mesh):
    """The host-bridge allreduce must work under tf.function — i.e. in
    a compiled model.fit loop (py_function routing)."""
    rng = np.random.default_rng(1)
    x = rng.normal(size=(32, 4)).astype(np.float32)
    w = rng.normal(size=(4, 1)).astype(np.float32)
    y = (x @ w).astype(np.float32)

    model = tf.keras.Sequential(
        [
            tf.keras.layers.Dense(8),
            hvd_tf.SyncBatchNormalization(momentum=0.9),
            tf.keras.layers.Dense(1),
        ]
    )
    model.compile(optimizer=tf.keras.optimizers.SGD(0.05), loss="mse")
    hist = model.fit(x, y, epochs=5, batch_size=16, verbose=0)
    losses = hist.history["loss"]
    assert losses[-1] < losses[0]
    # moving stats moved off their init values during training
    sbn = model.layers[1]
    assert not np.allclose(sbn.moving_mean.numpy(), 0.0)
    # and predict (training=False) runs the moving-stats path
    preds = model.predict(x[:4], verbose=0)
    assert preds.shape == (4, 1)


def test_scale_center_off(hvd_mesh):
    """center=False/scale=False still trains (identity coefficients)."""
    rng = np.random.default_rng(2)
    x = rng.normal(size=(8, 3)).astype(np.float32)
    sbn = hvd_tf.SyncBatchNormalization(center=False, scale=False)
    sbn.build(x.shape)
    xa = tf.constant(x)
    with tf.GradientTape() as tape:
        tape.watch(xa)
        out = sbn(xa, training=True)
        loss = tf.reduce_sum(out * out)
    g = tape.gradient(loss, xa)
    assert g is not None and np.isfinite(g.numpy()).all()
    # normalized output: per-channel mean ~0, var ~1
    np.testing.assert_allclose(
        out.numpy().mean(0), np.zeros(3), atol=1e-5
    )


def test_symbolic_training_flag_in_graph(hvd_mesh):
    """Legacy Keras paths pass a symbolic `training` tensor inside
    tf.function; `not training` would raise
    OperatorNotAllowedInGraphError (ADVICE r3). Both branch values must
    match the corresponding python-bool calls."""
    rng = np.random.default_rng(3)
    x = rng.normal(size=(4, 6, 3)).astype(np.float32)
    sbn = hvd_tf.SyncBatchNormalization(momentum=0.9)
    sbn.build(x.shape)
    xa = tf.constant(x)

    # seed the moving stats so train/infer outputs differ
    sbn(xa, training=True)

    @tf.function
    def run(flag):
        return sbn(xa, training=flag)

    got_infer = run(tf.constant(False))
    want_infer = sbn(xa, training=False)
    np.testing.assert_allclose(
        got_infer.numpy(), want_infer.numpy(), rtol=1e-5, atol=1e-5
    )

    moving_before = sbn.moving_mean.numpy().copy()
    got_train = run(tf.constant(True))
    # the symbolic-True branch must behave as training: batch stats
    # normalize the output and the moving average advances
    assert not np.allclose(got_train.numpy(), want_infer.numpy())
    assert not np.allclose(sbn.moving_mean.numpy(), moving_before)
