"""Persistent compiled-executable store (``HOROVOD_EXE_CACHE``).

Every gang restart and every serve scale-up used to pay full
recompilation of the executor caches — the gap between the elastic
plane's "self-healing" and healing *fast* (ROADMAP item 5). The
executables are already held by exact key (the PR 1 fusion two-tier
cache, the serving engine's AOT prefill/decode tables), so the repo
knows precisely what to persist: this module gives those tables a disk
tier with the same contract the tuner cache established in PR 12 —
best-effort, tmp+rename writes, corrupt or version-mismatched entries
read as a counted cold start, never an error.

Entry key anatomy (also docs/elastic.md):

* **topology fingerprint** ``w<world>-l<intra>-<platform>`` (shared
  with :func:`..common.autotune.topology_fingerprint`) — an executable
  compiled for an 8-world mesh must never load into a 6-world one; the
  elastic 8→6 reshard warm-starts from the 6-world entries captured in
  prior epochs precisely because they live under a different prefix.
* **HLO fingerprint** — sha256 of the lowered program's StableHLO
  text. This is the semantic key: model weights' *shapes*, the wire
  recipe, sharding, and the jit options all land in the lowered text,
  so any drift misses cleanly instead of loading a wrong program.
* **wire format** — the resolved wire string for collective
  executables (``fp32``/``int8``/``bf16``/``intra/inter``); ``none``
  for serving programs. Redundant with the HLO text, kept explicit so
  operators can read a cache directory listing.
* **donation signature** — ``d<argnums>`` of the donated buffers. Two
  programs with identical HLO but different donation would alias
  differently; they must not share an entry.

On top of the key, the header pins ``jax``/``jaxlib`` versions and the
platform: a deserialized executable is only ever loaded into the exact
software it was serialized from. Anything else — torn file, flipped
bit (chaos site ``exe_cache.load``), version skew — degrades to a
counted cold compile (``exe_cache.corrupt`` / ``exe_cache.rejected``).

File format, one entry per file::

    HVDEXE1\\n | u32 header_len | header JSON | pickled
    (payload, in_tree, out_tree) from
    jax.experimental.serialize_executable.serialize

Writes ride a background writer thread (serialization happens on the
caller, only the file I/O is deferred) and are flushed by
``preemption`` drain hooks and atexit — persist-on-drain, so a
SIGTERM'd worker leaves its compiles behind for the standby that
replaces it.

Telemetry: ``exe_cache.{hits,misses,corrupt,rejected,stores,bytes,
deserialize_ms}`` ride the counter plane (StepStats deltas +
``/metrics``).
"""

from __future__ import annotations

import atexit
import hashlib
import json
import os
import pickle
import queue
import struct
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from .logging import get_logger
from .metrics import registry as _metrics

_log = get_logger("exe_cache")

FORMAT_VERSION = 1
MAGIC = b"HVDEXE1\n"
_SUFFIX = ".hvdexe"

# --------------------------------------------------------------------- keys


def cache_dir(base: Optional[str] = None) -> Optional[str]:
    """The resolved cache directory, or None when the disk tier is off
    (no ``HOROVOD_EXE_CACHE`` and no explicit base) — every caller
    gates on this so the no-cache path stays byte-identical to the
    pre-disk-tier engine."""
    if base:
        return base
    return os.environ.get("HOROVOD_EXE_CACHE") or None


def topology_fingerprint() -> str:
    """``w<world>-l<intra>-<platform>`` — the same namespace the tuner
    cache uses (one fleet, one fingerprint vocabulary)."""
    from .autotune import topology_fingerprint as _fp

    return _fp()


def hlo_fingerprint(lowered_or_text) -> str:
    """sha256 of the lowered program text (``jax.stages.Lowered`` or a
    pre-rendered string)."""
    text = (
        lowered_or_text
        if isinstance(lowered_or_text, str)
        else lowered_or_text.as_text()
    )
    return hashlib.sha256(text.encode()).hexdigest()


def donation_signature(donate_argnums) -> str:
    """``d<i>.<j>`` for donated argument indices; ``none`` without
    donation."""
    nums = tuple(int(i) for i in (donate_argnums or ()))
    return "d" + ".".join(str(i) for i in nums) if nums else "none"


def _entry_hash(hlo_fp: str, wire: str, donation: str) -> str:
    return hashlib.sha256(
        f"{hlo_fp}|{wire}|{donation}".encode()
    ).hexdigest()[:24]


def entry_path(
    family: str,
    hlo_fp: str,
    wire: str = "none",
    donation: str = "none",
    fingerprint: Optional[str] = None,
    base: Optional[str] = None,
) -> Optional[str]:
    """The entry file for one executable key, or None when the disk
    tier is off."""
    root = cache_dir(base)
    if not root:
        return None
    if fingerprint is None:
        fingerprint = topology_fingerprint()
    name = (
        f"{family.replace('/', '_')}-{fingerprint}"
        f"-{_entry_hash(hlo_fp, wire, donation)}{_SUFFIX}"
    )
    return os.path.join(root, name)


def _software() -> Dict[str, str]:
    import jax
    import jaxlib

    try:
        platform = jax.devices()[0].platform
    except Exception:
        platform = "unknown"
    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "platform": platform,
    }


# ----------------------------------------------------------- write side


class _Writer:
    """Background entry writer: serialization already happened on the
    caller; this thread only owns the tmp+rename file I/O, so a slow
    disk never blocks a decode step. ``flush`` drains it — registered
    as a preemption drain hook and at exit (persist-on-drain)."""

    def __init__(self) -> None:
        self._q: "queue.Queue" = queue.Queue()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    def _ensure_thread(self) -> None:
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, daemon=True, name="exe-cache-writer"
                )
                self._thread.start()

    def _run(self) -> None:
        while True:
            path, blob = self._q.get()
            try:
                _write_atomic(path, blob)
            except OSError as e:  # best-effort by contract
                _metrics.counter("exe_cache.store_errors")
                _log.warning("exe cache write failed for %s: %s", path, e)
            finally:
                self._q.task_done()

    def submit(self, path: str, blob: bytes) -> None:
        self._ensure_thread()
        self._q.put((path, blob))

    def flush(self, timeout: Optional[float] = None) -> bool:
        """Drain queued writes; True when the queue emptied in time."""
        if self._thread is None:
            return True
        deadline = None if timeout is None else time.monotonic() + timeout
        while self._q.unfinished_tasks:
            if deadline is not None and time.monotonic() > deadline:
                return False
            time.sleep(0.005)
        return True


_writer = _Writer()
_drain_registered = False
_drain_lock = threading.Lock()


def flush(timeout: Optional[float] = None) -> bool:
    """Drain pending entry writes (drain hooks, tests)."""
    return _writer.flush(timeout)


def _register_drain() -> None:
    """Lazy one-shot: writes survive SIGTERM (preemption drain) and
    normal exit."""
    global _drain_registered
    with _drain_lock:
        if _drain_registered:
            return
        _drain_registered = True
    atexit.register(flush, 5.0)
    try:
        from .. import preemption

        preemption.register_drain(lambda: flush(5.0))
    except Exception:  # pragma: no cover — import-order edge
        pass


def _write_atomic(path: str, blob: bytes) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    fd, tmp = tempfile.mkstemp(
        dir=os.path.dirname(path), prefix=".tmp-", suffix=_SUFFIX
    )
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def store(
    compiled,
    family: str,
    hlo_fp: str,
    wire: str = "none",
    donation: str = "none",
    meta: Optional[Dict[str, Any]] = None,
    fingerprint: Optional[str] = None,
    base: Optional[str] = None,
    sync: bool = False,
) -> Optional[str]:
    """Serialize ``compiled`` and persist it under its key. Returns
    the entry path, or None when the disk tier is off or serialization
    is unsupported on this backend. Never raises — persistence must
    never take a serving loop down."""
    path = entry_path(family, hlo_fp, wire, donation, fingerprint, base)
    if path is None:
        return None
    try:
        from jax.experimental import serialize_executable as _se

        payload, in_tree, out_tree = _se.serialize(compiled)
        body = pickle.dumps(
            (payload, in_tree, out_tree), protocol=pickle.HIGHEST_PROTOCOL
        )
    except Exception as e:
        _metrics.counter("exe_cache.serialize_errors")
        _log.warning("exe cache serialize failed (%s): %s", family, e)
        return None
    header = dict(_software())
    header.update(
        format=FORMAT_VERSION,
        family=family,
        topology=fingerprint or topology_fingerprint(),
        hlo=hlo_fp,
        wire=wire,
        donation=donation,
        meta=dict(meta or {}),
        payload_sha256=hashlib.sha256(body).hexdigest(),
        payload_bytes=len(body),
    )
    hdr = json.dumps(header, sort_keys=True).encode()
    blob = MAGIC + struct.pack(">I", len(hdr)) + hdr + body
    _metrics.counter("exe_cache.stores")
    _register_drain()
    if sync:
        try:
            _write_atomic(path, blob)
        except OSError as e:
            _metrics.counter("exe_cache.store_errors")
            _log.warning("exe cache write failed for %s: %s", path, e)
            return None
    else:
        _writer.submit(path, blob)
    return path


# ------------------------------------------------------------ read side


def _read_header(blob: bytes) -> Tuple[Dict[str, Any], bytes]:
    if not blob.startswith(MAGIC):
        raise ValueError("bad magic")
    off = len(MAGIC)
    (hlen,) = struct.unpack(">I", blob[off:off + 4])
    off += 4
    header = json.loads(blob[off:off + hlen].decode())
    return header, blob[off + hlen:]


def _header_mismatch(
    header: Dict[str, Any],
    hlo_fp: str,
    wire: str,
    donation: str,
    fingerprint: str,
) -> Optional[str]:
    """The invalidation rules: every pinned field must match the
    reader exactly. Returns the first offending field, or None."""
    want = dict(_software())
    want.update(
        format=FORMAT_VERSION,
        topology=fingerprint,
        hlo=hlo_fp,
        wire=wire,
        donation=donation,
    )
    for field, expect in want.items():
        if header.get(field) != expect:
            return field
    return None


def load(
    family: str,
    hlo_fp: str,
    wire: str = "none",
    donation: str = "none",
    fingerprint: Optional[str] = None,
    base: Optional[str] = None,
):
    """Load one executable by key; None on any miss. Misses are always
    safe: absent file (``exe_cache.misses``), torn/bitflipped payload
    (``exe_cache.corrupt``), or a header that fails the invalidation
    rules — wrong JAX/jaxlib version, platform, topology, wire, or
    donation signature (``exe_cache.rejected``; the payload is never
    deserialized into a mismatched runtime). Hits count bytes and
    deserialize wall-ms."""
    if fingerprint is None:
        fingerprint = topology_fingerprint()
    path = entry_path(family, hlo_fp, wire, donation, fingerprint, base)
    if path is None:
        return None
    from ..testing import chaos as _chaos

    try:
        with open(path, "rb") as f:
            blob = f.read()
    except OSError:
        _metrics.counter("exe_cache.misses")
        return None
    # the chaos site: ``bitflip`` corrupts the just-read payload (the
    # caller-owns-the-corruption DATA contract), ``delay`` stalls the
    # deserialization inside fire()
    if _chaos.inject("exe_cache.load") == "bitflip":
        flip = len(blob) - 1  # payload tail: past magic + header
        blob = blob[:flip] + bytes([blob[flip] ^ 0x40]) + blob[flip:][1:]
    t0 = time.monotonic()
    try:
        header, body = _read_header(blob)
    except (ValueError, struct.error, UnicodeDecodeError):
        _metrics.counter("exe_cache.corrupt")
        _log.warning("exe cache entry %s is corrupt (header)", path)
        return None
    bad = _header_mismatch(header, hlo_fp, wire, donation, fingerprint)
    if bad is not None:
        _metrics.counter("exe_cache.rejected")
        _log.warning(
            "exe cache entry %s rejected: %s mismatch (%r != reader)",
            path, bad, header.get(bad),
        )
        return None
    if (
        hashlib.sha256(body).hexdigest() != header.get("payload_sha256")
        or len(body) != header.get("payload_bytes")
    ):
        _metrics.counter("exe_cache.corrupt")
        _log.warning("exe cache entry %s is corrupt (payload)", path)
        return None
    try:
        from jax.experimental import serialize_executable as _se

        payload, in_tree, out_tree = pickle.loads(body)
        exe = _se.deserialize_and_load(payload, in_tree, out_tree)
    except Exception as e:
        _metrics.counter("exe_cache.corrupt")
        _log.warning("exe cache entry %s failed to deserialize: %s",
                     path, e)
        return None
    _metrics.counter("exe_cache.hits")
    _metrics.counter("exe_cache.bytes", len(blob))
    _metrics.counter(
        "exe_cache.deserialize_ms",
        max((time.monotonic() - t0) * 1e3, 0.0),
    )
    return exe


def get_or_compile(
    lowered,
    family: str,
    wire: str = "none",
    donation: str = "none",
    meta: Optional[Dict[str, Any]] = None,
    fingerprint: Optional[str] = None,
    base: Optional[str] = None,
):
    """The one-call disk tier: try the entry for ``lowered``'s key,
    else ``.compile()`` and persist. Returns ``(exe, hit)``; counts a
    miss only when the file could have existed (disk tier on)."""
    fp = hlo_fingerprint(lowered)
    exe = load(family, fp, wire, donation, fingerprint, base)
    if exe is not None:
        return exe, True
    exe = lowered.compile()
    store(exe, family, fp, wire, donation, meta, fingerprint, base)
    return exe, False


def scan(
    family: Optional[str] = None,
    fingerprint: Optional[str] = None,
    base: Optional[str] = None,
) -> List[Dict[str, Any]]:
    """Headers (never payloads) of every readable entry for this
    topology — the warm-start enumeration: an engine cannot know which
    prompt widths past runs promoted, so it scans its family, re-lowers
    each candidate from the entry's ``meta``, and loads by exact key
    (the fingerprint match happens in :func:`load`). Unreadable files
    are skipped, not raised."""
    root = cache_dir(base)
    if not root or not os.path.isdir(root):
        return []
    if fingerprint is None:
        fingerprint = topology_fingerprint()
    out = []
    for name in sorted(os.listdir(root)):
        if not name.endswith(_SUFFIX) or name.startswith(".tmp-"):
            continue
        if fingerprint not in name:
            continue
        if family is not None and not name.startswith(
            family.replace("/", "_") + "-"
        ):
            continue
        path = os.path.join(root, name)
        try:
            with open(path, "rb") as f:
                head = f.read(len(MAGIC) + 4)
                if not head.startswith(MAGIC):
                    continue
                (hlen,) = struct.unpack(">I", head[len(MAGIC):])
                header = json.loads(f.read(hlen).decode())
        except (OSError, ValueError, struct.error, UnicodeDecodeError):
            continue
        if header.get("topology") != fingerprint:
            continue
        if family is not None and header.get("family") != family:
            continue
        header["path"] = path
        out.append(header)
    return out


def preload(
    family: Optional[str] = None,
    fingerprint: Optional[str] = None,
    base: Optional[str] = None,
    limit: Optional[int] = None,
) -> Tuple[int, int]:
    """Deserialize every readable entry for this topology — the warm-
    standby staging step: a standby host pays the deserialization (and
    page-cache fault-in) cost BEFORE it is swapped into a gang, so the
    swap-in itself starts with validated, warm entries. Returns
    ``(loaded, bytes)``; corrupt/mismatched entries count through the
    usual :func:`load` counters and are skipped, never raised."""
    if fingerprint is None:
        fingerprint = topology_fingerprint()
    loaded = total = 0
    for header in scan(family, fingerprint, base):
        if limit is not None and loaded >= limit:
            break
        exe = load(
            header.get("family", ""),
            header.get("hlo", ""),
            header.get("wire", "none"),
            header.get("donation", "none"),
            fingerprint,
            base,
        )
        if exe is not None:
            loaded += 1
            total += int(header.get("payload_bytes", 0))
    return loaded, total


# ------------------------------------------- schedule-decision sidecars
#
# The overlap/ZeRO schedule caches persist their partition decisions
# BESIDE the executables: a restarted worker re-derives the same
# buckets from the same inputs today, but the sidecar makes the
# decision durable against heuristic drift (a code change reads the
# recorded partition and its exe-cache entries still hit) and gives
# operators the partition that produced each persisted executable.


def sidecar_path(
    name: str,
    fingerprint: Optional[str] = None,
    base: Optional[str] = None,
) -> Optional[str]:
    root = cache_dir(base)
    if not root:
        return None
    if fingerprint is None:
        fingerprint = topology_fingerprint()
    return os.path.join(root, f"{name}-{fingerprint}.json")


def load_json(
    name: str,
    fingerprint: Optional[str] = None,
    base: Optional[str] = None,
) -> Dict[str, Any]:
    """Best-effort sidecar read: {} when off, absent, or corrupt."""
    path = sidecar_path(name, fingerprint, base)
    if not path or not os.path.exists(path):
        return {}
    try:
        with open(path) as f:
            obj = json.load(f)
    except (OSError, ValueError):
        _metrics.counter("exe_cache.corrupt")
        return {}
    return obj if isinstance(obj, dict) else {}


def persist_json(
    name: str,
    entries: Dict[str, Any],
    fingerprint: Optional[str] = None,
    base: Optional[str] = None,
) -> Optional[str]:
    """Merge-and-write a sidecar (own entries win, disk's other keys
    survive — the tuner-cache merge contract), tmp+rename."""
    path = sidecar_path(name, fingerprint, base)
    if not path:
        return None
    merged = dict(load_json(name, fingerprint, base))
    merged.update(entries)
    try:
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fd, tmp = tempfile.mkstemp(
            dir=os.path.dirname(path), prefix=".tmp-", suffix=".json"
        )
        with os.fdopen(fd, "w") as f:
            json.dump(merged, f, sort_keys=True)
        os.replace(tmp, path)
    except OSError as e:
        _metrics.counter("exe_cache.store_errors")
        _log.warning("exe cache sidecar write failed for %s: %s", path, e)
        return None
    return path
