"""Local-SGD mode (PR 14, horovod_tpu/local_sgd.py + optimizer knobs):

* grouped hierarchical Adasum (the sync-round combiner) vs the host
  VHDD oracle, scale invariance, the non-power-of-two slice-count
  excess path, and int8-wire replica consistency;
* K=1 bit-parity with the existing path; K>1 within-slice replication,
  cross-slice divergence, and consensus reconciliation for BOTH
  optimizers;
* the tentpole structural invariant: lowered local-phase step programs
  carry ZERO inter-slice replica groups (the hloaudit
  ReplicaGroupStructure rule, asserted on real lowered modules);
* EF-residual chaining across rounds (bit-exact conservation at the
  pre-quantization point);
* the ``"local"`` layout family's 8→6 reshard migration;
* chaos: a DCN fault mid-sync-round defers the round (zero gang
  restarts — training continues on the ICI wire) and the counter
  ledger records it;
* elastic rejoin: a slice restored at the anchor re-syncs from the
  Adasum consensus, not from rank 0's parameters;
* the eager fused dispatcher's local-phase routing.
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

WORLD = 8


def _stages(world=WORLD, intra=4):
    from horovod_tpu.common.topology import hierarchical_stage_groups

    return hierarchical_stage_groups(world, intra)


def _rank_major(tree, world=WORLD):
    return jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(
            jnp.asarray(x)[None], (world,) + tuple(np.shape(jnp.asarray(x)))
        ),
        tree,
    )


def _strip(tree):
    return jax.tree_util.tree_map(lambda x: x[0], tree)


def _lift(tree):
    return jax.tree_util.tree_map(lambda x: x[None], tree)


INTRA_KINDS = (
    "all_reduce", "reduce_scatter", "all_gather", "all_to_all",
    "collective_permute",
)


def _assert_intra_only(graph, intra_groups):
    from horovod_tpu import analysis
    from horovod_tpu.analysis import rules

    intra = tuple(tuple(g) for g in intra_groups)
    for kind in INTRA_KINDS:
        analysis.expect(
            graph,
            rules.ReplicaGroupStructure(
                kind, groups_any_of=(intra,), forbid_world_spanning=True
            ),
        )


# ---------------------------------------------------------------------------
# grouped hierarchical Adasum (the sync-round combiner)
# ---------------------------------------------------------------------------


class TestGroupedAdasum:
    def _run(self, hvd, slice_vals, intra, wire="fp32", seed=0,
             world=None):
        """Each slice's ranks hold the slice value (replicated);
        returns the merged output rows."""
        from horovod_tpu.ops.adasum import adasum_allreduce_groups

        world = world or WORLD
        stages = _stages(world, intra)
        mesh = hvd.mesh() if world == WORLD else None
        if mesh is None:
            from jax.sharding import Mesh

            mesh = Mesh(
                np.asarray(jax.devices()[:world]), ("hvd",)
            )
        rows = np.stack(
            [slice_vals[r // intra] for r in range(world)]
        ).astype(np.float32)

        @partial(
            jax.shard_map, mesh=mesh, in_specs=(P("hvd"),),
            out_specs=P("hvd"), check_vma=False,
        )
        def run(x):
            return adasum_allreduce_groups(
                x[0], axis_name="hvd", stages=stages, inter_wire=wire,
                seed=seed,
            )[None]

        return np.asarray(jax.jit(run)(jnp.asarray(rows)))

    def test_matches_host_oracle_fp32(self, hvd, rng):
        from horovod_tpu.ops.adasum import adasum_vhdd_host

        vals = [rng.normal(size=(97,)).astype(np.float32) for _ in range(2)]
        out = self._run(hvd, vals, intra=4)
        want = adasum_vhdd_host(vals)
        np.testing.assert_allclose(out[0], want, rtol=1e-5, atol=1e-6)
        # replicated result across every rank
        for r in range(WORLD):
            np.testing.assert_array_equal(out[r], out[0])

    def test_four_slices(self, hvd, rng):
        from horovod_tpu.ops.adasum import adasum_vhdd_host

        vals = [rng.normal(size=(64,)).astype(np.float32) for _ in range(4)]
        out = self._run(hvd, vals, intra=2)
        want = adasum_vhdd_host(vals)
        np.testing.assert_allclose(out[0], want, rtol=1e-5, atol=1e-6)

    def test_non_pow2_slice_count_excess_path(self, hvd, rng):
        """world=6, L=2 → H=3: the VHDD pre-reduction (excess) path."""
        from horovod_tpu.ops.adasum import adasum_vhdd_host

        vals = [rng.normal(size=(40,)).astype(np.float32) for _ in range(3)]
        out = self._run(hvd, vals, intra=2, world=6)
        want = adasum_vhdd_host(vals)
        np.testing.assert_allclose(out[0], want, rtol=1e-5, atol=1e-6)
        for r in range(6):
            np.testing.assert_array_equal(out[r], out[0])

    def test_scale_invariance(self, hvd, rng):
        """Adasum is invariant to rescaling any input — the property
        that makes it the right merge operator for deltas whose local
        learning rates (or local step counts) differ."""
        vals = [rng.normal(size=(64,)).astype(np.float32) for _ in range(2)]
        base = self._run(hvd, vals, intra=4)
        scaled = self._run(
            hvd, [vals[0] * 7.5, vals[1]], intra=4
        )
        # adasum(c·a, b) has the same direction structure; for the
        # 2-slice case adasum(a,b) with a scaled keeps b's projection
        # removal exact: compare against the host oracle directly
        from horovod_tpu.ops.adasum import adasum_vhdd_host

        want = adasum_vhdd_host([vals[0] * 7.5, vals[1]])
        np.testing.assert_allclose(scaled[0], want, rtol=1e-4, atol=1e-5)
        assert not np.allclose(scaled[0], base[0])

    def test_int8_wire_close_and_replica_consistent(self, hvd, rng):
        from horovod_tpu.ops.adasum import adasum_vhdd_host

        vals = [rng.normal(size=(512,)).astype(np.float32) for _ in range(2)]
        out = self._run(hvd, vals, intra=4, wire="int8", seed=3)
        want = adasum_vhdd_host(vals)
        scale = np.abs(want).max()
        assert np.abs(out[0] - want).max() < 0.05 * max(scale, 1e-3)
        for r in range(WORLD):
            # bitwise identical replicas under the lossy wire (the
            # owner-consumes-self-wire discipline)
            np.testing.assert_array_equal(out[r], out[0])


class TestGroupedQuantizedEF:
    def test_average_ef_steady_state_unbiased(self, hvd, rng):
        """The grouped int8 wire's EF carry under op=Average: the
        time-averaged output must converge to the true group average
        within a fraction of one quantum. Regression for the stage-2
        e2 over-correction (×L) that made EF a persistent bias on
        this path — the grouped recipe quantizes the SUM shard (the
        ÷L happens after), so its e2 must stay UN-scaled."""
        from horovod_tpu.ops import traced

        stages = _stages()  # L=4, two groups
        intra = stages[0]
        mesh = hvd.mesh()
        vals = rng.normal(size=(WORLD, 257)).astype(np.float32)
        truth = np.stack(
            [vals[(r // 4) * 4 : (r // 4) * 4 + 4].mean(axis=0)
             for r in range(WORLD)]
        )

        @partial(
            jax.shard_map, mesh=mesh,
            in_specs=(P("hvd"), P("hvd"), P()),
            out_specs=(P("hvd"), P("hvd")),
            check_vma=False,
        )
        def ar(xm, resm, seed):
            out, new_r = traced.quantized_allreduce(
                xm[0] + resm[0], op=hvd.Average, seed=seed,
                return_residual=True, groups=intra,
            )
            return out[None], new_r[None]

        run = jax.jit(ar)
        res = jnp.zeros_like(jnp.asarray(vals))
        errs = []
        for i in range(30):
            out, res = run(jnp.asarray(vals), res, jnp.int32(i))
            errs.append(np.asarray(out) - truth)
        per_round = np.abs(np.stack(errs)).max()
        bias = np.abs(np.mean(np.stack(errs[5:]), axis=0)).max()
        # EF keeps the walk unbiased: the time-mean error is far
        # below the per-round quantum (the ×L bug sat ~20x higher)
        assert bias < per_round / 4, (bias, per_round)
        assert bias < 3e-3, bias


# ---------------------------------------------------------------------------
# DistributedOptimizer local-SGD mode
# ---------------------------------------------------------------------------


def _make_opt_step(hvd, opt, mesh):
    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(hvd.WORLD_AXIS),) * 3,
        out_specs=(P(hvd.WORLD_AXIS), P(hvd.WORLD_AXIS)),
        check_vma=False,
    )
    def step(pm, sm, gm):
        p, s, g = _strip(pm), _strip(sm), _strip(gm)
        u, s = opt.update(g, s, p)
        p = optax.apply_updates(p, u)
        return _lift(p), _lift(s)

    return jax.jit(step)


def _make_sync_step(hvd, opt, mesh, method=None):
    sync = method if method is not None else opt.sync

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(hvd.WORLD_AXIS),) * 2,
        out_specs=(P(hvd.WORLD_AXIS), P(hvd.WORLD_AXIS)),
        check_vma=False,
    )
    def sync_step(pm, sm):
        p, s = _strip(pm), _strip(sm)
        p, s = sync(p, s)
        return _lift(p), _lift(s)

    return jax.jit(sync_step)


def _params(rng):
    return {
        "w": jnp.asarray(rng.normal(size=(24, 8)), jnp.float32),
        "b": jnp.asarray(rng.normal(size=(8,)), jnp.float32),
    }


def _grads(rng, world=WORLD):
    return {
        "w": jnp.asarray(rng.normal(size=(world, 24, 8)), jnp.float32),
        "b": jnp.asarray(rng.normal(size=(world, 8)), jnp.float32),
    }


class TestDistributedOptimizerLocalSGD:
    def test_k1_is_the_existing_path_bitwise(self, hvd, rng):
        """local_sgd_steps=1 IS the existing optimizer — identical
        transformation, bit-identical trajectory."""
        params = _params(rng)
        grads = _grads(rng)
        mesh = hvd.mesh()
        outs = []
        for kw in ({}, {"local_sgd_steps": 1}):
            opt = hvd.DistributedOptimizer(
                optax.adam(1e-2), op=hvd.Average, **kw
            )
            assert not isinstance(opt, hvd.LocalSGDGradientTransformation)
            step = _make_opt_step(hvd, opt, mesh)
            pm, sm = _rank_major(params), _rank_major(opt.init(params))
            for _ in range(3):
                pm, sm = step(pm, sm, grads)
            outs.append(np.asarray(pm["w"]))
        np.testing.assert_array_equal(outs[0], outs[1])

    def test_local_phase_diverges_and_sync_reconciles(self, hvd, rng):
        params = _params(rng)
        grads = _grads(rng)
        mesh = hvd.mesh()
        opt = hvd.DistributedOptimizer(
            optax.sgd(0.1), op=hvd.Average, local_sgd_steps=4,
            local_sgd_intra=4,
        )
        assert isinstance(opt, hvd.LocalSGDGradientTransformation)
        assert opt.local_sgd_steps == 4
        step = _make_opt_step(hvd, opt, mesh)
        pm, sm = _rank_major(params), _rank_major(opt.init(params))
        for _ in range(4):
            pm, sm = step(pm, sm, grads)
        w = np.asarray(pm["w"])
        np.testing.assert_array_equal(w[0], w[3])  # intra replicas
        assert not np.allclose(w[0], w[4])  # slices diverged
        sync = _make_sync_step(hvd, opt, mesh)
        pm2, sm2 = sync(pm, sm)
        w2 = np.asarray(pm2["w"])
        np.testing.assert_array_equal(w2[0], w2[7])  # world replicas
        # anchor re-based on the consensus
        anc = np.asarray(sm2.local_anchor["w"])
        np.testing.assert_array_equal(anc[0], w2[0])

    def test_sync_matches_host_adasum_of_deltas(self, hvd, rng):
        from horovod_tpu.ops.adasum import adasum_vhdd_host

        params = _params(rng)
        grads = _grads(rng)
        mesh = hvd.mesh()
        L = 4
        opt = hvd.DistributedOptimizer(
            optax.sgd(0.1), op=hvd.Average, local_sgd_steps=2,
            local_sgd_intra=L, local_sgd_inter_wire="fp32",
        )
        step = _make_opt_step(hvd, opt, mesh)
        pm0 = _rank_major(params)
        pm, sm = pm0, _rank_major(opt.init(params))
        for _ in range(2):
            pm, sm = step(pm, sm, grads)
        pm2, _ = _make_sync_step(hvd, opt, mesh)(pm, sm)
        deltas = []
        for h in range(WORLD // L):
            dw = np.asarray(pm["w"])[h * L] - np.asarray(pm0["w"])[0]
            db = np.asarray(pm["b"])[h * L] - np.asarray(pm0["b"])[0]
            deltas.append(
                np.concatenate([dw.reshape(-1), db.reshape(-1)])
            )
        merged = adasum_vhdd_host(deltas)
        want_w = (
            np.asarray(pm0["w"])[0].reshape(-1) + merged[: 24 * 8]
        )
        np.testing.assert_allclose(
            np.asarray(pm2["w"])[0].reshape(-1), want_w,
            rtol=1e-5, atol=1e-5,
        )

    def test_local_phase_program_has_zero_inter_groups(self, hvd, rng):
        """The tentpole structural invariant, on the real lowered
        module — bucketed AND monolithic paths."""
        from horovod_tpu import analysis

        params = _params(rng)
        grads = _grads(rng)
        mesh = hvd.mesh()
        stages = _stages()
        for buckets in (0, 3):
            opt = hvd.DistributedOptimizer(
                optax.sgd(0.1), op=hvd.Sum, local_sgd_steps=8,
                local_sgd_intra=4, overlap_buckets=buckets,
                overlap_min_bytes=0,
            )
            step = _make_opt_step(hvd, opt, mesh)
            pm, sm = _rank_major(params), _rank_major(opt.init(params))
            g = analysis.parse_module(step.lower(pm, sm, grads))
            _assert_intra_only(g, stages[0])
            assert g.count("all_reduce") >= 1

    def test_local_phase_program_int8_wire_intra_only(self, hvd, rng):
        """int8 local wire: the quantized exchange stays inside the
        slice too (every all_to_all / all_gather group-limited)."""
        from horovod_tpu import analysis

        params = _params(rng)
        grads = _grads(rng)
        mesh = hvd.mesh()
        opt = hvd.DistributedOptimizer(
            optax.sgd(0.1), op=hvd.Average, local_sgd_steps=8,
            local_sgd_intra=4, compression=hvd.Compression.int8_block,
            overlap_buckets=2, overlap_min_bytes=0,
        )
        step = _make_opt_step(hvd, opt, mesh)
        pm, sm = _rank_major(params), _rank_major(opt.init(params))
        g = analysis.parse_module(step.lower(pm, sm, grads))
        _assert_intra_only(g, _stages()[0])
        assert g.count("all_to_all") >= 1  # the quantized wire ran

    def test_ef_residual_chains_across_rounds(self, hvd, rng):
        """int8 inter wire EF: conservation at the pre-quantization
        point is bit-exact (quantized + residual' == delta + residual)
        and the carry actually lands in the next round's signal."""
        params = _params(rng)
        mesh = hvd.mesh()
        opt = hvd.DistributedOptimizer(
            optax.sgd(0.1), op=hvd.Average, local_sgd_steps=2,
            local_sgd_intra=4, local_sgd_inter_wire="int8",
        )
        step = _make_opt_step(hvd, opt, mesh)
        sync = _make_sync_step(hvd, opt, mesh)
        pm, sm = _rank_major(params), _rank_major(opt.init(params))
        res0 = np.asarray(sm.local_residual["w"])
        assert np.all(res0 == 0.0)
        grads = _grads(rng)
        for _ in range(2):
            pm, sm = step(pm, sm, grads)
        pm, sm = sync(pm, sm)
        res1 = np.asarray(sm.local_residual["w"])
        assert np.any(res1 != 0.0), "int8 wire must leave a carry"
        # replicated-consistent carry (gathered over intra)
        np.testing.assert_array_equal(res1[0], res1[3])
        # round 2 consumes the carry: running again from the same
        # params with a zeroed carry changes the merged result
        grads2 = _grads(rng)
        for _ in range(2):
            pm, sm = step(pm, sm, grads2)
        pm_a, sm_a = sync(pm, sm)
        sm_zero = sm._replace(
            local_residual=jax.tree_util.tree_map(
                jnp.zeros_like, sm.local_residual
            )
        )
        pm_b, _ = sync(pm, sm_zero)
        assert not np.array_equal(
            np.asarray(pm_a["w"]), np.asarray(pm_b["w"])
        ), "the EF carry must join the next round's wire signal"

    def test_rejects_bad_configs(self, hvd):
        with pytest.raises(ValueError, match="Sum/Average"):
            hvd.DistributedOptimizer(
                optax.sgd(0.1), op=hvd.Adasum, local_sgd_steps=4
            )
        with pytest.raises(ValueError, match="inter_wire"):
            hvd.DistributedOptimizer(
                optax.sgd(0.1), local_sgd_steps=4,
                local_sgd_inter_wire="fp8",
            )

    def test_env_default(self, hvd, monkeypatch):
        monkeypatch.setenv("HOROVOD_LOCAL_SGD_STEPS", "4")
        # the live config snapshots at init — re-init under the env
        hvd.shutdown()
        hvd.init()
        opt = hvd.DistributedOptimizer(optax.sgd(0.1))
        assert isinstance(opt, hvd.LocalSGDGradientTransformation)
        assert opt.local_sgd_steps == 4

    def test_unresolvable_split_raises(self, hvd, rng):
        """No intra override, single-slice CPU runtime: the local
        phase cannot exist and the trace says why."""
        opt = hvd.DistributedOptimizer(
            optax.sgd(0.1), local_sgd_steps=4
        )
        params = _params(rng)
        step = _make_opt_step(hvd, opt, hvd.mesh())
        with pytest.raises(ValueError, match="two-level topology"):
            step(
                _rank_major(params), _rank_major(opt.init(params)),
                _grads(rng),
            )


# ---------------------------------------------------------------------------
# ShardedDistributedOptimizer local-SGD mode
# ---------------------------------------------------------------------------


def _make_sharded_steps(hvd, opt, mesh):
    def loss(p, xb):
        return jnp.sum(jnp.tanh(xb @ p["w"]) * p["b"])

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(hvd.WORLD_AXIS), opt.state_spec(), P(hvd.WORLD_AXIS)),
        out_specs=(P(hvd.WORLD_AXIS), opt.state_spec()),
        check_vma=False,
    )
    def step(pm, s, xb):
        p = _strip(pm)
        _, g_sh = opt.value_and_grad(loss)(p, xb[0])
        u, s = opt.update(g_sh, s, p)
        return _lift(optax.apply_updates(p, u)), s

    @partial(
        jax.shard_map, mesh=mesh,
        in_specs=(P(hvd.WORLD_AXIS), opt.state_spec()),
        out_specs=(P(hvd.WORLD_AXIS), opt.state_spec()),
        check_vma=False,
    )
    def sync_step(pm, s):
        p, s = opt.sync_round(_strip(pm), s)
        return _lift(p), s

    return jax.jit(step), jax.jit(sync_step)


def _sharded_params(rng):
    return {
        "w": jnp.asarray(rng.normal(size=(12, 6)), jnp.float32),
        "b": jnp.asarray(rng.normal(size=(6,)), jnp.float32),
    }


class TestShardedLocalSGD:
    def test_stage2_local_phase_and_sync(self, hvd, rng):
        params = _sharded_params(rng)
        xs = jnp.asarray(rng.normal(size=(WORLD, 4, 12)), jnp.float32)
        mesh = hvd.mesh()
        opt = hvd.ShardedDistributedOptimizer(
            optax.adam(1e-2), op=hvd.Sum, zero_stage=2,
            overlap_buckets=2, overlap_min_bytes=0,
            local_sgd_steps=4, local_sgd_intra=4,
        )
        state = opt.init(params)
        assert "local" in state
        step, sync = _make_sharded_steps(hvd, opt, mesh)
        pm = _rank_major(params)
        for _ in range(4):
            pm, state = step(pm, state, xs)
        w = np.asarray(pm["w"])
        np.testing.assert_array_equal(w[0], w[3])
        assert not np.allclose(w[0], w[4])
        pm2, state2 = sync(pm, state)
        w2 = np.asarray(pm2["w"])
        np.testing.assert_array_equal(w2[0], w2[7])
        assert int(np.asarray(state2["local"]["round"])[0]) == 1

    def test_stage2_local_program_zero_inter_groups(self, hvd, rng):
        from horovod_tpu import analysis

        params = _sharded_params(rng)
        xs = jnp.asarray(rng.normal(size=(WORLD, 4, 12)), jnp.float32)
        mesh = hvd.mesh()
        for stage in (1, 2):
            opt = hvd.ShardedDistributedOptimizer(
                optax.adam(1e-2), op=hvd.Sum, zero_stage=stage,
                overlap_buckets=2, overlap_min_bytes=0,
                local_sgd_steps=4, local_sgd_intra=4,
            )
            state = opt.init(params)
            step, _ = _make_sharded_steps(hvd, opt, mesh)
            g = analysis.parse_module(
                step.lower(_rank_major(params), state, xs)
            )
            _assert_intra_only(g, _stages()[0])

    def test_stage3_rejected(self, hvd):
        with pytest.raises(NotImplementedError, match="zero_stage<=2"):
            hvd.ShardedDistributedOptimizer(
                optax.adam(1e-2), zero_stage=3, local_sgd_steps=4
            )

    def test_guard_agreement_is_intra_only(self, hvd, rng):
        """A NaN in one slice skips THAT slice's step; the other slice
        applies its update — slices are independent during the local
        phase, and the guard flag never crosses DCN."""
        params = _sharded_params(rng)
        mesh = hvd.mesh()
        opt = hvd.ShardedDistributedOptimizer(
            optax.sgd(0.1), op=hvd.Sum, zero_stage=1,
            overlap_buckets=0, grad_guard=True,
            local_sgd_steps=4, local_sgd_intra=4,
        )
        state = opt.init(params)

        @partial(
            jax.shard_map, mesh=mesh,
            in_specs=(
                P(hvd.WORLD_AXIS), opt.state_spec(), P(hvd.WORLD_AXIS),
            ),
            out_specs=(P(hvd.WORLD_AXIS), opt.state_spec()),
            check_vma=False,
        )
        def step(pm, s, gm):
            p, g = _strip(pm), _strip(gm)
            u, s = opt.update(g, s, p)
            return _lift(optax.apply_updates(p, u)), s

        grads = _grads(rng)

        def poisoned(g):
            arr = np.asarray(g["w"])
            arr = arr.copy()
            arr[0, 0, 0] = np.nan  # rank 0 → slice 0 only
            return {"w": jnp.asarray(arr), "b": g["b"]}

        gw = {
            "w": jnp.asarray(
                rng.normal(size=(WORLD, 12, 6)), jnp.float32
            ),
            "b": jnp.asarray(rng.normal(size=(WORLD, 6)), jnp.float32),
        }
        pm = _rank_major(params)
        pm2, state2 = jax.jit(step)(pm, state, poisoned(gw))
        w0 = np.asarray(pm["w"])[0]
        w2 = np.asarray(pm2["w"])
        np.testing.assert_array_equal(w2[0], w0)  # slice 0 skipped
        assert not np.allclose(w2[4], w0)  # slice 1 applied
        skips = np.asarray(state2["guard"]["skips"])
        assert skips[0] == 1 and skips[4] == 0

    def test_reshard_local_family_8_to_6(self, hvd, rng):
        """The "local" layout family migrates across a world change:
        anchor values bit-exact, width re-resolved, round carried."""
        params = _sharded_params(rng)
        xs = jnp.asarray(rng.normal(size=(WORLD, 4, 12)), jnp.float32)
        mesh = hvd.mesh()
        opt = hvd.ShardedDistributedOptimizer(
            optax.adam(1e-2), op=hvd.Sum, zero_stage=2,
            overlap_buckets=2, overlap_min_bytes=0,
            local_sgd_steps=4, local_sgd_intra=4,
        )
        state = opt.init(params)
        step, sync = _make_sharded_steps(hvd, opt, mesh)
        pm = _rank_major(params)
        for _ in range(4):
            pm, state = step(pm, state, xs)
        pm, state = sync(pm, state)
        L_old = int(np.asarray(state["local"]["intra"])[0])
        size = int(np.asarray(params["w"]).size)
        anc_full_old = np.concatenate(
            [
                np.asarray(state["local"]["anchor"]["w"])[i]
                for i in range(L_old)
            ]
        )[:size]
        params_host = {k: np.asarray(v)[0] for k, v in pm.items()}
        st6 = opt.reshard_state(state, params_host, 6)
        L_new = int(np.asarray(st6["local"]["intra"])[0])
        assert L_new == 2  # gcd(4, 6)
        anc_full_new = np.concatenate(
            [
                np.asarray(st6["local"]["anchor"]["w"])[i]
                for i in range(L_new)
            ]
        )[:size]
        np.testing.assert_array_equal(anc_full_old, anc_full_new)
        assert int(np.asarray(st6["local"]["round"])[0]) == 1
        assert np.asarray(st6["local"]["anchor"]["w"]).shape[0] == 6
        # downgrade: local turned off strips the family and re-cuts
        # the moments to the flat world split
        opt_flat = hvd.ShardedDistributedOptimizer(
            optax.adam(1e-2), op=hvd.Sum, zero_stage=2,
            overlap_buckets=2, overlap_min_bytes=0,
        )
        opt_flat._world = WORLD
        st_flat = opt_flat.reshard_state(state, params_host, 6)
        assert "local" not in st_flat or not isinstance(
            st_flat, dict
        ) or set(st_flat) == {"state"}

    def test_layout_mismatch_errors(self, hvd, rng):
        params = _sharded_params(rng)
        opt_local = hvd.ShardedDistributedOptimizer(
            optax.sgd(0.1), zero_stage=1, local_sgd_steps=4,
            local_sgd_intra=4,
        )
        opt_flat = hvd.ShardedDistributedOptimizer(
            optax.sgd(0.1), zero_stage=1
        )
        st_local = opt_local.init(params)
        st_flat = opt_flat.init(params)
        mesh = hvd.mesh()

        def run(opt, st):
            @partial(
                jax.shard_map, mesh=mesh,
                in_specs=(
                    P(hvd.WORLD_AXIS), opt.state_spec(),
                    P(hvd.WORLD_AXIS),
                ),
                out_specs=(P(), opt.state_spec()),
                check_vma=False,
            )
            def step(pm, s, gm):
                p, g = _strip(pm), _strip(gm)
                u, s = opt.update(g, s, p)
                return u, s

            gm = {
                "w": jnp.ones((WORLD, 12, 6)), "b": jnp.ones((WORLD, 6)),
            }
            return step(_rank_major(params), st, gm)

        with pytest.raises(ValueError, match='no "local" layout'):
            run(opt_local, st_flat)
        with pytest.raises(ValueError, match="local_sgd_steps <= 1"):
            run(opt_flat, st_local)


# ---------------------------------------------------------------------------
# round driver: cadence, chaos-defer, counters, rejoin
# ---------------------------------------------------------------------------


class TestRoundDriver:
    def test_due_cadence(self, hvd):
        from horovod_tpu import local_sgd

        assert [local_sgd.due(i, 4) for i in range(8)] == [
            False, False, False, True, False, False, False, True,
        ]
        assert not any(local_sgd.due(i, 1) for i in range(8))

    def test_round_inter_bytes_model(self, hvd):
        from horovod_tpu import local_sgd
        from horovod_tpu.ops.adasum import vhdd_wire_bytes

        stages = _stages()
        got = local_sgd.round_inter_bytes(1 << 20, stages, "int8")
        # 2^18 fp32 elems / L=4 = 2^16 shard elems at 1 byte/elem,
        # VHDD over H=2
        want = vhdd_wire_bytes(2, (1 << 16))
        assert got == want
        assert local_sgd.round_inter_bytes(
            1 << 20, stages, "fp32"
        ) == 4 * want

    def test_chaos_fault_defers_round_zero_restarts(self, hvd, rng):
        """The acceptance drill, in-process: a DCN fault mid-sync-round
        exhausts the retry ladder, the round DEFERS (counted), training
        continues on the ICI wire, and the NEXT round completes — zero
        gang restarts, no exception reaches the training loop."""
        from horovod_tpu import local_sgd
        from horovod_tpu.common.metrics import registry
        from horovod_tpu.common.retry import RetryPolicy
        from horovod_tpu.testing import chaos

        params = _params(rng)
        mesh = hvd.mesh()
        opt = hvd.DistributedOptimizer(
            optax.sgd(0.1), op=hvd.Average, local_sgd_steps=2,
            local_sgd_intra=4,
        )
        step = _make_opt_step(hvd, opt, mesh)
        sync = _make_sync_step(hvd, opt, mesh)
        pm, sm = _rank_major(params), _rank_major(opt.init(params))
        base = registry.snapshot()
        # two resets in a row beats attempts=2 → the round defers once
        chaos.configure("seed=7;local_sgd.sync@1:reset;local_sgd.sync@2:reset")
        policy = RetryPolicy.from_env(
            "local_sgd.sync", attempts=2, backoff_ms=1.0,
            circuit_threshold=0,
        )
        try:
            grads = _grads(rng)
            histories = []
            for i in range(4):
                pm, sm = step(pm, sm, grads)
                out, synced = local_sgd.maybe_sync(
                    sync, pm, sm, step=i, k=2, policy=policy,
                    payload_bytes=1 << 10, stages=_stages(),
                )
                if synced:
                    pm, sm = out
                histories.append(synced)
        finally:
            chaos.reset()
        assert histories == [False, False, False, True]
        snap = registry.snapshot()
        assert (
            snap.get("local_sgd.rounds_deferred", 0)
            - base.get("local_sgd.rounds_deferred", 0)
        ) == 1
        assert (
            snap.get("local_sgd.sync_rounds", 0)
            - base.get("local_sgd.sync_rounds", 0)
        ) == 1
        assert (
            snap.get("local_sgd.local_steps", 0)
            - base.get("local_sgd.local_steps", 0)
        ) == 4
        assert (
            snap.get("local_sgd.inter_bytes", 0)
            - base.get("local_sgd.inter_bytes", 0)
        ) > 0
        assert (
            snap.get("faults_injected", 0)
            - base.get("faults_injected", 0)
        ) == 2
        # params ended reconciled: the deferred round extended the
        # local phase, the next one completed the reconciliation
        w = np.asarray(pm["w"])
        np.testing.assert_array_equal(w[0], w[7])

    def test_single_fault_retries_round_whole(self, hvd, rng):
        """One transient fault < attempts: the round RETRIES and
        completes — no deferral at all."""
        from horovod_tpu import local_sgd
        from horovod_tpu.common.metrics import registry
        from horovod_tpu.common.retry import RetryPolicy
        from horovod_tpu.testing import chaos

        params = _params(rng)
        mesh = hvd.mesh()
        opt = hvd.DistributedOptimizer(
            optax.sgd(0.1), op=hvd.Average, local_sgd_steps=2,
            local_sgd_intra=4,
        )
        step = _make_opt_step(hvd, opt, mesh)
        sync = _make_sync_step(hvd, opt, mesh)
        pm, sm = _rank_major(params), _rank_major(opt.init(params))
        base = registry.snapshot()
        chaos.configure("seed=7;local_sgd.sync@1:timeout")
        policy = RetryPolicy.from_env(
            "local_sgd.sync", attempts=3, backoff_ms=1.0,
            circuit_threshold=0,
        )
        try:
            grads = _grads(rng)
            for i in range(2):
                pm, sm = step(pm, sm, grads)
            out, synced = local_sgd.run_round(sync, pm, sm, policy=policy)
        finally:
            chaos.reset()
        assert synced
        snap = registry.snapshot()
        assert (
            snap.get("local_sgd.rounds_deferred", 0)
            - base.get("local_sgd.rounds_deferred", 0)
        ) == 0

    def test_flight_recorder_carries_round_deltas(self, hvd):
        """StepStats records carry the local_sgd.* per-step deltas, so
        a postmortem pins a deferred round to its exact step."""
        from horovod_tpu.common.metrics import registry
        from horovod_tpu.common.telemetry import TelemetryHub

        hub = TelemetryHub(capacity=4)
        hub.step_begin(0)
        registry.counter("local_sgd.local_steps")
        registry.counter("local_sgd.rounds_deferred")
        hub.step_end()
        hub.step_begin(1)
        registry.counter("local_sgd.local_steps")
        registry.counter("local_sgd.sync_rounds")
        registry.counter("local_sgd.inter_bytes", 4096)
        hub.step_end()
        recs = hub.records()
        assert recs[-2]["local_sgd.rounds_deferred"] == 1.0
        assert recs[-2]["local_sgd.sync_rounds"] == 0.0
        assert recs[-1]["local_sgd.sync_rounds"] == 1.0
        assert recs[-1]["local_sgd.inter_bytes"] == 4096.0
        assert recs[-1]["local_sgd.rounds_deferred"] == 0.0

    def test_rejoin_syncs_from_consensus_not_root(self, hvd, rng):
        """Elastic rejoin: slice 0 'restored at the anchor' (zero
        delta — the newcomer), slice 1 kept training. The rejoin round
        lands EVERY rank on the Adasum consensus — which, with one
        zero delta, is the SURVIVING slice's progress — and NOT on
        rank 0's (the root's) stale parameters."""
        from horovod_tpu import local_sgd
        from horovod_tpu.ops.adasum import adasum_vhdd_host

        params = _params(rng)
        mesh = hvd.mesh()
        opt = hvd.DistributedOptimizer(
            optax.sgd(0.1), op=hvd.Average, local_sgd_steps=4,
            local_sgd_intra=4, local_sgd_inter_wire="fp32",
        )
        step = _make_opt_step(hvd, opt, mesh)
        sync = _make_sync_step(hvd, opt, mesh)
        pm0 = _rank_major(params)
        pm, sm = pm0, _rank_major(opt.init(params))
        grads = _grads(rng)
        for _ in range(3):
            pm, sm = step(pm, sm, grads)
        # simulate the newcomer: slice 0 restored AT the anchor
        def stale_slice0(leaf, anchor_leaf):
            arr = np.asarray(leaf).copy()
            arr[0:4] = np.asarray(anchor_leaf)[0:4]
            return jnp.asarray(arr)

        pm_stale = jax.tree_util.tree_map(
            stale_slice0, pm, sm.local_anchor
        )
        out, synced = local_sgd.rejoin_sync(sync, pm_stale, sm)
        assert synced
        pm2, _ = out
        w2 = np.asarray(pm2["w"])
        np.testing.assert_array_equal(w2[0], w2[7])
        # consensus: anchor + adasum(0, delta_slice1) == slice 1's
        # progress folded in — NOT rank 0's stale params
        anchor_w = np.asarray(sm.local_anchor["w"])[0]
        d1 = np.asarray(pm["w"])[4] - anchor_w
        zero = np.zeros_like(d1).reshape(-1)
        merged = adasum_vhdd_host([zero, d1.reshape(-1)])
        want = anchor_w.reshape(-1) + merged
        np.testing.assert_allclose(
            w2[0].reshape(-1), want, rtol=1e-5, atol=1e-6
        )
        assert not np.allclose(w2[0], anchor_w), (
            "a root broadcast from the stale newcomer would have "
            "landed here"
        )


# ---------------------------------------------------------------------------
# eager fused dispatcher phase routing
# ---------------------------------------------------------------------------


class TestEagerLocalPhase:
    def test_fused_allreduce_routes_intra(self, hvd):
        from horovod_tpu.common import topology as topo

        mesh = hvd.mesh()
        stages = _stages()
        x = topo.shard_from_rank_fn(
            lambda r: np.full((8,), float(r)), mesh, dtype=np.float32
        )
        fusion = hvd.common.basics.state().fusion
        before = fusion.cache_stats()["local_dispatches"]
        with hvd.local_sgd.local_phase(stages):
            out = np.asarray(hvd.allreduce(x, op=hvd.Sum))
        assert np.all(out[0] == 6.0) and np.all(out[4] == 22.0)
        assert fusion.cache_stats()["local_dispatches"] == before + 1
        # phase cleared: the SAME composition now reduces world-wide
        # (cache keys split — a flat executable never serves a local
        # dispatch and vice versa)
        flat = np.asarray(hvd.allreduce(x, op=hvd.Sum))
        assert np.all(flat[0] == 28.0)

    def test_int8_fused_local_phase(self, hvd):
        from horovod_tpu.common import topology as topo

        mesh = hvd.mesh()
        stages = _stages()
        base = np.linspace(0.0, 1.0, 4096)
        x = topo.shard_from_rank_fn(
            lambda r: base + r, mesh, dtype=np.float32
        )
        with hvd.local_sgd.local_phase(stages):
            out = np.asarray(
                hvd.allreduce(
                    x, op=hvd.Average, compression=hvd.Compression.int8
                )
            )
        # per-chunk scales, two quantization stages: bound ~2 quanta
        # of the slice-1 range (|max| ≈ 6.5 → quantum ≈ 0.05)
        want0 = base + np.mean([0, 1, 2, 3])
        assert np.abs(out[0] - want0).max() < 0.11
        want1 = base + np.mean([4, 5, 6, 7])
        assert np.abs(out[4] - want1).max() < 0.11

    def test_phase_reset(self, hvd):
        from horovod_tpu import local_sgd

        local_sgd.set_local_phase(_stages())
        assert local_sgd.active_intra_groups() is not None
        local_sgd.reset()
        assert local_sgd.active_intra_groups() is None
