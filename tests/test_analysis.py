"""Static-analysis subsystem tests (horovod_tpu/analysis/).

Three surfaces:

* the :mod:`hlo_parse` parser + :mod:`rules` engine over both
  hand-built module text (exact control of the shapes) and real
  lowered programs (the format contract against this JAX version);
* the :mod:`sched_audit` runtime recorder: deterministic folding,
  the FusionManager dispatch hook, KV round-trip, majority
  arbitration, first-divergent-index recovery;
* the driver's ``sched_divergence`` path — in-process, and the
  acceptance drill: a multi-process fleet where one rank's fusion
  composition is deliberately skewed and the driver must flag the
  divergence through the rendezvous KV BEFORE the stall inspector's
  shutdown window could fire.
"""

import subprocess
import sys
import textwrap
import time

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

import horovod_tpu as hvd_mod  # noqa: E402
from horovod_tpu import analysis  # noqa: E402
from horovod_tpu.analysis import rules, sched_audit  # noqa: E402
from horovod_tpu.common.compat import shard_map  # noqa: E402


# A hand-built module: two independent world all_reduces, one scalar
# inter-group all_reduce, an int8 all_to_all on intra groups, a
# dependent chain, and a donated arg — every parser feature in ~30
# lines of exact text.
_MODULE = textwrap.dedent(
    """
    module @jit_step attributes {mhlo.num_partitions = 8 : i32, mhlo.num_replicas = 1 : i32} {
      func.func public @main(%arg0: tensor<8x16xf32> {jax.buffer_donor = true}, %arg1: tensor<8x16xf32>) -> (tensor<8x16xf32> {jax.result_info = ""}) {
        %0 = call @shmap_body(%arg0) : (tensor<8x16xf32>) -> tensor<8x16xf32>
        return %0 : tensor<8x16xf32>
      }
      func.func private @shmap_body(%arg0: tensor<1x16xf32>) -> (tensor<1x16xf32>) {
        %0 = "stablehlo.all_reduce"(%arg0) <{channel_handle = #stablehlo.channel_handle<handle = 1, type = 1>, replica_groups = dense<[[0, 1, 2, 3, 4, 5, 6, 7]]> : tensor<1x8xi64>, use_global_device_ids}> ({
        ^bb0(%a: tensor<f32>, %b: tensor<f32>):
          %s = stablehlo.add %a, %b : tensor<f32>
          stablehlo.return %s : tensor<f32>
        }) : (tensor<1x16xf32>) -> tensor<1x16xf32>
        %1 = "stablehlo.all_reduce"(%arg0) <{channel_handle = #stablehlo.channel_handle<handle = 2, type = 1>, replica_groups = dense<[[0, 1, 2, 3, 4, 5, 6, 7]]> : tensor<1x8xi64>, use_global_device_ids}> ({
        ^bb0(%a: tensor<f32>, %b: tensor<f32>):
          %s = stablehlo.add %a, %b : tensor<f32>
          stablehlo.return %s : tensor<f32>
        }) : (tensor<1x16xf32>) -> tensor<1x16xf32>
        %2 = "stablehlo.all_reduce"(%1) <{channel_handle = #stablehlo.channel_handle<handle = 3, type = 1>, replica_groups = dense<[[0, 4], [1, 5], [2, 6], [3, 7]]> : tensor<4x2xi64>, use_global_device_ids}> ({
        ^bb0(%a: tensor<f32>, %b: tensor<f32>):
          %s = stablehlo.add %a, %b : tensor<f32>
          stablehlo.return %s : tensor<f32>
        }) : (tensor<f32>) -> tensor<f32>
        %3 = stablehlo.convert %arg0 : (tensor<1x16xf32>) -> tensor<1x16xi8>
        %4 = "stablehlo.all_to_all"(%3) <{channel_handle = #stablehlo.channel_handle<handle = 4, type = 1>, replica_groups = dense<[[0, 1, 2, 3], [4, 5, 6, 7]]> : tensor<2x4xi64>, split_dimension = 0 : i64, concat_dimension = 0 : i64, split_count = 4 : i64}> : (tensor<1x16xi8>) -> tensor<1x16xi8>
        %5 = stablehlo.add %0, %1 : tensor<1x16xf32>
        return %5 : tensor<1x16xf32>
      }
    }
    """
)

WORLD_G = ((0, 1, 2, 3, 4, 5, 6, 7),)
INTRA_G = ((0, 1, 2, 3), (4, 5, 6, 7))
INTER_G = ((0, 4), (1, 5), (2, 6), (3, 7))


class TestParser:
    def test_collectives_groups_types(self):
        g = analysis.parse_module(_MODULE)
        assert g.num_partitions == 8
        assert g.counts() == {
            "all_reduce": 3, "reduce_scatter": 0, "all_gather": 0,
            "all_to_all": 1, "collective_permute": 0,
        }
        ars = g.collectives("all_reduce")
        assert ars[0].replica_groups == WORLD_G
        assert ars[2].replica_groups == INTER_G
        assert ars[0].operand_types[0].shape == (1, 16)
        assert ars[0].operand_types[0].dtype == "f32"
        assert ars[0].operand_types[0].nbytes == 64
        assert ars[2].is_scalar()
        assert not ars[0].is_scalar()
        assert ars[0].reduction_dtype == "f32"
        a2a = g.collectives("all_to_all")[0]
        assert a2a.dtypes == ("i8",)
        assert a2a.replica_groups == INTRA_G
        assert g.group_sizes("all_to_all") == [4]

    def test_def_use_edges(self):
        g = analysis.parse_module(_MODULE)
        # %2 consumes %1: exactly one dependent pair among all_reduces
        pairs = g.dependent_pairs("all_reduce")
        assert len(pairs) == 1
        dep, on = pairs[0]
        assert (dep.sid, on.sid) == ("%2", "%1")
        assert not g.independent("all_reduce")
        assert g.independent("all_to_all")

    def test_donation_args(self):
        g = analysis.parse_module(_MODULE)
        args = g.args()
        assert [a.donated for a in args] == [True, False]
        assert g.donated_args()[0].index == 0

    def test_world_spanning(self):
        g = analysis.parse_module(_MODULE)
        ars = g.collectives("all_reduce")
        assert ars[0].spans(8)
        assert not ars[2].spans(8)

    def test_snippet_and_line_anchor(self):
        g = analysis.parse_module(_MODULE)
        c = g.collectives("all_to_all")[0]
        assert '"stablehlo.all_to_all"' in c.snippet
        line = _MODULE.splitlines()[c.line_no].strip()
        # snippets are truncated for readability but stay anchored to
        # the exact source line
        assert line.startswith(c.snippet.rstrip("."))
        assert len(c.snippet) <= 240

    def test_real_lowered_program(self, hvd):
        """Format contract against THIS jax version: shard_map psum
        over 8 CPU devices parses with groups, dtype, donation."""
        mesh = hvd_mod.mesh()

        def body(x):
            return jax.lax.psum(x, hvd_mod.WORLD_AXIS)

        fn = jax.jit(
            shard_map(
                body, mesh=mesh, in_specs=P(hvd_mod.WORLD_AXIS),
                out_specs=P(hvd_mod.WORLD_AXIS), check_vma=False,
            ),
            donate_argnums=(0,),
        )
        g = analysis.parse_module(fn.lower(jnp.ones((8, 16))))
        assert g.count("all_reduce") == 1
        assert g.collectives("all_reduce")[0].replica_groups == WORLD_G
        assert g.donated_args()


class TestRules:
    def _g(self):
        return analysis.parse_module(_MODULE)

    def test_collective_count_int_and_range(self):
        g = self._g()
        assert not rules.CollectiveCount("all_reduce", 3).check(g)
        assert rules.CollectiveCount("all_reduce", 2).check(g)
        assert not rules.CollectiveCount("all_to_all", (1, 2)).check(g)
        assert rules.CollectiveCount("all_to_all", (2, 9)).check(g)

    def test_def_use_rule_names_the_pair(self):
        f = rules.NoInterCollectiveDefUse("all_reduce").check(self._g())
        assert len(f) == 1
        assert "%2" in f[0].message and "%1" in f[0].message
        assert "all_reduce" in f[0].snippet

    def test_replica_group_structure(self):
        g = self._g()
        assert not rules.ReplicaGroupStructure(
            "all_to_all", groups=INTRA_G
        ).check(g)
        assert rules.ReplicaGroupStructure(
            "all_to_all", groups=INTER_G
        ).check(g)
        assert not rules.ReplicaGroupStructure(
            "all_to_all", forbid_world_spanning=True
        ).check(g)
        assert rules.ReplicaGroupStructure(
            "all_reduce", forbid_world_spanning=True
        ).check(g)
        # vacuous pass is a violation under require_present
        assert rules.ReplicaGroupStructure(
            "reduce_scatter", require_present=True
        ).check(g)
        assert not rules.ReplicaGroupStructure(
            "all_to_all", groups_any_of=(INTRA_G, INTER_G)
        ).check(g)
        assert rules.ReplicaGroupStructure(
            "all_to_all", groups_any_of=(INTER_G,)
        ).check(g)

    def test_wire_dtype_placement(self):
        g = self._g()
        # the module's i8 all_to_all rides INTRA groups: a placement
        # violation under the two-level contract
        f = rules.WireDtype(
            inter_groups=INTER_G, intra_groups=INTRA_G
        ).check(g)
        assert len(f) == 1 and "INTRA hop" in f[0].message
        # and any i8 at all violates a full-width contract
        assert rules.WireDtype(int8_allowed=False).check(g)

    def test_donation_coverage(self):
        g = self._g()
        assert not rules.DonationCoverage(arg_indices=(0,)).check(g)
        assert rules.DonationCoverage(arg_indices=(1,)).check(g)
        assert not rules.DonationCoverage(min_donated=1).check(g)
        assert rules.DonationCoverage(min_donated=2).check(g)

    def test_guard_overhead(self):
        base = self._g()
        same = self._g()
        assert not rules.GuardOverhead(base).check(same)
        # a module with one extra SCALAR all_reduce passes +1, fails +0
        extra = analysis.parse_module(
            _MODULE.replace(
                "%5 = stablehlo.add %0, %1 : tensor<1x16xf32>",
                """%9 = "stablehlo.all_reduce"(%arg0) <{replica_groups = dense<[[0, 1, 2, 3, 4, 5, 6, 7]]> : tensor<1x8xi64>}> ({
    ^bb0(%a: tensor<f32>, %b: tensor<f32>):
      %s = stablehlo.add %a, %b : tensor<f32>
      stablehlo.return %s : tensor<f32>
    }) : (tensor<f32>) -> tensor<f32>
    %5 = stablehlo.add %0, %1 : tensor<1x16xf32>""",
            )
        )
        assert rules.GuardOverhead(base).check(extra)
        assert not rules.GuardOverhead(
            base, extra_scalar_allreduces=1
        ).check(extra)

    def test_compile_budget(self):
        r = rules.CompileBudget(decode_compiles=1, prefills=(2, 4))
        assert not r.check({"decode_compiles": 1, "prefills": 3})
        assert r.check({"decode_compiles": 2, "prefills": 3})
        assert r.check({"prefills": 3})  # absent counter is a finding

    def test_expect_raises_with_snippet(self):
        with pytest.raises(AssertionError, match="all_reduce"):
            analysis.expect(
                self._g(), rules.NoInterCollectiveDefUse("all_reduce")
            )

    def test_report_json_shape(self):
        rep = rules.check_program(
            self._g(),
            [rules.CollectiveCount("all_reduce", 2)],
        )
        d = rep.to_dict()
        assert d["ok"] is False
        assert d["rules_checked"] == ["CollectiveCount[all_reduce==2]"]
        assert d["violations"][0]["rule"].startswith("CollectiveCount")


# ------------------------------------------------ schedule recorder


class TestScheduleRecorder:
    def test_deterministic_and_composition_sensitive(self):
        r = sched_audit.ScheduleRecorder()
        r.record("allreduce:2", ("a", (32,), "float32"), wire="fp32")
        r.record("allreduce:2", ("b", (64,), "float32"), wire="int8")
        fp1 = r.fingerprint()
        r2 = sched_audit.ScheduleRecorder()
        r2.record("allreduce:2", ("a", (32,), "float32"), wire="fp32")
        r2.record("allreduce:2", ("b", (64,), "float32"), wire="int8")
        assert r2.fingerprint() == fp1  # identical schedule, identical fp
        r3 = sched_audit.ScheduleRecorder()
        r3.record("allreduce:2", ("a", (32,), "float32"), wire="fp32")
        r3.record("allreduce:2", ("b", (64,), "float32"), wire="fp32")
        assert r3.fingerprint() != fp1  # the WIRE is part of the schedule

    def test_ring_bounded_and_indexed(self):
        r = sched_audit.ScheduleRecorder()
        for i in range(300):
            r.record("allreduce:2", ("t", (i,), "float32"))
        snap = r.snapshot()
        assert snap["dispatches"] == 300
        assert len(snap["ring"]) == 128
        assert snap["ring"][0][0] == 300 - 128
        assert snap["ring"][-1][0] == 299

    def test_reset(self):
        r = sched_audit.ScheduleRecorder()
        r.record("allreduce:2", ("t", (4,), "float32"))
        fp = r.fingerprint()
        r.reset()
        assert r.dispatch_count == 0
        assert r.fingerprint() != fp

    def test_disabled_by_env(self, monkeypatch):
        monkeypatch.setenv("HOROVOD_SCHED_AUDIT", "0")
        sched_audit.reset()
        sched_audit.record("allreduce:2", ("t", (4,), "float32"))
        assert sched_audit.recorder().dispatch_count == 0
        assert sched_audit.publish(step=1, rank=0) is False
        monkeypatch.setenv("HOROVOD_SCHED_AUDIT", "1")
        sched_audit.record("allreduce:2", ("t", (4,), "float32"))
        assert sched_audit.recorder().dispatch_count == 1
        sched_audit.reset()

    def test_fusion_dispatch_folds(self, hvd):
        """The real hook: identical eager dispatch sequences fold to
        identical fingerprints; a skewed composition diverges."""
        mesh = hvd_mod.mesh()

        def run(shapes):
            sched_audit.reset()
            for s in shapes:
                hvd_mod.allreduce(
                    hvd_mod.shard_from_rank_fn(
                        lambda r: np.ones(s, np.float32), mesh
                    )
                )
            return sched_audit.recorder().snapshot()

        a = run([(32,), (64,)])
        b = run([(32,), (64,)])
        c = run([(32,), (48,)])
        assert a["dispatches"] >= 2
        assert a["fingerprint"] == b["fingerprint"]
        assert a["fingerprint"] != c["fingerprint"]
        sched_audit.reset()

    def test_first_divergent_index_with_full_rings(self):
        """Trailing-extra-dispatch divergence stays locatable once both
        rings are full: the frontier comparison, not ring length, names
        the first divergent index."""
        good_r = sched_audit.ScheduleRecorder()
        for _ in range(299):
            good_r.record("allreduce:2", ("t", (32,), "float32"))
        bad = dict(good_r.snapshot())
        good = dict(good_r.snapshot())
        # bad rank ran ONE extra dispatch; both rings hold 128 entries
        bad_r = sched_audit.ScheduleRecorder()
        for _ in range(299):
            bad_r.record("allreduce:2", ("t", (32,), "float32"))
        bad_r.record("allreduce:2", ("EXTRA", (48,), "float32"))
        bad = bad_r.snapshot()
        assert len(bad["ring"]) == len(good["ring"]) == 128
        assert sched_audit.first_divergent_index(bad, good) == 299

    def test_grouped_auto_names_fold_without_counter(self):
        """grouped_allreduce auto-names carry the process counter AND a
        member index: the counter must not reach the fingerprint (a
        rejoined worker restarts it at 0), the member index must."""
        from horovod_tpu.ops.fusion import _sched_entry_name

        assert _sched_entry_name("allreduce.noname.7") == "allreduce"
        assert (
            _sched_entry_name("grouped_allreduce.noname.42.0")
            == "grouped_allreduce.0"
        )
        assert (
            _sched_entry_name("grouped_allreduce.noname.9000.0")
            == "grouped_allreduce.0"
        )
        assert _sched_entry_name("my_grad/layer0") == "my_grad/layer0"

    def test_find_divergent_majority_and_index(self):
        r = sched_audit.ScheduleRecorder()
        for i in range(3):
            r.record("allreduce:2", ("t", (32,), "float32"))
        good = dict(r.snapshot(), step=5)
        r.record("allreduce:2", ("EXTRA", (48,), "float32"))
        bad = dict(r.snapshot(), step=5)
        out = sched_audit.find_divergent({0: good, 1: dict(good), 2: bad})
        assert out == (5, (2,))
        assert sched_audit.first_divergent_index(bad, good) == 3
        # agreement -> None
        assert (
            sched_audit.find_divergent({0: good, 1: dict(good)}) is None
        )

    def test_kv_roundtrip(self):
        from horovod_tpu.runner.rendezvous import (
            KVStore,
            put_sched,
            read_sched_fingerprints,
        )

        class _C:
            def __init__(self, store):
                self._s = store

            def put(self, scope, key, value):
                self._s.put(scope, key, value)

        store = KVStore()
        put_sched(_C(store), 3, 17, "abcd", 42, [[41, "ffff"]])
        store.put("sched", "bogus", b"not json")
        out = read_sched_fingerprints(store)
        assert set(out) == {3}
        assert out[3]["fingerprint"] == "abcd"
        assert out[3]["dispatches"] == 42
        assert out[3]["ring"] == [[41, "ffff"]]


# ------------------------------------------------ driver integration


def _driver_with_store():
    from horovod_tpu.elastic.driver import ElasticDriver
    from horovod_tpu.runner.hosts import HostInfo
    from horovod_tpu.runner.rendezvous import KVStore

    from tests.test_chaos import _StoreServer
    from tests.test_elastic import FakeDiscovery

    d = ElasticDriver(
        FakeDiscovery([HostInfo("a", 2), HostInfo("b", 6)]),
        ["true"], min_np=1,
    )
    d.host_manager.refresh()
    d._server = _StoreServer(KVStore())
    d._blocks = [
        {"HOROVOD_RANK": str(r), "HOROVOD_HOSTNAME": h}
        for r, h in enumerate(["a"] * 2 + ["b"] * 6)
    ]

    class _C:
        def __init__(self, store):
            self._s = store

        def put(self, scope, key, value):
            self._s.put(scope, key, value)

    return d, _C(d._server.store)


class TestDriverSchedDivergence:
    def test_quarantine_reason_and_dispatch_index(self):
        from horovod_tpu.common.metrics import registry
        from horovod_tpu.runner.rendezvous import put_sched

        d, c = _driver_with_store()
        r = sched_audit.ScheduleRecorder()
        for _ in range(3):
            r.record("allreduce:2", ("t", (32,), "float32"))
        good = r.snapshot()
        r.record("allreduce:2", ("EXTRA", (48,), "float32"))
        bad = r.snapshot()
        before = registry.snapshot()
        for rank in range(8):
            snap = bad if rank == 1 else good
            put_sched(
                c, rank, 9, snap["fingerprint"], snap["dispatches"],
                snap["ring"],
            )
        d._last_audit_poll = -1e9
        reason = d._poll_audit(time.monotonic())
        assert reason is not None and reason.startswith("sched_divergence")
        assert "1" in reason
        assert "first divergent dispatch #3" in reason
        assert d.host_manager.is_blacklisted("a")
        assert not d.host_manager.is_blacklisted("b")
        snap_m = registry.snapshot()
        assert (
            snap_m.get("driver.sched_divergence_restarts", 0)
            - before.get("driver.sched_divergence_restarts", 0)
            == 1
        )
        # the same round is never judged twice
        d._last_audit_poll = -1e9
        assert d._poll_audit(time.monotonic()) is None

    def test_sched_agreement_falls_through_to_param_audit(self):
        from horovod_tpu.runner.rendezvous import put_audit, put_sched

        d, c = _driver_with_store()
        r = sched_audit.ScheduleRecorder()
        r.record("allreduce:2", ("t", (32,), "float32"))
        snap = r.snapshot()
        for rank in range(8):
            put_sched(
                c, rank, 4, snap["fingerprint"], snap["dispatches"],
                snap["ring"],
            )
            put_audit(c, rank, 4, "good" if rank != 2 else "evil")
        d._last_audit_poll = -1e9
        reason = d._poll_audit(time.monotonic())
        # schedules agree; the PARAM divergence is still caught
        assert reason is not None and reason.startswith("divergence")
        assert "2" in reason


class TestMultiProcessSkewedSchedule:
    def test_driver_flags_sched_divergence_before_stall_window(
        self, tmp_path, monkeypatch
    ):
        """Acceptance drill: three REAL worker processes run eager
        fused dispatches — rank 1's fusion composition deliberately
        skewed — and publish schedule fingerprints + heartbeats over
        HTTP into a live rendezvous KV. The driver must quarantine
        rank 1 with reason ``sched_divergence`` while every rank's
        heartbeat is fresh and the stall inspector's shutdown window
        (set explicitly below) has not elapsed — divergence caught as
        a SCHEDULE mismatch, not minutes later as a hang."""
        import os
        import signal  # noqa: F401  (symmetry with sibling drills)

        from horovod_tpu.elastic.driver import ElasticDriver
        from horovod_tpu.runner.hosts import HostInfo
        from horovod_tpu.runner.rendezvous import RendezvousServer

        from tests.test_elastic import FakeDiscovery

        stall_window_s = 300.0
        monkeypatch.setenv(
            "HOROVOD_STALL_SHUTDOWN_TIME_SECONDS", str(stall_window_s)
        )
        server = RendezvousServer(secret_key=None, backend="python")
        port = server.start()
        worker = tmp_path / "sched_worker.py"
        worker.write_text(
            textwrap.dedent(
                """
                import os, sys
                os.environ.setdefault("JAX_PLATFORMS", "cpu")
                rank, skew = int(sys.argv[1]), sys.argv[2] == "1"
                import numpy as np
                import horovod_tpu as hvd
                from horovod_tpu.analysis import sched_audit
                from horovod_tpu.common.config import Config
                from horovod_tpu.runner.rendezvous import (
                    _client_from_cfg, put_heartbeat,
                )

                hvd.init()
                mesh = hvd.mesh()

                def ar(n):
                    hvd.allreduce(
                        hvd.shard_from_rank_fn(
                            lambda r: np.ones((n,), np.float32), mesh
                        )
                    )

                for _ in range(3):
                    ar(32)
                if skew:
                    ar(48)  # the divergent dispatch (index 3)
                client = _client_from_cfg(Config.from_env())
                put_heartbeat(client, rank)
                ok = sched_audit.publish(step=1, rank=rank)
                print("PUBLISHED", ok, sched_audit.recorder().dispatch_count)
                hvd.shutdown()
                """
            )
        )
        t0 = time.monotonic()
        try:
            env = dict(os.environ)
            env["PYTHONPATH"] = os.path.dirname(
                os.path.dirname(os.path.abspath(__file__))
            )
            env["HOROVOD_GLOO_RENDEZVOUS_ADDR"] = "127.0.0.1"
            env["HOROVOD_GLOO_RENDEZVOUS_PORT"] = str(port)
            env.pop("HOROVOD_SECRET_KEY", None)
            env.pop("XLA_FLAGS", None)  # 1-device worker: faster init
            procs = [
                subprocess.Popen(
                    [sys.executable, str(worker), str(rank),
                     "1" if rank == 1 else "0"],
                    env=env, stdout=subprocess.PIPE,
                    stderr=subprocess.PIPE, text=True,
                )
                for rank in range(3)
            ]
            outs = [p.communicate(timeout=240) for p in procs]
            for p, (out, err) in zip(procs, outs):
                assert p.returncode == 0, err[-2000:]
                assert "PUBLISHED True" in out, (out, err[-2000:])

            d = ElasticDriver(
                FakeDiscovery([HostInfo("h0", 1), HostInfo("h1", 1),
                               HostInfo("h2", 1)]),
                ["true"], min_np=1,
            )
            d.host_manager.refresh()
            d._server = server
            d._blocks = [
                {"HOROVOD_RANK": str(r), "HOROVOD_HOSTNAME": f"h{r}"}
                for r in range(3)
            ]
            # heartbeats are FRESH (the divergent rank is alive and
            # beating — nothing for the stall path to see)
            d._last_hb_poll = -1e9
            assert d._poll_heartbeats(time.monotonic()) is None
            d._last_audit_poll = -1e9
            reason = d._poll_audit(time.monotonic())
            elapsed = time.monotonic() - t0
            assert reason is not None, "sched divergence not flagged"
            assert reason.startswith("sched_divergence"), reason
            assert "1" in reason
            assert "first divergent dispatch #3" in reason, reason
            assert d.host_manager.is_blacklisted("h1")
            assert not d.host_manager.is_blacklisted("h0")
            # ... and the whole detection ran inside the stall window:
            # the hang this prevents would not even have been NOTICED yet
            assert elapsed < stall_window_s, (
                f"detection took {elapsed:.1f}s, stall window "
                f"{stall_window_s}s"
            )
        finally:
            server.stop()
