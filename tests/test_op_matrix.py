"""Op x dtype x reduce-op x scale-factor matrix over the eager surface.

The reference's main parity suite is exactly this grid (ref:
test/parallel/test_tensorflow.py ~4k LoC: every op x dtype x avg/sum x
prescale/postscale with closed-form expectations [V], SURVEY.md §4.1).
Here the grid runs once over the 8-device CPU mesh — same closed-form
math, real XLA collectives. 64-bit dtypes are excluded: the framework
runs under JAX's default 32-bit mode (jax_enable_x64 off), where they
would silently truncate.
"""

import jax.numpy as jnp
import numpy as np
import pytest

WORLD = 8

DTYPES = [np.float32, np.int32, np.uint8, jnp.bfloat16]
FLOAT_DTYPES = [np.float32, jnp.bfloat16]


def _rank_major(fn, dtype):
    rows = [np.asarray(fn(r)) for r in range(WORLD)]
    return jnp.asarray(np.stack(rows)).astype(dtype)


def _np(x):
    return np.asarray(jnp.asarray(x).astype(jnp.float32))


@pytest.mark.parametrize("dtype", DTYPES, ids=lambda d: jnp.dtype(d).name)
def test_allreduce_sum_every_dtype(hvd, dtype):
    x = _rank_major(lambda r: np.full((4,), r + 1), dtype)
    out = hvd.allreduce(x, op=hvd.Sum)
    expect = np.full((4,), sum(range(1, WORLD + 1)))  # 36: fits uint8/bf16
    np.testing.assert_allclose(_np(out)[0], expect)
    assert jnp.asarray(out).dtype == jnp.dtype(dtype)


@pytest.mark.parametrize("dtype", FLOAT_DTYPES, ids=lambda d: jnp.dtype(d).name)
def test_allreduce_average_float_dtypes(hvd, dtype):
    x = _rank_major(lambda r: np.full((4,), float(2 * r)), dtype)
    out = hvd.allreduce(x, op=hvd.Average)
    np.testing.assert_allclose(_np(out)[0], np.full((4,), 7.0))


@pytest.mark.parametrize("prescale", [1.0, 0.5])
@pytest.mark.parametrize("postscale", [1.0, 2.0])
def test_allreduce_pre_post_scale(hvd, prescale, postscale):
    """Closed form: sum_r(prescale * r) * postscale (ref: the
    prescale_factor/postscale_factor args on hvd.allreduce [V])."""
    x = _rank_major(lambda r: np.full((4,), float(r)), np.float32)
    out = hvd.allreduce(
        x, op=hvd.Sum, prescale_factor=prescale, postscale_factor=postscale
    )
    expect = sum(prescale * r for r in range(WORLD)) * postscale
    np.testing.assert_allclose(_np(out)[0], np.full((4,), expect), rtol=1e-6)


@pytest.mark.parametrize("dtype", DTYPES, ids=lambda d: jnp.dtype(d).name)
def test_allgather_every_dtype(hvd, dtype):
    x = _rank_major(lambda r: np.full((2, 3), r), dtype)
    out = hvd.allgather(x)
    got = _np(out)
    # rank-major result: out[r] is the full gather for rank r
    assert got.shape == (WORLD, WORLD, 2, 3)
    flat = got[0].reshape(WORLD * 2, 3)
    expected = np.concatenate(
        [np.full((2, 3), float(r), np.float32) for r in range(WORLD)]
    )
    np.testing.assert_allclose(flat, expected)
    # every rank sees the same gather
    for r in range(1, WORLD):
        np.testing.assert_allclose(got[r], got[0])


@pytest.mark.parametrize("dtype", DTYPES, ids=lambda d: jnp.dtype(d).name)
@pytest.mark.parametrize("root", [0, 3, 7])
def test_broadcast_every_dtype_and_root(hvd, dtype, root):
    x = _rank_major(lambda r: np.full((4,), r * 10), dtype)
    out = hvd.broadcast(x, root_rank=root)
    got = _np(out)
    for r in range(WORLD):
        np.testing.assert_allclose(got[r], float(root * 10))


@pytest.mark.parametrize("dtype", DTYPES, ids=lambda d: jnp.dtype(d).name)
def test_reducescatter_sum_every_dtype(hvd, dtype):
    # rank r contributes constant r over [W*2, 3]; shard s of the result
    # is rows [2s, 2s+2) of the sum = 28 everywhere (fits uint8/bf16)
    x = _rank_major(lambda r: np.full((WORLD * 2, 3), r), dtype)
    out = hvd.reducescatter(x, op=hvd.Sum)
    got = _np(out)
    total = float(sum(range(WORLD)))
    for s in range(WORLD):
        np.testing.assert_allclose(got[s], np.full((2, 3), total))


@pytest.mark.parametrize("dtype", [np.float32, np.int32],
                         ids=lambda d: jnp.dtype(d).name)
def test_alltoall_equal_splits_every_dtype(hvd, dtype):
    # rank r sends value 100*r + dest to dest; after exchange, rank d
    # holds [100*s + d for s in ranks]
    x = _rank_major(
        lambda r: np.asarray([100 * r + d for d in range(WORLD)]), dtype
    )
    out = hvd.alltoall(x)
    got = _np(out)
    for d in range(WORLD):
        np.testing.assert_allclose(
            got[d], [100.0 * s + d for s in range(WORLD)]
        )


@pytest.mark.parametrize("op_name", ["min", "max", "product"])
def test_other_reduce_ops_if_supported(hvd, op_name):
    """Min/Max/Product parity with upstream's ReduceOp surface [V];
    skip cleanly if this build doesn't expose them."""
    op = getattr(hvd, op_name.capitalize(), None)
    if op is None:
        pytest.skip(f"{op_name} not exposed")
    x = _rank_major(lambda r: np.full((4,), float(r + 1)), np.float32)
    out = hvd.allreduce(x, op=op)
    vals = np.arange(1, WORLD + 1, dtype=np.float64)
    expect = {
        "min": vals.min(), "max": vals.max(), "product": vals.prod()
    }[op_name]
    np.testing.assert_allclose(_np(out)[0], np.full((4,), expect))


def test_alltoall_v_over_process_set(hvd):
    """Uneven alltoall scoped to a process set: members exchange by
    member position, non-members pass through unchanged (ref:
    process-set Alltoallv [V]; closed the silent-global-exchange gap)."""
    ps = hvd.add_process_set([1, 3, 5])
    try:
        # every member sends 1 row to the 1st member, 2 to the 2nd,
        # 3 to the 3rd (genuinely uneven); rows carry the sender id
        rows = [
            np.full((6, 2), float(r), np.float32) for r in range(WORLD)
        ]
        splits = [[1, 2, 3] for _ in range(WORLD)]
        out, recv = hvd.alltoall(rows, splits=splits, process_set=ps)
        got = [np.asarray(o) for o in out]
        # member 3 (position 1) receives 2 rows from each of 1, 3, 5
        np.testing.assert_allclose(
            got[3][:, 0], [1.0, 1.0, 3.0, 3.0, 5.0, 5.0]
        )
        assert recv[3] == [2, 2, 2]
        # member 5 (position 2) receives 3 rows from each member
        assert recv[5] == [3, 3, 3] and got[5].shape == (9, 2)
        # non-member 0 passes through unchanged
        np.testing.assert_allclose(got[0], rows[0])
    finally:
        hvd.remove_process_set(ps)


def test_alltoall_v_nonmember_split_rows_are_placeholders(hvd):
    """The documented contract: non-member splits rows are IGNORED —
    None placeholders must work (review regression)."""
    ps = hvd.add_process_set([0, 2, 4])
    try:
        rows = [
            np.full((3, 2), float(r), np.float32) for r in range(WORLD)
        ]
        splits = [
            [1, 1, 1] if r in (0, 2, 4) else None for r in range(WORLD)
        ]
        out, recv = hvd.alltoall(rows, splits=splits, process_set=ps)
        np.testing.assert_allclose(
            np.asarray(out[0])[:, 0], [0.0, 2.0, 4.0]
        )
        np.testing.assert_allclose(np.asarray(out[1]), rows[1])
    finally:
        hvd.remove_process_set(ps)
