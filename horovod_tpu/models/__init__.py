"""Model zoo mirroring the reference's benchmark/example configs
(BASELINE.json: MNIST ConvNet, ResNet-50, BERT-large, GPT-2 medium,
ViT-B/16; plus the reference's published-scaling models Inception V3 /
ResNet-101 / VGG-16 — docs/benchmarks.rst [V], BASELINE.md reference
table; ref: examples/pytorch/pytorch_mnist.py,
examples/pytorch/pytorch_synthetic_benchmark.py [V]), implemented
TPU-first in flax: bfloat16-friendly, static shapes, remat hooks."""

from .inception import InceptionV3  # noqa: F401
from .mnist import MNISTConvNet  # noqa: F401
from .resnet import ResNet50, ResNet101  # noqa: F401
from .transformer import (  # noqa: F401
    Transformer,
    TransformerConfig,
    init_cache,
)
from .vgg import VGG16  # noqa: F401
from .vit import ViT, ViTConfig  # noqa: F401
