"""TorchEstimator tests (ref: horovod/spark/torch/estimator.py [V],
SURVEY.md §2.5): declare-fit-predict contract, optimizer factory form,
store checkpointing, save/load round-trip, batch-iterable input."""

import os

import numpy as np
import pytest

torch = pytest.importorskip("torch")

from horovod_tpu.spark import LocalStore
from horovod_tpu.spark.torch import TorchEstimator, TorchModelWrapper


def _net():
    torch.manual_seed(0)
    return torch.nn.Sequential(
        torch.nn.Linear(4, 16), torch.nn.ReLU(), torch.nn.Linear(16, 1)
    )


def _data(n=256, d=4, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=(n, d)).astype(np.float32)
    w = rng.normal(size=(d, 1)).astype(np.float32)
    y = x @ w + 0.01 * rng.normal(size=(n, 1)).astype(np.float32)
    return x, y


def test_fit_learns_and_checkpoints(hvd, tmp_path):
    x, y = _data()
    net = _net()
    est = TorchEstimator(
        model=net,
        loss=torch.nn.MSELoss(),
        optimizer=lambda params: torch.optim.Adam(params, lr=1e-2),
        store=LocalStore(str(tmp_path / "store")),
        run_id="fit1",
        epochs=12,
        batch_size=64,
    )
    model = est.fit(x, y)
    assert isinstance(model, TorchModelWrapper)
    assert est.history[-1]["loss"] < est.history[0]["loss"] * 0.1
    preds = model.predict(x[:8])
    assert preds.shape == (8, 1)
    ckpt_dir = est.store.checkpoint_dir("fit1")
    assert os.path.isdir(ckpt_dir) and os.listdir(ckpt_dir)
    # checkpoint payload restores into a fresh architecture
    ckpt = torch.load(
        os.path.join(ckpt_dir, sorted(os.listdir(ckpt_dir))[-1]),
        weights_only=True,
    )
    fresh = _net()
    fresh.load_state_dict(ckpt["model"])


def test_optimizer_instance_form(hvd):
    x, y = _data(n=64)
    net = _net()
    est = TorchEstimator(
        model=net,
        optimizer=torch.optim.SGD(net.parameters(), lr=1e-2),
        loss=torch.nn.MSELoss(),
        epochs=2,
        batch_size=32,
    )
    est.fit(x, y)
    assert len(est.history) == 2
    assert all(np.isfinite(h["loss"]) for h in est.history)


def test_fit_with_batch_iterable(hvd):
    x, y = _data(n=128)
    batches = [(x[i : i + 32], y[i : i + 32]) for i in range(0, 128, 32)]
    est = TorchEstimator(model=_net(), epochs=1, batch_size=32)
    est.fit(batches)
    assert len(est.history) == 1


def test_model_save_load_roundtrip(hvd, tmp_path):
    x, y = _data(n=64)
    est = TorchEstimator(model=_net(), epochs=1, batch_size=32)
    model = est.fit(x, y)
    path = str(tmp_path / "served.pt")
    model.save(path)
    loaded = TorchModelWrapper.load(_net(), path)
    np.testing.assert_allclose(
        loaded.predict(x[:4]), model.predict(x[:4]), rtol=1e-6
    )


def test_backward_passes_per_step(hvd):
    """Local aggregation window: k microbatches per optimizer step
    still trains (the shim's accumulate-union flush)."""
    x, y = _data(n=128)
    est = TorchEstimator(
        model=_net(),
        loss=torch.nn.MSELoss(),
        optimizer=lambda p: torch.optim.SGD(p, lr=1e-2),
        epochs=6,
        batch_size=32,
        backward_passes_per_step=2,
    )
    est.fit(x, y)
    assert est.history[-1]["loss"] < est.history[0]["loss"]
