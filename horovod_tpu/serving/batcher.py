"""Continuous batching: admissions between decode steps, never flushes.

The scheduler the Gemma-on-TPU serving paper centers on (PAPERS.md,
arXiv 2605.25645): a queued request is admitted into a freed decode
slot *between* decode steps — prefill it, write its KV rows, and the
next fixed-shape decode step simply carries one more live slot. No
retrace (shapes never change — engine.py), no flush (in-flight
sequences keep their slots and their cache), no batch barrier (a long
generation never holds short ones hostage, and vice versa).

Policy knobs:

* ``max_admit_per_step`` — prefills admitted between two decode steps
  (``HOROVOD_SERVE_MAX_BATCH``). Prefill happens on the decode thread,
  so each admission delays every in-flight token by one prefill: this
  knob IS the TTFT-vs-TPOT interleaving trade (docs/serving.md).
* ``policy="static"`` — the A/B baseline (bench_serve.py): admissions
  only when the previous batch fully completed, i.e. classic batched
  inference with its head-of-line blocking.
* per-request deadlines — queued requests expire before wasting a
  prefill; running requests are evicted at the deadline with their
  partial output (status ``"deadline"``).

Paged memory plane (serving/paged_kv.py, the default): admission is
additionally gated on free KV *pages* — a request is only admitted
when its worst-case prompt pages fit above the reserve watermark, so
mid-decode allocation can't strand in-flight sequences. If the pool
still exhausts mid-decode (prefix-cache churn, undersized pools), the
step does not raise: the YOUNGEST running request is paused — re-queued
at the front with its pages kept for a pointer-cheap resume — and, as
the last resort, paused requests' kept pages are reclaimed
deadline-aware (nearest deadline first; those resume by re-prefilling
prompt + generated-so-far, usually through the prefix cache).

Draining (``drain()``, wired to SIGTERM via
``preemption.register_drain``) stops ADMISSION of new submissions but
runs queue + in-flight to completion — every accepted request finishes
before the worker leaves the gang. With a drain DEADLINE
(``HOROVOD_SERVE_DRAIN_DEADLINE_S``), sequences still in flight past
it are live-migrated instead: :meth:`export_inflight` detaches each
slot's pages + generated tokens + armed sampling state and the
frontend streams them to a reserved peer over the kv_transfer wire
(the ``migrate`` frame), where they resume mid-decode without
re-prefill.

Role-split fleets (``HOROVOD_SERVE_ROLE``, serving/kv_transfer.py): a
``prefill``-role batcher reserves decode capacity BEFORE each fresh
prefill, then detaches the finished pages and hands them to the
transfer coordinator — the request never occupies a decode slot here
unless the transfer plane has no capacity (local fallback, the
unified path). A ``decode``-role batcher admits transferred requests
through :meth:`submit_ingested`: the foreign pages pointer-attach
exactly like a pause-resume, so admission changes data, never shapes —
``decode_compiles`` stays 1. In-flight handoffs count against drain:
SIGTERM waits for streamed requests to finish or fall back.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from ..common import telemetry as _telemetry
from ..common import tracing as _tracing
from ..common.logging import get_logger
from ..common.metrics import registry as _metrics
from ..testing import chaos as _chaos
from .paged_kv import PagePoolExhausted
from .slo import LatencyRecorder

_log = get_logger("serve.batcher")

QUEUED = "queued"
RUNNING = "running"
DONE = "done"
DEADLINE = "deadline"
REJECTED = "rejected"
ERROR = "error"


class Rejected(RuntimeError):
    """Request refused at submission (draining, or it can never fit)."""


@dataclasses.dataclass
class Request:
    id: int
    prompt: np.ndarray
    max_new_tokens: int
    deadline_ts: Optional[float]  # monotonic; None = no deadline
    submitted: float = dataclasses.field(default_factory=time.monotonic)
    status: str = QUEUED
    out_tokens: List[int] = dataclasses.field(default_factory=list)
    ttft_ms: float = 0.0
    gen_ms: float = 0.0
    # paged memory plane (serving/paged_kv.py): pause/resume state. A
    # request paused on pool exhaustion re-queues with ``paused=True``;
    # ``kept_pages`` holds its page-table snapshot (refcounts
    # transferred from the slot) so resume is a pointer re-attach — or
    # None once the deadline-aware reclaim dropped them, in which case
    # resume re-prefills prompt + generated-so-far.
    paused: bool = False
    kept_pages: Optional[list] = None
    resume_length: int = 0
    admit_seq: int = -1
    # KV-transfer ingest payload (serving/kv_transfer.py): host page
    # arrays + logical indices waiting for their admit-time device
    # write. Dropped (None) once attached — the arrays are large.
    ingest: Optional[dict] = dataclasses.field(default=None, repr=False)
    # per-request sampling (engine.set_sampling — pure DATA through the
    # one decode executable): temperature 0 = bit-identical greedy,
    # top_k 0 = no truncation, seed None = derived from the request id
    # (stable across replays). Armed at every admission (fresh, resume
    # and ingest alike), cleared when the slot retires.
    temperature: float = 0.0
    top_k: int = 0
    seed: Optional[int] = None
    # trace plane (common/tracing.py): the request's TraceContext (None
    # = untraced — every span site below skips on None, so the default
    # path carries zero tracing cost) and the open admit→retire decode
    # span riding it
    trace: Optional[object] = dataclasses.field(default=None, repr=False)
    span: Optional[object] = dataclasses.field(default=None, repr=False)
    _done: threading.Event = dataclasses.field(
        default_factory=threading.Event
    )

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)

    def finished(self) -> bool:
        return self._done.is_set()

    def result(self) -> Dict:
        out = {
            "id": self.id,
            "status": self.status,
            "tokens": list(self.out_tokens),
            "prompt_len": int(self.prompt.size),
            "ttft_ms": round(self.ttft_ms, 3),
            "gen_ms": round(self.gen_ms, 3),
        }
        if self.trace is not None:
            out["trace_id"] = self.trace.trace_id
        return out


class ContinuousBatcher:
    """Single decode-thread scheduler over an InferenceEngine."""

    def __init__(
        self,
        engine,
        *,
        max_admit_per_step: int = 4,
        default_max_new_tokens: int = 64,
        default_deadline_ms: float = 0.0,
        eos_id: Optional[int] = None,
        policy: str = "continuous",
        recorder: Optional[LatencyRecorder] = None,
        role: str = "unified",
    ) -> None:
        if policy not in ("continuous", "static"):
            raise ValueError(f"unknown policy {policy!r}")
        if role not in ("unified", "prefill", "decode"):
            raise ValueError(f"unknown serve role {role!r}")
        if role != "unified" and not engine.paged:
            raise ValueError(
                "prefill/decode roles need the paged KV plane "
                "(HOROVOD_SERVE_KV=paged) — the transfer wire moves "
                "pool pages, not slab slots"
            )
        self.engine = engine
        self.role = role
        # TransferCoordinator (prefill role), wired by serve() after
        # construction — None means no transfer plane: every request
        # decodes locally (the unified path)
        self.transfer = None
        self._handoffs = 0  # requests streamed out, result not back yet
        self.max_admit_per_step = max(int(max_admit_per_step), 1)
        self.default_max_new_tokens = int(default_max_new_tokens)
        self.default_deadline_ms = float(default_deadline_ms)
        self.eos_id = eos_id
        self.policy = policy
        self.recorder = recorder or LatencyRecorder()
        self._ids = itertools.count()
        self._admit_ids = itertools.count()
        self._cond = threading.Condition()
        self._queue: "deque[Request]" = deque()
        self._slot_req: Dict[int, Request] = {}
        self._draining = False
        self._drain_active = False  # a drain() loop is live-stepping
        self._running = False
        self._thread: Optional[threading.Thread] = None
        self._decode_steps = 0
        self._last_publish = 0.0

    # ------------------------------------------------------------ submission

    def submit(
        self,
        prompt,
        max_new_tokens: Optional[int] = None,
        deadline_ms: Optional[float] = None,
        temperature: float = 0.0,
        top_k: int = 0,
        seed: Optional[int] = None,
        trace=None,
    ) -> Request:
        if self.role == "decode":
            # the Router never sends prompts here (role-aware pick);
            # this guard keeps a misconfigured client from tripping the
            # engine's role gate deep inside the scheduler thread
            _metrics.counter("serve.rejected")
            raise Rejected(
                "decode-role worker takes KV transfers, not prompts"
            )
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        if prompt.size == 0:
            _metrics.counter("serve.rejected")
            raise Rejected("empty prompt")
        max_new = (
            self.default_max_new_tokens
            if max_new_tokens is None
            else int(max_new_tokens)
        )
        # the generation must fit the slot's KV capacity: clamp, and
        # reject prompts that leave no room for even the first token
        max_new = min(max_new, self.engine.max_len - int(prompt.size))
        if max_new < 1:
            _metrics.counter("serve.rejected")
            raise Rejected(
                f"prompt of {prompt.size} tokens leaves no room in a "
                f"{self.engine.max_len}-token KV slot"
            )
        if self.engine.paged:
            mgr = self.engine.manager
            worst = mgr.pages_needed(int(prompt.size) + max_new)
            if worst > mgr.num_pages:
                # can NEVER fit, even with the whole pool to itself —
                # the paged analog of the slot-capacity reject above
                _metrics.counter("serve.rejected")
                raise Rejected(
                    f"request needs {worst} KV pages but the pool has "
                    f"only {mgr.num_pages}"
                )
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        req = Request(
            id=next(self._ids),
            prompt=prompt,
            max_new_tokens=max_new,
            deadline_ts=(
                time.monotonic() + deadline_ms / 1e3
                if deadline_ms and deadline_ms > 0
                else None
            ),
            temperature=float(temperature),
            top_k=int(top_k),
            seed=seed,
            trace=trace,
        )
        with self._cond:
            # drain check and enqueue under ONE lock: a submit racing
            # the SIGTERM drain either lands before the flag flips (the
            # drain loop re-checks the queue, so it WILL be served) or
            # sees the flag and is rejected — never accepted-then-lost
            if self._draining:
                _metrics.counter("serve.rejected")
                raise Rejected(
                    "worker is draining (shutdown in progress)"
                )
            self._queue.append(req)
            self._cond.notify_all()
        _metrics.counter("serve.requests_total")
        self._publish_gauges()
        return req

    # ------------------------------------------------- transfer plane hooks

    def submit_ingested(
        self,
        prompt,
        first_token: int,
        max_new_tokens: int,
        logical,
        arrays,
        length: int,
        hashes=(),
        deadline_ms: Optional[float] = None,
        temperature: float = 0.0,
        top_k: int = 0,
        seed: Optional[int] = None,
        trace=None,
    ) -> Request:
        """Admit a KV-transferred request (serving/kv_transfer.py
        receiver). Called from an HTTP handler thread: only host-side
        bookkeeping happens here — the device write (ingest_attach)
        runs at admit time on the scheduler thread, like every other
        pool touch. The first token was already emitted by the remote
        prefill, so ``out_tokens`` starts seeded and decode produces
        the remaining ``max_new_tokens - 1``."""
        if not self.engine.paged:
            raise Rejected("KV ingest needs the paged plane")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        pages = list(logical)
        if len(pages) > self.engine.manager.num_pages:
            _metrics.counter("serve.rejected")
            raise Rejected(
                f"ingest of {len(pages)} pages exceeds the "
                f"{self.engine.manager.num_pages}-page pool"
            )
        req = Request(
            id=next(self._ids),
            prompt=prompt,
            max_new_tokens=int(max_new_tokens),
            deadline_ts=(
                time.monotonic() + float(deadline_ms) / 1e3
                if deadline_ms and float(deadline_ms) > 0
                else None
            ),
            temperature=float(temperature),
            top_k=int(top_k),
            seed=seed,
            trace=trace,
        )
        req.out_tokens.append(int(first_token))
        req.ingest = {
            "logical": [int(lp) for lp in pages],
            "arrays": arrays,
            "length": int(length),
            "hashes": list(hashes),
        }
        with self._cond:
            if self._draining:
                _metrics.counter("serve.rejected")
                raise Rejected("worker is draining (shutdown in progress)")
            self._queue.append(req)
            self._cond.notify_all()
        _metrics.counter("serve.requests_total")
        self._publish_gauges()
        return req

    def submit_migrated(
        self,
        prompt,
        tokens,
        max_new_tokens: int,
        logical,
        arrays,
        length: int,
        deadline_ms: Optional[float] = None,
        sample: Optional[dict] = None,
        trace=None,
    ) -> Request:
        """Admit a live-migrated in-flight sequence (the ``migrate``
        frame, serving/kv_transfer.py receiver). Unlike
        :meth:`submit_ingested` the request arrives MID-DECODE: the
        full generated-token history seeds ``out_tokens`` (the newest
        one feeds the next decode step — the same frontier it left the
        sender at) and ``sample`` carries the sender's armed sampling
        snapshot including the raw mid-stream PRNG key, so sampled
        sequences continue bit-identically. No prefix publication: the
        pages hold generated tokens, not a shareable prompt prefix."""
        if not self.engine.paged:
            raise Rejected("migration needs the paged plane")
        toks = [int(t) for t in tokens]
        if not toks:
            raise Rejected("migrated sequence carries no tokens")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        pages = list(logical)
        if len(pages) > self.engine.manager.num_pages:
            _metrics.counter("serve.rejected")
            raise Rejected(
                f"migration of {len(pages)} pages exceeds the "
                f"{self.engine.manager.num_pages}-page pool"
            )
        req = Request(
            id=next(self._ids),
            prompt=prompt,
            max_new_tokens=int(max_new_tokens),
            deadline_ts=(
                time.monotonic() + float(deadline_ms) / 1e3
                if deadline_ms and float(deadline_ms) > 0
                else None
            ),
            trace=trace,
        )
        req.out_tokens.extend(toks)
        req.ingest = {
            "logical": [int(lp) for lp in pages],
            "arrays": arrays,
            "length": int(length),
            "hashes": [],
            "sample": sample,
        }
        with self._cond:
            if self._draining:
                _metrics.counter("serve.rejected")
                raise Rejected("worker is draining (shutdown in progress)")
            self._queue.append(req)
            self._cond.notify_all()
        _metrics.counter("serve.requests_total")
        self._publish_gauges()
        return req

    def requeue_fallback(self, req: Request, kept, length: int) -> None:
        """Transfer failed after the prefill (retries exhausted, or the
        decode worker answered with an error status): bring the request
        home. Its pages are still held, so it re-queues paused at the
        FRONT for a pointer-cheap local decode — even while draining
        (it was accepted; accepted work completes). Called from the
        handoff thread."""
        req.kept_pages = kept
        req.resume_length = int(length)
        req.paused = True
        req.status = QUEUED
        with self._cond:
            self._handoffs -= 1
            if (
                self._draining and not self._drain_active
                and not self._running and self._thread is None
            ):
                # scheduler crashed or already stopped: nothing will
                # ever serve the queue — fail loudly, don't park waiters
                req.kept_pages = None
                self.engine.manager.release_kept(kept)
                req.status = ERROR
                req._done.set()
                _metrics.counter("serve.errored")
                return
            self._queue.appendleft(req)
            self._cond.notify_all()
        _metrics.counter("serve.transfer_fallbacks")
        _log.info(
            "request %d fell back to local decode after transfer failure",
            req.id,
        )

    def complete_handoff(self, req: Request, result: Dict) -> None:
        """Remote decode finished: copy the decode worker's output into
        the local request and release its waiter. TTFT stays the value
        measured HERE (the client's clock); gen_ms is the decode
        worker's. Called from the handoff thread."""
        req.out_tokens = [int(t) for t in result.get("tokens", ())]
        req.gen_ms = float(result.get("gen_ms", 0.0))
        req.status = DONE if result.get("status") == "done" else DEADLINE
        with self._cond:
            self._handoffs -= 1
            self._cond.notify_all()
        if req.status == DONE:
            _metrics.counter("serve.completed")
        else:
            _metrics.counter("serve.expired")
        _metrics.counter("serve.handed_off")
        req._done.set()

    # ------------------------------------------------------------- the loop

    def start(self) -> None:
        if self._thread is not None:
            return
        self._running = True
        self._thread = threading.Thread(
            target=self._run, name="hvd-serve-batcher", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        with self._cond:
            self._running = False
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None

    def drain(
        self,
        timeout: float = 30.0,
        migrate_after: Optional[float] = None,
        on_deadline=None,
    ) -> bool:
        """Stop admitting NEW submissions; run everything already
        accepted (queued + in-flight) to completion. Returns True when
        the plane is empty. Works both loop-driven and manually-stepped
        (tests): without a running loop the drain steps inline.

        With ``migrate_after`` (seconds) AND an ``on_deadline``
        callback, sequences still in flight past that point are
        exported (:meth:`export_inflight`) and handed to the callback —
        the frontend's live-migration hook. The exported records count
        as handoffs, so the drain keeps waiting until each one's result
        lands (remote completion) or its fallback requeue is served
        inline."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()
        start = time.monotonic()
        deadline = start + timeout
        migrate_at = (
            start + max(float(migrate_after), 0.0)
            if migrate_after is not None and on_deadline is not None
            else None
        )
        self._drain_active = True
        try:
            while time.monotonic() < deadline:
                if (
                    not self._queue and not self._slot_req
                    and not self._handoffs
                ):
                    return True
                if (
                    migrate_at is not None
                    and time.monotonic() >= migrate_at
                ):
                    migrate_at = None
                    if self._slot_req:
                        records = self.export_inflight()
                        _log.info(
                            "drain deadline: migrating %d in-flight "
                            "sequence(s)", len(records),
                        )
                        on_deadline(records)
                    continue
                if self._running:
                    time.sleep(0.005)
                elif not self.step():
                    # idle but handoffs still in flight: they finish
                    # (or fall back into the queue) on their own threads
                    time.sleep(0.005)
            return (
                not self._queue and not self._slot_req
                and not self._handoffs
            )
        finally:
            self._drain_active = False

    def export_inflight(self) -> List[dict]:
        """Detach every in-flight sequence for live migration (the
        drain-deadline path). Stops the scheduler loop first — the
        drain thread becomes the single consumer — then, per slot:
        snapshot the armed sampling state BEFORE the detach (the raw
        mid-stream PRNG key; clearing after detach keeps the next
        occupant clean), detach the pages with refcounts transferred,
        and count the record as an in-flight handoff so drain() waits
        for its remote result or fallback exactly like a streamed
        prefill."""
        self.stop()
        records: List[dict] = []
        for slot in sorted(self._slot_req):
            req = self._slot_req.pop(slot)
            sample = self.engine.export_sampling(slot)
            kept, length = self.engine.manager.detach_keep(slot)
            self.engine.clear_sampling(slot)
            records.append({
                "req": req,
                "kept": kept,
                "length": length,
                "sample": sample,
            })
        with self._cond:
            self._handoffs += len(records)
        self._publish_gauges(min_interval=0.0)
        return records

    def _run(self) -> None:
        while True:
            with self._cond:
                if not self._running:
                    return
            try:
                did = self.step()
            except Exception:
                # the scheduler thread must NEVER die silently: every
                # accepted request's done-event would stay unset and
                # the HTTP handlers parked on them would block forever
                # while the announce loop kept advertising a live
                # worker. Fail loudly: abort everything accepted,
                # refuse new work, and let /healthz report not-ok.
                _log.exception(
                    "serve scheduler failed; aborting accepted requests"
                )
                self._abort_all("scheduler failure")
                with self._cond:
                    self._draining = True
                    self._running = False
                return
            if not did:
                with self._cond:
                    if self._running and not self._queue:
                        # short timeout: queued deadlines must still
                        # expire while the plane idles
                        self._cond.wait(timeout=0.02)

    def _abort_all(self, reason: str) -> None:
        """Fail every queued and in-flight request (status ``error``)
        so their waiters unblock — the crash path's drain."""
        with self._cond:
            queued = list(self._queue)
            self._queue.clear()
        for slot in list(self._slot_req):
            req = self._slot_req.pop(slot)
            self.engine.manager.free(slot)
            queued.append(req)
        for req in queued:
            if req.kept_pages:
                self.engine.manager.release_kept(req.kept_pages)
                req.kept_pages = None
            req.status = ERROR
            if req.span is not None:
                req.span.end(outcome="error", reason=reason)
                req.span = None
            req._done.set()
            _metrics.counter("serve.errored")
        self._publish_gauges(min_interval=0.0)

    # ------------------------------------------------------------- one step

    def step(self) -> bool:
        """One scheduler round: expire → admit → decode → retire.
        Returns False when there was nothing to do (idle)."""
        # chaos site `serve.worker_kill`: a transport-kind fault raises
        # here — the loop's crash handler aborts every accepted request
        # (the Router's replay path fires); the `kill` kind SIGKILLs
        # the process for the subprocess drills
        _chaos.inject("serve.worker_kill")
        now = time.monotonic()
        self._expire_queued(now)
        admitted = self._admit(now)
        stepped = self._decode(now)
        self._publish_gauges()
        return bool(admitted or stepped)

    def _expire_queued(self, now: float) -> None:
        with self._cond:
            keep: "deque[Request]" = deque()
            expired = []
            for req in self._queue:
                if req.deadline_ts is not None and now >= req.deadline_ts:
                    expired.append(req)
                else:
                    keep.append(req)
            self._queue = keep
        for req in expired:
            if req.kept_pages:
                # a paused request expiring in the queue releases the
                # pages it was holding for resume
                self.engine.manager.release_kept(req.kept_pages)
                req.kept_pages = None
            req.status = DEADLINE
            req._done.set()
            _metrics.counter("serve.expired")

    def _resume_seq(self, req: Request) -> np.ndarray:
        """The token sequence a page-dropped paused request re-prefills:
        prompt plus everything generated EXCEPT the newest token — that
        one is fed to the next decode step (which writes its kv), the
        same frontier the request was paused at."""
        return np.concatenate(
            [req.prompt, np.asarray(req.out_tokens[:-1], np.int32)]
        )

    def _admission_pages_needed(self, req: Request) -> int:
        """Pages the admission gate must see headroom for: a resume
        with kept pages needs none (they are already held); a
        page-dropped resume re-prefills its whole sequence-so-far; a
        fresh request needs its prompt (prefix hits can only reduce
        this — the gate is deliberately conservative)."""
        mgr = self.engine.manager
        if req.kept_pages is not None:
            return 0
        if req.ingest is not None:
            return len(req.ingest["logical"])
        if req.paused and req.out_tokens:
            return mgr.pages_needed(self._resume_seq(req).size)
        return mgr.pages_needed(int(req.prompt.size))

    def _admit(self, now: float) -> int:
        admitted = 0
        mid_decode = bool(self._slot_req)
        if self.policy == "static" and mid_decode:
            return 0
        limit = (
            self.engine.slots
            if self.policy == "static"
            else self.max_admit_per_step
        )
        paged = self.engine.paged
        while admitted < limit:
            sample_armed = False
            with self._cond:
                if not self._queue:
                    break
                req = self._queue[0]
            if paged and (
                self._admission_pages_needed(req)
                > self.engine.manager.admission_headroom()
            ):
                # the page gate: admission never dips into the reserve
                # watermark — those pages belong to in-flight decodes
                break
            slot = self.engine.manager.alloc(req.id)
            if slot is None:
                break
            with self._cond:
                # single consumer: the head is still req
                self._queue.popleft()
            req.admit_seq = next(self._admit_ids)
            if req.kept_pages is not None:
                # resume from pause: the kept pages pointer-attach and
                # decode continues exactly where it stopped — no
                # prefill, no second TTFT
                self.engine.manager.reattach(
                    slot, req.kept_pages, req.resume_length
                )
                req.kept_pages = None
                req.paused = False
                req.status = RUNNING
                _metrics.counter("serve.resumed")
                if req.trace is not None:
                    s = _tracing.start_span(
                        "serve.resume", req.trace, path="reattach",
                        slot=slot,
                    )
                    if s is not None:
                        s.end()
            elif req.ingest is not None:
                # KV-transfer ingest: foreign pages land in the pool
                # and pointer-attach — data changes, shapes don't, so
                # this admission path never retraces (decode_compiles
                # stays 1 across streamed admissions)
                ing = req.ingest
                kept = self.engine.ingest_attach(
                    slot, ing["logical"], ing["arrays"],
                    ing["length"], ing["hashes"],
                )
                if kept is None:
                    # pool raced dry between the gate and the alloc
                    # (reserve TTL expiry, prefix churn): put the head
                    # back and stop admitting this round
                    self.engine.manager.free(slot)
                    with self._cond:
                        self._queue.appendleft(req)
                    break
                if ing.get("sample"):
                    # migrated resume: import the sender's armed
                    # sampling snapshot (raw mid-stream key) verbatim —
                    # the common arming block below would re-seed and
                    # fork the sampled sequence
                    self.engine.import_sampling(slot, ing["sample"])
                    sample_armed = True
                npages = len(ing["logical"])
                req.ingest = None
                req.status = RUNNING
                _metrics.counter("serve.transfer_admits")
                _metrics.counter("serve.tokens_out")
                if req.trace is not None:
                    s = _tracing.start_span(
                        "serve.ingest_admit", req.trace, pages=npages,
                        slot=slot, migrated=bool(sample_armed),
                    )
                    if s is not None:
                        s.end()
            else:
                if req.paused and req.out_tokens:
                    # pages were reclaimed while paused: rebuild the
                    # slot by re-prefilling prompt + generated-so-far
                    # (the prefix cache usually makes this cheap); the
                    # emitted token is discarded — the real newest
                    # token is fed to the next decode step
                    pspan = _tracing.start_span(
                        "serve.prefill", req.trace, resume=True,
                        slot=slot,
                    )
                    self.engine.prefill(
                        slot, self._resume_seq(req), trace=req.trace
                    )
                    if pspan is not None:
                        pspan.end()
                    req.paused = False
                    req.status = RUNNING
                    _metrics.counter("serve.resumed")
                else:
                    reservation = None
                    if (
                        self.role == "prefill"
                        and self.transfer is not None
                        and req.max_new_tokens > 1
                    ):
                        # reserve decode capacity BEFORE spending the
                        # prefill — a prefill whose pages have nowhere
                        # to go is work wasted (docs/serving.md
                        # reservation protocol)
                        need = self.engine.manager.pages_needed(
                            int(req.prompt.size) + req.max_new_tokens
                        )
                        reservation = self.transfer.reserve(
                            need, trace=req.trace
                        )
                        if reservation is None:
                            # no decode capacity anywhere: the unified
                            # path — decode locally (this role compiles
                            # its decode table lazily, only here)
                            _metrics.counter("serve.transfer_local")
                    pspan = _tracing.start_span(
                        "serve.prefill", req.trace,
                        prompt_len=int(req.prompt.size), slot=slot,
                    )
                    first = self.engine.prefill(
                        slot, req.prompt, trace=req.trace
                    )
                    req.status = RUNNING
                    req.ttft_ms = (time.monotonic() - req.submitted) * 1e3
                    req.out_tokens.append(int(first))
                    if pspan is not None:
                        pspan.end(ttft_ms=round(req.ttft_ms, 3))
                    self.recorder.record_ttft(
                        req.ttft_ms,
                        req.trace.trace_id if req.trace else "",
                    )
                    _metrics.counter(
                        "serve.prefill_tokens", int(req.prompt.size)
                    )
                    _metrics.counter("serve.tokens_out")
                    if reservation is not None:
                        # hand the finished pages to the transfer
                        # coordinator: detach_keep frees the slot (the
                        # refcounts move to the handoff), the stream +
                        # result-wait run off-thread, and this worker's
                        # decode plane never sees the request
                        kept, length = self.engine.manager.detach_keep(
                            slot
                        )
                        with self._cond:
                            self._handoffs += 1
                        self.transfer.start_handoff(
                            self, req, kept, length, reservation
                        )
                        admitted += 1
                        continue
            if mid_decode:
                # counted for every admission path — fresh prefill,
                # reprefill-resume AND pointer reattach-resume alike
                _metrics.counter("serve.admitted_mid_decode")
            admitted += 1
            # arm the slot's sampling knobs for every admission path
            # (fresh, resume, ingest): data writes, never a retrace —
            # except a migrated resume, whose imported key already IS
            # the armed state
            if sample_armed:
                pass
            elif req.temperature > 0 or req.top_k > 0:
                self.engine.set_sampling(
                    slot, req.temperature, req.top_k,
                    seed=req.id if req.seed is None else req.seed,
                )
            else:
                self.engine.clear_sampling(slot)
            if req.trace is not None and req.span is None:
                # admit→retire lifecycle span: opened ONCE (survives
                # pause/resume cycles), closed by _retire/_abort_all —
                # no per-decode-step tracing work happens inside it
                req.span = _tracing.start_span(
                    "serve.decode", req.trace, slot=slot,
                )
            self._slot_req[slot] = req
            if self._req_complete(req, now):
                self._retire(slot, req)
        return admitted

    def _pause_youngest(self, now: float) -> bool:
        """Pool-exhaustion remedy: take the youngest running request
        out of its slot and re-queue it (front), keeping its pages for
        a pointer-cheap resume. A request already past its deadline
        expires instead (its pages free immediately). Returns False
        when there is no second request to pause."""
        if len(self._slot_req) < 2:
            return False
        slot, req = max(
            self._slot_req.items(), key=lambda kv: kv[1].admit_seq
        )
        self._slot_req.pop(slot)
        mgr = self.engine.manager
        if req.deadline_ts is not None and now >= req.deadline_ts:
            mgr.free(slot)
            req.status = DEADLINE
            req._done.set()
            _metrics.counter("serve.expired")
            return True
        req.kept_pages, req.resume_length = mgr.detach_keep(slot)
        req.paused = True
        req.status = QUEUED
        with self._cond:
            self._queue.appendleft(req)
        _metrics.counter("serve.paused")
        if req.trace is not None:
            s = _tracing.start_span(
                "serve.pause", req.trace, slot=slot,
                kept_pages=len(req.kept_pages),
            )
            if s is not None:
                s.end()
        _log.debug(
            "page pool exhausted: paused request %d (kept %d pages)",
            req.id, len(req.kept_pages),
        )
        return True

    def _reclaim_paused_pages(self) -> bool:
        """Last-resort page source: drop the kept pages of a paused
        request so an older in-flight one can take its next page.
        Deadline-aware: the victim is the paused holder with the LEAST
        deadline headroom (most likely to expire unserved anyway);
        holders with no deadline are spared longest. The victim stays
        queued — it re-prefills on resume."""
        if self.role == "decode":
            # a decode-role worker has no prefill executables: dropped
            # pages could never be rebuilt, so kept holds are pinned —
            # pause (pointer resume) remains the only remedy here
            return False
        with self._cond:
            holders = [r for r in self._queue if r.kept_pages]
        if not holders:
            return False
        victim = min(
            holders,
            key=lambda r: (
                r.deadline_ts is None,
                r.deadline_ts if r.deadline_ts is not None else 0.0,
            ),
        )
        self.engine.manager.release_kept(victim.kept_pages)
        victim.kept_pages = None
        _metrics.counter("serve.paused_pages_reclaimed")
        return True

    def _make_decodable(self, now: float) -> None:
        """Run the pre-decode page sweep until every remaining slot
        can take its next token, pausing the youngest request (then
        reclaiming paused holds) as needed — graceful degradation, the
        step itself never sees exhaustion."""
        # bounded: each round pauses a request or reclaims one holder
        for _ in range(self.engine.slots + len(self._queue) + 2):
            if not self.engine.prepare_decode():
                return
            if self._pause_youngest(now):
                continue
            if self._reclaim_paused_pages():
                continue
            # a single in-flight request, nothing left to reclaim:
            # unreachable when the pool admits only what fits
            # (submit's can-never-fit gate), but never silent
            raise PagePoolExhausted(
                list(self.engine.prepare_decode())
            )

    def _decode(self, now: float) -> bool:
        if not self._slot_req:
            return False
        if self.engine.paged:
            self._make_decodable(now)
            if not self._slot_req:
                return False
        tokens = np.zeros(self.engine.slots, np.int32)
        for slot, req in self._slot_req.items():
            tokens[slot] = req.out_tokens[-1]
        hub = None
        if _telemetry.auto_enabled():
            hub = _telemetry.hub()
            hub.step_begin(self._decode_steps)
        t0 = time.monotonic()
        nxt = self.engine.decode_step(tokens)
        step_ms = (time.monotonic() - t0) * 1e3
        self._decode_steps += 1
        now = time.monotonic()
        for slot, req in list(self._slot_req.items()):
            self.engine.manager.advance(slot)
            req.out_tokens.append(int(nxt[slot]))
            req.gen_ms = (now - req.submitted) * 1e3 - req.ttft_ms
            self.recorder.record_tpot(
                step_ms, req.trace.trace_id if req.trace else ""
            )
            _metrics.counter("serve.tokens_out")
            if self._req_complete(req, now):
                self._retire(slot, req)
        if hub is not None:
            # close AFTER the per-token bookkeeping so the record's
            # serve.* deltas carry this step's tokens
            hub.step_end()
        return True

    def _req_complete(self, req: Request, now: float) -> bool:
        if req.deadline_ts is not None and now >= req.deadline_ts:
            req.status = DEADLINE
            return True
        if len(req.out_tokens) >= req.max_new_tokens:
            return True
        if self.eos_id is not None and req.out_tokens[-1] == self.eos_id:
            return True
        return False

    def _retire(self, slot: int, req: Request) -> None:
        self.engine.manager.free(slot)
        self.engine.clear_sampling(slot)
        self._slot_req.pop(slot, None)
        if req.status == DEADLINE:
            _metrics.counter("serve.expired")
        else:
            req.status = DONE
            _metrics.counter("serve.completed")
        if req.span is not None:
            req.span.end(
                outcome=req.status, tokens=len(req.out_tokens),
                steps=self._decode_steps,
            )
            req.span = None
        req._done.set()

    # --------------------------------------------------------------- stats

    @property
    def draining(self) -> bool:
        """True once no new work is accepted — set by drain() or by the
        scheduler-crash handler. The frontend folds this into its own
        draining state (503s, /healthz, the KV announcement), so a
        crashed batcher is visibly drained fleet-wide, not a 429-ing
        blackhole the Router keeps preferring."""
        return self._draining

    def queue_depth(self) -> int:
        with self._cond:
            return len(self._queue)

    def active(self) -> int:
        return len(self._slot_req)

    def stats(self) -> Dict[str, float]:
        out = {
            "queue_depth": self.queue_depth(),
            "decode_steps": self._decode_steps,
            "draining": 1.0 if self._draining else 0.0,
            "handoffs_inflight": float(self._handoffs),
        }
        out.update(self.engine.manager.stats())
        return out

    def _publish_gauges(self, min_interval: float = 0.25) -> None:
        """Registry gauge refresh, rate-limited off the decode hot path
        (recorder.publish sorts the latency rings — O(capacity log
        capacity) per call has no business running per token; the serve
        port's /metrics renders its summaries live regardless, so only
        scrape-side registry staleness is bounded by the interval)."""
        now = time.monotonic()
        if now - self._last_publish < min_interval:
            return
        self._last_publish = now
        _metrics.update("serve", self.stats())
        self.engine.publish()
        self.recorder.publish()
