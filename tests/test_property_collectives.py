"""Property-based collective tests (hypothesis): random shapes, dtypes
and values against numpy oracles — the randomized complement to the
closed-form op matrix (ref: the reference's test grids are exhaustive
but fixed-value; SURVEY.md §4.1). Also checks the Adasum invariants the
reference documents (scale behavior, agreement across ranks)."""

import jax.numpy as jnp
import numpy as np
import pytest

pytest.importorskip("hypothesis")
from hypothesis import HealthCheck, given, settings, strategies as st

# the hvd fixture is stable across examples (module-level init); not
# resetting it between generated inputs is exactly what we want
_SETTINGS = dict(
    max_examples=10,
    deadline=None,
    suppress_health_check=[HealthCheck.function_scoped_fixture],
)

WORLD = 8

shapes = st.lists(
    st.integers(min_value=1, max_value=5), min_size=1, max_size=3
).map(tuple)


def _payload(rng_seed, shape, dtype=np.float32):
    rng = np.random.default_rng(rng_seed)
    return np.stack(
        [
            rng.normal(size=shape).astype(dtype) * (r + 1)
            for r in range(WORLD)
        ]
    )


@settings(**_SETTINGS)
@given(shape=shapes, seed=st.integers(0, 2**16))
def test_allreduce_sum_matches_numpy(hvd, shape, seed):
    x = _payload(seed, shape)
    out = np.asarray(hvd.allreduce(jnp.asarray(x), op=hvd.Sum))
    np.testing.assert_allclose(out[0], x.sum(0), rtol=2e-5, atol=1e-5)
    # every rank agrees
    for r in range(1, WORLD):
        np.testing.assert_array_equal(out[r], out[0])


@settings(**_SETTINGS)
@given(shape=shapes, seed=st.integers(0, 2**16),
       root=st.integers(0, WORLD - 1))
def test_broadcast_matches_root(hvd, shape, seed, root):
    x = _payload(seed, shape)
    out = np.asarray(hvd.broadcast(jnp.asarray(x), root_rank=root))
    for r in range(WORLD):
        np.testing.assert_array_equal(out[r], x[root])


@settings(**_SETTINGS)
@given(rows=st.integers(1, 4), cols=st.integers(1, 5),
       seed=st.integers(0, 2**16))
def test_allgather_concat_matches_numpy(hvd, rows, cols, seed):
    x = _payload(seed, (rows, cols))
    out = np.asarray(hvd.allgather(jnp.asarray(x)))
    flat = out[0].reshape(WORLD * rows, cols)
    np.testing.assert_allclose(flat, x.reshape(WORLD * rows, cols))


@settings(**_SETTINGS)
@given(seed=st.integers(0, 2**16), scale=st.floats(0.25, 8.0))
def test_adasum_positive_homogeneous(hvd, seed, scale):
    """Adasum(s·g1..s·gN) == s·Adasum(g1..gN) — the scale-invariance
    the reference's docs claim for the combiner (positive scales)."""
    x = _payload(seed, (6,))
    a = np.asarray(hvd.allreduce(jnp.asarray(x), op=hvd.Adasum))[0]
    b = np.asarray(
        hvd.allreduce(jnp.asarray(x * scale), op=hvd.Adasum)
    )[0]
    np.testing.assert_allclose(b, scale * a, rtol=5e-4, atol=1e-5)


@settings(**_SETTINGS)
@given(seed=st.integers(0, 2**16))
def test_reducescatter_then_allgather_is_allreduce(hvd, seed):
    """Composition law: reduce-scatter followed by all-gather of the
    shards reproduces the allreduce result (the two halves of the
    ring)."""
    x = _payload(seed, (WORLD * 2, 3))
    rs = hvd.reducescatter(jnp.asarray(x), op=hvd.Sum)
    gathered = np.asarray(hvd.allgather(rs))
    full = np.asarray(hvd.allreduce(jnp.asarray(x), op=hvd.Sum))
    np.testing.assert_allclose(
        gathered[0].reshape(full[0].shape), full[0], rtol=2e-5, atol=1e-5
    )
