"""Data-parallel training with ZeRO-1 sharded weight update.

Beyond the reference's surface (Horovod keeps optimizer state fully
replicated on every rank): ``ShardedDistributedOptimizer``
reduce-scatters gradients, updates a 1/N shard of the optimizer state
per rank, and all-gathers the parameter updates — the same wire bytes
as the reference's ring allreduce with 1/N of the optimizer memory
(docs/design.md "Long-context & multi-axis parallelism").

Run (8-way CPU simulation):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python examples/zero1_data_parallel.py
Run (TPU slice): no flags; the world mesh spans the slice.
"""

import os
from functools import partial

# Mirror the sibling examples: default to an 8-device simulated mesh
# when the caller hasn't chosen a device count (must precede jax init;
# APPEND to any existing XLA_FLAGS — tests/conftest.py pattern).
if "xla_force_host_platform_device_count" not in os.environ.get(
    "XLA_FLAGS", ""
) and os.environ.get("JAX_PLATFORMS") == "cpu":
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax

if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models import MNISTConvNet


def main():
    hvd.init()
    mesh = hvd.mesh()
    world = hvd.size()
    model = MNISTConvNet()

    sample = jnp.zeros((16, 28, 28, 1), jnp.float32)
    variables = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        sample,
    )
    params = variables["params"]

    opt = hvd.ShardedDistributedOptimizer(optax.adamw(1e-3))
    opt_state = opt.init(params)  # every leaf: [world, shard] — 1/N per rank

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(), opt.state_spec(), P(hvd.WORLD_AXIS),
                  P(hvd.WORLD_AXIS)),
        out_specs=(P(), opt.state_spec(), P()),
        check_vma=False,
    )
    def train_step(params, opt_state, images, labels):
        images, labels = images[0], labels[0]

        def loss_fn(p):
            logits = model.apply(
                {"params": p}, images,
                rngs={"dropout": jax.random.PRNGKey(2)},
            )
            return optax.softmax_cross_entropy_with_integer_labels(
                logits, labels
            ).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, jax.lax.pmean(loss, hvd.WORLD_AXIS)

    step = jax.jit(train_step)
    rng = np.random.default_rng(0)
    for it in range(20):
        images = jnp.asarray(
            rng.normal(size=(world, 16, 28, 28, 1)), jnp.float32
        )
        labels = jnp.asarray(
            rng.integers(0, 10, size=(world, 16)), jnp.int32
        )
        params, opt_state, loss = step(params, opt_state, images, labels)
        if hvd.rank() == 0 and it % 5 == 0:
            print(f"step {it}: loss {float(loss):.4f}")

    n_state = sum(
        leaf[0].size for leaf in jax.tree_util.tree_leaves(opt_state)
        if leaf.ndim > 1
    )
    n_params = sum(p.size for p in jax.tree_util.tree_leaves(params))
    if hvd.rank() == 0:
        print(
            f"done. per-rank optimizer state {n_state} elems "
            f"vs {2 * n_params} replicated (adamw mu+nu) — "
            f"{world}x smaller"
        )


if __name__ == "__main__":
    main()
