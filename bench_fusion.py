"""Eager-dispatch fusion microbenchmark — the measurement behind the
core-runtime redesign's premise (VERDICT r4 Weak #2 / item 3).

`ops/fusion.py` exists because "many small eager collectives are slow
if dispatched one XLA executable each" (module header; ref:
fusion_buffer_manager.cc, parameter_manager.cc semantics [V]). This
harness measures that claim directly, on whatever backend is present:

  * unfused — threshold=1 byte: every enqueue flushes a single-entry
    batch → N executable launches per step (the no-fusion world).
  * fused — threshold > N·bytes: one flush concatenates all N entries
    into one [world, total] buffer → ONE launch per step.
  * traced — one jit'd shard_map psum over the same total bytes: the
    floor (no queue, no scatter-back, no per-entry Python).
  * autotune — `common/autotune.py`'s BayesianOptimizer proposes
    (threshold, cycle) pairs against the same workload; the run shows
    whether the GP's pick beats the shipped defaults.

A/B legs for the compile-fused rework (ISSUE 1), each emitting one
JSON artifact under BENCH_ARTIFACT_DIR (default bench_results/fusion):

  * ab_pack      — host-side pack (pre-rework dispatch) vs in-JIT
                   pack/unpack (one donated executable per batch).
  * ab_bucketing — drifting batch compositions with power-of-two
                   bucketing on vs off; reports executor recompiles,
                   bucket-tier hits and pad bytes alongside ms/step.
  * ab_gather    — same-key broadcast+allgather+reducescatter groups
                   fused through the batch machinery vs dispatched
                   serially (threshold=1).

Per mode prints one JSON line:
  {"metric": "eager_fusion", "mode": ..., "n_tensors": N,
   "bytes_each": B, "value": ms/step, "unit": "ms"}
then a speedup summary and the autotune verdict line.

Env: BENCH_FUSION_N (default 200), BENCH_FUSION_BYTES (default 1 MiB),
BENCH_ITERS (default 10), BENCH_AUTOTUNE_TRIALS (default 10, 0 = skip),
BENCH_PLATFORM=cpu for the simulated mesh (sim lines carry the
quarantine note — dispatch overhead on CPU validates logic only),
BENCH_DRYRUN=1 for the CI smoke configuration (tiny sizes, A/B legs
only exercised for correctness of the harness itself),
BENCH_ARTIFACT_DIR for the per-leg JSON artifact directory.
"""

import json
import os
import time

from _benchlib import stamp as _stamp

_SIM_NOTE = (
    "logic-validation only (CPU simulation); NOT a TPU dispatch number"
)


def main():
    import jax

    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    import horovod_tpu as hvd
    from _benchlib import sync as _sync
    from horovod_tpu.common import basics
    from horovod_tpu.common.topology import WORLD_AXIS
    from horovod_tpu.ops import traced

    dryrun = os.environ.get("BENCH_DRYRUN", "").strip() in ("1", "true")
    if dryrun:
        n_tensors = int(os.environ.get("BENCH_FUSION_N", "8"))
        nbytes = int(os.environ.get("BENCH_FUSION_BYTES", "4096"))
        iters = int(os.environ.get("BENCH_ITERS", "2"))
        trials = int(os.environ.get("BENCH_AUTOTUNE_TRIALS", "0"))
    else:
        n_tensors = int(os.environ.get("BENCH_FUSION_N", "200"))
        nbytes = int(os.environ.get("BENCH_FUSION_BYTES", str(1 << 20)))
        iters = int(os.environ.get("BENCH_ITERS", "10"))
        trials = int(os.environ.get("BENCH_AUTOTUNE_TRIALS", "10"))
    n_elems = max(nbytes // 4, 1)
    artifact_dir = os.environ.get(
        "BENCH_ARTIFACT_DIR", os.path.join("bench_results", "fusion")
    )
    os.makedirs(artifact_dir, exist_ok=True)

    hvd.init()
    fusion = basics._state.fusion
    world = hvd.size()
    platform = jax.devices()[0].platform
    mesh = hvd.mesh()

    default_threshold = fusion.threshold_bytes
    default_cycle = fusion.cycle_time_ms
    default_injit = fusion.injit_pack
    default_bucketing = fusion.bucketing

    rng = np.random.default_rng(0)
    # Host arrays on purpose: the eager layer stages numpy to FRESH
    # device buffers, so the donation path (default-on for TPU/GPU)
    # can never consume a buffer a later leg still holds. jnp inputs
    # here would be donated/deleted by the first run_eager and crash
    # every subsequent leg on aliasing backends.
    bufs0 = [
        rng.normal(size=(world, n_elems)).astype(np.float32)
        for _ in range(n_tensors)
    ]

    def eager_step(bufs):
        handles = [
            hvd.allreduce_async(b, op=hvd.Average, name=f"t{i}")
            for i, b in enumerate(bufs)
        ]
        return [h.wait() for h in handles]

    def run_eager(threshold, cycle_ms):
        fusion.threshold_bytes = int(threshold)
        fusion.cycle_time_ms = float(cycle_ms)
        bufs = eager_step(list(bufs0))  # warm: compile executors
        bufs = eager_step(bufs)  # warm again on committed outputs
        _sync(sum(jnp.sum(b) for b in bufs))
        t0 = time.perf_counter()
        for _ in range(iters):
            bufs = eager_step(bufs)
        _sync(sum(jnp.sum(b) for b in bufs))
        return (time.perf_counter() - t0) / iters * 1e3  # ms/step

    def emit(mode, ms, extra=None, leg=None):
        line = {
            "metric": "eager_fusion",
            "mode": mode,
            "n_tensors": n_tensors,
            "bytes_each": nbytes,
            "world": world,
            "value": round(ms, 3),
            "unit": "ms",
            "platform": platform,
        }
        if extra:
            line.update(extra)
        if platform != "tpu":
            line["note"] = _SIM_NOTE
        print(json.dumps(_stamp(line)), flush=True)
        if leg:
            with open(
                os.path.join(artifact_dir, f"fusion_{leg}.json"), "a"
            ) as f:
                f.write(json.dumps(_stamp(line)) + "\n")
        return ms

    total = n_tensors * nbytes
    ms_unfused = emit("unfused", run_eager(1, 1e9))
    ms_fused = emit("fused", run_eager(total * 2, 1e9))
    ms_default = emit(
        "default",
        run_eager(default_threshold, default_cycle),
        {"threshold": default_threshold, "cycle_ms": default_cycle},
    )

    # ---- A/B leg 1: host-side pack vs in-JIT pack/unpack -------------
    fusion.injit_pack = False
    ms_hostpack = emit(
        "host_pack", run_eager(total * 2, 1e9), leg="ab_pack"
    )
    fusion.injit_pack = True
    ms_injit = run_eager(total * 2, 1e9)
    emit(
        "injit_pack",
        ms_injit,
        {
            "speedup_vs_host_pack": round(ms_hostpack / ms_injit, 3),
            "donate": fusion.donate,
        },
        leg="ab_pack",
    )

    # ---- A/B leg 2: shape bucketing under composition churn ---------
    # Workload: every step reshapes the SAME bytes into a different
    # composition (rotating split points), the drifting-tensor-set case
    # the bucket tier exists for. Without bucketing each composition
    # would need its own executable; with it they share one bucket.
    churn_steps = 4 if dryrun else 8
    churn_elems = n_elems * 4

    def churn_compositions():
        comps = []
        for s in range(churn_steps):
            # drift both the split point AND the total (staying inside
            # one power-of-two bucket) — the realistic "tensor set
            # changes a little every cycle" shape
            total = churn_elems - s * max(churn_elems // 64, 1)
            a = (s + 1) * total // (churn_steps + 1)
            comps.append([max(a, 1), max(total - a, 1)])
        return comps

    def run_churn():
        comps = churn_compositions()
        # warm one composition so the bucket exists
        for sizes in comps[:1]:
            for h in [
                hvd.allreduce_async(
                    jnp.ones((world, n), jnp.float32), op=hvd.Average
                )
                for n in sizes
            ]:
                h.wait()
        m0, b0, p0 = (
            fusion.cache_misses,
            fusion.bucket_hits,
            fusion.pad_bytes_total,
        )
        t0 = time.perf_counter()
        for sizes in comps:
            handles = [
                hvd.allreduce_async(
                    jnp.ones((world, n), jnp.float32), op=hvd.Average
                )
                for n in sizes
            ]
            _sync(sum(jnp.sum(h.wait()) for h in handles))
        ms = (time.perf_counter() - t0) / len(comps) * 1e3
        return ms, {
            "recompiles": fusion.cache_misses - m0,
            "bucket_hits": fusion.bucket_hits - b0,
            "pad_bytes": fusion.pad_bytes_total - p0,
            "compositions": len(comps),
        }

    fusion.threshold_bytes = 1 << 40
    fusion.cycle_time_ms = 1e9
    fusion.bucketing = True
    ms, extra = run_churn()
    emit("bucketing_on", ms, extra, leg="ab_bucketing")
    fusion.bucketing = False
    ms, extra = run_churn()
    emit("bucketing_off", ms, extra, leg="ab_bucketing")
    fusion.bucketing = default_bucketing

    # ---- A/B leg 3: gather-family fusion vs serial dispatch ---------
    gather_n = 4 if dryrun else 16
    g_elems = max(n_elems // 4, world)
    g_elems -= g_elems % world  # reducescatter divisibility
    # Host arrays (see bufs0): each buffer feeds THREE collectives per
    # step AND every timed iteration — a jnp.Array here would be
    # donated by the first fused executable and crash the second.
    g_bufs = [
        np.ones((world, max(g_elems, world)), np.float32)
        for _ in range(gather_n)
    ]

    def gather_step():
        hs = [
            hvd.broadcast_async(b, root_rank=0, name=f"gb{i}")
            for i, b in enumerate(g_bufs)
        ]
        hs += [
            hvd.allgather_async(b, name=f"ga{i}")
            for i, b in enumerate(g_bufs)
        ]
        hs += [
            hvd.reducescatter_async(b, op=hvd.Sum, name=f"gr{i}")
            for i, b in enumerate(g_bufs)
        ]
        outs = [h.wait() for h in hs]
        return outs[0]

    def run_gather(threshold):
        fusion.threshold_bytes = int(threshold)
        fusion.cycle_time_ms = 1e9
        gather_step()  # warm
        d0 = fusion.dispatches
        t0 = time.perf_counter()
        for _ in range(iters):
            out = gather_step()
        _sync(jnp.sum(out))
        ms = (time.perf_counter() - t0) / iters * 1e3
        return ms, {"dispatches_per_step": (fusion.dispatches - d0) // iters}

    ms, extra = run_gather(1 << 40)
    emit("gather_fused", ms, extra, leg="ab_gather")
    ms, extra = run_gather(1)
    emit("gather_serial", ms, extra, leg="ab_gather")

    # traced floor: ONE psum over the same bytes, chained for sync
    from functools import partial

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=P(WORLD_AXIS),
        out_specs=P(WORLD_AXIS),
        check_vma=False,
    )
    def reduce(x):
        return traced.allreduce(x[0], op=hvd.Average)[None]

    step = jax.jit(reduce)
    x = jnp.ones((world, n_tensors * n_elems), jnp.float32)
    x = step(step(x))
    _sync(x)
    t0 = time.perf_counter()
    for _ in range(iters):
        x = step(x)
    _sync(x)
    ms_traced = emit(
        "traced", (time.perf_counter() - t0) / iters * 1e3
    )

    line = {
        "metric": "eager_fusion_speedup",
        "value": round(ms_unfused / ms_fused, 3),
        "unit": "x",
        "unfused_ms": round(ms_unfused, 3),
        "fused_ms": round(ms_fused, 3),
        "traced_ms": round(ms_traced, 3),
        "world": world,
        "platform": platform,
    }
    if platform != "tpu":
        line["note"] = _SIM_NOTE
    print(json.dumps(_stamp(line)), flush=True)

    if trials > 0:
        from horovod_tpu.common.autotune import BayesianOptimizer

        bo = BayesianOptimizer(seed=0)
        # seed the GP with the three corners already measured
        bo.observe(1, 1e3, -ms_unfused)
        bo.observe(total * 2, 1e3, -ms_fused)
        bo.observe(default_threshold, default_cycle, -ms_default)
        for _ in range(trials):
            thr, cyc = bo.suggest()
            bo.observe(thr, cyc, -run_eager(thr, cyc))
        (best_thr, best_cyc) = bo.best()
        ms_best = run_eager(best_thr, best_cyc)
        line = {
            "metric": "fusion_autotune",
            "threshold": int(best_thr),
            "cycle_ms": round(float(best_cyc), 3),
            "value": round(ms_best, 3),
            "unit": "ms",
            "default_ms": round(ms_default, 3),
            "default_threshold": default_threshold,
            "trials": trials,
            "world": world,
            "platform": platform,
        }
        if platform != "tpu":
            line["note"] = _SIM_NOTE
        print(json.dumps(_stamp(line)), flush=True)

    # restore shipped defaults (harmless — process exits anyway)
    fusion.threshold_bytes = default_threshold
    fusion.cycle_time_ms = default_cycle
    fusion.injit_pack = default_injit
    fusion.bucketing = default_bucketing


if __name__ == "__main__":
    main()
