"""Native (C++) runtime components, loaded over ctypes.

The reference ships its core as a C++ shared library bound into Python
(ref: horovod/common/basics.py loading libhorovod over ctypes [V] —
SURVEY.md §2.4); this package is that layer for the TPU rebuild. The
sources live in ``csrc/`` and build into ``libhvd_native.so`` on first
use (g++ is assumed present, as cmake is for the reference). Everything
here degrades gracefully: if the toolchain or library is unavailable,
callers fall back to pure-Python implementations.
"""

from . import loader  # noqa: F401
