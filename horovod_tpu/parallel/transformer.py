"""The flagship distributed model: a causal transformer trained with
dp x pp x ep x sp x tp parallelism composed over one mesh.

This is the survey build-plan's "exceed parity" layer (SURVEY.md §2.6):
the reference stops at data parallelism; here every axis of
horovod_tpu/parallel/ composes in one SPMD program:

- dp: batch sharded; gradients pmean'd across ('dp','sp') — the
  reference's entire job, one psum here.
- pp: layers split into stages, GPipe microbatch schedule (pipeline.py).
- sp: sequence sharded; exact attention via ring_attention (ppermute ring).
- tp: heads + FFN sharded Megatron-style (tp.py), one psum per block.
- ep: a switch-MoE FFN block after the pipelined stack, tokens routed
  across 'ep' with all_to_all (moe.py).

Everything is per-device code executed under one
``jit(shard_map(step, mesh, ...))`` — XLA sees every collective and
schedules them against compute on ICI.

Data layout: the batch is sharded over ('dp','ep') — the 'ep' axis acts
as additional data parallelism for the dense layers, and the MoE block's
all_to_all then routes each shard's tokens to their experts across 'ep'
(so expert parallelism splits real tokens, not replicas); the sequence is
sharded over 'sp'.

Gradient synchronization (``_sync_grads``) follows one rule derived from
shard_map's transpose semantics (each device's loss output is seeded with
cotangent 1, and every psum/all_to_all edge transposes to a psum of
cotangents, multiplying the upstream cotangent by the replica count):

    for each parameter leaf with partition spec S:
      g ← pmean(g, every mesh axis NOT in S)   # combines per-shard
                                               # partials; replicated-path
                                               # contributions are equal
                                               # so pmean keeps them 1x
      g ← g / Π(size of axes in S ∩ {pp, ep, tp})
           # sharded-axis params received their cotangent through a
           # collective edge once per replica of the downstream loss —
           # uniform over-count by exactly that axis size

This is validated numerically: one train step produces identical
parameters on every mesh factorization (tests/test_parallel.py's
cross-mesh equivalence test).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..common.compat import shard_map
from .moe import MoEParams, init_moe_params, moe_ffn
from .pipeline import gpipe, pipeline_1f1b
from .ring_attention import ring_attention, ring_flash_attention
from .tp import column_parallel_dense, row_parallel_dense


@dataclasses.dataclass(frozen=True)
class ParallelTransformerConfig:
    vocab_size: int = 256
    num_layers: int = 4  # total; must divide by pp
    d_model: int = 64
    num_heads: int = 4  # must divide by tp
    d_ff: int = 128  # must divide by tp
    max_len: int = 128
    n_experts: int = 4  # total; must divide by ep
    moe_capacity_factor: float = 2.0
    # Expert wire (PR 12, parallel/moe.py): dispatch/return format of
    # the MoE alltoall — None defers to HOROVOD_MOE_WIRE; "int8" rides
    # the block-scaled quantized wire (routing decisions are computed
    # on fp32 logits BEFORE the wire, so they are identical across
    # formats). moe_hier routes the exchange two-level (intra-ICI /
    # inter-DCN; None = the HOROVOD_HIERARCHICAL default decision,
    # "on"/"off" force it, or explicit (intra, inter) stages) — under
    # a split, moe_wire names the INTER hop and moe_intra_wire the
    # ICI legs.
    moe_wire: Any = None
    moe_intra_wire: Any = None
    moe_hier: Any = None
    n_microbatches: int = 2
    dtype: Any = jnp.float32
    learning_rate: float = 1e-2
    # SP attention engine. "auto": Pallas flash-block ring
    # (ring_flash_attention) on TPU when the local sequence shard is
    # flash-tileable, dense ring otherwise. True forces the flash ring
    # on any backend (interpret-mode kernels off-TPU — tests), False
    # forces the dense ring.
    flash_ring: Any = "auto"
    # Rotary position embeddings instead of the learned pos table: the
    # rotation offset is this shard's global start (axis_index("sp") *
    # t_local) — RoPE's relative form is what makes it compose with
    # sequence parallelism without any cross-shard exchange.
    rope: bool = False
    # Pipeline schedule for training. "1f1b" (default): the production
    # path — explicit per-stage backward inside the scan, activation
    # live-set bounded by pp (pipeline.pipeline_1f1b); the MoE+head
    # tail runs per-MICROBATCH (per-micro expert capacity). "gpipe":
    # differentiate through the fill/drain scan — checkpoints
    # O(n_micro) activations; demo/small-model path (VERDICT r4 #7).
    # The composed model runs pipeline_1f1b at virtual_stages=1:
    # Megatron-interleaved chunking needs the L axis pre-permuted so
    # P("pp") hands each device its STRIDED global stages (c*pp+s),
    # which would make the sharded param layout factorization-dependent
    # — use pipeline_1f1b(virtual_stages=...) directly for interleaved
    # custom stacks.
    pipeline_schedule: str = "1f1b"


Params = Dict[str, Any]


def _init_full_params(cfg: ParallelTransformerConfig, key) -> Params:
    """Full (unsharded) parameter pytree; sharding slices it per device."""
    d, f, h = cfg.d_model, cfg.d_ff, cfg.num_heads
    hd = d // h
    L, V = cfg.num_layers, cfg.vocab_size
    ks = jax.random.split(key, 8)
    s = 0.02
    dt = cfg.dtype
    params = {
        "embed": {
            "tok": (jax.random.normal(ks[0], (V, d)) * s).astype(dt),
            "pos": (jax.random.normal(ks[1], (cfg.max_len, d)) * s).astype(dt),
        },
        "stages": {
            # leading axis L: layer-stacked, later split into pp stages
            "ln1_scale": jnp.ones((L, d), dt),
            "ln1_bias": jnp.zeros((L, d), dt),
            "wqkv": (jax.random.normal(ks[2], (L, d, 3, h, hd)) * s).astype(dt),
            "wo": (jax.random.normal(ks[3], (L, h, hd, d)) * s).astype(dt),
            "ln2_scale": jnp.ones((L, d), dt),
            "ln2_bias": jnp.zeros((L, d), dt),
            "w1": (jax.random.normal(ks[4], (L, d, f)) * s).astype(dt),
            "b1": jnp.zeros((L, f), dt),
            "w2": (jax.random.normal(ks[5], (L, f, d)) * s).astype(dt),
            "b2": jnp.zeros((L, d), dt),
        },
        "tail": {
            "lnf_scale": jnp.ones((d,), dt),
            "lnf_bias": jnp.zeros((d,), dt),
            "lm_head": (jax.random.normal(ks[6], (d, V)) * s).astype(dt),
            "moe": init_moe_params(
                ks[7], d, f, cfg.n_experts, cfg.n_experts, dtype=dt
            ),
        },
    }
    return params


def param_specs(cfg: ParallelTransformerConfig) -> Params:
    """PartitionSpecs for every leaf: how the global pytree shards over
    the mesh axes (dp/pp/ep/sp/tp)."""
    return {
        "embed": {"tok": P(), "pos": P()},
        "stages": {
            "ln1_scale": P("pp"),
            "ln1_bias": P("pp"),
            "wqkv": P("pp", None, None, "tp", None),
            "wo": P("pp", "tp", None, None),
            "ln2_scale": P("pp"),
            "ln2_bias": P("pp"),
            "w1": P("pp", None, "tp"),
            "b1": P("pp", "tp"),
            "w2": P("pp", "tp", None),
            "b2": P("pp"),
        },
        "tail": {
            "lnf_scale": P(),
            "lnf_bias": P(),
            "lm_head": P(None, "tp"),  # vocab-parallel head (see loss)
            "moe": MoEParams(
                router=P(),
                w1=P("ep"),
                b1=P("ep"),
                w2=P("ep"),
                b2=P("ep"),
            ),
        },
    }


def make_sharded_params(
    cfg: ParallelTransformerConfig, mesh: Mesh, key
) -> Params:
    full = _init_full_params(cfg, key)
    specs = param_specs(cfg)
    return jax.tree_util.tree_map(
        lambda x, sp: jax.device_put(x, NamedSharding(mesh, sp)), full, specs
    )


def _layer_norm(x, scale, bias):
    xf = x.astype(jnp.float32)
    mu = xf.mean(-1, keepdims=True)
    var = ((xf - mu) ** 2).mean(-1, keepdims=True)
    return ((xf - mu) * lax.rsqrt(var + 1e-5) * scale + bias).astype(x.dtype)


def _block(layer_params, x, use_flash_ring=False, rope=False):
    """One transformer block, per-device view: heads/FFN tp-sharded,
    sequence sp-sharded (ring attention handles the full context)."""
    h = _layer_norm(x, layer_params["ln1_scale"], layer_params["ln1_bias"])
    qkv = jnp.einsum("btd,dchx->btchx", h, layer_params["wqkv"])
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # [B,T,H/tp,hd]
    if rope:
        from ..models.transformer import apply_rope

        offset = lax.axis_index("sp") * x.shape[1]
        q = apply_rope(q, offset=offset)
        k = apply_rope(k, offset=offset)
    attn_fn = ring_flash_attention if use_flash_ring else ring_attention
    attn = attn_fn(q, k, v, axis_name="sp", causal=True)
    proj = jnp.einsum("bthx,hxd->btd", attn, layer_params["wo"])
    x = x + lax.psum(proj, "tp")
    h = _layer_norm(x, layer_params["ln2_scale"], layer_params["ln2_bias"])
    h = column_parallel_dense(h, layer_params["w1"], layer_params["b1"])
    h = jax.nn.gelu(h)
    h = row_parallel_dense(h, layer_params["w2"], axis_name="tp")
    return x + h + layer_params["b2"]


def _resolve_flash_ring(cfg: "ParallelTransformerConfig", t_local: int):
    """Trace-time engine choice (backend + tileability are static).
    The auto gate also checks the per-hop backward VMEM budget — each
    ring hop runs the dK/dV kernel at the local length (ADVICE r4)."""
    import numpy as np

    from ..ops.flash_attention import fits_vmem, supports_seq

    if cfg.flash_ring == "auto":
        return (
            jax.default_backend() == "tpu"
            and supports_seq(t_local)
            and fits_vmem(
                t_local,
                cfg.d_model // cfg.num_heads,
                1,
                np.dtype(cfg.dtype).itemsize,
            )
        )
    return bool(cfg.flash_ring)


def _stage_fn(stage_params, x, use_flash_ring=False, rope=False):
    """Apply this pp stage's layer stack (scan over its layers)."""

    def body(h, layer):
        return _block(layer, h, use_flash_ring, rope), None

    out, _ = lax.scan(body, x, stage_params)
    return out


DATA_AXES = ("dp", "ep", "sp")  # batch over dp+ep, sequence over sp


def _embed(embed_params, tokens, cfg: ParallelTransformerConfig):
    """Token (+ learned position, unless RoPE) embedding. tokens:
    [B_local, T_local] -> [B_local, T_local, d]."""
    sp_idx = lax.axis_index("sp")
    t_local = tokens.shape[1]
    x = embed_params["tok"][tokens]
    if not cfg.rope:
        pos = embed_params["pos"][sp_idx * t_local + jnp.arange(t_local)]
        x = x + pos[None]
    return x


def _tail_loss(tail_params, x, labels, cfg: ParallelTransformerConfig):
    """MoE block + final norm + vocab-parallel cross-entropy over the
    stack's output. x: [B, T_local, d], labels: [B, T_local] -> scalar
    (LOCAL mean; data-axis reduction is the caller's)."""
    b, t_local = labels.shape
    # Expert-parallel MoE block (switch-style) + residual.
    flat = x.reshape(b * t_local, -1)
    x = x + moe_ffn(
        tail_params["moe"],
        flat,
        axis_name="ep",
        capacity_factor=cfg.moe_capacity_factor,
        wire=cfg.moe_wire,
        intra_wire=cfg.moe_intra_wire,
        hier=cfg.moe_hier,
    ).reshape(x.shape)

    x = _layer_norm(x, tail_params["lnf_scale"], tail_params["lnf_bias"])
    # Vocab-parallel cross-entropy (the Megatron-style tail; single-chip
    # analog: ops/fused_xent.py). The head is sharded over "tp" on its
    # vocabulary axis — each member computes only its (bt, V/tp) logit
    # shard and the softmax statistics cross the axis as two scalars
    # per token (pmax of the shard max, psum of the scaled expsum, psum
    # of the masked target logit). Full-vocab logits never exist on any
    # device, so head memory AND logit traffic scale down with tp.
    tp_idx = lax.axis_index("tp")
    head = tail_params["lm_head"]  # local shard: [d, V/tp]
    v_local = head.shape[1]
    logits = jnp.einsum(
        "btd,dv->btv", x.astype(jnp.float32), head.astype(jnp.float32)
    )
    # stop_gradient BEFORE pmax: the stability shift carries no
    # gradient, and pmax has no differentiation rule — a symbolically
    # zero tangent keeps autodiff from ever asking for one
    m = lax.pmax(jnp.max(lax.stop_gradient(logits), axis=-1), "tp")
    s = lax.psum(
        jnp.sum(jnp.exp(logits - m[..., None]), axis=-1), "tp"
    )
    lse = m + jnp.log(s)
    local = labels - tp_idx * v_local
    hit = (local >= 0) & (local < v_local)
    idx = jnp.clip(local, 0, v_local - 1)
    target = lax.psum(
        jnp.where(
            hit,
            jnp.take_along_axis(logits, idx[..., None], axis=-1)[..., 0],
            0.0,
        ),
        "tp",
    )
    return (lse - target).mean()


def _pick_n_micro(b_local: int, want: int) -> int:
    """Largest microbatch count <= want that divides the local batch
    (min(want, b_local) alone crashes the reshape when it doesn't
    divide, e.g. b_local=6, want=4)."""
    n = min(want, b_local)
    while b_local % n:
        n -= 1
    return n


def _forward_loss(params, tokens, labels, cfg: ParallelTransformerConfig):
    """Per-device forward + loss, GPipe schedule (differentiate-through;
    the 1F1B path in make_train_step never calls this). tokens/labels:
    [B_local, T_local]."""
    t_local = tokens.shape[1]
    x = _embed(params["embed"], tokens, cfg)

    # Pipeline over microbatches (batch split).
    b_local = x.shape[0]
    n_micro = _pick_n_micro(b_local, cfg.n_microbatches)
    xm = x.reshape(n_micro, b_local // n_micro, t_local, -1)
    use_flash_ring = _resolve_flash_ring(cfg, t_local)
    out = gpipe(
        functools.partial(
            _stage_fn, use_flash_ring=use_flash_ring, rope=cfg.rope
        ),
        params["stages"],
        xm,
        axis_name="pp",
    )
    # Output lives on the last pp stage; broadcast to all stages so the
    # tail (loss) is computed everywhere (keeps the program SPMD-uniform).
    pp = lax.axis_size("pp")
    stage = lax.axis_index("pp")
    out = lax.psum(jnp.where(stage == pp - 1, out, jnp.zeros_like(out)), "pp")
    x = out.reshape(b_local, t_local, -1)
    loss = _tail_loss(params["tail"], x, labels, cfg)
    return lax.pmean(loss, DATA_AXES)


def _spec_axes(spec) -> set:
    """Mesh axes a PartitionSpec shards over."""
    axes = set()
    for part in spec:
        if part is None:
            continue
        for a in part if isinstance(part, tuple) else (part,):
            axes.add(a)
    return axes


def _sync_grads(grads, specs, axis_sizes):
    """Per-leaf gradient synchronization (rule in module docstring)."""
    all_axes = tuple(axis_sizes)

    def one(g, spec):
        sharded = _spec_axes(spec)
        reduce_axes = tuple(a for a in all_axes if a not in sharded)
        if reduce_axes:
            g = lax.pmean(g, reduce_axes)
        div = 1
        for a in sharded & {"pp", "ep", "tp"}:
            div *= axis_sizes[a]
        if div != 1:
            g = g / div
        return g

    return jax.tree_util.tree_map(one, grads, specs)


def make_train_step(cfg: ParallelTransformerConfig, mesh: Mesh):
    """Build the jitted full train step over the mesh: forward, backward,
    gradient sync on every axis, SGD update. Returns step(params, tokens,
    labels) -> (params, loss)."""
    specs = param_specs(cfg)
    data_spec = P(("dp", "ep"), "sp")
    axis_sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    tp = axis_sizes.get("tp", 1)
    if cfg.vocab_size % tp:
        raise ValueError(
            f"vocab_size={cfg.vocab_size} must divide evenly over the "
            f"tp axis ({tp}) for the vocab-parallel head"
        )

    def _grads_gpipe(params, tokens, labels):
        return jax.value_and_grad(_forward_loss)(
            params, tokens, labels, cfg
        )

    def _grads_1f1b(params, tokens, labels):
        """Training grads via the bounded-memory 1F1B schedule: embed
        under jax.vjp in front, the stage stack inside pipeline_1f1b,
        the MoE+head tail as its parameterized loss (per-microbatch
        expert capacity). Local grads carry NO data-axis scaling —
        matching the gpipe path, where the trailing pmean contributes
        none either (JAX transposes psum to psum: the 1/n and the
        backward psum cancel, so the cotangent reaching the local loss
        is 1). _sync_grads then treats both paths identically."""
        t_local = tokens.shape[1]
        x, embed_vjp = jax.vjp(
            lambda ep: _embed(ep, tokens, cfg), params["embed"]
        )
        b_local = x.shape[0]
        n_micro = _pick_n_micro(b_local, cfg.n_microbatches)
        xm = x.reshape(n_micro, b_local // n_micro, t_local, -1)
        lm = labels.reshape(n_micro, b_local // n_micro, t_local)
        use_flash_ring = _resolve_flash_ring(cfg, t_local)
        loss, stage_grads, tail_grads, dxm = pipeline_1f1b(
            functools.partial(
                _stage_fn, use_flash_ring=use_flash_ring, rope=cfg.rope
            ),
            lambda tp_, y, tgt: _tail_loss(tp_, y, tgt, cfg),
            params["stages"],
            xm,
            lm,
            axis_name="pp",
            loss_params=params["tail"],
            return_dx=True,
        )
        # input cotangents live on stage 0; broadcast over pp so every
        # stage computes identical (replicated) embed grads
        stage = lax.axis_index("pp")
        dx = lax.psum(
            jnp.where(stage == 0, dxm, jnp.zeros_like(dxm)), "pp"
        ).reshape(b_local, t_local, -1)
        (embed_grads,) = embed_vjp(dx.astype(x.dtype))
        # pipeline_1f1b returns EXACT per-stage grads; _sync_grads
        # expects the gpipe-autodiff convention, where pp-sharded stage
        # grads arrive pp-inflated (the transpose of the output
        # broadcast psum sums identical cotangents from all pp members)
        # and are divided back. Convert so one sync rule serves both.
        pp = lax.axis_size("pp")
        stage_grads = jax.tree_util.tree_map(
            lambda g: g * pp, stage_grads
        )
        grads = {
            "embed": embed_grads,
            "stages": stage_grads,
            "tail": tail_grads,
        }
        return lax.pmean(loss, DATA_AXES), grads

    if cfg.pipeline_schedule not in ("1f1b", "gpipe"):
        raise ValueError(
            f"unknown pipeline_schedule {cfg.pipeline_schedule!r}"
        )
    # pp=1 has nothing to schedule: the gpipe path is then plain
    # differentiate-through with full-batch MoE capacity and no
    # per-stage recompute — keep that cost/numerics for non-pipelined
    # meshes (ADVICE: 1f1b at pp=1 would only add ~2x stage FLOPs and
    # per-microbatch expert capacity).
    grads_fn = (
        _grads_1f1b
        if cfg.pipeline_schedule == "1f1b" and axis_sizes.get("pp", 1) > 1
        else _grads_gpipe
    )

    def per_device_step(params, tokens, labels):
        loss, grads = grads_fn(params, tokens, labels)
        grads = _sync_grads(grads, specs, axis_sizes)
        params = jax.tree_util.tree_map(
            lambda p, g: p - cfg.learning_rate * g.astype(p.dtype),
            params,
            grads,
        )
        return params, loss

    mapped = shard_map(
        per_device_step,
        mesh=mesh,
        in_specs=(specs, data_spec, data_spec),
        out_specs=(specs, P()),
        check_vma=False,
    )
    return jax.jit(mapped)
