#!/usr/bin/env bash
# Post-capture chip work: Pallas kernel smokes + perf probes that need
# the real TPU. Chained after capture_remaining_r03.sh (never two TPU
# clients at once — docs/perf.md "chip-claim wedge").

set -uo pipefail
cd "$(dirname "$0")/.."
mkdir -p bench_results

# wait for the capture loop (if running) to release the chip
while pgrep -f capture_remaining_r03.sh >/dev/null 2>&1; do sleep 60; done

echo "=== pallas kernel smoke on real TPU" >&2
python - <<'EOF' > bench_results/pallas_smoke_r03.txt 2>&1
import numpy as np
import jax, jax.numpy as jnp
from horovod_tpu.ops import pallas_kernels as pk

rng = np.random.default_rng(0)
x = jnp.asarray(rng.normal(size=(1000, 257)).astype(np.float32))

y = pk.scale_cast(x, 2.5, jnp.bfloat16)
ref = (np.asarray(x, np.float32) * 2.5).astype(jnp.bfloat16)
assert np.allclose(np.asarray(y, np.float32), np.asarray(ref, np.float32), rtol=1e-2), "scale_cast"
print("scale_cast OK", y.dtype, y.shape)

vals, scale = pk.int8_quantize(x, seed=7)
deq = np.asarray(vals, np.float32) * float(scale)
err = np.abs(deq - np.asarray(x)).max()
assert err <= float(scale) * 1.01, ("int8 roundtrip err", err, float(scale))
print("int8_quantize OK maxerr/scale", err / float(scale))

a = jnp.asarray(rng.normal(size=(4096,)).astype(np.float32))
b = jnp.asarray(rng.normal(size=(4096,)).astype(np.float32))
got = np.asarray(pk.adasum_pair(a, b))
an, bn = np.asarray(a, np.float64), np.asarray(b, np.float64)
dot, asq, bsq = an @ bn, an @ an, bn @ bn
oracle = (1 - dot / (2 * asq)) * an + (1 - dot / (2 * bsq)) * bn
assert np.allclose(got, oracle, rtol=1e-4, atol=1e-5), "adasum_pair"
print("adasum_pair OK")
print("ALL PALLAS KERNELS PASS ON TPU")
EOF
tail -2 bench_results/pallas_smoke_r03.txt >&2

echo "=== driver-gate entry() compile check" >&2
python - <<'EOF' >&2
import jax
import __graft_entry__ as g
fn, args = g.entry()
out = jax.jit(fn)(*args)
print("entry() compiles+runs:", jax.tree.leaves(out)[0].shape)
EOF

echo "=== resnet space_to_depth stem probe" >&2
BENCH_INNER=1 BENCH_STEM=space_to_depth python bench.py \
  > bench_results/resnet50_s2d_r03.json 2> bench_results/resnet50_s2d_r03.err \
  && rm -f bench_results/resnet50_s2d_r03.err
cat bench_results/resnet50_s2d_r03.json >&2 || true

echo "=== gpt2 full-context probe (seq 1024 = model max, flash attention)" >&2
BENCH_MODEL=gpt2_medium BENCH_BATCH=4 BENCH_SEQ=1024 python bench_lm.py \
  > bench_results/gpt2_seq1024_r03.json 2> bench_results/gpt2_seq1024_r03.err \
  && rm -f bench_results/gpt2_seq1024_r03.err
cat bench_results/gpt2_seq1024_r03.json >&2 || true

echo "=== flash block-size sweep (bert, best config)" >&2
for blk in 256 512; do
  BENCH_MODEL=bert_large BENCH_BATCH=16 BENCH_REMAT=0 BENCH_FLASH_BLOCK=$blk \
    python bench_lm.py > "bench_results/bert_blk${blk}_r03.json" \
    2> "bench_results/bert_blk${blk}_r03.err" \
    && rm -f "bench_results/bert_blk${blk}_r03.err"
  cat "bench_results/bert_blk${blk}_r03.json" >&2 || true
done

echo "chipwork done" >&2
