"""Profile one ResNet-50 training step and itemize the layout-change
`copy`/`transpose` device time (VERDICT r4 item 4: the last 5% of
addressable step time — either recover it or close the memory-bound
case with this data).

Uses the traced timeline (jax.profiler -> merged chrome JSON) and sums
device-lane complete events by bucket: copy, transpose, fusion,
convolution, other. Prints per-bucket ms plus the N largest individual
copy/transpose ops with their durations, then one JSON line for the
chipwork harness.

Env: BENCH_BATCH (256), BENCH_STEM (space_to_depth), BENCH_STEPS (3).
"""

import json
import os
import sys
import tempfile

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

import jax
import jax.numpy as jnp
import numpy as np
import optax

import horovod_tpu as hvd
from horovod_tpu import model_zoo


def main():
    assert jax.devices()[0].platform == "tpu", "profile on the chip"
    batch = int(os.environ.get("BENCH_BATCH", "256"))
    stem = os.environ.get("BENCH_STEM", "space_to_depth")
    steps = int(os.environ.get("BENCH_STEPS", "3"))

    model = model_zoo.ResNet50(dtype=jnp.bfloat16, stem=stem)
    rng = jax.random.PRNGKey(0)
    images = jnp.asarray(
        np.random.default_rng(0).uniform(size=(batch, 224, 224, 3)),
        jnp.bfloat16,
    )
    labels = jnp.zeros((batch,), jnp.int32)
    variables = jax.jit(lambda: model.init(rng, images, train=False))()
    params, batch_stats = variables["params"], variables["batch_stats"]
    opt = optax.sgd(0.01, momentum=0.9)
    opt_state = opt.init(params)

    @jax.jit
    def step(params, batch_stats, opt_state, images, labels):
        def loss_fn(p):
            logits, mut = model.apply(
                {"params": p, "batch_stats": batch_stats},
                images,
                train=True,
                mutable=["batch_stats"],
            )
            one = jax.nn.one_hot(labels, logits.shape[-1])
            return (
                -jnp.mean(
                    jnp.sum(
                        jax.nn.log_softmax(
                            logits.astype(jnp.float32)
                        )
                        * one,
                        axis=-1,
                    )
                ),
                mut["batch_stats"],
            )

        (loss, bs), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params
        )
        upd, opt_state = opt.update(grads, opt_state)
        return optax.apply_updates(params, upd), bs, opt_state, loss

    # warm/compile outside the trace
    params, batch_stats, opt_state, loss = step(
        params, batch_stats, opt_state, images, labels
    )
    from _benchlib import sync

    sync(loss)

    path = os.path.join(tempfile.mkdtemp(), "resnet_profile.json")
    hvd.start_timeline(path, traced=True)
    for _ in range(steps):
        params, batch_stats, opt_state, loss = step(
            params, batch_stats, opt_state, images, labels
        )
    sync(loss)
    hvd.stop_timeline()

    events = json.load(open(path))["traceEvents"]
    buckets = {}
    tops = []
    for ev in events:
        if ev.get("ph") != "X" or not ev.get("dur"):
            continue
        name = str(ev.get("name", ""))
        low = name.lower()
        if low.startswith("end:"):
            continue
        if "copy" in low:
            b = "copy"
        elif "transpose" in low:
            b = "transpose"
        elif "convolution" in low or "conv" in low:
            b = "convolution"
        elif "fusion" in low:
            b = "fusion"
        else:
            b = "other"
        buckets[b] = buckets.get(b, 0.0) + ev["dur"] / 1e3
        if b in ("copy", "transpose"):
            tops.append((ev["dur"] / 1e3, name))

    per_step = {k: round(v / steps, 3) for k, v in buckets.items()}
    print("== per-step ms by bucket (over", steps, "steps):")
    for k, v in sorted(per_step.items(), key=lambda kv: -kv[1]):
        print(f"  {k:14s} {v:8.3f} ms")
    print("== largest copy/transpose ops (ms, name):")
    for dur, name in sorted(tops, reverse=True)[:15]:
        print(f"  {dur:8.3f}  {name}")
    print(
        json.dumps(
            {
                "metric": "resnet50_copy_profile",
                "value": per_step.get("copy", 0.0),
                "unit": "ms_copy_per_step",
                "batch": batch,
                "stem": stem,
                "buckets_ms": per_step,
                "platform": "tpu",
            }
        )
    )


if __name__ == "__main__":
    main()
