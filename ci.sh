#!/usr/bin/env bash
# CI entrypoint (ref: the reference's buildkite pipeline,
# .buildkite/gen-pipeline.sh + docker test matrix [V], SURVEY.md §2.7 —
# scaled to this repo: one host, no docker matrix, same three gates).
#
#   1. lint        — compile-level hygiene over the package and tests
#   2. native+TSAN — csrc/ builds clean AND passes a ThreadSanitizer
#                    stress of its concurrent pieces (SURVEY.md §5.2)
#   3. tests       — the full CPU suite on the virtual 8-device mesh
#
# Usage: ./ci.sh [lint|native|tests|all]   (default: all)

set -euo pipefail
cd "$(dirname "$0")"

step() { printf '\n=== %s ===\n' "$*"; }

lint() {
  step "lint: pyflakes-level check via python -m compileall + import"
  python -m compileall -q horovod_tpu tests bench.py bench_lm.py \
    bench_allreduce.py __graft_entry__.py
  # ruff/flake8 aren't in the image; compile + import-sanity is the
  # supported floor. Import must succeed without TPU hardware.
  JAX_PLATFORMS=cpu python -c "import horovod_tpu"
}

native() {
  step "native: release build"
  make -C csrc clean >/dev/null
  make -C csrc
  step "native: ThreadSanitizer stress (kvstore + timeline)"
  local tsan_bin
  tsan_bin="$(mktemp -d)/tsan_stress"
  g++ -std=c++17 -g -O1 -fsanitize=thread -pthread \
    csrc/timeline.cc csrc/kvstore.cc csrc/sha256.cc csrc/tsan_stress.cc \
    -o "$tsan_bin"
  TSAN_OPTIONS="halt_on_error=1" "$tsan_bin"
  step "native: AddressSanitizer stress (same driver)"
  local asan_bin
  asan_bin="$(mktemp -d)/asan_stress"
  g++ -std=c++17 -g -O1 -fsanitize=address,undefined -pthread \
    csrc/timeline.cc csrc/kvstore.cc csrc/sha256.cc csrc/tsan_stress.cc \
    -o "$asan_bin"
  ASAN_OPTIONS="halt_on_error=1" "$asan_bin"
}

tests() {
  step "tests: full CPU suite (8-device virtual mesh)"
  python -m pytest tests/ -q
}

case "${1:-all}" in
  lint)   lint ;;
  native) native ;;
  tests)  tests ;;
  all)    lint; native; tests ;;
  *) echo "usage: $0 [lint|native|tests|all]" >&2; exit 2 ;;
esac
