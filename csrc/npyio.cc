// mmap'd .npy shard reader — the native data-loader half.
//
// TPU-native stand-in for the reference's native data plumbing (ref:
// horovod/spark's petastorm reader + the torch DataLoader C workers the
// examples lean on [V] — SURVEY.md §2.5): the Python layer
// (horovod_tpu/data.py ShardedFileDataset) decides WHICH rows each rank
// reads; this layer makes reading them cheap. A shard is mapped once
// (MAP_SHARED, page cache does the buffering) and a shuffled batch's
// rows are gathered with one C call instead of k Python-level copies.
//
// Parser scope (deliberately minimal): C-order little-endian .npy,
// format versions 1.0/2.0, any dtype — the row stride is derived from
// (file size − data offset) / rows, so descr never needs decoding; a
// Fortran-order file is rejected (row gather would be wrong).

#include "export.h"

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cstdlib>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Npy {
  void* map = nullptr;
  size_t map_len = 0;
  const char* data = nullptr;  // first row
  long rows = 0;
  long row_bytes = 0;
};

// Parse "'shape': (123, 4, 5)" out of the header dict; returns the
// FIRST dimension (row count) or -1. A 0-d / empty-shape file has no
// row axis and is rejected.
long parse_rows(const char* hdr, size_t n) {
  const char* key = static_cast<const char*>(
      memmem(hdr, n, "'shape'", 7));
  if (!key) return -1;
  const char* p = static_cast<const char*>(
      memchr(key, '(', n - (key - hdr)));
  if (!p) return -1;
  ++p;
  while (p < hdr + n && *p == ' ') ++p;
  if (p >= hdr + n || *p < '0' || *p > '9') return -1;
  return strtol(p, nullptr, 10);
}

bool fortran_order(const char* hdr, size_t n) {
  const char* key = static_cast<const char*>(
      memmem(hdr, n, "'fortran_order'", 15));
  if (!key) return true;  // can't verify: reject
  const char* rest = key + 15;
  size_t left = n - (rest - hdr);
  const char* t = static_cast<const char*>(memmem(rest, left, "True", 4));
  const char* f = static_cast<const char*>(memmem(rest, left, "False", 5));
  if (!f) return true;
  return t != nullptr && t < f;
}

}  // namespace

extern "C" {

HVD_EXPORT void* hvd_npy_open(const char* path) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < 10) {
    ::close(fd);
    return nullptr;
  }
  size_t len = static_cast<size_t>(st.st_size);
  void* map = ::mmap(nullptr, len, PROT_READ, MAP_SHARED, fd, 0);
  ::close(fd);  // mapping keeps the file alive
  if (map == MAP_FAILED) return nullptr;
  const unsigned char* b = static_cast<const unsigned char*>(map);
  auto fail = [&]() -> void* {
    ::munmap(map, len);
    return nullptr;
  };
  if (memcmp(b, "\x93NUMPY", 6) != 0) return fail();
  int major = b[6];
  size_t hdr_off, hdr_len;
  if (major == 1) {
    if (len < 10) return fail();
    hdr_len = static_cast<size_t>(b[8]) | (static_cast<size_t>(b[9]) << 8);
    hdr_off = 10;
  } else if (major == 2 || major == 3) {
    if (len < 12) return fail();
    hdr_len = static_cast<size_t>(b[8]) |
              (static_cast<size_t>(b[9]) << 8) |
              (static_cast<size_t>(b[10]) << 16) |
              (static_cast<size_t>(b[11]) << 24);
    hdr_off = 12;
  } else {
    return fail();
  }
  if (hdr_off + hdr_len > len) return fail();
  const char* hdr = reinterpret_cast<const char*>(b + hdr_off);
  if (fortran_order(hdr, hdr_len)) return fail();
  long rows = parse_rows(hdr, hdr_len);
  if (rows <= 0) return fail();
  size_t data_off = hdr_off + hdr_len;
  size_t payload = len - data_off;
  if (payload % static_cast<size_t>(rows) != 0) return fail();
  Npy* h = new Npy;
  h->map = map;
  h->map_len = len;
  h->data = reinterpret_cast<const char*>(b + data_off);
  h->rows = rows;
  h->row_bytes = static_cast<long>(payload / static_cast<size_t>(rows));
  return h;
}

HVD_EXPORT long hvd_npy_rows(void* handle) {
  return static_cast<Npy*>(handle)->rows;
}

HVD_EXPORT long hvd_npy_row_bytes(void* handle) {
  return static_cast<Npy*>(handle)->row_bytes;
}

// Gather rows idx[0..k) into dst (k * row_bytes bytes). Out-of-range
// indices are clamped-checked: returns the number of rows copied (== k
// on success), stopping at the first bad index rather than reading
// beyond the mapping.
HVD_EXPORT long hvd_npy_gather(void* handle, const long* idx, long k,
                               void* dst) {
  const Npy* h = static_cast<const Npy*>(handle);
  char* out = static_cast<char*>(dst);
  for (long i = 0; i < k; ++i) {
    if (idx[i] < 0 || idx[i] >= h->rows) return i;
    std::memcpy(out + i * h->row_bytes,
                h->data + idx[i] * h->row_bytes,
                static_cast<size_t>(h->row_bytes));
  }
  return k;
}

HVD_EXPORT void hvd_npy_close(void* handle) {
  Npy* h = static_cast<Npy*>(handle);
  ::munmap(h->map, h->map_len);
  delete h;
}

}  // extern "C"

extern "C" {

// Scattered gather across MANY mapped shards in one call: row i of dst
// comes from handles[hsel[i]] at row local[i]. All handles must share
// one row stride (the caller validates dtype/trailing shape); returns
// the number of rows copied (== k on success), stopping at the first
// out-of-range index. This is the batch-level entry point: one C call
// replaces a Python loop over touched files.
HVD_EXPORT long hvd_npy_gather_scattered(void** handles, const long* hsel,
                                         const long* local, long k,
                                         void* dst) {
  if (k <= 0) return 0;
  char* out = static_cast<char*>(dst);
  const long rb = static_cast<const Npy*>(handles[hsel[0]])->row_bytes;
  for (long i = 0; i < k; ++i) {
    const Npy* h = static_cast<const Npy*>(handles[hsel[i]]);
    if (h->row_bytes != rb || local[i] < 0 || local[i] >= h->rows) {
      return i;
    }
    std::memcpy(out + i * rb, h->data + local[i] * rb,
                static_cast<size_t>(rb));
  }
  return k;
}

}  // extern "C"
