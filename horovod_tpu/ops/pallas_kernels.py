"""Pallas TPU kernels for the hot per-tensor ops around collectives.

TPU-native rebuild of the reference's CUDA kernels (ref:
horovod/common/ops/cuda/cuda_kernels.cu [V] — SURVEY.md §2.2: the
``ScaleBuffer`` pre/post-scale kernel and the batched D2D memcpy that
fuses many small per-tensor copies into one launch). The reference needs
hand-written CUDA because its collectives run outside the framework's
graph; on TPU most of this fuses automatically under XLA, but the eager
dispatch path (ops/eager.py → ops/fusion.py) and quantized wire
compression benefit from explicit kernels:

* ``scale_cast``     — fused scale+dtype-cast in one VMEM pass
  (ScaleBuffer + the fp16/bf16 compressor applied in one read).
* ``int8_quantize`` / ``int8_dequantize`` — int8 wire format with
  per-tensor scale and stochastic rounding (beyond-parity; EQuARX-style
  quantized collectives — PAPERS.md — are built from exactly this).
* ``adasum_coefficients_apply`` path: ``adasum_reduce_dots`` +
  ``adasum_apply`` — the two phases of the Adasum combine
  (adasum/adasum.h [V]) as explicit kernels, keeping the dot-product
  pass and the weighted-sum pass each to a single VMEM traversal.

Kernels run in interpret mode off-TPU (CPU test mesh), so the same code
path is exercised everywhere.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Row tile: float32 min tile is (8, 128); 256x128 amortizes grid
# overhead while staying far under VMEM.
_TILE_ROWS = 256
_LANES = 128


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _as_tiles(flat: jax.Array):
    """Zero-pad a flat vector to a [rows, 128] view with rows a multiple
    of the row tile, so every grid block is exact — a partial final
    block would hand the reduction kernels undefined out-of-bounds
    values on real hardware."""
    n = flat.shape[0]
    rows = max(pl.cdiv(n, _LANES), 1)
    rows = pl.cdiv(rows, _TILE_ROWS) * _TILE_ROWS
    pad = rows * _LANES - n
    if pad:
        flat = jnp.pad(flat, (0, pad))
    return flat.reshape(rows, _LANES), n


# ------------------------------------------------------------ scale+cast


def _scale_cast_kernel(x_ref, scale_ref, out_ref):
    out_ref[:] = (x_ref[:].astype(jnp.float32) * scale_ref[0]).astype(
        out_ref.dtype
    )


@functools.partial(jax.jit, static_argnames=("out_dtype",))
def scale_cast(x: jax.Array, scale, out_dtype=None) -> jax.Array:
    """``(x * scale).astype(out_dtype)`` in one fused VMEM pass — the
    explicit-kernel analog of the reference's ScaleBuffer [V]. Production
    call site: :func:`int8_dequantize` (and through it
    ``Compression.int8.decompress``). Inside jit-traced graphs prefer
    plain ``x * s`` — XLA fuses it into the surrounding collective; this
    kernel is for standalone/eager dispatches where there is no
    surrounding graph to fuse into. Arbitrary shapes, any numeric dtype
    in, float out.
    """
    out_dtype = jnp.dtype(out_dtype or x.dtype)
    shape = x.shape
    tiles, n = _as_tiles(x.reshape(-1))
    rows = tiles.shape[0]
    grid = (pl.cdiv(rows, _TILE_ROWS),)
    out = pl.pallas_call(
        _scale_cast_kernel,
        out_shape=jax.ShapeDtypeStruct(tiles.shape, out_dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (_TILE_ROWS, _LANES),
                lambda i: (i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec(
            (_TILE_ROWS, _LANES), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        interpret=_interpret(),
    )(tiles, jnp.asarray([scale], jnp.float32))
    return out.reshape(-1)[:n].reshape(shape)


# --------------------------------------------------------- int8 quantize


@jax.jit
def int8_quantize(x: jax.Array, seed=0):
    """Quantize to int8 with a per-tensor scale and stochastic rounding.

    Returns ``(values_int8, scale_f32)``; ``x ≈ values * scale``.
    Stochastic rounding keeps the quantizer unbiased, which is what
    makes the averaged gradients converge (same rationale as the
    reference's fp16 compressor note on unbiasedness [V]).
    """
    shape = x.shape
    flat = x.reshape(-1).astype(jnp.float32)
    absmax = jnp.maximum(jnp.max(jnp.abs(flat)), 1e-30)
    scale = absmax / 127.0
    if _interpret():
        # The TPU PRNG primitives don't lower off-TPU; equivalent
        # unbiased stochastic rounding via jax.random.
        scaled = flat / scale
        floor = jnp.floor(scaled)
        frac = scaled - floor
        u = jax.random.uniform(jax.random.PRNGKey(seed), flat.shape)
        rounded = floor + (u < frac).astype(jnp.float32)
        vals = jnp.clip(rounded, -128, 127).astype(jnp.int8)
        return vals.reshape(shape), scale
    tiles, n = _as_tiles(flat / scale)
    rows = tiles.shape[0]
    grid = (pl.cdiv(rows, _TILE_ROWS),)
    values = pl.pallas_call(
        _quantize_int8_body,
        out_shape=jax.ShapeDtypeStruct(tiles.shape, jnp.int8),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (_TILE_ROWS, _LANES),
                lambda i: (i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec(
            (_TILE_ROWS, _LANES), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        interpret=_interpret(),
    )(tiles, jnp.asarray([seed], jnp.int32))
    return values.reshape(-1)[:n].reshape(shape), scale


def _quantize_int8_body(x_ref, seed_ref, values_ref):
    # Hand-rolled stochastic round-to-int8 (the hardware stochastic-
    # round primitive only targets bf16/fp8): uniform u in [0,1) from
    # the top 24 bits of the PRNG, round down + bernoulli(frac) up.
    pltpu.prng_seed(seed_ref[0] + pl.program_id(0))
    bits = pltpu.bitcast(pltpu.prng_random_bits(x_ref.shape), jnp.int32)
    # logical shift keeps the top 24 bits as a non-negative int32,
    # which (unlike uint32) Mosaic can cast to float32
    u = jax.lax.shift_right_logical(bits, 8).astype(jnp.float32) * (
        1.0 / (1 << 24)
    )
    scaled = x_ref[:]
    floor = jnp.floor(scaled)
    frac = scaled - floor
    rounded = floor + (u < frac).astype(jnp.float32)
    values_ref[:] = jnp.clip(rounded, -128, 127).astype(jnp.int8)


@functools.partial(jax.jit, static_argnames=("out_dtype",))
def int8_dequantize(values: jax.Array, scale, out_dtype=jnp.float32):
    """Inverse of :func:`int8_quantize` — exactly a scale+cast, so it IS
    :func:`scale_cast` (one kernel, one set of tiling scaffolding)."""
    return scale_cast(values, scale, out_dtype)


# --------------------------------------------------- block-scaled int8


@functools.partial(jax.jit, static_argnames=("block_size",))
def int8_block_quantize(x: jax.Array, block_size: int = 512, seed=0):
    """Block-scaled int8: one float32 scale per ``block_size`` elements
    of the flattened tensor, stochastic rounding (unbiased).

    Returns ``(values_int8, scales_f32)`` with ``values`` shaped like
    ``x`` and ``scales`` shaped ``[ceil(n/block_size)]``;
    ``x ≈ values * repeat(scales, block_size)[:n]``. The per-tensor
    :func:`int8_quantize` forces every element to share one dynamic
    range; block scales keep mixed-magnitude regions (a fused buffer
    concatenating many gradients — ops/fusion.py's quantized wire, the
    EQuARX wire format) each within their own, at 4 bytes of scale per
    block on the wire. A short tail block is padded with zeros for the
    absmax only — zeros never raise a block's scale, so padding cannot
    leak into the quantization (the pad-exclusion contract the fused
    bucket tier relies on).
    """
    if block_size < 1:
        raise ValueError(f"block_size must be >= 1, got {block_size}")
    shape = x.shape
    flat = x.reshape(-1).astype(jnp.float32)
    n = flat.shape[0]
    nb = -(-n // block_size)
    pad = nb * block_size - n
    blocks = (jnp.pad(flat, (0, pad)) if pad else flat).reshape(
        nb, block_size
    )
    absmax = jnp.max(jnp.abs(blocks), axis=1)
    scales = jnp.maximum(absmax, 1e-30) / 127.0
    scaled = (blocks / scales[:, None]).reshape(-1)[:n]
    if _interpret():
        floor = jnp.floor(scaled)
        frac = scaled - floor
        u = jax.random.uniform(jax.random.PRNGKey(seed), scaled.shape)
        rounded = floor + (u < frac).astype(jnp.float32)
        vals = jnp.clip(rounded, -128, 127).astype(jnp.int8)
        return vals.reshape(shape), scales
    tiles, _ = _as_tiles(scaled)
    rows = tiles.shape[0]
    grid = (pl.cdiv(rows, _TILE_ROWS),)
    values = pl.pallas_call(
        _quantize_int8_body,
        out_shape=jax.ShapeDtypeStruct(tiles.shape, jnp.int8),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (_TILE_ROWS, _LANES),
                lambda i: (i, 0),
                memory_space=pltpu.VMEM,
            ),
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec(
            (_TILE_ROWS, _LANES), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        interpret=_interpret(),
    )(tiles, jnp.asarray([seed], jnp.int32))
    return values.reshape(-1)[:n].reshape(shape), scales


@functools.partial(jax.jit, static_argnames=("block_size", "out_dtype"))
def int8_block_dequantize(
    values: jax.Array, scales, block_size: int = 512,
    out_dtype=jnp.float32,
):
    """Inverse of :func:`int8_block_quantize`. Plain jnp on purpose:
    the production call sites are inside traced programs (the fused
    wire's consumer side), where XLA fuses the broadcast-multiply into
    the collective's consumer — a dedicated kernel would only fence
    that fusion off."""
    shape = values.shape
    flat = values.reshape(-1).astype(jnp.float32)
    n = flat.shape[0]
    nb = scales.shape[0]
    pad = nb * block_size - n
    blocks = (jnp.pad(flat, (0, pad)) if pad else flat).reshape(
        nb, block_size
    )
    out = (blocks * scales[:, None].astype(jnp.float32)).reshape(-1)[:n]
    return out.reshape(shape).astype(out_dtype)


# ----------------------------------------------------------- adasum fuse


def _adasum_dots_kernel(a_ref, b_ref, acc_ref):
    """Accumulate [a·b, a·a, b·b] across sequential grid steps."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        acc_ref[0] = 0.0
        acc_ref[1] = 0.0
        acc_ref[2] = 0.0

    a = a_ref[:].astype(jnp.float32)
    b = b_ref[:].astype(jnp.float32)
    acc_ref[0] += jnp.sum(a * b)
    acc_ref[1] += jnp.sum(a * a)
    acc_ref[2] += jnp.sum(b * b)


def _adasum_apply_kernel(a_ref, b_ref, coef_ref, out_ref):
    out_ref[:] = (
        coef_ref[0] * a_ref[:].astype(jnp.float32)
        + coef_ref[1] * b_ref[:].astype(jnp.float32)
    ).astype(out_ref.dtype)


@jax.jit
def adasum_pair(a: jax.Array, b: jax.Array) -> jax.Array:
    """Fused Adasum combine of two same-shaped tensors (adasum.h [V]):
    one VMEM pass for the three dot products, one for the weighted sum.
    Matches ops/adasum.py::adasum_pair numerically (float32 accumulate).
    """
    shape = a.shape
    at, n = _as_tiles(a.reshape(-1))
    bt, _ = _as_tiles(b.reshape(-1))
    rows = at.shape[0]
    grid = (pl.cdiv(rows, _TILE_ROWS),)
    tile_spec = pl.BlockSpec(
        (_TILE_ROWS, _LANES), lambda i: (i, 0), memory_space=pltpu.VMEM
    )
    dots = pl.pallas_call(
        _adasum_dots_kernel,
        out_shape=jax.ShapeDtypeStruct((3,), jnp.float32),
        grid=grid,
        in_specs=[tile_spec, tile_spec],
        out_specs=pl.BlockSpec(memory_space=pltpu.SMEM),
        interpret=_interpret(),
    )(at, bt)
    dot, asq, bsq = dots[0], dots[1], dots[2]
    acoef = 1.0 - jnp.where(asq > 0, dot / (2.0 * asq), 0.0)
    bcoef = 1.0 - jnp.where(bsq > 0, dot / (2.0 * bsq), 0.0)
    out = pl.pallas_call(
        _adasum_apply_kernel,
        out_shape=jax.ShapeDtypeStruct(at.shape, a.dtype),
        grid=grid,
        in_specs=[
            tile_spec,
            tile_spec,
            pl.BlockSpec(memory_space=pltpu.SMEM),
        ],
        out_specs=tile_spec,
        interpret=_interpret(),
    )(at, bt, jnp.stack([acoef, bcoef]))
    return out.reshape(-1)[:n].reshape(shape)
