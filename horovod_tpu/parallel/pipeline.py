"""Pipeline parallelism: GPipe-style microbatch schedule over the 'pp' axis.

Absent from the reference (SURVEY.md §2.6); built TPU-first: stages are
chips along the 'pp' mesh axis, activations hop stage→stage with
`ppermute`, and the fill/drain schedule is a `lax.scan` — fully static,
so XLA overlaps each hop with the next microbatch's compute.

Per-device code for use inside shard_map: every chip runs the same scan;
chip s applies its own stage parameters. The classic GPipe bubble is
(pp-1)/(n_micro+pp-1); callers pick n_micro >> pp to amortize it.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax import lax


def gpipe(
    stage_fn: Callable,
    stage_params,
    x_micro,
    axis_name: str = "pp",
):
    """Run microbatches through the pipeline.

    stage_fn(params, x) -> y: this chip's stage (shapes preserved).
    stage_params: this chip's stage parameters (pp-sharded pytree leaf(s)).
    x_micro: [n_micro, ...] microbatched input. Only stage 0's copy is
        consumed; other stages may pass the same array (ignored).

    Returns [n_micro, ...] outputs, valid on the LAST stage (other stages
    return zeros) — broadcast back with a psum or collective if every
    stage needs them.
    """
    pp = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    n_micro = x_micro.shape[0]
    total = n_micro + pp - 1  # fill + drain
    micro_shape = x_micro.shape[1:]

    # Send each stage's output to the next stage; the wrap-around edge
    # (last → 0) carries drained values nobody reads.
    perm = [(j, (j + 1) % pp) for j in range(pp)]

    def step(carry, t):
        out_acc = carry["out"]
        prev_act = carry["act"]  # activation received from previous stage
        # Stage 0 injects microbatch t (zeros once drained); others use
        # what arrived over the ring.
        inject = jnp.where(
            t < n_micro,
            lax.dynamic_index_in_dim(
                x_micro, jnp.minimum(t, n_micro - 1), keepdims=False
            ),
            jnp.zeros(micro_shape, x_micro.dtype),
        )
        x_in = jnp.where(stage == 0, inject, prev_act)
        y = stage_fn(stage_params, x_in)
        # Last stage: microbatch index t - (pp-1) completes at step t.
        done_idx = t - (pp - 1)
        is_done = jnp.logical_and(done_idx >= 0, stage == pp - 1)
        out_acc = lax.cond(
            is_done,
            lambda acc: lax.dynamic_update_index_in_dim(
                acc, y, jnp.maximum(done_idx, 0), axis=0
            ),
            lambda acc: acc,
            out_acc,
        )
        act_next = lax.ppermute(y, axis_name, perm)
        return {"out": out_acc, "act": act_next}, None

    init = {
        "out": jnp.zeros((n_micro,) + micro_shape, x_micro.dtype),
        "act": jnp.zeros(micro_shape, x_micro.dtype),
    }
    final, _ = lax.scan(step, init, jnp.arange(total))
    return final["out"]
