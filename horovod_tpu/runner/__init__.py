"""Launcher / runner layer — TPU-native rebuild of the reference's
``horovodrun`` stack (ref: horovod/runner/ [V] — SURVEY.md §2.5, §3.3;
the reference mount was empty, citations are structural).

What survives the TPU redesign and what changes:

* The reference's launcher probes NICs over SSH and builds an ``mpirun``
  or Gloo command line; its workers rendezvous through an HTTP KV store.
  On TPU the *data plane* is XLA collectives over ICI, so the runner's
  only jobs are (a) process bootstrap with the ``HOROVOD_*`` env
  contract, (b) wiring the ``jax.distributed`` coordination service
  (rank-0 host is the coordinator), and (c) watching workers and
  collecting exit codes.
* The HTTP KV rendezvous survives (elastic re-keying and the env
  contract depend on it) — see ``rendezvous.py``.
* NIC probing is replaced by TPU slice-topology discovery from
  environment metadata — see ``tpu_discovery.py``.

Public API mirrors ``horovod.run.run()`` / the ``horovodrun`` CLI:

    python -m horovod_tpu.runner -np 8 python train.py
    from horovod_tpu.runner import run
"""

from .hosts import (  # noqa: F401
    HostInfo,
    SlotInfo,
    assign_slots,
    parse_hostfile,
    parse_hosts,
)
from .launch import main, parse_args, run, run_commandline  # noqa: F401
from .rendezvous import KVStore, RendezvousServer  # noqa: F401
from .secret import make_secret_key, sign, verify  # noqa: F401
from .service import BasicClient, BasicService  # noqa: F401
