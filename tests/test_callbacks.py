"""Callback + SyncBatchNorm tests (ref test model: the Keras callback
coverage inside test/parallel/test_tensorflow_keras.py [V])."""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P


def test_broadcast_global_variables_callback(hvd, rng):
    """All workers leave on_train_begin with rank 0's weights."""
    from horovod_tpu.callbacks import BroadcastGlobalVariablesCallback

    # Rank-dependent params: only rank 0's values must survive.
    params = {
        "w": hvd.shard_from_rank_fn(
            lambda r: np.full((4,), float(r), np.float32), hvd.mesh()
        )
    }
    cb = BroadcastGlobalVariablesCallback(root_rank=0)
    out = cb.on_train_begin(params)
    host = np.asarray(out["w"])
    np.testing.assert_allclose(host, 0.0)


def test_metric_average_callback(hvd, monkeypatch):
    """Scalar metrics are averaged across workers; strings untouched."""
    from horovod_tpu.callbacks import MetricAverageCallback

    cb = MetricAverageCallback()
    logs = {"loss": 2.0, "acc": 0.5, "note": "epoch done"}
    cb.on_epoch_end(0, logs)
    # single-controller world: average of identical values is identity,
    # but the value must round-trip through a real collective
    assert logs["loss"] == pytest.approx(2.0)
    assert logs["acc"] == pytest.approx(0.5)
    assert logs["note"] == "epoch done"


def test_warmup_callback_ramp(hvd):
    from horovod_tpu.callbacks import LearningRateWarmupCallback

    cb = LearningRateWarmupCallback(initial_lr=0.8, warmup_epochs=4)
    size = hvd.size()
    cb.on_epoch_begin(0)
    assert cb.current_lr == pytest.approx(0.8 / size)
    cb.on_epoch_begin(4)
    assert cb.current_lr == pytest.approx(0.8)
    # monotone ramp
    lrs = []
    for e in range(5):
        cb.on_epoch_begin(e)
        lrs.append(cb.current_lr)
    assert all(a <= b + 1e-12 for a, b in zip(lrs, lrs[1:]))


def test_warmup_multiplier_per_batch(hvd):
    from horovod_tpu.callbacks import LearningRateWarmupCallback

    cb = LearningRateWarmupCallback(
        initial_lr=1.0, warmup_epochs=2, steps_per_epoch=10
    )
    m0 = cb.multiplier(0, batch=0)
    m_half = cb.multiplier(0, batch=5)
    m1 = cb.multiplier(1, batch=0)
    assert m0 < m_half < m1 <= 1.0
    assert cb.multiplier(2, batch=0) == 1.0


def test_schedule_callback_piecewise():
    from horovod_tpu.callbacks import LearningRateScheduleCallback

    cb = LearningRateScheduleCallback(
        initial_lr=1.0, multiplier=0.1, start_epoch=30, end_epoch=60
    )
    cb.on_epoch_begin(0)
    assert cb.current_lr == pytest.approx(1.0)
    cb.on_epoch_begin(30)
    assert cb.current_lr == pytest.approx(0.1)
    cb.on_epoch_begin(60)  # out of range: keeps last value (ref behavior)
    assert cb.current_lr == pytest.approx(0.1)


def test_schedule_callback_callable_multiplier():
    from horovod_tpu.callbacks import LearningRateScheduleCallback

    cb = LearningRateScheduleCallback(
        initial_lr=2.0, multiplier=lambda e: 1.0 / (1 + e)
    )
    cb.on_epoch_begin(3)
    assert cb.current_lr == pytest.approx(2.0 / 4)


def test_callback_list_threads_state(hvd):
    from horovod_tpu.callbacks import (
        BroadcastGlobalVariablesCallback,
        CallbackList,
        LearningRateWarmupCallback,
    )

    cbs = CallbackList(
        [
            BroadcastGlobalVariablesCallback(),
            LearningRateWarmupCallback(0.1, warmup_epochs=2),
        ]
    )
    params = {"w": hvd.replicate(np.ones((2,), np.float32))}
    out = cbs.on_train_begin(params)
    assert out is not None and "w" in out
    out = cbs.on_epoch_begin(0, out)
    assert "w" in out


def test_warmup_schedule_pure(hvd):
    from horovod_tpu.callbacks import warmup_schedule

    size = hvd.size()
    sched = warmup_schedule(base_lr=0.8, warmup_steps=100)
    assert float(sched(0)) == pytest.approx(0.8 / size)
    assert float(sched(100)) == pytest.approx(0.8)
    assert float(sched(1000)) == pytest.approx(0.8)
    assert float(sched(50)) == pytest.approx(
        0.8 * size**0.5 / size, rel=1e-5
    )


def test_piecewise_schedule_pure():
    from horovod_tpu.callbacks import piecewise_schedule

    sched = piecewise_schedule(1.0, [(30, 0.1), (60, 0.01)])
    assert float(sched(0)) == pytest.approx(1.0)
    assert float(sched(30)) == pytest.approx(0.1)
    assert float(sched(59)) == pytest.approx(0.1)
    assert float(sched(61)) == pytest.approx(0.01)


def test_sync_batch_norm_global_stats(hvd, rng):
    """SyncBatchNorm inside shard_map normalizes with GLOBAL batch
    statistics: replicas with different data agree on mean/var (ref:
    test_torch.py's sync-BN equivalence-to-global-batch pattern [V])."""
    import horovod_tpu as hvd_pkg
    from jax.experimental.shard_map import shard_map

    mesh = hvd.mesh()
    bn = hvd_pkg.SyncBatchNorm(axis_name=hvd.WORLD_AXIS)
    # per-rank batches with very different means
    data = np.stack(
        [rng.normal(loc=float(r), size=(4, 3)).astype(np.float32)
         for r in range(8)]
    )

    variables = bn.init(jax.random.PRNGKey(0), data[0])

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(P(), P(hvd.WORLD_AXIS)),
        out_specs=P(hvd.WORLD_AXIS),
        check_rep=False,
    )
    def apply(vars_, x):
        y, _ = bn.apply(
            vars_, x[0], use_running_average=False,
            mutable=["batch_stats"],
        )
        return y[None]

    out = np.asarray(jax.jit(apply)(variables, jnp.asarray(data)))
    # global normalization: concatenating all shards gives ~zero mean,
    # ~unit variance per feature
    flat = out.reshape(-1, 3)
    np.testing.assert_allclose(flat.mean(axis=0), 0.0, atol=1e-4)
    np.testing.assert_allclose(flat.std(axis=0), 1.0, atol=1e-2)
    # and per-shard means are NOT zero (each shard is offset), proving
    # stats were global, not local
    per_shard_means = out.mean(axis=(1, 2))
    assert np.abs(per_shard_means).max() > 0.3


def test_sync_batch_norm_running_average_inference(hvd, rng):
    import horovod_tpu as hvd_pkg

    bn = hvd_pkg.SyncBatchNorm()  # no axis: plain BN on one device
    x = rng.normal(size=(16, 5)).astype(np.float32)
    variables = bn.init(jax.random.PRNGKey(0), x)
    y, mutated = bn.apply(
        variables, x, use_running_average=False, mutable=["batch_stats"]
    )
    # running stats moved toward batch stats
    assert not np.allclose(
        np.asarray(mutated["batch_stats"]["mean"]), 0.0
    )
    # inference path uses running stats without mutation
    y2 = bn.apply(
        {**variables, "batch_stats": mutated["batch_stats"]},
        x,
        use_running_average=True,
    )
    assert y2.shape == x.shape
