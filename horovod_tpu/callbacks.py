"""Training-loop callbacks — parity with the reference's Keras callbacks.

(ref: horovod/_keras/callbacks.py + horovod/tensorflow/keras/callbacks.py
[V] — SURVEY.md §2.4: BroadcastGlobalVariablesCallback,
MetricAverageCallback, LearningRateWarmupCallback,
LearningRateScheduleCallback.)

The TPU rebuild has no Keras loop to hook, so each callback exists in
two idiomatic forms:

* a **callback object** with the reference's hook names
  (``on_train_begin`` / ``on_epoch_end`` / ``on_epoch_begin``) for
  hand-written training loops — drive them with :class:`CallbackList`;
* where the reference mutates optimizer state imperatively (the LR
  callbacks), a **pure optax schedule** factory — the JAX-native shape
  of the same behavior, usable directly in ``optax.sgd(schedule)``.
"""

from __future__ import annotations

import math
from typing import Callable, Iterable, List, Optional, Sequence

import numpy as np


class Callback:
    """Hook surface (subset of Keras' Callback the reference uses [V])."""

    def on_train_begin(self, state=None):
        return state

    def on_epoch_begin(self, epoch: int, state=None):
        return state

    def on_epoch_end(self, epoch: int, logs: Optional[dict] = None,
                     state=None):
        return state


class CallbackList:
    """Drives a sequence of callbacks, threading the (immutable) train
    state through — JAX state is values, not objects, so every hook
    returns the possibly-replaced state."""

    def __init__(self, callbacks: Sequence[Callback]):
        self._callbacks: List[Callback] = list(callbacks)

    def on_train_begin(self, state=None):
        for cb in self._callbacks:
            state = cb.on_train_begin(state)
        return state

    def on_epoch_begin(self, epoch: int, state=None):
        for cb in self._callbacks:
            state = cb.on_epoch_begin(epoch, state)
        return state

    def on_epoch_end(self, epoch: int, logs: Optional[dict] = None,
                     state=None):
        for cb in self._callbacks:
            state = cb.on_epoch_end(epoch, logs, state)
        return state


class BroadcastGlobalVariablesCallback(Callback):
    """Broadcast the train state from ``root_rank`` at train start
    (ref: BroadcastGlobalVariablesCallback [V] — makes every worker
    start from identical weights after e.g. a restore on rank 0)."""

    def __init__(self, root_rank: int = 0):
        self.root_rank = root_rank

    def on_train_begin(self, state=None):
        from .optimizer import broadcast_parameters

        if state is None:
            return state
        return broadcast_parameters(state, root_rank=self.root_rank)


class MetricAverageCallback(Callback):
    """Average epoch metrics over all workers before they are logged
    (ref: MetricAverageCallback [V]). Works on a logs dict of scalars;
    non-numeric entries pass through untouched."""

    def __init__(self, process_set=None):
        self.process_set = process_set

    def on_epoch_end(self, epoch: int, logs: Optional[dict] = None,
                     state=None):
        if not logs:
            return state
        from .ops import eager
        from .ops.reduction_ops import Average

        for key in list(logs.keys()):
            value = logs[key]
            if isinstance(value, (int, float, np.floating, np.integer)):
                averaged = eager.allreduce(
                    eager.replicate(np.asarray(float(value), np.float32)),
                    op=Average,
                    name=f"metric.{key}",
                    process_set=self.process_set,
                )
                logs[key] = float(np.asarray(averaged).reshape(-1)[0])
        return state


class LearningRateWarmupCallback(Callback):
    """Warmup mirror of the reference's callback [V]: an LR multiplier
    ramping 1/size → 1 over ``warmup_epochs``. Epoch granularity via
    ``on_epoch_begin``; per-batch granularity (the reference's behavior)
    via ``self.multiplier(epoch, batch=b)`` with ``steps_per_epoch``
    set. Preferred under jit: the pure :func:`warmup_schedule`.
    """

    def __init__(
        self,
        initial_lr: float,
        warmup_epochs: int = 5,
        steps_per_epoch: Optional[int] = None,
        momentum_correction: bool = True,  # accepted for parity
        verbose: bool = False,
    ):
        from .common import basics

        self.initial_lr = initial_lr
        self.warmup_epochs = warmup_epochs
        self.steps_per_epoch = steps_per_epoch
        self.verbose = verbose
        self._size = basics.size() if basics.is_initialized() else 1
        self.current_lr = initial_lr / self._size

    def multiplier(self, epoch: float, batch: Optional[int] = None) -> float:
        """size^(progress) / size — exponential ramp from 1/size to 1,
        the reference's gradual-warmup rule (Goyal et al.) [V]. With
        ``batch`` and ``steps_per_epoch``, progress advances within the
        epoch (the reference's per-batch ramp)."""
        effective = float(epoch)
        if batch is not None and self.steps_per_epoch:
            effective += batch / float(self.steps_per_epoch)
        if effective >= self.warmup_epochs:
            return 1.0
        progress = effective / max(self.warmup_epochs, 1e-9)
        return math.pow(self._size, progress) / self._size

    def on_epoch_begin(self, epoch: int, state=None):
        self.current_lr = self.initial_lr * self.multiplier(epoch)
        if self.verbose:
            print(
                f"Epoch {epoch}: LearningRateWarmupCallback sets lr "
                f"to {self.current_lr:.6g}"
            )
        return state


class LearningRateScheduleCallback(Callback):
    """Piecewise LR multiplier by epoch range (ref:
    LearningRateScheduleCallback [V]): ``multiplier`` is a float or
    fn(epoch)->float applied to ``initial_lr`` on
    ``start_epoch <= epoch < end_epoch``."""

    def __init__(
        self,
        initial_lr: float,
        multiplier,
        start_epoch: int = 0,
        end_epoch: Optional[int] = None,
        staircase: bool = True,
    ):
        self.initial_lr = initial_lr
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        if callable(multiplier):
            self._fn = multiplier
        else:
            self._fn = lambda epoch: multiplier
        self.current_lr = initial_lr

    def _active(self, epoch: float) -> bool:
        if epoch < self.start_epoch:
            return False
        return self.end_epoch is None or epoch < self.end_epoch

    def on_epoch_begin(self, epoch: int, state=None):
        e = float(int(epoch)) if self.staircase else float(epoch)
        if self._active(e):
            self.current_lr = self.initial_lr * float(self._fn(e))
        return state


class TelemetryCallback(Callback):
    """Epoch-level bridge to the flight recorder (common/telemetry.py):
    merges the ring's step-time percentiles into the epoch ``logs`` (so
    whatever logger consumes them — the reference's pattern is
    TensorBoard — sees step p50/p95 next to loss/accuracy) and, when a
    flight-recorder path is configured, persists the ring each epoch —
    a periodic dump point between the SIGTERM/atexit ones.

    No reference analog: the reference's callbacks stop at metric
    averaging; this is the observability layer's loop hook."""

    def __init__(self, dump: bool = True, prefix: str = "step_ms"):
        self._dump = dump
        self._prefix = prefix

    def on_epoch_end(self, epoch: int, logs: Optional[dict] = None,
                     state=None):
        from .common import telemetry

        h = telemetry.hub()
        pct = h.percentiles()
        if logs is not None and pct:
            logs[f"{self._prefix}_p50"] = pct["p50"]
            logs[f"{self._prefix}_p95"] = pct["p95"]
        if self._dump:
            h.dump()  # no-op without a flight-recorder path
        return state


# ------------------------------------------------------- optax schedules


def warmup_schedule(
    base_lr: float,
    warmup_steps: int,
    size: Optional[int] = None,
) -> Callable:
    """The warmup callback as a pure optax schedule: exponential ramp
    ``base_lr/size → base_lr`` over ``warmup_steps``, then constant.
    This is the jit-native form — feed it straight to
    ``optax.sgd(learning_rate=...)``."""
    import jax.numpy as jnp

    from .common import basics

    n = float(size if size is not None else
              (basics.size() if basics.is_initialized() else 1))

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        progress = jnp.clip(step / max(warmup_steps, 1), 0.0, 1.0)
        return base_lr * jnp.power(n, progress) / n

    return schedule


def piecewise_schedule(
    base_lr: float,
    boundaries_and_multipliers: Iterable,
) -> Callable:
    """LearningRateScheduleCallback as a pure schedule: a list of
    ``(step_boundary, multiplier)`` applied in order (the classic
    ResNet 30-60-80 decay is ``[(30*spe, 0.1), (60*spe, 0.01), ...]``)."""
    import jax.numpy as jnp

    pairs = sorted(boundaries_and_multipliers)

    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        mult = jnp.asarray(1.0, jnp.float32)
        for boundary, multiplier in pairs:
            mult = jnp.where(step >= boundary, multiplier, mult)
        return base_lr * mult

    return schedule
