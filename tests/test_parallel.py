"""Parallelism primitives: each strategy checked against its sequential
reference (the SURVEY.md §4 lesson — closed-form/replayable math on a
simulated mesh)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from horovod_tpu.parallel import (
    MeshSpec,
    gpipe,
    moe_ffn,
    init_moe_params,
    ring_attention,
    column_parallel_dense,
    row_parallel_dense,
)


def mesh_1d(name, n=8):
    return Mesh(np.asarray(jax.devices()[:n]), (name,))


from conftest import dense_attention_oracle as dense_attention


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(hvd, rng, causal):
    b, t, h, d = 2, 32, 4, 8  # t sharded 8 ways → 4 tokens per chip
    q = rng.normal(size=(b, t, h, d)).astype(np.float32)
    k = rng.normal(size=(b, t, h, d)).astype(np.float32)
    v = rng.normal(size=(b, t, h, d)).astype(np.float32)
    mesh = mesh_1d("sp")
    f = jax.jit(
        jax.shard_map(
            lambda q, k, v: ring_attention(q, k, v, "sp", causal=causal),
            mesh=mesh,
            in_specs=P(None, "sp"),
            out_specs=P(None, "sp"),
            check_vma=False,
        )
    )
    out = f(q, k, v)
    expected = dense_attention(q, k, v, causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(expected), rtol=2e-4, atol=2e-5
    )


def test_tp_dense_pair_matches_full(hvd, rng):
    d, f_dim, n = 16, 32, 8
    x = rng.normal(size=(4, d)).astype(np.float32)
    w1 = rng.normal(size=(d, f_dim)).astype(np.float32)
    b1 = rng.normal(size=(f_dim,)).astype(np.float32)
    w2 = rng.normal(size=(f_dim, d)).astype(np.float32)
    mesh = mesh_1d("tp")

    def per_device(x, w1s, b1s, w2s):
        h = column_parallel_dense(x, w1s, b1s)
        return row_parallel_dense(h, w2s, axis_name="tp")

    out = jax.jit(
        jax.shard_map(
            per_device,
            mesh=mesh,
            in_specs=(P(), P(None, "tp"), P("tp"), P("tp", None)),
            out_specs=P(),
            check_vma=False,
        )
    )(x, w1, b1, w2)
    expected = (x @ w1 + b1) @ w2
    np.testing.assert_allclose(np.asarray(out), expected, rtol=2e-4, atol=1e-4)


def test_gpipe_matches_sequential(hvd, rng):
    """4-stage pipeline of affine stages == composed application."""
    n_micro, bm, d = 6, 2, 8
    pp = 4
    mesh = Mesh(np.asarray(jax.devices()[:4]), ("pp",))
    x = rng.normal(size=(n_micro, bm, d)).astype(np.float32)
    # stage s: x -> x * w[s] + c[s]  (elementwise affine, shape-preserving)
    w = rng.normal(size=(pp, d)).astype(np.float32)
    c = rng.normal(size=(pp, d)).astype(np.float32)

    def stage_fn(params, xb):
        ws, cs = params
        return xb * ws + cs

    def per_device(x, w_shard, c_shard):
        out = gpipe(stage_fn, (w_shard[0], c_shard[0]), x, axis_name="pp")
        # broadcast result from last stage to all
        stage = lax.axis_index("pp")
        return lax.psum(
            jnp.where(stage == pp - 1, out, jnp.zeros_like(out)), "pp"
        )

    out = jax.jit(
        jax.shard_map(
            per_device,
            mesh=mesh,
            in_specs=(P(), P("pp"), P("pp")),
            out_specs=P(),
            check_vma=False,
        )
    )(x, w, c)
    expected = x
    for s in range(pp):
        expected = expected * w[s] + c[s]
    np.testing.assert_allclose(np.asarray(out), expected, rtol=1e-5, atol=1e-5)


def test_1f1b_schedule_invariants():
    """Static-table sanity across (pp, n_micro): every microbatch's F
    and B land exactly once per stage, dependencies point strictly
    backward in time, in-flight stays <= pp (the memory bound), and no
    two live stash entries collide in their modular slot."""
    from horovod_tpu.parallel.pipeline import _build_1f1b_schedule

    from horovod_tpu.parallel.pipeline import _default_in_flight

    for pp, n_micro, v in [
        (2, 1, 1), (2, 5, 1), (4, 4, 1), (4, 9, 1), (8, 16, 1),
        (1, 3, 2), (2, 4, 2), (2, 7, 3), (4, 8, 2),
    ]:
        cap = _default_in_flight(pp)
        s = _build_1f1b_schedule(pp, n_micro, v)
        T = s["do_f"].shape[0]
        S = cap + 1
        N = v * pp  # global stages; g = c*pp + device
        t_f = np.full((N, n_micro), -1)
        t_b = np.full((N, n_micro), -1)
        for t in range(T):
            for st in range(pp):
                if s["do_f"][t, st]:
                    g = s["f_c"][t, st] * pp + st
                    m = s["f_idx"][t, st]
                    assert t_f[g, m] == -1
                    t_f[g, m] = t
                if s["do_b"][t, st]:
                    g = s["b_c"][t, st] * pp + st
                    m = s["b_idx"][t, st]
                    assert t_b[g, m] == -1
                    t_b[g, m] = t
        assert (t_f >= 0).all() and (t_b >= 0).all()
        for g in range(N):
            for m in range(n_micro):
                if g > 0:
                    assert t_f[g - 1, m] < t_f[g, m]
                if g < N - 1:
                    assert t_b[g + 1, m] < t_b[g, m]
                else:
                    assert t_f[g, m] <= t_b[g, m]  # same-tick ok
        # memory bound + slot collision freedom per global stage
        for g in range(N):
            for t in range(T):
                live = [
                    m for m in range(n_micro)
                    if t_f[g, m] <= t and (t_b[g, m] == -1 or t_b[g, m] > t)
                    and t_f[g, m] >= 0
                ]
                assert len(live) <= cap, (pp, n_micro, v, g, t, live)
                slots = [m % S for m in live]
                assert len(set(slots)) == len(slots)


def test_1f1b_ring_routing_replay():
    """Symbolic replay of the ra_*/rc_* receive tables against the two
    ppermute rings, at pp >= 3 where the chunk-boundary wrap (device
    pp-1 -> 0) differs from ordinary neighbors: every consumed
    activation must be EXACTLY the act the previous global stage
    produced for that microbatch, every consumed cotangent the next
    stage's, and no inbox slot may be overwritten while still live."""
    from horovod_tpu.parallel.pipeline import (
        _build_1f1b_schedule,
        _default_in_flight,
    )

    for pp, n_micro, v in [(3, 7, 1), (3, 6, 2), (4, 9, 3), (5, 7, 2)]:
        cap = _default_in_flight(pp)
        S = cap + 1
        s = _build_1f1b_schedule(pp, n_micro, v)
        T = s["do_f"].shape[0]
        N = v * pp
        sent_a = [None] * pp  # tag carried on the fwd ring
        sent_c = [None] * pp
        inbox_a = [dict() for _ in range(pp)]  # (c, slot) -> tag
        inbox_c = [dict() for _ in range(pp)]
        consumed_f = set()  # acts awaiting consumption, by (g, m)
        pending_a = [dict() for _ in range(pp)]  # (c,slot) -> (g,m) live
        pending_c = [dict() for _ in range(pp)]
        for t in range(T):
            recv_a = [sent_a[(d - 1) % pp] for d in range(pp)]
            recv_c = [sent_c[(d + 1) % pp] for d in range(pp)]
            for d in range(pp):
                if s["ra_v"][t, d]:
                    key = (s["ra_c"][t, d], s["ra_s"][t, d])
                    # overwrite of a live (unconsumed) act = data loss
                    assert key not in pending_a[d], (pp, v, t, d, key)
                    assert recv_a[d] is not None
                    inbox_a[d][key] = recv_a[d]
                    pending_a[d][key] = recv_a[d]
                if s["rc_v"][t, d]:
                    key = (s["rc_c"][t, d], s["rc_s"][t, d])
                    assert key not in pending_c[d], (pp, v, t, d, key)
                    assert recv_c[d] is not None
                    inbox_c[d][key] = recv_c[d]
                    pending_c[d][key] = recv_c[d]
            new_sent_a = list(sent_a)
            new_sent_c = list(sent_c)
            for d in range(pp):
                if s["do_f"][t, d]:
                    c, m = s["f_c"][t, d], s["f_idx"][t, d]
                    g = c * pp + d
                    if g > 0:
                        key = (c, m % S)
                        got = inbox_a[d].get(key)
                        assert got == ("act", g - 1, m), (
                            pp, v, t, d, g, m, got
                        )
                        pending_a[d].pop(key, None)
                    new_sent_a[d] = ("act", g, m)
                if s["do_b"][t, d]:
                    c, m = s["b_c"][t, d], s["b_idx"][t, d]
                    g = c * pp + d
                    if g < N - 1:
                        key = (c, m % S)
                        got = inbox_c[d].get(key)
                        assert got == ("cot", g + 1, m), (
                            pp, v, t, d, g, m, got
                        )
                        pending_c[d].pop(key, None)
                    new_sent_c[d] = ("cot", g, m)
            sent_a, sent_c = new_sent_a, new_sent_c


def test_1f1b_matches_autodiff_oracle(hvd, rng):
    """pp=4 pipeline of nonlinear stages: (loss, per-stage grads) from
    pipeline_1f1b must equal jax.value_and_grad of the composed model
    on the full microbatch set."""
    from horovod_tpu.parallel.pipeline import pipeline_1f1b

    n_micro, bm, d = 7, 2, 8
    pp = 4
    mesh = Mesh(np.asarray(jax.devices()[:4]), ("pp",))
    x = rng.normal(size=(n_micro, bm, d)).astype(np.float32)
    y = rng.normal(size=(n_micro, bm, d)).astype(np.float32)
    w = (0.5 * rng.normal(size=(pp, d, d))).astype(np.float32)
    b = (0.1 * rng.normal(size=(pp, d))).astype(np.float32)

    def stage_fn(params, xb):
        ws, bs = params
        return jnp.tanh(xb @ ws + bs)

    def loss_fn(out, tgt):
        return jnp.mean((out - tgt) ** 2)

    def per_device(x, y, w_shard, b_shard):
        loss, grads = pipeline_1f1b(
            stage_fn,
            loss_fn,
            (w_shard[0], b_shard[0]),
            x,
            y,
            axis_name="pp",
        )
        # re-add the leading stage axis so out_specs=P("pp") stacks
        # per-stage grads back into the [pp, ...] layout of the inputs
        return loss, jax.tree.map(lambda g: g[None], grads)

    loss, grads = jax.jit(
        jax.shard_map(
            per_device,
            mesh=mesh,
            in_specs=(P(), P(), P("pp"), P("pp")),
            out_specs=(P(), P("pp")),
            check_vma=False,
        )
    )(x, y, w, b)

    def full_loss(params):
        w_all, b_all = params
        total = 0.0
        for m in range(n_micro):
            h = x[m]
            for s in range(pp):
                h = jnp.tanh(h @ w_all[s] + b_all[s])
            total = total + loss_fn(h, y[m])
        return total / n_micro

    ref_loss, (ref_dw, ref_db) = jax.value_and_grad(full_loss)((w, b))
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(grads[0]), np.asarray(ref_dw), rtol=1e-4, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(grads[1]), np.asarray(ref_db), rtol=1e-4, atol=1e-5
    )


def test_1f1b_tail_params_and_input_cotangents(hvd, rng):
    """The full-model composition surface: a parameterized loss tail
    (loss_params) and input cotangents (return_dx) — both must match
    the end-to-end autodiff oracle, enabling embed-front + head-tail
    models around the pipelined stack."""
    from horovod_tpu.parallel.pipeline import pipeline_1f1b

    n_micro, bm, d = 5, 2, 8
    pp = 4
    mesh = Mesh(np.asarray(jax.devices()[:4]), ("pp",))
    x = rng.normal(size=(n_micro, bm, d)).astype(np.float32)
    y = rng.normal(size=(n_micro, bm, d)).astype(np.float32)
    w = (0.5 * rng.normal(size=(pp, d, d))).astype(np.float32)
    w_tail = (0.5 * rng.normal(size=(d, d))).astype(np.float32)

    def stage_fn(params, xb):
        return jnp.tanh(xb @ params)

    def tail_loss(tail, out, tgt):
        return jnp.mean((out @ tail - tgt) ** 2)

    def per_device(x, y, w_shard, w_tail):
        loss, grads, tail_grads, dx = pipeline_1f1b(
            stage_fn,
            tail_loss,
            w_shard[0],
            x,
            y,
            axis_name="pp",
            loss_params=w_tail,
            return_dx=True,
        )
        stage = lax.axis_index("pp")
        # dx is valid on stage 0; broadcast for a replicated output
        dx = lax.psum(
            jnp.where(stage == 0, dx, jnp.zeros_like(dx)), "pp"
        )
        return loss, grads[None], tail_grads, dx

    loss, gw, gtail, gx = jax.jit(
        jax.shard_map(
            per_device,
            mesh=mesh,
            in_specs=(P(), P(), P("pp"), P()),
            out_specs=(P(), P("pp"), P(), P()),
            check_vma=False,
        )
    )(x, y, w, w_tail)

    def full_loss(w_all, tail, xin):
        total = 0.0
        for m in range(n_micro):
            h = xin[m]
            for s in range(pp):
                h = jnp.tanh(h @ w_all[s])
            total = total + tail_loss(tail, h, y[m])
        return total / n_micro

    ref_loss, (rw, rtail, rx) = jax.value_and_grad(
        full_loss, argnums=(0, 1, 2)
    )(w, w_tail, x)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(gw), np.asarray(rw), rtol=1e-4, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(gtail), np.asarray(rtail), rtol=1e-4, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(gx), np.asarray(rx), rtol=1e-4, atol=1e-5
    )


@pytest.mark.parametrize("pp,v", [(2, 2), (4, 2)])
def test_1f1b_interleaved_matches_autodiff_oracle(hvd, rng, pp, v):
    """Interleaved 1F1B (v chunks/device, pp*v global stages): loss,
    per-chunk stage grads, tail grads, and input cotangents must all
    match the composed autodiff oracle. Chunk c on device s is global
    stage c*pp + s (Megatron layout); pp=4 exercises the ring wrap
    with non-trivial neighbors at runtime."""
    from horovod_tpu.parallel.pipeline import pipeline_1f1b

    n_micro, bm, d = 5, 2, 8
    N = pp * v
    mesh = Mesh(np.asarray(jax.devices()[:pp]), ("pp",))
    x = rng.normal(size=(n_micro, bm, d)).astype(np.float32)
    y = rng.normal(size=(n_micro, bm, d)).astype(np.float32)
    w_global = (0.5 * rng.normal(size=(N, d, d))).astype(np.float32)
    w_tail = (0.5 * rng.normal(size=(d, d))).astype(np.float32)
    # device-major layout: w_dev[s, c] = w_global[c*pp + s]
    w_dev = np.stack(
        [[w_global[c * pp + s] for c in range(v)] for s in range(pp)]
    )

    def stage_fn(params, xb):
        return jnp.tanh(xb @ params)

    def tail_loss(tail, out, tgt):
        return jnp.mean((out @ tail - tgt) ** 2)

    def per_device(x, y, w_shard, w_tail):
        loss, grads, tail_grads, dx = pipeline_1f1b(
            stage_fn,
            tail_loss,
            w_shard[0],  # [v, d, d]
            x,
            y,
            axis_name="pp",
            loss_params=w_tail,
            return_dx=True,
            virtual_stages=v,
        )
        stage = lax.axis_index("pp")
        dx = lax.psum(
            jnp.where(stage == 0, dx, jnp.zeros_like(dx)), "pp"
        )
        return loss, grads[None], tail_grads, dx

    loss, gw, gtail, gx = jax.jit(
        jax.shard_map(
            per_device,
            mesh=mesh,
            in_specs=(P(), P(), P("pp"), P()),
            out_specs=(P(), P("pp"), P(), P()),
            check_vma=False,
        )
    )(x, y, w_dev, w_tail)

    def full_loss(w_all, tail, xin):
        total = 0.0
        for m in range(n_micro):
            h = xin[m]
            for g in range(N):
                h = jnp.tanh(h @ w_all[g])
            total = total + tail_loss(tail, h, y[m])
        return total / n_micro

    ref_loss, (rw, rtail, rx) = jax.value_and_grad(
        full_loss, argnums=(0, 1, 2)
    )(w_global, w_tail, x)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    # gw: [pp, v, d, d] device-major; map back to global stage order
    gw = np.asarray(gw)
    for g in range(N):
        s, c = g % pp, g // pp
        np.testing.assert_allclose(
            gw[s, c], np.asarray(rw[g]), rtol=1e-4, atol=1e-5,
            err_msg=f"stage grad mismatch at global stage {g}",
        )
    np.testing.assert_allclose(
        np.asarray(gtail), np.asarray(rtail), rtol=1e-4, atol=1e-5
    )
    np.testing.assert_allclose(
        np.asarray(gx), np.asarray(rx), rtol=1e-4, atol=1e-5
    )


def test_1f1b_activation_memory_bounded(hvd, rng):
    """The 1F1B claim in numbers: growing n_micro 4x must NOT grow the
    schedule's live activation buffers — they are [v, max_in_flight+1]
    stashes (default window 2·pp+1), O(pp) and independent of n_micro
    — while gpipe-with-autodiff's backward grows O(n_micro). Measured
    on the compiled executable's buffer assignment when the backend
    reports it."""
    from horovod_tpu.parallel.pipeline import pipeline_1f1b

    pp, bm, d = 4, 4, 64
    mesh = Mesh(np.asarray(jax.devices()[:4]), ("pp",))

    def stage_fn(params, xb):
        return jnp.tanh(xb @ params)

    def loss_fn(out, tgt):
        return jnp.mean((out - tgt) ** 2)

    def build(n_micro):
        x = jnp.zeros((n_micro, bm, d), jnp.float32)
        y = jnp.zeros((n_micro, bm, d), jnp.float32)
        w = jnp.zeros((pp, d, d), jnp.float32)

        def per_device(x, y, w_shard):
            return pipeline_1f1b(
                stage_fn, loss_fn, w_shard[0], x, y, axis_name="pp"
            )

        fn = jax.jit(
            jax.shard_map(
                per_device,
                mesh=mesh,
                in_specs=(P(), P(), P("pp")),
                out_specs=(P(), P("pp")),
                check_vma=False,
            )
        )
        return fn.lower(x, y, w).compile()

    small = build(8).memory_analysis()
    big = build(32).memory_analysis()
    if small is None or not hasattr(small, "temp_size_in_bytes"):
        pytest.skip("backend reports no memory analysis")
    # temp (activation working set) must not scale with n_micro; the
    # argument/output buffers legitimately grow (x_micro itself).
    micro_bytes = bm * d * 4
    assert big.temp_size_in_bytes <= small.temp_size_in_bytes + (
        8 * micro_bytes  # slack: scheduler noise, not 24 extra micros
    ), (small.temp_size_in_bytes, big.temp_size_in_bytes)


def test_moe_matches_dense_routing(hvd, rng):
    """ep-sharded MoE == locally computed top-1 routing (big capacity,
    no drops)."""
    ep, t_local, d, f = 4, 8, 16, 32
    n_exp = 4  # one expert per chip
    mesh = Mesh(np.asarray(jax.devices()[:4]), ("ep",))
    key = jax.random.PRNGKey(0)
    full = init_moe_params(key, d, f, n_exp, n_exp)  # all experts
    x = rng.normal(size=(ep, t_local, d)).astype(np.float32)

    out = jax.jit(
        jax.shard_map(
            lambda p, xb: moe_ffn(p, xb[0], "ep", capacity_factor=8.0)[None],
            mesh=mesh,
            in_specs=(
                type(full)(
                    router=P(), w1=P("ep"), b1=P("ep"), w2=P("ep"), b2=P("ep")
                ),
                P("ep"),
            ),
            out_specs=P("ep"),
            check_vma=False,
        )
    )(full, x)

    # dense reference over all tokens
    xs = x.reshape(-1, d)
    logits = xs @ np.asarray(full.router)
    probs = jax.nn.softmax(jnp.asarray(logits), axis=-1)
    eidx = np.argmax(np.asarray(probs), -1)
    gate = np.take_along_axis(np.asarray(probs), eidx[:, None], 1)[:, 0]
    ref = np.zeros_like(xs)
    for i, (e, g) in enumerate(zip(eidx, gate)):
        h = xs[i] @ np.asarray(full.w1[e]) + np.asarray(full.b1[e])
        h = np.asarray(jax.nn.gelu(jnp.asarray(h)))
        ref[i] = (h @ np.asarray(full.w2[e]) + np.asarray(full.b2[e])) * g
    np.testing.assert_allclose(
        np.asarray(out).reshape(-1, d), ref, rtol=2e-3, atol=2e-4
    )


def test_mesh_spec():
    spec = MeshSpec.auto(8, tp=2, sp=2)
    assert spec.dp == 2 and spec.size == 8
    mesh = spec.build(jax.devices())
    assert mesh.axis_names == ("dp", "pp", "ep", "sp", "tp")
    assert mesh.devices.shape == (2, 1, 1, 2, 2)
    with pytest.raises(ValueError):
        MeshSpec.auto(8, tp=3)
    with pytest.raises(ValueError):
        MeshSpec(dp=3).build(jax.devices())


def _run_steps(spec, n_steps=1, lr=0.05, seed=0, **cfg_overrides):
    import jax

    from horovod_tpu.parallel.transformer import (
        ParallelTransformerConfig,
        make_sharded_params,
        make_train_step,
    )

    cfg = ParallelTransformerConfig(
        vocab_size=64,
        num_layers=2,
        d_model=16,
        num_heads=2,
        d_ff=32,
        max_len=32,
        n_experts=2,
        n_microbatches=2,
        moe_capacity_factor=8.0,  # no drops → layout-independent routing
        learning_rate=lr,
        **cfg_overrides,
    )
    mesh = spec.build(jax.devices()[: spec.size])
    params = make_sharded_params(cfg, mesh, jax.random.PRNGKey(seed))
    step = make_train_step(cfg, mesh)
    rng = np.random.default_rng(seed)
    tokens = rng.integers(0, 64, size=(4, 16)).astype(np.int32)
    labels = np.roll(tokens, -1, axis=1).astype(np.int32)
    losses = []
    for _ in range(n_steps):
        params, loss = step(params, tokens, labels)
        losses.append(float(loss))
    full = jax.tree_util.tree_map(np.asarray, jax.device_get(params))
    return full, losses


@pytest.mark.parametrize(
    "spec",
    [
        MeshSpec(dp=2, sp=2, tp=2),
        MeshSpec(dp=2, pp=2, ep=2),
        MeshSpec(pp=2, sp=2, tp=2),
        MeshSpec(ep=2, sp=2, tp=2),
    ],
)
def test_parallel_step_matches_dp_baseline(hvd, spec):
    """One train step must produce the SAME parameters on every mesh
    factorization (catches wrong gradient-sync scaling per axis — the
    pp/ep/tp over-count class of bug)."""
    base_params, base_losses = _run_steps(MeshSpec(dp=2), n_steps=1)
    test_params, test_losses = _run_steps(spec, n_steps=1)
    np.testing.assert_allclose(base_losses, test_losses, rtol=1e-5)

    flat_base, _ = jax.tree_util.tree_flatten_with_path(base_params)
    flat_test = jax.tree_util.tree_leaves(test_params)
    for (path, b), t in zip(flat_base, flat_test):
        np.testing.assert_allclose(
            b,
            t,
            rtol=5e-4,
            atol=1e-5,
            err_msg=f"param mismatch under {spec} at {jax.tree_util.keystr(path)}",
        )


def test_parallel_step_1f1b_matches_gpipe_schedule(hvd):
    """The two pipeline schedules are different DATAFLOWS of the same
    math: one train step on a pp=2 mesh must produce identical loss
    and parameters under both (ample MoE capacity — per-micro vs
    full-batch expert capacity is the one documented divergence)."""
    g_params, g_losses = _run_steps(
        MeshSpec(dp=2, pp=2, ep=2), n_steps=1, pipeline_schedule="gpipe"
    )
    f_params, f_losses = _run_steps(
        MeshSpec(dp=2, pp=2, ep=2), n_steps=1, pipeline_schedule="1f1b"
    )
    np.testing.assert_allclose(g_losses, f_losses, rtol=1e-5)
    flat_g, _ = jax.tree_util.tree_flatten_with_path(g_params)
    flat_f = jax.tree_util.tree_leaves(f_params)
    for (path, b), t in zip(flat_g, flat_f):
        np.testing.assert_allclose(
            b, t, rtol=5e-4, atol=1e-5,
            err_msg=f"schedule mismatch at {jax.tree_util.keystr(path)}",
        )


def test_parallel_step_flash_ring_matches_dp_baseline(hvd):
    """The composed transformer with the flash-block ring engine
    (flash_ring=True — interpret-mode kernels on CPU) must take the
    SAME training step as the dense-ring dp baseline."""
    base_params, base_losses = _run_steps(MeshSpec(dp=2), n_steps=1)
    test_params, test_losses = _run_steps(
        MeshSpec(dp=2, sp=2, tp=2), n_steps=1, flash_ring=True
    )
    np.testing.assert_allclose(base_losses, test_losses, rtol=1e-5)
    flat_base, _ = jax.tree_util.tree_flatten_with_path(base_params)
    flat_test = jax.tree_util.tree_leaves(test_params)
    for (path, b), t in zip(flat_base, flat_test):
        np.testing.assert_allclose(
            b, t, rtol=5e-4, atol=1e-5,
            err_msg=f"flash-ring param mismatch at {jax.tree_util.keystr(path)}",
        )


@pytest.mark.parametrize(
    "spec",
    [
        MeshSpec(dp=2, sp=2, tp=2),
        MeshSpec(dp=2, pp=2, ep=2),
        MeshSpec(pp=2, sp=2, tp=2),
    ],
)
def test_parallel_transformer_trains(hvd, spec):
    """Full composed train step: loss decreases under every axis combo."""
    from horovod_tpu.parallel.transformer import (
        ParallelTransformerConfig,
        make_sharded_params,
        make_train_step,
    )

    cfg = ParallelTransformerConfig(
        vocab_size=64,
        num_layers=2,
        d_model=16,
        num_heads=2,
        d_ff=32,
        max_len=32,
        n_experts=2,
        n_microbatches=2,
        learning_rate=0.05,
    )
    mesh = spec.build(jax.devices())
    params = make_sharded_params(cfg, mesh, jax.random.PRNGKey(0))
    step = make_train_step(cfg, mesh)

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 64, size=(4, 16)).astype(np.int32)
    labels = np.roll(tokens, -1, axis=1).astype(np.int32)
    losses = []
    for _ in range(8):
        params, loss = step(params, tokens, labels)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses
    assert np.isfinite(losses).all()


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_gradients_match_dense(hvd, rng, causal):
    """The second-ring-pass VJP must reproduce dense-attention gradients
    for q, k and v exactly (round-3: without the custom VJP, autodiff
    through the forward scan checkpointed O(sp·T_local²) score blocks)."""
    b, t, h, d = 1, 32, 2, 8
    q = rng.normal(size=(b, t, h, d)).astype(np.float32)
    k = rng.normal(size=(b, t, h, d)).astype(np.float32)
    v = rng.normal(size=(b, t, h, d)).astype(np.float32)
    w = rng.normal(size=(b, t, h, d)).astype(np.float32)
    mesh = mesh_1d("sp")

    def ring_loss(q, k, v, w):
        # local term only: psum'ing the loss would double-count the
        # cotangent (transpose of psum is psum), scaling grads by sp
        o = ring_attention(q, k, v, "sp", causal=causal)
        return jnp.sum(o * w)

    grad_fn = jax.jit(
        jax.shard_map(
            lambda q, k, v, w: jax.grad(ring_loss, argnums=(0, 1, 2))(
                q, k, v, w
            ),
            mesh=mesh,
            in_specs=P(None, "sp"),
            out_specs=P(None, "sp"),
            check_vma=False,
        )
    )
    gq, gk, gv = grad_fn(q, k, v, w)

    def dense_loss(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal) * w)

    dq, dk, dv = jax.grad(dense_loss, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)
    )
    np.testing.assert_allclose(np.asarray(gq), np.asarray(dq), rtol=5e-4,
                               atol=5e-5)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(dk), rtol=5e-4,
                               atol=5e-5)
    np.testing.assert_allclose(np.asarray(gv), np.asarray(dv), rtol=5e-4,
                               atol=5e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_attention_matches_dense(hvd, rng, causal):
    """The flash-block ring (Pallas kernels per hop + online (o, lse)
    merge) must agree with the dense oracle — interpret-mode kernels on
    the CPU mesh, fp32, so tolerances stay tight."""
    from horovod_tpu.parallel.ring_attention import ring_flash_attention

    b, t, h, d = 2, 64, 2, 8  # 8 tokens per chip — flash-tileable
    q = rng.normal(size=(b, t, h, d)).astype(np.float32)
    k = rng.normal(size=(b, t, h, d)).astype(np.float32)
    v = rng.normal(size=(b, t, h, d)).astype(np.float32)
    mesh = mesh_1d("sp")
    f = jax.jit(
        jax.shard_map(
            lambda q, k, v: ring_flash_attention(
                q, k, v, "sp", causal=causal
            ),
            mesh=mesh,
            in_specs=P(None, "sp"),
            out_specs=P(None, "sp"),
            check_vma=False,
        )
    )
    out = f(q, k, v)
    expected = dense_attention(q, k, v, causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(expected), rtol=2e-4, atol=2e-5
    )


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_attention_gradients_match_dense(hvd, rng, causal):
    """The flash-bwd-per-hop second ring pass (global lse handed to the
    Pallas dq/dkv kernels) must reproduce dense gradients."""
    from horovod_tpu.parallel.ring_attention import ring_flash_attention

    b, t, h, d = 1, 64, 2, 8
    q = rng.normal(size=(b, t, h, d)).astype(np.float32)
    k = rng.normal(size=(b, t, h, d)).astype(np.float32)
    v = rng.normal(size=(b, t, h, d)).astype(np.float32)
    w = rng.normal(size=(b, t, h, d)).astype(np.float32)
    mesh = mesh_1d("sp")

    def ring_loss(q, k, v, w):
        o = ring_flash_attention(q, k, v, "sp", causal=causal)
        return jnp.sum(o * w)

    grad_fn = jax.jit(
        jax.shard_map(
            lambda q, k, v, w: jax.grad(ring_loss, argnums=(0, 1, 2))(
                q, k, v, w
            ),
            mesh=mesh,
            in_specs=P(None, "sp"),
            out_specs=P(None, "sp"),
            check_vma=False,
        )
    )
    gq, gk, gv = grad_fn(q, k, v, w)

    def dense_loss(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal) * w)

    dq, dk, dv = jax.grad(dense_loss, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)
    )
    np.testing.assert_allclose(np.asarray(gq), np.asarray(dq), rtol=5e-4,
                               atol=5e-5)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(dk), rtol=5e-4,
                               atol=5e-5)
    np.testing.assert_allclose(np.asarray(gv), np.asarray(dv), rtol=5e-4,
                               atol=5e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_flash_gqa_matches_dense(hvd, rng, causal):
    """Long-context GQA on the ring: kv heads < q heads, per-hop
    shared-KV kernels — fwd + all grads vs the repeat-heads dense
    oracle."""
    from horovod_tpu.parallel.ring_attention import ring_flash_attention

    b, t, h, g, d = 1, 64, 4, 2, 8
    q = rng.normal(size=(b, t, h, d)).astype(np.float32)
    k = rng.normal(size=(b, t, g, d)).astype(np.float32)
    v = rng.normal(size=(b, t, g, d)).astype(np.float32)
    w = rng.normal(size=(b, t, h, d)).astype(np.float32)
    mesh = mesh_1d("sp")

    def ring_loss(q, k, v, w):
        o = ring_flash_attention(q, k, v, "sp", causal=causal)
        return jnp.sum(o * w)

    fwd_fn = jax.jit(
        jax.shard_map(
            lambda q, k, v: ring_flash_attention(
                q, k, v, "sp", causal=causal
            ),
            mesh=mesh,
            in_specs=P(None, "sp"),
            out_specs=P(None, "sp"),
            check_vma=False,
        )
    )
    out = fwd_fn(q, k, v)
    grad_fn = jax.jit(
        jax.shard_map(
            lambda q, k, v, w: jax.grad(ring_loss, argnums=(0, 1, 2))(
                q, k, v, w
            ),
            mesh=mesh,
            in_specs=P(None, "sp"),
            out_specs=P(None, "sp"),
            check_vma=False,
        )
    )
    gq, gk, gv = grad_fn(q, k, v, w)

    rep = h // g
    kk = jnp.repeat(jnp.asarray(k), rep, axis=2)
    vv = jnp.repeat(jnp.asarray(v), rep, axis=2)
    want = dense_attention(jnp.asarray(q), kk, vv, causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(want), rtol=2e-4, atol=2e-5
    )

    def dense_loss(q, k, v):
        rep_k = jnp.repeat(k, rep, axis=2)
        rep_v = jnp.repeat(v, rep, axis=2)
        return jnp.sum(dense_attention(q, rep_k, rep_v, causal) * w)

    dq, dk, dv = jax.grad(dense_loss, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)
    )
    np.testing.assert_allclose(np.asarray(gq), np.asarray(dq), rtol=5e-4,
                               atol=5e-5)
    np.testing.assert_allclose(np.asarray(gk), np.asarray(dk), rtol=5e-4,
                               atol=5e-5)
    np.testing.assert_allclose(np.asarray(gv), np.asarray(dv), rtol=5e-4,
                               atol=5e-5)


def test_dense_ring_gqa_matches_repeat_heads(hvd, rng):
    """Dense ring with grouped-query inputs: repeat OUTSIDE the custom
    VJP means dk/dv group-sum automatically — fwd + grads vs the
    repeat-heads oracle."""
    b, t, h, g, d = 1, 32, 4, 2, 8
    q = rng.normal(size=(b, t, h, d)).astype(np.float32)
    k = rng.normal(size=(b, t, g, d)).astype(np.float32)
    v = rng.normal(size=(b, t, g, d)).astype(np.float32)
    w = rng.normal(size=(b, t, h, d)).astype(np.float32)
    mesh = mesh_1d("sp")

    def ring_loss(q, k, v, w):
        o = ring_attention(q, k, v, "sp", causal=True)
        return jnp.sum(o * w)

    gq, gk, gv = jax.jit(
        jax.shard_map(
            lambda q, k, v, w: jax.grad(ring_loss, argnums=(0, 1, 2))(
                q, k, v, w
            ),
            mesh=mesh, in_specs=P(None, "sp"), out_specs=P(None, "sp"),
            check_vma=False,
        )
    )(q, k, v, w)
    rep = h // g

    def dense_loss(q, k, v):
        kk = jnp.repeat(k, rep, axis=2)
        vv = jnp.repeat(v, rep, axis=2)
        return jnp.sum(dense_attention(q, kk, vv, True) * w)

    dq, dk, dv = jax.grad(dense_loss, argnums=(0, 1, 2))(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v)
    )
    for got, want in ((gq, dq), (gk, dk), (gv, dv)):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=5e-4, atol=5e-5
        )


def test_parallel_step_rope_matches_dp_baseline(hvd):
    """RoPE x sequence parallelism: the per-shard rotation offset
    (axis_index * t_local) must reproduce the dp-only baseline's step
    exactly on an sp-bearing mesh — the relative-position property
    under real sharding."""
    base_params, base_losses = _run_steps(
        MeshSpec(dp=2), n_steps=1, rope=True
    )
    test_params, test_losses = _run_steps(
        MeshSpec(dp=2, sp=2, tp=2), n_steps=1, rope=True
    )
    np.testing.assert_allclose(base_losses, test_losses, rtol=1e-5)
    flat_base, _ = jax.tree_util.tree_flatten_with_path(base_params)
    flat_test = jax.tree_util.tree_leaves(test_params)
    for (path, b), t in zip(flat_base, flat_test):
        np.testing.assert_allclose(
            b, t, rtol=5e-4, atol=1e-5,
            err_msg=f"rope param mismatch at {jax.tree_util.keystr(path)}",
        )


@pytest.mark.parametrize("with_tail_params", [False, True], ids=str)
def test_1f1b_collective_free_loss_fast_path(hvd, rng, with_tail_params):
    """loss_collective_free=True (the tail fast path) must reproduce
    the mesh-uniform default bit-for-bit, while its lowered program
    carries a REAL conditional around the tail (the FLOPs are skipped,
    not masked — advisor r5's T·pp tail-tax finding)."""
    from functools import partial as _partial

    from horovod_tpu.parallel.pipeline import pipeline_1f1b

    n_micro, bm, d = 6, 2, 8
    pp = 4
    mesh = Mesh(np.asarray(jax.devices()[:pp]), ("pp",))
    x = rng.normal(size=(n_micro, bm, d)).astype(np.float32)
    y = rng.normal(size=(n_micro, bm, d)).astype(np.float32)
    w = (0.5 * rng.normal(size=(pp, d, d))).astype(np.float32)
    lp = {"s": jnp.asarray(1.3, jnp.float32)}

    def stage_fn(ws, xb):
        return jnp.tanh(xb @ ws)

    if with_tail_params:
        def loss_fn(p, out, tgt):
            return p["s"] * jnp.mean((out - tgt) ** 2)
    else:
        def loss_fn(out, tgt):
            return jnp.mean((out - tgt) ** 2)

    def make(fast):
        kwargs = dict(
            axis_name="pp", loss_collective_free=fast,
        )
        if with_tail_params:
            kwargs["loss_params"] = lp

        @_partial(
            jax.shard_map, mesh=mesh,
            in_specs=(P(), P(), P("pp")),
            out_specs=(
                (P(), P("pp"), P()) if with_tail_params
                else (P(), P("pp"))
            ),
            check_vma=False,
        )
        def f(xm, ym, ws):
            out = pipeline_1f1b(
                stage_fn, loss_fn, ws[0], xm, ym, **kwargs
            )
            grads = jax.tree.map(lambda g: g[None], out[1])
            return (
                (out[0], grads, out[2]) if with_tail_params
                else (out[0], grads)
            )

        return jax.jit(f)

    slow_out = make(False)(x, y, w)
    fast_fn = make(True)
    fast_out = fast_fn(x, y, w)
    np.testing.assert_array_equal(
        np.asarray(slow_out[0]), np.asarray(fast_out[0])
    )
    np.testing.assert_array_equal(
        np.asarray(slow_out[1]), np.asarray(fast_out[1])
    )
    if with_tail_params:
        np.testing.assert_array_equal(
            np.asarray(slow_out[2]["s"]), np.asarray(fast_out[2]["s"])
        )
    # the declaration produced a real branch, not a masked select
    # (lax.cond lowers to stablehlo.case on this path)
    assert "stablehlo.case" in fast_fn.lower(x, y, w).as_text()
