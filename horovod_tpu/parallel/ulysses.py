"""Ulysses-style all-to-all sequence parallelism.

The second of the two long-context strategies (the first is
``ring_attention``): instead of rotating K/V blocks around a ring,
**exchange sequence shards for head shards** with one all_to_all, run
ordinary full-sequence attention on each rank's subset of heads, and
exchange back (DeepSpeed-Ulysses; the reference ships the alltoall
primitive this rides [V: horovod/common/ops/*alltoall*] but no
sequence parallelism at all — SURVEY.md §2.6/§5.7).

Communication: 3 all_to_alls in (q, k, v) + 1 out, each moving
(sp−1)/sp of a [B, T/sp, H, D] shard — O(B·T·H·D/sp) per rank,
constant in sequence length per chip, vs the ring's (sp−1) hops of
K/V blocks. Ulysses wins when heads ≥ sp and the interconnect favors
few large transfers; ring wins when H < sp or memory for the full-
sequence scores is the binding constraint (here scores are computed
per head-shard over the FULL sequence: O(T²/ sp · H) total — use
ring attention for extreme T).

Use inside ``shard_map`` with the sequence axis sharded:

    out = ulysses_attention(q, k, v, axis_name="sp", causal=True)

q/k/v: [batch, seq_local, heads, head_dim]; heads % sp == 0.
Differentiable (all_to_all is linear; XLA autodiffs through it).
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax


# VMEM guard for the flash auto-gate: the flash kernels stage whole-
# sequence K/V per program, so past this full-sequence length the auto
# choice falls back to the dense path (explicit attn_fn overrides).
_FLASH_AUTO_MAX_SEQ = 8192


def _dense_attention(q, k, v, causal: bool):
    """fp32-softmax reference attention over [B, T, H, D] — the SAME
    precision convention as the repo-wide test oracle
    (tests/conftest.py dense_attention_oracle): fp32 scores, fp32
    probability-value matmul, cast at the end. Grouped-query inputs
    (fewer kv heads) are repeated here — the flash path shares rows
    instead."""
    d = q.shape[-1]
    if k.shape[2] != q.shape[2]:
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
    ) / jnp.sqrt(jnp.asarray(d, jnp.float32))
    if causal:
        t_q, t_k = s.shape[-2], s.shape[-1]
        rows = lax.broadcasted_iota(jnp.int32, (t_q, t_k), 0)
        cols = lax.broadcasted_iota(jnp.int32, (t_q, t_k), 1)
        s = jnp.where(rows[None, None] >= cols[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum(
        "bhqk,bkhd->bqhd", p, v.astype(jnp.float32)
    ).astype(q.dtype)


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str = "sp",
    causal: bool = False,
    attn_fn: Optional[Callable] = None,
) -> jax.Array:
    """All-to-all sequence-parallel attention (module docstring).

    ``attn_fn(q, k, v, causal)`` runs the full-sequence attention on
    the head shard. Default (None) resolves at trace time the same way
    the composed transformer's ``flash_ring='auto'`` does: the Pallas
    flash kernel on TPU when the FULL sequence (sp·t_local — that is
    what the inner attention sees post-exchange) is flash-tileable,
    the dense fp32-softmax oracle otherwise. Pass a callable to
    override either way.
    """
    sp = lax.axis_size(axis_name)
    b, t_local, h, d = q.shape
    if attn_fn is None:
        from ..ops.flash_attention import (
            fits_vmem,
            flash_attention,
            supports_seq,
        )

        full_t = t_local * sp
        # The kernels stage K and V whole-sequence in VMEM per program,
        # so the auto-gate also caps the post-exchange sequence length
        # (~2 MB per bf16 operand at 8192·128 — comfortably inside a
        # v5e core's ~16 MB VMEM; beyond that, per the module
        # docstring, extreme T is ring territory). With grouped-query
        # inputs the backward dK/dV kernel stages r-fold more, so the
        # gate also checks the VMEM budget (ADVICE r4). Pass attn_fn
        # explicitly to override.
        if (
            jax.default_backend() == "tpu"
            and supports_seq(full_t)
            and full_t <= _FLASH_AUTO_MAX_SEQ
            and fits_vmem(
                full_t,
                d,
                q.shape[2] // k.shape[2],
                q.dtype.itemsize,
            )
        ):
            attn_fn = flash_attention
    kv_h = k.shape[2]
    if h % sp or kv_h % sp:
        # kv heads must ALSO split evenly (grouped-query inputs): each
        # rank then holds whole q-head groups, so the post-exchange
        # local q-head -> kv-head map stays the kernel's contiguous
        # x // (h/g) rule.
        raise ValueError(
            f"ulysses_attention needs q heads ({h}) and kv heads "
            f"({kv_h}) divisible by the sequence-parallel axis size "
            f"({sp}); use ring_attention for head-poor models"
        )
    if v.shape[2] != kv_h or h % kv_h:
        raise ValueError(
            "kv heads must match and divide q heads: "
            f"q={h}, k={kv_h}, v={v.shape[2]}"
        )

    def seq_to_heads(x):
        # [B, T/sp, H, D] -> [B, T, H/sp, D]: ship head-group j to rank
        # j (tiled split of the head dim, group-major) while collecting
        # every rank's sequence shard (rank-major concat = seq order)
        return lax.all_to_all(
            x, axis_name, split_axis=2, concat_axis=1, tiled=True
        )

    def heads_to_seq(x):
        # [B, T, H/sp, D] -> [B, T/sp, H, D]: the inverse exchange
        return lax.all_to_all(
            x, axis_name, split_axis=1, concat_axis=2, tiled=True
        )

    qg = seq_to_heads(q)
    kg = seq_to_heads(k)
    vg = seq_to_heads(v)
    attn = attn_fn or _dense_attention
    out = attn(qg, kg, vg, causal)
    return heads_to_seq(out.astype(q.dtype))
