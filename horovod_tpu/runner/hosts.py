"""Host / slot bookkeeping for the launcher.

TPU-native rebuild of the reference's host parsing and slot assignment
(ref: horovod/runner/launch.py + horovod/runner/elastic/driver.py slot
math [V] — SURVEY.md §2.5; empty mount, structural citations).

A "host" is a TPU-VM worker (one process per chip by default); a "slot"
is one rank. ``assign_slots`` produces the rank/local_rank/cross_rank
numbering the env contract exposes: ranks are dense in host order, the
same ordering the reference derives from its hostfile.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Sequence


@dataclasses.dataclass(frozen=True)
class HostInfo:
    hostname: str
    slots: int

    @staticmethod
    def from_string(spec: str) -> "HostInfo":
        """Parse ``host:slots`` (``host`` alone means 1 slot). IPv6
        addresses use brackets: ``[::1]:4``; a bare multi-colon string
        is taken whole as an IPv6 hostname with 1 slot."""
        spec = spec.strip()
        if not spec:
            raise ValueError("empty host spec")
        if spec.startswith("["):
            addr, bracket, rest = spec.partition("]")
            if not bracket:
                raise ValueError(f"unterminated '[' in host spec {spec!r}")
            host = addr[1:]
            if not rest:
                n = 1
            elif rest.startswith(":"):
                try:
                    n = int(rest[1:])
                except ValueError:
                    raise ValueError(f"bad slot count in host spec {spec!r}")
            else:
                raise ValueError(f"bad host spec {spec!r}")
        elif spec.count(":") == 1:
            host, _, slots = spec.partition(":")
            try:
                n = int(slots)
            except ValueError:
                raise ValueError(f"bad slot count in host spec {spec!r}")
        else:
            host, n = spec, 1
        if not host:
            raise ValueError(f"empty hostname in host spec {spec!r}")
        if n < 1:
            raise ValueError(f"slot count must be >= 1 in {spec!r}")
        return HostInfo(host, n)


@dataclasses.dataclass(frozen=True)
class SlotInfo:
    """One rank's coordinates — exactly the fields of the reference's env
    contract (HOROVOD_RANK/SIZE/LOCAL_RANK/LOCAL_SIZE/CROSS_RANK/
    CROSS_SIZE [V])."""

    hostname: str
    rank: int
    size: int
    local_rank: int
    local_size: int
    cross_rank: int
    cross_size: int

    def to_env(self) -> Dict[str, str]:
        return {
            "HOROVOD_HOSTNAME": self.hostname,
            "HOROVOD_RANK": str(self.rank),
            "HOROVOD_SIZE": str(self.size),
            "HOROVOD_LOCAL_RANK": str(self.local_rank),
            "HOROVOD_LOCAL_SIZE": str(self.local_size),
            "HOROVOD_CROSS_RANK": str(self.cross_rank),
            "HOROVOD_CROSS_SIZE": str(self.cross_size),
        }


def parse_hosts(hosts: str) -> List[HostInfo]:
    """Parse ``host1:4,host2:4`` (commas or whitespace)."""
    specs = [s for s in re.split(r"[,\s]+", hosts.strip()) if s]
    if not specs:
        raise ValueError(f"no hosts in {hosts!r}")
    out = [HostInfo.from_string(s) for s in specs]
    seen = set()
    for h in out:
        if h.hostname in seen:
            raise ValueError(f"duplicate host {h.hostname!r}")
        seen.add(h.hostname)
    return out


def parse_hostfile(path: str) -> List[HostInfo]:
    """One ``host slots=N`` (or ``host:N`` / bare ``host``) per line;
    ``#`` comments allowed — the reference accepts the mpirun-style
    ``slots=`` form [V]."""
    hosts: List[HostInfo] = []
    with open(path) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            m = re.match(r"^(\S+)\s+slots\s*=\s*(\d+)\s*$", line)
            if m:
                hosts.append(HostInfo(m.group(1), int(m.group(2))))
            else:
                hosts.append(HostInfo.from_string(line))
    if not hosts:
        raise ValueError(f"hostfile {path!r} contains no hosts")
    seen = set()
    for h in hosts:
        if h.hostname in seen:
            raise ValueError(f"duplicate host {h.hostname!r} in hostfile")
        seen.add(h.hostname)
    return hosts


def assign_slots(hosts: Sequence[HostInfo], np: int) -> List[SlotInfo]:
    """Dense rank assignment over hosts in order, matching the
    reference's numbering: rank-major by host, local_rank within host,
    cross_rank = index of the host among used hosts (ranks with the same
    local_rank form a cross set) [V]."""
    capacity = sum(h.slots for h in hosts)
    if np < 1:
        raise ValueError("np must be >= 1")
    if np > capacity:
        raise ValueError(
            f"requested np={np} exceeds total slots {capacity} across "
            f"{len(hosts)} host(s)"
        )
    # How many ranks land on each host (fill hosts in order).
    remaining = np
    per_host: List[int] = []
    for h in hosts:
        take = min(h.slots, remaining)
        per_host.append(take)
        remaining -= take
    used = [(h, n) for h, n in zip(hosts, per_host) if n > 0]
    cross_size = len(used)
    slots: List[SlotInfo] = []
    rank = 0
    for cross_rank, (h, n) in enumerate(used):
        for local_rank in range(n):
            slots.append(
                SlotInfo(
                    hostname=h.hostname,
                    rank=rank,
                    size=np,
                    local_rank=local_rank,
                    local_size=n,
                    cross_rank=cross_rank,
                    cross_size=cross_size,
                )
            )
            rank += 1
    return slots
