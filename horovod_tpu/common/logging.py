"""The logging subsystem: HOROVOD_LOG_LEVEL / HOROVOD_LOG_TIMESTAMP.

TPU-native stand-in for the reference's logging.cc/.h (ref:
horovod/common/logging.cc — LOG(level) macros gated by
HOROVOD_LOG_LEVEL with an optional timestamp prefix controlled by
HOROVOD_LOG_TIMESTAMP [V], SURVEY.md §2.1). One module owns the
``horovod_tpu`` logger hierarchy; every subsystem (runner, elastic
driver, rendezvous, fusion cycles) gets its child logger here so the
env contract configures them all at once.

Level names match the reference's: trace, debug, info, warning, error,
fatal (trace maps to a level below DEBUG; fatal to CRITICAL).
"""

from __future__ import annotations

import logging
import os
import sys
from typing import Optional

TRACE = 5  # below logging.DEBUG, like the reference's TRACE [V]
logging.addLevelName(TRACE, "TRACE")

_LEVELS = {
    "trace": TRACE,
    "debug": logging.DEBUG,
    "info": logging.INFO,
    "warning": logging.WARNING,
    "error": logging.ERROR,
    "fatal": logging.CRITICAL,
    "off": logging.CRITICAL + 10,
}

_ROOT = "horovod_tpu"
_configured = False
_configured_explicitly = False  # a caller passed real args (not lazy)


def parse_level(name: Optional[str]) -> int:
    """HOROVOD_LOG_LEVEL value → numeric level; unknown names behave
    like the reference (fall back to warning)."""
    if not name:
        return logging.WARNING
    return _LEVELS.get(str(name).strip().lower(), logging.WARNING)


def configure(
    level: Optional[str] = None,
    timestamp: Optional[bool] = None,
    stream=None,
    force: bool = False,
) -> logging.Logger:
    """Configure the ``horovod_tpu`` logger from the env contract.

    Arguments override HOROVOD_LOG_LEVEL / HOROVOD_LOG_TIMESTAMP; called
    with defaults it reads the environment (so init() wires the whole
    tree with zero ceremony). Idempotent unless ``force``.
    """
    global _configured, _configured_explicitly
    explicit = (
        level is not None or timestamp is not None or stream is not None
    )
    root = logging.getLogger(_ROOT)
    if _configured and not force:
        return root
    if explicit:
        _configured_explicitly = True
    if level is None:
        level = os.environ.get("HOROVOD_LOG_LEVEL", "warning")
    if timestamp is None:
        raw = os.environ.get("HOROVOD_LOG_TIMESTAMP", "1")
        timestamp = str(raw).lower() not in ("0", "false", "no", "")
    fmt = (
        "[%(asctime)s] [%(levelname)s] %(name)s: %(message)s"
        if timestamp
        else "[%(levelname)s] %(name)s: %(message)s"
    )
    handler = logging.StreamHandler(stream or sys.stderr)
    handler.setFormatter(logging.Formatter(fmt))
    # Replace any prior horovod handler so force-reconfig doesn't stack.
    for h in list(root.handlers):
        root.removeHandler(h)
    root.addHandler(handler)
    root.setLevel(parse_level(level))
    root.propagate = False
    _configured = True
    return root


def configure_from_init(level: str, timestamp: bool) -> logging.Logger:
    """init()'s entry point: module-level ``get_logger`` calls already
    configured the tree lazily at import time, which would make a plain
    ``configure(...)`` a no-op; init's Config values must win over that
    lazy default — but never over an explicit programmatic
    ``configure(...)`` the user made first."""
    if _configured_explicitly:
        return logging.getLogger(_ROOT)
    return configure(level=level, timestamp=timestamp, force=True)


def get_logger(name: Optional[str] = None) -> logging.Logger:
    """Child logger under the horovod_tpu hierarchy (e.g.
    get_logger('fusion') → 'horovod_tpu.fusion'). Lazily configures the
    tree from the environment on first use."""
    configure()
    if not name:
        return logging.getLogger(_ROOT)
    return logging.getLogger(f"{_ROOT}.{name}")


def trace(logger: logging.Logger, msg: str, *args) -> None:
    """LOG(TRACE) spelling (logging has no .trace method)."""
    logger.log(TRACE, msg, *args)
