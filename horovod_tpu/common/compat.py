"""JAX cross-version compatibility shims.

The codebase (library, tests, benches, examples) is written against the
modern JAX surface: top-level ``jax.shard_map`` with the ``check_vma=``
kwarg (the >= 0.6/0.8 spelling). Older installs — the pinned 0.4.x
toolchain included — only ship ``jax.experimental.shard_map.shard_map``
with the equivalent kwarg spelled ``check_rep=``. This module bridges
both directions with ONE wrapper:

* ``compat.shard_map`` — call it like modern ``jax.shard_map``:
  ``shard_map(f, mesh=..., in_specs=..., out_specs=..., check_vma=...)``,
  the deferred/decorator form ``shard_map(mesh=..., ...)``(f), and
  ``functools.partial(shard_map, mesh=...)`` all work. ``check_vma`` /
  ``check_rep`` are accepted interchangeably and forwarded under
  whichever name the underlying implementation takes (dropped when it
  takes neither).
* ``compat.install()`` — publishes the wrapper as ``jax.shard_map``
  when the attribute is missing, so downstream code (tests, examples,
  user scripts) written against the modern spelling runs unmodified on
  old JAX. A real ``jax.shard_map`` is never shadowed.

``install()`` runs from ``horovod_tpu/__init__`` — importing the
package is enough to get a working ``jax.shard_map`` everywhere.
"""

from __future__ import annotations

import inspect

import jax as _jax

_native = getattr(_jax, "shard_map", None)
if _native is None or getattr(_native, "__horovod_tpu_shim__", False):
    from jax.experimental.shard_map import shard_map as _native  # type: ignore

_params = inspect.signature(_native).parameters
if "check_vma" in _params:
    _CHECK_KW = "check_vma"
elif "check_rep" in _params:
    _CHECK_KW = "check_rep"
else:
    _CHECK_KW = None


def shard_map(
    f=None,
    *,
    mesh=None,
    in_specs=None,
    out_specs=None,
    check_vma=None,
    check_rep=None,
    **kwargs,
):
    """Version-portable ``jax.shard_map``; see module docstring."""
    check = check_vma if check_vma is not None else check_rep

    def bind(fn):
        kw = dict(kwargs)
        kw["mesh"] = mesh
        kw["in_specs"] = in_specs
        kw["out_specs"] = out_specs
        if check is not None and _CHECK_KW is not None:
            kw[_CHECK_KW] = check
        return _native(fn, **kw)

    return bind if f is None else bind(f)


shard_map.__horovod_tpu_shim__ = True


def axis_size(axis_name):
    """``lax.axis_size`` on new JAX; on old JAX, ``psum(1, axis)`` of a
    static value — which JAX evaluates at trace time to the concrete
    axis size (the historical spelling of the same query)."""
    native = getattr(_jax.lax, "axis_size", None)
    if native is not None and not getattr(
        native, "__horovod_tpu_shim__", False
    ):
        return native(axis_name)
    return _jax.lax.psum(1, axis_name)


axis_size.__horovod_tpu_shim__ = True


def install() -> None:
    """Expose the wrappers as ``jax.shard_map`` / ``jax.lax.axis_size``
    on JAX versions that lack the modern names. Idempotent; never
    shadows a real implementation."""
    if getattr(_jax, "shard_map", None) is None:
        _jax.shard_map = shard_map
    if getattr(_jax.lax, "axis_size", None) is None:
        _jax.lax.axis_size = axis_size
