"""Flight-recorder telemetry tests (common/telemetry.py + satellites).

Covers the three faces of the hub — StepStats ring, live /metrics
scrape, cross-rank straggler ledger — plus the observability
satellites: delta-aware metrics dumps, stall gauges, the
timeline stop()-during-emit race, and the SIGTERM post-mortem dump
(the analog of the reference's kill-based elastic tests, SURVEY §4.3).
"""

import json
import os
import signal
import subprocess
import sys
import textwrap
import threading
import time
import urllib.request

import numpy as np
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _fresh_hub(**kw):
    from horovod_tpu.common.telemetry import TelemetryHub

    return TelemetryHub(**kw)


# ------------------------------------------------------------- the ring


class TestStepRing:
    def test_ring_bounded_and_ordered(self):
        hub = _fresh_hub(capacity=4)
        for _ in range(10):
            hub.step_begin()
            hub.step_end()
        recs = hub.records()
        assert len(recs) == 4  # bounded
        steps = [r["step"] for r in recs]
        assert steps == sorted(steps)
        assert steps == [6, 7, 8, 9]  # the LAST N, not the first
        assert all(r["wall_ms"] >= 0 for r in recs)

    def test_explicit_step_ids_thread_through(self):
        hub = _fresh_hub(capacity=8)
        hub.step_begin(100)
        hub.step_end()
        # auto ids continue monotonically after an explicit id
        hub.step_begin()
        rec = hub.step_end()
        assert rec["step"] == 101

    def test_begin_closes_open_record(self):
        """A loop that misses one step_end degrades to tick semantics
        instead of wedging the hub."""
        hub = _fresh_hub(capacity=8)
        hub.step_begin(0)
        hub.step_begin(1)  # implicitly closes step 0
        hub.step_end()
        assert [r["step"] for r in hub.records()] == [0, 1]

    def test_percentiles(self):
        hub = _fresh_hub(capacity=16)
        for _ in range(5):
            hub.step_begin()
            hub.step_end()
        pct = hub.percentiles()
        assert pct["count"] == 5
        assert 0 <= pct["p50"] <= pct["p95"]

    def test_records_capture_fusion_deltas(self, hvd):
        """The StepStats record carries what THIS step did on the wire
        (snapshot deltas of the fusion counters), not running totals."""
        import horovod_tpu as hvd_mod

        hub = _fresh_hub(capacity=8)
        x = np.stack([np.full((16,), float(r), np.float32) for r in range(8)])
        # one warmup dispatch so cumulative counters are nonzero before
        # the recorded step — a totals-vs-delta confusion would show
        hvd_mod.allreduce(x, op=hvd_mod.Sum, name="warm")
        hub.step_begin()
        hvd_mod.allreduce(x, op=hvd_mod.Sum, name="stepped")
        rec = hub.step_end()
        assert rec["fusion_dispatches"] == 1.0
        assert rec["fusion_cycles"] == 1.0
        assert rec["wire_bytes"] == x.nbytes  # the rank-major payload
        hub.step_begin()
        rec2 = hub.step_end()  # idle step: no wire movement
        assert rec2["fusion_dispatches"] == 0.0
        assert rec2["wire_bytes"] == 0.0

    def test_tick_stands_down_for_explicit_steps(self):
        hub = _fresh_hub(capacity=8)
        hub.step_begin(0)
        hub.step_end()
        hub.tick(99)  # explicit instrumentation closed a record: no-op
        assert [r["step"] for r in hub.records()] == [0]
        # with no other source, ticks record tick-to-tick steps
        hub.tick(10)
        hub.tick(11)
        hub.tick(12)
        steps = [r["step"] for r in hub.records()]
        assert steps == [0, 10, 11]

    def test_duplicate_ticks_after_close_are_noops(self):
        """Per-shard duplicate ticks can drain AFTER step_end closed
        the manual record — they must not insert bogus near-zero
        records (would drag p50 toward zero and corrupt the straggler
        ledger)."""
        hub = _fresh_hub(capacity=16)
        for step in range(3):
            hub.step_begin(step)
            hub.step_end()
            for _ in range(8):  # 8 shard callbacks of the same step
                hub.tick(step)
        steps = [r["step"] for r in hub.records()]
        assert steps == [0, 1, 2]

    def test_tape_tick_source_outranks_optimizer(self):
        """When value_and_grad (threaded hvd_step, source 'tape') and
        DistributedOptimizer (internal counter, source 'opt') both
        tick in one program with diverging ids, only one source may
        drive the recorder — otherwise every step splits into two
        fragment records."""
        hub = _fresh_hub(capacity=16)
        for i in range(4):
            hub.tick(1000 + i, source="tape")  # resumed global step
            hub.tick(i, source="opt")  # fresh optimizer counter
        steps = [r["step"] for r in hub.records()]
        assert steps == [1000, 1001, 1002]  # one record/step, tape ids
        # optimizer-only jobs still adopt "opt"
        hub2 = _fresh_hub(capacity=8)
        hub2.tick(0)
        hub2.tick(1)
        assert [r["step"] for r in hub2.records()] == [0]

    def test_device_step_tick_propagates_stall_escalation(self):
        """The stall inspector's shutdown escalation must not be
        swallowed by the tick's defensive except — it exists to kill a
        wedged job."""
        from horovod_tpu.common import telemetry
        from horovod_tpu.common.basics import HorovodInternalError

        telemetry._reset_hub()
        try:
            hub = telemetry.hub()

            class _Insp:
                def check(self):
                    raise HorovodInternalError("stalled")

            hub.stall_inspector = _Insp()
            hub.tick(0)  # opens
            with pytest.raises(HorovodInternalError):
                telemetry.device_step_tick(1)  # closes 0 -> check fires
        finally:
            telemetry._reset_hub()


# ---------------------------------------------------- flight recorder


class TestFlightRecorder:
    def test_dump_roundtrip(self, tmp_path):
        hub = _fresh_hub(capacity=8)
        for _ in range(3):
            hub.step_begin()
            hub.step_end()
        path = str(tmp_path / "flight.jsonl")
        assert hub.dump(path) == path
        recs = [json.loads(line) for line in open(path)]
        assert len(recs) == 3
        for rec in recs:
            assert {"step", "ts", "wall_ms", "exposed_collective_ms",
                    "hidden_collective_ms", "wire_bytes",
                    "wire_format"} <= set(rec)

    def test_dump_without_path_is_noop(self):
        hub = _fresh_hub(capacity=4)
        hub.step_begin()
        hub.step_end()
        assert hub.dump() is None

    def test_dump_is_signal_safe_under_held_lock(self, tmp_path):
        """The SIGTERM dump runs in a signal handler on the main
        thread; if the signal landed while that thread held the hub
        lock, a blocking acquire would deadlock the handler and eat
        the whole preemption grace window. dump() must complete
        anyway (bounded acquire + lock-free ring copy)."""
        hub = _fresh_hub(capacity=4)
        hub.step_begin()
        hub.step_end()
        path = str(tmp_path / "f.jsonl")
        hub._lock.acquire()  # simulate the interrupted critical section
        try:
            t0 = time.monotonic()
            assert hub.dump(path) == path
            assert time.monotonic() - t0 < 5.0
        finally:
            hub._lock.release()
        assert len([json.loads(l) for l in open(path)]) == 1

    def test_sigterm_dumps_ring(self, tmp_path):
        """Kill a worker mid-loop: the flight-recorder file must exist,
        parse, hold <= ring-size records with monotonically increasing
        step ids, and carry the collective/wire fields."""
        flight = str(tmp_path / "flight.jsonl")
        script = tmp_path / "worker.py"
        script.write_text(
            textwrap.dedent(
                f"""
                import os, sys, time
                os.environ["JAX_PLATFORMS"] = "cpu"
                os.environ["HOROVOD_FLIGHT_RECORDER"] = {flight!r}
                os.environ["HOROVOD_TELEMETRY_STEPS"] = "8"
                import jax
                jax.config.update("jax_platforms", "cpu")
                import horovod_tpu as hvd

                print("READY", flush=True)
                while True:
                    hvd.step_begin()
                    time.sleep(0.01)
                    hvd.step_end()
                """
            )
        )
        env = dict(os.environ)
        env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
        env.pop("HOROVOD_FLIGHT_RECORDER", None)
        errfile = tmp_path / "worker.err"
        with open(errfile, "w") as errf:
            proc = subprocess.Popen(
                [sys.executable, str(script)],
                env=env, stdout=subprocess.PIPE, stderr=errf, text=True,
            )
            try:
                line = proc.stdout.readline()
                assert "READY" in line, (
                    f"first line {line!r}:\n{errfile.read_text()[-2000:]}"
                )
                time.sleep(1.0)  # let > ring-size steps elapse
                proc.send_signal(signal.SIGTERM)
                rc = proc.wait(timeout=60)
                assert rc == 143, (
                    f"rc={rc}:\n{errfile.read_text()[-2000:]}"
                )
            finally:
                if proc.poll() is None:
                    proc.kill()
        assert os.path.exists(flight), errfile.read_text()[-2000:]
        recs = [json.loads(line) for line in open(flight)]
        assert 0 < len(recs) <= 8
        steps = [r["step"] for r in recs]
        assert steps == sorted(steps)
        assert len(set(steps)) == len(steps)  # strictly increasing
        for rec in recs:
            assert "exposed_collective_ms" in rec
            assert "hidden_collective_ms" in rec
            assert "wire_bytes" in rec

    def test_graceful_shutdown_dumps_ring(self, tmp_path):
        """preemption.GracefulShutdown's drain path persists the ring
        before os._exit — checked via its _drain_and_exit internals
        with exit intercepted."""
        from horovod_tpu.common import telemetry

        flight = str(tmp_path / "flight.jsonl")
        hub = telemetry.hub()
        hub.configure(flight_path=flight)
        try:
            hub.step_begin()
            hub.step_end()

            class _State:
                committed = False

                def persist(self):
                    self.committed = True

                def wait_until_finished(self):
                    pass

            from horovod_tpu.preemption import GracefulShutdown

            gs = GracefulShutdown(_State())
            exits = []
            real_exit = os._exit
            os._exit = lambda code: exits.append(code)
            try:
                gs._drain_and_exit()
            finally:
                os._exit = real_exit
            assert exits == [143]
            assert os.path.exists(flight)
            assert [json.loads(l) for l in open(flight)]
        finally:
            hub.flight_path = None


# ------------------------------------------------------ scrape endpoint


def _minimal_prom_parse(text):
    """Minimal Prometheus text parser: returns ({name: value}, typed
    names). Raises on NaN samples or malformed lines."""
    samples, types = {}, set()
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# TYPE "):
            types.add(line.split()[2])
            continue
        if line.startswith("#"):
            continue
        name_part, _, value = line.rpartition(" ")
        base = name_part.split("{", 1)[0]
        val = float(value)
        assert val == val, f"NaN sample: {line}"
        samples[name_part] = val
        samples.setdefault(base, val)
    return samples, types


class TestScrapeEndpoint:
    def _server(self, hub):
        from horovod_tpu.common.telemetry import MetricsServer

        return MetricsServer(port=0, hub_instance=hub)

    def test_metrics_prometheus_roundtrip(self, hvd):
        from horovod_tpu.common.metrics import registry

        hub = _fresh_hub(capacity=8)
        for _ in range(4):
            hub.step_begin()
            hub.step_end()
        registry.gauge("smoke.answer", 42.0)
        server = self._server(hub)
        port = server.start()
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10
            ) as resp:
                ctype = resp.headers.get("Content-Type", "")
                text = resp.read().decode()
        finally:
            server.stop()
        assert ctype.startswith("text/plain")
        assert "version=0.0.4" in ctype
        samples, types = _minimal_prom_parse(text)
        # step summary present with both quantiles
        assert samples['telemetry_step_ms{quantile="0.5"}'] >= 0
        assert samples['telemetry_step_ms{quantile="0.95"}'] >= 0
        assert samples["telemetry_step_ms_count"] == 4
        assert "telemetry_step_ms" in types
        # registry gauges with HELP/TYPE lines
        assert samples["hvd_smoke_answer"] == 42.0
        assert "hvd_smoke_answer" in types
        assert "# HELP hvd_smoke_answer" in text

    def test_nan_gauges_are_dropped(self):
        from horovod_tpu.common.telemetry import render_prometheus

        text = render_prometheus({"bad.gauge": float("nan"),
                                  "good.gauge": 1.0}, {})
        assert "NaN" not in text and "nan" not in text
        assert "hvd_good_gauge 1" in text
        assert "hvd_bad_gauge" not in text

    def test_telemetry_json_and_healthz(self):
        hub = _fresh_hub(capacity=8)
        hub.step_begin(7)
        hub.step_end()
        server = self._server(hub)
        port = server.start()
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/telemetry", timeout=10
            ) as resp:
                tele = json.load(resp)
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10
            ) as resp:
                assert resp.read() == b"ok\n"
            code = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10
            ).status
            assert code == 200
        finally:
            server.stop()
        assert tele["ring_capacity"] == 8
        assert [r["step"] for r in tele["steps"]] == [7]
        assert "percentiles" in tele and "metrics" in tele

    def test_env_port_starts_server_at_init(self, monkeypatch):
        """HOROVOD_METRICS_PORT wires the endpoint into hvd.init()."""
        import socket

        import horovod_tpu as hvd_mod
        from horovod_tpu.common import basics

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        monkeypatch.setenv("HOROVOD_METRICS_PORT", str(port))
        hvd_mod.shutdown()
        hvd_mod.init()
        try:
            assert basics.state().telemetry_server is not None
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10
            ) as resp:
                assert resp.status == 200
        finally:
            hvd_mod.shutdown()


# --------------------------------------------------- auto-threading


class TestAutoThreading:
    def test_value_and_grad_opens_steps(self, hvd, monkeypatch):
        """Host-level (non-traced) tape calls open/close an auto record
        per step. The allreduce is stubbed out: eagerly there is no
        axis context, and the hook under test is pure host plumbing."""
        import jax.numpy as jnp

        import horovod_tpu as hvd_mod
        from horovod_tpu import optimizer as opt_mod
        from horovod_tpu.common import telemetry

        monkeypatch.setenv("HOROVOD_TELEMETRY", "1")
        monkeypatch.setattr(
            opt_mod, "_allreduce_grads", lambda grads, *a, **k: grads
        )
        telemetry._reset_hub()
        try:
            hub = telemetry.hub()
            assert hub.enabled
            vg = hvd_mod.value_and_grad(lambda w: jnp.sum(w * w))
            before = len(hub)
            for _ in range(3):
                vg(jnp.ones((4,)))
            assert len(hub) == before + 3
            steps = [r["step"] for r in hub.records()]
            assert steps == sorted(steps)
        finally:
            telemetry._reset_hub()

    def test_value_and_grad_ticks_under_jit_with_step(self, hvd,
                                                      monkeypatch):
        """The real usage shape — vg inside jit/shard_map with a
        threaded hvd_step — ticks the flight recorder per executed
        step with the caller's step ids."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        import horovod_tpu as hvd_mod
        from horovod_tpu.common import telemetry

        monkeypatch.setenv("HOROVOD_TELEMETRY", "1")
        telemetry._reset_hub()
        try:
            hub = telemetry.hub()
            vg = hvd_mod.value_and_grad(lambda w, x: jnp.sum(w * x))
            mesh = hvd_mod.mesh()

            @jax.jit
            @jax.shard_map(
                mesh=mesh, in_specs=(P(), P(hvd_mod.WORLD_AXIS), P()),
                out_specs=(P(), P()), check_vma=False,
            )
            def step(w, x, s):
                return vg(w, x[0], hvd_step=s)

            w = jnp.ones(3)
            x = np.stack([np.full((3,), float(r), np.float32)
                          for r in range(8)])
            for i in range(4):
                out = step(w, x, jnp.asarray(i, jnp.int32))
            jax.block_until_ready(out)
            # the last tick's record is still open → >= 3 closed, with
            # the threaded ids (per-shard duplicates deduped)
            assert len(hub) >= 3
            steps = [r["step"] for r in hub.records()]
            assert steps == sorted(steps)
            assert set(steps) <= {0, 1, 2, 3}
            assert len(set(steps)) == len(steps)
        finally:
            telemetry._reset_hub()

    def test_auto_hooks_off_by_default(self, hvd, monkeypatch):
        import jax.numpy as jnp

        import horovod_tpu as hvd_mod
        from horovod_tpu import optimizer as opt_mod
        from horovod_tpu.common import telemetry

        monkeypatch.setattr(
            opt_mod, "_allreduce_grads", lambda grads, *a, **k: grads
        )
        telemetry._reset_hub()
        try:
            assert not telemetry.auto_enabled()
            hub = telemetry.hub()
            vg = hvd_mod.value_and_grad(lambda w: jnp.sum(w * w))
            vg(jnp.ones((4,)))
            assert len(hub) == 0
        finally:
            telemetry._reset_hub()

    def test_distributed_optimizer_ticks_under_jit(self, hvd, monkeypatch):
        """The debug-callback tick: a FULLY jitted update loop still
        produces flight-recorder records."""
        import jax
        import jax.numpy as jnp
        import optax

        import horovod_tpu as hvd_mod
        from horovod_tpu.common import telemetry
        from horovod_tpu.common.topology import WORLD_AXIS
        from jax.sharding import PartitionSpec as P

        monkeypatch.setenv("HOROVOD_TELEMETRY", "1")
        telemetry._reset_hub()
        try:
            hub = telemetry.hub()
            opt = hvd_mod.DistributedOptimizer(optax.sgd(0.1))
            mesh = hvd_mod.mesh()

            params = jnp.ones((8, 4))

            @jax.jit
            @jax.shard_map(
                mesh=mesh, in_specs=(P(WORLD_AXIS), P(WORLD_AXIS), P()),
                out_specs=(P(WORLD_AXIS), P()), check_vma=False,
            )
            def step(p, g, s):
                updates, s = opt.update(g, s, p)
                return optax.apply_updates(p, updates), s

            state = opt.init(params[:1])
            for _ in range(4):
                params, state = step(params, params, state)
            jax.block_until_ready(params)
            # one tick per executed update (per-shard duplicates are
            # deduped by step id); the last tick's record is still
            # open, so >= 3 closed records with distinct ordered ids
            assert len(hub) >= 3
            steps = [r["step"] for r in hub.records()]
            assert steps == sorted(steps)
            assert len(set(steps)) == len(steps)
        finally:
            telemetry._reset_hub()


# ------------------------------------------- metrics delta-aware dump


class TestMetricsDeltaDump:
    def test_delta_dump_and_seq(self, tmp_path):
        from horovod_tpu.common.metrics import MetricsRegistry

        reg = MetricsRegistry()
        path = str(tmp_path / "m.jsonl")
        reg.configure_export(path)
        reg.gauge("a", 1.0)
        reg.gauge("b", 2.0)
        reg.dump()
        lines = [json.loads(l) for l in open(path)]
        assert {l["name"] for l in lines} == {"a", "b"}  # first: full
        # unchanged: nothing appended
        reg.dump()
        assert len([json.loads(l) for l in open(path)]) == 2
        # one change: one line
        reg.gauge("b", 3.0)
        reg.dump()
        lines = [json.loads(l) for l in open(path)]
        assert len(lines) == 3
        assert lines[-1]["name"] == "b" and lines[-1]["value"] == 3.0
        # force: full snapshot again
        reg.dump(force=True)
        lines = [json.loads(l) for l in open(path)]
        assert len(lines) == 5
        # seq strictly monotonic across every line
        seqs = [l["seq"] for l in lines]
        assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)

    def test_explicit_path_gets_full_snapshot(self, tmp_path):
        from horovod_tpu.common.metrics import MetricsRegistry

        reg = MetricsRegistry()
        sink = str(tmp_path / "sink.jsonl")
        reg.configure_export(sink)
        reg.gauge("a", 1.0)
        reg.dump()
        other = str(tmp_path / "other.jsonl")
        # a different explicit path: full snapshot, sink state untouched
        reg.dump(other)
        assert len(open(other).readlines()) == 1
        reg.gauge("a", 2.0)
        reg.dump()
        lines = [json.loads(l) for l in open(sink)]
        assert [l["value"] for l in lines if l["name"] == "a"] == [1.0, 2.0]

    def test_reset_rebaselines(self, tmp_path):
        from horovod_tpu.common.metrics import MetricsRegistry

        reg = MetricsRegistry()
        path = str(tmp_path / "m.jsonl")
        reg.configure_export(path)
        reg.gauge("a", 1.0)
        reg.dump()
        reg.reset()
        reg.gauge("a", 1.0)
        reg.dump()  # after reset the sink re-baselines: full write
        lines = [json.loads(l) for l in open(path)]
        assert len(lines) == 2


# ------------------------------------------------ stall + stragglers


class TestStallMetricsAndStragglers:
    def test_check_publishes_gauges(self):
        from horovod_tpu.common.metrics import registry
        from horovod_tpu.common.stall_inspector import StallInspector

        insp = StallInspector(warning_seconds=3600.0)
        insp.record_enqueue("t1")
        insp.record_enqueue("t2")
        insp.record_heartbeat(0, time.time() - 7200.0)
        insp.record_heartbeat(1, time.time())
        insp.warning_seconds = 60.0
        insp.check()
        snap = registry.snapshot()
        assert snap["stall.pending"] == 2.0
        assert snap["stall.stale_ranks"] == 1.0
        assert "stall.straggler.count" in snap

    def test_traced_dispatch_runs_stall_check(self, hvd, monkeypatch):
        """Satellite: the stall inspector fires from the traced
        collective dispatch path, not only eager fusion cycles."""
        import jax
        from jax.sharding import PartitionSpec as P

        import horovod_tpu as hvd_mod
        from horovod_tpu.common import basics
        from horovod_tpu.ops import traced

        calls = []
        insp = basics.state().stall_inspector
        assert insp is not None
        monkeypatch.setattr(insp, "check", lambda: calls.append(1))
        monkeypatch.setattr(traced, "_last_stall_check", [0.0])
        mesh = hvd_mod.mesh()

        @jax.jit
        @jax.shard_map(
            mesh=mesh, in_specs=P(hvd_mod.WORLD_AXIS), out_specs=P(),
            check_vma=False,
        )
        def step(x):
            return traced.allreduce(x[0], op=hvd_mod.Sum)

        import jax.numpy as jnp

        step(jnp.ones((8, 4)))
        assert calls  # checked at trace/dispatch time

    def test_straggler_by_p50_multiple(self):
        from horovod_tpu.common.stall_inspector import StallInspector

        insp = StallInspector(straggler_factor=3.0)
        now = time.time()
        for r, p50 in enumerate([10.0, 11.0, 9.0, 100.0]):
            insp.record_heartbeat(r, now, step=50, step_ms_p50=p50)
        assert insp.straggler_ranks() == [3]
        # configurable multiple: at factor 15 nobody is flagged
        assert insp.straggler_ranks(factor=15.0) == []

    def test_straggler_by_step_lag(self):
        from horovod_tpu.common.stall_inspector import StallInspector

        insp = StallInspector()
        now = time.time()
        for r, step in enumerate([100, 101, 99, 2]):
            insp.record_heartbeat(r, now, step=step, step_ms_p50=10.0)
        assert insp.straggler_ranks() == [3]
        assert insp.straggler_ranks(lag_steps=1000) == []

    def test_straggler_needs_a_gang(self):
        from horovod_tpu.common.stall_inspector import StallInspector

        insp = StallInspector()
        insp.record_heartbeat(0, step=5, step_ms_p50=1000.0)
        assert insp.straggler_ranks() == []  # a median of one is itself

    def test_reset_heartbeats_clears_ledger(self):
        from horovod_tpu.common.stall_inspector import StallInspector

        insp = StallInspector()
        insp.record_heartbeat(0, step=5, step_ms_p50=10.0)
        insp.record_heartbeat(1, step=5, step_ms_p50=99.0)
        insp.reset_heartbeats()
        assert insp.straggler_ranks() == []
        assert insp.heartbeat_stats() == {}

    def test_heartbeat_payload_roundtrip(self):
        """Worker stats ride the KV heartbeat; legacy bare-float
        payloads still parse."""
        from horovod_tpu.runner.rendezvous import (
            HEARTBEAT_SCOPE,
            KVStore,
            put_heartbeat,
            read_heartbeat_stats,
            read_heartbeats,
        )

        class _Client:
            def __init__(self, store):
                self.store = store

            def put(self, scope, key, value):
                self.store.put(scope, key, value)

        store = KVStore()
        put_heartbeat(
            _Client(store), 0,
            stats={"step": 17, "step_ms_p50": 12.5, "last_step_ts": 1.0},
        )
        store.put(HEARTBEAT_SCOPE, "1", repr(time.time()).encode())  # legacy
        stats = read_heartbeat_stats(store)
        assert stats[0]["step"] == 17
        assert stats[0]["step_ms_p50"] == 12.5
        assert set(read_heartbeats(store)) == {0, 1}

    def test_multiprocess_straggler_flagged(self, tmp_path):
        """Acceptance: an injected slow rank is flagged through the
        REAL channel — subprocess workers PUT heartbeats over HTTP into
        the driver's rendezvous KV; the elastic driver's poll feeds the
        inspector, which flags the slow rank."""
        from horovod_tpu.elastic.driver import ElasticDriver
        from horovod_tpu.elastic.discovery import HostDiscovery
        from horovod_tpu.runner.hosts import HostInfo
        from horovod_tpu.runner.rendezvous import RendezvousServer

        class _Disc(HostDiscovery):
            def find_available_hosts_and_slots(self):
                return [HostInfo("localhost", 2)]

        server = RendezvousServer(secret_key=None, backend="python")
        port = server.start()
        try:
            worker = tmp_path / "beat.py"
            # stdlib-only worker: no horovod import, so the test stays
            # fast while the payload still crosses a process + socket
            worker.write_text(
                textwrap.dedent(
                    """
                    import json, sys, time, urllib.request
                    port, rank, p50 = sys.argv[1:4]
                    payload = json.dumps({
                        "ts": time.time(), "step": int(sys.argv[4]),
                        "step_ms_p50": float(p50),
                        "last_step_ts": time.time(),
                    }).encode()
                    req = urllib.request.Request(
                        f"http://127.0.0.1:{port}/kv/heartbeat/{rank}",
                        data=payload, method="PUT",
                    )
                    urllib.request.urlopen(req, timeout=10)
                    """
                )
            )
            procs = [
                subprocess.run(
                    [sys.executable, str(worker), str(port), str(rank),
                     str(p50), "40"],
                    capture_output=True, text=True, timeout=60,
                )
                for rank, p50 in [(0, 10.0), (1, 12.0), (2, 95.0)]
            ]
            for p in procs:
                assert p.returncode == 0, p.stderr
            driver = ElasticDriver(_Disc(), ["true"], min_np=1)
            driver._server = server
            driver._last_hb_poll = -1e9
            # no restart reason: stragglers are flagged but one poll is
            # under the quarantine hysteresis (K consecutive polls)
            assert driver._poll_heartbeats(time.monotonic()) is None
            assert driver.stall_inspector.straggler_ranks() == [2]
            stats = driver.stall_inspector.heartbeat_stats()
            assert stats[2]["step_ms_p50"] == 95.0
            from horovod_tpu.common.metrics import registry

            snap = registry.snapshot()
            assert snap["stall.straggler.count"] == 1.0
            assert snap["stall.straggler.worst_ratio"] > 3.0
        finally:
            server.stop()


# --------------------------------------------------- timeline satellite


class TestTimelineRaceAndStepTrack:
    def test_stop_during_emit_loses_nothing(self, tmp_path):
        """Concurrent counter() spam while stop() flushes: every event
        that made it into memory is in the file stop() wrote — the
        final _write can no longer miss a racing emit."""
        from horovod_tpu.common.timeline import Timeline

        path = str(tmp_path / "tl.json")
        tl = Timeline(path)
        stop_evt = threading.Event()
        emitted = []

        def spam():
            i = 0
            while not stop_evt.is_set():
                tl.counter("race.counter", i)
                i += 1
            emitted.append(i)

        threads = [threading.Thread(target=spam) for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.05)
        tl.stop()
        stop_evt.set()
        for t in threads:
            t.join()
        with open(path) as f:
            on_disk = [
                e for e in json.load(f)["traceEvents"]
                if e.get("name") == "race.counter"
            ]
        in_memory = [
            e for e in tl._events if e.get("name") == "race.counter"
        ]
        # the invariant under test: memory holds nothing the file lacks
        assert len(in_memory) == len(on_disk)

    def test_emit_after_stop_dropped(self, tmp_path):
        from horovod_tpu.common.timeline import Timeline

        path = str(tmp_path / "tl.json")
        tl = Timeline(path)
        tl.counter("c", 1)
        tl.stop()
        tl.counter("c", 2)  # dropped, not deferred
        tl.span("t", "X", 0.0, 1.0)
        tl.close()
        with open(path) as f:
            events = json.load(f)["traceEvents"]
        assert len([e for e in events if e.get("name") == "c"]) == 1

    def test_step_end_emits_telemetry_step_counter(self, tmp_path):
        """Traces align with StepStats: each step boundary lands a
        telemetry.step counter event on the eager timeline."""
        from horovod_tpu.common.timeline import Timeline

        hub = _fresh_hub(capacity=8)
        path = str(tmp_path / "tl.json")
        tl = Timeline(path)
        hub.timeline = tl
        hub.step_begin(3)
        hub.step_end()
        tl.close()
        with open(path) as f:
            events = json.load(f)["traceEvents"]
        track = [e for e in events if e.get("name") == "telemetry.step"]
        assert track and track[0]["ph"] == "C"
        assert track[0]["args"]["telemetry.step"] == 3

    def test_runtime_start_timeline_attaches_hub(self, hvd, tmp_path,
                                                 monkeypatch):
        """hvd.start_timeline() AFTER init must wire the new timeline
        into the telemetry hub, so step boundaries land on the trace
        (found by driving the runtime-activation path)."""
        import horovod_tpu as hvd_mod
        from horovod_tpu.common import telemetry

        monkeypatch.setenv("HOROVOD_TELEMETRY", "1")
        path = str(tmp_path / "tl.json")
        hvd_mod.start_timeline(path)
        hub = telemetry.hub()
        try:
            hub.step_begin(5)
            hub.step_end()
            hvd_mod.stop_timeline()
            with open(path) as f:
                events = json.load(f)["traceEvents"]
            track = [e for e in events
                     if e.get("name") == "telemetry.step"]
            assert track and track[0]["args"]["telemetry.step"] == 5
        finally:
            hub.timeline = None

    def test_step_end_runs_stall_check(self):
        from horovod_tpu.common.stall_inspector import StallInspector

        hub = _fresh_hub(capacity=4)
        insp = StallInspector()
        calls = []
        insp.check = lambda: calls.append(1)
        hub.stall_inspector = insp
        hub.step_begin()
        hub.step_end()
        assert calls == [1]
