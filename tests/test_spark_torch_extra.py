"""Regression tests for TorchEstimator input-contract edges (review
findings: one-shot generators must train every epoch; an impossible
batch_size must fail loudly, not record nan losses)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

from horovod_tpu.spark.torch import TorchEstimator


def _net():
    torch.manual_seed(0)
    return torch.nn.Sequential(torch.nn.Linear(4, 8), torch.nn.Linear(8, 1))


def test_one_shot_generator_trains_every_epoch(hvd):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(96, 4)).astype(np.float32)
    y = rng.normal(size=(96, 1)).astype(np.float32)

    def gen():
        for i in range(0, 96, 32):
            yield x[i : i + 32], y[i : i + 32]

    est = TorchEstimator(model=_net(), epochs=3, batch_size=32)
    est.fit(gen())
    assert len(est.history) == 3
    assert all(np.isfinite(h["loss"]) for h in est.history)


def test_batch_size_larger_than_dataset_raises(hvd):
    x = np.zeros((8, 4), np.float32)
    y = np.zeros((8, 1), np.float32)
    est = TorchEstimator(model=_net(), epochs=1, batch_size=32)
    with pytest.raises(ValueError, match="exceeds dataset size"):
        est.fit(x, y)


def test_empty_iterable_raises(hvd):
    est = TorchEstimator(model=_net(), epochs=1)
    with pytest.raises(ValueError, match="empty batch iterable"):
        est.fit(iter([]))
