#!/usr/bin/env python
"""hlo_audit: evaluate the lowered-program invariant catalog over the
canonical roster (docs/analysis.md).

Every structural claim the repo's perf/serving planes rest on — N
independent per-bucket collectives, group-limited two-level routing
with int8 licensed on the inter hop only, zero guard overhead, donated
serving carries, ``decode_compiles == 1`` — is checked here as a
declarative rule set over real ``jit(...).lower()`` modules on an
8-device CPU mesh. Nonzero exit on ANY violated invariant; the JSON
report is the CI artifact (``ci.sh audit-smoke``).

Usage:
    JAX_PLATFORMS=cpu python scripts/hlo_audit.py [--json out.json]
        [--only NAME] [--break MODE] [--list]

``--break MODE`` injects a deliberately-broken program (e.g.
``int8-intra`` forces int8 onto an intra-hop group) so the gate can
prove the auditor FAILS when it should — an auditor that cannot fail
is not evidence.
"""

import argparse
import json
import os
import sys

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "--xla_force_host_platform_device_count" not in os.environ.get(
    "XLA_FLAGS", ""
):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

from functools import partial  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

import horovod_tpu as hvd  # noqa: E402
from horovod_tpu import analysis  # noqa: E402
from horovod_tpu.analysis import rules  # noqa: E402
from horovod_tpu.common import topology as topo  # noqa: E402
from horovod_tpu.ops import overlap, traced  # noqa: E402

WORLD = 8
LOCAL = 4
INTRA = tuple(tuple(g) for g in topo.hierarchical_stage_groups(WORLD, LOCAL)[0])
INTER = tuple(tuple(g) for g in topo.hierarchical_stage_groups(WORLD, LOCAL)[1])
STAGES = topo.hierarchical_stage_groups(WORLD, LOCAL)
WORLD_GROUP = (tuple(range(WORLD)),)


def _sm(body, in_specs=(P(),), out_specs=P()):
    return partial(
        jax.shard_map,
        mesh=hvd.mesh(),
        in_specs=in_specs,
        out_specs=out_specs,
        check_vma=False,
    )(body)


def _tree(n_leaves=6, size=64):
    rng = np.random.default_rng(7)
    return {
        f"p{i}": jnp.asarray(
            rng.normal(size=(WORLD, size)).astype(np.float32)
        )
        for i in range(n_leaves)
    }


def _graph(fn, *args):
    return analysis.parse_module(jax.jit(fn).lower(*args))


def _bucketed(n_buckets, hier_stages=None, compression=None):
    def body(tr):
        local = jax.tree_util.tree_map(lambda x: x[0], tr)
        kw = {}
        if compression is not None:
            kw["compression"] = compression
        out = overlap.bucketed_allreduce(
            local, op=hvd.Sum, n_buckets=n_buckets, min_bucket_bytes=0,
            hier_stages=hier_stages, **kw
        )
        return jax.tree_util.tree_map(lambda x: x[None], out)

    return _sm(body)


# --------------------------------------------------------------- roster
# Each program returns [(rule, subject), ...]; the runner evaluates
# them into one report. Rule parameters mirror the acceptance tests
# that ride the same analysis API (tests/test_overlap.py etc.).


def prog_fused_allreduce_fp32():
    """PR 1/3 premise: N buckets -> N world-spanning all_reduces, no
    inter-bucket def-use edge, full-width wire."""
    g = _graph(_bucketed(3), _tree())
    return [
        (rules.CollectiveCount("all_reduce", 3), g),
        (rules.CollectiveCount("reduce_scatter", 0), g),
        (rules.NoInterCollectiveDefUse("all_reduce"), g),
        (rules.ReplicaGroupStructure("all_reduce", groups=WORLD_GROUP,
                                     require_present=True), g),
        (rules.WireDtype(int8_allowed=False), g),
    ]


def prog_fused_allreduce_int8():
    """PR 2 premise: the flat quantized wire moves int8 payloads (the
    fp32 payload never traverses the collective) and stays one
    independent exchange family."""

    def body(t):
        return traced.quantized_allreduce(t[0], op=hvd.Sum, seed=3)[None]

    g = _graph(_sm(body), jnp.asarray(
        np.random.default_rng(0).normal(size=(WORLD, 4096)).astype(np.float32)
    ))
    int8_colls = [
        c for c in g.collectives()
        if any(t.dtype in ("i8", "ui8") for t in c.operand_types)
    ]
    report_rules = [
        (rules.CollectiveCount("all_to_all", (1, 4)), g),
        (rules.NoInterCollectiveDefUse("all_to_all"), g),
        (
            rules.CompileBudget(int8_collectives=(1, 8)),
            {"int8_collectives": len(int8_colls)},
        ),
    ]
    return report_rules


def prog_overlap_buckets():
    """PR 3: the overlap contract at N=3 on a 6-leaf tree."""
    g = _graph(_bucketed(3), _tree(n_leaves=6))
    return [
        (rules.CollectiveCount("all_reduce", 3), g),
        (rules.NoInterCollectiveDefUse("all_reduce"), g),
    ]


def _zero_graphs(stage, guard=False, n_buckets=3):
    import optax

    rng = np.random.default_rng(4)
    params = {
        f"w{i}": jnp.asarray(rng.normal(size=(16, 16)), jnp.float32)
        for i in range(6)
    }
    x = jnp.asarray(rng.normal(size=(WORLD, 4, 16)), jnp.float32)
    opt = hvd.ShardedDistributedOptimizer(
        optax.adam(1e-2), op=hvd.Sum, zero_stage=stage,
        overlap_buckets=n_buckets, overlap_min_bytes=0, grad_guard=guard,
    )

    def loss(p, xb):
        h = xb
        for k in sorted(p):
            h = jnp.tanh(h @ p[k])
        return jnp.sum(h * h)

    if stage == 3:
        ps, st = opt.init_params(params), opt.init(params)

        @partial(
            jax.shard_map, mesh=hvd.mesh(),
            in_specs=(opt.state_spec(), opt.state_spec(), P(hvd.WORLD_AXIS)),
            out_specs=(opt.state_spec(), opt.state_spec()),
            check_vma=False,
        )
        def step(psh, s, xb):
            import optax as _optax

            local = opt.local_shards(psh)
            _, g_sh = opt.value_and_grad(loss)(local, xb[0])
            u, s = opt.update(g_sh, s, local)
            return opt.as_rows(_optax.apply_updates(local, u)), s

        return _graph(step, ps, st, x)

    st = opt.init(params)

    @partial(
        jax.shard_map, mesh=hvd.mesh(),
        in_specs=(P(), opt.state_spec(), P(hvd.WORLD_AXIS)),
        out_specs=(P(), opt.state_spec()),
        check_vma=False,
    )
    def step(p, s, xb):
        import optax as _optax

        _, g_sh = opt.value_and_grad(loss)(p, xb[0])
        u, s = opt.update(g_sh, s, p)
        return _optax.apply_updates(p, u), s

    return _graph(step, params, st, x)


def prog_zero2():
    """PR 9: ZeRO-2 lowers to N per-bucket reduce-scatters + N
    all-gathers, ZERO full all-reduces, mutually independent."""
    g = _zero_graphs(2)
    return [
        (rules.CollectiveCount("reduce_scatter", 3), g),
        (rules.CollectiveCount("all_gather", 3), g),
        (rules.CollectiveCount("all_reduce", 0), g),
        (rules.NoInterCollectiveDefUse("reduce_scatter"), g),
    ]


def prog_zero3():
    """PR 9: ZeRO-3 carries N forward-interleaved parameter
    all-gathers (no monolithic unshard) + N gradient reduce-scatters."""
    g = _zero_graphs(3)
    return [
        (rules.CollectiveCount("all_gather", 3), g),
        (rules.CollectiveCount("reduce_scatter", 3), g),
        (rules.CollectiveCount("all_reduce", 0), g),
        (rules.NoInterCollectiveDefUse("all_gather"), g),
    ]


def prog_zero_guard_overhead():
    """PR 7 on the sharded path: the guard costs exactly ONE extra
    SCALAR all_reduce (the 4-byte agreement flag) and nothing else."""
    base = _zero_graphs(2, guard=False)
    guarded = _zero_graphs(2, guard=True)
    return [
        (rules.GuardOverhead(base, extra_scalar_allreduces=1), guarded),
    ]


def prog_guard_overhead():
    """PR 7 on the replicated path: guard on == guard off, zero extra
    collectives (the flag folds into the existing bucket reductions)."""
    import optax

    def graphs(guard):
        opt = hvd.DistributedOptimizer(
            optax.sgd(0.1), op=hvd.Sum, grad_guard=guard,
            overlap_buckets=3, overlap_min_bytes=0,
        )
        params = {
            "a": jnp.ones((32, 8)), "b": jnp.ones((32, 8)),
            "c": jnp.ones((32, 8)),
        }
        state = opt.init(params)
        grads = {
            k: jnp.ones((WORLD,) + tuple(np.shape(v)))
            for k, v in params.items()
        }

        def step(g, s, p):
            def body(g, s, p):
                g = jax.tree_util.tree_map(lambda x: x[0], g)
                return opt.update(g, s, p)

            return partial(
                jax.shard_map, mesh=hvd.mesh(),
                in_specs=(P(hvd.WORLD_AXIS), P(), P()),
                out_specs=(P(), P()),
                check_vma=False,
            )(body)(g, s, p)

        return _graph(step, grads, state, params)

    base, guarded = graphs(False), graphs(True)
    return [
        (rules.CollectiveCount("all_reduce", 3), base),
        (rules.GuardOverhead(base, extra_scalar_allreduces=0), guarded),
    ]


def prog_hier_allreduce():
    """PR 10: the two-level wire — per-bucket intra RS -> inter AR ->
    intra AG, group-limited everywhere, independent buckets."""
    g = _graph(_bucketed(3, hier_stages=STAGES), _tree())
    return [
        (rules.CollectiveCount("reduce_scatter", 3), g),
        (rules.CollectiveCount("all_reduce", 3), g),
        (rules.CollectiveCount("all_gather", 3), g),
        (rules.ReplicaGroupStructure("reduce_scatter", groups=INTRA), g),
        (rules.ReplicaGroupStructure("all_gather", groups=INTRA), g),
        (rules.ReplicaGroupStructure(
            "all_reduce", groups=INTER, forbid_world_spanning=True), g),
        (rules.NoInterCollectiveDefUse("all_reduce"), g),
        (rules.WireDtype(int8_allowed=False), g),
    ]


def prog_hier_int8():
    """PR 10 placement: int8 on the inter (DCN) hop ONLY — the intra
    hops stay full-width, and no world-spanning exchange exists."""

    def body(t):
        return traced.hierarchical_allreduce_groups(
            t[0], op=hvd.Sum, stages=STAGES, inter_wire="int8",
            seed=5, block_size=64,
        )[None]

    g = _graph(_sm(body), jnp.asarray(
        np.random.default_rng(1).normal(size=(WORLD, 2048)).astype(np.float32)
    ))
    return [
        (rules.ReplicaGroupStructure("reduce_scatter", groups=INTRA), g),
        # the quantized inter exchange legitimately all-gathers values
        # and block scales across the INTER groups; the intra unshard
        # all-gathers across INTRA — both group-limited, neither world
        (rules.ReplicaGroupStructure(
            "all_gather", groups_any_of=(INTRA, INTER),
            forbid_world_spanning=True), g),
        (rules.WireDtype(inter_groups=INTER, intra_groups=INTRA), g),
        (rules.CompileBudget(int8_collectives=(1, 8)), {
            "int8_collectives": sum(
                1 for c in g.collectives()
                if any(t.dtype in ("i8", "ui8") for t in c.operand_types)
            )
        }),
    ]


def prog_moe_alltoall():
    """PR 12: expert dispatch is two-level — every all_to_all is
    group-limited (intra or inter), none spans the world, and the int8
    inter wire never touches the intra hop."""

    def body(v):
        return traced.hierarchical_alltoall(
            v[0], axis_name=hvd.WORLD_AXIS, stages=STAGES,
            inter_wire="int8", block_size=32,
        )[None]

    x = np.zeros((WORLD, WORLD, 4, 64), np.float32)
    g = _graph(_sm(body), jnp.asarray(x))
    return [
        (rules.ReplicaGroupStructure(
            "all_to_all", forbid_world_spanning=True,
            require_present=True), g),
        (rules.WireDtype(inter_groups=INTER, intra_groups=INTRA), g),
    ]


def _serve_engine(paged, role="unified", paged_attn=None):
    from horovod_tpu.models.transformer import Transformer, TransformerConfig
    from horovod_tpu.serving.engine import InferenceEngine

    cfg = TransformerConfig(
        vocab_size=61, num_layers=1, d_model=16, num_heads=2, d_ff=32,
        max_len=64, causal=True, dtype=jnp.float32,
    )
    model = Transformer(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 4), jnp.int32), train=False
    )
    return InferenceEngine(
        model, params, slots=4, max_len=64, min_bucket=4,
        donate=True, paged=paged, role=role, paged_attn=paged_attn,
    )


def prog_local_sgd_phase():
    """PR 14: the local-SGD local-phase step program carries ZERO
    inter-slice replica groups — every collective (the bucketed
    gradient exchange AND anything else the update folds in) routes
    over the intra groups only, full-width wire, N independent
    buckets. The sync round is a SEPARATE program and is allowed its
    inter groups; the local phase is not."""
    import optax

    opt = hvd.DistributedOptimizer(
        optax.sgd(0.1), op=hvd.Sum, local_sgd_steps=8,
        local_sgd_intra=LOCAL, overlap_buckets=3, overlap_min_bytes=0,
    )
    params = {
        "a": jnp.ones((32, 8)), "b": jnp.ones((32, 8)),
        "c": jnp.ones((32, 8)),
    }
    state = opt.init(params)
    pm = jax.tree_util.tree_map(
        lambda p: jnp.broadcast_to(p[None], (WORLD,) + p.shape), params
    )
    sm = jax.tree_util.tree_map(
        lambda s: jnp.broadcast_to(
            jnp.asarray(s)[None],
            (WORLD,) + tuple(np.shape(jnp.asarray(s))),
        ),
        state,
    )
    grads = jax.tree_util.tree_map(
        lambda p: jnp.ones((WORLD,) + tuple(np.shape(p))), params
    )

    @partial(
        jax.shard_map, mesh=hvd.mesh(),
        in_specs=(
            P(hvd.WORLD_AXIS), P(hvd.WORLD_AXIS), P(hvd.WORLD_AXIS),
        ),
        out_specs=(P(hvd.WORLD_AXIS), P(hvd.WORLD_AXIS)),
        check_vma=False,
    )
    def step(pm, sm, gm):
        import optax as _optax

        p = jax.tree_util.tree_map(lambda x: x[0], pm)
        s = jax.tree_util.tree_map(lambda x: x[0], sm)
        g = jax.tree_util.tree_map(lambda x: x[0], gm)
        u, s = opt.update(g, s, p)
        p = _optax.apply_updates(p, u)
        return jax.tree_util.tree_map(lambda x: x[None], (p, s))

    g = _graph(step, pm, sm, grads)
    pairs = [
        (rules.CollectiveCount("all_reduce", 3), g),
        (rules.NoInterCollectiveDefUse("all_reduce"), g),
        (rules.WireDtype(int8_allowed=False), g),
    ]
    # the tentpole invariant: no collective of ANY kind spans slices
    for kind in (
        "all_reduce", "reduce_scatter", "all_gather", "all_to_all",
        "collective_permute",
    ):
        pairs.append(
            (
                rules.ReplicaGroupStructure(
                    kind, groups_any_of=(INTRA,),
                    forbid_world_spanning=True,
                    require_present=(kind == "all_reduce"),
                ),
                g,
            )
        )
    return pairs


def prog_serve_decode():
    """PR 8/11: the decode carry is DONATED (arg 1 = the KV cache) and
    steady-state serving compiles the decode step exactly once across
    rolling admissions (``decode_compiles == 1``)."""
    eng = _serve_engine(paged=False)
    g = analysis.parse_module(eng.lowered_decode())
    # the donated carry is the KV-cache pytree: its leaves land
    # flattened among the entry args, so coverage is counted, not
    # positional
    n_cache = len(jax.tree_util.tree_leaves(eng.manager.cache))
    pairs = [
        (rules.DonationCoverage(min_donated=n_cache), g),
    ]
    # compile-budget leg: a short rolling-admission loop on the live
    # engine — admissions/evictions change data, never shapes
    rng = np.random.default_rng(3)
    for i in range(4):
        slot = eng.manager.alloc(f"warm{i}")
        eng.prefill(slot, rng.integers(1, 60, size=5 + i).tolist())
    for i in range(6):
        eng.decode_step(np.zeros(eng.slots, np.int32))
        if i == 2:  # roll one admission mid-decode
            eng.manager.free(1)
            slot = eng.manager.alloc("rolled")
            eng.prefill(slot, rng.integers(1, 60, size=9).tolist())
    stats = eng.stats()
    pairs.append((rules.CompileBudget(decode_compiles=1), stats))
    return pairs


def prog_serve_prefill():
    """PR 8: the prefill executable donates the cache carry too, and
    the bucket tier serves multiple lengths from one executable."""
    eng = _serve_engine(paged=False)
    g = analysis.parse_module(eng.lowered_prefill(8))
    n_cache = len(jax.tree_util.tree_leaves(eng.manager.cache))
    pairs = [
        (rules.DonationCoverage(min_donated=n_cache), g),
    ]
    for i, n in enumerate((5, 6, 7, 8)):
        slot = eng.manager.alloc(i)
        eng.prefill(slot, list(range(1, n + 1)))
    stats = eng.stats()
    # four prompts in (4,8] share the ONE width-8 bucket executable
    pairs.append(
        (rules.CompileBudget(prefill_compiles=1, prefill_bucket_hits=3),
         stats)
    )
    return pairs


def prog_serve_prefill_role():
    """PR 16: a prefill-role worker's executable table carries ONLY
    prefill executables. Finished pages leave over the transfer wire
    (serving/kv_transfer.py) before any decode step runs, so after a
    full prefill-and-extract workload ``decode_compiles == 0`` — the
    decode table's compile time and executable HBM are never paid.
    The prefill carry stays donated and the bucket tier still serves
    multiple lengths from one executable, exactly as on unified."""
    eng = _serve_engine(paged=True, role="prefill")
    g = analysis.parse_module(eng.lowered_prefill(8))
    n_cache = len(jax.tree_util.tree_leaves(eng.manager.cache))
    pairs = [
        (rules.DonationCoverage(min_donated=n_cache), g),
    ]
    rng = np.random.default_rng(5)
    for i, n in enumerate((5, 6, 7, 8)):
        slot = eng.manager.alloc(i)
        eng.prefill(slot, rng.integers(1, 60, size=n).tolist())
        # the handoff path: detach the finished slot, gather its pages
        # to host for the wire — no decode executable involved
        kept, length = eng.manager.detach_keep(slot)
        eng.extract_pages(kept, length)
        eng.manager.release_kept(kept)
    stats = eng.stats()
    pairs.append(
        (rules.CompileBudget(decode_compiles=0, prefill_compiles=1),
         stats)
    )
    return pairs


def prog_serve_decode_role():
    """PR 16: a decode-role worker admits sequences as INGESTED pages
    (serving/kv_transfer.py), never as prompts — its table carries only
    the decode executable, the decode carry stays donated, and rolling
    streamed admissions change data, never shapes: the executable
    compiled for the first ingest serves every later one
    (``decode_compiles == 1``, ``prefill_compiles == 0``)."""
    from horovod_tpu.serving.kv_transfer import pack_raw_pages, unpack_pages

    # a unified source engine plays the prefill fleet: prefill, detach,
    # extract — then the payload crosses the (in-process) wire into the
    # decode-role engine via the same pack/unpack codec the fleet uses
    src = _serve_engine(paged=True, role="unified")
    eng = _serve_engine(paged=True, role="decode")
    g = analysis.parse_module(eng.lowered_decode())
    n_cache = len(jax.tree_util.tree_leaves(eng.manager.cache))
    pairs = [
        (rules.DonationCoverage(min_donated=n_cache), g),
    ]
    rng = np.random.default_rng(6)
    pt = src.manager.page_tokens
    for i in range(3):  # >=3 streamed admissions across decode steps
        prompt = rng.integers(1, 60, size=5 + i).tolist()
        slot = src.manager.alloc(f"src{i}")
        src.prefill(slot, prompt)
        kept, length = src.manager.detach_keep(slot)
        raw = src.extract_pages(kept, length)
        meta, blob = pack_raw_pages(
            raw, [lp for lp, _ in kept], length,
            page_tokens=pt, wire="fp32",
        )
        arrays = unpack_pages(meta, blob)
        dslot = eng.manager.alloc(f"dst{i}")
        assert eng.ingest_attach(
            dslot, meta["pages"], arrays, meta["length"]
        ) is not None
        src.manager.release_kept(kept)
        eng.decode_step(np.zeros(eng.slots, np.int32))
        eng.decode_step(np.zeros(eng.slots, np.int32))
    stats = eng.stats()
    pairs.append(
        (rules.CompileBudget(
            decode_compiles=1, prefill_compiles=0, transfer_ingests=3),
         stats)
    )
    return pairs


def prog_serve_paged_attn():
    """PR 17: with the fused paged-attention read (``paged_attn=on``),
    the decode program streams K/V straight from the page pool — the
    transient contiguous ``[slots, max_len, kvh, hd]`` gather view is
    GONE from the lowered module (TransientBuffer forbid), while the
    gather-path baseline still carries it (falsifiability: the same
    matcher detects the buffer it bans). The pool carry stays donated
    and the compile budget is untouched: ``decode_compiles == 1``
    across rolling admissions on the kernel path, zero fallbacks."""
    eng = _serve_engine(paged=True, paged_attn="on")
    base = _serve_engine(paged=True, paged_attn="off")
    gk = analysis.parse_module(eng.lowered_decode())
    gb = analysis.parse_module(base.lowered_decode())
    n_cache = len(jax.tree_util.tree_leaves(eng.manager.cache))
    shape = (eng.slots, eng.max_len)
    pairs = [
        (rules.TransientBuffer(shape, forbid=True), gk),
        (rules.TransientBuffer(shape, forbid=False), gb),
        (rules.DonationCoverage(min_donated=n_cache), gk),
    ]
    rng = np.random.default_rng(7)
    for i in range(4):
        slot = eng.manager.alloc(f"warm{i}")
        eng.prefill(slot, rng.integers(1, 60, size=5 + i).tolist())
    for i in range(6):
        eng.decode_step(np.zeros(eng.slots, np.int32))
        if i == 2:  # roll one admission mid-decode
            eng.manager.free(1)
            slot = eng.manager.alloc("rolled")
            eng.prefill(slot, rng.integers(1, 60, size=9).tolist())
    stats = eng.stats()
    pairs.append(
        (rules.CompileBudget(decode_compiles=1, paged_attn_fallbacks=0),
         stats)
    )
    return pairs


def prog_serve_warm_start():
    """PR 18: a second engine against a populated ``HOROVOD_EXE_CACHE``
    serves the SAME traffic with ZERO prefill and ZERO decode compiles
    — the decode table and every seen prefill width deserialize from
    the persistent executable store (warm restarts recompile nothing).
    A cold engine populates the cache first (its own budget is the
    usual ``decode_compiles == 1``), writes are drained, then the warm
    engine replays the trace."""
    import tempfile

    from horovod_tpu.common import exe_cache

    cache = tempfile.mkdtemp(prefix="hloaudit-exe-cache-")
    prev = os.environ.get("HOROVOD_EXE_CACHE")
    os.environ["HOROVOD_EXE_CACHE"] = cache
    try:
        rng = np.random.default_rng(11)
        prompts = [rng.integers(1, 60, size=n).tolist()
                   for n in (5, 6, 7)]

        def trace(eng):
            for i, p in enumerate(prompts):
                slot = eng.manager.alloc(f"r{i}")
                eng.prefill(slot, p)
            for _ in range(4):
                eng.decode_step(np.zeros(eng.slots, np.int32))
            eng.drain_promotions()
            return eng.stats()

        cold_stats = trace(_serve_engine(paged=False))
        assert exe_cache.flush(30), "cache writes did not drain"
        warm_stats = trace(_serve_engine(paged=False))
    finally:
        if prev is None:
            os.environ.pop("HOROVOD_EXE_CACHE", None)
        else:
            os.environ["HOROVOD_EXE_CACHE"] = prev
    return [
        (rules.CompileBudget(decode_compiles=1), cold_stats),
        (rules.CompileBudget(
            decode_compiles=0, prefill_compiles=0, decode_disk_hits=1,
        ), warm_stats),
    ]


def prog_serve_migrate_resume():
    """PR 19: live-migrated sequences resume MID-DECODE on the
    receiver through the ingest admission path — pages, the full
    generated history, and the armed sampling state are data writes
    into the donated decode carry, never shapes. Two sequences are
    exported mid-decode at DIFFERENT lengths from a unified source
    batcher and resumed on one decode-role receiver, rolling (the
    second lands while the first is still decoding): the receiver
    compiles ONE decode executable across both resumes and ZERO
    prefill executables — a resume that re-prefilled would break the
    budget, a re-trace would break the donation."""
    from horovod_tpu.serving.batcher import ContinuousBatcher
    from horovod_tpu.serving.kv_transfer import (
        pack_raw_pages,
        unpack_pages,
    )

    src = _serve_engine(paged=True, role="unified")
    sbat = ContinuousBatcher(src, default_max_new_tokens=12)
    rng = np.random.default_rng(19)
    r1 = sbat.submit(rng.integers(1, 60, size=5).tolist(),
                     max_new_tokens=12)
    r2 = sbat.submit(rng.integers(1, 60, size=9).tolist(),
                     max_new_tokens=10)
    for _ in range(4):
        sbat.step()
    assert r1.status == "running" and r2.status == "running"
    assert len(r1.out_tokens) != len(r2.out_tokens) or (
        len(r1.out_tokens) > 1
    )
    records = sbat.export_inflight()
    assert len(records) == 2, len(records)

    deng = _serve_engine(paged=True, role="decode")
    dbat = ContinuousBatcher(deng, role="decode",
                             default_max_new_tokens=12)
    pt = src.manager.page_tokens
    resumed = []
    for rec in records:
        req, kept, length = rec["req"], rec["kept"], rec["length"]
        raw = src.extract_pages(kept, length)
        meta, blob = pack_raw_pages(
            raw, [lp for lp, _ in kept], length,
            page_tokens=pt, wire="fp32",
        )
        resumed.append(dbat.submit_migrated(
            prompt=[int(t) for t in req.prompt],
            tokens=list(req.out_tokens),
            max_new_tokens=req.max_new_tokens,
            logical=meta["pages"],
            arrays=unpack_pages(meta, blob),
            length=meta["length"],
            sample=rec.get("sample"),
        ))
        src.manager.release_kept(kept)
        dbat.step()  # rolling: resume #2 admits mid-decode of #1
    guard = 0
    while not all(r.finished() for r in resumed):
        dbat.step()
        guard += 1
        assert guard < 1000, "migrated resumes stalled"
    assert all(r.status == "done" for r in resumed)
    g = analysis.parse_module(deng.lowered_decode())
    n_cache = len(jax.tree_util.tree_leaves(deng.manager.cache))
    return [
        (rules.DonationCoverage(min_donated=n_cache), g),
        (rules.CompileBudget(
            decode_compiles=1, prefill_compiles=0, transfer_ingests=2),
         deng.stats()),
    ]


ROSTER = {
    "fused_allreduce_fp32": prog_fused_allreduce_fp32,
    "fused_allreduce_int8": prog_fused_allreduce_int8,
    "overlap_buckets": prog_overlap_buckets,
    "zero2": prog_zero2,
    "zero3": prog_zero3,
    "guard_overhead": prog_guard_overhead,
    "zero_guard_overhead": prog_zero_guard_overhead,
    "hier_allreduce": prog_hier_allreduce,
    "hier_int8": prog_hier_int8,
    "moe_alltoall": prog_moe_alltoall,
    "local_sgd_phase": prog_local_sgd_phase,
    "serve_decode": prog_serve_decode,
    "serve_prefill": prog_serve_prefill,
    "serve_prefill_role": prog_serve_prefill_role,
    "serve_decode_role": prog_serve_decode_role,
    "serve_paged_attn": prog_serve_paged_attn,
    "serve_warm_start": prog_serve_warm_start,
    "serve_migrate_resume": prog_serve_migrate_resume,
}


# ------------------------------------------------- deliberate breakage
# `--break MODE`: programs that VIOLATE an invariant on purpose, so the
# CI gate can assert the auditor exits nonzero when the contract rots.


def break_int8_intra():
    """Force int8 onto the INTRA hop: the placement rule must flag it."""

    def body(v):
        panes = jnp.tile(v[0][None], (LOCAL, 1))  # [intra, cols] pane rows
        sh = traced.quantized_reducescatter(
            panes, op=hvd.Sum, seed=1, block_size=64, groups=list(INTRA)
        )
        return sh[None]

    g = _graph(
        _sm(body),
        jnp.asarray(
            np.random.default_rng(2).normal(size=(WORLD, 256)).astype(
                np.float32
            )
        ),
    )
    return [(rules.WireDtype(inter_groups=INTER, intra_groups=INTRA), g)]


def break_serialized_buckets():
    """Chain one bucket's exchange through another: independence gone."""

    def body(t):
        a = jax.lax.psum(t[0], hvd.WORLD_AXIS)
        b = jax.lax.psum(a * 2.0, hvd.WORLD_AXIS)
        return b[None]

    g = _graph(_sm(body), jnp.ones((WORLD, 64), jnp.float32))
    return [(rules.NoInterCollectiveDefUse("all_reduce"), g)]


def break_monolithic_alltoall():
    """A world-spanning all_to_all where the two-level contract holds."""

    def body(v):
        return jax.lax.all_to_all(
            v[0], hvd.WORLD_AXIS, 0, 0, tiled=True
        )[None]

    x = np.zeros((WORLD, WORLD * 4, 8), np.float32)
    g = _graph(_sm(body), jnp.asarray(x))
    return [(
        rules.ReplicaGroupStructure(
            "all_to_all", forbid_world_spanning=True, require_present=True
        ),
        g,
    )]


def break_undonated_carry():
    """Serve decode WITHOUT the donated cache carry."""
    eng = _serve_engine(paged=False)
    eng.donate = False
    g = analysis.parse_module(eng.lowered_decode())
    n_cache = len(jax.tree_util.tree_leaves(eng.manager.cache))
    return [(rules.DonationCoverage(min_donated=n_cache), g)]


BREAKS = {
    "int8-intra": break_int8_intra,
    "serialized-buckets": break_serialized_buckets,
    "monolithic-alltoall": break_monolithic_alltoall,
    "undonated-carry": break_undonated_carry,
}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--json", default=os.environ.get("HLO_AUDIT_JSON", ""))
    ap.add_argument("--only", default="")
    ap.add_argument("--break", dest="break_mode", default="",
                    choices=[""] + sorted(BREAKS))
    ap.add_argument("--list", action="store_true")
    args = ap.parse_args(argv)

    if args.list:
        for name in ROSTER:
            print(name)
        for name in BREAKS:
            print(f"--break {name}")
        return 0

    hvd.init()
    try:
        roster = dict(ROSTER)
        if args.only:
            roster = {k: v for k, v in roster.items() if args.only in k}
            if not roster:
                print(f"no roster program matches {args.only!r}",
                      file=sys.stderr)
                return 2
        if args.break_mode:
            roster = {f"break:{args.break_mode}": BREAKS[args.break_mode]}

        report = {"programs": {}, "ok": True}
        for name, builder in roster.items():
            pairs = builder()
            prog_report = rules.run_rules(pairs)
            report["programs"][name] = prog_report.to_dict()
            status = "OK" if prog_report.ok else "VIOLATED"
            print(f"[{status:8s}] {name}: {len(pairs)} rule(s)")
            for f in prog_report.findings:
                print(f"    {f}")
            report["ok"] = report["ok"] and prog_report.ok

        if args.json:
            tmp = args.json + ".tmp"
            with open(tmp, "w") as fh:
                json.dump(report, fh, indent=2)
            os.replace(tmp, args.json)
            print(f"report: {args.json}")

        if not report["ok"]:
            print("hlo_audit: invariant violation(s) found", file=sys.stderr)
            return 1
        print(f"hlo_audit: {len(roster)} program(s) green")
        return 0
    finally:
        hvd.shutdown()


if __name__ == "__main__":
    sys.exit(main())
