"""Timeline coverage for BOTH execution modes (SURVEY.md §5.1): the
eager per-collective lifecycle writer, and the traced-path profiler
wrapper (the round-1 gap: the fast path had zero observability)."""

import json

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd_mod


def _chrome_events(path):
    with open(path) as f:
        data = json.load(f)
    assert "traceEvents" in data
    return data["traceEvents"]


def test_eager_timeline_phases(hvd, tmp_path):
    """start_timeline → collective → stop: file is chrome-trace JSON
    with QUEUE and ALLREDUCE phases (the verify-skill probe)."""
    path = str(tmp_path / "tl.json")
    hvd_mod.start_timeline(path)
    x = np.stack([np.full((4,), float(r), np.float32) for r in range(8)])
    hvd.allreduce(x, op=hvd_mod.Sum, name="tltensor")
    hvd_mod.stop_timeline()
    hvd_mod.common.basics.state().timeline.close()
    events = _chrome_events(path)
    names = {e.get("name") for e in events}
    assert "QUEUE" in names
    assert "ALLREDUCE" in names


def test_traced_timeline_produces_chrome_trace(hvd, tmp_path):
    """A jitted shard_map training loop under the traced timeline must
    yield a chrome://tracing file containing the step annotation and
    compiled-op events — per-collective visibility on the fast path."""
    path = str(tmp_path / "traced.json")
    mesh = hvd_mod.mesh()

    @jax.jit
    @jax.shard_map(
        mesh=mesh, in_specs=P(hvd_mod.WORLD_AXIS), out_specs=P(),
        check_vma=False,
    )
    def step(x):
        return jax.lax.psum(x[0] @ x[0], hvd_mod.WORLD_AXIS)

    x = jnp.ones((8, 16, 16), jnp.float32)
    jax.block_until_ready(step(x))  # compile outside the profile window

    hvd_mod.start_timeline(path, traced=True)
    for i in range(2):
        with hvd_mod.timeline_step("train", i):
            out = step(x)
            jax.block_until_ready(out)
    hvd_mod.stop_timeline()

    events = _chrome_events(path)
    assert len(events) > 0
    names = [str(e.get("name", "")) for e in events]
    assert any("train" in n for n in names)  # step annotation
    # XLA op-level events exist (the per-collective visibility claim)
    assert any("psum" in n or "all-reduce" in n or "jit" in n
               for n in names)
    # the distilled per-collective device spans (VERDICT r4 item 9):
    # a named ALLREDUCE phase span with the HLO op recorded, on the
    # dedicated 'horovod collectives' track, with a real duration
    spans = [
        e for e in events
        if str(e.get("name", "")).startswith("ALLREDUCE")
        and e.get("ph") == "X"
    ]
    assert spans, names
    assert any(
        "psum" in s["args"]["hlo_op"] or "all-reduce" in s["args"]["hlo_op"]
        for s in spans
    )
    procs = [
        e for e in events
        if e.get("ph") == "M" and e.get("name") == "process_name"
        and e.get("args", {}).get("name") == "horovod collectives"
    ]
    assert procs


def test_timeline_step_noop_without_session(hvd):
    """timeline_step must be a cheap no-op when no traced timeline is
    running (training loops keep the annotation unconditionally)."""
    with hvd_mod.timeline_step("train", 0):
        pass


def test_eager_timeline_device_completion_span(hvd, tmp_path):
    """The fused flush stamps a device-completion span per entry: a
    complete 'X' event named <PHASE>_DEVICE whose duration is the
    dispatch→block_until_ready delta (SURVEY §7 checklist row, eager
    half — see docs/design.md for the semantics and the remote-tunnel
    caveat)."""
    path = str(tmp_path / "tl.json")
    hvd_mod.start_timeline(path)
    x = np.stack([np.full((4,), float(r), np.float32) for r in range(8)])
    hvd.allreduce(x, op=hvd_mod.Sum, name="devtensor")
    hvd_mod.stop_timeline()
    hvd_mod.common.basics.state().timeline.close()
    events = _chrome_events(path)
    spans = [
        e
        for e in events
        if e.get("ph") == "X" and e.get("name") == "ALLREDUCE_DEVICE"
    ]
    assert spans, "no device-completion span stamped"
    assert all(e.get("dur", 0) >= 0 for e in spans)
    # the device span belongs to the same tensor row as the dispatch
    # lifecycle events (shared pid ⇒ one process row per tensor)
    queue_pids = {
        e.get("pid") for e in events if e.get("name") == "QUEUE"
    }
    assert {e.get("pid") for e in spans} <= queue_pids
