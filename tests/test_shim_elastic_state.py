"""Elastic State objects for the framework shims (ref:
horovod/torch/elastic/state.py TorchState +
horovod/tensorflow/elastic.py TensorFlowKerasState [V], SURVEY §2.5):
commit/restore round-trips model + optimizer + scalars; sync
broadcasts without error on the single-controller mesh."""

import numpy as np
import pytest


def test_torch_state_commit_restore_sync(hvd):
    torch = pytest.importorskip("torch")
    from horovod_tpu.torch.elastic import TorchState

    torch.manual_seed(0)
    model = torch.nn.Linear(4, 2)
    opt = torch.optim.SGD(model.parameters(), lr=0.1, momentum=0.9)
    state = TorchState(model=model, optimizer=opt, epoch=0, batch=0)

    w0 = model.weight.detach().clone()
    # train a step so both weights and momentum buffers change
    loss = model(torch.randn(8, 4)).pow(2).mean()
    loss.backward()
    opt.step()
    state.epoch = 3
    assert not torch.allclose(model.weight, w0)

    # restore rolls weights, optimizer state AND scalars back
    state.restore()
    assert torch.allclose(model.weight, w0)
    assert state.epoch == 0
    assert not opt.state_dict()["state"]  # momentum rolled back too

    # commit then mutate then restore -> back to the commit point
    loss = model(torch.randn(8, 4)).pow(2).mean()
    loss.backward()
    opt.step()
    state.epoch = 5
    state.commit()
    w_commit = model.weight.detach().clone()
    opt.step()
    state.epoch = 9
    state.restore()
    assert torch.allclose(model.weight, w_commit)
    assert state.epoch == 5
    # sync broadcasts from root without error and re-saves
    state.sync()
    assert torch.allclose(model.weight, w_commit)


def test_tf_keras_state_commit_restore_sync(hvd):
    tf = pytest.importorskip("tensorflow")
    from horovod_tpu.tensorflow.elastic import TensorFlowKerasState

    tf.keras.utils.set_random_seed(0)
    model = tf.keras.Sequential(
        [tf.keras.Input((4,)), tf.keras.layers.Dense(2)]
    )
    model.compile(optimizer=tf.keras.optimizers.SGD(0.1), loss="mse")
    x = np.random.default_rng(0).normal(size=(16, 4)).astype(np.float32)
    y = np.zeros((16, 2), np.float32)

    state = TensorFlowKerasState(model, epoch=0)
    w0 = [np.copy(w) for w in model.get_weights()]

    model.fit(x, y, epochs=1, verbose=0)
    state.epoch = 2
    assert not np.allclose(model.get_weights()[0], w0[0])

    state.restore()
    for got, want in zip(model.get_weights(), w0):
        np.testing.assert_allclose(got, want)
    assert state.epoch == 0

    model.fit(x, y, epochs=1, verbose=0)
    state.epoch = 4
    state.commit()
    w_commit = [np.copy(w) for w in model.get_weights()]
    model.fit(x, y, epochs=1, verbose=0)
    state.restore()
    for got, want in zip(model.get_weights(), w_commit):
        np.testing.assert_allclose(got, want)
    assert state.epoch == 4
    state.sync()
    for got, want in zip(model.get_weights(), w_commit):
        np.testing.assert_allclose(got, want)


def test_torch_state_with_elastic_run(hvd):
    """TorchState drives hvd.elastic.run end to end: an internal error
    rolls the model back to the last commit and retries."""
    torch = pytest.importorskip("torch")
    from horovod_tpu.common.basics import HorovodInternalError
    from horovod_tpu.elastic.worker import run as elastic_run
    from horovod_tpu.torch.elastic import TorchState

    torch.manual_seed(1)
    model = torch.nn.Linear(3, 1)
    opt = torch.optim.SGD(model.parameters(), lr=0.5)
    state = TorchState(model=model, optimizer=opt, step=0)
    w0 = model.weight.detach().clone()
    attempts = {"n": 0}

    @elastic_run
    def train(st):
        attempts["n"] += 1
        if attempts["n"] == 1:
            # uncommitted training progress, then a peer failure
            loss = st.model(torch.ones(4, 3)).pow(2).mean()
            loss.backward()
            st.optimizer.step()
            st.step = 10
            raise HorovodInternalError("peer died")
        # after restore: the uncommitted step is gone
        assert torch.allclose(st.model.weight, w0)
        return st.step

    assert train(state) == 0
    assert attempts["n"] == 2


def test_tf_state_snapshot_before_optimizer_build(hvd):
    """Snapshot taken at compile time (optimizer slot vars not yet
    built): restore after training must roll iterations back AND zero
    the momentum slots born during the failed attempt (review
    finding: the old positional snapshot silently kept them)."""
    tf = pytest.importorskip("tensorflow")
    from horovod_tpu.tensorflow.elastic import TensorFlowKerasState

    tf.keras.utils.set_random_seed(1)
    model = tf.keras.Sequential(
        [tf.keras.Input((4,)), tf.keras.layers.Dense(2)]
    )
    model.compile(
        optimizer=tf.keras.optimizers.SGD(0.1, momentum=0.9), loss="mse"
    )
    state = TensorFlowKerasState(model, epoch=0)  # pre-build snapshot
    x = np.random.default_rng(1).normal(size=(16, 4)).astype(np.float32)
    y = np.ones((16, 2), np.float32)
    model.fit(x, y, epochs=2, verbose=0)  # builds + fills momentum

    state.restore()
    for v in model.optimizer.variables:
        name = getattr(v, "path", None) or v.name
        if "learning_rate" in name:
            np.testing.assert_allclose(np.asarray(v), 0.1)  # snapshotted
        else:
            # iterations + momentum slots: rolled back / zeroed
            np.testing.assert_allclose(
                np.asarray(v), np.zeros(v.shape), atol=0,
                err_msg=f"{name} not rolled back",
            )


def test_shim_namespace_parity(hvd):
    """Reference API shape: hvd.torch-style `hvd.elastic.run` +
    `hvd.elastic.TorchState` from ONE namespace (and the TF twin)."""
    torch = pytest.importorskip("torch")
    import horovod_tpu.torch as hvdt

    assert callable(hvdt.elastic.run)
    assert hvdt.elastic.TorchState is not None
    assert hvdt.elastic.State is not None

    tf = pytest.importorskip("tensorflow")
    import horovod_tpu.tensorflow as hvdtf

    assert callable(hvdtf.elastic.run)
    assert hvdtf.elastic.TensorFlowKerasState is not None


class TestElasticSampler:
    """ref: horovod/torch/elastic/sampler.py [V] — mid-epoch re-shard of
    the unprocessed remainder, no drops, no repeats."""

    @staticmethod
    def _sampler(n=40, world=4, rank=0, **kw):
        from horovod_tpu.torch.elastic import ElasticSampler

        return ElasticSampler(
            list(range(n)), num_replicas=world, rank=rank, **kw
        )

    def test_covers_all_and_equal_shards(self, hvd):
        shards = [
            self._sampler(n=40, world=4, rank=r, shuffle=False).indices
            for r in range(4)
        ]
        assert all(len(sh) == 10 for sh in shards)
        assert set().union(*map(set, shards)) == set(range(40))

    def test_record_and_reshard_no_repeat_no_drop(self, hvd):
        samplers = [
            self._sampler(n=64, world=4, rank=r, shuffle=True, seed=3)
            for r in range(4)
        ]
        # every rank processes its first two batches of 4
        processed = set()
        for s in samplers:
            s.record_batch(0, 4)
            s.record_batch(1, 4)
            processed |= s.processed_indices
        # membership change 4 -> 2: the union travels via
        # sampler.sync() (allgather semantics; under the single
        # controller allgather_object returns the caller's own set, so
        # we seed each survivor with its pre-change local view plus the
        # union — multi-process coverage of allgather_object itself
        # lives in tests/test_multiprocess_ops.py's op family)
        survivors = []
        for r in range(2):
            s = self._sampler(n=64, world=2, rank=r, shuffle=True, seed=3)
            s.processed_indices = set(processed)
            s.sync()
            survivors.append(s)
        remaining = set(range(64)) - processed
        got = set(survivors[0].indices) | set(survivors[1].indices)
        assert got == remaining
        # nothing processed is repeated
        for s in survivors:
            assert not (set(s.indices) & processed)
        # equal step counts (wrap-around padding)
        assert len(survivors[0]) == len(survivors[1])

    def test_set_epoch_clears_progress_and_reshuffles(self, hvd):
        s = self._sampler(n=32, world=2, rank=0, shuffle=True, seed=0)
        s.record_batch(0, 4)
        e0 = list(s.indices)
        s.set_epoch(1)
        assert s.processed_indices == set()
        assert s.indices != e0  # different epoch permutation

    def test_state_dict_roundtrip(self, hvd):
        s = self._sampler(n=32, world=2, rank=1)
        s.set_epoch(2)
        s.record_batch(0, 4)
        sd = s.state_dict()
        s2 = self._sampler(n=32, world=2, rank=1)
        s2.load_state_dict(sd)
        assert s2.epoch == 2
        assert s2.processed_indices == s.processed_indices
        s.reset()  # same post-restore view: both exclude the processed set
        assert s2.indices == s.indices


def test_torch_state_packed_native_snapshot(hvd):
    """Commit rides the native packed block (csrc/cext.cc) when every
    tensor is CPU/numpy-eligible; restore from the block is exact."""
    torch = pytest.importorskip("torch")
    from horovod_tpu._native import loader as native_loader
    from horovod_tpu.torch.elastic import TorchState, _PackedStateDict

    torch.manual_seed(1)
    model = torch.nn.Sequential(
        torch.nn.Linear(4, 8), torch.nn.ReLU(), torch.nn.Linear(8, 2)
    )
    opt = torch.optim.SGD(model.parameters(), lr=0.1, momentum=0.9)
    # populate momentum buffers so the optimizer snapshot has tensors
    model(torch.randn(8, 4)).pow(2).mean().backward()
    opt.step()

    state = TorchState(model=model, optimizer=opt, batch=1)
    if native_loader.ext_available() or native_loader.available():
        assert isinstance(state._saved_model_state, _PackedStateDict)
        assert isinstance(state._saved_optimizer_state, _PackedStateDict)
        assert state._saved_model_state.nbytes == sum(
            t.numel() * t.element_size()
            for t in model.state_dict().values()
        )

    committed = {
        k: v.detach().clone() for k, v in model.state_dict().items()
    }
    mom_committed = [
        b["momentum_buffer"].detach().clone()
        for b in opt.state_dict()["state"].values()
    ]
    # mutate weights + momentum, then roll back
    model(torch.randn(8, 4)).pow(2).mean().backward()
    opt.step()
    state.restore()
    for k, v in model.state_dict().items():
        assert torch.equal(v, committed[k]), k
    for got, want in zip(
        (b["momentum_buffer"]
         for b in opt.state_dict()["state"].values()),
        mom_committed,
    ):
        assert torch.equal(got, want)


def test_torch_state_packed_preserves_0d_adam_step(hvd):
    """Adam's 0-d 'step' tensors must come back 0-d from the packed
    block (np.ascontiguousarray promotes 0-d to (1,); the snapshot
    records the original shape)."""
    torch = pytest.importorskip("torch")
    from horovod_tpu.torch.elastic import TorchState

    model = torch.nn.Linear(3, 3)
    opt = torch.optim.Adam(model.parameters(), lr=1e-3)
    model(torch.randn(2, 3)).sum().backward()
    opt.step()
    step_shapes = [
        s["step"].shape for s in opt.state_dict()["state"].values()
    ]
    state = TorchState(model=model, optimizer=opt)
    model(torch.randn(2, 3)).sum().backward()
    opt.step()
    state.restore()
    for s, want in zip(
        opt.state_dict()["state"].values(), step_shapes
    ):
        assert s["step"].shape == want


def test_torch_state_bf16_falls_back_to_clone(hvd):
    """A numpy-unsupported dtype anywhere in the state dict routes the
    whole snapshot through the per-tensor clone path — still correct."""
    torch = pytest.importorskip("torch")
    from horovod_tpu.torch.elastic import TorchState, _PackedStateDict

    class WithBf16(torch.nn.Module):
        def __init__(self):
            super().__init__()
            self.lin = torch.nn.Linear(3, 3)
            self.register_buffer(
                "scale", torch.ones(4, dtype=torch.bfloat16)
            )

        def forward(self, x):
            return self.lin(x)

    model = WithBf16()
    state = TorchState(model=model)
    assert not isinstance(state._saved_model_state, _PackedStateDict)
    with torch.no_grad():
        model.lin.weight.add_(1.0)
        model.scale.mul_(2.0)
    state.restore()
    assert torch.all(model.scale == torch.ones(4, dtype=torch.bfloat16))


def test_torch_state_double_restore_does_not_corrupt_snapshot(hvd):
    """Optimizer.load_state_dict shallow-copies (torch>=2.x), so a
    restore must hand it OWNED tensors: commit -> restore -> train ->
    restore again has to return the committed state, not the
    post-training values (on both the packed and clone paths)."""
    torch = pytest.importorskip("torch")
    from horovod_tpu.torch.elastic import TorchState

    def run_cycle():
        torch.manual_seed(3)
        model = torch.nn.Linear(4, 4)
        opt = torch.optim.SGD(
            model.parameters(), lr=0.5, momentum=0.9
        )
        model(torch.randn(8, 4)).pow(2).mean().backward()
        opt.step()
        state = TorchState(model=model, optimizer=opt)
        committed = [
            b["momentum_buffer"].clone()
            for b in opt.state_dict()["state"].values()
        ]
        state.restore()
        # train AFTER the restore: if the live optimizer aliases the
        # snapshot, these steps corrupt it in place
        for _ in range(3):
            opt.zero_grad()
            model(torch.randn(8, 4)).pow(2).mean().backward()
            opt.step()
        state.restore()
        got = [
            b["momentum_buffer"]
            for b in opt.state_dict()["state"].values()
        ]
        for g, w in zip(got, committed):
            assert torch.equal(g, w)

    run_cycle()  # packed path (native available in CI)
    import os
    from unittest import mock
    with mock.patch.dict(os.environ, {"HOROVOD_NATIVE": "0"}):
        run_cycle()  # clone fallback path
