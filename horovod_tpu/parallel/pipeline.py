"""Pipeline parallelism over the 'pp' mesh axis: GPipe forward (demo)
and a 1F1B training schedule with bounded activation memory.

Absent from the reference (SURVEY.md §2.6); built TPU-first: stages are
chips along the 'pp' mesh axis, activations hop stage→stage with
`ppermute`, and the schedules are `lax.scan`s over STATIC tick tables —
fully static control flow, so XLA sees one compiled program per stage
and overlaps each hop with compute.

Two schedules:

* `gpipe` — fill/drain forward-only scan. Differentiating through it
  checkpoints every tick's carry, so its backward holds O(n_micro)
  activations: fine as a demo / for inference, NOT the production
  training path (VERDICT r4 Weak #6).
* `pipeline_1f1b` — the training schedule. Combined-op 1F1B
  (PipeDream-flush dataflow; a stage may run one forward AND one
  backward in the same tick): explicit per-stage backward via
  `jax.vjp` recompute from a stash of STAGE INPUTS, so the activation
  live-set is <= pp microbatch inputs per stage — bounded by the
  pipeline depth, never by n_micro. Returns (loss, per-stage grads)
  directly; nothing differentiates through the scan.

Per-device code for use inside shard_map: every chip runs the same
scan; chip s applies its own stage parameters. The classic bubble is
(pp-1)/(n_micro+pp-1) for GPipe and the same fill+drain term for 1F1B;
callers pick n_micro >> pp to amortize it.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


def gpipe(
    stage_fn: Callable,
    stage_params,
    x_micro,
    axis_name: str = "pp",
):
    """Run microbatches through the pipeline.

    stage_fn(params, x) -> y: this chip's stage (shapes preserved).
    stage_params: this chip's stage parameters (pp-sharded pytree leaf(s)).
    x_micro: [n_micro, ...] microbatched input. Only stage 0's copy is
        consumed; other stages may pass the same array (ignored).

    Returns [n_micro, ...] outputs, valid on the LAST stage (other stages
    return zeros) — broadcast back with a psum or collective if every
    stage needs them.
    """
    pp = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    n_micro = x_micro.shape[0]
    total = n_micro + pp - 1  # fill + drain
    micro_shape = x_micro.shape[1:]

    # Send each stage's output to the next stage; the wrap-around edge
    # (last → 0) carries drained values nobody reads.
    perm = [(j, (j + 1) % pp) for j in range(pp)]

    def step(carry, t):
        out_acc = carry["out"]
        prev_act = carry["act"]  # activation received from previous stage
        # Stage 0 injects microbatch t (zeros once drained); others use
        # what arrived over the ring.
        inject = jnp.where(
            t < n_micro,
            lax.dynamic_index_in_dim(
                x_micro, jnp.minimum(t, n_micro - 1), keepdims=False
            ),
            jnp.zeros(micro_shape, x_micro.dtype),
        )
        x_in = jnp.where(stage == 0, inject, prev_act)
        y = stage_fn(stage_params, x_in)
        # Last stage: microbatch index t - (pp-1) completes at step t.
        done_idx = t - (pp - 1)
        is_done = jnp.logical_and(done_idx >= 0, stage == pp - 1)
        out_acc = lax.cond(
            is_done,
            lambda acc: lax.dynamic_update_index_in_dim(
                acc, y, jnp.maximum(done_idx, 0), axis=0
            ),
            lambda acc: acc,
            out_acc,
        )
        act_next = lax.ppermute(y, axis_name, perm)
        return {"out": out_acc, "act": act_next}, None

    init = {
        "out": jnp.zeros((n_micro,) + micro_shape, x_micro.dtype),
        "act": jnp.zeros(micro_shape, x_micro.dtype),
    }
    final, _ = lax.scan(step, init, jnp.arange(total))
    return final["out"]


# --------------------------------------------------------------- 1F1B


def _build_1f1b_schedule(pp: int, n_micro: int):
    """Static 1F1B tick tables (numpy, computed at trace time — pp and
    n_micro are static). Combined-op variant: a stage may do one
    forward AND one backward in the same tick (uniform compute per
    tick; see pipeline_1f1b). Greedy under the 1F1B constraints:

    * F(s, m) needs F(s-1, m) from an earlier tick (act over the ring)
      and < pp microbatches in flight on s (the memory bound);
    * B(s, m) needs B(s+1, m) from an earlier tick (cotangent over the
      ring), except the last stage, which may do F(m) and B(m) in the
      SAME tick (its dy comes from its own loss, computed in-tick).

    Returns dict of int32/bool [T, pp] arrays:
      do_f/do_b (op masks), f_idx/b_idx (microbatch indices),
      ra_v/ra_s (receive-activation valid + stash slot),
      rc_v/rc_s (receive-cotangent valid + slot).
    """
    if n_micro < 1:
        raise ValueError("n_micro must be >= 1")
    S = pp + 1  # stash slots; in-flight <= pp consecutive => distinct
    t_f = [[None] * n_micro for _ in range(pp)]
    t_b = [[None] * n_micro for _ in range(pp)]
    next_f = [0] * pp
    next_b = [0] * pp
    rows = []
    t = 0
    while any(nb < n_micro for nb in next_b):
        row = {
            "do_f": [0] * pp, "f_idx": [0] * pp,
            "do_b": [0] * pp, "b_idx": [0] * pp,
        }
        for s in range(pp):
            m = next_f[s]
            can_f = (
                m < n_micro
                and (next_f[s] - next_b[s]) < pp
                and (s == 0 or (
                    t_f[s - 1][m] is not None and t_f[s - 1][m] < t
                ))
            )
            if can_f:
                row["do_f"][s] = 1
                row["f_idx"][s] = m
                t_f[s][m] = t
                next_f[s] += 1
            m = next_b[s]
            if s == pp - 1:
                can_b = (
                    m < next_f[s]
                    and t_f[s][m] is not None
                    and t_f[s][m] <= t  # same-tick F -> B
                )
            else:
                can_b = (
                    m < next_f[s]
                    and t_b[s + 1][m] is not None
                    and t_b[s + 1][m] < t
                )
            if can_b:
                row["do_b"][s] = 1
                row["b_idx"][s] = m
                t_b[s][m] = t
                next_b[s] += 1
        rows.append(row)
        t += 1
        if t > 4 * (n_micro + pp) + 8:
            raise AssertionError("1F1B schedule failed to converge")

    T = len(rows)
    out = {
        k: np.zeros((T, pp), np.int32)
        for k in (
            "do_f", "f_idx", "do_b", "b_idx",
            "ra_v", "ra_s", "rc_v", "rc_s",
        )
    }
    for t, row in enumerate(rows):
        for k in ("do_f", "f_idx", "do_b", "b_idx"):
            out[k][t] = row[k]
    # receive gating: what arrived over the ring THIS tick is whatever
    # the neighbor sent LAST tick
    for t in range(1, T):
        prev = rows[t - 1]
        for s in range(pp):
            if s > 0 and prev["do_f"][s - 1]:
                out["ra_v"][t, s] = 1
                out["ra_s"][t, s] = prev["f_idx"][s - 1] % S
            if s < pp - 1 and prev["do_b"][s + 1]:
                out["rc_v"][t, s] = 1
                out["rc_s"][t, s] = prev["b_idx"][s + 1] % S
    return out


def pipeline_1f1b(
    stage_fn: Callable,
    loss_fn: Callable,
    stage_params,
    x_micro,
    y_micro,
    axis_name: str = "pp",
    loss_params=None,
    return_dx: bool = False,
):
    """1F1B pipeline TRAINING step: returns ``(loss, grads)`` directly.

    The production PP schedule (VERDICT r4 item 7): unlike
    differentiating through `gpipe` — whose scan-of-activations
    backward checkpoints O(n_micro) activations per stage — this runs
    an explicit per-stage backward inside the same scan. Each stage
    stashes only its microbatch INPUTS (<= pp+1 slots) and recomputes
    its forward in `jax.vjp` at backward time (recompute beats storing
    on an HBM-bound chip — the same trade the flash kernels make), so
    the activation live-set is bounded by the pipeline depth pp, never
    by n_micro. Nothing differentiates through the scan: the returned
    grads ARE the backward.

    stage_fn(params, x) -> y: this chip's stage; activation shapes are
        preserved across stages (the `gpipe` contract). May contain
        collectives over OTHER mesh axes (tp/dp): every tick runs
        stage_fn and its vjp unconditionally (idle ticks compute on
        zeros and their effects are masked out with `where`-selects),
        so collectives inside stage_fn stay uniform across the mesh.
    loss_fn(y, target) -> scalar: evaluated on the LAST stage's output
        per microbatch; its value-grad seeds the backward. With
        ``loss_params`` given, the signature becomes
        ``loss_fn(loss_params, y, target)`` — a parameterized model
        TAIL (e.g. final norm + LM head + loss) whose gradients are
        returned too. Like stage_fn it runs unconditionally every
        tick, so collectives inside are mesh-uniform.
    stage_params: this chip's stage parameters (pp-sharded pytree).
    x_micro, y_micro: [n_micro, ...] microbatched inputs/targets. Only
        stage 0 consumes x_micro and only the last stage consumes
        y_micro; other stages may pass the same arrays (ignored).
    return_dx: also return d(loss)/d(x_micro) — the input cotangents,
        [n_micro, ...], valid on STAGE 0 only (zeros elsewhere; psum
        over the axis masked to stage 0 to broadcast) — for a
        differentiable HEAD in front of the pipeline (embeddings).
        This buffer is O(n_micro) like x_micro itself; the bounded-
        memory claim concerns per-LAYER activations, which stay <= pp.

    Returns (loss, grads[, loss_grads][, dx_micro]) by position:
      loss — mean microbatch loss, identical on every stage (psum'd).
      grads — THIS stage's parameter gradients of that mean loss
        (pp-sharded like stage_params; combine over dp with the usual
        allreduce).
      loss_grads — gradients for loss_params (only when loss_params is
        given); accumulated on the last stage and psum-broadcast so
        every stage holds them.
      dx_micro — only when return_dx=True.

    Bubble: fill+drain idle ticks ~ 2·pp/(n_micro + 2·pp); pick
    n_micro >> pp. Microbatch loss is averaged, matching a
    full-batch mean loss when loss_fn itself averages over its
    microbatch.
    """
    pp = lax.axis_size(axis_name)
    stage = lax.axis_index(axis_name)
    n_micro = x_micro.shape[0]
    S = pp + 1
    sched = _build_1f1b_schedule(pp, n_micro)
    T = sched["do_f"].shape[0]
    micro_shape = x_micro.shape[1:]
    dtype = x_micro.dtype
    tables = {k: jnp.asarray(v) for k, v in sched.items()}

    fwd_perm = [(j, (j + 1) % pp) for j in range(pp)]
    bwd_perm = [(j, (j - 1) % pp) for j in range(pp)]
    is_first = stage == 0
    is_last = stage == pp - 1

    def idx(arr, i):
        return lax.dynamic_index_in_dim(arr, i, keepdims=False)

    def upd(arr, val, i):
        return lax.dynamic_update_index_in_dim(arr, val, i, axis=0)

    def step(carry, t):
        row = {k: idx(v, t)[stage] for k, v in tables.items()}

        # ring exchanges — unconditional, every tick (receivers gate)
        recv_a = lax.ppermute(carry["sent_a"], axis_name, fwd_perm)
        recv_c = lax.ppermute(carry["sent_c"], axis_name, bwd_perm)
        inbox_a = upd(
            carry["inbox_a"],
            jnp.where(
                row["ra_v"] == 1,
                recv_a,
                idx(carry["inbox_a"], row["ra_s"]),
            ),
            row["ra_s"],
        )
        inbox_c = upd(
            carry["inbox_c"],
            jnp.where(
                row["rc_v"] == 1,
                recv_c,
                idx(carry["inbox_c"], row["rc_s"]),
            ),
            row["rc_s"],
        )

        # ---- forward micro-op (masked when not scheduled)
        do_f = row["do_f"] == 1
        f_slot = row["f_idx"] % S
        x_in = jnp.where(
            is_first,
            idx(x_micro, row["f_idx"]),
            idx(inbox_a, f_slot),
        )
        y = stage_fn(stage_params, x_in)
        tgt = idx(y_micro, row["f_idx"])
        if loss_params is None:
            l_m, dy_m = jax.value_and_grad(
                lambda yy: loss_fn(yy, tgt)
            )(y)
        else:
            l_m, (dlp_m, dy_m) = jax.value_and_grad(
                lambda lp, yy: loss_fn(lp, yy, tgt), argnums=(0, 1)
            )(loss_params, y)
        carry_lacc = carry.get("lacc")
        if loss_params is not None:
            take = jnp.logical_and(do_f, is_last)
            carry_lacc = jax.tree.map(
                lambda a, d: a + jnp.where(take, d, jnp.zeros_like(d)),
                carry_lacc,
                dlp_m,
            )
        stash_x = upd(
            carry["stash_x"],
            jnp.where(do_f, x_in, idx(carry["stash_x"], f_slot)),
            f_slot,
        )
        stash_dy = upd(
            carry["stash_dy"],
            jnp.where(
                do_f,
                dy_m.astype(dtype),
                idx(carry["stash_dy"], f_slot),
            ),
            f_slot,
        )
        loss = carry["loss"] + jnp.where(
            jnp.logical_and(do_f, is_last),
            l_m.astype(jnp.float32),
            0.0,
        )
        sent_a = jnp.where(do_f, y, carry["sent_a"])

        # ---- backward micro-op (masked when not scheduled)
        do_b = row["do_b"] == 1
        b_slot = row["b_idx"] % S
        x_b = idx(stash_x, b_slot)
        dy_b = jnp.where(
            is_last, idx(stash_dy, b_slot), idx(inbox_c, b_slot)
        )
        _, pull = jax.vjp(stage_fn, stage_params, x_b)
        dp, dx = pull(dy_b.astype(dtype))
        gacc = jax.tree.map(
            lambda a, d: a + jnp.where(do_b, d, jnp.zeros_like(d)),
            carry["gacc"],
            dp,
        )
        sent_c = jnp.where(do_b, dx, carry["sent_c"])

        out = {
            "inbox_a": inbox_a,
            "inbox_c": inbox_c,
            "stash_x": stash_x,
            "stash_dy": stash_dy,
            "sent_a": sent_a,
            "sent_c": sent_c,
            "gacc": gacc,
            "loss": loss,
        }
        if loss_params is not None:
            out["lacc"] = carry_lacc
        if return_dx:
            take_dx = jnp.logical_and(do_b, is_first)
            out["dx"] = upd(
                carry["dx"],
                jnp.where(
                    take_dx, dx, idx(carry["dx"], row["b_idx"])
                ),
                row["b_idx"],
            )
        return out, None

    zeros_micro = jnp.zeros(micro_shape, dtype)
    init = {
        "inbox_a": jnp.zeros((S,) + micro_shape, dtype),
        "inbox_c": jnp.zeros((S,) + micro_shape, dtype),
        "stash_x": jnp.zeros((S,) + micro_shape, dtype),
        "stash_dy": jnp.zeros((S,) + micro_shape, dtype),
        "sent_a": zeros_micro,
        "sent_c": zeros_micro,
        "gacc": jax.tree.map(jnp.zeros_like, stage_params),
        "loss": jnp.zeros((), jnp.float32),
    }
    if loss_params is not None:
        init["lacc"] = jax.tree.map(jnp.zeros_like, loss_params)
    if return_dx:
        init["dx"] = jnp.zeros((n_micro,) + micro_shape, dtype)
    final, _ = lax.scan(step, init, jnp.arange(T))
    loss = lax.psum(final["loss"], axis_name) / n_micro
    grads = jax.tree.map(lambda g: g / n_micro, final["gacc"])
    result = [loss, grads]
    if loss_params is not None:
        # accumulated on the last stage only; broadcast so every stage
        # holds the tail grads (they're replicated over pp)
        result.append(
            jax.tree.map(
                lambda g: lax.psum(
                    jnp.where(is_last, g, jnp.zeros_like(g)),
                    axis_name,
                )
                / n_micro,
                final["lacc"],
            )
        )
    if return_dx:
        result.append(jax.tree.map(lambda g: g / n_micro, final["dx"]))
    return tuple(result)
