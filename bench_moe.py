"""Expert wire A/B (PR 12, parallel/moe.py + ops/traced.py
quantized/hierarchical alltoall + common/autotune.py CapacityTuner).

Measures what the quantized two-level dispatch buys on the axis that
matters for MoE at multi-slice scale: expert-dispatch bytes crossing
the DCN hop, at IDENTICAL routing. Three legs over the SAME tokens,
router and expert bank (a synthetic multi-slice split of the 8-device
mesh, intra groups of ``BENCH_INTRA``), each appending one JSON
artifact under BENCH_ARTIFACT_DIR (default bench_results/moe/):

* ``ab_flat``      — the seed wire: raw fp32 through one monolithic
  ``lax.all_to_all`` each way; every cross-slice token crosses DCN at
  payload width.
* ``ab_hier_int8`` — the EQuARX placement for expert dispatch: the
  inter hop moves block-scaled int8 (+fp32 scales) for CROSS-SLICE
  tokens only (intra-slice tokens ride ICI exact), ~4x fewer scarce-
  hop bytes. Routing decisions are computed on fp32 logits BEFORE the
  wire, so the two legs route identically — asserted bitwise on the
  expert histograms — and outputs agree within the pre-registered
  bound (docs/perf.md).
* ``ab_captuned``  — the capacity-factor autotuner loop: each
  candidate factor is its own compiled step (capacity is a shape);
  the harness times a few honestly-synced steps per candidate, feeds
  kept-token goodput + the overflow/drop counters into the
  CapacityTuner, and reports the factor it converges on plus the
  drop-rate-vs-factor curve (the docs/perf.md prediction table's
  third row).

Each artifact records ms/step, the lowered all_to_all replica-group
structure (the compiled-program evidence: group-limited intra+inter
legs, NO world-spanning alltoall on the hier leg), and per-hop
expert-dispatch byte accounting from the row-crossing model below
(dispatch + return, payload rows only — the int32 expert map is
world-size-invariant noise). BENCH_DRYRUN=1 is the CI smoke shape
(tiny model, 2 iters; ``./ci.sh bench-smoke`` gates on the artifacts
AND on the pre-registered prediction that the hier-int8 leg drops
inter-hop expert-dispatch bytes >= 3x vs flat fp32 with identical
routing). CPU lines carry the quarantine note: wall-clock claims need
the on-chip capture; the dryrun validates harness + HLO shape + byte
accounting.

Env: BENCH_TOKENS / BENCH_DMODEL / BENCH_DFF / BENCH_INTRA /
BENCH_ITERS / BENCH_DRYRUN / BENCH_ARTIFACT_DIR.
"""

import json
import os
import time

from _benchlib import stamp as _stamp

_SIM_NOTE = (
    "logic-validation only (CPU simulation); step-time is NOT a TPU "
    "wall-clock number — byte accounting and HLO shape are exact"
)


def _a2a_group_sizes(lowered):
    """Replica-group row lengths of every all_to_all in the module —
    via the shared horovod_tpu.analysis parser (same gate as
    tests/test_moe_wire)."""
    from horovod_tpu import analysis

    return analysis.parse_module(lowered).group_sizes("all_to_all")


def _hop_bytes(leg, L, H, capacity, d, block):
    """Per-step per-rank expert-dispatch wire bytes by hop (dispatch +
    return, payload rows only): a row crosses the INTER (DCN) boundary
    iff its destination lives in another slice — (H-1)·L·C rows either
    way — at fp32 on the flat leg, int8 + per-block fp32 scales on the
    hier-int8 leg. The intra (ICI) hop carries (L-1)·C rows flat /
    (L-1)·H·C rows hier, always exact."""
    nb = -(-d // block)
    int8_row = d + nb * 4
    fp32_row = d * 4
    inter_rows = (H - 1) * L * capacity
    if leg == "ab_hier_int8":
        inter = 2 * inter_rows * int8_row
        intra = 2 * (L - 1) * H * capacity * fp32_row
    else:
        inter = 2 * inter_rows * fp32_row
        intra = 2 * (L - 1) * capacity * fp32_row
    return {"intra_bytes": intra, "inter_bytes": inter}


def main():
    import jax

    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import PartitionSpec as P

    import horovod_tpu as hvd
    from _benchlib import sync as _sync
    from horovod_tpu.common.autotune import shared_capacity_tuner
    from horovod_tpu.common.compat import shard_map
    from horovod_tpu.common.metrics import publish_moe
    from horovod_tpu.common.topology import hierarchical_stage_groups
    from horovod_tpu.parallel.moe import (
        MoEParams,
        init_moe_params,
        moe_ffn,
    )

    dryrun = os.environ.get("BENCH_DRYRUN", "").strip() in ("1", "true")
    iters = int(os.environ.get("BENCH_ITERS", "2" if dryrun else "30"))
    tokens = int(os.environ.get("BENCH_TOKENS", "32" if dryrun else "512"))
    d_model = int(os.environ.get("BENCH_DMODEL", "64" if dryrun else "512"))
    d_ff = int(os.environ.get("BENCH_DFF", "128" if dryrun else "2048"))
    intra = int(os.environ.get("BENCH_INTRA", "4"))
    block = min(128, d_model)

    artifact_dir = os.environ.get(
        "BENCH_ARTIFACT_DIR", os.path.join("bench_results", "moe")
    )
    os.makedirs(artifact_dir, exist_ok=True)

    hvd.init()
    mesh = hvd.mesh()
    world = hvd.size()
    if world % intra:
        intra = 2 if world % 2 == 0 else 1
    stages = hierarchical_stage_groups(world, intra)
    if stages is None:
        raise SystemExit(
            f"no two-level split for world={world} intra={intra}"
        )
    L, H = intra, world // intra
    platform = jax.devices()[0].platform
    e_local = 2
    e_total = e_local * world

    rng = np.random.default_rng(0)
    params = init_moe_params(
        jax.random.PRNGKey(0), d_model, d_ff, e_total, e_total
    )
    spec = MoEParams(
        router=P(), w1=P(hvd.WORLD_AXIS), b1=P(hvd.WORLD_AXIS),
        w2=P(hvd.WORLD_AXIS), b2=P(hvd.WORLD_AXIS),
    )
    x = rng.normal(size=(world, tokens, d_model)).astype(np.float32)

    def make_step(leg, capacity_factor=1.25):
        hier = None if leg == "ab_flat" else stages
        wire = "int8" if leg == "ab_hier_int8" else "fp32"

        def body(p, v, s):
            out, st = moe_ffn(
                p, v[0], axis_name=hvd.WORLD_AXIS,
                capacity_factor=capacity_factor, wire=wire, hier=hier,
                seed=s, block_size=block, return_stats=True,
            )
            return out[None], st

        return jax.jit(
            shard_map(
                body,
                mesh=mesh,
                in_specs=(spec, P(hvd.WORLD_AXIS), P()),
                out_specs=(P(hvd.WORLD_AXIS), P()),
                check_vma=False,
            )
        )

    def emit(leg, ms, a2a_sizes, hops, extra=None):
        line = {
            "metric": "moe_ab",
            "leg": leg,
            "world": world,
            "intra": L,
            "slices": H,
            "tokens_per_rank": tokens,
            "d_model": d_model,
            "e_total": e_total,
            "value": round(ms, 3),
            "unit": "ms/step",
            "platform": platform,
            "a2a_group_sizes": sorted(a2a_sizes),
            **hops,
        }
        if extra:
            line.update(extra)
        if platform != "tpu":
            line["note"] = _SIM_NOTE
        print(json.dumps(_stamp(line)), flush=True)
        with open(
            os.path.join(artifact_dir, f"moe_{leg}.json"), "a"
        ) as f:
            f.write(json.dumps(_stamp(line)) + "\n")

    capacity = int(max(1, round(1.25 * tokens / world)))
    xd = jnp.asarray(x)
    results = {}
    flat_hops = None
    for leg in ("ab_flat", "ab_hier_int8"):
        step = make_step(leg)
        sizes = _a2a_group_sizes(step.lower(params, xd, jnp.int32(0)))
        out, st = step(params, xd, jnp.int32(0))  # compile + warm
        _sync(out)
        t0 = time.perf_counter()
        for i in range(iters):
            out, st = step(params, xd, jnp.int32(i + 1))
        _sync(out)
        ms = (time.perf_counter() - t0) * 1e3 / iters
        hops = _hop_bytes(leg, L, H, capacity, d_model, block)
        if leg == "ab_flat":
            flat_hops = hops
        hops["inter_ratio_vs_flat"] = (
            round(flat_hops["inter_bytes"] / hops["inter_bytes"], 2)
            if hops["inter_bytes"]
            else None
        )
        emit(leg, ms, sizes, hops)
        results[leg] = {
            "sizes": sizes,
            "hops": hops,
            "hist": np.asarray(st.expert_tokens),
            "dropped": float(st.dropped),
            "out": np.asarray(out),
        }

    # ------------------------------------------- capacity autotune leg
    # durable instance (HOROVOD_TUNER_CACHE): warm-started from prior
    # runs, persisted at exit — capacity exploration is paid once per
    # topology fingerprint, not once per process per run
    tuner = shared_capacity_tuner(
        trials=1 if dryrun else 2,
        candidates=(1.0, 2.0) if dryrun else (1.0, 1.25, 1.5, 2.0),
    )
    key = ("moe", world, tokens, d_model)
    curve = {}
    cap_iters = max(2, iters)
    while tuner.needs_trial(key, tuner.choose(key)):
        cf = tuner.choose(key)
        step = make_step("ab_captuned", capacity_factor=cf)
        out, st = step(params, xd, jnp.int32(0))
        _sync(out)
        t0 = time.perf_counter()
        for i in range(cap_iters):
            out, st = step(params, xd, jnp.int32(i + 1))
        _sync(out)
        secs = (time.perf_counter() - t0) / cap_iters
        hist = np.asarray(st.expert_tokens)
        tuner.observe_load(
            key, cf, hist, dropped=float(st.dropped),
            total=float(st.total), seconds=secs,
        )
        publish_moe(
            hist, float(st.dropped), float(st.total), capacity_factor=cf
        )
        curve[str(cf)] = {
            "drop_rate": round(tuner.drop_rate(key, cf), 4),
            "imbalance": round(tuner.imbalance(key, cf), 3),
            "ms_per_step": round(secs * 1e3, 3),
        }
    chosen = tuner.choose(key)
    emit(
        "ab_captuned",
        curve[str(chosen)]["ms_per_step"],
        [],
        {"intra_bytes": 0, "inter_bytes": 0},
        extra={
            "chosen_capacity_factor": chosen,
            "drop_curve": curve,
            "unit_note": "ms/step at the chosen factor",
        },
    )
    assert chosen in tuner.candidates
    # the curve is monotone where it must be: more capacity, fewer drops
    cands = sorted(float(c) for c in curve)
    drops = [curve[str(c)]["drop_rate"] for c in cands]
    assert all(a >= b - 1e-9 for a, b in zip(drops, drops[1:])), curve

    # structural gates (valid on every backend): the hier leg's
    # compiled program carries ONLY group-limited all_to_alls (intra
    # size-L legs + inter size-H legs), never a monolithic flat one;
    # the flat leg is exactly the monolithic baseline
    flat_sizes = results["ab_flat"]["sizes"]
    hier_sizes = results["ab_hier_int8"]["sizes"]
    assert flat_sizes and all(s == world for s in flat_sizes), flat_sizes
    assert hier_sizes and all(s < world for s in hier_sizes), hier_sizes
    assert {s for s in hier_sizes} <= {L, H}, hier_sizes
    # identical routing: the wire is downstream of the router by
    # construction — bitwise-equal expert histograms and drop counts
    np.testing.assert_array_equal(
        results["ab_flat"]["hist"], results["ab_hier_int8"]["hist"]
    )
    assert results["ab_flat"]["dropped"] == (
        results["ab_hier_int8"]["dropped"]
    )
    # outputs within the pre-registered bound (docs/perf.md): a few
    # quanta through the expert FFN on cross-slice tokens only
    a, b = results["ab_flat"]["out"], results["ab_hier_int8"]["out"]
    scale = float(np.abs(a).max())
    max_dev = float(np.abs(a - b).max())
    assert max_dev <= 0.15 * scale, (max_dev, scale)
    # the pre-registered DCN-byte prediction: >= 3x fewer inter-hop
    # expert-dispatch bytes for hier-int8 vs flat fp32
    ratio = results["ab_hier_int8"]["hops"]["inter_ratio_vs_flat"]
    assert ratio >= 3.0, results
    print(
        json.dumps(
            {
                "metric": "moe_ab_summary",
                "inter_ratio_hier_int8": ratio,
                "routing_identical": True,
                "max_output_dev_frac": round(max_dev / scale, 5),
                "chosen_capacity_factor": chosen,
                "gate": (
                    "inter expert-dispatch bytes drop >=3x, routing "
                    "bitwise identical, outputs within 0.15*scale"
                ),
            }
        ),
        flush=True,
    )


if __name__ == "__main__":
    main()
