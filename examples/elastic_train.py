"""Elastic training example — survive worker churn with commit/restore.

Parity with the reference's elastic examples
(ref: examples/elastic/pytorch/pytorch_mnist_elastic.py [V] and the
``hvd.elastic.run`` + ``State`` protocol, SURVEY.md §3.4): train under a
decorator that catches peer failures, rolls state back to the last
``commit()``, re-rendezvouses, and resumes.

On TPU, "membership changed" means slice re-acquisition rather than
NCCL communicator rebuild, but the user-facing protocol is identical.

Run under the elastic launcher:
    python -m horovod_tpu.runner -np 2 --placement per-slot \
        --  python examples/elastic_train.py
or single-process: python examples/elastic_train.py
"""

import os

import numpy as np
import optax
import jax

# The sandbox's sitecustomize can force-select a TPU platform; honor an
# explicit JAX_PLATFORMS request at the config level (see tests/conftest.py).
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
import jax.numpy as jnp

import horovod_tpu as hvd
from horovod_tpu.models import MNISTConvNet


def main():
    hvd.init()
    model = MNISTConvNet()
    opt = hvd.DistributedOptimizer(optax.sgd(0.02, momentum=0.9))

    sample = jnp.zeros((32, 28, 28, 1), jnp.float32)
    params = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        sample,
    )
    opt_state = opt.init(params)

    # State holds everything that must survive a membership change
    # (ref: hvd.elastic.TorchState [V]; here JaxState snapshots pytrees
    # to host on commit). batch tracks progress so a restore resumes
    # where the last commit left off.
    state = hvd.elastic.JaxState(
        params=params, opt_state=opt_state, batch=0, epoch=0
    )

    rng = np.random.default_rng(0)

    from functools import partial

    from jax.sharding import PartitionSpec as P

    # The DistributedOptimizer's allreduce needs the mesh axis bound, so
    # the step runs under shard_map; each rank trains on its own shard
    # of the batch (rank-major leading axis, like examples/mnist.py).
    @partial(
        jax.shard_map,
        mesh=hvd.mesh(),
        in_specs=(P(), P(), P(hvd.WORLD_AXIS), P(hvd.WORLD_AXIS), P()),
        out_specs=(P(), P(), P()),
        check_vma=False,
    )
    def train_step(params, opt_state, x, y, key):
        x, y = x[0], y[0]

        def loss_fn(p):
            logits = model.apply(p, x, train=True, rngs={"dropout": key})
            return optax.softmax_cross_entropy(
                logits, jax.nn.one_hot(y, 10)
            ).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, jax.lax.pmean(loss, hvd.WORLD_AXIS)

    train_step = jax.jit(train_step)

    @hvd.elastic.run
    def train(state):
        loss = None  # may resume at an epoch boundary with no new batch
        while state.epoch < 2:
            while state.batch < 20:
                world = hvd.size()
                x = rng.normal(size=(world, 8, 28, 28, 1)).astype(np.float32)
                y = rng.integers(0, 10, size=(world, 8)).astype(np.int32)
                state.params, state.opt_state, loss = train_step(
                    state.params,
                    state.opt_state,
                    jnp.asarray(x),
                    jnp.asarray(y),
                    jax.random.fold_in(
                        jax.random.PRNGKey(3), state.epoch * 1000 + state.batch
                    ),
                )
                state.batch += 1
                if state.batch % 10 == 0:
                    # Checkpoint-in-memory: a failure after this point
                    # rolls back here, not to the epoch start.
                    state.commit()
            if hvd.rank() == 0 and loss is not None:
                print(f"epoch {state.epoch}: loss {float(loss):.4f}")
            state.batch = 0
            state.epoch += 1
            state.commit()

    train(state)
    if hvd.rank() == 0:
        print("elastic training complete")


if __name__ == "__main__":
    main()
