"""Chunked fused linear-cross-entropy (ops/fused_xent.py) vs the
materialized logits path — the numerics contract is exact equality of
value AND gradients under compute_dtype=None, and matmul-precision
agreement under the bf16 head recipe."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from horovod_tpu.ops.fused_xent import (
    _chunk_starts,
    fused_linear_cross_entropy,
)


def _dense_loss(x, kernel, bias, labels, dtype):
    if dtype is not None:
        logits = jax.lax.dot_general(
            x.astype(dtype), kernel.astype(dtype),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) + bias[None, :].astype(jnp.float32)
    else:
        logits = x.astype(jnp.float32) @ kernel.astype(jnp.float32) + bias
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels)


def _problem(n=24, d=16, vocab=101, seed=0, x_dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.normal(size=(n, d)), x_dtype)
    kernel = jnp.asarray(rng.normal(size=(d, vocab)) * 0.2, jnp.float32)
    bias = jnp.asarray(rng.normal(size=(vocab,)) * 0.1, jnp.float32)
    # hit every boundary class: 0, vocab-1, chunk edges
    labels = jnp.asarray(
        np.concatenate(
            [[0, vocab - 1], rng.integers(0, vocab, size=n - 2)]
        ),
        jnp.int32,
    )
    return x, kernel, bias, labels


def test_chunk_starts_cover_exactly():
    for vocab, chunk in [(101, 32), (101, 101), (101, 1000), (64, 64),
                         (64, 16), (7, 3), (1, 5)]:
        spans = _chunk_starts(vocab, chunk)
        cols = [c for s, w in spans for c in range(s, s + w)]
        assert cols == list(range(vocab)), (vocab, chunk)


@pytest.mark.parametrize("chunk", [16, 32, 101, 4096])
def test_fp32_exact_match(chunk):
    x, kernel, bias, labels = _problem()
    want = _dense_loss(x, kernel, bias, labels, None)
    got = fused_linear_cross_entropy(
        x, kernel, bias, labels, chunk=chunk, compute_dtype=None
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("chunk", [16, 37, 101])
def test_fp32_gradients_match(chunk):
    x, kernel, bias, labels = _problem()

    def fused(x, k, b):
        return fused_linear_cross_entropy(
            x, k, b, labels, chunk=chunk, compute_dtype=None
        ).mean()

    def dense(x, k, b):
        return _dense_loss(x, k, b, labels, None).mean()

    gf = jax.grad(fused, argnums=(0, 1, 2))(x, kernel, bias)
    gd = jax.grad(dense, argnums=(0, 1, 2))(x, kernel, bias)
    for got, want, name in zip(gf, gd, ("dx", "dW", "db")):
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-6,
            err_msg=name,
        )


def test_bf16_head_recipe_agrees_with_dense_bf16():
    x, kernel, bias, labels = _problem(n=32, d=32, vocab=257)

    def fused(x, k, b):
        return fused_linear_cross_entropy(
            x, k, b, labels, chunk=64, compute_dtype=jnp.bfloat16
        ).mean()

    def dense(x, k, b):
        return _dense_loss(x, k, b, labels, jnp.bfloat16).mean()

    lv_f = fused(x, kernel, bias)
    lv_d = dense(x, kernel, bias)
    # same operand rounding, fp32 accumulation: only chunk-order of the
    # logsumexp differs
    np.testing.assert_allclose(float(lv_f), float(lv_d), rtol=5e-3)
    gf = jax.grad(fused, argnums=(0, 1, 2))(x, kernel, bias)
    gd = jax.grad(dense, argnums=(0, 1, 2))(x, kernel, bias)
    for got, want, name in zip(gf, gd, ("dx", "dW", "db")):
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32),
            rtol=0.08, atol=5e-3, err_msg=name,
        )


def test_bf16_activations_gradient_dtype():
    x, kernel, bias, labels = _problem(x_dtype=jnp.bfloat16)
    dx = jax.grad(
        lambda x: fused_linear_cross_entropy(
            x, kernel, bias, labels, chunk=32
        ).mean()
    )(x)
    assert dx.dtype == jnp.bfloat16


def test_jit_and_shapes():
    x, kernel, bias, labels = _problem()
    f = jax.jit(
        lambda x, k, b, l: fused_linear_cross_entropy(
            x, k, b, l, chunk=32, compute_dtype=None
        )
    )
    out = f(x, kernel, bias, labels)
    assert out.shape == labels.shape and out.dtype == jnp.float32
    with pytest.raises(ValueError, match="tokens, d_model"):
        fused_linear_cross_entropy(x[None], kernel, bias, labels)
    with pytest.raises(ValueError, match="labels shape"):
        fused_linear_cross_entropy(x, kernel, bias, labels[:3])


def test_transformer_hidden_path_matches_logits_path(hvd):
    """model(..., return_hidden=True) + fused loss == logits + optax
    loss on a tiny causal transformer (the bench_lm integration)."""
    from horovod_tpu.models import Transformer, TransformerConfig

    cfg = TransformerConfig.tiny(causal=True)
    model = Transformer(cfg)
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(0, cfg.vocab_size, (2, 16)),
        jnp.int32,
    )
    labels = jnp.asarray(
        np.random.default_rng(2).integers(0, cfg.vocab_size, (2, 16)),
        jnp.int32,
    )
    params = model.init(jax.random.PRNGKey(0), tokens, train=False)

    def dense_loss(p):
        logits = model.apply(p, tokens, train=False)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits.astype(jnp.float32), labels
        ).mean()

    def fused_loss(p):
        h = model.apply(p, tokens, train=False, return_hidden=True)
        head = p["params"]["lm_head"]
        return fused_linear_cross_entropy(
            h.reshape(-1, cfg.d_model),
            head["kernel"], head["bias"],
            labels.reshape(-1),
            chunk=64,
            compute_dtype=cfg.dtype if cfg.head_mixed_precision else None,
        ).mean()

    np.testing.assert_allclose(
        float(dense_loss(params)), float(fused_loss(params)), rtol=5e-3
    )
    gd = jax.grad(dense_loss)(params)
    gf = jax.grad(fused_loss)(params)
    flat_d = jax.tree_util.tree_leaves_with_path(gd)
    flat_f = {jax.tree_util.keystr(k): v
              for k, v in jax.tree_util.tree_leaves_with_path(gf)}
    for key, want in flat_d:
        got = flat_f[jax.tree_util.keystr(key)]
        np.testing.assert_allclose(
            np.asarray(got, np.float32), np.asarray(want, np.float32),
            rtol=0.1, atol=6e-3, err_msg=jax.tree_util.keystr(key),
        )
