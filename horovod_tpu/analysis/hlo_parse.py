"""Structured parser over lowered StableHLO/HLO text.

``jit(fn).lower(*args).as_text()`` prints the module in MLIR generic
form; the collectives this repo's invariants are written against all
surface as quoted ops with their routing attributes inline::

    %0 = "stablehlo.all_reduce"(%arg0) <{..., replica_groups =
        dense<[[0, 1, 2, 3], [4, 5, 6, 7]]> : tensor<2x4xi64>, ...}> ({
    ^bb0(%arg1: tensor<f32>, %arg2: tensor<f32>):
      ...
    }) : (tensor<1x16xf32>) -> tensor<1x16xf32>

The parser is deliberately line-structured (the format the rest of the
repo already greps) rather than a full MLIR frontend: it recovers
exactly the facts the rule engine needs — per-function SSA def-use,
the five collective kinds with replica groups / operand types /
reduction scalar type, and donation coverage from the entry function's
``jax.buffer_donor`` arg attributes — and attaches line-accurate
snippets so a violated invariant can show the offending HLO.

Scope notes:

* Def-use edges are computed WITHIN each function body; ``call`` edges
  are opaque. Every collective this repo lowers lives inside a single
  ``shmap_body``/entry function, so independence questions never cross
  a call boundary in practice.
* Donation at the StableHLO level is the ``jax.buffer_donor`` arg
  attribute (plus ``tf.aliasing_output`` for pre-pinned aliases); the
  post-compile ``input_output_alias`` table is derived from it.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Tuple

# The lowered-program surface the invariant catalog is written over.
COLLECTIVE_KINDS = (
    "all_reduce",
    "reduce_scatter",
    "all_gather",
    "all_to_all",
    "collective_permute",
)

_DTYPE_BYTES = {
    "i1": 1, "i4": 1, "i8": 1, "i16": 2, "i32": 4, "i64": 8,
    "ui4": 1, "ui8": 1, "ui16": 2, "ui32": 4, "ui64": 8,
    "f8E4M3FN": 1, "f8E5M2": 1, "bf16": 2, "f16": 2, "f32": 4,
    "f64": 8, "c64": 8, "c128": 16,
}

_STMT_RE = re.compile(r"^\s*(%[\w.#:]+)\s*=\s*(.*)$")
_SSA_RE = re.compile(r"%[\w.#]+")
_TENSOR_RE = re.compile(r"tensor<([^>]*)>")
_FUNC_RE = re.compile(r"^\s*func\.func\s+(?:public|private)?\s*@([\w.]+)\s*\(")
_GROUPS_RE = re.compile(r"replica_groups\s*=\s*dense<(.*?)>\s*:\s*tensor<")
_PAIRS_RE = re.compile(r"source_target_pairs\s*=\s*dense<(.*?)>\s*:\s*tensor<")
_PARTITIONS_RE = re.compile(r"mhlo.num_partitions\s*=\s*(\d+)")
_SIG_RE = re.compile(r":\s*\((.*?)\)\s*->\s*(.*)$")


@dataclasses.dataclass(frozen=True)
class TensorType:
    """One ``tensor<...>`` type: shape, element dtype, sizes."""

    shape: Tuple[int, ...]
    dtype: str

    @property
    def elems(self) -> int:
        n = 1
        for d in self.shape:
            n *= d
        return n

    @property
    def nbytes(self) -> int:
        return self.elems * _DTYPE_BYTES.get(self.dtype, 4)

    @property
    def is_scalar(self) -> bool:
        return self.elems == 1

    def __str__(self) -> str:  # tensor<1x16xf32> back-form, for messages
        dims = "x".join(str(d) for d in self.shape)
        return f"tensor<{dims}{'x' if dims else ''}{self.dtype}>"


def _parse_tensor_type(inner: str) -> TensorType:
    """``1x16xf32`` / ``f32`` / ``2x4xi64`` -> TensorType. Dynamic or
    exotic dims parse as 0 (they never occur in this repo's modules)."""
    parts = inner.strip().split("x")
    dims: List[int] = []
    dtype = parts[-1]
    for p in parts[:-1]:
        try:
            dims.append(int(p))
        except ValueError:
            dims.append(0)
    return TensorType(tuple(dims), dtype.strip())


def _types_in(text: str) -> Tuple[TensorType, ...]:
    return tuple(_parse_tensor_type(m) for m in _TENSOR_RE.findall(text))


def _parse_groups(raw: str) -> Tuple[Tuple[int, ...], ...]:
    """``[[0, 1, 2, 3], [4, 5, 6, 7]]`` (or a splat like ``0``) ->
    tuple of rank rows."""
    raw = raw.strip()
    if not raw.startswith("["):
        # dense splat (single scalar) — one group of one
        try:
            return ((int(raw),),)
        except ValueError:
            return ()
    rows = []
    for row in re.findall(r"\[([-\d,\s]*?)\]", raw.replace("[[", "[").replace("]]", "]")):
        vals = [int(v) for v in row.replace(" ", "").split(",") if v != ""]
        if vals:
            rows.append(tuple(vals))
    return tuple(rows)


@dataclasses.dataclass
class Statement:
    """One SSA statement inside a function body."""

    sid: str
    func: str
    rhs: str
    operands: Tuple[str, ...]
    line_no: int  # 0-based index into the module's line list


@dataclasses.dataclass
class Collective:
    """A parsed collective op with its routing and payload facts."""

    kind: str  # one of COLLECTIVE_KINDS
    sid: str
    func: str
    index: int  # order of appearance among the module's collectives
    replica_groups: Tuple[Tuple[int, ...], ...]
    operand_types: Tuple[TensorType, ...]
    result_types: Tuple[TensorType, ...]
    reduction_dtype: Optional[str]  # region block-arg scalar type
    line_no: int
    snippet: str

    @property
    def group_sizes(self) -> Tuple[int, ...]:
        return tuple(len(g) for g in self.replica_groups)

    @property
    def operand_bytes(self) -> int:
        return sum(t.nbytes for t in self.operand_types)

    @property
    def dtypes(self) -> Tuple[str, ...]:
        return tuple(t.dtype for t in self.operand_types)

    def spans(self, world: int) -> bool:
        """True when any replica group covers the whole world."""
        return any(len(g) >= world for g in self.replica_groups)

    def is_scalar(self) -> bool:
        return all(t.is_scalar for t in self.operand_types)


@dataclasses.dataclass(frozen=True)
class ArgInfo:
    """One entry-function argument: its type and donation marking."""

    index: int
    type: Optional[TensorType]
    donated: bool
    aliased_output: Optional[int]  # tf.aliasing_output target, if pinned


class ProgramGraph:
    """Typed view of one lowered module: collectives, def-use edges,
    donation coverage. Built by :func:`parse_module`."""

    def __init__(
        self,
        text: str,
        collectives: List[Collective],
        statements: Dict[str, Dict[str, Statement]],
        args: Dict[str, List[ArgInfo]],
        entry: str,
        num_partitions: int,
    ) -> None:
        self.text = text
        self._collectives = collectives
        self._stmts = statements  # {func: {sid: Statement}}
        self._args = args  # {func: [ArgInfo]}
        self.entry = entry
        self.num_partitions = num_partitions

    # ------------------------------------------------------------ queries

    def collectives(self, kind: Optional[str] = None) -> List[Collective]:
        if kind is None:
            return list(self._collectives)
        _check_kind(kind)
        return [c for c in self._collectives if c.kind == kind]

    def count(self, kind: str) -> int:
        return len(self.collectives(kind))

    def counts(self) -> Dict[str, int]:
        """{kind: count} over every collective kind (bench gates)."""
        out = {k: 0 for k in COLLECTIVE_KINDS}
        for c in self._collectives:
            out[c.kind] += 1
        return out

    def replica_groups(
        self, kind: Optional[str] = None
    ) -> List[Tuple[Tuple[int, ...], ...]]:
        return [c.replica_groups for c in self.collectives(kind)]

    def group_sizes(self, kind: Optional[str] = None) -> List[int]:
        """First-row group size of each matching collective (the
        monolithic-exchange detector: a size == world row spans it)."""
        out = []
        for c in self.collectives(kind):
            if c.replica_groups:
                out.append(len(c.replica_groups[0]))
        return out

    def args(self, func: Optional[str] = None) -> List[ArgInfo]:
        return list(self._args.get(func or self.entry, []))

    def donated_args(self, func: Optional[str] = None) -> List[ArgInfo]:
        return [a for a in self.args(func) if a.donated or a.aliased_output is not None]

    # ---------------------------------------------------------- def-use

    def _deps_of(self, stmt: Statement) -> set:
        """Transitive SSA dependencies of one statement (within its
        function body; call boundaries are opaque)."""
        defs = self._stmts.get(stmt.func, {})
        out: set = set()
        stack = [o.split("#")[0] for o in stmt.operands]
        while stack:
            o = stack.pop()
            if o in out or o not in defs:
                continue
            out.add(o)
            # `%a#0` uses resolve to the multi-result def `%a`
            stack.extend(x.split("#")[0] for x in defs[o].operands)
        return out

    def dependent_pairs(
        self, kind: Optional[str] = None
    ) -> List[Tuple[Collective, Collective]]:
        """(dependent, dependency) pairs among the matching collectives:
        empty means every matching collective is mutually independent —
        the overlap contract (no artificial serialization between
        buckets)."""
        colls = self.collectives(kind)
        by_func: Dict[str, List[Collective]] = {}
        for c in colls:
            by_func.setdefault(c.func, []).append(c)
        pairs: List[Tuple[Collective, Collective]] = []
        for func, group in by_func.items():
            defs = self._stmts.get(func, {})
            ids = {c.sid: c for c in group}
            for c in group:
                stmt = defs.get(c.sid)
                if stmt is None:
                    continue
                deps = self._deps_of(stmt)
                for other_sid, other in ids.items():
                    if other_sid != c.sid and other_sid in deps:
                        pairs.append((c, other))
        return pairs

    def independent(self, kind: Optional[str] = None) -> bool:
        return not self.dependent_pairs(kind)


def _check_kind(kind: str) -> None:
    if kind not in COLLECTIVE_KINDS:
        raise ValueError(
            f"unknown collective kind {kind!r}; expected one of "
            f"{COLLECTIVE_KINDS}"
        )


def _parse_func_args(sig: str) -> List[ArgInfo]:
    """Arguments of one ``func.func`` signature line: type + donation
    attrs. The signature is everything between the outer parens."""
    args: List[ArgInfo] = []
    # split on top-level commas (attr dicts `{...}` and types `<...>`
    # carry nested commas)
    depth = 0
    start = 0
    parts: List[str] = []
    for i, ch in enumerate(sig):
        if ch in "<{([":
            depth += 1
        elif ch in ">})]":
            depth -= 1
        elif ch == "," and depth == 0:
            parts.append(sig[start:i])
            start = i + 1
    tail = sig[start:].strip()
    if tail:
        parts.append(tail)
    for i, part in enumerate(parts):
        tm = _TENSOR_RE.search(part)
        ttype = _parse_tensor_type(tm.group(1)) if tm else None
        donated = "jax.buffer_donor" in part
        alias = None
        am = re.search(r"tf\.aliasing_output\s*=\s*(\d+)", part)
        if am:
            alias = int(am.group(1))
        args.append(ArgInfo(i, ttype, donated, alias))
    return args


def parse_module(lowered) -> ProgramGraph:
    """Parse a lowered module into a :class:`ProgramGraph`.

    Accepts the module text, anything with ``.as_text()`` (a
    ``jax.stages.Lowered``), or anything with ``.lower`` already
    applied. This is THE shared entry point — tests and bench gates
    pass their lowered step here instead of regexing the text."""
    if hasattr(lowered, "as_text"):
        text = lowered.as_text()
    else:
        text = str(lowered)
    lines = text.splitlines()

    num_partitions = 1
    pm = _PARTITIONS_RE.search(text)
    if pm:
        num_partitions = int(pm.group(1))

    statements: Dict[str, Dict[str, Statement]] = {}
    func_args: Dict[str, List[ArgInfo]] = {}
    collectives: List[Collective] = []
    entry = "main"
    current_func = ""

    i = 0
    n = len(lines)
    while i < n:
        line = lines[i]
        fm = _FUNC_RE.match(line)
        if fm:
            current_func = fm.group(1)
            if "public" in line.split("@")[0] and not func_args.get(entry):
                entry = current_func
            # signatures in as_text() print single-line
            inner = line[line.index("(") + 1 :]
            # cut at the matching close paren of the arg list
            depth = 1
            for k, ch in enumerate(inner):
                if ch == "(":
                    depth += 1
                elif ch == ")":
                    depth -= 1
                    if depth == 0:
                        inner = inner[:k]
                        break
            func_args[current_func] = _parse_func_args(inner)
            statements.setdefault(current_func, {})
            i += 1
            continue

        m = _STMT_RE.match(line)
        if not m:
            i += 1
            continue
        sid, rhs = m.group(1), m.group(2)
        # a `%a:2 = ...` multi-result statement: normalize the id
        sid = sid.split(":")[0]
        op_lines = [line]
        end = i
        if rhs.rstrip().endswith("({"):
            # region-carrying op (all_reduce / reduce_scatter): the
            # type signature rides the closing `})` line
            depth = 1
            j = i + 1
            while j < n and depth > 0:
                op_lines.append(lines[j])
                depth += lines[j].count("({")
                if lines[j].lstrip().startswith("})"):
                    depth -= 1
                j += 1
            end = j - 1
            rhs_full = rhs + " " + " ".join(
                ln.strip() for ln in op_lines[1:]
            )
        else:
            rhs_full = rhs
        operands = tuple(_SSA_RE.findall(rhs))
        statements.setdefault(current_func, {})[sid] = Statement(
            sid, current_func, rhs_full, operands, i
        )

        kind = None
        for k in COLLECTIVE_KINDS:
            if f'"stablehlo.{k}"' in rhs or f'"mhlo.{k}"' in rhs:
                kind = k
                break
        if kind is not None:
            gm = _GROUPS_RE.search(rhs)
            if gm is None:
                gm = _PAIRS_RE.search(rhs)
            groups = _parse_groups(gm.group(1)) if gm else ()
            # operand/result types: trailing `: (...) -> ...` on the
            # closing line (region ops) or the op line itself
            sig_line = op_lines[-1]
            sm = _SIG_RE.search(sig_line)
            if sm:
                operand_types = _types_in(sm.group(1))
                result_types = _types_in(sm.group(2))
            else:
                operand_types = result_types = ()
            red_dtype = None
            for ln in op_lines:
                bm = re.search(r"\^bb\d+\(%[\w.#]+:\s*tensor<([^>]*)>", ln)
                if bm:
                    red_dtype = _parse_tensor_type(bm.group(1)).dtype
                    break
            snippet = op_lines[0].strip()
            if len(snippet) > 240:
                snippet = snippet[:237] + "..."
            collectives.append(
                Collective(
                    kind=kind,
                    sid=sid,
                    func=current_func,
                    index=len(collectives),
                    replica_groups=groups,
                    operand_types=operand_types,
                    result_types=result_types,
                    reduction_dtype=red_dtype,
                    line_no=i,
                    snippet=snippet,
                )
            )
        i = end + 1

    return ProgramGraph(
        text, collectives, statements, func_args, entry, num_partitions
    )
