"""Continuous-vs-static batching A/B (horovod_tpu/serving/).

Measures what the continuous-batching scheduler actually buys over
classic batch-barrier inference ON THE SAME engine — the serving
analog of the Gemma-on-TPU paper's scheduling claim (PAPERS.md, arXiv
2605.25645; the pre-registered prediction table is in docs/perf.md
§"Serving: continuous vs static batching").

Two legs over the SAME toy decoder, the SAME Poisson-ish staggered
arrival trace, and the SAME per-request token budget, each appending
one JSON artifact under BENCH_ARTIFACT_DIR (default
bench_results/serve/):

* ``ab_static``     — ``ContinuousBatcher(policy="static")``: requests
  admitted only when the previous batch fully completed. A late
  arrival waits for the whole in-flight batch (head-of-line blocking);
  the batch's tail token rate decays as members finish.
* ``ab_continuous`` — the default policy: arrivals admitted into freed
  slots between decode steps, no flush, no barrier.

Each artifact records per-request TTFT and per-token TPOT p50/p95 plus
aggregate generated tokens/s. Both legs pay their compiles in an
untimed warmup (prefill buckets + the decode step), so the measured
delta is pure scheduling. BENCH_DRYRUN=1 is the CI smoke shape
(`./ci.sh bench-smoke` gates on the artifacts existing); CPU lines
carry the quarantine note — the decode step is milliseconds on CPU and
microseconds of MXU on a chip, so only an on-chip capture decides the
wall-clock claim, but the SCHEDULING effect (TTFT under load) is real
in either domain.

Env: BENCH_REQUESTS / BENCH_GEN_TOKENS / BENCH_SLOTS / BENCH_STAGGER_MS.
"""

import json
import os
import time

_SIM_NOTE = (
    "logic-validation only (CPU simulation); decode steps are ms on "
    "CPU vs us on MXU — NOT a TPU wall-clock number, but the "
    "scheduling deltas (TTFT under load) are structural"
)


def main():
    import jax

    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

    import jax.numpy as jnp
    import numpy as np

    from horovod_tpu.models.transformer import (
        Transformer,
        TransformerConfig,
    )
    from horovod_tpu.serving.batcher import ContinuousBatcher
    from horovod_tpu.serving.engine import InferenceEngine

    dryrun = os.environ.get("BENCH_DRYRUN", "").strip() in ("1", "true")
    n_requests = int(
        os.environ.get("BENCH_REQUESTS", "6" if dryrun else "32")
    )
    gen_tokens = int(
        os.environ.get("BENCH_GEN_TOKENS", "4" if dryrun else "32")
    )
    slots = int(os.environ.get("BENCH_SLOTS", "4" if dryrun else "8"))
    stagger_ms = float(
        os.environ.get("BENCH_STAGGER_MS", "5" if dryrun else "20")
    )
    platform = jax.devices()[0].platform

    artifact_dir = os.environ.get(
        "BENCH_ARTIFACT_DIR", os.path.join("bench_results", "serve")
    )
    os.makedirs(artifact_dir, exist_ok=True)

    if dryrun:
        cfg = TransformerConfig(
            vocab_size=61, num_layers=1, d_model=16, num_heads=2,
            d_ff=32, max_len=128, causal=True, dtype=jnp.float32,
        )
    else:
        cfg = TransformerConfig(
            vocab_size=1024, num_layers=4, d_model=256, num_heads=8,
            d_ff=1024, max_len=512, causal=True, dtype=jnp.float32,
        )
    model = Transformer(cfg)
    params = model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 4), jnp.int32), train=False
    )
    rng = np.random.default_rng(0)
    # mixed-length arrival trace, shared by both legs
    lengths = rng.integers(4, 48 if dryrun else 128, size=n_requests)
    prompts = [
        list(rng.integers(1, cfg.vocab_size, size=int(n)))
        for n in lengths
    ]

    def run_leg(policy: str) -> dict:
        engine = InferenceEngine(
            model, params, slots=slots, max_len=cfg.max_len
        )
        batcher = ContinuousBatcher(
            engine,
            policy=policy,
            max_admit_per_step=max(slots // 2, 1),
            default_max_new_tokens=gen_tokens,
        )
        # untimed warmup: pay every prefill-bucket + decode compile the
        # trace will touch, so the timed region measures scheduling
        warm = batcher.submit(prompts[0][: max(len(prompts[0]) // 2, 1)])
        while not warm.finished():
            batcher.step()
        for _ in range(2):  # twice: the 2nd sighting promotes, so the
            for p in prompts:  # exact-tier compiles land here, untimed
                engine._get_prefill_exe(len(p))
        batcher.start()
        t0 = time.monotonic()
        reqs = []
        for p in prompts:
            reqs.append(batcher.submit(p))
            time.sleep(stagger_ms / 1e3)
        for r in reqs:
            r.wait(timeout=600)
        wall_s = time.monotonic() - t0
        batcher.stop()
        assert all(r.status == "done" for r in reqs), [
            r.status for r in reqs
        ]
        ttfts = sorted(r.ttft_ms for r in reqs)
        slo = batcher.recorder.summaries()
        total_tokens = sum(len(r.out_tokens) for r in reqs)

        def pct(vals, q):
            idx = min(
                int(q * (len(vals) - 1) + 0.5), len(vals) - 1
            )
            return vals[idx]

        return {
            "metric": "serve_ab",
            "leg": f"ab_{policy}",
            "policy": policy,
            "platform": platform,
            "requests": n_requests,
            "slots": slots,
            "gen_tokens": gen_tokens,
            "stagger_ms": stagger_ms,
            "wall_s": round(wall_s, 4),
            "tokens_out": total_tokens,
            "tokens_per_s": round(total_tokens / wall_s, 3),
            "ttft_ms_p50": round(pct(ttfts, 0.5), 3),
            "ttft_ms_p95": round(pct(ttfts, 0.95), 3),
            "tpot_ms_p50": round(slo["tpot_ms"]["p50"], 4),
            "tpot_ms_p95": round(slo["tpot_ms"]["p95"], 4),
            "decode_steps": engine.stats()["decode_steps"],
            "decode_compiles": engine.stats()["decode_compiles"],
            "dryrun": dryrun,
            "note": _SIM_NOTE if platform == "cpu" else "on-chip",
        }

    for policy in ("static", "continuous"):
        line = run_leg(policy)
        path = os.path.join(artifact_dir, f"serve_ab_{policy}.json")
        with open(path, "w") as f:
            f.write(json.dumps(line) + "\n")
        print(json.dumps(line))
    print(f"bench_serve artifacts in {artifact_dir}")


if __name__ == "__main__":
    main()
