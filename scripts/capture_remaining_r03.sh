#!/usr/bin/env bash
# Unattended capture of the round-3 artifacts that the chip-claim wedge
# blocked (docs/perf.md "Backend outage note"): retry each bench with
# long patience — a failed claim takes ~20 min to report UNAVAILABLE,
# which doubles as the backoff. Never kill a claiming process: kills
# are what wedge the chip in the first place.

set -uo pipefail
cd "$(dirname "$0")/.."
mkdir -p bench_results

try_capture() {
  local name="$1" attempts="$2"; shift 2
  local out="bench_results/${name}_r03.json"
  for i in $(seq 1 "$attempts"); do
    echo "=== $name attempt $i -> $out" >&2
    "$@" > "$out".tmp 2> "bench_results/${name}_r03.err"
    if grep -qE '^\{' "$out".tmp; then
      grep -E '^\{' "$out".tmp > "$out"
      rm -f "$out".tmp "bench_results/${name}_r03.err"
      echo "captured $name" >&2
      return 0
    fi
    rm -f "$out".tmp
    sleep 120
  done
  echo "GAVE UP: $name" >&2
  return 1
}

try_capture gpt2_medium 6 env BENCH_MODEL=gpt2_medium BENCH_BATCH=16 BENCH_REMAT=0 python bench_lm.py
try_capture gpt2_medium_remat 2 env BENCH_MODEL=gpt2_medium python bench_lm.py
try_capture bert_large_remat 2 env BENCH_MODEL=bert_large python bench_lm.py
try_capture allreduce 4 python bench_allreduce.py
echo "remaining-matrix done" >&2
