"""Eager dispatch: fusion cycles, async handles, v-variants, join masks.

Reference model: the async/handle sections of test/parallel/test_torch.py
(allreduce_async + synchronize, grouped ops, join with uneven tensors) [V]
(SURVEY.md §4.1), plus fusion behavior the reference only exercises
implicitly via HOROVOD_FUSION_THRESHOLD.
"""

import numpy as np
import pytest

import horovod_tpu as hvd_mod


def rank_major(fn, dtype=np.float32):
    return np.stack([np.asarray(fn(r), dtype=dtype) for r in range(8)])


def test_allreduce_average(hvd):
    x = rank_major(lambda r: np.full((3, 2), float(r)))
    out = hvd.allreduce(x)
    assert out.shape == (8, 3, 2)
    for r in range(8):
        np.testing.assert_allclose(np.asarray(out[r]), np.full((3, 2), 3.5))


def test_allreduce_sum_int(hvd):
    x = rank_major(lambda r: np.full((4,), r), dtype=np.int32)
    out = hvd.allreduce(x, op=hvd_mod.Sum)
    np.testing.assert_array_equal(np.asarray(out[0]), np.full(4, 28))


def test_allreduce_replicate_helper(hvd):
    out = hvd.allreduce(hvd.replicate(np.ones(5)), op=hvd_mod.Sum)
    np.testing.assert_allclose(np.asarray(hvd.first(out)), np.full(5, 8.0))


def test_allreduce_rejects_non_rank_major(hvd):
    with pytest.raises(ValueError):
        hvd.allreduce(np.ones((3, 5)))


def test_async_handle_poll_and_wait(hvd):
    x = rank_major(lambda r: np.full((2,), float(r + 1)))
    handle = hvd.allreduce_async(x, op=hvd_mod.Sum)
    # flush() resolves pending work; wait() forces it.
    out = hvd.synchronize(handle)
    assert handle.poll()
    np.testing.assert_allclose(np.asarray(out[2]), np.full(2, 36.0))


def test_fusion_batches_multiple_tensors(hvd):
    """Multiple pending allreduces of one dtype flush as one fused dispatch."""
    fusion = hvd_mod.common.basics.state().fusion
    fusion.cycle_time_ms = 1e6  # no time-based flush during this test
    before = fusion.cycles
    tensors = [
        rank_major(lambda r, i=i: np.full((5,), float(r * i))) for i in range(4)
    ]
    handles = [
        hvd.allreduce_async(t, op=hvd_mod.Sum, name=f"t{i}")
        for i, t in enumerate(tensors)
    ]
    outs = [h.wait() for h in handles]
    assert fusion.cycles == before + 1  # one cycle, one fused buffer
    for i, out in enumerate(outs):
        np.testing.assert_allclose(np.asarray(out[0]), np.full(5, 28.0 * i))


def test_cache_capacity_enforced(hvd):
    """HOROVOD_CACHE_CAPACITY semantics (ref: response_cache.cc [V]):
    the executor cache stays <= capacity via LRU eviction, an evicted
    key recompiles as a miss, and hit/miss/eviction counters track it."""
    fusion = hvd_mod.common.basics.state().fusion
    fusion.cache_capacity = 2
    fusion._executors.clear()
    fusion.cache_hits = fusion.cache_misses = fusion.cache_evictions = 0

    def reduce_of_size(n):
        x = rank_major(lambda r: np.full((n,), float(r)))
        return hvd.allreduce(x, op=hvd_mod.Sum)

    reduce_of_size(2)  # miss
    reduce_of_size(3)  # miss
    reduce_of_size(2)  # hit (LRU refresh: 3 is now oldest)
    assert fusion.cache_stats()["size"] == 2
    assert fusion.cache_hits == 1 and fusion.cache_misses == 2

    reduce_of_size(4)  # miss -> evicts size-3 executor
    assert fusion.cache_stats()["size"] == 2
    assert fusion.cache_evictions == 1

    out = reduce_of_size(3)  # miss again: must recompile, still correct
    assert fusion.cache_misses == 4
    np.testing.assert_allclose(np.asarray(out[0]), np.full(3, 28.0))

    # capacity 0 disables caching entirely
    fusion.cache_capacity = 0
    fusion._executors.clear()
    reduce_of_size(5)
    reduce_of_size(5)
    assert fusion.cache_stats()["size"] == 0


def test_cache_capacity_env_plumbed(hvd, monkeypatch):
    """The env var reaches the FusionManager at init."""
    import horovod_tpu as hvd2

    monkeypatch.setenv("HOROVOD_CACHE_CAPACITY", "7")
    hvd2.shutdown()
    hvd2.init()
    try:
        assert hvd_mod.common.basics.state().fusion.cache_capacity == 7
    finally:
        hvd2.shutdown()


def test_fusion_threshold_triggers_flush(hvd):
    fusion = hvd_mod.common.basics.state().fusion
    fusion.threshold_bytes = 64  # tiny: every enqueue flushes
    h = hvd.allreduce_async(rank_major(lambda r: np.ones(16)), op=hvd_mod.Sum)
    assert h.poll()  # already flushed by threshold


def test_grouped_allreduce(hvd):
    xs = [
        rank_major(lambda r: np.full((3,), float(r))),
        rank_major(lambda r: np.full((2, 2), 2.0 * r)),
    ]
    outs = hvd.grouped_allreduce(xs, op=hvd_mod.Average)
    np.testing.assert_allclose(np.asarray(outs[0][0]), np.full(3, 3.5))
    np.testing.assert_allclose(np.asarray(outs[1][0]), np.full((2, 2), 7.0))


def test_allreduce_min_max_product(hvd):
    x = rank_major(lambda r: np.array([float(r)]))
    np.testing.assert_allclose(
        np.asarray(hvd.allreduce(x, op=hvd_mod.Min)[0]), [0.0]
    )
    np.testing.assert_allclose(
        np.asarray(hvd.allreduce(x, op=hvd_mod.Max)[5]), [7.0]
    )
    x2 = rank_major(lambda r: np.array([2.0]))
    np.testing.assert_allclose(
        np.asarray(hvd.allreduce(x2, op=hvd_mod.Product)[0]), [256.0]
    )


def test_allreduce_process_set(hvd):
    ps = hvd.add_process_set([0, 1])
    x = rank_major(lambda r: np.full((2,), float(r + 1)))
    out = hvd.allreduce(x, op=hvd_mod.Sum, process_set=ps)
    np.testing.assert_allclose(np.asarray(out[0]), np.full(2, 3.0))
    np.testing.assert_allclose(np.asarray(out[1]), np.full(2, 3.0))
    # ranks outside the set keep their input
    np.testing.assert_allclose(np.asarray(out[5]), np.full(2, 6.0))


def test_allgather_even(hvd):
    x = rank_major(lambda r: np.full((2, 3), float(r)))
    out = hvd.allgather(x)
    assert out.shape == (8, 8, 2, 3)
    # Horovod semantics: concat along dim0; our rank-major rows hold the
    # stacked per-rank contributions.
    flat = np.asarray(out[4]).reshape(16, 3)
    expected = np.concatenate([np.full((2, 3), float(r)) for r in range(8)])
    np.testing.assert_allclose(flat, expected)


def test_allgather_uneven(hvd):
    rows = [np.full((r + 1, 2), float(r), dtype=np.float32) for r in range(8)]
    out = hvd.allgather(rows)
    total = sum(r + 1 for r in range(8))
    assert out.shape == (8, total, 2)
    expected = np.concatenate(rows)
    np.testing.assert_allclose(np.asarray(out[3]), expected)


def test_broadcast(hvd):
    x = rank_major(lambda r: np.full((4,), float(r)))
    out = hvd.broadcast(x, root_rank=5)
    for r in range(8):
        np.testing.assert_allclose(np.asarray(out[r]), np.full(4, 5.0))


def test_alltoall_even(hvd):
    x = rank_major(lambda r: np.array([r * 10.0 + j for j in range(8)]))
    out = hvd.alltoall(x)
    np.testing.assert_allclose(
        np.asarray(out[2]), [s * 10.0 + 2 for s in range(8)]
    )


def test_alltoall_uneven(hvd):
    # rank r sends j+1 elements to peer j, all valued r.
    rows = [
        np.full((sum(j + 1 for j in range(8)),), float(r), dtype=np.float32)
        for r in range(8)
    ]
    splits = [[j + 1 for j in range(8)] for _ in range(8)]
    outs, recv = hvd.alltoall(rows, splits=splits)
    # peer j receives j+1 elements from each rank → 8*(j+1) total
    assert outs[3].shape == (8 * 4,)
    np.testing.assert_allclose(
        np.asarray(outs[3][:4]), np.zeros(4)
    )  # from rank 0
    assert recv[3] == [4] * 8


def test_reducescatter_even(hvd):
    x = rank_major(lambda r: np.arange(16.0) + r)
    out = hvd.reducescatter(x, op=hvd_mod.Sum)
    reduced = 8 * np.arange(16.0) + 28.0
    assert out.shape == (8, 2)
    np.testing.assert_allclose(np.asarray(out[3]), reduced[6:8])


def test_reducescatter_uneven(hvd):
    x = rank_major(lambda r: np.arange(10.0))
    out = hvd.reducescatter(x, op=hvd_mod.Sum)
    # 10 = 8*1 + 2 → ranks 0,1 get 2 elements, ranks 2..7 get 1.
    reduced = 8 * np.arange(10.0)
    np.testing.assert_allclose(np.asarray(out[0]), reduced[0:2])
    np.testing.assert_allclose(np.asarray(out[2]), reduced[4:5])
    np.testing.assert_allclose(np.asarray(out[7]), reduced[9:10])


def test_join_mask_average(hvd):
    x = rank_major(lambda r: np.full((3,), float(r)))
    with hvd.join_ranks([6, 7]):
        out = hvd.allreduce(x)  # average over ranks 0..5
    np.testing.assert_allclose(np.asarray(out[0]), np.full(3, 2.5))


def test_join_mask_sum(hvd):
    x = rank_major(lambda r: np.full((2,), 1.0))
    with hvd.join_ranks([0]):
        out = hvd.allreduce(x, op=hvd_mod.Sum)
    np.testing.assert_allclose(np.asarray(out[3]), np.full(2, 7.0))


def test_join_barrier_returns_last_joined(hvd):
    assert hvd.join([2, 5]) == 5
    assert hvd.join() == -1


def test_prescale_postscale(hvd):
    x = rank_major(lambda r: np.ones(4))
    out = hvd.allreduce(
        x, op=hvd_mod.Sum, prescale_factor=0.25, postscale_factor=2.0
    )
    np.testing.assert_allclose(np.asarray(out[0]), np.full(4, 4.0))


def test_executor_cache_reuse(hvd):
    fusion = hvd_mod.common.basics.state().fusion
    x = rank_major(lambda r: np.ones(4))
    hvd.allreduce(x, op=hvd_mod.Sum)
    n = len(fusion._executors)
    hvd.allreduce(x * 2, op=hvd_mod.Sum)
    assert len(fusion._executors) == n  # response-cache analog hit


def test_grouped_allreduce_atomic_over_threshold(hvd):
    """A group larger than the fusion threshold must not be split
    mid-group (group_table.cc semantics [V]): begin_group defers the
    threshold flush and all members complete in one cycle."""
    fusion = hvd_mod.common.basics.state().fusion
    fusion.threshold_bytes = 64  # each member alone crosses the threshold
    before = fusion.cycles
    xs = [rank_major(lambda r: np.full((64,), float(r + i))) for i in range(4)]
    outs = hvd.grouped_allreduce(xs, op=hvd_mod.Sum)
    assert fusion.cycles == before + 1  # one cycle for the whole group
    for i, out in enumerate(outs):
        expected = np.full(64, sum(r + i for r in range(8)))
        np.testing.assert_allclose(np.asarray(out[0]), expected)


def test_grouped_allreduce_single_fused_dispatch(hvd):
    """Group members share ONE fused executable even when their total
    size exceeds the threshold (the unit is indivisible in
    _batches_by_threshold)."""
    fusion = hvd_mod.common.basics.state().fusion
    fusion.threshold_bytes = 64
    misses_before = fusion.cache_misses
    xs = [rank_major(lambda r: np.full((64,), 1.0 * r)) for _ in range(3)]
    hvd.grouped_allreduce(xs, op=hvd_mod.Sum)
    # one fused allreduce executor build, not three
    assert fusion.cache_misses == misses_before + 1


def test_grouped_allgather(hvd):
    """Atomic multi-tensor allgather (ref: hvd.grouped_allgather [V])."""
    fusion = hvd_mod.common.basics.state().fusion
    fusion.threshold_bytes = 64
    before = fusion.cycles
    xs = [
        rank_major(lambda r: np.full((2, 3), float(r + i)))
        for i in range(3)
    ]
    outs = hvd.grouped_allgather(xs)
    assert fusion.cycles == before + 1
    for i, out in enumerate(outs):
        got = np.asarray(out[0]).reshape(8, 2, 3)
        for r in range(8):
            np.testing.assert_allclose(got[r], np.full((2, 3), float(r + i)))


def test_grouped_reducescatter(hvd):
    xs = [rank_major(lambda r: np.arange(16.0) + r + i) for i in range(2)]
    outs = hvd.grouped_reducescatter(xs, op=hvd_mod.Sum)
    for i, out in enumerate(outs):
        reduced = 8 * np.arange(16.0) + 28.0 + 8 * i
        np.testing.assert_allclose(np.asarray(out[3]), reduced[6:8])


def test_grouped_allgather_aborts_cleanly_on_bad_member(hvd):
    """A member failing validation mid-group must not leave earlier
    members enqueued (partial 'atomic' group)."""
    fusion = hvd_mod.common.basics.state().fusion
    good = rank_major(lambda r: np.full((2,), float(r)))
    bad = np.zeros((3,))  # wrong leading axis
    with pytest.raises(ValueError, match="rank-major"):
        hvd.grouped_allgather([good, bad])
    assert fusion.pending == []
    assert fusion.pending_bytes == 0
    # the queue still works after the aborted group
    out = hvd.allreduce(good, op=hvd_mod.Sum)
    np.testing.assert_allclose(np.asarray(out[0]), np.full(2, 28.0))


def test_barrier_single_controller(hvd):
    """hvd.barrier() (ref: horovod/common/basics.py barrier [V]):
    returns promptly under a single controller, flushes pending fused
    work first, and accepts a process set."""
    import horovod_tpu as hvd_mod

    h = hvd_mod.allreduce_async(
        hvd_mod.replicate(np.ones(3, np.float32)), op=hvd_mod.Sum
    )
    hvd_mod.barrier()  # must drive/flush the pending cycle
    assert h.poll()
    ps = hvd_mod.add_process_set([0, 1])
    try:
        hvd_mod.barrier(process_set=ps)
    finally:
        hvd_mod.remove_process_set(ps)
