"""MNIST data-parallel training — the canonical first example.

Parity with the reference's ``examples/pytorch/pytorch_mnist.py`` [V]
(BASELINE.json config #1): same 2-layer ConvNet capacity, same flow —
init, shard the data by rank, wrap the optimizer, broadcast initial
state, train, evaluate on rank 0.

TPU-native shape: one jit-compiled train step over the world mesh via
shard_map; the DistributedOptimizer's allreduce is an XLA collective
scheduled by the compiler, not a background thread.

Run (single host, 8-way CPU simulation):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    JAX_PLATFORMS=cpu python examples/mnist.py --epochs 1

Run (TPU): python examples/mnist.py
"""

import argparse
import os
from functools import partial

import jax

# The sandbox's sitecustomize can force-select a TPU platform; honor an
# explicit JAX_PLATFORMS request at the config level (see tests/conftest.py).
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import PartitionSpec as P

import horovod_tpu as hvd
from horovod_tpu.models import MNISTConvNet


def synthetic_mnist(n: int, rng: np.random.Generator):
    """Deterministic stand-in for the MNIST download (this sandbox has
    no network; the reference example downloads via torchvision [V])."""
    x = rng.normal(size=(n, 28, 28, 1)).astype(np.float32)
    y = rng.integers(0, 10, size=(n,)).astype(np.int32)
    # Plant a learnable signal: mean intensity encodes the label.
    x += y[:, None, None, None].astype(np.float32) / 10.0
    return x, y


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--epochs", type=int, default=2)
    parser.add_argument("--batch-size", type=int, default=64,
                        help="per-replica batch size (ref default 64)")
    parser.add_argument("--lr", type=float, default=0.01)
    parser.add_argument("--steps-per-epoch", type=int, default=30)
    args = parser.parse_args()

    hvd.init()
    mesh = hvd.mesh()
    world = hvd.size()

    model = MNISTConvNet()
    # Horovod's LR scaling rule: scale by world size (ref docs [V]).
    opt = hvd.DistributedOptimizer(
        optax.sgd(args.lr * world, momentum=0.9), op=hvd.Average
    )

    rng = np.random.default_rng(hvd.rank())
    sample_x = jnp.zeros((args.batch_size, 28, 28, 1), jnp.float32)
    params = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        sample_x,
    )
    opt_state = opt.init(params)
    # Every replica starts from identical weights (ref:
    # hvd.broadcast_parameters / broadcast_optimizer_state [V]).
    params = hvd.broadcast_parameters(params, root_rank=0)
    opt_state = hvd.broadcast_optimizer_state(opt_state, root_rank=0)

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(), P(), P(hvd.WORLD_AXIS), P(hvd.WORLD_AXIS), P()),
        out_specs=(P(), P(), P()),
        check_vma=False,
    )
    def train_step(params, opt_state, x, y, dropout_key):
        x, y = x[0], y[0]  # this replica's shard

        def loss_fn(p):
            logits = model.apply(
                p, x, train=True, rngs={"dropout": dropout_key}
            )
            one_hot = jax.nn.one_hot(y, 10)
            return optax.softmax_cross_entropy(logits, one_hot).mean()

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        # loss is per-replica; average it for logging
        loss = jax.lax.pmean(loss, hvd.WORLD_AXIS)
        return params, opt_state, loss

    step = jax.jit(train_step)
    for epoch in range(args.epochs):
        for it in range(args.steps_per_epoch):
            xs, ys = [], []
            for _ in range(world):
                x, y = synthetic_mnist(args.batch_size, rng)
                xs.append(x)
                ys.append(y)
            params, opt_state, loss = step(
                params,
                opt_state,
                jnp.asarray(np.stack(xs)),
                jnp.asarray(np.stack(ys)),
                jax.random.fold_in(
                    jax.random.PRNGKey(2), epoch * 10_000 + it
                ),
            )
        if hvd.rank() == 0:
            print(f"epoch {epoch}: loss {float(loss):.4f}")

    if hvd.rank() == 0:
        x, y = synthetic_mnist(256, np.random.default_rng(999))
        logits = jax.jit(lambda p, x: model.apply(p, x, train=False))(
            params, jnp.asarray(x)
        )
        acc = float((np.argmax(np.asarray(logits), -1) == y).mean())
        print(f"eval accuracy {acc:.3f}")


if __name__ == "__main__":
    main()
