"""KerasEstimator (ref: horovod/spark/keras/estimator.py [V]):
declare-fit-predict with Store checkpointing on the TF shim."""

import os

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from horovod_tpu.spark import LocalStore  # noqa: E402
from horovod_tpu.spark.keras import (  # noqa: E402
    KerasEstimator,
    KerasModelWrapper,
)


def _model():
    return tf.keras.Sequential(
        [tf.keras.layers.Dense(8, activation="relu", input_shape=(3,)),
         tf.keras.layers.Dense(1)]
    )


def test_keras_estimator_fit_predict_checkpoint(hvd, tmp_path):
    rng = np.random.default_rng(0)
    x = rng.normal(size=(128, 3)).astype(np.float32)
    y = x.sum(axis=1, keepdims=True).astype(np.float32)
    est = KerasEstimator(
        model=_model(),
        optimizer=tf.keras.optimizers.Adam(0.05),
        loss="mse",
        store=LocalStore(str(tmp_path / "store")),
        run_id="k1",
        epochs=3,
        batch_size=32,
    )
    wrapper = est.fit(x, y)
    losses = est.history.history["loss"]
    assert losses[-1] < losses[0]
    preds = wrapper.predict(x[:4])
    assert preds.shape == (4, 1)
    ckpts = os.listdir(est.store.checkpoint_dir("k1"))
    assert any(c.endswith(".weights.h5") for c in ckpts)

    path = str(tmp_path / "served.keras")
    wrapper.save(path)
    loaded = KerasModelWrapper.load(path)
    np.testing.assert_allclose(
        loaded.predict(x[:4]), preds, rtol=1e-5, atol=1e-6
    )


def test_served_artifact_loads_with_hvd_load_model(hvd, tmp_path):
    """The serving path for compiled-with-DistributedOptimizer saves is
    hvd.load_model — it injects the Distributed* reconstruction
    factories exactly like the reference's keras load_model [V], and
    the result can resume distributed training (optimizer re-wrapped)."""
    import horovod_tpu.tensorflow as hvd_tf

    x = np.random.default_rng(1).normal(size=(32, 3)).astype(np.float32)
    y = x.sum(axis=1, keepdims=True).astype(np.float32)
    est = KerasEstimator(model=_model(), loss="mse", epochs=1,
                         batch_size=16)
    wrapper = est.fit(x, y)
    path = str(tmp_path / "plain.keras")
    wrapper.save(path)
    served = hvd_tf.load_model(path)  # compile=True: optimizer rebuilt
    assert type(served.optimizer).__name__.startswith("Distributed")
    preds = served.predict(x[:4], verbose=0)
    np.testing.assert_allclose(preds, wrapper.predict(x[:4]), rtol=1e-5,
                               atol=1e-6)
    # and it can keep TRAINING distributed after reload
    served.fit(x, y, epochs=1, batch_size=16, verbose=0)
