"""Collective operations: traced (inside jit/shard_map) and eager (dispatch
+ fusion) flavors. TPU-native replacement for horovod/common/ops/ [V]."""

from .reduction_ops import (  # noqa: F401
    Average,
    Sum,
    Adasum,
    Min,
    Max,
    Product,
    ReduceOp,
)
