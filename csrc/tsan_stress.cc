// ThreadSanitizer stress driver for the concurrent pieces of the native
// runtime: the KV rendezvous server (per-connection threads behind a
// mutex) and the timeline ring buffer (producer threads vs drain).
//
// Role parity: the reference gates its C++ core behind sanitizer CI
// lanes (SURVEY.md §5.2); this binary IS that lane for csrc/ — built
// with -fsanitize=thread by ci.sh and run to completion. Any data race
// TSAN finds is a non-zero exit.
//
// Build (see ci.sh):
//   g++ -std=c++17 -g -O1 -fsanitize=thread -pthread \
//       timeline.cc kvstore.cc sha256.cc tsan_stress.cc -o tsan_stress

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void* hvd_kv_start(int port, const uint8_t* secret, long secret_len,
                   int* out_port);
int hvd_kv_port(void* h);
void hvd_kv_stop(void* h);
void hvd_kv_put(void* h, const char* scope, const char* key,
                const uint8_t* val, long len);
long hvd_kv_get(void* h, const char* scope, const char* key, uint8_t* buf,
                long cap);
long hvd_kv_keys(void* h, const char* scope, uint8_t* buf, long cap);
void hvd_kv_drop_scope(void* h, const char* scope);

void* hvd_tl_create();
void hvd_tl_destroy(void* h);
void hvd_tl_emit(void* h, const char* json);
long hvd_tl_count(void* h);
long hvd_tl_drain_size(void* h);
long hvd_tl_drain(void* h, char* dst, long cap);
}

namespace {

constexpr int kThreads = 8;
constexpr int kOpsPerThread = 200;

void kv_worker(void* server, int tid, std::atomic<int>* errors) {
  char key[64];
  char scope[32];
  uint8_t buf[256];
  for (int i = 0; i < kOpsPerThread; ++i) {
    std::snprintf(scope, sizeof(scope), "scope%d", i % 3);
    std::snprintf(key, sizeof(key), "t%d.k%d", tid, i);
    std::string val = "value-" + std::to_string(tid * 1000 + i);
    hvd_kv_put(server, scope, key,
               reinterpret_cast<const uint8_t*>(val.data()),
               static_cast<long>(val.size()));
    long n = hvd_kv_get(server, scope, key, buf, sizeof(buf));
    if (n != static_cast<long>(val.size()) ||
        std::memcmp(buf, val.data(), val.size()) != 0) {
      errors->fetch_add(1);
    }
    if (i % 17 == 0) {
      hvd_kv_keys(server, scope, buf, sizeof(buf));
    }
    // Exercise drop_scope against concurrent put/get/keys — but on a
    // scope whose values nobody verifies: dropping scope0-2 mid-flight
    // would make another thread's put/get check fail by DESIGN (the
    // drop legally races the pair), which is a driver bug, not a
    // kvstore race (observed as a rare "value mismatches: 1").
    if (i % 13 == 0) {
      std::snprintf(key, sizeof(key), "s%d.k%d", tid, i);
      hvd_kv_put(server, "scratch", key,
                 reinterpret_cast<const uint8_t*>(val.data()),
                 static_cast<long>(val.size()));
      // UNVERIFIED reads on the droppable scope: keeps TSAN coverage
      // of get()/keys() racing drop_scope() without a value check
      // that the race legally breaks.
      hvd_kv_get(server, "scratch", key, buf, sizeof(buf));
      hvd_kv_keys(server, "scratch", buf, sizeof(buf));
    }
    if (i % 61 == 60) {
      hvd_kv_drop_scope(server, "scratch");
    }
  }
}

void tl_producer(void* tl, int tid) {
  char ev[128];
  for (int i = 0; i < kOpsPerThread; ++i) {
    std::snprintf(ev, sizeof(ev),
                  "{\"name\":\"op%d.%d\",\"ph\":\"X\",\"ts\":%d}", tid, i, i);
    hvd_tl_emit(tl, ev);
  }
}

void tl_drainer(void* tl, std::atomic<bool>* stop) {
  std::vector<char> buf(1 << 16);
  while (!stop->load()) {
    long need = hvd_tl_drain_size(tl);
    if (need > 0 && need <= static_cast<long>(buf.size())) {
      hvd_tl_drain(tl, buf.data(), static_cast<long>(buf.size()));
    }
    std::this_thread::yield();
  }
  hvd_tl_drain(tl, buf.data(), static_cast<long>(buf.size()));
}

}  // namespace

int main() {
  std::atomic<int> errors{0};

  // --- KV server: concurrent put/get/keys/drop through the same mutex
  // the socket handler threads use.
  int port = 0;
  const uint8_t secret[] = "tsan-secret";
  void* server = hvd_kv_start(0, secret, sizeof(secret) - 1, &port);
  if (server == nullptr) {
    std::fprintf(stderr, "kv server failed to start\n");
    return 2;
  }
  {
    std::vector<std::thread> ts;
    ts.reserve(kThreads);
    for (int t = 0; t < kThreads; ++t) {
      ts.emplace_back(kv_worker, server, t, &errors);
    }
    for (auto& t : ts) t.join();
  }
  hvd_kv_stop(server);

  // --- Timeline ring buffer: producers racing a drainer.
  void* tl = hvd_tl_create();
  {
    std::atomic<bool> stop{false};
    std::thread drainer(tl_drainer, tl, &stop);
    std::vector<std::thread> ts;
    for (int t = 0; t < kThreads; ++t) {
      ts.emplace_back(tl_producer, tl, t);
    }
    for (auto& t : ts) t.join();
    stop.store(true);
    drainer.join();
  }
  hvd_tl_destroy(tl);

  if (errors.load() != 0) {
    std::fprintf(stderr, "value mismatches: %d\n", errors.load());
    return 1;
  }
  std::puts("tsan_stress: ok");
  return 0;
}
