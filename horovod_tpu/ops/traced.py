"""Traced-mode collectives: the TPU fast path.

These functions are called *inside* ``jit`` / ``shard_map`` over a mesh axis
(default ``'hvd'``). XLA sees the collective, fuses and schedules it, and
overlaps it with compute — statically doing what the reference's background
negotiate-fuse-execute machine (horovod/common/operations.cc RunLoopOnce +
horovod/common/ops/nccl_operations.cc [V], SURVEY.md §3.2) does dynamically.
There is deliberately no fusion buffer here: XLA's combiner pass is the
fusion buffer.

Process-set restriction (ref: per-set communicators in
horovod/common/process_set.cc [V]) is implemented with *masked full-axis
collectives* and static ``ppermute`` routes, NOT ``axis_index_groups``:
XLA's TPU lowering requires every replica group to have the same size,
and a set-plus-singletons partition can never satisfy that. Masking has
no such constraint, lowers on every backend, and costs one full-axis
collective (ICI-cheap) instead of a sub-group one. Ranks outside the
set contribute the reduction identity and get their own input back —
the closest SPMD analog of "non-members don't call the op".
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..common.topology import WORLD_AXIS
from ..common.process_sets import ProcessSet
from .reduction_ops import Average, Sum, Adasum, Min, Max, Product, resolve_op

# The stall inspector used to run only on EAGER fusion cycles, so a
# purely-traced job (the TPU fast path) could stall silently: leaked
# eager handles aged unobserved and stale worker heartbeats never got
# re-checked. Traced collectives have no background loop to hook, but
# their Python entry points ARE the dispatch path (they run at trace /
# retrace time on the host), and the telemetry hub re-checks at every
# step close (common/telemetry.py) for steady state. Rate-limited so a
# per-leaf optimizer trace doesn't pay a check per gradient tensor.
_STALL_CHECK_INTERVAL_S = 0.5
_last_stall_check = [0.0]


def _stall_check() -> None:
    import time as _time

    now = _time.monotonic()
    if now - _last_stall_check[0] < _STALL_CHECK_INTERVAL_S:
        return
    _last_stall_check[0] = now
    from ..common import basics as _basics

    insp = _basics.state().stall_inspector
    if insp is not None:
        insp.check()  # may raise the shutdown escalation — intended


class _SetInfo(NamedTuple):
    """Static per-world lookup tables for a proper-subset process set."""

    mask: np.ndarray  # [world] bool — rank is a member
    pos: np.ndarray  # [world] int32 — rank's index within the set (0 outside)
    size: int
    ranks: Tuple[int, ...]


def _set_info(
    process_set: Optional[ProcessSet], axis_name
) -> Optional[_SetInfo]:
    """None for the global set (or a set covering the whole axis)."""
    if process_set is None or process_set.process_set_id == 0:
        return None
    world = int(lax.axis_size(axis_name))
    if process_set.size == world:
        return None
    mask = np.zeros(world, dtype=bool)
    pos = np.zeros(world, dtype=np.int32)
    for i, r in enumerate(process_set.ranks):
        mask[r] = True
        pos[r] = i
    return _SetInfo(mask, pos, process_set.size, tuple(process_set.ranks))


def _member(info: _SetInfo, axis_name):
    idx = lax.axis_index(axis_name)
    return jnp.asarray(info.mask)[idx], jnp.asarray(info.pos)[idx]


def _masked_gather(tensor, info: _SetInfo, axis_name, member, pos):
    """All-gather over the set's members only: each member drops its
    tensor into its set-slot of a [k·d, ...] buffer, a full-axis psum
    assembles them (outsiders contribute zeros). Every rank — member or
    not — ends up holding the set's gather."""
    d = tensor.shape[0]
    contrib = jnp.where(member, tensor, jnp.zeros_like(tensor))
    buf = jnp.zeros(
        (info.size * d,) + tuple(tensor.shape[1:]), tensor.dtype
    )
    buf = lax.dynamic_update_slice_in_dim(buf, contrib, pos * d, axis=0)
    return lax.psum(buf, axis_name)


def rank(axis_name: str = WORLD_AXIS):
    """Per-chip rank inside a traced region (= hvd.rank() of the owning
    rank in the reference's per-process model)."""
    return lax.axis_index(axis_name)


def size(axis_name: str = WORLD_AXIS) -> int:
    return lax.axis_size(axis_name)


def allreduce(
    tensor,
    average: Optional[bool] = None,
    op=None,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    process_set: Optional[ProcessSet] = None,
    axis_name: str = WORLD_AXIS,
    mask=None,
    groups=None,
):
    """Allreduce across the mesh axis (ref: hvd.allreduce,
    horovod/torch/mpi_ops.py + MPI/NCCL Allreduce ops [V]).

    pre/postscale mirror HOROVOD's prescale_factor/postscale_factor —
    applied before/after the reduction, fused into the XLA program (the
    reference needs a dedicated ScaleBuffer CUDA kernel; XLA fuses the
    multiply for free, SURVEY.md §2.2 GPU context row).

    With a process set, members reduce among themselves (masked
    full-axis collective — see module docstring) and non-members return
    their input unchanged.

    ``mask`` is the traced join mask (ref: hvd.join / JoinOp [V] —
    the eager layer's `join_ranks` semantics inside a jitted step): a
    [world] bool vector, static numpy or traced, where ``mask[r] ==
    False`` means rank r ran out of data. Masked-out ranks contribute
    the reduction identity, ``Average`` divides by the LIVE count (a
    traced scalar — the mask may change step to step without a
    retrace), and every participating rank receives the live
    reduction. Sum/Average only (a dynamic live-count has no analog
    for min/max/product); composes with a process set by intersection.

    ``groups`` restricts the reduction to ``axis_index_groups`` of the
    flat axis (uniform group sizes — the intra-slice groups of
    ``topology.hierarchy_stages()``): each group reduces among its own
    members and ``Average`` divides by the GROUP size. This is the
    local-SGD local-phase wire (every gradient byte stays on ICI);
    Sum/Average only, and it composes with neither process sets nor
    join masks (a masked subgroup has no uniform replica-group shape).
    """
    _stall_check()
    op = resolve_op(op, average)
    if mask is not None and op not in (Average, Sum):
        raise ValueError(
            "allreduce(mask=) supports op=Sum/Average only"
        )
    if groups is not None:
        if op not in (Average, Sum):
            raise ValueError(
                "allreduce(groups=) supports op=Sum/Average only"
            )
        if mask is not None or (
            process_set is not None and process_set.process_set_id != 0
        ):
            raise NotImplementedError(
                "allreduce(groups=) composes with neither process "
                "sets nor join masks"
            )
        if prescale_factor != 1.0:
            tensor = tensor * jnp.asarray(
                prescale_factor, dtype=tensor.dtype
            )
        out = lax.psum(
            tensor, axis_name, axis_index_groups=[list(g) for g in groups]
        )
        if op == Average:
            out = out / jnp.asarray(len(groups[0]), out.dtype)
        if postscale_factor != 1.0:
            out = out * jnp.asarray(postscale_factor, dtype=out.dtype)
        return out
    info = _set_info(process_set, axis_name)
    n = info.size if info is not None else lax.axis_size(axis_name)
    raw = tensor

    if op == Adasum:
        from .adasum import adasum_allreduce

        if prescale_factor != 1.0:
            tensor = tensor * jnp.asarray(prescale_factor, tensor.dtype)
        if info is not None:
            member, pos = _member(info, axis_name)
            stacked = _masked_gather(
                tensor[None], info, axis_name, member, pos
            )
            from .adasum import _tree_combine

            out = _tree_combine([stacked[i] for i in range(info.size)])
        else:
            out = adasum_allreduce(tensor, axis_name=axis_name)
        if postscale_factor != 1.0:
            out = out * jnp.asarray(postscale_factor, out.dtype)
        if info is not None:
            out = jnp.where(member, out, raw)
        return out

    if prescale_factor != 1.0:
        tensor = tensor * jnp.asarray(prescale_factor, dtype=tensor.dtype)
    member = None
    if info is not None:
        member, _ = _member(info, axis_name)
    live = None
    if mask is not None:
        live = jnp.asarray(mask)[lax.axis_index(axis_name)]
    if op in (Average, Sum):
        gate = member
        if live is not None:
            gate = live if gate is None else jnp.logical_and(gate, live)
        contrib = (
            tensor
            if gate is None
            else jnp.where(gate, tensor, jnp.zeros_like(tensor))
        )
        out = lax.psum(contrib, axis_name)
        if op == Average:
            if live is None:
                out = out / jnp.asarray(n, dtype=out.dtype)
            else:
                # live count is traced: the join mask may differ step
                # to step without forcing a retrace
                n_live = lax.psum(
                    jnp.where(gate, 1.0, 0.0).astype(out.dtype), axis_name
                )
                out = out / jnp.maximum(
                    n_live, jnp.ones((), out.dtype)
                )
    elif op == Min:
        contrib = (
            tensor
            if member is None
            else jnp.where(
                member, tensor, jnp.full_like(tensor, _identity(tensor, Min))
            )
        )
        out = lax.pmin(contrib, axis_name)
    elif op == Max:
        contrib = (
            tensor
            if member is None
            else jnp.where(
                member, tensor, jnp.full_like(tensor, _identity(tensor, Max))
            )
        )
        out = lax.pmax(contrib, axis_name)
    elif op == Product:
        contrib = (
            tensor
            if member is None
            else jnp.where(member, tensor, jnp.ones_like(tensor))
        )
        gathered = lax.all_gather(contrib, axis_name)
        out = jnp.prod(gathered, axis=0)
    else:
        raise ValueError(f"unsupported reduce op {op}")
    if postscale_factor != 1.0:
        out = out * jnp.asarray(postscale_factor, dtype=out.dtype)
    if member is not None:
        out = jnp.where(member, out, raw)
    return out


def _identity(tensor, op):
    """Reduction identity for masking non-members out of pmin/pmax."""
    if jnp.issubdtype(tensor.dtype, jnp.floating):
        fin = jnp.finfo(tensor.dtype)
        return fin.max if op == Min else fin.min
    iin = jnp.iinfo(tensor.dtype)
    return iin.max if op == Min else iin.min


# ------------------------------------------------- non-finite sentinel


def finite_scalar(x):
    """One in-JIT boolean: ``all(isfinite(x))`` — the per-bucket guard
    reduction (common/guard.py). Non-float payloads are finite by
    construction, so the flag folds to a constant and costs nothing.

    Applied to ALREADY-REDUCED values the flag needs no collective: a
    psum/all-gather output is replicated, so every rank computes the
    identical bit and a ``lax.cond`` on it stays uniform across the
    gang (the SPMD-safety requirement for skip-step semantics)."""
    if not jnp.issubdtype(jnp.result_type(x), jnp.floating):
        return jnp.asarray(True)
    return jnp.all(jnp.isfinite(x))


def tree_finite(tree):
    """``finite_scalar`` over a pytree, combined with logical AND —
    one scalar reduction per leaf, one boolean out. Empty trees are
    finite."""
    flags = [
        finite_scalar(leaf)
        for leaf in jax.tree_util.tree_leaves(tree)
        if jnp.issubdtype(jnp.result_type(leaf), jnp.floating)
    ]
    if not flags:
        return jnp.asarray(True)
    out = flags[0]
    for f in flags[1:]:
        out = jnp.logical_and(out, f)
    return out


def grouped_allreduce(
    tensors,
    average: Optional[bool] = None,
    op=None,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    process_set: Optional[ProcessSet] = None,
    axis_name: str = WORLD_AXIS,
):
    """Reduce a list of tensors as one logical op (ref: hvd.grouped_allreduce
    / group_table.cc [V]). In traced mode the group contract — all members
    reduced atomically in one fused collective — is expressed by a single
    psum over the tuple; XLA emits one fused all-reduce."""
    _stall_check()
    op = resolve_op(op, average)
    info = _set_info(process_set, axis_name)
    n = info.size if info is not None else lax.axis_size(axis_name)
    if op == Adasum:
        return [
            allreduce(
                t,
                op=Adasum,
                prescale_factor=prescale_factor,
                postscale_factor=postscale_factor,
                process_set=process_set,
                axis_name=axis_name,
            )
            for t in tensors
        ]
    raws = list(tensors)
    if prescale_factor != 1.0:
        tensors = [t * jnp.asarray(prescale_factor, t.dtype) for t in tensors]
    member = None
    if info is not None:
        member, _ = _member(info, axis_name)
    if op in (Average, Sum):
        contribs = tuple(
            t if member is None else jnp.where(member, t, jnp.zeros_like(t))
            for t in tensors
        )
        outs = lax.psum(contribs, axis_name)
        if op == Average:
            outs = tuple(o / jnp.asarray(n, o.dtype) for o in outs)
    elif op == Min:
        contribs = tuple(
            t
            if member is None
            else jnp.where(member, t, jnp.full_like(t, _identity(t, Min)))
            for t in tensors
        )
        outs = lax.pmin(contribs, axis_name)
    elif op == Max:
        contribs = tuple(
            t
            if member is None
            else jnp.where(member, t, jnp.full_like(t, _identity(t, Max)))
            for t in tensors
        )
        outs = lax.pmax(contribs, axis_name)
    else:
        raise ValueError(f"unsupported grouped reduce op {op}")
    outs = list(outs)
    if postscale_factor != 1.0:
        outs = [o * jnp.asarray(postscale_factor, o.dtype) for o in outs]
    if member is not None:
        outs = [jnp.where(member, o, r) for o, r in zip(outs, raws)]
    return outs


def allgather(
    tensor,
    process_set: Optional[ProcessSet] = None,
    axis_name: str = WORLD_AXIS,
):
    """Concatenate each rank's tensor along axis 0 (ref: hvd.allgather /
    MPI_Allgatherv path [V]). Traced mode requires equal shapes (static
    shapes under jit); the eager path supports uneven dim0 via padding.

    With a process set, the result is the concatenation of the members'
    tensors in set order — every rank (members and outsiders alike)
    receives it; outsiders contribute nothing."""
    _stall_check()
    info = _set_info(process_set, axis_name)
    if info is None:
        return lax.all_gather(tensor, axis_name, axis=0, tiled=True)
    member, pos = _member(info, axis_name)
    return _masked_gather(tensor, info, axis_name, member, pos)


def broadcast(
    tensor,
    root_rank: int,
    process_set: Optional[ProcessSet] = None,
    axis_name: str = WORLD_AXIS,
):
    """Every rank receives root_rank's value (ref: hvd.broadcast /
    NCCLBroadcast [V]). Implemented as a masked psum — XLA lowers this to a
    broadcast-from-source collective on ICI. With a process set, members
    receive the root's value and outsiders keep their own input."""
    _stall_check()
    info = _set_info(process_set, axis_name)
    idx = lax.axis_index(axis_name)
    contribution = jnp.where(idx == root_rank, tensor, jnp.zeros_like(tensor))
    out = lax.psum(contribution, axis_name)
    if info is not None:
        member, _ = _member(info, axis_name)
        out = jnp.where(member, out, tensor)
    return out


def alltoall(
    tensor,
    process_set: Optional[ProcessSet] = None,
    axis_name: str = WORLD_AXIS,
):
    """Scatter dim-0 blocks to peers, gather their blocks (ref: hvd.alltoall
    / MPI_Alltoallv [V]). Traced mode is the equal-splits case (dim0 %
    participant count == 0); uneven splits are an eager-mode feature.

    With a process set, routing runs over static ``ppermute`` rings among
    the members only — k-1 hops of one block each, the wire-optimal
    (k-1)/k·P, with no replica-group size constraint. Non-members return
    their input unchanged."""
    _stall_check()
    info = _set_info(process_set, axis_name)
    if info is None:
        return lax.all_to_all(
            tensor, axis_name, split_axis=0, concat_axis=0, tiled=True
        )
    k = info.size
    if tensor.shape[0] % k:
        raise ValueError(
            f"alltoall over a {k}-rank process set needs dim0 divisible "
            f"by {k}, got {tensor.shape[0]}"
        )
    d = tensor.shape[0] // k
    member, pos = _member(info, axis_name)
    # Block p stays home: each member keeps its own pos-th block in place.
    own = lax.dynamic_slice_in_dim(tensor, pos * d, d, axis=0)
    out = jnp.zeros_like(tensor)
    out = lax.dynamic_update_slice_in_dim(out, own, pos * d, axis=0)
    for s in range(1, k):
        # Rotation s: the member at set-position q sends its block
        # (q+s)%k to the member at set-position (q+s)%k; equivalently we
        # receive, from position (pos-s)%k, that member's block `pos`.
        perm = [(info.ranks[q], info.ranks[(q + s) % k]) for q in range(k)]
        send_at = ((pos + s) % k) * d
        send = lax.dynamic_slice_in_dim(tensor, send_at, d, axis=0)
        recv = lax.ppermute(send, axis_name, perm)
        recv_slot = ((pos - s) % k) * d
        out = lax.dynamic_update_slice_in_dim(out, recv, recv_slot, axis=0)
    return jnp.where(member, out, tensor)


def reducescatter(
    tensor,
    op=None,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    process_set: Optional[ProcessSet] = None,
    axis_name: str = WORLD_AXIS,
):
    """Reduce then scatter dim-0 shards (ref: hvd.reducescatter, upstream
    v0.27+ [V]). Maps directly onto the ICI-optimal psum_scatter.

    With a process set, members psum the masked tensor over the full
    axis and slice their set-position's shard (outsiders contribute
    zeros and get the set-position-0 shard — their output, like the
    reference's, is meaningless; its shape must still be uniform under
    SPMD)."""
    _stall_check()
    op = resolve_op(op, None)
    info = _set_info(process_set, axis_name)
    if prescale_factor != 1.0:
        tensor = tensor * jnp.asarray(prescale_factor, tensor.dtype)
    if info is None:
        n = lax.axis_size(axis_name)
        out = lax.psum_scatter(
            tensor, axis_name, scatter_dimension=0, tiled=True
        )
    else:
        k = info.size
        if tensor.shape[0] % k:
            raise ValueError(
                f"reducescatter over a {k}-rank process set needs dim0 "
                f"divisible by {k}, got {tensor.shape[0]}"
            )
        n = k
        member, pos = _member(info, axis_name)
        contrib = jnp.where(member, tensor, jnp.zeros_like(tensor))
        total = lax.psum(contrib, axis_name)
        d = tensor.shape[0] // k
        out = lax.dynamic_slice_in_dim(total, pos * d, d, axis=0)
    if op == Average:
        out = out / jnp.asarray(n, out.dtype)
    if postscale_factor != 1.0:
        out = out * jnp.asarray(postscale_factor, out.dtype)
    return out


def _stochastic_round_rows(x2d, key):
    """Per-row int8 quantization with stochastic rounding (unbiased):
    row-wise absmax scale, floor + bernoulli(frac) up. Plain jnp — XLA
    fuses it into one pass; the per-tensor Pallas kernel
    (pallas_kernels.int8_quantize) covers the single-scale case."""
    absmax = jnp.max(jnp.abs(x2d), axis=1)
    scale = jnp.maximum(absmax, 1e-30) / 127.0
    scaled = x2d / scale[:, None]
    floor = jnp.floor(scaled)
    frac = scaled - floor
    u = jax.random.uniform(key, x2d.shape)
    q = jnp.clip(floor + (u < frac), -128, 127).astype(jnp.int8)
    return q, scale


def _stochastic_round_blocks(x2d, block: int, key):
    """Block-scaled variant of :func:`_stochastic_round_rows`: one
    absmax scale per ``block`` elements within each row, so
    mixed-magnitude regions of a fused buffer never share a dynamic
    range (the block-scaled wire of pallas_kernels.int8_block_quantize,
    expressed as plain jnp for use inside traced programs where XLA
    fuses it into the collective's producer).

    Returns ``(q, scales)`` with ``q`` int8 ``[rows, nb, block]``
    (tail block zero-padded — zeros quantize to zeros and never raise
    a block's absmax, so padding is excluded from the scales by
    construction) and ``scales`` float32 ``[rows, nb]``.
    """
    rows, cols = x2d.shape
    nb = -(-cols // block)
    pad = nb * block - cols
    xb = (
        jnp.pad(x2d, ((0, 0), (0, pad))) if pad else x2d
    ).reshape(rows, nb, block)
    absmax = jnp.max(jnp.abs(xb), axis=2)
    scales = jnp.maximum(absmax, 1e-30) / 127.0
    scaled = xb / scales[:, :, None]
    floor = jnp.floor(scaled)
    frac = scaled - floor
    u = jax.random.uniform(key, scaled.shape)
    q = jnp.clip(floor + (u < frac), -128, 127).astype(jnp.int8)
    return q, scales


def _block_dequant(q, scales):
    """``[rows, nb, block]`` int8 × ``[rows, nb]`` scales → float32
    ``[rows, nb*block]``."""
    rows, nb, block = q.shape
    return (q.astype(jnp.float32) * scales[:, :, None]).reshape(
        rows, nb * block
    )


def quantized_allreduce(
    tensor,
    op=None,
    axis_name: str = WORLD_AXIS,
    seed=0,
    return_residual: bool = False,
    prescale_factor: float = 1.0,
    block_size: Optional[int] = None,
    groups=None,
):
    """Allreduce moving int8 across ICI — the quantized-collective
    recipe of EQuARX (PAPERS.md), built from primitives the reference
    stops short of (its wire compression ends at fp16 [V]).

    Shape: quantized reduce-scatter (all_to_all of per-chunk int8 +
    scales, dequantize-sum locally) then quantized all_gather of the
    reduced shards. Per-device wire bytes ≈ 2·(n-1)/n · P/4 versus
    2·(n-1)/n · P for an fp32 ring allreduce — a true ~4x at every
    world size, with O(P) peak memory (the naive gather-everything
    formulation would move MORE than fp32 psum beyond n=8 and
    materialize an n·P fp32 intermediate).

    Two quantization stages ⇒ error ~2 quanta worst case; stochastic
    rounding (seeded per rank and, when the caller threads a step
    counter in via ``seed``, per step) keeps it unbiased over time.
    Sum/Average only: quantization commutes with neither min/max nor
    product.

    ``return_residual=True`` additionally returns this rank's stage-1
    quantization error (``local − dequant(quant(local))``, same shape
    as ``tensor``) — the carry for error-feedback compression
    (DistributedOptimizer(error_feedback=True)): adding it to the NEXT
    step's gradient keeps the cumulative transmitted signal within a
    constant number of quanta of the true sum instead of a random walk.

    ``prescale_factor`` is FOLDED INTO the stage-1 wire scales rather
    than multiplied through the tensor: quantization is scale-invariant
    (``q = round(x/absmax(x)·127)`` is unchanged by ``x → c·x`` for
    ``c > 0``), so scaling the per-chunk wire scale — n floats — after
    the fact is bit-identical to pre-multiplying the payload, minus one
    full HBM read-write pass over the tensor. The residual stays in
    INPUT (unscaled) units: add it to the next step's raw tensor.

    ``block_size`` switches both stages to block-wise scales (one per
    ``block_size`` elements within each chunk — the wire format of
    ``Compression.int8_block`` and the fused path), so mixed-magnitude
    regions never share a dynamic range; ``None`` keeps the per-chunk
    scale of ``Compression.int8``. The block branch intentionally
    mirrors ``fusion.FusionManager._core_allreduce_q`` (same numeric
    contracts, minus its mask/pset/hier machinery) — a residual-
    contract change must land in both; the fused-vs-unfused parity
    tests are the tripwire.
    """
    _stall_check()
    from .pallas_kernels import int8_quantize

    op = resolve_op(op, None)
    if op not in (Average, Sum):
        raise ValueError("quantized_allreduce supports Sum/Average only")
    if groups is not None:
        # group-limited wire (the local-SGD local phase: int8 that
        # never leaves the slice): the two-stage grouped recipe with
        # the SAME residual contracts as the flat path below —
        # prescale folded into the wire scales, Average's stage-2
        # error surfaced ×n, carry in input units
        gn = len(groups[0])
        shape, dtype = tensor.shape, tensor.dtype
        flat = tensor.reshape(-1).astype(jnp.float32)
        if prescale_factor != 1.0:
            # the grouped core has no scale-fold hook; at group sizes
            # the pre-multiply is one fused producer op, not a
            # separate HBM pass worth optimizing around
            flat = flat * jnp.asarray(prescale_factor, jnp.float32)
        gidx = lax.axis_index(axis_name)
        gkey = jax.random.fold_in(jax.random.PRNGKey(seed), gidx)
        gblock = int(block_size) if block_size else max(
            -(-flat.shape[0] // gn), 1
        )
        out, res = _quantized_sum_groups(
            flat, axis_name, [list(g) for g in groups], gn, gblock,
            gkey, want_residual=return_residual,
        )
        if op == Average:
            out = out / jnp.asarray(gn, out.dtype)
        out = out.reshape(shape).astype(dtype)
        if not return_residual:
            return out
        if prescale_factor == 0.0:
            return out, jnp.zeros(shape, dtype)
        if prescale_factor != 1.0:
            res = res / jnp.asarray(prescale_factor, res.dtype)
        return out, res.reshape(shape).astype(dtype)
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    shape, dtype = tensor.shape, tensor.dtype
    flat = tensor.reshape(-1).astype(jnp.float32)
    m = flat.shape[0]
    chunk = -(-m // n)  # ceil
    flat = jnp.pad(flat, (0, chunk * n - m))
    chunks = flat.reshape(n, chunk)  # row j is destined for rank j

    key = jax.random.fold_in(jax.random.PRNGKey(seed), idx)
    prescale = jnp.asarray(prescale_factor, jnp.float32)
    if block_size:
        q, scales = _stochastic_round_blocks(chunks, block_size, key)
        wire_scales = scales * prescale if prescale_factor != 1.0 else scales
        # all_to_all = the scatter half of reduce-scatter: afterwards
        # row r holds the chunk rank r quantized for us, with its scales
        recv = lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0,
                              tiled=True)              # [n, nb, block]
        recv_scales = lax.all_to_all(
            wire_scales, axis_name, split_axis=0, concat_axis=0,
            tiled=True,
        )                                               # [n, nb]
        shard = jnp.sum(_block_dequant(recv, recv_scales), axis=0)  # [cpad]
        if op == Average:
            shard = shard / jnp.asarray(n, shard.dtype)
        q2, s2 = _stochastic_round_blocks(
            shard[None], block_size, jax.random.fold_in(key, 7919)
        )
        all_q = lax.all_gather(q2[0], axis_name)   # [n, nb, block]
        all_s = lax.all_gather(s2[0], axis_name)   # [n, nb]
        out = _block_dequant(all_q, all_s)[:, :chunk].reshape(-1)[:m]
        dequant_local = _block_dequant(q, scales)[:, :chunk]
        e2 = (shard - _block_dequant(q2, s2)[0])[:chunk]
    else:
        q, scales = _stochastic_round_rows(chunks, key)
        wire_scales = (
            scales * prescale if prescale_factor != 1.0 else scales
        )
        # all_to_all = the scatter half of reduce-scatter: afterwards
        # row r holds the chunk rank r quantized for us, with its scale.
        recv = lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0,
                              tiled=True)
        recv_scales = lax.all_to_all(
            wire_scales.reshape(n, 1), axis_name, split_axis=0,
            concat_axis=0, tiled=True,
        ).reshape(n)
        shard = jnp.sum(
            recv.astype(jnp.float32) * recv_scales[:, None], axis=0
        )
        if op == Average:
            shard = shard / jnp.asarray(n, shard.dtype)
        # Second stage: per-tensor Pallas quantizer on the reduced
        # shard, decorrelated from stage one and from other ranks.
        q2, s2 = int8_quantize(shard, seed=seed * 2 + 1 + idx * 7919)
        all_q = lax.all_gather(q2, axis_name)    # [n, chunk] int8
        all_s = lax.all_gather(s2, axis_name)    # [n] f32
        out = (all_q.astype(jnp.float32) * all_s[:, None]).reshape(-1)[:m]
        dequant_local = q.astype(jnp.float32) * scales[:, None]
        e2 = shard - q2.astype(jnp.float32) * s2
    out = out.reshape(shape).astype(dtype)
    if not return_residual:
        return out
    if prescale_factor == 0.0:
        # a zero prescale transmits nothing, so no input correction
        # could ever surface in the output — the carry is zero (the
        # two-pass form's behavior: zeroed chunks quantize to zeros),
        # and dividing e2 by the factor would manufacture NaNs
        return out, jnp.zeros(shape, dtype)
    # Error-feedback carry, BOTH stages, in input units:
    # * stage 1: this rank's local quantization error, elementwise —
    #   against the UNSCALED scales, since the output responds to an
    #   input correction through the folded prescale already;
    # * stage 2: the reduced-shard quantization error of the chunk this
    #   rank owns — adding it to our next-step contribution restores it
    #   in everyone's output (x n under Average, which divides by n;
    #   / prescale, which the input correction will be re-multiplied by).
    res_flat = (chunks - dequant_local).reshape(-1)
    if op == Average:
        e2 = e2 * jnp.asarray(n, jnp.float32)
    if prescale_factor != 1.0:
        e2 = e2 / jnp.asarray(prescale_factor, e2.dtype)
    res_flat = jax.lax.dynamic_update_slice(
        res_flat,
        jax.lax.dynamic_slice(res_flat, (idx * chunk,), (chunk,)) + e2,
        (idx * chunk,),
    )
    residual = res_flat[:m].reshape(shape).astype(dtype)
    return out, residual


def quantized_reducescatter(
    panes,
    op=None,
    axis_name: str = WORLD_AXIS,
    seed=0,
    block_size: Optional[int] = None,
    return_residual: bool = False,
    groups=None,
):
    """Single-stage quantized reduce-scatter of a ``[n, cols]`` pane
    buffer (row ``j`` destined for rank ``j`` — the ``psum_scatter``
    layout the sharded optimizer's bucket panes already use): each rank
    block-quantizes its rows to int8 with stochastic rounding, an
    ``all_to_all`` moves int8 + scales, and the destination dequantizes
    and sums in fp32 — the scatter half of :func:`quantized_allreduce`
    with NO second quantization stage, so the error bound is ONE
    quantum per element (vs two for the full quantized allreduce).

    Pad exclusion by construction: pane pad entries are zeros
    (``parallel.fsdp.pad_to`` contract), zeros quantize to zeros and
    never raise a block's absmax, so a padded pane's block scales equal
    the unpadded pane's and pad positions carry zero residual —
    asserted in tests/test_zero.py.

    Returns the fp32 ``[cols]`` shard. ``return_residual=True``
    additionally returns this rank's local quantization error
    (``panes − dequant(quant(panes))``, input units, ``[n, cols]``) —
    the error-feedback carry: add it to the NEXT step's panes before
    quantizing. Input-unit carry needs no Average rescale: the error
    enters the output pre-division, so a +res input correction restores
    exactly what the quantization cost. Sum/Average only.
    """
    _stall_check()
    op = resolve_op(op, None)
    if op not in (Average, Sum):
        raise ValueError("quantized_reducescatter supports Sum/Average only")
    n = len(groups[0]) if groups is not None else lax.axis_size(axis_name)
    if panes.ndim != 2 or panes.shape[0] != n:
        raise ValueError(
            f"panes must be [world={n}, cols], got {panes.shape}"
        )
    cols = panes.shape[1]
    idx = lax.axis_index(axis_name)
    x = panes.astype(jnp.float32)
    block = int(block_size) if block_size else max(cols, 1)
    key = jax.random.fold_in(jax.random.PRNGKey(0), seed)
    key = jax.random.fold_in(key, idx)
    q, scales = _stochastic_round_blocks(x, block, key)  # [n, nb, block]
    recv = lax.all_to_all(q, axis_name, split_axis=0, concat_axis=0,
                          tiled=True, axis_index_groups=groups)
    recv_s = lax.all_to_all(scales, axis_name, split_axis=0,
                            concat_axis=0, tiled=True,
                            axis_index_groups=groups)
    shard = jnp.sum(_block_dequant(recv, recv_s), axis=0)[:cols]
    if op == Average:
        shard = shard / jnp.asarray(n, shard.dtype)
    if not return_residual:
        return shard
    residual = x - _block_dequant(q, scales)[:, :cols]
    return shard, residual


def quantized_allgather(
    shard,
    axis_name: str = WORLD_AXIS,
    seed=0,
    block_size: Optional[int] = None,
    return_residual: bool = False,
    groups=None,
):
    """Quantized all-gather of a per-rank ``[cols]`` shard: block-scaled
    int8 with stochastic rounding on the wire, one quantization stage.
    EVERY rank — the shard's owner included — consumes the dequantized
    wire value, so a gathered parameter-update stays bit-identical
    across replicas (the Horovod replica-consistency contract) at the
    cost of one quantum of update error, which the error-feedback carry
    (``return_residual=True``: ``shard − dequant(quant(shard))``, input
    units, ``[cols]``) cancels cumulatively. Same pad-exclusion-by-
    construction contract as :func:`quantized_reducescatter`.

    Returns the fp32 ``[n, cols]`` gather (row ``r`` = rank r's shard).
    """
    _stall_check()
    n = len(groups[0]) if groups is not None else lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    x = shard.reshape(1, -1).astype(jnp.float32)
    cols = x.shape[1]
    block = int(block_size) if block_size else max(cols, 1)
    key = jax.random.fold_in(jax.random.PRNGKey(1), seed)
    key = jax.random.fold_in(key, idx)
    q, s = _stochastic_round_blocks(x, block, key)  # [1, nb, block]
    all_q = lax.all_gather(
        q[0], axis_name, axis_index_groups=groups
    )  # [n, nb, block]
    all_s = lax.all_gather(
        s[0], axis_name, axis_index_groups=groups
    )  # [n, nb]
    out = _block_dequant(all_q, all_s)[:, :cols]
    if not return_residual:
        return out
    residual = (x - _block_dequant(q, s)[:, :cols])[0]
    return out, residual


def quantized_alltoall(
    tensor,
    axis_name: str = WORLD_AXIS,
    seed=0,
    block_size: Optional[int] = None,
    groups=None,
):
    """Block-scaled int8 alltoall of a ``[n, slots, d]`` dispatch
    buffer (row ``j`` destined for rank ``j`` — the MoE expert-dispatch
    layout of ``parallel/moe.py``): each (destination, slot) row is
    quantized to int8 with one absmax scale per ``block_size`` elements
    of ``d`` and stochastic rounding, an ``all_to_all`` moves int8 +
    scales, and the receiver dequantizes to fp32 — the quantized-MoE
    wire EQuARX motivates (PAPERS.md, arXiv 2506.17615), ~4x fewer
    bytes than the fp32 dispatch at one quantum of error per element.

    Pad exclusion by construction: empty dispatch slots (tokens dropped
    by the capacity gate, slots past a destination's fill) are all-zero
    rows — ``moe.py`` scatters into a zero-initialized buffer and
    carries a ``-1`` expert sentinel per slot — and zeros quantize to
    zeros without ever raising a block's absmax, so a pad slot
    contributes nothing to any scale and arrives as exact zeros.

    ``groups`` restricts the exchange to ``axis_index_groups`` of the
    flat axis (the inter hop of :func:`hierarchical_alltoall`); then
    ``n`` is the group size. Returns fp32 ``[n, slots, d]``.
    """
    _stall_check()
    n = len(groups[0]) if groups is not None else lax.axis_size(axis_name)
    if tensor.ndim != 3 or tensor.shape[0] != n:
        raise ValueError(
            f"dispatch buffer must be [n={n}, slots, d], "
            f"got {tensor.shape}"
        )
    _, slots, d = tensor.shape
    idx = lax.axis_index(axis_name)
    x = tensor.reshape(n * slots, d).astype(jnp.float32)
    # clamp to the row width: a block wider than d would zero-pad every
    # row up to it and the "quantized" wire would move MORE bytes than
    # fp32 (516 vs 256 B/row at d=64 under the default block 512)
    block = min(int(block_size), d) if block_size else max(d, 1)
    block = max(block, 1)
    key = jax.random.fold_in(jax.random.PRNGKey(2), seed)
    key = jax.random.fold_in(key, idx)
    q, scales = _stochastic_round_blocks(x, block, key)
    nb = scales.shape[1]
    recv = lax.all_to_all(
        q.reshape(n, slots, nb, block), axis_name,
        split_axis=0, concat_axis=0, tiled=True, axis_index_groups=groups,
    )
    recv_s = lax.all_to_all(
        scales.reshape(n, slots, nb), axis_name,
        split_axis=0, concat_axis=0, tiled=True, axis_index_groups=groups,
    )
    out = _block_dequant(
        recv.reshape(n * slots, nb, block), recv_s.reshape(n * slots, nb)
    )[:, :d]
    return out.reshape(n, slots, d)


def hierarchical_alltoall(
    tensor,
    axis_name: str = WORLD_AXIS,
    stages=None,
    intra_wire: str = "fp32",
    inter_wire: str = "fp32",
    seed=0,
    block_size: Optional[int] = None,
):
    """Two-level alltoall of a ``[n, slots, d]`` dispatch buffer on the
    FLAT axis (replica groups — ``topology.hierarchy_stages()``),
    elementwise equal to the flat ``lax.all_to_all`` for exact wires:

    1. **inter hop** (DCN): same-position ranks across slices exchange
       whole per-destination-slice sub-buffers — only blocks bound for
       ANOTHER slice cross the wire. ``inter_wire='int8'`` rides
       :func:`quantized_alltoall`; either lossy wire (bf16/int8)
       restores the SELF-slice block from the local fp32 original
       afterwards, so tokens bound for intra-slice experts never pay
       quantization — the PR 10 placement rule (EQuARX: quantize only
       where bytes are scarce) applied to expert dispatch.
    2. **intra hop** (ICI): one alltoall inside each slice delivers
       every block to its destination rank, at ``intra_wire``
       (fp32/bf16 — never int8; ICI is fast).

    The lowered module carries the two-level structure — group-limited
    ``all_to_all`` ops only, never a monolithic world-spanning one
    (tests/bench assert the replica-group text). Non-float payloads
    (the MoE expert-index map) ride both hops unmodified; pass exact
    wires for them. Requires the canonical contiguous-intra ``stages``
    layout. Returns the input dtype (int8 inter returns fp32-rounded
    values cast back).
    """
    if stages is None:
        raise ValueError("stages is required (topology.hierarchy_stages)")
    intra_groups, inter_groups = stages
    L = len(intra_groups[0])
    H = len(inter_groups[0])
    n = L * H
    if tensor.ndim != 3 or tensor.shape[0] != n:
        raise ValueError(
            f"dispatch buffer must be [n={n}, slots, d], "
            f"got {tensor.shape}"
        )
    _, slots, d = tensor.shape
    dtype = tensor.dtype
    lossy = inter_wire in ("bf16", "int8")
    exact = not jnp.issubdtype(jnp.dtype(dtype), jnp.floating)
    idx = lax.axis_index(axis_name)
    # destination blocks, slice-major: xr[h_d] = the [L·slots, d] of
    # everything this rank sends to slice h_d
    xr = tensor.reshape(H, L * slots, d)
    if inter_wire == "int8" and not exact:
        y = quantized_alltoall(
            xr, axis_name=axis_name, seed=seed, block_size=block_size,
            groups=inter_groups,
        ).astype(dtype)
    else:
        wire = "fp32" if exact else inter_wire
        y = lax.all_to_all(
            _stage_cast(xr, wire), axis_name,
            split_axis=0, concat_axis=0, tiled=True,
            axis_index_groups=inter_groups,
        ).astype(dtype)
    if lossy and not exact:
        # the self-slice block never crossed DCN: row h (this rank's
        # position within its inter group) is its own block — restore
        # the fp32 original so intra-bound tokens stay exact
        pos = jnp.asarray(_group_pos_table(inter_groups))[idx]
        own = lax.dynamic_slice_in_dim(xr, pos, 1, axis=0).astype(dtype)
        y = lax.dynamic_update_slice_in_dim(y, own, pos, axis=0)
    # y[h_s] = blocks from (h_s, l_self) for every (h_self, l_d);
    # regroup by destination intra position and deliver inside the slice
    y = y.reshape(H, L, slots, d).transpose(1, 0, 2, 3)  # [L_d, H_s, ...]
    iw = "fp32" if exact else intra_wire
    z = lax.all_to_all(
        _stage_cast(y.reshape(L, H * slots, d), iw), axis_name,
        split_axis=0, concat_axis=0, tiled=True,
        axis_index_groups=intra_groups,
    ).astype(dtype)
    # z[l_s] = blocks from (h_s, l_s) — back to flat rank-major order
    return (
        z.reshape(L, H, slots, d).transpose(1, 0, 2, 3).reshape(
            n, slots, d
        )
    )


# Axis names for the two-level mesh built by hierarchical_mesh()
# (canonical home: common/topology.py — re-bound here for the existing
# import surface).
from ..common.topology import INTRA_AXIS, INTER_AXIS  # noqa: E402,F401


# ------------------------------------------------------------------
# Two-level recipe family ON THE FLAT AXIS (replica groups).
#
# The two-axis forms below (hierarchical_allreduce & co over a
# hierarchical_mesh) prove the dataflow; these group-flavored forms are
# what the DEFAULT wire actually routes through — the fused dispatcher,
# the overlap buckets and the ZeRO legs all trace over the flat "hvd"
# axis, where the slice boundary is expressible only as
# axis_index_groups (common/topology.py hierarchy_stages). Every recipe
# is the same three-hop shape: intra reduce-scatter -> inter collective
# on the 1/L shard -> intra all-gather, each hop with its own wire
# format; zero-pad never reaches a block scale or residual (zeros
# quantize to zeros and never raise an absmax — the standing pad
# contract).
# ------------------------------------------------------------------


def _stage_cast(x, wire):
    """Cast a buffer onto one hop's wire: bf16 halves the bytes (XLA
    fuses the cast into the collective's producer/consumer); fp32 /
    payload width is the identity."""
    return x.astype(jnp.bfloat16) if wire == "bf16" else x


def _group_pos_table(groups):
    """Static [world] int32 table: each rank's index within its group
    (chunk ownership for the grouped quantized recipes)."""
    from ..common.topology import stage_positions

    return stage_positions(groups)


def _quantized_sum_groups(
    row, axis_name, groups, n, block, key, pos=None, want_residual=False,
):
    """The two-stage block-scaled int8 allreduce recipe of
    :func:`quantized_allreduce`, over ``axis_index_groups`` of the flat
    axis (``groups=None`` = the whole axis): chunk the row across the
    ``n`` group members, stochastic-round to int8, all_to_all int8 +
    scales, dequant-sum, re-round the reduced chunk, all_gather.
    SUM semantics (callers divide for Average). Returns ``(out, res)``
    with ``res`` the sum-level input-unit EF carry (both stages, the
    quantized_allreduce contract) or None."""
    m = row.shape[0]
    chunk = -(-m // n)
    flat = jnp.pad(row, (0, chunk * n - m)) if chunk * n != m else row
    chunks = flat.reshape(n, chunk)
    q, scales = _stochastic_round_blocks(chunks, block, key)
    recv = lax.all_to_all(
        q, axis_name, split_axis=0, concat_axis=0, tiled=True,
        axis_index_groups=groups,
    )
    recv_s = lax.all_to_all(
        scales, axis_name, split_axis=0, concat_axis=0, tiled=True,
        axis_index_groups=groups,
    )
    shard = jnp.sum(_block_dequant(recv, recv_s), axis=0)  # [chunk]
    q2, s2 = _stochastic_round_blocks(
        shard[None], block, jax.random.fold_in(key, 7919)
    )
    all_q = lax.all_gather(q2[0], axis_name, axis_index_groups=groups)
    all_s = lax.all_gather(s2[0], axis_name, axis_index_groups=groups)
    out = _block_dequant(all_q, all_s)[:, :chunk].reshape(-1)[:m]
    if not want_residual:
        return out, None
    # which chunk this rank owns = its position within its group
    if pos is None:
        idx = lax.axis_index(axis_name)
        p = (
            jnp.asarray(_group_pos_table(groups))[idx]
            if groups is not None
            else idx
        )
    else:
        p = pos
    res_flat = (chunks - _block_dequant(q, scales)[:, :chunk]).reshape(-1)
    # e2 stays UN-scaled even when the caller averages afterwards:
    # this recipe quantizes the SUM shard (the /n happens outside), so
    # the stage-2 error and an input correction both reach the output
    # through the same later divide — unlike the flat path, which
    # divides BEFORE stage 2 and therefore multiplies its e2 by n
    e2 = (shard - _block_dequant(q2, s2)[0])[:chunk]
    res_flat = lax.dynamic_update_slice(
        res_flat,
        lax.dynamic_slice(res_flat, (p * chunk,), (chunk,)) + e2,
        (p * chunk,),
    )
    return out, res_flat[:m]


def hierarchical_allreduce_groups(
    tensor,
    op=None,
    axis_name: str = WORLD_AXIS,
    stages=None,
    intra_wire: str = "fp32",
    inter_wire: str = "fp32",
    seed=0,
    block_size: Optional[int] = None,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
    return_residual: bool = False,
):
    """Two-level allreduce on the FLAT axis: intra reduce-scatter ->
    inter collective on the 1/L shard -> intra all-gather, via the
    replica groups in ``stages`` (``topology.hierarchy_stages()``).
    This is the recipe the fused dispatcher, the overlap buckets and
    the hier_int8 optimizer path ride when an inter axis is present:
    the slow cross-slice hop carries 1/L of the bytes — times another
    ~4x when ``inter_wire='int8'`` (EQuARX's placement: quantize only
    where bytes are scarce).

    ``intra_wire`` ∈ {fp32, bf16} applies to BOTH intra hops;
    ``inter_wire`` ∈ {fp32, bf16, int8}. With everything at fp32 the
    result is the exact two-level sum (bit-exact vs flat for payloads
    whose partial sums are exactly representable — integer-valued
    grids; a few ulp of reassociation otherwise, see docs/perf.md).
    Sum/Average only.

    ``return_residual`` (int8 inter only): the inter-stage EF carry in
    INPUT units — the shard residual re-broadcast over the intra
    groups divided by L, so adding it to the NEXT step's tensor makes
    the intra reduce-scatter reconstruct exactly one copy at the shard
    owner (``hierarchical_quantized_allreduce``'s contract, group
    edition)."""
    op = resolve_op(op, None)
    if op not in (Average, Sum):
        raise ValueError(
            "hierarchical_allreduce_groups supports Sum/Average only"
        )
    if stages is None:
        raise ValueError("stages is required (topology.hierarchy_stages)")
    if return_residual and inter_wire != "int8":
        raise ValueError(
            "return_residual needs inter_wire='int8' (exact hops have "
            "no residual to carry)"
        )
    intra_groups, inter_groups = stages
    L = len(intra_groups[0])
    H = len(inter_groups[0])
    n = L * H
    shape, dtype = tensor.shape, tensor.dtype
    flat = tensor.reshape(-1)
    if inter_wire == "int8":
        flat = flat.astype(jnp.float32)
    m = flat.shape[0]
    pad = (-m) % L
    if pad:
        flat = jnp.pad(flat, (0, pad))
    if prescale_factor != 1.0:
        flat = flat * jnp.asarray(prescale_factor, flat.dtype)
    shard = lax.psum_scatter(
        _stage_cast(flat, intra_wire), axis_name,
        scatter_dimension=0, tiled=True, axis_index_groups=intra_groups,
    ).astype(flat.dtype)
    residual = None
    if inter_wire == "int8":
        idx = lax.axis_index(axis_name)
        key = jax.random.fold_in(jax.random.PRNGKey(0), seed)
        key = jax.random.fold_in(key, idx)
        block = int(block_size) if block_size else max(shard.shape[0], 1)
        pos = jnp.asarray(_group_pos_table(inter_groups))[idx]
        red, res = _quantized_sum_groups(
            shard, axis_name, inter_groups, H, block, key, pos=pos,
            want_residual=return_residual,
        )
        if res is not None:
            if prescale_factor == 0.0:
                # nothing was transmitted: zero carry (the
                # quantized_allreduce contract), not 0/0 NaNs
                res = jnp.zeros_like(res)
            elif prescale_factor != 1.0:
                # back to INPUT units: the correction will be
                # re-multiplied by the prescale on its way in
                res = res / jnp.asarray(prescale_factor, res.dtype)
            residual = lax.all_gather(
                res / jnp.asarray(L, res.dtype), axis_name,
                tiled=True, axis_index_groups=intra_groups,
            )[:m]
    else:
        red = lax.psum(
            _stage_cast(shard, inter_wire), axis_name,
            axis_index_groups=inter_groups,
        ).astype(shard.dtype)
    out = lax.all_gather(
        _stage_cast(red, intra_wire), axis_name,
        tiled=True, axis_index_groups=intra_groups,
    ).astype(flat.dtype)
    if op == Average:
        out = out / jnp.asarray(n, out.dtype)
    if postscale_factor != 1.0:
        out = out * jnp.asarray(postscale_factor, out.dtype)
    out = out[:m].reshape(shape).astype(dtype)
    if not return_residual:
        return out
    residual = (
        jnp.zeros(shape, dtype)
        if residual is None
        else residual[:m].reshape(shape).astype(dtype)
    )
    return out, residual


def hierarchical_reducescatter(
    panes,
    op=None,
    axis_name: str = WORLD_AXIS,
    stages=None,
    intra_wire: str = "fp32",
    inter_wire: str = "fp32",
    seed=0,
    block_size: Optional[int] = None,
):
    """Two-level reduce-scatter of a ``[n, cols]`` pane buffer (row j
    destined for flat rank j — the ZeRO bucket layout): intra
    reduce-scatter of the destination rows that share this rank's
    slice-local slot -> inter collective on the 1/L-sized ``[H, cols]``
    panes -> this rank's ``[cols]`` shard. The DCN hop moves 1/L of the
    flat reduce-scatter's bytes (int8 inter: ~4x less again).
    Elementwise identical to the flat scatter for exact wires (each
    output element is the same set of addends, summed intra-then-inter).
    Requires the canonical ``stages`` layout (contiguous intra groups —
    ``topology.hierarchy_stages``). Sum/Average only."""
    op = resolve_op(op, None)
    if op not in (Average, Sum):
        raise ValueError(
            "hierarchical_reducescatter supports Sum/Average only"
        )
    if stages is None:
        raise ValueError("stages is required (topology.hierarchy_stages)")
    intra_groups, inter_groups = stages
    L = len(intra_groups[0])
    H = len(inter_groups[0])
    n = L * H
    if panes.ndim != 2 or panes.shape[0] != n:
        raise ValueError(
            f"panes must be [world={n}, cols], got {panes.shape}"
        )
    cols = panes.shape[1]
    dtype = panes.dtype
    buf = panes.reshape(H, L, cols)
    s1 = lax.psum_scatter(
        _stage_cast(buf, intra_wire), axis_name,
        scatter_dimension=1, tiled=True, axis_index_groups=intra_groups,
    ).astype(dtype).reshape(H, cols)
    if inter_wire == "int8":
        shard = quantized_reducescatter(
            s1.astype(jnp.float32), op=Sum, axis_name=axis_name,
            seed=seed, block_size=block_size, groups=inter_groups,
        ).astype(dtype)
    else:
        shard = lax.psum_scatter(
            _stage_cast(s1, inter_wire), axis_name,
            scatter_dimension=0, tiled=True,
            axis_index_groups=inter_groups,
        ).astype(dtype).reshape(cols)
    if op == Average:
        shard = shard / jnp.asarray(n, shard.dtype)
    return shard.reshape(cols)


def hierarchical_allgather(
    shard,
    axis_name: str = WORLD_AXIS,
    stages=None,
    intra_wire: str = "fp32",
    inter_wire: str = "fp32",
    seed=0,
    block_size: Optional[int] = None,
):
    """Two-level all-gather, the dual of
    :func:`hierarchical_reducescatter`: each rank's ``[cols]`` shard ->
    inter all-gather among same-slot peers (1/L of the DCN bytes of a
    flat gather; int8 inter rides
    :func:`quantized_allgather`'s one-stage wire, every rank — owners
    included — consuming the dequantized value so replicas stay
    bit-identical) -> intra all-gather + static reorder back to flat
    rank-major ``[n, cols]``."""
    if stages is None:
        raise ValueError("stages is required (topology.hierarchy_stages)")
    intra_groups, inter_groups = stages
    L = len(intra_groups[0])
    H = len(inter_groups[0])
    n = L * H
    cols = shard.shape[0]
    dtype = shard.dtype
    if inter_wire == "int8":
        g1 = quantized_allgather(
            shard.astype(jnp.float32), axis_name=axis_name, seed=seed,
            block_size=block_size, groups=inter_groups,
        ).astype(dtype)  # [H, cols]
    else:
        g1 = lax.all_gather(
            _stage_cast(shard, inter_wire), axis_name,
            axis_index_groups=inter_groups,
        ).astype(dtype)  # [H, cols]
    g2 = lax.all_gather(
        _stage_cast(g1, intra_wire), axis_name,
        axis_index_groups=intra_groups,
    ).astype(dtype)  # [L, H, cols]
    return jnp.transpose(g2, (1, 0, 2)).reshape(n, cols)


def hierarchical_mesh(local_size: Optional[int] = None):
    """A 2-axis (inter, intra) mesh over the world devices — the TPU
    shape of the reference's node-hierarchy split (NCCL intra-node + MPI
    inter-node, HOROVOD_HIERARCHICAL_ALLREDUCE in nccl_operations.cc
    [V]): ``intra`` rides ICI within a host/slice, ``inter`` rides DCN
    across them. ``local_size`` defaults to the topology's chips-per-host.
    """
    import numpy as np
    from jax.sharding import Mesh

    from ..common import basics

    topo = basics.topology()
    devices = np.asarray(topo.devices)
    if local_size is None:
        # slice-boundary detection incl. the HOROVOD_INTRA_SIZE
        # override (common/topology.py); falls back to chips-per-host
        local_size = topo.intra_size
    if local_size < 1 or devices.size % local_size:
        raise ValueError(
            f"local_size {local_size} must divide world {devices.size}"
        )
    grid = devices.reshape(devices.size // local_size, local_size)
    return Mesh(grid, (INTER_AXIS, INTRA_AXIS))


def hierarchical_allreduce(
    tensor,
    op=None,
    intra_axis: str = INTRA_AXIS,
    inter_axis: str = INTER_AXIS,
    prescale_factor: float = 1.0,
    postscale_factor: float = 1.0,
):
    """Two-level allreduce for use inside shard_map over a
    :func:`hierarchical_mesh`: reduce-scatter on the intra (ICI) axis,
    allreduce the 1/L-sized shards on the inter (DCN) axis, all-gather
    back on intra — the reference's exact hierarchical dataflow
    (ReduceScatter→MPI-allreduce→Allgather, nccl_operations.cc [V]),
    which keeps the slow cross-slice hop at 1/local_size of the bytes.

    The tensor is flattened and zero-padded to a multiple of the intra
    size internally; shape is restored on return. Sum/Average only (the
    decomposition relies on reduction associativity over partitions).
    """
    op = resolve_op(op, None)
    if op not in (Average, Sum):
        raise ValueError("hierarchical_allreduce supports Sum/Average only")
    out, _ = _two_level_allreduce(
        tensor, op, intra_axis, inter_axis,
        lambda shard: (lax.psum(shard, inter_axis), None),
        prescale=prescale_factor, postscale=postscale_factor,
    )
    return out


def _two_level_allreduce(
    tensor, op, intra_axis, inter_axis, inter_reduce,
    prescale=1.0, postscale=1.0,
):
    """Shared rs-intra → inter_reduce → ag-intra scaffolding
    (flatten/pad/unpad, Average divisor, scale factors) for
    :func:`hierarchical_allreduce` and its quantized composition —
    one copy of the dataflow, two inter-stage reducers.
    ``inter_reduce(shard) -> (reduced_shard, extra_or_None)``; a
    non-None extra (the EF residual) gets the output's dual transform:
    divided by the intra size, all-gathered, unpadded (see
    hierarchical_quantized_allreduce's carry semantics)."""
    intra_n = lax.axis_size(intra_axis)
    inter_n = lax.axis_size(inter_axis)
    shape, dtype = tensor.shape, tensor.dtype
    flat = tensor.reshape(-1)
    m = flat.shape[0]
    padded = -(-m // intra_n) * intra_n
    if padded != m:
        flat = jnp.pad(flat, (0, padded - m))
    if prescale != 1.0:
        flat = flat * jnp.asarray(prescale, flat.dtype)
    shard = lax.psum_scatter(
        flat, intra_axis, scatter_dimension=0, tiled=True
    )                                       # [padded/L], summed intra
    red, extra = inter_reduce(shard)        # cross-slice hop, 1/L bytes
    out = lax.all_gather(red, intra_axis, tiled=True)  # [padded]
    if op == Average:
        out = out / jnp.asarray(intra_n * inter_n, out.dtype)
    if postscale != 1.0:
        out = out * jnp.asarray(postscale, out.dtype)
    out = out[:m].reshape(shape).astype(dtype)
    if extra is None:
        return out, None
    extra_full = lax.all_gather(
        extra / jnp.asarray(intra_n, extra.dtype), intra_axis,
        tiled=True,
    )
    return out, extra_full[:m].reshape(shape).astype(dtype)


def hierarchical_quantized_allreduce(
    tensor,
    op=None,
    intra_axis: str = INTRA_AXIS,
    inter_axis: str = INTER_AXIS,
    seed=0,
    return_residual: bool = False,
):
    """Hierarchical allreduce with the int8 wire on the CROSS-SLICE hop
    only — EQuARX's placement insight (PAPERS.md, pattern reference)
    composed from this module's two primitives: ICI is fast, so the
    intra reduce-scatter and all-gather stay full-precision; DCN is
    the bottleneck, so the inter-slice allreduce of the 1/L-sized
    shards rides :func:`quantized_allreduce`'s two-stage int8 (~4x
    fewer bytes exactly where bytes are scarcest). Quantization error
    is confined to the inter stage — two stochastic roundings on
    intra-summed shards — so the error bound matches flat
    ``quantized_allreduce`` while the ICI legs contribute none.

    ``return_residual=True``: error-feedback carry in INPUT units.
    The inter-stage residual lives on each rank's intra-shard; it is
    re-broadcast over ``intra_axis`` divided by the intra size, so
    adding it to the NEXT step's tensor makes the intra
    reduce-scatter reconstruct exactly one copy at the shard owner
    (each intra member contributes res/L to the same segment). Use
    with ``DistributedOptimizer(error_feedback=True)`` semantics.
    Sum/Average only.
    """
    op = resolve_op(op, None)
    if op not in (Average, Sum):
        raise ValueError(
            "hierarchical_quantized_allreduce supports Sum/Average only"
        )

    # input-unit carry (the `extra` leg of the shared scaffold): the
    # error enters the output linearly through the final (sum-level)
    # value, so no Average rescale is needed — a +res correction at
    # the input restores the output by res/n, exactly cancelling the
    # -res/n the quantization cost it.
    def inter(shard):
        r = quantized_allreduce(
            shard, op=Sum, axis_name=inter_axis, seed=seed,
            return_residual=return_residual,
        )
        return r if return_residual else (r, None)

    out, residual = _two_level_allreduce(
        tensor, op, intra_axis, inter_axis, inter
    )
    if not return_residual:
        return out
    return out, residual
