"""horovod_tpu.mxnet binding tests — modeled on the reference's
test/parallel/test_mxnet.py core cases [V]. MXNet itself is EOL and not
in the image, so these run against a minimal NDArray fake registered as
``mxnet``: the shim is duck-typed by design (module docstring) and only
touches ``mx.nd.array`` plus ``mx.gluon.Trainer``, which the fake
provides with real semantics (numpy storage, in-place [:] writes,
rescale_grad application in step). With real mxnet importable the same
tests would run unchanged against it.
"""

import sys
import types

import numpy as np
import pytest


class FakeNDArray:
    """numpy-backed stand-in for mx.nd.NDArray."""

    def __init__(self, array, ctx="cpu(0)", dtype=None):
        self._a = np.array(array, dtype=dtype, copy=True)
        self.context = ctx

    def asnumpy(self):
        return self._a.copy()

    @property
    def shape(self):
        return self._a.shape

    @property
    def dtype(self):
        return self._a.dtype

    def reshape(self, shape):
        return FakeNDArray(self._a.reshape(shape), ctx=self.context)

    def __setitem__(self, key, value):
        self._a[key] = value._a if isinstance(value, FakeNDArray) else value

    def __mul__(self, other):
        return FakeNDArray(self._a * other, ctx=self.context)

    __rmul__ = __mul__


class FakeTrainer:
    """Gluon-Trainer shape: holds params, steps via _allreduce_grads +
    a plain SGD update scaled by 1/batch_size (Gluon's rescale_grad)."""

    def __init__(self, params, optimizer, optimizer_params=None,
                 kvstore=None):
        del kvstore
        self._params = list(params.values()) if hasattr(params, "values") \
            else list(params)
        if not isinstance(optimizer, str):
            # real gluon.Trainer asserts exactly this; keep the fake
            # honest so the shim can't pass a dict it must not
            assert optimizer_params is None, (
                "optimizer_params must be None if optimizer is an "
                "Optimizer instance"
            )
        self._optimizer = optimizer
        opts = dict(optimizer_params or {})
        self._lr = float(opts.get("learning_rate", 0.1))
        if not isinstance(optimizer, str):
            self._lr = getattr(optimizer, "lr", 0.1)
        self._scale = 1.0

    def step(self, batch_size):
        self._allreduce_grads()
        factor = self._scale / float(batch_size)
        for p in self._params:
            if p.grad_req == "null":
                continue
            g = p.list_grad()[0]
            d = p.list_data()[0]
            d._a -= self._lr * factor * g._a

    def _allreduce_grads(self):  # overridden by DistributedTrainer
        raise AssertionError("subclass must override")


class FakeParameter:
    def __init__(self, data, grad=None, grad_req="write"):
        self._data = FakeNDArray(data)
        self._grad = FakeNDArray(grad if grad is not None else
                                 np.zeros_like(np.asarray(data)))
        self.grad_req = grad_req

    def list_data(self):
        return [self._data]

    def list_grad(self):
        return [self._grad]

    def set_data(self, value):
        self._data[:] = value


class FakeBaseOptimizer:
    """mx.optimizer.Optimizer shape: kwargs-only __init__ that seeds
    public knobs on self (as the real one does)."""

    def __init__(self, rescale_grad=1.0, learning_rate=0.01):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate


@pytest.fixture
def fake_mx(monkeypatch):
    mx = types.ModuleType("mxnet")
    nd = types.ModuleType("mxnet.nd")

    def _array(arr, ctx=None, dtype=None):
        a = np.asarray(arr)
        if dtype is not None:
            a = a.astype(dtype)
        return FakeNDArray(a, ctx=ctx or "cpu(0)")

    nd.array = _array
    nd.NDArray = FakeNDArray
    gluon = types.ModuleType("mxnet.gluon")
    gluon.Trainer = FakeTrainer
    optimizer = types.ModuleType("mxnet.optimizer")
    optimizer.Optimizer = FakeBaseOptimizer
    mx.nd = nd
    mx.gluon = gluon
    mx.optimizer = optimizer
    monkeypatch.setitem(sys.modules, "mxnet", mx)
    monkeypatch.setitem(sys.modules, "mxnet.nd", nd)
    monkeypatch.setitem(sys.modules, "mxnet.gluon", gluon)
    monkeypatch.setitem(sys.modules, "mxnet.optimizer", optimizer)
    return mx


@pytest.fixture
def hvdm(hvd, fake_mx):
    import horovod_tpu.mxnet as hvd_mx

    return hvd_mx


def test_identity_and_size(hvdm):
    assert hvdm.is_initialized()
    assert hvdm.size() >= 1
    assert hvdm.rank() == 0


def test_allreduce_average(hvdm):
    x = FakeNDArray(np.arange(6, dtype=np.float32).reshape(2, 3))
    out = hvdm.allreduce(x, op=hvdm.Average)
    np.testing.assert_allclose(out.asnumpy(), x.asnumpy())
    assert out.dtype == x.dtype


def test_allreduce_sum_scales_by_world(hvdm):
    x = FakeNDArray(np.ones(4, np.float32))
    out = hvdm.allreduce(x, op=hvdm.Sum)
    np.testing.assert_allclose(out.asnumpy(), np.full(4, hvdm.size()))


def test_allreduce_inplace(hvdm):
    x = FakeNDArray(np.ones(3, np.float32))
    ret = hvdm.allreduce_(x, op=hvdm.Sum)
    assert ret is x
    np.testing.assert_allclose(x.asnumpy(), np.full(3, hvdm.size()))


def test_allreduce_0d(hvdm):
    x = FakeNDArray(np.float32(5.0))
    out = hvdm.allreduce(x, op=hvdm.Sum)
    assert out.shape == ()
    np.testing.assert_allclose(out.asnumpy(), 5.0 * hvdm.size())


def test_grouped_allreduce_inplace(hvdm):
    xs = [FakeNDArray(np.full(2, i, np.float32)) for i in range(3)]
    outs = hvdm.grouped_allreduce_(xs, op=hvdm.Sum)
    for i, (x, o) in enumerate(zip(xs, outs)):
        assert o is x
        np.testing.assert_allclose(x.asnumpy(), np.full(2, i * hvdm.size()))


def test_allgather_concatenates(hvdm):
    x = FakeNDArray(np.arange(6, dtype=np.float32).reshape(2, 3))
    out = hvdm.allgather(x)
    assert out.shape == (2 * hvdm.size(), 3)
    np.testing.assert_allclose(
        out.asnumpy(), np.tile(x.asnumpy(), (hvdm.size(), 1))
    )


def test_broadcast_and_inplace(hvdm):
    x = FakeNDArray(np.arange(4, dtype=np.float32))
    out = hvdm.broadcast(x, root_rank=0)
    np.testing.assert_allclose(out.asnumpy(), x.asnumpy())
    y = FakeNDArray(np.ones(4, np.float32))
    ret = hvdm.broadcast_(y, root_rank=0)
    assert ret is y


def test_alltoall_even(hvdm):
    world = hvdm.size()
    x = FakeNDArray(np.arange(world * 2, dtype=np.float32).reshape(world, 2))
    out = hvdm.alltoall(x)
    assert out.shape[0] == world


def test_alltoall_uneven_splits(hvdm):
    world = hvdm.size()
    # this rank sends i+1 rows to peer i (replicated across ranks)
    splits = [i + 1 for i in range(world)]
    n = sum(splits)
    x = FakeNDArray(np.arange(n * 2, dtype=np.float32).reshape(n, 2))
    out, recv = hvdm.alltoall(x, splits=FakeNDArray(np.asarray(splits)))
    # every rank sends us our rank-indexed split: rank 0 receives 1 row
    # from each peer under the replicated single-controller model
    assert recv.asnumpy().tolist() == [1] * world
    assert out.shape == (world, 2)


def test_alltoall_bad_splits_raises(hvdm):
    world = hvdm.size()
    x = FakeNDArray(np.ones((4, 2), np.float32))
    with pytest.raises(ValueError):
        hvdm.alltoall(x, splits=[5] * world)  # sums != dim0


def test_reducescatter_shard(hvdm):
    world = hvdm.size()
    x = FakeNDArray(np.arange(world * 3, dtype=np.float32).reshape(world, 3))
    out = hvdm.reducescatter(x, op=hvdm.Sum)
    # rank 0's shard of the world-summed tensor
    np.testing.assert_allclose(out.asnumpy(), x.asnumpy()[:1] * world)


def test_broadcast_parameters_dict(hvdm):
    params = {
        "w": FakeNDArray(np.ones((2, 2), np.float32)),
        "b": FakeNDArray(np.zeros(2, np.float32)),
    }
    hvdm.broadcast_parameters(params, root_rank=0)
    np.testing.assert_allclose(params["w"].asnumpy(), np.ones((2, 2)))


def test_broadcast_parameters_gluon_style(hvdm):
    p = FakeParameter(np.full((3,), 7.0, np.float32))
    hvdm.broadcast_parameters({"layer.weight": p}, root_rank=0)
    np.testing.assert_allclose(p.list_data()[0].asnumpy(), np.full(3, 7.0))


class _SGD:
    """Duck-typed mx.optimizer.Optimizer: w -= lr * g."""

    def __init__(self, lr=0.5):
        self.lr = lr
        self.seen = []

    def update(self, index, weight, grad, state):
        self.seen.append(("update", index))
        ws = weight if isinstance(weight, list) else [weight]
        gs = grad if isinstance(grad, list) else [grad]
        for w, g in zip(ws, gs):
            w._a -= self.lr * g._a

    def update_multi_precision(self, index, weight, grad, state):
        self.seen.append(("ump", index))
        self.update(index, weight, grad, state)


def test_distributed_optimizer_update(hvdm):
    opt = _SGD(lr=0.5)
    dopt = hvdm.DistributedOptimizer(opt)
    w = FakeNDArray(np.zeros(3, np.float32))
    g = FakeNDArray(np.full(3, 2.0, np.float32))
    dopt.update(0, w, g, None)
    # Average over identical contributions == the gradient itself
    np.testing.assert_allclose(g.asnumpy(), np.full(3, 2.0))
    np.testing.assert_allclose(w.asnumpy(), np.full(3, -1.0))
    assert opt.seen == [("update", 0)]


def test_distributed_optimizer_multi_index(hvdm):
    opt = _SGD(lr=1.0)
    dopt = hvdm.DistributedOptimizer(opt, op=hvdm.Sum)
    ws = [FakeNDArray(np.zeros(2, np.float32)) for _ in range(2)]
    gs = [FakeNDArray(np.ones(2, np.float32)) for _ in range(2)]
    dopt.update_multi_precision([0, 1], ws, gs, None)
    world = hvdm.size()
    for w in ws:
        np.testing.assert_allclose(w.asnumpy(), np.full(2, -float(world)))


def test_distributed_optimizer_rejects_bad_op(hvdm):
    with pytest.raises(ValueError):
        hvdm.DistributedOptimizer(_SGD(), op=hvdm.Max)


def test_distributed_optimizer_predivide_requires_average(hvdm):
    with pytest.raises(ValueError, match="op=Average"):
        hvdm.DistributedOptimizer(
            _SGD(), op=hvdm.Sum, gradient_predivide_factor=64.0)


def test_distributed_optimizer_num_groups(hvdm):
    opt = _SGD(lr=1.0)
    dopt = hvdm.DistributedOptimizer(opt, op=hvdm.Sum, num_groups=2)
    ws = [FakeNDArray(np.zeros(2, np.float32)) for _ in range(5)]
    gs = [FakeNDArray(np.full(2, float(i), np.float32)) for i in range(5)]
    dopt.update_multi_precision(list(range(5)), ws, gs, None)
    world = hvdm.size()
    for i, w in enumerate(ws):
        np.testing.assert_allclose(w.asnumpy(), np.full(2, -float(i * world)))


def test_distributed_optimizer_reads_delegate_to_inner(hvdm, fake_mx):
    """Wrapper must not shadow the inner optimizer's knobs: reads of
    lr/learning_rate reflect the wrapped optimizer's NON-default value
    (Optimizer.__init__ is deliberately not run on the wrapper)."""

    class RealSGD(FakeBaseOptimizer):
        def update(self, index, weight, grad, state):
            pass

        update_multi_precision = update

    inner = RealSGD(learning_rate=0.5)
    dopt = hvdm.DistributedOptimizer(inner)
    assert dopt.lr == 0.5


def test_distributed_optimizer_delegates_attrs(hvdm):
    opt = _SGD(lr=0.25)
    dopt = hvdm.DistributedOptimizer(opt)
    assert dopt.lr == 0.25


def test_distributed_optimizer_subclasses_real_base(hvdm, fake_mx):
    """With a real mx.optimizer.Optimizer instance, the factory returns
    an Optimizer SUBCLASS (gluon.Trainer isinstance-checks this) and
    mirrors public knob writes onto the wrapped optimizer (Trainer sets
    rescale_grad per step; update() consumes the inner value)."""

    class RealSGD(FakeBaseOptimizer):
        def __init__(self, **kw):
            super().__init__(**kw)
            self.updates = []

        def update(self, index, weight, grad, state):
            self.updates.append(self.rescale_grad)

        def update_multi_precision(self, index, weight, grad, state):
            self.update(index, weight, grad, state)

    inner = RealSGD(rescale_grad=1.0)
    dopt = hvdm.DistributedOptimizer(inner)
    assert isinstance(dopt, fake_mx.optimizer.Optimizer)
    dopt.rescale_grad = 0.125  # what Trainer does each step
    assert inner.rescale_grad == 0.125
    w = FakeNDArray(np.zeros(2, np.float32))
    g = FakeNDArray(np.ones(2, np.float32))
    dopt.update(0, w, g, None)
    assert inner.updates == [0.125]


def test_distributed_trainer_accepts_optimizer_instance(hvdm):
    """gluon.Trainer asserts optimizer_params is None for Optimizer
    instances — the factory must forward None unchanged."""
    p = FakeParameter(np.zeros(2, np.float32),
                      grad=np.full(2, 4.0, np.float32))
    opt = FakeBaseOptimizer(learning_rate=0.5)
    trainer = hvdm.DistributedTrainer({"w": p}, opt)
    trainer.step(batch_size=2)
    np.testing.assert_allclose(p.list_data()[0].asnumpy(), np.full(2, -1.0))


def test_distributed_trainer_step(hvdm):
    p = FakeParameter(np.zeros(4, np.float32),
                      grad=np.full(4, 8.0, np.float32))
    frozen = FakeParameter(np.zeros(2, np.float32), grad_req="null")
    trainer = hvdm.DistributedTrainer(
        {"w": p, "frozen": frozen}, "sgd", {"learning_rate": 0.5}
    )
    trainer.step(batch_size=4)
    # grads averaged over identical contributions stay 8.0;
    # update = lr * (1/batch) * g = 0.5 * 2.0 = 1.0 per element
    np.testing.assert_allclose(p.list_data()[0].asnumpy(), np.full(4, -1.0))
    np.testing.assert_allclose(frozen.list_data()[0].asnumpy(), np.zeros(2))


def test_check_build_reports_mxnet(hvdm, capsys):
    from horovod_tpu.runner.launch import run_commandline

    assert run_commandline(["--check-build"]) == 0
    assert "[X] MXNet (host bridge)" in capsys.readouterr().out


@pytest.mark.parametrize("dtype", [np.float32, np.float64, np.int32])
@pytest.mark.parametrize("op_name", ["Sum", "Average", "Min", "Max"])
def test_mxnet_op_dtype_matrix(hvdm, dtype, op_name):
    """op x dtype closed-form grid over the NDArray bridge (the
    reference's test_mxnet.py pattern [V])."""
    if op_name == "Average" and np.issubdtype(dtype, np.integer):
        pytest.skip("average over ints is float-contract territory")
    op = getattr(hvdm, op_name)
    x = FakeNDArray(np.asarray([1, 5, 7], dtype=dtype))
    out = hvdm.allreduce(x, op=op)
    base = x.asnumpy()
    expect = {
        "Sum": base * hvdm.size(),
        "Average": base,
        "Min": base,
        "Max": base,
    }[op_name]
    np.testing.assert_allclose(out.asnumpy(), expect)
    assert out.dtype == np.dtype(dtype)
