"""HTTP key-value rendezvous server.

Rebuild of the reference's Gloo rendezvous (ref:
horovod/runner/http/http_server.py [V] — SURVEY.md §2.5, §3.3; empty
mount, structural citations): the driver runs a threaded HTTP server
holding a scoped KV store; each worker PUTs its own address material and
GETs (polling) its peers' until the world has converged. Elastic re-keys
by bumping the scope (one scope per rendezvous round).

On TPU the payloads are the ``jax.distributed`` coordinator address and
per-host topology rather than Gloo connection strings, but the protocol
(scoped KV over HTTP, driver-hosted) is the same.

Wire protocol:
    GET    /kv/<scope>/<key>   -> 200 value | 404
    PUT    /kv/<scope>/<key>   body = value -> 200
    DELETE /kv/<scope>         -> 200 (drop whole scope)
    GET    /scope/<scope>      -> 200 JSON list of keys

If the server was created with a secret key, every request must carry
``X-Horovod-Digest: hex(hmac_sha256(secret, method + path + body))``;
bad or missing digests get 403 (parity with the HMAC-signed services,
SURVEY.md §2.5).
"""

from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional

from .secret import sign
from ..common.logging import TRACE as _TRACE, get_logger
from ..common.retry import RetryPolicy, backoff_delays
from ..testing import chaos as _chaos

_log = get_logger("rendezvous")


class KVStore:
    """Thread-safe scoped key-value store."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._data: Dict[str, Dict[str, bytes]] = {}

    def put(self, scope: str, key: str, value: bytes) -> None:
        with self._lock:
            self._data.setdefault(scope, {})[key] = value

    def get(self, scope: str, key: str) -> Optional[bytes]:
        with self._lock:
            return self._data.get(scope, {}).get(key)

    def keys(self, scope: str) -> List[str]:
        with self._lock:
            return sorted(self._data.get(scope, {}).keys())

    def drop_scope(self, scope: str) -> None:
        with self._lock:
            self._data.pop(scope, None)


def _make_handler(store: KVStore, secret_key: Optional[bytes]):
    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def log_message(self, fmt, *args):
            # Route through the horovod logger at trace level instead
            # of stderr spam; %-args pass through so logging defers the
            # formatting to the (rare) TRACE-enabled case.
            _log.log(_TRACE, "http " + fmt, *args)

        def _body(self) -> bytes:
            length = int(self.headers.get("Content-Length", 0))
            return self.rfile.read(length) if length else b""

        def _authed(self, body: bytes) -> bool:
            if secret_key is None:
                return True
            digest = self.headers.get("X-Horovod-Digest", "")
            want = sign(
                secret_key, self.command.encode() + self.path.encode() + body
            ).hex()
            import hmac as _hmac

            return _hmac.compare_digest(digest, want)

        def _reply(self, code: int, body: bytes = b"") -> None:
            self.send_response(code)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _inject_chaos(self) -> bool:
            """``kv.server`` injection site. Returns True when the
            request was consumed by a fault (503 answered, or the
            connection torn down mid-exchange). (Named so it cannot
            shadow the module's ``_chaos`` import inside the class.)"""
            try:
                _chaos.inject("kv.server")
            except _chaos.InjectedServerError:
                self._reply(503)
                return True
            except (ConnectionResetError, TimeoutError):
                # abrupt teardown: the client sees a dropped/short
                # response and must absorb it with its RetryPolicy
                self.close_connection = True
                try:
                    self.connection.close()
                except OSError:
                    pass
                return True
            return False

        def do_GET(self):
            if self._inject_chaos():
                return
            if not self._authed(b""):
                return self._reply(403)
            parts = self.path.strip("/").split("/")
            if len(parts) == 3 and parts[0] == "kv":
                value = store.get(parts[1], parts[2])
                if value is None:
                    return self._reply(404)
                return self._reply(200, value)
            if len(parts) == 2 and parts[0] == "scope":
                return self._reply(
                    200, json.dumps(store.keys(parts[1])).encode()
                )
            return self._reply(404)

        def do_PUT(self):
            if self._inject_chaos():
                return
            body = self._body()
            if not self._authed(body):
                return self._reply(403)
            parts = self.path.strip("/").split("/")
            if len(parts) == 3 and parts[0] == "kv":
                store.put(parts[1], parts[2], body)
                return self._reply(200)
            return self._reply(404)

        def do_DELETE(self):
            if not self._authed(b""):
                return self._reply(403)
            parts = self.path.strip("/").split("/")
            if len(parts) == 2 and parts[0] == "kv":
                store.drop_scope(parts[1])
                return self._reply(200)
            return self._reply(404)

    return Handler


class RendezvousServer:
    """Driver-side rendezvous: ephemeral or fixed port.

    Two backends behind one interface: the native C++ server
    (csrc/kvstore.cc — the reference's rendezvous consumers are native,
    gloo_context.cc [V], and a many-worker polling storm shouldn't
    contend with the driver's interpreter) and a threaded Python
    http.server fallback. ``backend`` is "auto" (native if buildable),
    "native", or "python"; ``HOROVOD_RENDEZVOUS_BACKEND`` overrides.
    ``.store`` exposes the same KV surface either way (the elastic
    driver reads it directly)."""

    def __init__(
        self,
        port: int = 0,
        secret_key: Optional[bytes] = None,
        backend: str = "auto",
    ) -> None:
        backend = os.environ.get("HOROVOD_RENDEZVOUS_BACKEND", backend)
        self._secret_key = secret_key
        self._native = None
        self._httpd = None
        self._thread: Optional[threading.Thread] = None
        self.backend = "python"
        if backend in ("auto", "native"):
            try:
                from .._native import loader as _native_loader

                self._native = _native_loader.NativeKVServer(
                    port=port, secret_key=secret_key
                )
                self.backend = "native"
            except Exception:
                if backend == "native":
                    raise
                self._native = None
        if self._native is not None:
            self.store = self._native  # KVStore-compatible surface
        else:
            self.store = KVStore()
            self._httpd = ThreadingHTTPServer(
                ("0.0.0.0", port), _make_handler(self.store, secret_key)
            )

    @property
    def port(self) -> int:
        if self._native is not None:
            return self._native.port
        return self._httpd.server_address[1]

    def start(self) -> int:
        if self._native is not None:
            return self._native.port  # native server accepts from creation
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="hvd-rendezvous", daemon=True
        )
        self._thread.start()
        _log.info("rendezvous server listening on port %d", self.port)
        return self.port

    def stop(self) -> None:
        if self._native is not None:
            self._native.stop()
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None


class RendezvousClient:
    """Worker-side accessor for the driver's KV store.

    Every HTTP exchange runs under the shared ``RetryPolicy`` (site
    ``kv.request``): transient connection resets / timeouts / 5xx are
    absorbed with jittered backoff, a dead driver trips the per-peer
    circuit breaker so callers fail fast instead of stalling the gang,
    and every absorbed flake is a ``retry.kv.request.*`` counter on
    ``/metrics``. All KV verbs are idempotent (GET, last-write-wins
    PUT, scope DELETE), so re-sending after an ambiguous failure is
    safe by construction."""

    # polling backoff cap for wait(): a worker parked on a slow key
    # settles at ~1 req/s instead of 20/s (12k hits per worker over a
    # 600s start_timeout was the pre-retry behavior)
    WAIT_BACKOFF_CAP_S = 1.0

    def __init__(
        self,
        addr: str,
        port: int,
        secret_key: Optional[bytes] = None,
        retry: Optional[RetryPolicy] = None,
    ) -> None:
        self._base = f"http://{addr}:{port}"
        self._secret_key = secret_key
        self._retry = retry or RetryPolicy.from_env("kv.request")

    def _request_once(self, method: str, path: str, body: bytes = b""):
        import urllib.error
        import urllib.request

        _chaos.inject("kv.request")
        req = urllib.request.Request(
            self._base + path, data=body if method == "PUT" else None,
            method=method,
        )
        if self._secret_key is not None:
            req.add_header(
                "X-Horovod-Digest",
                sign(
                    self._secret_key, method.encode() + path.encode() + body
                ).hex(),
            )
        try:
            with urllib.request.urlopen(
                req, timeout=self._retry.attempt_timeout_s
            ) as resp:
                return resp.status, resp.read()
        except urllib.error.HTTPError as e:
            if e.code == 429 or 500 <= e.code <= 599:
                raise  # transient server-side failure: retryable
            return e.code, b""

    def _request(self, method: str, path: str, body: bytes = b""):
        """One KV exchange under the retry policy. Raises
        ``RetryError`` (a ``ConnectionError``) on exhaustion and
        ``CircuitOpenError`` once the driver's endpoint is known-dead —
        both land in the callers' existing ``except OSError`` paths."""
        return self._retry.call(
            self._request_once, method, path, body, peer=self._base
        )

    def put(self, scope: str, key: str, value: bytes) -> None:
        status, _ = self._request("PUT", f"/kv/{scope}/{key}", value)
        if status != 200:
            raise RuntimeError(f"rendezvous PUT failed with HTTP {status}")

    def get(self, scope: str, key: str) -> Optional[bytes]:
        status, body = self._request("GET", f"/kv/{scope}/{key}")
        return body if status == 200 else None

    def wait(
        self,
        scope: str,
        key: str,
        timeout: float = 30.0,
        interval: Optional[float] = None,
        should_stop=None,
    ) -> bytes:
        """Poll until the key appears — the worker-side rendezvous loop.

        The poll interval follows the shared jittered-doubling backoff
        (``interval`` seeds it, default 0.05s, capped at ~1s), so a
        worker parked behind a 600s ``start_timeout`` costs the driver
        ~O(600) requests instead of ~12k. ``should_stop`` (a callable)
        aborts the wait early — the elastic worker passes its shutdown
        event so a driver teardown doesn't leave pollers spinning to
        their full deadline; a tripped KV circuit (driver gone) aborts
        it the same way."""
        import time

        deadline = time.monotonic() + timeout
        delays = backoff_delays(
            0.05 if interval is None else float(interval),
            self.WAIT_BACKOFF_CAP_S,
        )
        while True:
            # shutdown first: a latched abort must not pay one more
            # KV exchange (against a hung driver that is a full retry
            # ladder of the preemption grace window)
            if should_stop is not None and should_stop():
                raise RuntimeError(
                    f"rendezvous wait for {scope}/{key} aborted: "
                    f"shutdown requested"
                )
            # per-POLL injection site (a plan can flake iteration N of
            # a long wait, not just the call as a whole)
            _chaos.inject("kv.wait")
            value = self.get(scope, key)
            if value is not None:
                return value
            now = time.monotonic()
            if now >= deadline:
                raise TimeoutError(
                    f"rendezvous key {scope}/{key} not published in {timeout}s"
                )
            time.sleep(min(next(delays), deadline - now))

    def keys(self, scope: str) -> List[str]:
        status, body = self._request("GET", f"/scope/{scope}")
        return json.loads(body) if status == 200 else []


# Worker-side shutdown latch: once set (elastic worker teardown, or the
# preemption handler's SIGTERM), every in-flight KV poll loop aborts at
# its next iteration instead of spinning to its full deadline — a dying
# process must not keep hammering the driver's KV for up to 600s.
_poll_shutdown = threading.Event()


def request_poll_shutdown() -> None:
    _poll_shutdown.set()


def reset_poll_shutdown() -> None:
    """Re-arm after an elastic re-init (the process lives on)."""
    _poll_shutdown.clear()


_broadcast_counts: Dict[str, int] = {}


def broadcast_via_kv(obj, root_rank: int = 0, name: Optional[str] = None):
    """Object broadcast through the job's rendezvous KV store — the
    multi-controller backend of ``hvd.broadcast_object`` (ref:
    horovod/torch/functions.py broadcast_object, pickle-over-collective
    [V]). The process owning ``root_rank`` publishes the pickled object;
    everyone else polls for it. The channel is HMAC-authenticated with
    the per-job secret, which is what makes pickle acceptable here: only
    holders of the job secret can publish payloads.
    """
    import pickle

    from ..common import basics

    cfg = basics.get_config()
    if not cfg.rendezvous_addr or not cfg.rendezvous_port:
        raise RuntimeError(
            "broadcast_object across processes needs the runner's "
            "rendezvous (HOROVOD_GLOO_RENDEZVOUS_ADDR/PORT not set)"
        )
    client = _client_from_cfg(cfg)
    # Broadcast is a collective: every process calls it in the same
    # order, so a per-name call counter is identical everywhere. Folding
    # it into the key makes each round a fresh key — a reused explicit
    # ``name`` must not hand non-root processes the previous round's
    # payload.
    base = "broadcast_object" if name is None else name
    count = _broadcast_counts.get(base, 0)
    _broadcast_counts[base] = count + 1
    name = f"{base}.{count}"
    topo = basics.topology()
    lead = topo.rank
    owns_root = lead <= root_rank < lead + topo.local_size
    if owns_root:
        client.put("broadcast", name, pickle.dumps(obj))
        return obj
    payload = client.wait(
        "broadcast", name, timeout=cfg.gloo_timeout_seconds,
        should_stop=_poll_shutdown.is_set,
    )
    return pickle.loads(payload)


# ------------------------------------------------------------- heartbeats
# Worker→driver liveness over the KV channel (the rebuilt signal for the
# stall inspector's cross-process half — stall_inspector.cc reports
# "ranks absent" [V]; here absence = heartbeat staleness).

HEARTBEAT_SCOPE = "heartbeat"


def put_heartbeat(
    client: "RendezvousClient", rank: int, stats: Optional[dict] = None
) -> None:
    """Stamp this worker's liveness. Call on a timer (the elastic worker
    loop does; any long-running worker can).

    ``stats`` piggybacks the straggler-ledger payload from the worker's
    flight recorder (``common.telemetry.heartbeat_stats()``: ``step``,
    ``step_ms_p50``, ``last_step_ts``) onto the same KV write — the
    driver-side StallInspector uses it to tell SLOW ranks from SILENT
    ones. The payload is JSON ``{"ts": ..., **stats}``; readers still
    accept the legacy bare-float form."""
    import time as _time

    payload = {"ts": _time.time()}
    if stats:
        payload.update(stats)
    client.put(
        HEARTBEAT_SCOPE, str(int(rank)), json.dumps(payload).encode()
    )


def _parse_heartbeat(raw: bytes) -> Optional[dict]:
    """One heartbeat value → dict with at least ``ts``. Accepts the
    JSON payload and the legacy ``repr(time.time())`` float."""
    try:
        text = raw.decode()
    except UnicodeDecodeError:
        return None
    try:
        obj = json.loads(text)
    except json.JSONDecodeError:
        obj = None
    if isinstance(obj, dict) and "ts" in obj:
        try:
            obj["ts"] = float(obj["ts"])
        except (TypeError, ValueError):
            return None
        return obj
    try:
        return {"ts": float(text)}
    except ValueError:
        return None


def read_heartbeats(store_or_client) -> Dict[int, float]:
    """Driver side: {rank: unix_ts} of every heartbeat present. Accepts
    the in-process KVStore or a RendezvousClient."""
    return {
        r: s["ts"] for r, s in read_heartbeat_stats(store_or_client).items()
    }


def read_heartbeat_stats(store_or_client) -> Dict[int, dict]:
    """Driver side of the straggler ledger: {rank: payload} with at
    least ``ts``, plus whatever telemetry the worker piggybacked
    (``step``, ``step_ms_p50``, ``last_step_ts``)."""
    out: Dict[int, dict] = {}
    for key in store_or_client.keys(HEARTBEAT_SCOPE):
        raw = store_or_client.get(HEARTBEAT_SCOPE, key)
        if raw is None:
            continue
        try:
            rank = int(key)
        except ValueError:
            continue
        parsed = _parse_heartbeat(raw)
        if parsed is not None:
            out[rank] = parsed
    return out


AUDIT_SCOPE = "audit"


def put_audit(
    client: "RendezvousClient", rank: int, step: int, digest: str
) -> None:
    """Worker side of the parameter-audit ledger (audit.py): publish
    this rank's newest tree digest. One KV key per rank, overwritten
    per audit — the driver only ever compares the latest round."""
    import time as _time

    payload = {"ts": _time.time(), "step": int(step), "digest": str(digest)}
    client.put(AUDIT_SCOPE, str(int(rank)), json.dumps(payload).encode())


def read_audit_digests(store_or_client) -> Dict[int, dict]:
    """Driver side: ``{rank: {"ts", "step", "digest"}}`` of every
    published audit entry. Malformed entries are skipped — a corrupt
    audit record must not crash the auditor."""
    out: Dict[int, dict] = {}
    for key in store_or_client.keys(AUDIT_SCOPE):
        raw = store_or_client.get(AUDIT_SCOPE, key)
        if raw is None:
            continue
        try:
            rank = int(key)
            obj = json.loads(raw.decode())
        except (ValueError, UnicodeDecodeError):
            continue
        if isinstance(obj, dict) and "digest" in obj and "step" in obj:
            out[rank] = obj
    return out


SCHED_SCOPE = "sched"


def put_sched(
    client: "RendezvousClient",
    rank: int,
    step: int,
    fingerprint: str,
    dispatches: int,
    ring=None,
) -> None:
    """Worker side of the collective-schedule ledger
    (analysis/sched_audit.py): publish this rank's rolling schedule
    fingerprint, total dispatch count, and the bounded ring of recent
    per-dispatch digests (``[[index, digest], ...]`` — how the driver
    recovers the FIRST divergent dispatch). One KV key per rank,
    overwritten per audit round, scope dropped per gang launch beside
    the parameter digests."""
    import time as _time

    payload = {
        "ts": _time.time(),
        "step": int(step),
        "fingerprint": str(fingerprint),
        "dispatches": int(dispatches),
        "ring": [[int(i), str(d)] for i, d in (ring or [])],
    }
    client.put(SCHED_SCOPE, str(int(rank)), json.dumps(payload).encode())


def read_sched_fingerprints(store_or_client) -> Dict[int, dict]:
    """Driver side: ``{rank: {"ts", "step", "fingerprint",
    "dispatches", "ring"}}``. Malformed entries are skipped — a
    corrupt schedule record must not crash the auditor."""
    out: Dict[int, dict] = {}
    for key in store_or_client.keys(SCHED_SCOPE):
        raw = store_or_client.get(SCHED_SCOPE, key)
        if raw is None:
            continue
        try:
            rank = int(key)
            obj = json.loads(raw.decode())
        except (ValueError, UnicodeDecodeError):
            continue
        if isinstance(obj, dict) and "fingerprint" in obj and "step" in obj:
            out[rank] = obj
    return out


REBALANCE_SCOPE = "rebalance"


def put_rebalance_weights(
    store_or_client, weights: Dict[int, float], epoch: int = 0
) -> None:
    """Driver side of straggler-aware scheduling (HOROVOD_REBALANCE):
    publish the gang's micro-batch weight map — ``weights[r]`` in
    (0, 1], 1.0 = full share, <1 = the driver wants rank r's slice to
    take proportionally less work because its step p50 STAYS flagged
    by the straggler ledger. One KV blob, overwritten per update —
    workers only ever apply the newest map."""
    import time as _time

    payload = {
        "ts": _time.time(),
        "epoch": int(epoch),
        "weights": {str(int(r)): float(w) for r, w in weights.items()},
    }
    store_or_client.put(
        REBALANCE_SCOPE, "weights", json.dumps(payload).encode()
    )


def read_rebalance_weights(store_or_client) -> Dict[int, float]:
    """Worker side: ``{rank: weight}`` of the newest published map, or
    ``{}`` when the driver never published one (rebalance off, or no
    straggler ever stayed flagged). Malformed blobs read as {} — a
    corrupt scheduling hint must never stall training."""
    raw = store_or_client.get(REBALANCE_SCOPE, "weights")
    if raw is None:
        return {}
    try:
        obj = json.loads(raw.decode())
        return {
            int(r): float(w)
            for r, w in obj.get("weights", {}).items()
        }
    except (ValueError, UnicodeDecodeError, AttributeError):
        return {}


EXPERT_LOAD_SCOPE = "expert_load"


def put_expert_load(
    store_or_client,
    rank: int,
    expert_tokens,
    dropped: float,
    total: float,
    capacity_factor: Optional[float] = None,
) -> None:
    """Worker side of the expert-load ledger (PR 12 — the PR 10
    rebalance plumbing generalized from step-time to expert load):
    publish this rank's newest per-expert kept-token histogram plus
    overflow counters (``parallel/moe.py`` MoEStats, host floats). One
    KV key per rank, overwritten per publication — the driver only
    ever aggregates the latest round. Hot experts ARE stragglers; this
    is how the scheduler sees them before step time does."""
    import time as _time

    payload = {
        "ts": _time.time(),
        "expert_tokens": [float(t) for t in expert_tokens],
        "dropped": float(dropped),
        "total": float(total),
    }
    if capacity_factor is not None:
        payload["capacity_factor"] = float(capacity_factor)
    store_or_client.put(
        EXPERT_LOAD_SCOPE, str(int(rank)), json.dumps(payload).encode()
    )


def read_expert_loads(store_or_client) -> Dict[int, dict]:
    """Driver side: ``{rank: {"ts", "expert_tokens", "dropped",
    "total", ...}}`` of every published load summary. Malformed
    entries are skipped — a corrupt scheduling hint must never crash
    the driver."""
    out: Dict[int, dict] = {}
    for key in store_or_client.keys(EXPERT_LOAD_SCOPE):
        raw = store_or_client.get(EXPERT_LOAD_SCOPE, key)
        if raw is None:
            continue
        try:
            rank = int(key)
            obj = json.loads(raw.decode())
        except (ValueError, UnicodeDecodeError):
            continue
        if (
            isinstance(obj, dict)
            and isinstance(obj.get("expert_tokens"), list)
            and "total" in obj
        ):
            out[rank] = obj
    return out


STANDBY_SCOPE = "standby"
RESTART_SCOPE = "restart"


def put_standby(
    store_or_client,
    hostname: str,
    state: str,
    detail: Optional[dict] = None,
) -> None:
    """Standby-warmer side of the warm-standby lifecycle
    (elastic/standby.py): publish this host's standby state —
    ``announce`` (registered, staging not started), ``staging``
    (deserializing executables / loading the checkpoint), ``armed``
    (ready to swap in), ``released`` (the driver folded it into a
    gang). One KV key per hostname, overwritten per transition, ``ts``
    refreshed by the warmer's keepalive loop so the driver can age out
    a dead warmer."""
    import time as _time

    payload = {"ts": _time.time(), "state": str(state)}
    if detail:
        payload.update(detail)
    store_or_client.put(
        STANDBY_SCOPE, str(hostname), json.dumps(payload).encode()
    )


def read_standbys(store_or_client) -> Dict[str, dict]:
    """Driver side: ``{hostname: {"ts", "state", ...}}`` of every
    published standby announcement. Malformed entries are skipped — a
    corrupt announcement must never crash the driver's poll loop."""
    out: Dict[str, dict] = {}
    for key in store_or_client.keys(STANDBY_SCOPE):
        raw = store_or_client.get(STANDBY_SCOPE, key)
        if raw is None:
            continue
        try:
            obj = json.loads(raw.decode())
        except (ValueError, UnicodeDecodeError):
            continue
        if isinstance(obj, dict) and "state" in obj:
            out[key] = obj
    return out


def put_restart_stamp(
    store_or_client,
    epoch: int,
    reason: str,
    warm: bool = False,
    kind: str = "restart",
) -> None:
    """Driver side of the restart clock: stamped at gang-teardown time
    (``_reset``), read by every worker of the NEXT epoch at init —
    ``now - ts`` is that worker's ``elastic.restart_ms`` (or
    ``serve.scaleup_ms`` when ``kind == "scaleup"``). ``warm`` records
    whether a warm standby absorbed the restart, so the gauge can be
    compared against the cold baseline."""
    import time as _time

    payload = {
        "ts": _time.time(),
        "epoch": int(epoch),
        "reason": str(reason),
        "warm": bool(warm),
        "kind": str(kind),
    }
    store_or_client.put(
        RESTART_SCOPE, "stamp", json.dumps(payload).encode()
    )


def read_restart_stamp(store_or_client) -> Optional[dict]:
    """Worker side: the newest restart stamp, or None (first launch /
    malformed blob — a corrupt stamp must never fail worker init)."""
    raw = store_or_client.get(RESTART_SCOPE, "stamp")
    if raw is None:
        return None
    try:
        obj = json.loads(raw.decode())
    except (ValueError, UnicodeDecodeError):
        return None
    if isinstance(obj, dict) and "ts" in obj and "epoch" in obj:
        return obj
    return None


def put_dead_hosts(store_or_client, hosts, ranks=()) -> None:
    """Driver side of the dead-set channel: publish the blacklisted/
    quarantined host set into the SERVE scope (key ``dead_hosts`` — a
    non-numeric key, so ``read_announcements`` skips it by
    construction) so the serving Router evicts a dead worker's
    announcement IMMEDIATELY instead of waiting out the freshness
    window. ``ranks`` carries the worker ranks the driver mapped onto
    those hosts at publication time (announcements are keyed by rank;
    the host name is the fallback match)."""
    import time as _time

    payload = {
        "ts": _time.time(),
        "hosts": sorted(str(h) for h in hosts),
        "ranks": sorted(int(r) for r in ranks),
    }
    store_or_client.put(
        "serve", "dead_hosts", json.dumps(payload).encode()
    )


def read_dead_hosts(store_or_client) -> Dict[str, list]:
    """Router side: ``{"hosts": [...], "ranks": [...]}`` — empty lists
    on first launch or a malformed blob (the dead set accelerates
    eviction; a corrupt one must never break routing)."""
    raw = store_or_client.get("serve", "dead_hosts")
    if raw is None:
        return {"hosts": [], "ranks": []}
    try:
        obj = json.loads(raw.decode())
    except (ValueError, UnicodeDecodeError):
        return {"hosts": [], "ranks": []}
    if not isinstance(obj, dict):
        return {"hosts": [], "ranks": []}
    return {
        "hosts": [str(h) for h in obj.get("hosts", ()) or ()],
        "ranks": [
            int(r) for r in obj.get("ranks", ()) or ()
            if isinstance(r, (int, float, str)) and str(r).lstrip("-").isdigit()
        ],
    }


def _client_from_cfg(cfg) -> "RendezvousClient":
    """Shared construction of the worker-side KV client from config
    (secret decode + endpoint) — used by the object collectives and the
    version guard alike."""
    secret = (
        bytes.fromhex(cfg.secret_key_hex) if cfg.secret_key_hex else None
    )
    return RendezvousClient(
        cfg.rendezvous_addr, cfg.rendezvous_port, secret_key=secret
    )


def check_version_consistency(cfg, topology, log=None) -> None:
    """Fail fast when gang members run different horovod_tpu versions
    (ref: the launch driver's same-version probe across hosts,
    horovod/runner/driver/driver_service.py [V] — there it happens
    before launch; here each worker checks itself against the lead
    worker at init over the rendezvous KV, which catches the same skew
    without an extra pre-launch RPC round).

    Non-root workers publish their version and compare against rank 0's
    (pairwise-to-root detects any skew). A TIMEOUT waiting for rank 0
    only warns — the check must never turn a slow coordinator into a
    hard failure — but an actual mismatch raises, because a skewed gang
    fails later in far less diagnosable ways (wire-format or op-surface
    drift mid-training).
    """
    import os as _os

    import horovod_tpu

    if not cfg.rendezvous_addr or not cfg.rendezvous_port:
        return
    mine = getattr(horovod_tpu, "__version__", "unknown")
    client = _client_from_cfg(cfg)
    # Scope keyed by the elastic epoch: the KV server outlives worker
    # gangs across elastic restarts, and a stale 'version/0' from a
    # previous incarnation would either fake a skew (gang upgraded
    # between epochs) or mask a real one.
    scope = f"version.{_os.environ.get('HOROVOD_ELASTIC_EPOCH', '0')}"
    try:
        client.put(scope, str(topology.rank), mine.encode())
        if topology.rank == 0:
            return
        raw = client.wait(
            scope, "0",
            timeout=min(30.0, float(cfg.gloo_timeout_seconds)),
            should_stop=_poll_shutdown.is_set,
        )
    except TimeoutError:
        if log is not None:
            log.warning(
                "version check: rank 0 did not publish within the "
                "window; skipping (my version %s)", mine,
            )
        return
    except (OSError, RuntimeError) as e:
        # RuntimeError = non-200 from the KV (auth skew mid-re-key,
        # transient 500). The guard's contract: only an actual version
        # MISMATCH may fail init; rendezvous trouble warns.
        if log is not None:
            log.warning("version check skipped (rendezvous: %s)", e)
        return
    lead_version = raw.decode()
    if lead_version != mine:
        raise RuntimeError(
            f"horovod_tpu version skew in the gang: rank "
            f"{topology.rank} runs {mine} but rank 0 runs "
            f"{lead_version}. Install the same version on every host "
            f"(the reference's driver enforces this before launch "
            f"[V])."
        )


def allgather_via_kv(obj, name: Optional[str] = None):
    """Object allgather through the rendezvous KV — the multi-controller
    backend of ``hvd.allgather_object`` (ref: horovod/torch/functions.py
    allgather_object [V]). Every process publishes its pickled object
    under its lead rank; all poll until the full set is present. Same
    HMAC trust model as broadcast_via_kv."""
    import pickle

    from ..common import basics

    cfg = basics.get_config()
    if not cfg.rendezvous_addr or not cfg.rendezvous_port:
        raise RuntimeError(
            "allgather_object across processes needs the runner's "
            "rendezvous (HOROVOD_GLOO_RENDEZVOUS_ADDR/PORT not set)"
        )
    client = _client_from_cfg(cfg)
    base = "allgather_object" if name is None else name
    count = _broadcast_counts.get(base, 0)
    _broadcast_counts[base] = count + 1
    scope = f"{base}.{count}"
    topo = basics.topology()
    client.put(scope, str(topo.rank), pickle.dumps(obj))
    out = []
    for r in range(topo.cross_size):
        lead = r * topo.local_size
        payload = client.wait(
            scope, str(lead), timeout=cfg.gloo_timeout_seconds,
            should_stop=_poll_shutdown.is_set,
        )
        # One entry PER RANK (size, not cross_size): each controller
        # speaks for local_size ranks, so its payload repeats — the
        # same contract as the single-controller [obj]*size path.
        out.extend([pickle.loads(payload)] * topo.local_size)
    return out
