// Gaussian-process regression core for the fusion autotuner.
//
// TPU-native rebuild of the reference's autotune math (ref:
// horovod/common/optim/gaussian_process.cc +
// optim/bayesian_optimization.cc — SURVEY.md §2.1; the reference builds
// this on Eigen + LBFGS in C++). Same model as the Python fallback in
// horovod_tpu/common/autotune.py::GaussianProcess and a drop-in for it:
// RBF kernel on unit-box-normalized inputs, y standardized, noise^2 on
// the diagonal, Cholesky solve; predictive variance clipped at 1e-12.
// Candidate scoring (expected improvement over a sampled box) stays in
// Python — at <=20 samples x 256 candidates the win is the O(n^3)
// refits, which happen on the dispatch path every sample window.

#include "export.h"

#include <cmath>
#include <vector>

namespace {

struct GP {
  double noise;
  double length_scale;
  long n = 0, d = 0;
  double y_mean = 0.0, y_std = 1.0;
  std::vector<double> x;      // n*d row-major training inputs
  std::vector<double> chol;   // n*n lower-triangular L
  std::vector<double> alpha;  // K^-1 y_norm
};

// RBF kernel between rows a (len d) and b (len d).
double kernel(const GP& gp, const double* a, const double* b) {
  double d2 = 0.0;
  for (long j = 0; j < gp.d; ++j) {
    double diff = a[j] - b[j];
    d2 += diff * diff;
  }
  return std::exp(-0.5 * d2 / (gp.length_scale * gp.length_scale));
}

// In-place Cholesky of the n*n matrix in gp.chol. Returns false if a
// pivot goes non-positive (matrix not PD).
bool cholesky(std::vector<double>& m, long n) {
  for (long i = 0; i < n; ++i) {
    for (long j = 0; j <= i; ++j) {
      double sum = m[i * n + j];
      for (long k = 0; k < j; ++k) sum -= m[i * n + k] * m[j * n + k];
      if (i == j) {
        if (sum <= 0.0) return false;
        m[i * n + j] = std::sqrt(sum);
      } else {
        m[i * n + j] = sum / m[j * n + j];
      }
    }
    for (long j = i + 1; j < n; ++j) m[i * n + j] = 0.0;
  }
  return true;
}

void solve_lower(const std::vector<double>& l, long n, double* b) {
  for (long i = 0; i < n; ++i) {
    double sum = b[i];
    for (long k = 0; k < i; ++k) sum -= l[i * n + k] * b[k];
    b[i] = sum / l[i * n + i];
  }
}

void solve_upper_t(const std::vector<double>& l, long n, double* b) {
  // Solves L^T z = b given lower-triangular L.
  for (long i = n - 1; i >= 0; --i) {
    double sum = b[i];
    for (long k = i + 1; k < n; ++k) sum -= l[k * n + i] * b[k];
    b[i] = sum / l[i * n + i];
  }
}

}  // namespace

HVD_EXPORT void* hvd_gp_create(double noise, double length_scale) {
  auto* gp = new GP();
  gp->noise = noise;
  gp->length_scale = length_scale;
  return gp;
}

HVD_EXPORT void hvd_gp_destroy(void* h) { delete static_cast<GP*>(h); }

// Fit on n observations of dimension d. Returns 0 on success, 1 if the
// kernel matrix is not positive definite.
HVD_EXPORT int hvd_gp_fit(void* h, const double* x, const double* y, long n,
                          long d) {
  auto* gp = static_cast<GP*>(h);
  gp->n = n;
  gp->d = d;
  gp->x.assign(x, x + n * d);

  double mean = 0.0;
  for (long i = 0; i < n; ++i) mean += y[i];
  mean /= static_cast<double>(n);
  double var = 0.0;
  for (long i = 0; i < n; ++i) var += (y[i] - mean) * (y[i] - mean);
  double std = std::sqrt(var / static_cast<double>(n));
  if (std == 0.0) std = 1.0;
  gp->y_mean = mean;
  gp->y_std = std;

  gp->chol.assign(n * n, 0.0);
  for (long i = 0; i < n; ++i) {
    for (long j = 0; j < n; ++j) {
      gp->chol[i * n + j] = kernel(*gp, &gp->x[i * d], &gp->x[j * d]);
    }
    gp->chol[i * n + i] += gp->noise * gp->noise;
  }
  if (!cholesky(gp->chol, n)) return 1;

  gp->alpha.resize(n);
  for (long i = 0; i < n; ++i) gp->alpha[i] = (y[i] - mean) / std;
  solve_lower(gp->chol, n, gp->alpha.data());
  solve_upper_t(gp->chol, n, gp->alpha.data());
  return 0;
}

// Predict mean and stddev at m query points (m*d row-major).
HVD_EXPORT int hvd_gp_predict(void* h, const double* xq, long m, double* mu,
                              double* sigma) {
  auto* gp = static_cast<GP*>(h);
  if (gp->n == 0) return 1;
  long n = gp->n, d = gp->d;
  std::vector<double> ks(n);
  for (long q = 0; q < m; ++q) {
    for (long i = 0; i < n; ++i) {
      ks[i] = kernel(*gp, &xq[q * d], &gp->x[i * d]);
    }
    double mean = 0.0;
    for (long i = 0; i < n; ++i) mean += ks[i] * gp->alpha[i];
    // v = L^-1 ks; var = k(x,x) - |v|^2, with k(x,x) = 1 for RBF.
    solve_lower(gp->chol, n, ks.data());
    double vv = 0.0;
    for (long i = 0; i < n; ++i) vv += ks[i] * ks[i];
    double var = 1.0 - vv;
    if (var < 1e-12) var = 1e-12;
    mu[q] = mean * gp->y_std + gp->y_mean;
    sigma[q] = std::sqrt(var) * gp->y_std;
  }
  return 0;
}
