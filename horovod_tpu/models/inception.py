"""Inception V3 — the reference's headline scaling model
(ref: docs/benchmarks.rst + the README scaling figure: Inception V3 at
~90% of linear on 128 GPUs [V]; BASELINE.md reference table row 1).

TPU-first choices: NHWC, bf16 compute, BN via the shared
``SyncBatchNorm`` (fp32 stats, fused bf16 normalize — models/resnet.py),
branch concatenation on the trailing (lane) axis so every tower feeds
the MXU without relayout. The factorized 7×1/1×7 and 3×1/1×3 towers are
kept — they are MXU-friendly (long contractions) — while the aux
classifier head is omitted (a training-regularizer, not a capability;
the reference's benchmark path doesn't exercise it either [V]).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional, Sequence

import flax.linen as nn
import jax.numpy as jnp

from .resnet import SyncBatchNorm


class ConvBN(nn.Module):
    features: int
    kernel: Sequence[int] = (3, 3)
    strides: Sequence[int] = (1, 1)
    padding: Any = "SAME"
    axis_name: Optional[str] = None
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Conv(
            self.features, tuple(self.kernel), strides=tuple(self.strides),
            padding=self.padding, use_bias=False, dtype=self.dtype,
        )(x)
        x = SyncBatchNorm(axis_name=self.axis_name, dtype=self.dtype)(
            x, use_running_average=not train
        )
        return nn.relu(x)


class InceptionA(nn.Module):
    pool_features: int
    axis_name: Optional[str] = None
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(ConvBN, axis_name=self.axis_name, dtype=self.dtype)
        b1 = conv(64, (1, 1))(x, train)
        b2 = conv(64, (5, 5))(conv(48, (1, 1))(x, train), train)
        b3 = conv(96, (3, 3))(
            conv(96, (3, 3))(conv(64, (1, 1))(x, train), train), train
        )
        pool = nn.avg_pool(x, (3, 3), (1, 1), padding="SAME")
        b4 = conv(self.pool_features, (1, 1))(pool, train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionB(nn.Module):
    """Grid reduction 35→17."""

    axis_name: Optional[str] = None
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(ConvBN, axis_name=self.axis_name, dtype=self.dtype)
        b1 = conv(384, (3, 3), strides=(2, 2), padding="VALID")(x, train)
        b2 = conv(96, (3, 3), strides=(2, 2), padding="VALID")(
            conv(96, (3, 3))(conv(64, (1, 1))(x, train), train), train
        )
        pool = nn.max_pool(x, (3, 3), (2, 2))
        return jnp.concatenate([b1, b2, pool], axis=-1)


class InceptionC(nn.Module):
    """Factorized 7×7 towers."""

    channels_7x7: int
    axis_name: Optional[str] = None
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(ConvBN, axis_name=self.axis_name, dtype=self.dtype)
        c7 = self.channels_7x7
        b1 = conv(192, (1, 1))(x, train)
        b2 = conv(c7, (1, 1))(x, train)
        b2 = conv(c7, (1, 7))(b2, train)
        b2 = conv(192, (7, 1))(b2, train)
        b3 = conv(c7, (1, 1))(x, train)
        b3 = conv(c7, (7, 1))(b3, train)
        b3 = conv(c7, (1, 7))(b3, train)
        b3 = conv(c7, (7, 1))(b3, train)
        b3 = conv(192, (1, 7))(b3, train)
        pool = nn.avg_pool(x, (3, 3), (1, 1), padding="SAME")
        b4 = conv(192, (1, 1))(pool, train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionD(nn.Module):
    """Grid reduction 17→8."""

    axis_name: Optional[str] = None
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(ConvBN, axis_name=self.axis_name, dtype=self.dtype)
        b1 = conv(320, (3, 3), strides=(2, 2), padding="VALID")(
            conv(192, (1, 1))(x, train), train
        )
        b2 = conv(192, (1, 1))(x, train)
        b2 = conv(192, (1, 7))(b2, train)
        b2 = conv(192, (7, 1))(b2, train)
        b2 = conv(192, (3, 3), strides=(2, 2), padding="VALID")(b2, train)
        pool = nn.max_pool(x, (3, 3), (2, 2))
        return jnp.concatenate([b1, b2, pool], axis=-1)


class InceptionE(nn.Module):
    """Expanded 8×8 blocks with split 1×3 / 3×1 branches."""

    axis_name: Optional[str] = None
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(ConvBN, axis_name=self.axis_name, dtype=self.dtype)
        b1 = conv(320, (1, 1))(x, train)
        b2 = conv(384, (1, 1))(x, train)
        b2 = jnp.concatenate(
            [conv(384, (1, 3))(b2, train), conv(384, (3, 1))(b2, train)],
            axis=-1,
        )
        b3 = conv(448, (1, 1))(x, train)
        b3 = conv(384, (3, 3))(b3, train)
        b3 = jnp.concatenate(
            [conv(384, (1, 3))(b3, train), conv(384, (3, 1))(b3, train)],
            axis=-1,
        )
        pool = nn.avg_pool(x, (3, 3), (1, 1), padding="SAME")
        b4 = conv(192, (1, 1))(pool, train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionV3(nn.Module):
    num_classes: int = 1000
    axis_name: Optional[str] = None
    dtype: Any = jnp.bfloat16
    dropout: float = 0.5

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(ConvBN, axis_name=self.axis_name, dtype=self.dtype)
        x = x.astype(self.dtype)
        # Stem: 299 -> 35 spatial.
        x = conv(32, (3, 3), strides=(2, 2), padding="VALID")(x, train)
        x = conv(32, (3, 3), padding="VALID")(x, train)
        x = conv(64, (3, 3))(x, train)
        x = nn.max_pool(x, (3, 3), (2, 2))
        x = conv(80, (1, 1), padding="VALID")(x, train)
        x = conv(192, (3, 3), padding="VALID")(x, train)
        x = nn.max_pool(x, (3, 3), (2, 2))
        # 3x InceptionA
        x = InceptionA(32, self.axis_name, self.dtype)(x, train)
        x = InceptionA(64, self.axis_name, self.dtype)(x, train)
        x = InceptionA(64, self.axis_name, self.dtype)(x, train)
        x = InceptionB(self.axis_name, self.dtype)(x, train)
        # 4x InceptionC
        for c7 in (128, 160, 160, 192):
            x = InceptionC(c7, self.axis_name, self.dtype)(x, train)
        x = InceptionD(self.axis_name, self.dtype)(x, train)
        x = InceptionE(self.axis_name, self.dtype)(x, train)
        x = InceptionE(self.axis_name, self.dtype)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        return nn.Dense(self.num_classes, dtype=jnp.float32)(
            x.astype(jnp.float32)
        )
