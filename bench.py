"""Synthetic ResNet-50 benchmark — parity with the reference's headline
harness (ref: examples/pytorch/pytorch_synthetic_benchmark.py [V]:
ResNet-50, synthetic ImageNet batches, reports img/sec; BASELINE.md
north star tracks the same metric on TPU).

Prints ONE JSON line:
  {"metric": "resnet50_synth_img_per_sec", "value": N, "unit": "img/s",
   "vs_baseline": R, "platform": "...", "mfu": M, "tflops_per_sec": T}

vs_baseline compares against the canonical single-P100 fp32 ResNet-50
throughput (~219 img/s, the tf_cnn_benchmarks number contemporaneous with
the reference's published scaling figures — BASELINE.md [V]): the
reference's own benchmark prints absolute img/sec per device, so the
honest single-chip comparison is chip vs chip. MFU is measured FLOP/s
(XLA cost analysis of the compiled train step) over the chip's peak
bf16 FLOP/s.

Resilience: the default invocation is an ORCHESTRATOR that runs the
measurement in a fresh subprocess (BENCH_INNER=1), retrying with backoff
when the TPU backend is unavailable (the sandbox's known stuck-chip-claim
failure mode — BENCH_r01 died on first touch with rc=1). If every TPU
attempt fails it falls back to a small CPU run and reports it honestly
(platform=cpu + error note), so the driver always gets a parseable line.

Env knobs: BENCH_BATCH (default 256 — measured-best MXU utilization on
the v5e-class chip; the reference harness defaults to 32, which here
leaves ~15% throughput on the table), BENCH_ITERS, BENCH_WARMUP,
BENCH_PLATFORM=cpu to force the host platform, BENCH_ATTEMPTS,
BENCH_ATTEMPT_TIMEOUT (s, per attempt — must outlast a chip-claim
queue cycle), BENCH_TOTAL_BUDGET (s, whole-orchestration cap: further
attempts start only while a full window fits, then the CPU fallback
runs within what remains), BENCH_PEAK_TFLOPS to override the MFU
denominator.
"""

import json
import os
import subprocess
import sys
import time

P100_FP32_IMG_PER_SEC = 219.0

from _benchlib import aot_compile as _aot_compile  # noqa: E402
from _benchlib import mfu_fields as _mfu_fields  # noqa: E402


def inner_main():
    model_name = os.environ.get("BENCH_MODEL", "resnet50")
    batch = int(os.environ.get("BENCH_BATCH", "256"))
    n_iters = int(os.environ.get("BENCH_ITERS", "20"))
    n_warmup = int(os.environ.get("BENCH_WARMUP", "3"))

    import jax

    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

    import jax.numpy as jnp
    import numpy as np
    import optax
    from functools import partial

    # The reference's synthetic-benchmark model family
    # (docs/benchmarks.rst: ResNet-50/101, Inception V3, VGG-16 [V]).
    from horovod_tpu import models as model_zoo

    image_size = 224
    stem = os.environ.get("BENCH_STEM", "conv7")  # or space_to_depth
    if model_name == "resnet50":
        model = model_zoo.ResNet50(dtype=jnp.bfloat16, stem=stem)
    elif model_name == "resnet101":
        model = model_zoo.ResNet101(dtype=jnp.bfloat16, stem=stem)
    elif model_name == "inception_v3":
        model = model_zoo.InceptionV3(dtype=jnp.bfloat16)
        image_size = 299
    elif model_name == "vgg16":
        model = model_zoo.VGG16(dtype=jnp.bfloat16)
    elif model_name == "vit_b16":
        # BASELINE.json config #5's model (the elastic-bench pairing);
        # LayerNorm-based, so the batch_stats collection stays empty.
        model = model_zoo.ViT(model_zoo.ViTConfig.b16())
    else:
        raise SystemExit(f"unknown BENCH_MODEL {model_name!r}")

    platform = jax.devices()[0].platform
    rng = jax.random.PRNGKey(0)
    images = jnp.asarray(
        np.random.default_rng(0).uniform(
            size=(batch, image_size, image_size, 3)
        ),
        jnp.bfloat16,
    )
    labels = jnp.zeros((batch,), jnp.int32)
    variables = jax.jit(lambda: model.init(rng, images, train=False))()
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    opt = optax.sgd(0.01, momentum=0.9)
    opt_state = opt.init(params)

    # Donating the carried state lets XLA update params/opt-state in
    # place instead of allocating fresh buffers every step — the same
    # HBM-traffic discipline the fusion-buffer reuse gives the reference.
    dropout_rng = jax.random.PRNGKey(42)

    @partial(jax.jit, donate_argnums=(0, 1, 2))
    def train_step(params, batch_stats, opt_state, images, labels):
        def loss_fn(p):
            logits, mutated = model.apply(
                {"params": p, "batch_stats": batch_stats},
                images,
                train=True,
                mutable=["batch_stats"],
                rngs={"dropout": dropout_rng},
            )
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, labels
            ).mean()
            return loss, mutated.get("batch_stats", {})

        (loss, new_stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params
        )
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, new_stats, opt_state, loss

    train_step, flops = _aot_compile(
        train_step, params, batch_stats, opt_state, images, labels
    )

    from _benchlib import sync as _sync

    loss = None
    for _ in range(n_warmup):
        params, batch_stats, opt_state, loss = train_step(
            params, batch_stats, opt_state, images, labels
        )
    if loss is not None:
        # host transfer: the only trustworthy sync (see _benchlib)
        _sync(loss)

    t0 = time.perf_counter()
    for _ in range(n_iters):
        params, batch_stats, opt_state, loss = train_step(
            params, batch_stats, opt_state, images, labels
        )
    _sync(loss)  # loss chains through every step's params
    dt = time.perf_counter() - t0

    img_per_sec = batch * n_iters / dt
    result = {
        "metric": f"{model_name}_synth_img_per_sec",
        "value": round(img_per_sec, 2),
        "unit": "img/s",
        "vs_baseline": round(img_per_sec / P100_FP32_IMG_PER_SEC, 3),
        "platform": platform,
        "batch": batch,
    }
    result.update(_mfu_fields(flops, n_iters, dt, platform))
    print(json.dumps(result))


def _spawn(env, timeout):
    try:
        return subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired as e:
        def _txt(v):
            return v.decode(errors="replace") if isinstance(v, bytes) else (
                v or "")

        return subprocess.CompletedProcess(
            e.cmd, 124, _txt(e.stdout),
            _txt(e.stderr) + f"\n[timeout after {timeout}s]",
        )


def _extract_json(stdout):
    for line in reversed(stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def orchestrate():
    attempts = int(os.environ.get("BENCH_ATTEMPTS", "4"))
    # A legitimate run needs ~2 min (compile + measure); only a wedged
    # chip-claim queue ever reaches the timeout — and KILLING a claiming
    # client is what wedges the queue further (docs/perf.md, measured
    # 2026-07-30: each kill costs every later client ~20 min). So the
    # timeout must outlast the queue, not race it: 1800s rides out a
    # full wedge cycle instead of perpetuating it.
    timeout = float(os.environ.get("BENCH_ATTEMPT_TIMEOUT", "1800"))
    forced = os.environ.get("BENCH_PLATFORM")

    base_env = dict(os.environ)
    base_env["BENCH_INNER"] = "1"

    if forced:
        attempts = 1  # platform is explicit; no TPU-retry dance

    # Total-time budget (BENCH_TOTAL_BUDGET, s): during a multi-hour
    # backend outage the full ladder (4 x 30 min + backoffs) could
    # outlive the caller's own patience and die rc=124 with NO line at
    # all — worse than the honest platform=cpu fallback. Rules:
    # * further attempts start only when a FULL attempt window still
    #   fits (a truncated window would be killed mid-claim — the very
    #   queue-wedging the 30-min timeout exists to avoid — and could
    #   not have succeeded anyway);
    # * the check runs BEFORE the backoff sleep, not after;
    # * attempt 0 always runs (floored at 120s — a legitimate run
    #   needs ~2 min), so tiny budgets still get one real try;
    # * the CPU fallback's own timeout is capped by what's left but
    #   floored at 300s so a line always gets out — consequently a
    #   budget below ~420s can be EXCEEDED by up to that floor sum;
    #   size any outer watchdog to BENCH_TOTAL_BUDGET + 600s.
    total_budget = float(os.environ.get("BENCH_TOTAL_BUDGET", "4200"))
    cpu_headroom = 420.0
    t_start = time.monotonic()

    def _remaining() -> float:
        return total_budget - (time.monotonic() - t_start)

    last_err = ""
    for i in range(attempts):
        delay = 120.0 * i  # backoff for THIS attempt (0 for the first)
        if not forced and i > 0 and (
            _remaining() - cpu_headroom - delay < timeout
        ):
            print(
                f"bench: {total_budget - _remaining():.0f}s spent of "
                f"{total_budget:.0f}s budget; a full attempt window no "
                "longer fits — moving to the honest CPU fallback",
                file=sys.stderr,
            )
            break
        if i > 0:
            # Stale chip claims take many minutes to clear (measured
            # 2026-07-30: ~20 min per wedge cycle; the r02 ladder of
            # 30s+60s was hopeless). 120/240/360s between attempts on
            # top of the 30-min in-attempt patience.
            print(
                f"bench: attempt {i} failed, retrying in {delay:.0f}s "
                f"(TPU backend may be recovering a stale chip claim)",
                file=sys.stderr,
            )
            time.sleep(delay)
        attempt_timeout = timeout
        if not forced and i == 0:
            attempt_timeout = min(
                timeout, max(total_budget - cpu_headroom, 120.0)
            )
        proc = _spawn(base_env, attempt_timeout)
        parsed = _extract_json(proc.stdout or "")
        if proc.returncode == 0 and parsed is not None:
            print(json.dumps(parsed))
            return 0
        last_err = (proc.stderr or "")[-1500:] or (proc.stdout or "")[-1500:]

    cpu_err = ""
    if not forced:
        # All TPU attempts failed: fall back to a small honest CPU run
        # so the round still records a parseable measurement. Skipped
        # when the caller forced a platform — overriding an explicit
        # choice would mask a hard requirement.
        from _hermetic import hermetic_cpu_env

        cpu_env = hermetic_cpu_env(base=base_env)
        cpu_env["BENCH_PLATFORM"] = "cpu"
        cpu_env["BENCH_BATCH"] = os.environ.get("BENCH_CPU_BATCH", "32")
        cpu_env["BENCH_ITERS"] = os.environ.get("BENCH_CPU_ITERS", "3")
        cpu_env["BENCH_WARMUP"] = "1"
        # cap by what's left of the budget, but always leave enough to
        # actually emit a line (~5 min compile+run at the small batch)
        proc = _spawn(cpu_env, min(timeout, max(_remaining(), 300.0)))
        parsed = _extract_json(proc.stdout or "")
        if proc.returncode == 0 and parsed is not None:
            parsed["error"] = (
                "tpu backend unavailable after "
                f"{attempts} attempts; CPU fallback. last error: "
                + last_err[-400:]
            )
            print(json.dumps(parsed))
            return 0
        cpu_err = (proc.stderr or "")[-400:]

    # Emit a diagnostic line the driver can still parse.
    print(
        json.dumps(
            {
                "metric": os.environ.get("BENCH_MODEL", "resnet50")
                + "_synth_img_per_sec",
                "value": 0.0,
                "unit": "img/s",
                "vs_baseline": 0.0,
                "error": (
                    f"all attempts failed (platform="
                    f"{forced or 'tpu'}). last error: " + last_err[-400:]
                    + (" | cpu fallback error: " + cpu_err
                       if cpu_err else "")
                ),
            }
        )
    )
    return 1


if __name__ == "__main__":
    if os.environ.get("BENCH_INNER") == "1":
        inner_main()
    else:
        sys.exit(orchestrate())
