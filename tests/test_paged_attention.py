"""Paged flash-attention kernel (ops/paged_attention.py): op-level
≤1-ulp parity vs the gather-view dense oracle (scrambled page tables,
staggered multi-slot lengths, GQA, prefill chunks), engine-level greedy
token parity kernel-vs-gather (RoPE/GQA, post-eviction page reuse,
chunked long prompts), zero-retrace with the kernel on across rolling
admissions AND pool-exhaustion pauses, every rung of the fallback
ladder counted, sampled decode as pure DATA through the one decode
executable (temp-0 bitwise greedy, seeded reproducibility, top-k), and
the transfer-sender split regression (device_get off the scheduler
thread — decode-round latency independent of an in-flight transfer)."""

import json
import threading
import time
import warnings

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from horovod_tpu.common.metrics import registry as _metrics

# ---------------------------------------------------------------- fixtures


def _cfg(**kw):
    from horovod_tpu.models.transformer import TransformerConfig

    base = dict(
        vocab_size=61,
        num_layers=1,
        d_model=16,
        num_heads=2,
        d_ff=32,
        max_len=64,
        causal=True,
        dtype=jnp.float32,
    )
    base.update(kw)
    return TransformerConfig(**base)


def _toy(**cfg_kw):
    from horovod_tpu.models.transformer import Transformer

    model = Transformer(_cfg(**cfg_kw))
    params = model.init(
        jax.random.PRNGKey(0), jnp.ones((1, 4), jnp.int32), train=False
    )
    return model, params


@pytest.fixture(scope="module")
def toy():
    return _toy()


def _engine(toy, **kw):
    from horovod_tpu.serving.engine import InferenceEngine

    model, params = toy
    kw.setdefault("slots", 4)
    kw.setdefault("max_len", 64)
    kw.setdefault("min_bucket", 4)
    kw.setdefault("paged", True)
    kw.setdefault("page_tokens", 16)
    return InferenceEngine(model, params, **kw)


def _greedy_ref(model, params, prompt, n):
    seq = list(map(int, prompt))
    for _ in range(n):
        lg = model.apply(params, jnp.asarray([seq]), train=False)
        seq.append(int(np.asarray(lg)[0, -1].argmax()))
    return seq[len(prompt):]


def _generate(engine, slot, prompt, n):
    out = [engine.prefill(slot, prompt)]
    for _ in range(n - 1):
        toks = np.zeros(engine.slots, np.int32)
        toks[slot] = out[-1]
        nxt = engine.decode_step(toks)
        engine.manager.advance(slot)
        out.append(int(nxt[slot]))
    return out


# ------------------------------------------------------- op-level parity

_EPS = float(np.finfo(np.float32).eps)


def _assert_ulp_close(got, ref, ulps=4):
    """The documented numerics bound: the kernel's only structural
    difference from the dense path is the online softmax's reassociated
    denominator, ≤1–2 ulp at the output scale (measured); 4 is the
    assertion envelope."""
    got = np.asarray(got, np.float32)
    ref = np.asarray(ref, np.float32)
    tol = ulps * _EPS * max(1.0, float(np.abs(ref).max()))
    assert float(np.abs(got - ref).max()) <= tol, (
        float(np.abs(got - ref).max()), tol
    )


def _gather_oracle(q, k_pool, v_pool, tables, lengths):
    """The pure-XLA baseline the kernel replaces: gather every slot's
    pages into a contiguous view (mode="clip", exactly like the model's
    jnp.take path), then causal dense softmax attention."""
    b, t, h, d = q.shape
    kvh = k_pool.shape[2]
    r = h // kvh
    tbl = jnp.asarray(tables, jnp.int32)
    k = jnp.take(k_pool, tbl, axis=0, mode="clip").reshape(b, -1, kvh, d)
    v = jnp.take(v_pool, tbl, axis=0, mode="clip").reshape(b, -1, kvh, d)
    kk, vv = jnp.repeat(k, r, axis=2), jnp.repeat(v, r, axis=2)
    s = jnp.einsum(
        "bqhd,bkhd->bhqk", q.astype(jnp.float32), kk.astype(jnp.float32)
    ) / np.sqrt(d)
    q_pos = jnp.asarray(lengths)[:, None] + jnp.arange(t)[None]  # [b, t]
    key_pos = jnp.arange(k.shape[1])
    mask = key_pos[None, None, None, :] <= q_pos[:, None, :, None]
    s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, vv.astype(jnp.float32))


def _pools(num_pages, pt, kvh, d, seed):
    rng = np.random.default_rng(seed)
    k = jnp.asarray(rng.normal(size=(num_pages, pt, kvh, d)), jnp.float32)
    v = jnp.asarray(
        rng.normal(size=(num_pages, pt, kvh, d)), jnp.float32
    )
    return k, v


def test_decode_parity_scrambled_pages_staggered_lengths():
    """t=1 decode over a shared pool: scrambled physical page order,
    ragged lengths (including a just-admitted length-0 slot and a full
    row), GQA r=2 — the fused read matches the gather oracle to ulps."""
    from horovod_tpu.ops.paged_attention import paged_attention

    b, pt, kvh, h, d = 4, 8, 2, 4, 8
    num_pages, n_logical = 20, 4  # 4 pages x 8 tokens = 32-token slots
    k_pool, v_pool = _pools(num_pages, pt, kvh, d, 0)
    rng = np.random.default_rng(1)
    tables = np.full((b, n_logical), num_pages, np.int32)  # sentinel
    phys = rng.permutation(num_pages)
    lengths = np.asarray([0, 5, 17, 31], np.int32)
    off = 0
    for i, n in enumerate(lengths):
        live = -(-(int(n) + 1) // pt)
        tables[i, :live] = phys[off:off + live]
        off += live
    q = jnp.asarray(rng.normal(size=(b, 1, h, d)), jnp.float32)
    got = paged_attention(
        q, k_pool, v_pool, jnp.asarray(tables), jnp.asarray(lengths)
    )
    ref = _gather_oracle(q, k_pool, v_pool, tables, lengths)
    assert got.shape == (b, 1, h, d)
    _assert_ulp_close(got, ref)


def test_prefill_chunk_parity_unaligned_starts():
    """t=8 chunk (the chunked-prefill shape): per-slot start offsets
    that do NOT sit on page boundaries still mask and accumulate to the
    oracle's values."""
    from horovod_tpu.ops.paged_attention import paged_attention

    b, t, pt, kvh, h, d = 3, 8, 8, 1, 2, 8
    num_pages, n_logical = 12, 4
    k_pool, v_pool = _pools(num_pages, pt, kvh, d, 2)
    rng = np.random.default_rng(3)
    tables = np.asarray(
        [[7, 2, 9, 0], [4, 11, 1, 3], [8, 5, 10, 6]], np.int32
    )
    lengths = np.asarray([0, 5, 16], np.int32)
    q = jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.float32)
    got = paged_attention(
        q, k_pool, v_pool, jnp.asarray(tables), jnp.asarray(lengths)
    )
    ref = _gather_oracle(q, k_pool, v_pool, tables, lengths)
    _assert_ulp_close(got, ref)


# --------------------------------------- engine-level kernel/gather parity


def _ab_engines(toy, **kw):
    on = _engine(toy, paged_attn="on", **kw)
    off = _engine(toy, paged_attn="off", **kw)
    assert on.paged_attn and not off.paged_attn
    return on, off


def test_kernel_greedy_parity_rope_gqa_staggered():
    """The acceptance gate: kernel-on greedy decode is token-identical
    to the gather read — on the variant most sensitive to KV placement
    (RoPE + grouped-query heads), with staggered admissions."""
    toy = _toy(num_heads=4, num_kv_heads=1, rope=True)
    model, params = toy
    on, off = _ab_engines(toy)
    p1, p2 = [3, 5, 7], [11, 13, 17, 19, 21]
    outs = {}
    for eng in (on, off):
        s1 = eng.manager.alloc("a")
        o1 = [eng.prefill(s1, p1)]
        for _ in range(3):
            toks = np.zeros(eng.slots, np.int32)
            toks[s1] = o1[-1]
            o1.append(int(eng.decode_step(toks)[s1]))
            eng.manager.advance(s1)
        s2 = eng.manager.alloc("b")  # staggered admission mid-stream
        o2 = [eng.prefill(s2, p2)]
        for _ in range(4):
            toks = np.zeros(eng.slots, np.int32)
            toks[s1], toks[s2] = o1[-1], o2[-1]
            nxt = eng.decode_step(toks)
            eng.manager.advance(s1)
            eng.manager.advance(s2)
            o1.append(int(nxt[s1]))
            o2.append(int(nxt[s2]))
        outs[eng is on] = (o1, o2)
    assert outs[True] == outs[False]
    assert outs[True][0] == _greedy_ref(model, params, p1, 8)
    assert outs[True][1] == _greedy_ref(model, params, p2, 5)
    assert on.stats()["paged_attn_calls"] > 0
    assert on.stats()["paged_attn_fallbacks"] == 0
    assert off.stats()["paged_attn_calls"] == 0


def test_kernel_parity_page_reuse_after_eviction(toy):
    """Recycled physical pages (no zeroing on free) decode exactly
    through the kernel read — stale pool contents past the frontier are
    invisible to the clamped page walk."""
    model, params = toy
    eng = _engine(
        toy, slots=1, pages=4, prefix_cache=False, paged_attn="on"
    )
    slot = eng.manager.alloc("a")
    _generate(eng, slot, [41, 43, 45, 47, 49, 51, 53], 12)
    eng.manager.free(slot)
    slot2 = eng.manager.alloc("b")
    out = _generate(eng, slot2, [2, 4], 6)
    assert out == _greedy_ref(model, params, [2, 4], 6)
    assert eng.stats()["paged_attn_fallbacks"] == 0


def test_kernel_parity_chunked_long_prompt(toy):
    """Chunked prefill rides the kernel too: every ceiling chunk and
    the tail each count one kernel call, and the long-prompt stream
    matches the dense reference."""
    model, params = toy
    eng = _engine(toy, prefill_ceiling=8, paged_attn="on")
    prompt = list(np.random.default_rng(3).integers(1, 60, size=21))
    slot = eng.manager.alloc()
    out = _generate(eng, slot, prompt, 4)
    assert out == _greedy_ref(model, params, prompt, 4)
    st = eng.stats()
    assert st["chunked_prefill_chunks"] == 2
    # 2 ceiling chunks + 1 tail prefill + 3 decode steps
    assert st["paged_attn_calls"] == 6
    assert st["paged_attn_fallbacks"] == 0


# ------------------------------------------------- zero-retrace invariant


def test_zero_retrace_kernel_on_admissions_and_exhaustion(toy):
    """decode_compiles stays EXACTLY 1 with the kernel on, across
    rolling admissions, pool-exhaustion pauses and resumes — page
    tables stay DATA through the scalar-prefetch grid, never shapes."""
    from horovod_tpu.serving.batcher import ContinuousBatcher

    model, params = toy
    _metrics.reset()
    eng = _engine(
        toy, slots=3, page_tokens=8, pages=9, page_watermark=1,
        prefix_cache=False, paged_attn="on",
    )
    b = ContinuousBatcher(
        eng, max_admit_per_step=3, default_max_new_tokens=24
    )
    reqs = [
        b.submit(list(range(i * 3 + 1, i * 3 + 11)), max_new_tokens=24)
        for i in range(3)
    ]
    guard = 0
    while not all(r.finished() for r in reqs):
        b.step()
        guard += 1
        assert guard < 5000, [r.status for r in reqs]
    snap = _metrics.snapshot()
    assert snap.get("serve.paused", 0) > 0, "pool never exhausted"
    assert snap.get("serve.resumed", 0) > 0
    st = eng.stats()
    assert st["decode_compiles"] == 1
    assert st["paged_attn_fallbacks"] == 0
    assert st["paged_attn_calls"] > 0
    for i, r in enumerate(reqs):
        assert r.status == "done"
        assert r.out_tokens == _greedy_ref(
            model, params, list(range(i * 3 + 1, i * 3 + 11)), 24
        ), f"request {i} diverged across pause/resume"


# --------------------------------------------------------- fallback ladder


def test_fallback_missing_pallas_counted(toy, monkeypatch):
    """Rung 1: no Pallas lowering — the engine serves on the gather
    read, warns, and counts the fallback; output stays exact."""
    from horovod_tpu.ops import paged_attention as pa

    model, params = toy
    monkeypatch.setattr(pa, "_PALLAS", False)
    reason = pa.unsupported_reason(128, 8)
    assert reason and "Pallas" in reason
    with pytest.raises(RuntimeError, match="Pallas"):
        pa.paged_attention(
            jnp.zeros((1, 1, 2, 8)), jnp.zeros((4, 8, 2, 8)),
            jnp.zeros((4, 8, 2, 8)), jnp.zeros((1, 4), jnp.int32),
            jnp.zeros((1,), jnp.int32),
        )
    eng = _engine(toy, paged_attn="on")
    assert eng.paged_attn is False
    assert eng.stats()["paged_attn_fallbacks"] == 1
    out = _generate(eng, eng.manager.alloc(), [3, 5, 7], 5)
    assert out == _greedy_ref(model, params, [3, 5, 7], 5)
    assert eng.stats()["paged_attn_calls"] == 0


def test_fallback_alignment_rungs_are_tpu_only():
    """Rungs 2–3: Mosaic tile floors (128-lane head_dim, 8-sublane
    page_tokens) gate only on real TPU backends — interpret mode (CPU
    tests, dryrun benches) runs any geometry."""
    from horovod_tpu.ops import paged_attention as pa

    assert pa.unsupported_reason(8, 16) is None  # CPU: lenient
    r = pa.unsupported_reason(8, 16, backend="tpu")
    assert r and "lane" in r
    r = pa.unsupported_reason(128, 12, backend="tpu")
    assert r and "sublane" in r
    assert pa.unsupported_reason(128, 16, backend="tpu") is None


def test_fallback_vmem_budget_counted(toy, monkeypatch):
    """Rung 4: the VMEM estimate vs HOROVOD_FLASH_VMEM_BUDGET — an
    oversized page staging footprint rides the gather path, counted."""
    from horovod_tpu.ops import paged_attention as pa

    monkeypatch.setenv("HOROVOD_FLASH_VMEM_BUDGET", "1024")
    reason = pa.unsupported_reason(8, 16)
    assert reason and "VMEM" in reason
    eng = _engine(toy, paged_attn="on")
    assert eng.paged_attn is False
    assert eng.stats()["paged_attn_fallbacks"] == 1


def test_fallback_sliding_window_counted():
    """Rung 5: the kernel has no band mask — sliding-window models keep
    the gather read and the fallback is counted at engine build."""
    toy = _toy(sliding_window=8)
    model, params = toy
    eng = _engine(toy, paged_attn="on")
    assert eng.paged_attn is False
    assert eng.stats()["paged_attn_fallbacks"] == 1
    out = _generate(eng, eng.manager.alloc(), [5, 9, 2], 4)
    assert len(out) == 4


def test_model_level_fallback_wide_prefill_chunk(toy, monkeypatch):
    """The per-trace rung: a budget that admits the decode geometry
    (t=1) but not an 8-wide prefill chunk falls back ONLY for the wide
    trace — loud warning + serve.paged_attn_fallbacks — while decode
    keeps the kernel. The fallen-back chunk is bitwise the gather
    path."""
    from horovod_tpu.models.transformer import init_cache
    from horovod_tpu.ops import paged_attention as pa

    model, params = toy
    cfg = model.cfg
    d = cfg.d_model // cfg.num_heads
    lo = pa.fwd_vmem_bytes(1, d, 16)
    hi = pa.fwd_vmem_bytes(8, d, 16)
    assert lo < hi
    monkeypatch.setenv("HOROVOD_FLASH_VMEM_BUDGET", str((lo + hi) // 2))
    _metrics.reset()

    pt, slots = 16, 2
    W = cfg.max_len // pt
    tables = np.full((slots, W), slots * W, np.int32)
    tables[0] = [1, 3, 0, 2]
    prompt = jnp.asarray([[9, 8, 7, 6, 5, 4, 3, 2]], jnp.int32)

    def run(paged_attn):
        pool = init_cache(cfg, slots * W, pt)
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            lg, pool = model.apply(
                params, prompt, train=False, cache=pool,
                cache_index=jnp.array([0]),
                pages=jnp.asarray(tables[0:1]),
                paged_attn=paged_attn,
            )
        return lg, pool, [str(x.message) for x in w]

    lg_k, pool, warns = run(True)
    assert any("unsupported" in m for m in warns)
    assert _metrics.snapshot().get("serve.paged_attn_fallbacks") == 1.0
    lg_g, _, warns_g = run(False)
    assert not any("paged_attn" in m for m in warns_g)
    assert bool(jnp.all(lg_k == lg_g))  # fell back -> same program

    # decode (t=1) stays inside the budget: kernel engages, no warning
    toks = jnp.asarray([[3], [0]], jnp.int32)
    lengths = jnp.asarray([8, 0], jnp.int32)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        lg_dk, _ = model.apply(
            params, toks, train=False, cache=pool, cache_index=lengths,
            pages=jnp.asarray(tables), paged_attn=True,
        )
    assert not any("paged_attn" in str(x.message) for x in w)
    lg_dg, _ = model.apply(
        params, toks, train=False, cache=pool, cache_index=lengths,
        pages=jnp.asarray(tables), paged_attn=False,
    )
    assert int(jnp.argmax(lg_dk[0, -1])) == int(jnp.argmax(lg_dg[0, -1]))
    _assert_ulp_close(lg_dk[0], lg_dg[0], ulps=16)  # logit scale


# ----------------------------------------------------------- sampled decode


def _sampled_stream(toy, prompt, n, temp, topk, seed, **engine_kw):
    eng = _engine(toy, **engine_kw)
    slot = eng.manager.alloc()
    eng.set_sampling(slot, temp, topk, seed=seed)
    return _generate(eng, slot, prompt, n), eng


def test_temperature_zero_is_bitwise_greedy(toy):
    """temperature 0 takes the jnp.where greedy branch — bit-identical
    to an engine that never heard of sampling, even with a seeded key
    riding the carry."""
    model, params = toy
    prompt = [7, 3, 9, 1]
    out, _ = _sampled_stream(toy, prompt, 10, 0.0, 0, seed=123)
    assert out == _greedy_ref(model, params, prompt, 10)


def test_seeded_sampling_reproducible_and_not_greedy(toy):
    """Same seed, fresh engines: identical streams (the key rides the
    donated carry deterministically). High temperature diverges from
    greedy; top_k=1 collapses back to greedy at ANY temperature."""
    model, params = toy
    prompt = [2, 4, 6, 8]
    a, _ = _sampled_stream(toy, prompt, 12, 5.0, 0, seed=7)
    b, _ = _sampled_stream(toy, prompt, 12, 5.0, 0, seed=7)
    assert a == b
    greedy = _greedy_ref(model, params, prompt, 12)
    assert a[0] == greedy[0]  # the prefill token is always greedy
    assert a != greedy
    c, _ = _sampled_stream(toy, prompt, 12, 5.0, 1, seed=7)
    assert c == greedy


def test_sampling_is_data_zero_retrace_and_slot_isolation(toy):
    """Sampling knobs through the batcher are DATA in the one decode
    executable: a sampled and a greedy request share a batch without
    retrace, the greedy stream stays exact, retirement clears the
    knobs for the slot's next occupant, and a replayed seed
    reproduces."""
    from horovod_tpu.serving.batcher import ContinuousBatcher

    model, params = toy
    eng = _engine(toy)
    bat = ContinuousBatcher(eng, default_max_new_tokens=8)
    g = bat.submit([1, 2, 3, 4], max_new_tokens=8)
    s = bat.submit([5, 6, 7, 8], max_new_tokens=8,
                   temperature=1.5, seed=11)
    while not (g.finished() and s.finished()):
        bat.step()
    assert g.result()["tokens"] == _greedy_ref(
        model, params, [1, 2, 3, 4], 8
    )
    assert eng.stats()["decode_compiles"] == 1
    # replayed seed reproduces the sampled stream bit for bit
    s2 = bat.submit([5, 6, 7, 8], max_new_tokens=8,
                    temperature=1.5, seed=11)
    # the sampled slot was cleared on retire: a greedy request landing
    # on any slot decodes greedy
    g2 = bat.submit([5, 6, 7, 8], max_new_tokens=8)
    while not (s2.finished() and g2.finished()):
        bat.step()
    assert s2.result()["tokens"] == s.result()["tokens"]
    assert g2.result()["tokens"] == _greedy_ref(
        model, params, [5, 6, 7, 8], 8
    )
    assert eng.stats()["decode_compiles"] == 1


def test_sampling_composes_with_kernel_read(toy):
    """Sampled decode and the paged-attention kernel share the decode
    executable: seeded reproducibility holds with the kernel on, and
    temp-0 matches the gather engine's greedy stream."""
    model, params = toy
    prompt = [9, 2, 5]
    a, ea = _sampled_stream(toy, prompt, 8, 3.0, 0, seed=4,
                            paged_attn="on")
    b, _ = _sampled_stream(toy, prompt, 8, 3.0, 0, seed=4,
                           paged_attn="on")
    assert a == b
    assert ea.stats()["paged_attn_calls"] > 0
    g, _ = _sampled_stream(toy, prompt, 8, 0.0, 0, seed=4,
                           paged_attn="on")
    assert g == _greedy_ref(model, params, prompt, 8)


# ------------------------------------- transfer-sender split (satellite 1)


def test_gather_pages_defers_device_get(toy, monkeypatch):
    """The sender split: gather_pages (scheduler-thread half) performs
    NO host transfer; pages_to_host does exactly ONE batched device_get
    for all pages of all leaves; the composition equals extract_pages
    bit for bit."""
    eng = _engine(toy, prefix_cache=False)
    slot = eng.manager.alloc("a")
    eng.prefill(slot, [1, 2, 3, 4, 5])
    eng.manager.set_length(slot, 5)
    kept, length = eng.manager.detach_keep(slot)
    calls = []
    real = jax.device_get

    def spy(x):
        calls.append(threading.current_thread().name)
        return real(x)

    monkeypatch.setattr(jax, "device_get", spy)
    raw = eng.gather_pages(kept)
    assert calls == [], "gather_pages touched the host on the hot path"
    out = eng.pages_to_host(raw, kept, length)
    assert len(calls) == 1, "pages_to_host must batch ONE device_get"
    monkeypatch.undo()
    ref = eng.extract_pages(kept, length)
    for a, b in zip(out, ref):
        np.testing.assert_array_equal(a, b)
    pt = eng.manager.page_tokens
    assert float(np.abs(out[0][-1, length % pt:]).max()) == 0.0
    eng.manager.release_kept(kept)


class _FakeAnnounceClient:
    def __init__(self, anns):
        self.anns = dict(anns)

    def keys(self, scope):
        return [str(r) for r in self.anns]

    def get(self, scope, key):
        return json.dumps(self.anns[int(key)]).encode()


def test_decode_round_latency_is_transfer_independent(toy):
    """The regression the split exists for: a SLOW host materialization
    (0.5 s injected into pages_to_host) must not stretch any scheduler
    step — the blocking half runs on the handoff thread, so in-flight
    transfers leave decode-round latency untouched."""
    from horovod_tpu.serving.batcher import ContinuousBatcher
    from horovod_tpu.serving.kv_transfer import (
        KVTransferServer,
        TransferCoordinator,
    )

    model, params = toy
    deng = _engine(toy, role="decode")
    dbat = ContinuousBatcher(deng, role="decode",
                             default_max_new_tokens=6)
    server = KVTransferServer(dbat, port=0, addr="127.0.0.1")
    server.start()
    peng = _engine(toy, role="prefill")
    pbat = ContinuousBatcher(peng, role="prefill",
                             default_max_new_tokens=6)
    pbat.transfer = TransferCoordinator(
        peng,
        client=_FakeAnnounceClient({0: {
            "port": 1, "addr": "127.0.0.1", "role": "decode",
            "transfer_port": server.port, "free_pages": 100,
            "free_slots": 4, "ts": time.time(),
        }}),
        wire="fp32",
    )
    dbat.start()
    try:
        def pump(req, measure=False):
            worst = 0.0
            deadline = time.monotonic() + 60.0
            while not req.finished() and time.monotonic() < deadline:
                t0 = time.perf_counter()
                pbat.step()
                worst = max(worst, time.perf_counter() - t0)
                time.sleep(0.002)
            assert req.finished(), "transfer never completed"
            return worst

        # warm-up ONCE: bucket→exact promotion now runs on a background
        # thread (disk tier first), so a repeat admission can no longer
        # inject a promotion compile into the measured hot path
        prompt = list(range(1, 9))
        pump(pbat.submit(prompt, max_new_tokens=6))

        seen = {}
        real = peng.pages_to_host

        def slow(raw, kept, length):
            seen["thread"] = threading.current_thread().name
            time.sleep(0.5)
            return real(raw, kept, length)

        peng.pages_to_host = slow
        try:
            req = pbat.submit(prompt, max_new_tokens=6)
            worst = pump(req, measure=True)
        finally:
            peng.pages_to_host = real
        assert req.status == "done"
        assert seen["thread"].startswith("hvd-kv-handoff"), seen
        # every scheduler round stayed far below the injected 0.5 s
        assert worst < 0.35, (
            f"a scheduler step blocked {worst:.3f}s on the transfer"
        )
        assert dbat.engine.stats()["transfer_ingests"] >= 2
    finally:
        dbat.stop()
        server.stop()
