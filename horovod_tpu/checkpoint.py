"""Durable checkpointing — async, sharded, elastic-aware.

The reference has NO core checkpoint subsystem (SURVEY.md §5.4): users
save on rank 0 by hand (`examples/pytorch/pytorch_mnist.py` pattern
[V]) and elastic state lives only in memory (`State.commit()`), so a
full-job failure loses everything since the last user save. On TPU this
gap is load-bearing — preemption is the COMMON failure — so this module
provides what the reference papered over, with Horovod's idioms:

* ``CheckpointManager`` — Orbax-backed async save/restore of arbitrary
  pytrees (params/opt_state/step), sharded-array aware: each host
  writes its own shards (no rank-0 gather bottleneck), restore places
  leaves back on the current mesh.
* ``DurableJaxState`` — ``hvd.elastic.JaxState`` whose ``commit()``
  ALSO persists to disk every ``save_interval`` commits, and which can
  resume from the latest checkpoint after a full-job restart — the
  elastic protocol extended beyond the reference's in-memory-only
  rollback.
"""

from __future__ import annotations

import glob
import json
import os
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np

from .common.logging import get_logger
from .testing import chaos as _chaos

_log = get_logger("checkpoint")


class CheckpointStructureError(ValueError):
    """``restore(like=)`` was handed a tree whose STRUCTURE disagrees
    with what the checkpoint holds — a deterministic caller bug (wrong
    state class, renamed field, a sampler registered after old saves),
    not storage corruption. Raised with the tree-path diff in the
    message instead of the raw Orbax traceback, and re-raised
    immediately by ``restore_latest_good`` (falling back through the
    retention window cannot fix a structure mismatch)."""


class CheckpointManager:
    """Async sharded checkpoints (Orbax engine, Horovod-shaped API).

    Degradation-aware by design: saves are atomic (Orbax finalizes a
    step directory with a commit marker only after every artifact write
    lands, so a SIGKILL mid-save leaves an *uncommitted* directory the
    step listing ignores, never a truncated file the restore path
    trusts), and :meth:`restore_latest_good` walks the retained steps
    newest-first past any corrupt/partial checkpoint — counting each
    skip as ``checkpoint.fallback`` — instead of crashing the resume.
    """

    def __init__(
        self,
        directory: str,
        max_to_keep: int = 3,
        async_save: bool = True,
    ) -> None:
        import orbax.checkpoint as ocp

        self._dir = os.path.abspath(directory)
        os.makedirs(self._dir, exist_ok=True)
        self._mgr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                enable_async_checkpointing=async_save,
            ),
        )
        self._digest_threads: list = []

    def save(self, step: int, tree: Any, force: bool = False) -> bool:
        """Queue an async save of ``tree`` at ``step``. Returns whether
        a save was started (Orbax dedupes repeated steps).

        A content digest (audit.tree_digest over the IN-MEMORY tree) is
        written beside the step as ``digest-<step>.json``;
        :meth:`restore_latest_good` re-digests what it restored and
        treats a mismatch as corruption — so post-commit disk damage
        that still PARSES (a flipped byte in an array chunk) falls back
        too, not just unreadable checkpoints."""
        import orbax.checkpoint as ocp

        chaos_kind = _chaos.inject("checkpoint.save")
        from .audit import tree_meta_digest

        # The device→host copy happens HERE, synchronously: the caller
        # may donate these buffers to its next step the moment save()
        # returns (the same reason Orbax's async save copies before
        # returning). The SHA-256 over the host bytes — the CPU-heavy
        # half — runs on a background thread joined by
        # wait_until_finished(), so the training loop does not stall
        # on hashing a multi-GB tree.
        digestible = _fully_addressable(tree)
        if digestible:
            meta = tree_meta_digest(tree)
            leaves, treedef = jax.tree_util.tree_flatten(tree)
            host_leaves = jax.device_get(leaves)
        saved = self._mgr.save(
            step, args=ocp.args.StandardSave(tree), force=force
        )
        if saved and digestible:
            import threading

            # drop finished threads so a long async job's list stays
            # at in-flight size, not one entry per commit forever
            self._digest_threads = [
                t for t in self._digest_threads if t.is_alive()
            ]
            t = threading.Thread(
                target=self._hash_and_write,
                args=(step, treedef, host_leaves, meta),
                daemon=True,
            )
            t.start()
            self._digest_threads.append(t)
            self._prune_digests(keep_also=step)
        if saved and chaos_kind == "bitflip":
            # corruption drill: land the commit, then flip one byte of
            # a committed artifact — exactly the damage the digest
            # verification exists to catch
            self.wait_until_finished()
            self._bitflip_step(step)
        return saved

    # ---------------------------------------------- digest sidecars

    def _digest_path(self, step: int) -> str:
        return os.path.join(self._dir, f"digest-{int(step)}.json")

    def _hash_and_write(self, step, treedef, host_leaves, meta) -> None:
        from .audit import digest_host_leaves

        try:
            self._write_digest(
                step, digest_host_leaves(treedef, host_leaves), meta
            )
        except Exception:
            _log.warning("digest sidecar write failed", exc_info=True)

    def _write_digest(self, step: int, digest: str, meta: str) -> None:
        path = self._digest_path(step)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                {"step": int(step), "digest": digest, "meta": meta}, f
            )
        os.replace(tmp, path)

    def _read_digest(self, step: int) -> Optional[dict]:
        try:
            with open(self._digest_path(step)) as f:
                info = json.load(f)
            return info if "digest" in info else None
        except (OSError, ValueError):
            return None

    def _prune_digests(self, keep_also: Optional[int] = None) -> None:
        """Drop sidecars for steps outside the retention window (the
        async save may not list ``keep_also`` yet — always keep it)."""
        keep = set(int(s) for s in self.all_steps())
        if keep_also is not None:
            keep.add(int(keep_also))
        for path in glob.glob(os.path.join(self._dir, "digest-*.json")):
            try:
                step = int(
                    os.path.basename(path)[len("digest-"): -len(".json")]
                )
            except ValueError:
                continue
            if step not in keep:
                try:
                    os.remove(path)
                except OSError:
                    pass

    def _bitflip_step(self, step: int) -> None:
        """Chaos helper: flip one byte in the largest artifact of a
        COMMITTED step directory (post-commit damage — the atomic
        marker cannot guard it; only content verification can)."""
        step_dir = os.path.join(self._dir, str(int(step)))
        candidates = [
            p
            for p in glob.glob(os.path.join(step_dir, "**"), recursive=True)
            if os.path.isfile(p) and os.path.getsize(p) > 0
        ]
        if not candidates:
            return
        # prefer ARRAY DATA (ocdbt `d/` payload files) over metadata:
        # metadata damage fails the parse outright (the easy case);
        # payload damage is what the content digest exists to catch
        data = [
            p for p in candidates
            if os.path.basename(os.path.dirname(p)) == "d"
        ]
        target = max(data or candidates, key=os.path.getsize)
        with open(target, "r+b") as f:
            f.seek(os.path.getsize(target) // 2)
            byte = f.read(1)
            f.seek(-1, os.SEEK_CUR)
            f.write(bytes([byte[0] ^ 0xFF]))
        _log.warning("chaos: flipped one byte of %s", target)

    def _verify_digest(self, step: int, restored: Any) -> None:
        """Compare the restored tree against the save-time digest; no
        sidecar (pre-digest checkpoints) verifies vacuously, and so
        does a restore whose META digest (structure/dtype/shape)
        differs from the saved one — the caller restored through a
        re-typed ``like`` (e.g. bf16 over an fp32 checkpoint) ON
        PURPOSE, and re-hashing casted bytes would misread every
        retained checkpoint as corrupt."""
        info = self._read_digest(step)
        if info is None:
            return
        if not _fully_addressable(restored):
            return  # multi-controller restore: cannot hash globally
        expect = str(info["digest"])
        from .audit import tree_digest, tree_meta_digest

        saved_meta = info.get("meta")
        if saved_meta and tree_meta_digest(restored) != saved_meta:
            _log.debug(
                "checkpoint step %d restored with a different "
                "dtype/structure than saved; digest verification "
                "skipped", step,
            )
            return
        actual = tree_digest(restored)
        if actual != expect:
            from .common.metrics import registry as _metrics

            _metrics.counter("checkpoint.digest_mismatch")
            raise RuntimeError(
                f"checkpoint step {step} digest mismatch: restored "
                f"{actual[:16]}, saved {expect[:16]} — content damaged "
                "after commit"
            )

    def restore(self, step: Optional[int] = None, like: Any = None) -> Any:
        """Restore the checkpoint at ``step`` (default: latest). With
        ``like`` (a pytree of arrays or ShapeDtypeStructs, possibly
        sharded), leaves are restored directly onto matching devices."""
        import orbax.checkpoint as ocp

        _chaos.inject("checkpoint.restore")
        if step is None:
            step = self.latest_step()
            if step is None:
                raise FileNotFoundError(
                    f"no checkpoint under {self._dir}"
                )
        if like is not None:
            target = jax.tree_util.tree_map(_as_restore_spec, like)
            try:
                return self._mgr.restore(
                    step, args=ocp.args.StandardRestore(target)
                )
            except Exception as e:
                diff = self._structure_diff(step, like)
                if diff:
                    raise CheckpointStructureError(
                        f"checkpoint step {step} does not match the "
                        f"`like` tree's structure: {diff}. This is a "
                        "caller/state-definition mismatch (not "
                        "corruption) — restore with the state class "
                        "that wrote the checkpoint, or migrate it."
                    ) from e
                raise
        return self._mgr.restore(step)

    def _structure_diff(self, step: int, like: Any) -> Optional[str]:
        """Tree-path prefix diff between the checkpoint's metadata and
        ``like``; None when the structures agree (the failure was
        something else) or metadata is unavailable."""
        try:
            meta = self._mgr.item_metadata(step)
        except Exception:
            return None
        if meta is None:
            return None

        def _paths(tree) -> set:
            flat = jax.tree_util.tree_flatten_with_path(tree)[0]
            return {jax.tree_util.keystr(p) for p, _ in flat}

        try:
            saved, want = _paths(meta), _paths(like)
        except Exception:
            return None
        missing = sorted(want - saved)
        extra = sorted(saved - want)
        if not missing and not extra:
            return None
        parts = []
        if missing:
            parts.append(
                "expected-but-not-saved "
                + ", ".join(missing[:8])
                + ("…" if len(missing) > 8 else "")
            )
        if extra:
            parts.append(
                "saved-but-not-expected "
                + ", ".join(extra[:8])
                + ("…" if len(extra) > 8 else "")
            )
        return "; ".join(parts)

    def restore_latest_good(
        self, like: Any = None
    ) -> Tuple[int, Any]:
        """Restore the newest checkpoint that actually loads.

        Walks the retained steps newest-first; a step that fails to
        restore (corrupt array file, half-written metadata — anything
        the atomic-commit marker didn't guard, e.g. post-commit disk
        damage) OR that restores but fails its saved content digest
        (corrupt-but-parseable — a flipped byte that still decodes) is
        logged, counted as ``checkpoint.fallback``, and skipped in
        favor of the next older one. Raises ``FileNotFoundError`` when
        no checkpoints exist, ``CheckpointStructureError`` immediately
        on a ``like``-structure mismatch (deterministic — older
        checkpoints cannot fix it), and a ``RuntimeError`` (chained to
        the last failure) when every retained checkpoint is bad —
        losing the whole retention window is a real failure the job
        must surface, not silently train from scratch over, so the
        all-corrupt case deliberately cannot collide with the
        fresh-start ``FileNotFoundError`` even when the underlying
        damage IS a missing file."""
        steps = sorted(self.all_steps(), reverse=True)
        if not steps:
            raise FileNotFoundError(f"no checkpoint under {self._dir}")
        last_exc: Optional[BaseException] = None
        for step in steps:
            try:
                restored = self.restore(step, like=like)
                self._verify_digest(step, restored)
                return step, restored
            except CheckpointStructureError:
                raise
            except Exception as e:  # noqa: BLE001 — any load failure
                from .common.metrics import registry as _metrics

                _metrics.counter("checkpoint.fallback")
                _log.warning(
                    "checkpoint step %d failed to restore (%s: %s); "
                    "falling back to the previous one",
                    step, type(e).__name__, e,
                )
                last_exc = e
        assert last_exc is not None
        raise RuntimeError(
            f"all {len(steps)} retained checkpoint(s) under "
            f"{self._dir} failed to restore"
        ) from last_exc

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return sorted(self._mgr.all_steps())

    def wait_until_finished(self) -> None:
        """Block until queued async saves — AND their digest-sidecar
        hashing threads — are durable; call before letting a preempted
        VM die (the TPU preemption-notice handler's job)."""
        self._mgr.wait_until_finished()
        threads, self._digest_threads = self._digest_threads, []
        for t in threads:
            t.join(timeout=60)

    def close(self) -> None:
        self._mgr.wait_until_finished()
        self._mgr.close()

    def __enter__(self) -> "CheckpointManager":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def _fully_addressable(tree) -> bool:
    """True when every jax.Array leaf is fully addressable from THIS
    process. Multi-controller jobs hold arrays spanning processes;
    ``jax.device_get`` on those raises, so the digest machinery (a
    per-process whole-tree hash) steps aside and leaves corruption
    detection to Orbax's own sharded-save handling there."""
    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
            return False
    return True


def _as_restore_spec(leaf):
    if isinstance(leaf, jax.Array):
        return jax.ShapeDtypeStruct(
            leaf.shape, leaf.dtype, sharding=leaf.sharding
        )
    if isinstance(leaf, np.ndarray):
        return jax.ShapeDtypeStruct(leaf.shape, leaf.dtype)
    return leaf


# --------------------------------------------------- elastic integration

from .elastic.state import JaxState  # noqa: E402  (import cycle: none)


class DurableJaxState(JaxState):
    """Elastic state with a durable spine.

    ``commit()`` keeps the reference's in-memory rollback semantics
    (peer failure → ``restore()`` to last commit, SURVEY.md §3.4) and
    additionally persists every ``save_interval``-th commit through a
    :class:`CheckpointManager`, so a FULL-job failure (every peer gone —
    the case the reference cannot survive) resumes from disk via
    :meth:`resume_latest`.

    The pytree attributes are saved; plain-object attributes ride along
    pickled into a side leaf only if numpy-representable (scalars/ints),
    mirroring what JaxState snapshots. Data cursors registered via
    :meth:`~horovod_tpu.elastic.state.JaxState.register_data` are
    persisted beside the model tree and loaded back by
    :meth:`resume_latest`, so a full-job restart resumes the sample
    stream at the exact next global index — exactly-once delivery
    across the durable boundary, including a world-size change (the
    cursor is global; the restored sampler re-stripes the remainder
    over the new replica count).

    ZeRO-2/3 layouts save AS-IS: the ShardedDistributedOptimizer's
    state dict (inner moments + guard counters + wire residual rows)
    and the stage-3 ``[world, cols]`` parameter shard rows are plain
    array pytrees, so the save path — and the content-digest sidecar
    the restore verifies — operates on the SHARDED layout directly;
    nothing is gathered to host-full form at any point
    (tests/test_zero.py::test_zero3_checkpoint_roundtrip_sharded_no_gather).
    """

    def __init__(
        self,
        checkpoint_dir: str,
        save_interval: int = 1,
        max_to_keep: int = 3,
        **kwargs: Any,
    ) -> None:
        self._ckpt = CheckpointManager(
            checkpoint_dir, max_to_keep=max_to_keep
        )
        self._save_interval = max(int(save_interval), 1)
        self._commits = 0
        self._step_counter = 0
        super().__init__(**kwargs)

    def _durable_tree(self) -> Dict[str, Any]:
        tree = {k: v for k, v in self._trees.items()}
        scalars = {
            k: v
            for k, v in self._attrs().items()
            if isinstance(v, (int, float, bool, np.integer, np.floating))
        }
        out: Dict[str, Any] = {"trees": tree, "scalars": scalars}
        if self._data:
            # registered sampler/dataset cursors (epoch + global
            # position, plain int leaves — the scalar type Orbax's
            # StandardSave accepts). The key exists only when
            # something is registered, so unregistered jobs keep
            # their checkpoint structure byte-for-byte.
            out["data"] = {
                name: {
                    k: int(v) for k, v in obj.state_dict().items()
                }
                for name, obj in self._data.items()
            }
        return out

    def commit(self) -> None:
        super().commit()
        self._commits += 1
        if self._commits % self._save_interval == 0:
            self._step_counter += 1
            self._ckpt.save(self._step_counter, self._durable_tree())

    def persist(self) -> None:
        """Unconditionally write the CURRENT live state to a durable
        checkpoint — no ``save_interval`` batching, no host-update check
        (``commit()`` does both, and either can lose the grace window:
        with save_interval>1 the write is skipped, and
        ``check_host_updates()`` can raise ``HostsUpdatedInterrupt``
        before saving). :class:`~horovod_tpu.preemption.GracefulShutdown`
        calls this, so a preempted VM always flushes its latest state."""
        self._step_counter += 1
        self._ckpt.save(self._step_counter, self._durable_tree(), force=True)

    def resume_latest(self) -> bool:
        """Load the newest *good* durable checkpoint into this state.
        Returns False when none exists (fresh start). A corrupt or
        partially-damaged newest checkpoint does not crash the resume:
        the manager falls back through the retention window
        (``checkpoint.fallback`` counts each skip) and only raises when
        every retained checkpoint is bad."""
        try:
            step, restored = self._ckpt.restore_latest_good(
                like=self._durable_tree()
            )
        except FileNotFoundError:
            return False
        for key, value in restored["trees"].items():
            self._trees[key] = self._replicate(value)
        for name, snap in restored.get("data", {}).items():
            obj = self._data.get(name)
            if obj is None:
                _log.warning(
                    "checkpoint carries data cursor %r but nothing is "
                    "registered under that name; skipping", name,
                )
                continue
            obj.load_state_dict({k: int(v) for k, v in snap.items()})
        for key, value in restored["scalars"].items():
            current = getattr(self, key, None)
            if isinstance(current, bool) or isinstance(value, np.bool_):
                value = bool(value)
            elif isinstance(current, int):
                value = int(value)
            elif isinstance(current, float):
                value = float(value)
            setattr(self, key, value)
        self._step_counter = step
        self.save()  # the restored state is the new rollback point
        return True

    def wait_until_finished(self) -> None:
        self._ckpt.wait_until_finished()

    def close(self) -> None:
        self._ckpt.close()
