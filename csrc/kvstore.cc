// Native HTTP key-value rendezvous server.
//
// TPU-native rebuild of the reference's rendezvous plane (ref:
// horovod/runner/http/http_server.py — the driver-hosted KV store the
// Gloo contexts bootstrap through, SURVEY.md §2.5/§3.3 — together with
// the C++ side that consumes it, horovod/common/gloo/gloo_context.cc;
// the reference vendors a C++ HTTP client in third_party/HTTPRequest).
// The reference serves this plane from Python; we serve it natively so
// a many-hundred-worker rendezvous storm (every worker polling every
// peer key) never contends with the driver's Python interpreter.
//
// Wire protocol — identical to the Python server in
// horovod_tpu/runner/rendezvous.py, so RendezvousClient works against
// either:
//   GET    /kv/<scope>/<key>   -> 200 value | 404
//   PUT    /kv/<scope>/<key>   body=value   -> 200
//   DELETE /kv/<scope>         -> 200 (drop scope)
//   GET    /scope/<scope>      -> 200 JSON sorted key list
// With a secret key, every request must carry
//   X-Horovod-Digest: hex(hmac_sha256(secret, method + path + body))
// or it gets 403.

#include "export.h"
#include "sha256.h"

#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <atomic>
#include <cctype>
#include <chrono>
#include <cstdio>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

struct KVServer {
  int listen_fd = -1;
  int port = 0;
  std::vector<uint8_t> secret;  // empty = no auth
  std::thread accept_thread;
  std::atomic<bool> running{false};
  std::atomic<long> active_handlers{0};
  std::mutex mu;
  std::map<std::string, std::map<std::string, std::string>> data;
};

// Rendezvous payloads are addresses/topology blobs; anything near this
// is hostile or broken. Bounding it keeps an unauthenticated client
// from ballooning the driver's memory before HMAC rejection.
constexpr size_t kMaxBody = 64 * 1024 * 1024;

std::string to_hex(const uint8_t* d, size_t n) {
  static const char* kHex = "0123456789abcdef";
  std::string s(n * 2, '0');
  for (size_t i = 0; i < n; ++i) {
    s[2 * i] = kHex[d[i] >> 4];
    s[2 * i + 1] = kHex[d[i] & 0xf];
  }
  return s;
}

bool const_time_eq(const std::string& a, const std::string& b) {
  if (a.size() != b.size()) return false;
  unsigned char diff = 0;
  for (size_t i = 0; i < a.size(); ++i) diff |= a[i] ^ b[i];
  return diff == 0;
}

bool authed(const KVServer& srv, const std::string& method,
            const std::string& path, const std::string& body,
            const std::string& digest_header) {
  if (srv.secret.empty()) return true;
  std::string payload = method + path + body;
  uint8_t mac[32];
  hvd::hmac_sha256(srv.secret.data(), srv.secret.size(),
                   reinterpret_cast<const uint8_t*>(payload.data()),
                   payload.size(), mac);
  return const_time_eq(digest_header, to_hex(mac, 32));
}

void reply(int fd, int code, const std::string& body) {
  const char* reason = code == 200   ? "OK"
                       : code == 403 ? "Forbidden"
                       : code == 404 ? "Not Found"
                                     : "Bad Request";
  char header[128];
  int n = std::snprintf(header, sizeof(header),
                        "HTTP/1.1 %d %s\r\nContent-Length: %zu\r\n"
                        "Connection: close\r\n\r\n",
                        code, reason, body.size());
  (void)!write(fd, header, n);
  if (!body.empty()) (void)!write(fd, body.data(), body.size());
}

// Split "/kv/scope/key" -> parts without empties.
std::vector<std::string> split_path(const std::string& path) {
  std::vector<std::string> parts;
  size_t i = 0;
  while (i < path.size()) {
    while (i < path.size() && path[i] == '/') ++i;
    size_t j = i;
    while (j < path.size() && path[j] != '/') ++j;
    if (j > i) parts.push_back(path.substr(i, j - i));
    i = j;
  }
  return parts;
}

std::string json_key_list(const std::vector<std::string>& keys) {
  // Keys here are env-style identifiers (rank addresses, host names);
  // escape the JSON specials anyway so arbitrary keys round-trip.
  std::string out = "[";
  for (size_t i = 0; i < keys.size(); ++i) {
    if (i) out += ", ";
    out += '"';
    for (char c : keys[i]) {
      if (c == '"' || c == '\\') { out += '\\'; out += c; }
      else if (static_cast<unsigned char>(c) < 0x20) {
        char buf[8];
        std::snprintf(buf, sizeof(buf), "\\u%04x", c);
        out += buf;
      } else out += c;
    }
    out += '"';
  }
  out += "]";
  return out;
}

void handle_connection_impl(KVServer* srv, int fd);

// Detached-thread entry: count in/out so shutdown can wait for us, and
// let no exception escape (an escaped exception in a detached thread is
// process abort).
void handle_connection(KVServer* srv, int fd) {
  try {
    handle_connection_impl(srv, fd);
  } catch (...) {
    close(fd);
  }
  srv->active_handlers.fetch_sub(1);
}

void handle_connection_impl(KVServer* srv, int fd) {
  // Read headers (bounded), then the Content-Length body.
  std::string buf;
  char tmp[4096];
  size_t header_end = std::string::npos;
  while (buf.size() < (1 << 20)) {
    ssize_t n = read(fd, tmp, sizeof(tmp));
    if (n <= 0) break;
    buf.append(tmp, n);
    header_end = buf.find("\r\n\r\n");
    if (header_end != std::string::npos) break;
  }
  if (header_end == std::string::npos) { close(fd); return; }

  // Request line: METHOD SP PATH SP VERSION
  size_t line_end = buf.find("\r\n");
  std::string line = buf.substr(0, line_end);
  size_t sp1 = line.find(' ');
  size_t sp2 = line.rfind(' ');
  if (sp1 == std::string::npos || sp2 <= sp1) { close(fd); return; }
  std::string method = line.substr(0, sp1);
  std::string path = line.substr(sp1 + 1, sp2 - sp1 - 1);

  // Headers we care about.
  size_t content_length = 0;
  std::string digest;
  size_t pos = line_end + 2;
  while (pos < header_end) {
    size_t eol = buf.find("\r\n", pos);
    std::string h = buf.substr(pos, eol - pos);
    pos = eol + 2;
    size_t colon = h.find(':');
    if (colon == std::string::npos) continue;
    std::string name = h.substr(0, colon);
    for (auto& c : name) c = std::tolower(c);
    size_t v = colon + 1;
    while (v < h.size() && h[v] == ' ') ++v;
    std::string value = h.substr(v);
    if (name == "content-length") {
      // Hand-parse: stoul throws on garbage, and an escaped exception in
      // a detached thread is std::terminate for the whole driver.
      size_t parsed = 0;
      bool ok = !value.empty();
      for (char c : value) {
        if (c < '0' || c > '9' || parsed > kMaxBody) { ok = false; break; }
        parsed = parsed * 10 + static_cast<size_t>(c - '0');
      }
      if (!ok || parsed > kMaxBody) {
        reply(fd, 400, "");
        close(fd);
        return;
      }
      content_length = parsed;
    } else if (name == "x-horovod-digest") {
      digest = value;
    }
  }

  std::string body = buf.substr(header_end + 4);
  while (body.size() < content_length) {
    ssize_t n = read(fd, tmp, sizeof(tmp));
    if (n <= 0) break;
    body.append(tmp, n);
  }
  body.resize(std::min(body.size(), content_length));

  if (!authed(*srv, method, path, body, digest)) {
    reply(fd, 403, "");
    close(fd);
    return;
  }

  auto parts = split_path(path);
  if (method == "GET" && parts.size() == 3 && parts[0] == "kv") {
    std::lock_guard<std::mutex> lock(srv->mu);
    auto scope_it = srv->data.find(parts[1]);
    if (scope_it != srv->data.end()) {
      auto key_it = scope_it->second.find(parts[2]);
      if (key_it != scope_it->second.end()) {
        reply(fd, 200, key_it->second);
        close(fd);
        return;
      }
    }
    reply(fd, 404, "");
  } else if (method == "GET" && parts.size() == 2 && parts[0] == "scope") {
    std::vector<std::string> keys;
    {
      std::lock_guard<std::mutex> lock(srv->mu);
      auto it = srv->data.find(parts[1]);
      if (it != srv->data.end()) {
        for (const auto& kv : it->second) keys.push_back(kv.first);
      }
    }
    reply(fd, 200, json_key_list(keys));  // std::map is already sorted
  } else if (method == "PUT" && parts.size() == 3 && parts[0] == "kv") {
    {
      std::lock_guard<std::mutex> lock(srv->mu);
      srv->data[parts[1]][parts[2]] = body;
    }
    reply(fd, 200, "");
  } else if (method == "DELETE" && parts.size() == 2 && parts[0] == "kv") {
    {
      std::lock_guard<std::mutex> lock(srv->mu);
      srv->data.erase(parts[1]);
    }
    reply(fd, 200, "");
  } else {
    reply(fd, 404, "");
  }
  close(fd);
}

void accept_loop(KVServer* srv) {
  while (srv->running.load()) {
    int fd = accept(srv->listen_fd, nullptr, nullptr);
    if (fd < 0) {
      if (!srv->running.load()) break;
      continue;
    }
    srv->active_handlers.fetch_add(1);
    try {
      std::thread(handle_connection, srv, fd).detach();
    } catch (...) {  // thread spawn failure (EAGAIN)
      srv->active_handlers.fetch_sub(1);
      close(fd);
    }
  }
}

}  // namespace

// Start a server on the given port (0 = ephemeral). Returns a handle,
// or nullptr on bind failure. out_port receives the bound port.
HVD_EXPORT void* hvd_kv_start(int port, const uint8_t* secret,
                              long secret_len, int* out_port) {
  auto srv = new KVServer();
  if (secret_len > 0) srv->secret.assign(secret, secret + secret_len);

  srv->listen_fd = socket(AF_INET, SOCK_STREAM, 0);
  if (srv->listen_fd < 0) { delete srv; return nullptr; }
  int one = 1;
  setsockopt(srv->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr = {};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(static_cast<uint16_t>(port));
  if (bind(srv->listen_fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) <
          0 ||
      listen(srv->listen_fd, 128) < 0) {
    close(srv->listen_fd);
    delete srv;
    return nullptr;
  }
  socklen_t len = sizeof(addr);
  getsockname(srv->listen_fd, reinterpret_cast<sockaddr*>(&addr), &len);
  srv->port = ntohs(addr.sin_port);
  if (out_port) *out_port = srv->port;

  srv->running.store(true);
  srv->accept_thread = std::thread(accept_loop, srv);
  return srv;
}

HVD_EXPORT int hvd_kv_port(void* h) { return static_cast<KVServer*>(h)->port; }

HVD_EXPORT void hvd_kv_stop(void* h) {
  auto* srv = static_cast<KVServer*>(h);
  srv->running.store(false);
  // Unblock accept(): shut down, then poke with a local connection in
  // case the platform's accept ignores shutdown on listen sockets.
  shutdown(srv->listen_fd, SHUT_RDWR);
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd >= 0) {
    sockaddr_in addr = {};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = htonl(INADDR_LOOPBACK);
    addr.sin_port = htons(static_cast<uint16_t>(srv->port));
    connect(fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr));
    close(fd);
  }
  if (srv->accept_thread.joinable()) srv->accept_thread.join();
  close(srv->listen_fd);
  // Detached handler threads may still hold srv; wait them out (bounded
  // — handlers only do in-memory work after their socket reads, so a
  // stuck peer can pin us at most until its read() fails on close).
  for (int i = 0; i < 50 * 60 && srv->active_handlers.load() > 0; ++i) {
    std::this_thread::sleep_for(std::chrono::milliseconds(20));
  }
  delete srv;
}

// --- direct store access for the driver process (the elastic driver
// reads/writes its own rendezvous without going through HTTP; parity
// with server.store in the Python implementation) ---

HVD_EXPORT void hvd_kv_put(void* h, const char* scope, const char* key,
                           const uint8_t* value, long len) {
  auto* srv = static_cast<KVServer*>(h);
  std::lock_guard<std::mutex> lock(srv->mu);
  srv->data[scope][key] = std::string(reinterpret_cast<const char*>(value),
                                      static_cast<size_t>(len));
}

// Returns value length, or -1 if absent. Copies min(len, cap) bytes
// into buf; call with cap=0 to probe the size.
HVD_EXPORT long hvd_kv_get(void* h, const char* scope, const char* key,
                           uint8_t* buf, long cap) {
  auto* srv = static_cast<KVServer*>(h);
  std::lock_guard<std::mutex> lock(srv->mu);
  auto scope_it = srv->data.find(scope);
  if (scope_it == srv->data.end()) return -1;
  auto key_it = scope_it->second.find(key);
  if (key_it == scope_it->second.end()) return -1;
  const std::string& v = key_it->second;
  long n = static_cast<long>(v.size());
  if (buf && cap > 0) {
    std::memcpy(buf, v.data(), static_cast<size_t>(std::min(n, cap)));
  }
  return n;
}

// Newline-joined sorted key list for a scope; same size-probe contract.
HVD_EXPORT long hvd_kv_keys(void* h, const char* scope, uint8_t* buf,
                            long cap) {
  auto* srv = static_cast<KVServer*>(h);
  std::string joined;
  {
    std::lock_guard<std::mutex> lock(srv->mu);
    auto it = srv->data.find(scope);
    if (it != srv->data.end()) {
      for (const auto& kv : it->second) {
        if (!joined.empty()) joined += '\n';
        joined += kv.first;
      }
    }
  }
  long n = static_cast<long>(joined.size());
  if (buf && cap > 0) {
    std::memcpy(buf, joined.data(), static_cast<size_t>(std::min(n, cap)));
  }
  return n;
}

HVD_EXPORT void hvd_kv_drop_scope(void* h, const char* scope) {
  auto* srv = static_cast<KVServer*>(h);
  std::lock_guard<std::mutex> lock(srv->mu);
  srv->data.erase(scope);
}
