"""Ring attention: exact attention over sequences sharded across chips.

Long-context sequence/context parallelism is absent from the reference
(SURVEY.md §5.7 — "no ring attention, no context parallel ... of any
kind"); the survey's build plan adds it as the TPU-native long-context
path: shard the sequence over the 'sp' mesh axis and rotate K/V blocks
around the ring with `ppermute` while accumulating attention online
(flash-attention-style running max/denominator), so each chip only ever
holds seq_len/sp keys — memory O(T/sp) with exact results, and each
ppermute hop overlaps with the block's compute on ICI.

Differentiation is a SECOND ring pass (custom VJP): the forward saves
only (q, k, v, out, lse); the backward recomputes each block's
probabilities from the logsumexp and rotates (k, v, dk, dv) together so
every gradient block arrives back at its owner having accumulated all
ranks' contributions. Without this, autodiff through the forward scan
would checkpoint per-step score matrices — O(sp·T_local²) residuals,
exactly the memory wall ring attention exists to avoid.

Per-device code for use inside shard_map. Causal masking uses global
positions derived from each block's rank of origin.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax


def _ring_perm(sp):
    return [(j, (j + 1) % sp) for j in range(sp)]


def _block_scores(q, k_cur, scale, q_pos, k_pos, causal):
    s = (
        jnp.einsum(
            "bqhd,bkhd->bhqk",
            q,
            k_cur.astype(jnp.float32),
            preferred_element_type=jnp.float32,
        )
        * scale
    )
    if causal:
        mask = q_pos[:, None] >= k_pos[None, :]  # [Tq, Tk]
        s = jnp.where(mask[None, None], s, -jnp.inf)
    return s


def _ring_fwd_pass(q, k, v, axis_name, causal):
    sp = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, t, h, d = q.shape
    scale = 1.0 / jnp.sqrt(d).astype(jnp.float32)
    qf = q.astype(jnp.float32)
    q_pos = my * t + jnp.arange(t)
    perm = _ring_perm(sp)

    def step(carry, i):
        k_cur, v_cur, out, m, denom = carry
        src = (my - i) % sp
        k_pos = src * t + jnp.arange(t)
        scores = _block_scores(qf, k_cur, scale, q_pos, k_pos, causal)
        block_max = jnp.max(scores, axis=-1)  # [B,H,Tq]
        new_m = jnp.maximum(m, block_max)
        # With causal masking a whole block can be -inf; guard the exp.
        safe_m = jnp.where(jnp.isfinite(new_m), new_m, 0.0)
        correction = jnp.exp(jnp.where(jnp.isfinite(m), m - safe_m, -jnp.inf))
        p = jnp.exp(scores - safe_m[..., None])  # masked entries → 0
        denom = denom * correction + jnp.sum(p, axis=-1)
        out = out * correction[..., None] + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_cur.astype(jnp.float32)
        )
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        return (k_next, v_next, out, new_m, denom), None

    out0 = jnp.zeros((b, h, t, d), jnp.float32)
    m0 = jnp.full((b, h, t), -jnp.inf, jnp.float32)
    denom0 = jnp.zeros((b, h, t), jnp.float32)
    (_, _, out, m, denom), _ = lax.scan(
        step, (k, v, out0, m0, denom0), jnp.arange(sp)
    )
    denom_safe = jnp.maximum(denom, 1e-30)
    out = out / denom_safe[..., None]
    # lse in the same guarded convention as the flash kernels
    lse = jnp.where(jnp.isfinite(m), m, 0.0) + jnp.log(denom_safe)
    return (
        jnp.einsum("bhqd->bqhd", out).astype(q.dtype),
        lse,  # [B, H, Tq] fp32
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def ring_attention(q, k, v, axis_name: str = "sp", causal: bool = False):
    """q, k, v: [B, T_local, H, Dh] (this chip's sequence shard).

    Returns [B, T_local, H, Dh] — exact softmax(QKᵀ)V over the full
    (sp·T_local)-token sequence. Differentiable via the second-ring-pass
    VJP (module docstring)."""
    out, _ = _ring_fwd_pass(q, k, v, axis_name, causal)
    return out


def _ring_attention_fwd(q, k, v, axis_name, causal):
    out, lse = _ring_fwd_pass(q, k, v, axis_name, causal)
    return out, (q, k, v, out, lse)


def _ring_attention_bwd(axis_name, causal, res, do):
    q, k, v, out, lse = res
    sp = lax.axis_size(axis_name)
    my = lax.axis_index(axis_name)
    b, t, h, d = q.shape
    scale = 1.0 / jnp.sqrt(d).astype(jnp.float32)
    qf = q.astype(jnp.float32)
    dof = do.astype(jnp.float32)
    q_pos = my * t + jnp.arange(t)
    perm = _ring_perm(sp)
    # delta = rowsum(dO ⊙ O) per query row — [B,H,Tq]
    delta = jnp.einsum(
        "bqhd,bqhd->bhq", dof, out.astype(jnp.float32)
    )

    def step(carry, i):
        k_cur, v_cur, dk_cur, dv_cur, dq = carry
        src = (my - i) % sp
        k_pos = src * t + jnp.arange(t)
        s = _block_scores(qf, k_cur, scale, q_pos, k_pos, causal)
        p = jnp.exp(s - lse[..., None])  # [B,H,Tq,Tk]; masked → 0
        dp = jnp.einsum(
            "bqhd,bkhd->bhqk", dof, v_cur.astype(jnp.float32)
        )
        ds = p * (dp - delta[..., None])
        dq = dq + scale * jnp.einsum(
            "bhqk,bkhd->bqhd", ds, k_cur.astype(jnp.float32)
        )
        dk_cur = dk_cur + scale * jnp.einsum(
            "bhqk,bqhd->bkhd", ds, qf
        )
        dv_cur = dv_cur + jnp.einsum("bhqk,bqhd->bkhd", p, dof)
        # The gradient blocks travel WITH their K/V blocks; after sp
        # hops every block is home with all contributions on board.
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        dk_next = lax.ppermute(dk_cur, axis_name, perm)
        dv_next = lax.ppermute(dv_cur, axis_name, perm)
        return (k_next, v_next, dk_next, dv_next, dq), None

    dk0 = jnp.zeros((b, t, h, d), jnp.float32)
    dv0 = jnp.zeros((b, t, h, d), jnp.float32)
    dq0 = jnp.zeros((b, t, h, d), jnp.float32)
    (k_back, v_back, dk, dv, dq), _ = lax.scan(
        step, (k, v, dk0, dv0, dq0), jnp.arange(sp)
    )
    del k_back, v_back
    return (
        dq.astype(q.dtype),
        dk.astype(k.dtype),
        dv.astype(v.dtype),
    )


ring_attention.defvjp(_ring_attention_fwd, _ring_attention_bwd)
