#!/usr/bin/env bash
# Round-4 chip work, part c. Part b ran captures back-to-back with two
# blind attempts each; when the backend went into an outage mid-list
# (gpt2_medium's claim sat >25 min), that discipline would have burned
# ~50 min per remaining capture and captured nothing. This part:
#   * waits for any in-flight bench process to finish and finalizes its
#     artifact (a claim in the queue must not be killed — it would
#     waste the queue slot);
#   * skips captures whose artifact already exists (resume semantics);
#   * after any failed capture, PROBES the backend (one untimed claim —
#     the ~25-min UNAVAILABLE report is the probe) until it answers,
#     then retries that capture once before moving on;
#   * finishes with a clean back-to-back stem A/B (the part-a resnet50
#     default capture overlapped a 14-min pytest run on the host, so
#     conv7 2511 vs s2d 2585 is load-confounded).
set -uo pipefail
cd "$(dirname "$0")/.."
mkdir -p bench_results
R=r04

finalize() {  # finalize <name>: adopt a finished .tmp if it has JSON
  local out="bench_results/$1_${R}.json"
  if [ -f "$out.tmp" ] && grep -qE '^\{' "$out.tmp"; then
    grep -E '^\{' "$out.tmp" > "$out"
    rm -f "$out.tmp" "bench_results/$1_${R}.err"
    echo "=== finalized $1 from previous part:" >&2
    cat "$out" >&2
  fi
}

echo "=== waiting for in-flight bench processes" >&2
while pgrep -f "python bench_lm.py|python bench.py" >/dev/null 2>&1; do
  sleep 60
done
finalize gpt2_medium

probe_backend() {
  timeout 7200 python - <<'PYEOF' >/dev/null 2>&1
import jax
assert jax.devices()[0].platform == "tpu"
PYEOF
}

wait_backend() {
  echo "=== probing TPU backend $(date -u +%H:%M)" >&2
  until probe_backend; do
    echo "backend still down $(date -u +%H:%M); retry in 300s" >&2
    sleep 300
  done
  echo "=== backend UP $(date -u +%H:%M)" >&2
}

run_one() {  # run_one <name> <cmd...>: one attempt, true iff artifact
  local name="$1"; shift
  local out="bench_results/${name}_${R}.json"
  echo "=== $name $(date -u +%H:%M)" >&2
  "$@" > "$out.tmp" 2> "bench_results/${name}_${R}.err"
  if grep -qE '^\{' "$out.tmp"; then
    grep -E '^\{' "$out.tmp" > "$out"
    rm -f "$out.tmp" "bench_results/${name}_${R}.err"
    cat "$out" >&2
    return 0
  fi
  rm -f "$out.tmp"
  return 1
}

cap() {  # cap <name> <cmd...>: skip-if-done; gate on backend after fail
  local name="$1"
  local out="bench_results/${name}_${R}.json"
  if [ -s "$out" ]; then
    echo "=== $name already captured, skipping" >&2
    return 0
  fi
  if run_one "$@"; then return 0; fi
  echo "=== $name failed; gating on backend health before one retry" >&2
  wait_backend
  if run_one "$@"; then return 0; fi
  echo "FAILED $name twice with backend up (see .err)" >&2
  return 1
}

cap gpt2_medium        env BENCH_MODEL=gpt2_medium python bench_lm.py
for blk in 64 256 512; do
  cap gpt2_blk${blk}   env BENCH_MODEL=gpt2_medium BENCH_FLASH_BLOCK=${blk} python bench_lm.py
done
cap gpt2_noremat_b16   env BENCH_MODEL=gpt2_medium BENCH_BATCH=16 BENCH_REMAT=0 python bench_lm.py
cap gpt2_seq1024       env BENCH_MODEL=gpt2_medium BENCH_BATCH=4 BENCH_SEQ=1024 python bench_lm.py
cap bert_large         env BENCH_MODEL=bert_large python bench_lm.py
cap bert_noremat_b16   env BENCH_MODEL=bert_large BENCH_BATCH=16 BENCH_REMAT=0 python bench_lm.py
cap vit_b16            env BENCH_INNER=1 BENCH_MODEL=vit_b16 python bench.py
cap allreduce          python bench_allreduce.py
cap resnet50_b512      env BENCH_INNER=1 BENCH_BATCH=512 python bench.py

# clean stem A/B, back-to-back on an idle host (replaces the
# load-confounded part-a default capture if it wins)
cap resnet50_clean     env BENCH_INNER=1 python bench.py
cap resnet50_s2d_clean env BENCH_INNER=1 BENCH_STEM=space_to_depth python bench.py

echo "=== chipwork_r04c complete $(date -u +%H:%M)" >&2
