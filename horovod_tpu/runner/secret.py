"""HMAC message signing for runner RPC.

Rebuild of the reference's signed-payload scheme (ref:
horovod/runner/common/util/secret.py [V] — SURVEY.md §2.5 "RPC
plumbing"): the driver generates a per-job secret key, every
request/response body is authenticated with HMAC-SHA256, and services
reject anything whose digest doesn't verify. This is what stops a
stray process on the cluster network from injecting rendezvous traffic.
"""

from __future__ import annotations

import hashlib
import hmac
import secrets as _secrets

DIGEST_BYTES = hashlib.sha256().digest_size


def make_secret_key() -> bytes:
    """Fresh 256-bit random key, one per launched job."""
    return _secrets.token_bytes(32)


def sign(key: bytes, payload: bytes) -> bytes:
    return hmac.new(key, payload, hashlib.sha256).digest()


def verify(key: bytes, payload: bytes, digest: bytes) -> bool:
    return hmac.compare_digest(sign(key, payload), digest)
