"""``hvd.serve`` — the elastic multi-host inference plane.

Seven PRs of training substrate (gang rendezvous, elastic driver,
donated fused executables, shape-bucketed executor caches, /metrics
telemetry, straggler ledger) turned into an inference fleet: continuous
batching over a fixed-shape donated decode step, a two-tier
(exact/bucket) prefill executor cache on the prompt-length axis, a
slot-based KV-cache manager, SLO-metered TTFT/TPOT on the existing
scrape endpoint, capacity announcements + straggler-aware routing over
the rendezvous KV, and a SIGTERM drain that finishes every accepted
request before the worker leaves the gang.

    import horovod_tpu as hvd

    handle = hvd.serve(model, params, port=8500)
    handle.wait()          # POST /generate, GET /healthz|/metrics|/stats

Layers (docs/serving.md): models/transformer.py owns the incremental-
decode model contract; `engine` the compiled prefill/decode split;
`kv_cache` the slots; `batcher` the scheduler; `slo` the latency
meters; `frontend` HTTP + fleet routing.
"""

from .batcher import (  # noqa: F401
    ContinuousBatcher,
    Rejected,
    Request,
)
from .engine import InferenceEngine  # noqa: F401
from .frontend import (  # noqa: F401
    Router,
    ServeFrontend,
    ServeHandle,
    read_announcements,
    serve,
)
from .kv_cache import KVCacheManager  # noqa: F401
from .slo import LatencyRecorder  # noqa: F401
