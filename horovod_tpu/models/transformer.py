"""Transformer family covering the reference's language-model benchmark
configs (BASELINE.json: BERT-large pretraining, GPT-2 medium [V]).

One configurable implementation: ``causal=True`` → GPT-2-style decoder;
``causal=False`` → BERT-style encoder. TPU-first: bfloat16 activations,
fp32 layernorm/softmax accumulation, static shapes, `remat` for
HBM-bound configs, head dims sized for the MXU (multiples of 128 at
real scale).

The distributed execution path (tp/sp/pp/ep over a mesh) lives in
horovod_tpu/parallel/ — this module is the single-chip / pure-DP model.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from ..ops.flash_attention import DEFAULT_BLOCK as _DEFAULT_FLASH_BLOCK


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 50257
    num_layers: int = 12
    d_model: int = 768
    num_heads: int = 12
    d_ff: int = 3072
    max_len: int = 1024
    causal: bool = True
    dropout_rate: float = 0.0
    dtype: Any = jnp.bfloat16
    remat: bool = False
    # Blockwise Pallas attention (ops/flash_attention.py): True/False,
    # or "auto" = use it on TPU whenever no padding mask is passed (the
    # flash path implements the causal mask itself; arbitrary padding
    # masks stay on the dense path, and off-TPU the interpret-mode
    # kernel would only be overhead). True forces it on any backend.
    flash_attention: Any = "auto"
    # Flash kernel block sizes (tunable: bigger blocks = fewer K/V loop
    # iterations and larger MXU matmuls, more VMEM per program). Auto-
    # shrunk to the sequence length when it is shorter. The default
    # (ops.flash_attention.DEFAULT_BLOCK = 512) won the round-4 on-chip
    # sweep on GPT-2-medium seq-512 (83.0 samp/s / MFU 0.563 vs 60.3 /
    # 0.409 at 128 — bench_results/gpt2_blk*_r04); VMEM per program
    # stays modest because K/V are staged whole-sequence regardless of
    # block_k, so bigger blocks only grow the (block_q, block_k) score
    # tile (512x512 fp32 = 1 MiB).
    flash_block_q: int = _DEFAULT_FLASH_BLOCK
    flash_block_k: int = _DEFAULT_FLASH_BLOCK
    # Rotary position embeddings (Llama/Mistral-style) applied to q/k
    # inside every attention block. When on, the learned absolute
    # position embedding is skipped — RoPE carries all position signal.
    # Orthogonal to the flash kernels (the rotation happens on q/k
    # before they enter attention).
    rope: bool = False
    rope_base: float = 10000.0
    # Mistral-style causal sliding window (requires causal=True): row r
    # attends (r-window, r]. On the flash path the band is masked
    # in-kernel with the block loops clamped to it; the dense path
    # builds the band mask explicitly.
    sliding_window: Optional[int] = None
    # Grouped-query attention (Llama/Mistral-style): number of KV heads
    # (must divide num_heads). None = MHA (one kv head per q head, the
    # fused qkv projection — param-tree-compatible with existing
    # checkpoints). Setting it splits the projection into "q" and "kv"
    # and the kernels read shared KV rows directly (no repeat ever
    # materializes).
    num_kv_heads: Optional[int] = None
    # Switch-MoE FFN (PR 12): 0 = the dense FFN (param-tree-compatible
    # with existing checkpoints). >0 replaces every block's FFN with a
    # top-1-routed expert bank of this many experts — the serving twin
    # of parallel/moe.py's moe_ffn. Routing is DATA (argmax over the
    # router logits), shapes are static (every expert's weights are
    # applied through a one-hot einsum), so the serving engine's
    # zero-retrace invariant holds: decode_compiles==1 across rolling
    # admissions with routing changing per token. Expert weights are
    # stacked on a leading [E] axis — `shard_moe_params` places them
    # over a mesh 'ep' axis for expert-sharded decode (GSPMD partitions
    # the expert einsums; hvd.serve threads it via engine ep_axis=).
    moe_experts: int = 0
    # LM head precision. True (default): bf16 operands on the MXU with
    # fp32 accumulation (preferred_element_type) and fp32 logits out —
    # the standard TPU head recipe; input rounding is bf16-epsilon on
    # logits while softmax/loss stay full fp32. False: the all-fp32
    # head (operands cast up, matmul at fp32 MXU rate — several times
    # slower on a vocab_size-wide projection that is ~15% of forward
    # FLOPs at GPT-2 scale).
    head_mixed_precision: bool = True

    def uses_flash(self, mask=None, seq=None) -> bool:
        """THE gating rule for the Pallas flash path — single source
        of truth for the model and for bench_lm's FLOPs correction.
        Pass ``seq`` when known: untileable lengths (e.g. ViT's 197
        tokens — no power-of-two block divides them) take the dense
        path rather than failing Mosaic's block constraints."""
        if mask is not None:
            return False
        if seq is not None:
            from ..ops.flash_attention import fits_vmem, supports_seq

            if not supports_seq(
                seq, self.flash_block_q, self.flash_block_k
            ):
                return False
            # The backward dK/dV kernel stages the whole q-head group
            # whole-sequence; past the VMEM budget the dense path is
            # the one that compiles (ADVICE r4).
            import numpy as _np

            if not fits_vmem(
                seq,
                self.d_model // self.num_heads,
                self.num_heads // (self.num_kv_heads or self.num_heads),
                _np.dtype(self.dtype).itemsize,
                self.flash_block_k,
            ):
                return False
        if self.flash_attention == "auto":
            import jax as _jax

            return _jax.default_backend() == "tpu"
        return bool(self.flash_attention)

    @staticmethod
    def gpt2_medium() -> "TransformerConfig":
        """BASELINE.json config #4 (GPT-2 medium, 345M)."""
        return TransformerConfig(
            num_layers=24, d_model=1024, num_heads=16, d_ff=4096, causal=True
        )

    @staticmethod
    def bert_large() -> "TransformerConfig":
        """BASELINE.json config #3 (BERT-large, 340M)."""
        return TransformerConfig(
            vocab_size=30522,
            num_layers=24,
            d_model=1024,
            num_heads=16,
            d_ff=4096,
            max_len=512,
            causal=False,
        )

    @staticmethod
    def tiny(causal: bool = True) -> "TransformerConfig":
        """Test-sized config."""
        return TransformerConfig(
            vocab_size=256,
            num_layers=2,
            d_model=64,
            num_heads=4,
            d_ff=128,
            max_len=128,
            causal=causal,
            dtype=jnp.float32,
        )


def apply_rope(x, base: float = 10000.0, offset=0):
    """Rotate [batch, seq, heads, head_dim] q or k by absolute position
    (RoFormer). Pairs are (x[..., :d/2], x[..., d/2:]) — the
    'rotate-half' convention — so the op is two multiplies and one
    concat, fully XLA-fusible. fp32 trig regardless of input dtype;
    ``offset`` shifts positions: a scalar (sequence-parallel shards
    pass their global start — may be a traced value, e.g.
    axis_index·t_local) or a ``[batch]`` array (incremental decode:
    every cache slot sits at its own position)."""
    b, t, h, d = x.shape
    half = d // 2
    if d % 2:
        raise ValueError(f"RoPE needs an even head_dim, got {d}")
    # offset + iota rather than arange(offset, ...) so traced offsets
    # (SP shards, decode cache indices) work
    pos = jnp.asarray(offset, jnp.float32)[..., None] + jnp.arange(
        t, dtype=jnp.float32
    )  # [t] for scalar offsets, [b, t] for per-slot offsets
    inv_freq = base ** (
        -jnp.arange(0, half, dtype=jnp.float32) / half
    )
    angles = pos[..., :, None] * inv_freq  # [(b,) t, half]
    cos = jnp.cos(angles)
    sin = jnp.sin(angles)
    if angles.ndim == 2:  # scalar offset: broadcast over batch
        cos, sin = cos[None], sin[None]
    cos, sin = cos[:, :, None, :], sin[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    xf1, xf2 = x1.astype(jnp.float32), x2.astype(jnp.float32)
    return jnp.concatenate(
        [xf1 * cos - xf2 * sin, xf2 * cos + xf1 * sin], axis=-1
    ).astype(x.dtype)


def init_cache(cfg: TransformerConfig, batch: int, max_len=None, dtype=None):
    """Allocate an empty decode KV cache: one ``{"k", "v"}`` dict per
    layer, each ``[batch, max_len, num_kv_heads, head_dim]`` of zeros.

    This is the model half of the serving contract
    (horovod_tpu/serving/): the cache rides
    ``Transformer.__call__(cache=, cache_index=)`` — written in place
    (functionally) at each call's positions and returned updated, so a
    jitted decode step can donate it through successive steps. Slots
    never need re-zeroing on reuse: positions at or beyond a slot's
    ``cache_index`` are masked out of attention and every attended
    position is overwritten by prefill/decode before it first becomes
    attendable."""
    seq = int(max_len) if max_len is not None else cfg.max_len
    if not cfg.rope and seq > cfg.max_len:
        # the learned position table has cfg.max_len rows; a longer
        # cache would let decode feed positions past it, and the jitted
        # gather CLAMPS out-of-range indices instead of raising —
        # silently wrong logits, so refuse here where it is loud
        raise ValueError(
            f"KV cache max_len ({seq}) exceeds the learned position "
            f"table ({cfg.max_len}); raise cfg.max_len or use rope=True"
        )
    kv_heads = cfg.num_kv_heads or cfg.num_heads
    head_dim = cfg.d_model // cfg.num_heads
    dt = cfg.dtype if dtype is None else dtype
    return [
        {
            "k": jnp.zeros((batch, seq, kv_heads, head_dim), dt),
            "v": jnp.zeros((batch, seq, kv_heads, head_dim), dt),
        }
        for _ in range(cfg.num_layers)
    ]


class MultiHeadAttention(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, mask=None, lengths=None, cache=None,
                 cache_index=None, pages=None, paged_attn=False):
        cfg = self.cfg
        head_dim = cfg.d_model // cfg.num_heads
        if cache is not None:
            if not cfg.causal:
                raise ValueError(
                    "incremental decode (cache=) requires causal=True"
                )
            if mask is not None or lengths is not None:
                raise ValueError(
                    "cache= does not compose with mask=/lengths=: the "
                    "cache_index IS the per-slot length"
                )
        elif pages is not None:
            raise ValueError(
                "pages= (the paged-KV page table) requires cache="
            )
        if cfg.num_kv_heads:
            if cfg.num_heads % cfg.num_kv_heads:
                raise ValueError(
                    f"num_kv_heads ({cfg.num_kv_heads}) must divide "
                    f"num_heads ({cfg.num_heads})"
                )
            q = nn.DenseGeneral(
                (cfg.num_heads, head_dim), dtype=cfg.dtype, name="q"
            )(x)
            kv = nn.DenseGeneral(
                (2, cfg.num_kv_heads, head_dim), dtype=cfg.dtype,
                name="kv",
            )(x)
            k, v = kv[..., 0, :, :], kv[..., 1, :, :]
        else:
            qkv = nn.DenseGeneral(
                (3, cfg.num_heads, head_dim), dtype=cfg.dtype, name="qkv"
            )(x)
            q, k, v = (
                qkv[..., 0, :, :], qkv[..., 1, :, :], qkv[..., 2, :, :]
            )
        if cfg.rope:
            rope_offset = 0 if cache is None else cache_index
            q = apply_rope(q, cfg.rope_base, offset=rope_offset)
            k = apply_rope(k, cfg.rope_base, offset=rope_offset)
        if cache is not None:
            return self._cached_attention(cfg, x, q, k, v, cache,
                                          cache_index, head_dim,
                                          pages=pages,
                                          paged_attn=paged_attn)
        # lengths (right-padding) stays on the flash path — the kernels
        # take it natively; only ARBITRARY masks force dense.
        use_flash = cfg.uses_flash(mask, seq=x.shape[1])
        if cfg.flash_attention and cfg.flash_attention != "auto" and (
            mask is not None
        ):
            # Explicit True + arbitrary mask: the flash kernel
            # implements only causal + right-padding masking, so this
            # degrades to the dense path. Loud, not silent.
            import warnings

            warnings.warn(
                "flash_attention=True but a padding mask was passed; "
                "falling back to dense attention (the flash path "
                "supports causal and lengths= masking only — pass "
                "lengths for right-padded batches)",
                stacklevel=2,
            )
        if use_flash:
            from ..ops.flash_attention import flash_attention

            out = flash_attention(
                q, k, v, causal=cfg.causal,
                block_q=cfg.flash_block_q, block_k=cfg.flash_block_k,
                lengths=lengths, window=cfg.sliding_window,
            )
            return nn.DenseGeneral(
                cfg.d_model, axis=(-2, -1), dtype=cfg.dtype, name="out"
            )(out)
        if cfg.num_kv_heads and cfg.num_kv_heads != cfg.num_heads:
            # dense fallback materializes the head repeat the flash
            # path avoids
            rep = cfg.num_heads // cfg.num_kv_heads
            k = jnp.repeat(k, rep, axis=2)
            v = jnp.repeat(v, rep, axis=2)
        # scores in fp32 for softmax stability
        scores = jnp.einsum(
            "bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32
        ) / jnp.sqrt(head_dim).astype(jnp.float32)
        if cfg.causal:
            t = x.shape[1]
            causal_mask = jnp.tril(jnp.ones((t, t), bool))
            if cfg.sliding_window:
                rows = jnp.arange(t)[:, None]
                cols = jnp.arange(t)[None, :]
                causal_mask = causal_mask & (
                    rows - cols < cfg.sliding_window
                )
            scores = jnp.where(causal_mask[None, None], scores, -1e30)
        elif cfg.sliding_window:
            raise ValueError("sliding_window requires causal=True")
        valid = None
        if lengths is not None:
            # dense twin of the kernel's lengths contract; combined
            # (AND) with an explicit mask rather than ignored, so
            # mask+lengths callers never have valid rows attending to
            # keys past the length
            valid = (
                jnp.arange(x.shape[1])[None, :]
                < jnp.asarray(lengths)[:, None]
            )
            mask = valid if mask is None else (mask & valid)
        if mask is not None:
            scores = jnp.where(mask[:, None, None, :], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs, v)
        if valid is not None:
            # match the flash path: padded query rows are zero
            out = jnp.where(valid[:, :, None, None], out, 0.0)
        return nn.DenseGeneral(
            cfg.d_model, axis=(-2, -1), dtype=cfg.dtype, name="out"
        )(out)

    def _cached_attention(self, cfg, x, q, k, v, cache, cache_index,
                          head_dim, pages=None, paged_attn=False):
        """Incremental-decode attention: write this call's k/v into the
        per-slot cache at ``cache_index`` (each batch row at its own
        position — prefill passes t=prompt tokens at index 0, decode
        passes t=1 at index=length), then attend q against the FULL
        cache under the global causal mask ``key_pos <= query_pos``.
        Positions at or beyond a slot's write frontier are masked to
        exact −1e30 → exact-zero probabilities, so stale slot contents
        (a reused slot, bucket padding) can never leak into the output
        and the dense path stays bit-comparable with the full-sequence
        forward. Returns ``(out, {"k", "v"})`` — the updated cache.

        Two cache layouts share this math:

        * contiguous slab (``pages=None``): per-slot rows
          ``[batch, max_len, kv_heads, head_dim]``, vmapped
          ``dynamic_update_slice`` writes;
        * paged (``pages=[batch, n_pages]`` int32 page table over a
          ``[num_pages, page_tokens, ...]`` block pool,
          `serving/paged_kv.py`): writes scatter into physical pages
          (``pool.at[phys, offset].set(..., mode="drop")`` — the
          sentinel/out-of-range entries of unallocated logical pages
          drop their writes, exactly the pad positions the slab path
          masks away), reads gather the slot's pages back into a
          transient contiguous view. Because a slot's pages tile
          ``max_len`` exactly, the gathered view has the SAME shape and
          the SAME values at every attendable position as the slab
          row, so the attention below is bit-identical between
          layouts — the serving plane's paged-parity contract.

        ``paged_attn=True`` (paged layout only) replaces the
        gather-then-attend READ with the fused Pallas kernel
        (`ops/paged_attention.py`): the kernel's grid walks the page
        table and streams K/V blocks straight from the pool, so the
        transient contiguous view never exists in the lowered program.
        The write scatter above is unchanged, the gather path stays the
        default-off numerics oracle, and unsupported geometries fall
        back to it loudly (``serve.paged_attn_fallbacks``). Outputs
        agree with the oracle to ≤1 ulp of the fp32 softmax (the online
        softmax reassociates the denominator sum) — greedy argmax
        tokens are identical.
        """
        b, t = x.shape[0], x.shape[1]
        idx = jnp.asarray(cache_index, jnp.int32)

        if pages is None:
            seq = cache["k"].shape[1]

            def _write(buf, new, i):
                return jax.lax.dynamic_update_slice(
                    buf, new.astype(buf.dtype), (i, 0, 0)
                )

            k_cache = jax.vmap(_write)(cache["k"], k, idx)
            v_cache = jax.vmap(_write)(cache["v"], v, idx)
        else:
            pages = jnp.asarray(pages, jnp.int32)
            num_pages, page_tokens = cache["k"].shape[:2]
            n_logical = pages.shape[1]
            seq = n_logical * page_tokens
            pos = idx[:, None] + jnp.arange(t)            # [b, t] global
            lp = pos // page_tokens
            off = pos % page_tokens
            # physical page per written token; positions past the table
            # (bucket-pad overhang) route to the out-of-range sentinel
            # and are dropped — they could never become attendable
            phys = jnp.take_along_axis(
                pages, jnp.clip(lp, 0, n_logical - 1), axis=1
            )
            phys = jnp.where(lp < n_logical, phys, num_pages)

            def _scatter(pool, new):
                return pool.at[phys, off].set(
                    new.astype(pool.dtype), mode="drop"
                )

            k_cache = _scatter(cache["k"], k)
            v_cache = _scatter(cache["v"], v)
        new_cache = {"k": k_cache, "v": v_cache}
        if pages is not None and paged_attn:
            from ..ops import paged_attention as _pa

            r = cfg.num_heads // (cfg.num_kv_heads or cfg.num_heads)
            reason = _pa.unsupported_reason(
                head_dim, page_tokens, queries=t * r
            )
            if reason is None and cfg.sliding_window:
                reason = (
                    "sliding_window is not implemented by the paged "
                    "kernel"
                )
            if reason is None:
                out = _pa.paged_attention(
                    q, k_cache, v_cache, pages, idx, causal=True
                )
                return nn.DenseGeneral(
                    cfg.d_model, axis=(-2, -1), dtype=cfg.dtype,
                    name="out",
                )(out), new_cache
            # loud fallback ladder: requested the kernel, geometry (or
            # backend) can't take it — warn at trace time, count it,
            # and ride the gather oracle below
            import warnings

            from ..common.metrics import registry as _metrics

            warnings.warn(
                f"paged_attn=True but the kernel path is unsupported "
                f"({reason}); falling back to the gather read",
                stacklevel=2,
            )
            _metrics.counter("serve.paged_attn_fallbacks")
        if pages is None:
            kk, vv = k_cache, v_cache
        else:
            # gather-from-pages read: reassemble each row's pages in
            # logical order (sentinel entries clamp into arbitrary
            # garbage the causal mask below zeroes exactly)
            def _gather(pool):
                g = jnp.take(pool, pages, axis=0, mode="clip")
                return g.reshape(b, seq, *pool.shape[2:])

            kk, vv = _gather(k_cache), _gather(v_cache)
        if cfg.num_kv_heads and cfg.num_kv_heads != cfg.num_heads:
            rep = cfg.num_heads // cfg.num_kv_heads
            kk = jnp.repeat(kk, rep, axis=2)
            vv = jnp.repeat(vv, rep, axis=2)
        scores = jnp.einsum(
            "bqhd,bkhd->bhqk", q, kk, preferred_element_type=jnp.float32
        ) / jnp.sqrt(head_dim).astype(jnp.float32)
        q_pos = idx[:, None] + jnp.arange(t)          # [b, t] global
        key_pos = jnp.arange(seq)                     # [seq]
        valid = key_pos[None, None, :] <= q_pos[:, :, None]  # [b, t, seq]
        if cfg.sliding_window:
            valid = valid & (
                q_pos[:, :, None] - key_pos[None, None, :]
                < cfg.sliding_window
            )
        scores = jnp.where(valid[:, None], scores, -1e30)
        probs = jax.nn.softmax(scores, axis=-1).astype(cfg.dtype)
        out = jnp.einsum("bhqk,bkhd->bqhd", probs, vv)
        return nn.DenseGeneral(
            cfg.d_model, axis=(-2, -1), dtype=cfg.dtype, name="out"
        )(out), new_cache


class MoEFFN(nn.Module):
    """Switch-style top-1 MoE FFN for decode/serving: router logits in
    fp32, argmax routing (pure DATA — shapes never depend on it), and
    the expert bank applied through dense one-hot einsums over the
    leading ``[E]`` axis (MXU-friendly, no gather/scatter; at decode
    scale — slots tokens per step — the E-fold FLOPs are noise next to
    attention over the cache, and under an 'ep'-sharded bank GSPMD
    partitions the einsum so each shard computes only its experts).
    Dropped-token capacity logic does not exist here: every token is
    served by exactly its routed expert, gated by the router prob —
    exact, static, retrace-free."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        e = cfg.moe_experts
        d, f = cfg.d_model, cfg.d_ff
        logits = nn.Dense(e, dtype=jnp.float32, name="router")(
            x.astype(jnp.float32)
        )  # [b, t, E]
        probs = jax.nn.softmax(logits, axis=-1)
        idx = jnp.argmax(probs, axis=-1)  # [b, t]
        gate = jnp.take_along_axis(probs, idx[..., None], axis=-1)
        sel = jax.nn.one_hot(idx, e, dtype=cfg.dtype)  # [b, t, E]
        scale = nn.initializers.lecun_normal(in_axis=-2, out_axis=-1)
        w1 = self.param("w1", scale, (e, d, f), jnp.float32)
        b1 = self.param("b1", nn.initializers.zeros, (e, f), jnp.float32)
        w2 = self.param("w2", scale, (e, f, d), jnp.float32)
        b2 = self.param("b2", nn.initializers.zeros, (e, d), jnp.float32)
        w1, b1 = w1.astype(cfg.dtype), b1.astype(cfg.dtype)
        w2, b2 = w2.astype(cfg.dtype), b2.astype(cfg.dtype)
        h = jnp.einsum("btd,edf,bte->btf", x, w1, sel)
        h = h + jnp.einsum("ef,bte->btf", b1, sel)
        h = nn.gelu(h)
        y = jnp.einsum("btf,efd,bte->btd", h, w2, sel)
        y = y + jnp.einsum("ed,bte->btd", b2, sel)
        # cfg.dtype, not x.dtype: the input is the fp32 LayerNorm
        # output, and the dense FFN branch this replaces emits
        # cfg.dtype activations — the residual contract must match
        return (y * gate).astype(cfg.dtype)


class Block(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x, mask=None, train: bool = True, lengths=None,
                 cache=None, cache_index=None, pages=None,
                 paged_attn=False):
        cfg = self.cfg
        h = nn.LayerNorm(dtype=jnp.float32)(x)
        new_cache = None
        if cache is None:
            h = MultiHeadAttention(cfg)(h, mask, lengths)
        else:
            h, new_cache = MultiHeadAttention(cfg)(
                h, mask, lengths, cache=cache, cache_index=cache_index,
                pages=pages, paged_attn=paged_attn,
            )
        h = nn.Dropout(cfg.dropout_rate, deterministic=not train)(h)
        x = x + h
        h = nn.LayerNorm(dtype=jnp.float32)(x)
        if cfg.moe_experts:
            h = MoEFFN(cfg, name="moe")(h)
        else:
            h = nn.Dense(cfg.d_ff, dtype=cfg.dtype)(h)
            h = nn.gelu(h)
            h = nn.Dense(cfg.d_model, dtype=cfg.dtype)(h)
        h = nn.Dropout(cfg.dropout_rate, deterministic=not train)(h)
        if cache is None:
            return x + h
        return x + h, new_cache


def shard_moe_params(params, mesh, ep_axis: str = "ep"):
    """Place every MoE expert bank (``.../moe/{w1,b1,w2,b2}`` — the
    leading-``[E]`` stacked leaves of :class:`MoEFFN`) over the mesh's
    ``ep_axis`` with ``NamedSharding(P(ep_axis))``, leaving everything
    else exactly where it is — the serving engine's expert-sharding
    hook (``InferenceEngine(ep_axis=)``): under jit, GSPMD partitions
    the one-hot expert einsums so each shard computes only its local
    experts' FFN — expert-sharded dispatch inside the fixed-shape
    decode step, no shape (and so no retrace) anywhere. The router
    stays replicated (routing is per-token data every shard needs).
    No-op when the mesh lacks the axis, or the axis does not divide
    the expert count (loud — silent replication would quietly undo
    expert parallelism)."""
    import jax as _jax
    from jax.sharding import NamedSharding, PartitionSpec as _P

    if mesh is None or ep_axis not in mesh.axis_names:
        return params
    ep = mesh.shape[ep_axis]
    if ep <= 1:
        return params

    moe_leaves = {"w1", "b1", "w2", "b2"}

    def _walk(node, path):
        if isinstance(node, dict):
            return {k: _walk(v, path + (k,)) for k, v in node.items()}
        if len(path) >= 2 and path[-2] == "moe" and path[-1] in moe_leaves:
            if node.shape[0] % ep:
                raise ValueError(
                    f"moe_experts ({node.shape[0]}) must divide over "
                    f"the '{ep_axis}' mesh axis ({ep})"
                )
            return _jax.device_put(
                node, NamedSharding(mesh, _P(ep_axis))
            )
        return node

    return _walk(params, ())


class LMHead(nn.Module):
    """Vocabulary projection with the TPU mixed-precision recipe (see
    TransformerConfig.head_mixed_precision). Same param tree as the
    nn.Dense it replaces (kernel fp32 [d_model, vocab], bias fp32), so
    checkpoints are layout-compatible either way."""

    cfg: TransformerConfig

    @nn.compact
    def __call__(self, x):
        cfg = self.cfg
        kernel = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            (cfg.d_model, cfg.vocab_size),
            jnp.float32,
        )
        bias = self.param(
            "bias", nn.initializers.zeros, (cfg.vocab_size,), jnp.float32
        )
        if cfg.head_mixed_precision:
            y = jax.lax.dot_general(
                x.astype(cfg.dtype),
                kernel.astype(cfg.dtype),
                dimension_numbers=(((x.ndim - 1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
        else:
            y = x.astype(jnp.float32) @ kernel
        return y + bias


class Transformer(nn.Module):
    cfg: TransformerConfig

    @nn.compact
    def __call__(
        self, tokens, mask=None, train: bool = True,
        return_hidden: bool = False, lengths=None,
        cache=None, cache_index=None, pages=None, paged_attn=False,
    ):
        cfg = self.cfg
        x = nn.Embed(cfg.vocab_size, cfg.d_model, dtype=cfg.dtype)(tokens)
        if not cfg.rope:
            if cache is None:
                positions = jnp.arange(tokens.shape[1])[None]
            else:
                # incremental decode: each cache slot sits at its own
                # absolute position (its current length)
                positions = (
                    jnp.asarray(cache_index, jnp.int32)[:, None]
                    + jnp.arange(tokens.shape[1])
                )
            pos = nn.Embed(cfg.max_len, cfg.d_model, dtype=cfg.dtype)(
                positions
            )
            x = x + pos
        if cache is not None:
            # KV-cache-threaded forward (the serving engine's model
            # contract, horovod_tpu/serving/engine.py): same param
            # tree, same block stack, dense attention over the cache.
            # pages= switches the layout to the paged block pool
            # (serving/paged_kv.py) — the table is shared by every
            # layer, each layer's pool is its cache[i] entry.
            # remat is a backward-pass memory trade — inference-only
            # path, so it never wraps here.
            if return_hidden:
                raise ValueError("return_hidden with cache= is not supported")
            new_cache = []
            for i in range(cfg.num_layers):
                x, layer_cache = Block(cfg, name=f"block_{i}")(
                    x, mask, train, lengths,
                    cache=cache[i], cache_index=cache_index,
                    pages=pages, paged_attn=paged_attn,
                )
                new_cache.append(layer_cache)
            x = nn.LayerNorm(dtype=jnp.float32)(x)
            return LMHead(cfg, name="lm_head")(x), new_cache
        block = Block
        if cfg.remat:
            block = nn.remat(Block, static_argnums=(3,))
        for i in range(cfg.num_layers):
            x = block(cfg, name=f"block_{i}")(x, mask, train, lengths)
        x = nn.LayerNorm(dtype=jnp.float32)(x)
        if return_hidden:
            # pre-head activations for the chunked fused loss
            # (ops/fused_xent.py): callers apply the lm_head params
            # through fused_linear_cross_entropy and never materialize
            # the (tokens, vocab) logits. Param tree is unchanged —
            # init traces the default path below.
            return x
        # fp32 logits; matmul precision per cfg.head_mixed_precision
        return LMHead(cfg, name="lm_head")(x)
