"""Synthetic ResNet-50 benchmark — parity with the reference's headline
harness (ref: examples/pytorch/pytorch_synthetic_benchmark.py [V]:
ResNet-50, synthetic ImageNet batches, reports img/sec; BASELINE.md
north star tracks the same metric on TPU).

Prints ONE JSON line:
  {"metric": "resnet50_synth_img_per_sec", "value": N, "unit": "img/s",
   "vs_baseline": R, "platform": "...", "mfu": M, "tflops_per_sec": T}

vs_baseline compares against the canonical single-P100 fp32 ResNet-50
throughput (~219 img/s, the tf_cnn_benchmarks number contemporaneous with
the reference's published scaling figures — BASELINE.md [V]): the
reference's own benchmark prints absolute img/sec per device, so the
honest single-chip comparison is chip vs chip. MFU is measured FLOP/s
(XLA cost analysis of the compiled train step) over the chip's peak
bf16 FLOP/s.

Resilience: the default invocation is an ORCHESTRATOR that runs the
measurement in a fresh subprocess (BENCH_INNER=1), retrying with backoff
when the TPU backend is unavailable (the sandbox's known stuck-chip-claim
failure mode — BENCH_r01 died on first touch with rc=1). If every TPU
attempt fails it falls back — in order of usefulness — to (a) the most
recent committed REAL-TPU artifact for this metric in bench_results/
(reprinted with "stale": true + capture timestamp), then (b) a small
honest CPU run (platform=cpu + error note), so the driver ALWAYS gets a
parseable line. The whole orchestration is budgeted to finish inside
~16 minutes by default: round 3's lesson (BENCH_r03 rc=124) is that a
budget sized for "eventually get a TPU number" (70 min) can outlive the
DRIVER's own timeout during a backend outage, recording a hang instead
of a number. The budget must lose to the driver's clock, never the
other way around.

Env knobs: BENCH_BATCH (default 256 — measured-best MXU utilization on
the v5e-class chip; the reference harness defaults to 32, which here
leaves ~15% throughput on the table), BENCH_ITERS, BENCH_WARMUP,
BENCH_PLATFORM=cpu to force the host platform, BENCH_ATTEMPTS,
BENCH_ATTEMPT_TIMEOUT (s, per attempt — capped by the budget),
BENCH_TOTAL_BUDGET (s, whole-orchestration cap, default 900: attempts
start only while a window plus fallback headroom fits), BENCH_STALE=0
to disable the stale-artifact fallback, BENCH_PEAK_TFLOPS to override
the MFU denominator.
"""

import json
import os
import subprocess
import sys
import time

from _benchlib import stamp as _stamp

P100_FP32_IMG_PER_SEC = 219.0

from _benchlib import aot_compile as _aot_compile  # noqa: E402
from _benchlib import mfu_fields as _mfu_fields  # noqa: E402


def inner_main():
    if os.environ.get("BENCH_FAIL_INNER"):
        # Test hook: simulate a backend-unavailable attempt instantly so
        # the orchestrator's fallback ladder is testable without a real
        # 20-minute chip-claim failure.
        print("simulated backend failure (BENCH_FAIL_INNER)", file=sys.stderr)
        raise SystemExit(3)
    model_name = os.environ.get("BENCH_MODEL", "resnet50")
    batch = int(os.environ.get("BENCH_BATCH", "256"))
    n_iters = int(os.environ.get("BENCH_ITERS", "20"))
    n_warmup = int(os.environ.get("BENCH_WARMUP", "3"))

    import jax

    if os.environ.get("BENCH_PLATFORM"):
        jax.config.update("jax_platforms", os.environ["BENCH_PLATFORM"])

    import jax.numpy as jnp
    import numpy as np
    import optax
    from functools import partial

    # The reference's synthetic-benchmark model family
    # (docs/benchmarks.rst: ResNet-50/101, Inception V3, VGG-16 [V]).
    from horovod_tpu import models as model_zoo

    image_size = 224
    # space_to_depth is the measured-best default (r04 A/B: 2585 vs
    # 2511 img/s; exact same function — equivalence proven in
    # tests/test_models.py). BENCH_STEM=conv7 keeps the control.
    stem = os.environ.get("BENCH_STEM", "space_to_depth")
    if model_name == "resnet50":
        model = model_zoo.ResNet50(dtype=jnp.bfloat16, stem=stem)
    elif model_name == "resnet101":
        model = model_zoo.ResNet101(dtype=jnp.bfloat16, stem=stem)
    elif model_name == "inception_v3":
        model = model_zoo.InceptionV3(dtype=jnp.bfloat16)
        image_size = 299
    elif model_name == "vgg16":
        model = model_zoo.VGG16(dtype=jnp.bfloat16)
    elif model_name == "vit_b16":
        # BASELINE.json config #5's model (the elastic-bench pairing);
        # LayerNorm-based, so the batch_stats collection stays empty.
        # BENCH_VIT_FLASHPAD: auto (default) pads 197->200 tokens and
        # runs the flash kernels with lengths=197 on TPU; 0 keeps the
        # dense control. Recorded as "attn" on the artifact.
        import dataclasses as _dc

        _fp = os.environ.get("BENCH_VIT_FLASHPAD", "auto")
        vit_cfg = model_zoo.ViTConfig.b16()
        if _fp in ("0", "false", "off"):
            vit_cfg = _dc.replace(vit_cfg, flash_pad=False)
        model = model_zoo.ViT(vit_cfg)
    else:
        raise SystemExit(f"unknown BENCH_MODEL {model_name!r}")

    platform = jax.devices()[0].platform
    rng = jax.random.PRNGKey(0)
    images = jnp.asarray(
        np.random.default_rng(0).uniform(
            size=(batch, image_size, image_size, 3)
        ),
        jnp.bfloat16,
    )
    labels = jnp.zeros((batch,), jnp.int32)
    variables = jax.jit(lambda: model.init(rng, images, train=False))()
    params = variables["params"]
    batch_stats = variables.get("batch_stats", {})
    opt = optax.sgd(0.01, momentum=0.9)
    opt_state = opt.init(params)

    # Donating the carried state lets XLA update params/opt-state in
    # place instead of allocating fresh buffers every step — the same
    # HBM-traffic discipline the fusion-buffer reuse gives the reference.
    dropout_rng = jax.random.PRNGKey(42)

    @partial(jax.jit, donate_argnums=(0, 1, 2))
    def train_step(params, batch_stats, opt_state, images, labels):
        def loss_fn(p):
            logits, mutated = model.apply(
                {"params": p, "batch_stats": batch_stats},
                images,
                train=True,
                mutable=["batch_stats"],
                rngs={"dropout": dropout_rng},
            )
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, labels
            ).mean()
            return loss, mutated.get("batch_stats", {})

        (loss, new_stats), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params
        )
        updates, opt_state = opt.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, new_stats, opt_state, loss

    train_step, flops = _aot_compile(
        train_step, params, batch_stats, opt_state, images, labels
    )
    from _benchlib import bytes_accessed as _bytes_accessed

    step_bytes = _bytes_accessed(train_step)

    from _benchlib import sync as _sync

    loss = None
    for _ in range(n_warmup):
        params, batch_stats, opt_state, loss = train_step(
            params, batch_stats, opt_state, images, labels
        )
    if loss is not None:
        # host transfer: the only trustworthy sync (see _benchlib)
        _sync(loss)

    t0 = time.perf_counter()
    for _ in range(n_iters):
        params, batch_stats, opt_state, loss = train_step(
            params, batch_stats, opt_state, images, labels
        )
    _sync(loss)  # loss chains through every step's params
    dt = time.perf_counter() - t0

    img_per_sec = batch * n_iters / dt
    import datetime

    result = {
        "metric": f"{model_name}_synth_img_per_sec",
        "value": round(img_per_sec, 2),
        "unit": "img/s",
        "vs_baseline": round(img_per_sec / P100_FP32_IMG_PER_SEC, 3),
        "platform": platform,
        "batch": batch,
        # capture-time stamp: the stale-artifact fallback trusts this
        # over file mtime (which a fresh checkout rewrites)
        "captured_at": datetime.datetime.now(datetime.timezone.utc).strftime(
            "%Y-%m-%dT%H:%M:%SZ"
        ),
    }
    if model_name.startswith("resnet"):
        # config provenance: the stale-artifact fallback must not
        # substitute a stem-variant probe for the default config
        result["stem"] = stem
    if model_name == "vit_b16":
        # flash-pad engages on TPU under the auto default (r04: the
        # padded kernels made ViT's 197 tokens tileable via 200+lengths)
        result["attn"] = _vit_attn_mode(platform)
    result.update(
        _mfu_fields(flops, n_iters, dt, platform, step_bytes=step_bytes)
    )
    print(json.dumps(_stamp(result)))


def _spawn(env, timeout):
    try:
        return subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired as e:
        def _txt(v):
            return v.decode(errors="replace") if isinstance(v, bytes) else (
                v or "")

        return subprocess.CompletedProcess(
            e.cmd, 124, _txt(e.stdout),
            _txt(e.stderr) + f"\n[timeout after {timeout}s]",
        )


def _extract_json(stdout):
    for line in reversed(stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def _vit_attn_mode(platform: str) -> str:
    """ViT attention-engine provenance (the artifact's "attn" field):
    ONE predicate shared by inner_main's stamp and the stale-gate's
    expectation so the two can't drift (ADVICE r4). flash_pad engages
    only on TPU under the auto default."""
    if os.environ.get("BENCH_VIT_FLASHPAD", "auto") in (
        "0", "false", "off"
    ):
        return "dense"
    return "flash_pad" if platform == "tpu" else "dense"


def _stale_artifact(metric, config=None):
    """Most recent committed REAL-TPU measurement for `metric` (and
    matching `config` fields) under bench_results/. Returns
    (parsed_dict, path, when) or None.

    This is the outage insurance VERDICT r3 asked for: when the backend
    is down for the driver's end-of-round capture but a same-metric TPU
    artifact was captured earlier (the nohup capture loops run all
    round), the round's official line is that number marked stale —
    not a timeout, and not a CPU number pretending nothing happened.

    `config` maps field name -> (required value, default when the
    artifact omits the field): exploratory probes (space_to_depth stem,
    nonstandard batch) share the metric name, and an outage reprint
    must never silently substitute one configuration for another.
    """
    import glob

    results_dir = os.environ.get("BENCH_RESULTS_DIR") or os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "bench_results"
    )
    best = None
    for path in glob.glob(os.path.join(results_dir, "*.json")):
        if os.path.basename(path).startswith("sim_"):
            continue  # CPU-simulation artifacts are logic-validation only
        try:
            with open(path) as f:
                lines = f.read().splitlines()
        except OSError:
            continue
        for line in lines:
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                d = json.loads(line)
            except json.JSONDecodeError:
                continue
            if (
                d.get("metric") == metric
                and d.get("platform") == "tpu"
                and d.get("value")
                and not d.get("stale")  # never re-launder a reprint
                and all(
                    d.get(k, dflt) == want
                    for k, (want, dflt) in (config or {}).items()
                )
            ):
                import datetime

                # Prefer the measurement's own capture timestamp
                # (inner_main stamps one); file mtime is checkout time
                # after a fresh clone, not capture time — so ANY
                # embedded stamp outranks ANY mtime-derived one.
                stamped = "captured_at" in d
                when = d.get("captured_at") or datetime.datetime.fromtimestamp(
                    os.path.getmtime(path), datetime.timezone.utc
                ).strftime("%Y-%m-%dT%H:%M:%SZ")
                rank = (stamped, when)
                if best is None or rank > best[3]:
                    best = (d, path, when, rank)
    return best[:3] if best else None


def orchestrate():
    attempts = int(os.environ.get("BENCH_ATTEMPTS", "4"))
    # Per-attempt patience. A legitimate run needs ~2-3 min (compile +
    # measure); a claim against a DOWN backend takes ~20-25 min to
    # report UNAVAILABLE. We no longer wait that out here: the
    # kill-wedges-the-queue theory was tested and DISPROVEN
    # (2026-07-30, docs/perf.md), so truncating a doomed claim only
    # costs this client its queue slot — which is exactly right when
    # the alternative is the driver timing US out (BENCH_r03 rc=124).
    timeout = float(os.environ.get("BENCH_ATTEMPT_TIMEOUT", "600"))
    forced = os.environ.get("BENCH_PLATFORM")
    metric = os.environ.get("BENCH_MODEL", "resnet50") + "_synth_img_per_sec"

    base_env = dict(os.environ)
    base_env["BENCH_INNER"] = "1"

    if forced:
        attempts = 1  # platform is explicit; no TPU-retry dance

    # Total-time budget (BENCH_TOTAL_BUDGET, s): the WHOLE orchestration
    # — attempts, fallbacks, everything — must finish comfortably inside
    # the driver's own timeout. Round 3 proved the failure mode: a
    # 4200s budget optimized for "eventually get a TPU number" outlived
    # the driver's patience during a backend outage and the official
    # artifact recorded rc=124/parsed=null. Rules:
    # * default 900s; `timeout 1200 python bench.py` must ALWAYS print
    #   a parseable line (that invocation is the acceptance test);
    # * further attempts start only when a full window plus fallback
    #   headroom still fits; the check runs BEFORE the backoff sleep;
    # * attempt 0 always runs (floored at 120s), so tiny budgets still
    #   get one real try;
    # * fallback headroom is small when a stale TPU artifact can be
    #   reprinted (instant) and ~330s when the CPU run is the only
    #   fallback left;
    # * CAVEAT: the floors mean a budget below ~450s can be EXCEEDED by
    #   up to ~420s (120s attempt floor + 300s CPU-fallback floor) —
    #   size any outer watchdog to BENCH_TOTAL_BUDGET + 450s. At the
    #   900s default the whole ladder fits `timeout 1200`.
    total_budget = float(os.environ.get("BENCH_TOTAL_BUDGET", "900"))
    stale_ok = os.environ.get("BENCH_STALE", "1") not in ("0", "false")
    # Config provenance: an outage reprint must match the live run's
    # configuration, not just its metric name (the chipwork probes
    # write stem/batch variants under the same metric).
    stale_config = {
        "batch": (int(os.environ.get("BENCH_BATCH", "256")), 256),
    }
    if os.environ.get("BENCH_MODEL", "resnet50").startswith("resnet"):
        # Only resnets have a stem variant, and inner_main only stamps
        # "stem" on resnet artifacts — gating every model on it would
        # reject valid ViT/Inception/VGG artifacts (which omit the key)
        # against the conv7 omission-default. Artifacts predating the
        # stem field were conv7 captures.
        stale_config["stem"] = (
            os.environ.get("BENCH_STEM", "space_to_depth"),
            "conv7",
        )
    if os.environ.get("BENCH_MODEL") == "vit_b16":
        # same provenance rule for ViT's attention engine (artifacts
        # predating the attn field were dense captures)
        # stale candidates are platform-filtered to "tpu" inside
        # _stale_artifact, so the expectation evaluates the shared
        # predicate at platform="tpu"
        stale_config["attn"] = (_vit_attn_mode("tpu"), "dense")

    def _find_stale():
        if not stale_ok or forced:
            return None
        return _stale_artifact(metric, config=stale_config)

    # Probe once up front only to size the fallback headroom; re-resolve
    # at the fallback point — the nohup capture loops run all round and
    # may land a FRESHER artifact while our attempts sit in the claim
    # queue.
    stale = _find_stale()
    cpu_headroom = 60.0 if stale else 330.0
    t_start = time.monotonic()

    def _remaining() -> float:
        return total_budget - (time.monotonic() - t_start)

    last_err = ""
    for i in range(attempts):
        delay = 120.0 * i  # backoff for THIS attempt (0 for the first)
        # Gate on the TRUNCATED window the attempt would actually get:
        # a retry is worth starting whenever a floored 120s window (the
        # "legitimate run needs ~2 min" bound) still fits after the
        # backoff — gating on the full untruncated timeout would make
        # the ladder unreachable at the default 900/600 settings.
        if not forced and i > 0 and (
            _remaining() - cpu_headroom - delay < 120.0
        ):
            print(
                f"bench: {total_budget - _remaining():.0f}s spent of "
                f"{total_budget:.0f}s budget; no attempt window fits — "
                "moving to the fallback ladder",
                file=sys.stderr,
            )
            break
        if i > 0:
            print(
                f"bench: attempt {i} failed, retrying in {delay:.0f}s "
                f"(TPU backend may be recovering a stale chip claim)",
                file=sys.stderr,
            )
            time.sleep(delay)
        attempt_timeout = timeout
        if not forced:
            attempt_timeout = min(
                timeout, max(_remaining() - cpu_headroom, 120.0)
            )
        proc = _spawn(base_env, attempt_timeout)
        parsed = _extract_json(proc.stdout or "")
        if proc.returncode == 0 and parsed is not None:
            print(json.dumps(parsed))
            return 0
        last_err = (proc.stderr or "")[-1500:] or (proc.stdout or "")[-1500:]

    stale = _find_stale()
    if stale is not None:
        parsed, path, when = stale
        parsed = dict(parsed)
        parsed["stale"] = True
        parsed["captured_at"] = when
        parsed["source"] = os.path.relpath(
            path, os.path.dirname(os.path.abspath(__file__))
        )
        parsed["error"] = (
            "tpu backend unavailable for the live capture; reprinting "
            "the most recent committed real-TPU artifact. last error: "
            + last_err[-300:]
        )
        print(json.dumps(parsed))
        return 0

    cpu_err = ""
    if not forced:
        # All TPU attempts failed: fall back to a small honest CPU run
        # so the round still records a parseable measurement. Skipped
        # when the caller forced a platform — overriding an explicit
        # choice would mask a hard requirement.
        from _hermetic import hermetic_cpu_env

        cpu_env = hermetic_cpu_env(base=base_env)
        cpu_env["BENCH_PLATFORM"] = "cpu"
        cpu_env["BENCH_BATCH"] = os.environ.get("BENCH_CPU_BATCH", "32")
        cpu_env["BENCH_ITERS"] = os.environ.get("BENCH_CPU_ITERS", "3")
        cpu_env["BENCH_WARMUP"] = "1"
        # cap by what's left of the budget, but always leave enough to
        # actually emit a line (~5 min compile+run at the small batch);
        # the 300s floor must hold even when BENCH_ATTEMPT_TIMEOUT is
        # tuned below it — the attempt timeout governs TPU claims, not
        # this last honest rung
        proc = _spawn(cpu_env, max(min(timeout, _remaining()), 300.0))
        parsed = _extract_json(proc.stdout or "")
        if proc.returncode == 0 and parsed is not None:
            parsed["error"] = (
                "tpu backend unavailable after "
                f"{attempts} attempts; CPU fallback. last error: "
                + last_err[-400:]
            )
            print(json.dumps(parsed))
            return 0
        cpu_err = (proc.stderr or "")[-400:]

    # Emit a diagnostic line the driver can still parse.
    print(
        json.dumps(
            {
                "metric": metric,
                "value": 0.0,
                "unit": "img/s",
                "vs_baseline": 0.0,
                "error": (
                    f"all attempts failed (platform="
                    f"{forced or 'tpu'}). last error: " + last_err[-400:]
                    + (" | cpu fallback error: " + cpu_err
                       if cpu_err else "")
                ),
            }
        )
    )
    return 1


if __name__ == "__main__":
    if os.environ.get("BENCH_INNER") == "1":
        inner_main()
    else:
        sys.exit(orchestrate())
