"""Data-sharding utilities: the input-pipeline half of the porting
recipe.

The reference leans on each framework's loader plus a rank-sharding
idiom (ref: examples use
``torch.utils.data.distributed.DistributedSampler(dataset,
num_replicas=hvd.size(), rank=hvd.rank())`` [V]); the TPU-native
equivalents here serve the same three needs without assuming torch:

* :class:`ShardedIndexSampler` — the DistributedSampler analog: a
  rank's epoch-shuffled slice of ``range(n)``, padded to equal length
  (SPMD needs identical step counts everywhere).
* :func:`shard_array` — slice host arrays by rank (the synthetic-data
  examples' one-liner).
* :func:`prefetch_to_device` — overlap host→device transfer with
  compute by keeping ``size`` batches in flight (the tf.data
  ``prefetch`` role for plain Python iterators).
"""

from __future__ import annotations

import collections
import itertools
from typing import Iterable, Iterator, Optional, Sequence

import numpy as np


class ShardedIndexSampler:
    """Per-rank index sampler with epoch shuffling (ref:
    DistributedSampler semantics [V]: equal-length shards, optional
    shuffle keyed by (seed, epoch), padding by wrap-around)."""

    def __init__(
        self,
        n: int,
        num_replicas: Optional[int] = None,
        rank: Optional[int] = None,
        shuffle: bool = True,
        seed: int = 0,
        drop_last: bool = False,
    ):
        from .common import basics

        self.n = int(n)
        self.num_replicas = (
            num_replicas if num_replicas is not None else basics.size()
        )
        self.rank = rank if rank is not None else basics.rank()
        if not 0 <= self.rank < self.num_replicas:
            raise ValueError(
                f"rank {self.rank} out of range for "
                f"{self.num_replicas} replicas"
            )
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        if drop_last:
            self.num_samples = self.n // self.num_replicas
        else:
            self.num_samples = -(-self.n // self.num_replicas)  # ceil

    def set_epoch(self, epoch: int) -> None:
        """Reshuffle differently each epoch (same contract as the
        torch sampler — call before iterating)."""
        self.epoch = int(epoch)

    def __len__(self) -> int:
        return self.num_samples

    def __iter__(self) -> Iterator[int]:
        if self.shuffle:
            rng = np.random.default_rng((self.seed, self.epoch))
            order = rng.permutation(self.n)
        else:
            order = np.arange(self.n)
        total = self.num_samples * self.num_replicas
        if self.drop_last:
            order = order[:total]
        else:
            # wrap-around padding so every rank sees num_samples items;
            # np.resize repeats the permutation as many times as needed
            # (n < num_replicas included — a single order[:pad] slice
            # would underfill the high ranks and deadlock SPMD loops).
            if total > self.n:
                order = np.resize(order, total)
        return iter(order[self.rank :: self.num_replicas].tolist())


def shard_array(x, num_replicas: Optional[int] = None,
                rank: Optional[int] = None):
    """This rank's contiguous dim-0 shard of a host array (drops the
    ragged tail so shards are equal — SPMD shape discipline)."""
    from .common import basics

    num_replicas = (
        num_replicas if num_replicas is not None else basics.size()
    )
    rank = rank if rank is not None else basics.rank()
    x = np.asarray(x)
    per = x.shape[0] // num_replicas
    if per == 0:
        raise ValueError(
            f"cannot shard dim0={x.shape[0]} across {num_replicas} ranks"
        )
    return x[rank * per : (rank + 1) * per]


def prefetch_to_device(
    iterator: Iterable,
    size: int = 2,
    devices=None,
    sharding=None,
):
    """Wrap a host batch iterator so device transfer runs ahead of
    compute: ``size`` batches are put on device before the first yield
    and one more is enqueued per step (jax device puts are async, so
    the copy of batch t+1 overlaps the compute of batch t).

    ``sharding`` (a jax.sharding.Sharding) places each pytree leaf;
    default is the first addressable device.
    """
    import jax

    if sharding is None:
        dev = (devices or jax.local_devices())[0]
        put = lambda t: jax.device_put(t, dev)  # noqa: E731
    else:
        put = lambda t: jax.device_put(t, sharding)  # noqa: E731

    queue = collections.deque()
    it = iter(iterator)

    def enqueue(k: int) -> None:
        for batch in itertools.islice(it, k):
            queue.append(jax.tree_util.tree_map(put, batch))

    enqueue(max(int(size), 1))
    while queue:
        yield queue.popleft()
        enqueue(1)
