"""Adasum: scale-invariant gradient combination.

TPU-native rebuild of the reference's Adasum reducer
(ref: horovod/common/ops/adasum/adasum.h — the recursive
vector-halving-distance-doubling combiner — and adasum_mpi_operations.cc /
adasum_gpu_operations.cc [V], SURVEY.md §2.2).

The math (adasum.h [V]): two gradients a, b combine as

    adasum(a, b) = (1 - a·b / (2·‖a‖²)) · a  +  (1 - a·b / (2·‖b‖²)) · b

which removes each vector's projection onto the other before summing —
orthogonal gradients add, parallel gradients average, and the result is
invariant to rescaling either input. n ranks combine pairwise along a
binary tree (the reference's recursive halving-doubling).

Where the reference hand-implements the distributed dot products with
MPI reduce-scatter, here each pairwise stage runs data-parallel on-chip:
for power-of-two worlds we use log2(n) XOR-partner ``ppermute`` stages
(comm-optimal on an ICI ring/torus); otherwise one ``all_gather`` then a
local pairwise tree (XLA fuses the arithmetic; dots run on the MXU).
Dot products accumulate in float32 regardless of input dtype, matching
the reference's fp64/fp32 accumulation discipline.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax.numpy as jnp
from jax import lax

from ..common.topology import WORLD_AXIS


def adasum_pair(a, b):
    """Combine two same-shaped gradient tensors by the Adasum rule.

    On TPU this dispatches to the two-pass Pallas kernel
    (ops/pallas_kernels.py — one VMEM traversal for the dots, one for
    the weighted sum); elsewhere the jnp formulation below is both the
    fallback and the numerics oracle the kernel is tested against."""
    import jax

    if jax.default_backend() == "tpu":
        from .pallas_kernels import adasum_pair as _pallas_pair

        return _pallas_pair(a, b)
    af = a.astype(jnp.float32)
    bf = b.astype(jnp.float32)
    dot = jnp.sum(af * bf)
    asq = jnp.sum(af * af)
    bsq = jnp.sum(bf * bf)
    acoef = 1.0 - jnp.where(asq > 0, dot / (2.0 * asq), 0.0)
    bcoef = 1.0 - jnp.where(bsq > 0, dot / (2.0 * bsq), 0.0)
    out = acoef * af + bcoef * bf
    return out.astype(a.dtype)


def _tree_combine(stack):
    """Pairwise-tree Adasum over a leading 'rank' axis. Odd counts carry the
    last element up a level (the reference pre-reduces to a power of two;
    same fixed combination order on every rank ⇒ deterministic)."""
    vals = list(stack)
    while len(vals) > 1:
        nxt = []
        for i in range(0, len(vals) - 1, 2):
            nxt.append(adasum_pair(vals[i], vals[i + 1]))
        if len(vals) % 2 == 1:
            nxt.append(vals[-1])
        vals = nxt
    return vals[0]


def adasum_allreduce(
    tensor,
    axis_name: str = WORLD_AXIS,
    process_set=None,
    groups: Optional[Sequence[Sequence[int]]] = None,
):
    """Adasum-allreduce across a mesh axis, for use inside jit/shard_map
    (ref: the Adasum path selected by hvd.DistributedOptimizer(op=hvd.Adasum)
    [V])."""
    if groups is None and process_set is not None:
        groups = process_set.axis_index_groups(lax.axis_size(axis_name))
    n = lax.axis_size(axis_name) if groups is None else len(groups[0])
    if groups is None and _is_power_of_two(n):
        out = tensor
        idx = lax.axis_index(axis_name)
        for k in range(n.bit_length() - 1):
            bit = 1 << k
            perm = [(i, i ^ bit) for i in range(n)]
            partner = lax.ppermute(out, axis_name, perm)
            # adasum_pair is symmetric, so both partners compute the same
            # combined value — no rank-dependent branch needed.
            out = adasum_pair(out, partner)
        return out
    gathered = lax.all_gather(tensor, axis_name, axis_index_groups=groups)
    return _tree_combine([gathered[i] for i in range(gathered.shape[0])])


def _is_power_of_two(n: int) -> bool:
    return n >= 1 and (n & (n - 1)) == 0


# ---- host-side variants (ref: the reference's CPU Adasum path,
# adasum_mpi_operations.cc [V]) — native C++ when built, numpy fallback.
# These are the numerics oracle for the on-device path above and serve
# host-resident tensors (elastic state reconciliation, eager numpy).

def adasum_pair_host(a, b):
    """Adasum combine of two host arrays (numpy in, numpy out)."""
    import numpy as np

    try:
        from .._native import loader as _native

        out = _native.adasum_pair(np.asarray(a), np.asarray(b))
        if out is not None:
            return out.astype(np.asarray(a).dtype)
    except Exception:
        pass
    af = np.asarray(a, dtype=np.float64)
    bf = np.asarray(b, dtype=np.float64)
    dot = float((af * bf).sum())
    asq = float((af * af).sum())
    bsq = float((bf * bf).sum())
    acoef = 1.0 - (dot / (2.0 * asq) if asq > 0 else 0.0)
    bcoef = 1.0 - (dot / (2.0 * bsq) if bsq > 0 else 0.0)
    return (acoef * af + bcoef * bf).astype(np.asarray(a).dtype)


def adasum_tree_host(stack):
    """Pairwise-tree Adasum over ``stack[k, ...]`` host arrays — same
    combination order as ``_tree_combine`` (odd counts carry the last
    element up a level)."""
    import numpy as np

    stack = np.asarray(stack)
    try:
        from .._native import loader as _native

        out = _native.adasum_tree(stack)
        if out is not None:
            return out.astype(stack.dtype)
    except Exception:
        pass
    vals = [stack[i] for i in range(stack.shape[0])]
    while len(vals) > 1:
        nxt = [
            adasum_pair_host(vals[i], vals[i + 1])
            for i in range(0, len(vals) - 1, 2)
        ]
        if len(vals) % 2 == 1:
            nxt.append(vals[-1])
        vals = nxt
    return vals[0]
