"""Chrome-trace timeline for eager collective lifecycles.

TPU-native rebuild of the reference's timeline writer
(ref: horovod/common/timeline.cc/.h [V], SURVEY.md §5.1): emits
``chrome://tracing`` JSON where each tensor is a "process" row and its
lifecycle phases are duration events. The reference's phases are kept —
NEGOTIATE_* is emitted with zero-ish duration since XLA removed the
negotiation round, documenting the semantic mapping rather than hiding it.

Activated by ``HOROVOD_TIMELINE=/path.json``; ``hvd.start_timeline()`` /
``hvd.stop_timeline()`` provide the runtime API added upstream in v0.21 [V].
When the native C runtime is available the event sink is the C++ ring
buffer (csrc/timeline_buffer.cc); otherwise a pure-Python writer is used.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List

# Lifecycle phase names, mirroring timeline.cc's event names [V].
NEGOTIATE = "NEGOTIATE_{}"
QUEUE = "QUEUE"
MEMCPY_IN_FUSION_BUFFER = "MEMCPY_IN_FUSION_BUFFER"
COMM = "{}"  # e.g. ALLREDUCE, ALLGATHER — on TPU the XLA/ICI collective
MEMCPY_OUT_FUSION_BUFFER = "MEMCPY_OUT_FUSION_BUFFER"
CYCLE_MARKER = "CYCLE"


class Timeline:
    """Thread-safe Chrome-trace event writer."""

    def __init__(self, path: str, mark_cycles: bool = False):
        self._path = path
        self._mark_cycles = mark_cycles
        self._lock = threading.Lock()
        self._events: List[dict] = []
        self._tensor_pids: Dict[str, int] = {}
        self._next_pid = 1
        self._t0 = time.perf_counter()
        self._active = True
        self._native = None
        try:
            from .._native import loader as _native_loader

            self._native = _native_loader.timeline_buffer()
        except Exception:
            self._native = None

    # -- runtime start/stop API (ref: horovod_start_timeline [V]) --

    def start(self) -> None:
        self._active = True

    def stop(self) -> None:
        """Deactivate AND flush the file: the reference writes the
        timeline incrementally, so after hvd.stop_timeline() the user
        can open the trace immediately — waiting for shutdown() to
        materialize it would silently diverge (timeline.cc [V]).
        start() may still resume recording; close() re-writes with any
        further events.

        The deactivation happens UNDER the emit lock: every emit path
        re-checks ``_active`` after acquiring the lock, so an emitter
        that raced past the cheap pre-check either lands its event
        before the flip (and the final ``_write`` below includes it) or
        observes the flip and drops the event entirely. Without this, a
        counter()/span() blocked on the lock could append its event
        AFTER stop()'s write — present in memory, silently missing from
        the file the user just opened."""
        with self._lock:
            self._active = False
        self._write()

    @property
    def active(self) -> bool:
        return self._active

    def _now_us(self) -> float:
        return (time.perf_counter() - self._t0) * 1e6

    def _pid(self, tensor_name: str) -> int:
        pid = self._tensor_pids.get(tensor_name)
        if pid is None:
            pid = self._next_pid
            self._next_pid += 1
            self._tensor_pids[tensor_name] = pid
            self._emit(
                {
                    "name": "process_name",
                    "ph": "M",
                    "pid": pid,
                    "args": {"name": tensor_name},
                }
            )
        return pid

    def _emit(self, event: dict) -> None:
        if self._native is not None:
            self._native.emit(json.dumps(event))
        else:
            self._events.append(event)

    def begin(self, tensor_name: str, phase: str) -> None:
        if not self._active:
            return
        with self._lock:
            if not self._active:  # lost the race with stop()'s flush
                return
            self._emit(
                {
                    "name": phase,
                    "ph": "B",
                    "pid": self._pid(tensor_name),
                    "ts": self._now_us(),
                }
            )

    def now_us(self) -> float:
        """Current trace-relative timestamp — for callers that measure
        a span themselves and stamp it via :meth:`span`."""
        return self._now_us()

    def span(
        self, tensor_name: str, phase: str, start_us: float, dur_us: float
    ) -> None:
        """Complete ('X') event with EXPLICIT timestamps. Used for the
        device-completion stamp on fused flushes (ops/fusion.py): the
        dispatch-side begin/end pairs record when the eager runtime
        QUEUED and launched the collective — the phase it owns — while
        this span carries the dispatch→`block_until_ready` delta, i.e.
        when the device actually finished. The traced path gets the
        same truth from the profiler (traced_timeline); this closes the
        eager half of SURVEY §7's device-completion checklist row.
        Caveat carried from docs/perf.md: on the sandbox's remote PJRT
        tunnel `block_until_ready` is advisory, so on that backend the
        span bounds dispatch, not device time — on real local backends
        it is the honest device-completion delta."""
        if not self._active:
            return
        with self._lock:
            if not self._active:
                return
            self._emit(
                {
                    "name": phase,
                    "ph": "X",
                    "pid": self._pid(tensor_name),
                    "ts": float(start_us),
                    "dur": float(dur_us),
                }
            )

    def end(self, tensor_name: str, phase: str) -> None:
        if not self._active:
            return
        with self._lock:
            if not self._active:
                return
            self._emit(
                {
                    "name": phase,
                    "ph": "E",
                    "pid": self._pid(tensor_name),
                    "ts": self._now_us(),
                }
            )

    def instant(self, tensor_name: str, phase: str) -> None:
        if not self._active:
            return
        with self._lock:
            if not self._active:
                return
            self._emit(
                {
                    "name": phase,
                    "ph": "i",
                    "pid": self._pid(tensor_name),
                    "ts": self._now_us(),
                    "s": "p",
                }
            )

    def counter(self, name: str, value: float) -> None:
        """Chrome-trace counter track (ph "C") — the fusion manager
        feeds per-cycle gauges (bucket pad bytes, fused dispatches)
        here so padding/dispatch cost lines up with the per-tensor
        lifecycle rows in the same trace. The telemetry hub feeds its
        ``telemetry.step`` track through here at every step boundary so
        traces align with StepStats records (common/telemetry.py)."""
        if not self._active:
            return
        with self._lock:
            if not self._active:
                return
            self._emit(
                {
                    "name": name,
                    "ph": "C",
                    "pid": 0,
                    "ts": self._now_us(),
                    "args": {name: value},
                }
            )

    def mark_cycle(self) -> None:
        """One eager fusion-cycle boundary (HOROVOD_TIMELINE_MARK_CYCLES)."""
        if self._mark_cycles and self._active:
            with self._lock:
                if not self._active:
                    return
                self._emit(
                    {
                        "name": CYCLE_MARKER,
                        "ph": "i",
                        "pid": 0,
                        "ts": self._now_us(),
                        "s": "g",
                    }
                )

    def _write(self) -> None:
        with self._lock:
            if self._native is not None:
                # drain() empties the ring; keep drained events so a
                # later write (stop → close) still has the full trace
                self._events.extend(
                    json.loads(s) for s in self._native.drain()
                )
            events = self._events
            tmp = f"{self._path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump({"traceEvents": events}, f)
            os.replace(tmp, self._path)

    def close(self) -> None:
        self._write()
