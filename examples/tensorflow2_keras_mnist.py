"""Keras-shim MNIST — the reference's canonical Keras example, ported
by changing one import (ref: examples/tensorflow2/
tensorflow2_keras_mnist.py [V]: init → scale LR by size →
DistributedOptimizer → model.fit with BroadcastGlobalVariables +
MetricAverage callbacks, checkpoint only on rank 0).

Synthetic MNIST-shaped data keeps the example hermetic (no downloads).

Run (CPU simulation): JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/tensorflow2_keras_mnist.py --steps 8
"""

import argparse
import os

if os.environ.get("JAX_PLATFORMS") == "cpu":
    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8"
    )

import numpy as np
import tensorflow as tf

import horovod_tpu.tensorflow.keras as hvd


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--steps", type=int, default=8)
    parser.add_argument("--batch", type=int, default=32)
    args = parser.parse_args()

    hvd.init()

    rng = np.random.default_rng(1234 + hvd.rank())
    images = rng.normal(size=(args.steps * args.batch, 28, 28, 1)).astype(
        np.float32
    )
    labels = rng.integers(0, 10, size=(args.steps * args.batch,))

    model = tf.keras.Sequential(
        [
            tf.keras.layers.Conv2D(8, 3, activation="relu"),
            tf.keras.layers.GlobalAveragePooling2D(),
            tf.keras.layers.Dense(10),
        ]
    )
    # LR scales with world size; the wrapped optimizer allreduces
    # gradients inside apply_gradients (the reference's recipe [V])
    opt = tf.keras.optimizers.SGD(0.05 * hvd.size())
    opt = hvd.DistributedOptimizer(opt)
    model.compile(
        optimizer=opt,
        loss=tf.keras.losses.SparseCategoricalCrossentropy(
            from_logits=True
        ),
        metrics=["accuracy"],
        # the wrapper reduces per-batch; Keras 3 would otherwise wrap
        # the train step in a way that bypasses apply_gradients hooks
        run_eagerly=True,
    )

    callbacks = [
        # rank 0's initial weights reach every worker before training
        hvd.callbacks.BroadcastGlobalVariablesCallback(0),
        # epoch metrics averaged over the world, not rank-local
        hvd.callbacks.MetricAverageCallback(),
    ]

    history = model.fit(
        images,
        labels,
        batch_size=args.batch,
        epochs=1,
        callbacks=callbacks,
        verbose=2 if hvd.rank() == 0 else 0,
    )

    if hvd.rank() == 0:
        final_loss = history.history["loss"][-1]
        print(f"final loss {final_loss:.4f}")
        print("DONE")


if __name__ == "__main__":
    main()
